"""Checkpoint save/load with reference-compatible layout + sharded I/O
and crash-consistent two-phase commit.

Reference: deepspeed/runtime/engine.py:1462-1890. Layout kept:

    <save_dir>/<tag>/mp_rank_00_model_states.msgpack
    <save_dir>/<tag>/zero_pp_rank_<dp>_mp_rank_00_optim_states.msgpack
    <save_dir>/<tag>/.ckpt_commit.json    (commit marker; see below)
    <save_dir>/latest                     (text file holding the tag)

Sharded design (reference engine.py:1462-1489 per-rank shard files):
device-sharded leaves are NOT gathered to one host. Each distinct shard of
a sharded jax.Array is written as a piece (with its index) into the
zero_pp_rank_<r> file of its shard rank; the model/optim skeleton files
keep a marker per sharded leaf. In multi-host jobs each process writes
only the pieces it can address — no cross-host gather, every host writes
in parallel (the reference's per-rank writer behaviour).

Crash consistency (two-phase commit): every file lands as tmp+rename, so
no reader ever sees a torn file.  A tag becomes COMMITTED only when
`.ckpt_commit.json` appears in its directory — written by process 0
after every process has posted a per-tag done-key on the coordination-
service KV (runtime/comm/hostwire.py), i.e. after ALL rank files are
durably on disk everywhere.  `latest` is rewritten (atomically) only
after the marker lands.  A save interrupted at ANY point therefore
leaves `latest` pointing at the previous committed tag, and
`read_latest_tag` additionally skips a tag without a marker back to the
newest committed one.  The marker doubles as checkpoint metadata: it
records the saving run's topology (dp size, hierarchy factor, ZeRO
stage), which the engine uses to log/validate resharding-on-restore.

Failure taxonomy: "nothing to resume from" (no latest, no tag dir)
raises FileNotFoundError — callers warn and start fresh.  "A tag is
present but incomplete/uncommitted/corrupt" raises
CheckpointIntegrityError naming the tag and what is missing — resuming
silently from it would be wrong, so that one is never swallowed.

Async saves: rank files are written by a background thread pool; with
async_save=True the call returns after the host snapshot and the
serialize+write+commit runs in the background (flush_pending() blocks
on it; a second save of the SAME tag, and any load from the same
directory, flush first so the writer is never raced).  Stall accounting
rides the monitor counters: `ckpt.stall_ms` (µs of blocked training per
save, in the bytes slot), `ckpt.bytes` (serialized bytes per committed
tag), `ckpt.pending` (writer-queue depth sampled per save).

On load the pieces are reassembled into full host arrays, so checkpoints
stay elastic by construction — loading at a different world size,
hierarchy factor, or ZeRO stage re-partitions via device_put under the
restoring run's own sharding plan (subsumes the reference's ZeRO-1
elastic re-partition logic, zero/stage1.py:924-1155). Unsharded
(round-1/2 format) checkpoints load unchanged.
"""

from __future__ import annotations

import json
import os
import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

import jax
from flax import serialization

from ..monitor.counters import COUNTERS
from ..utils.logging import logger
from .resilience import fault_filter, fault_point, retry_transient

_SHARD_MARKER = "__dstpu_sharded_leaf__"
COMMIT_MARKER = ".ckpt_commit.json"
COMMIT_SCHEMA_VERSION = 1
COMMIT_TIMEOUT_MS = 300_000


class CheckpointIntegrityError(RuntimeError):
    """A checkpoint tag exists but is incomplete, uncommitted, or
    corrupt.  Distinct from FileNotFoundError ("nothing to resume
    from"): silently training from scratch over a damaged checkpoint
    would lose the run, so engines let this propagate."""


# ---------------------------------------------------------------------------
# background writer + per-(dir, tag) pending bookkeeping
# ---------------------------------------------------------------------------

_writer = ThreadPoolExecutor(max_workers=4)
_pending_lock = threading.Lock()
_pending: Dict[Tuple[str, str], List[Future]] = {}
# last commit-bearing future per save_dir: async commits CHAIN on it so
# `latest` (and marker timestamps) always land in save-call order even
# when several tags are in flight on the pool at once
_dir_chain: Dict[str, Future] = {}
# per-(save_dir, tag) save counter: scopes the commit barrier's KV keys
# so a tag re-save never rendezvouses on the previous round's keys
_tag_seq: Dict[Tuple[str, str], int] = {}


def _pending_key(save_dir: str, tag) -> Tuple[str, str]:
    return (os.path.realpath(save_dir), str(tag))


def _track_pending(save_dir: str, tag, futures: List[Future]) -> None:
    with _pending_lock:
        _pending.setdefault(_pending_key(save_dir, tag), []).extend(futures)


def pending_count() -> int:
    """Async checkpoint jobs not yet finished (writer-queue depth)."""
    with _pending_lock:
        return sum(1 for fs in _pending.values()
                   for f in fs if not f.done())


def flush_pending(save_dir: Optional[str] = None,
                  tag=None) -> None:
    """Block until async checkpoint writes have landed (and committed).

    With no arguments: everything (engine teardown).  With `save_dir`
    (and optionally `tag`): only that directory/tag — used to serialize
    a tag re-save against the previous writer and a load against any
    in-flight save of the same directory."""
    with _pending_lock:
        if save_dir is None:
            keys = list(_pending)
        else:
            root = os.path.realpath(save_dir)
            keys = [k for k in _pending
                    if k[0] == root and (tag is None or k[1] == str(tag))]
        grabbed = [(k, _pending.pop(k)) for k in keys]
    errs = []
    for _k, futures in grabbed:
        for f in futures:
            try:
                f.result()
            except Exception as e:  # surface the FIRST failure, flush all
                errs.append(e)
    if errs:
        raise errs[0]


# ---------------------------------------------------------------------------
# atomic file plumbing
# ---------------------------------------------------------------------------


def _fsync_dir(path: str) -> None:
    """fsync a directory so a completed rename is durable — without this
    the rename can sit in the page cache after the data fsync, and a
    crash can publish a marker/`latest` over missing files."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return  # platforms without dir fds: rename alone is the best we get
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


_TMP_SEQ = __import__("itertools").count()


def _atomic_write(path: str, blob: bytes) -> int:
    """tmp + fsync + rename: readers never observe a torn file.  The
    tmp name carries pid AND a process-local sequence number: two
    background commits landing the same target (e.g. `latest` for
    overlapping async tags) must not collide on one tmp file.

    Transient storage faults (EIO, injected) retry with bounded backoff
    (runtime/resilience.py) — each attempt writes a FRESH tmp file, so
    a half-written casualty of attempt N can never be renamed by
    attempt N+1.  `ckpt.atomic_write` is a chaos injection site; a
    `corrupt` rule truncates the blob (the torn-write shape the commit
    marker + integrity errors exist to catch)."""
    blob = fault_filter("ckpt.atomic_write.payload", blob)

    def op() -> int:
        fault_point("ckpt.atomic_write")
        tmp = f"{path}.tmp.{os.getpid()}.{next(_TMP_SEQ)}"
        try:
            with open(tmp, "wb") as f:
                f.write(blob)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        except BaseException:
            # best-effort: do not leave the failed attempt's tmp behind
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        return len(blob)

    return retry_transient(op, site=f"ckpt.atomic_write {path}")


# ---------------------------------------------------------------------------
# commit barrier over the coordination-service KV
# ---------------------------------------------------------------------------


class CommitBarrier:
    """Two-phase commit rendezvous for one checkpoint tag.

    Every process posts a done-key after its rank files are durably
    renamed; process 0 blocks for all done-keys, runs the commit action
    (marker + latest), then posts a committed-key the other processes
    block on — so once ANY process's save (or flush_pending) returns,
    the tag is globally committed, and a tag missing its marker can only
    mean a save died before commit.

    Keys are scoped by a per-(tag) sequence number (`seq`) so a RE-SAVE
    of the same tag never sees the previous round's keys: without it,
    non-zero ranks would wait() the stale committed-key and return
    before the new commit ran.  Save calls are collective and ordered,
    so each process's local counter agrees; an elastic restart restarts
    every process, re-agreeing at 0 (jax.distributed has no partial
    restart).  `scope` additionally namespaces the keys per SAVE
    DIRECTORY (a hash of the collective save call's save_dir argument,
    `_barrier_scope`): without it, same-tag saves into two DIFFERENT
    directories — two experiment lanes in one job, a copy-then-save
    flow — rendezvous on one key, and the coordination service's
    write-once KV rejects the second commit with ALREADY_EXISTS (found
    by the chaos campaign's base/chaos lane pair against the real
    coordination service).

    `_endpoint=(client, rank, world)` lets tests drive the barrier over
    a fake in-memory KV (tests/test_hostwire.FakeCoordClient)."""

    def __init__(self, tag: str, timeout_ms: int = COMMIT_TIMEOUT_MS,
                 seq: int = 0, scope: str = "", _endpoint=None):
        from .comm.hostwire import KVSignals

        self.signals = KVSignals(_endpoint=_endpoint)
        self.tag = str(tag)
        self.seq = int(seq)
        self.scope = str(scope)
        self.timeout_ms = int(timeout_ms)

    @property
    def world(self) -> int:
        return self.signals.world

    def _key(self, kind: str, rank: Optional[int] = None) -> str:
        scope = f"{self.scope}/" if self.scope else ""
        base = f"dstpu-ckpt/{scope}{self.tag}/{self.seq}/{kind}"
        return base if rank is None else f"{base}/{rank}"

    def commit(self, commit_fn) -> None:
        """Collective: post done, rendezvous, run `commit_fn` on process
        0, release everyone.  Single-process runs commit_fn directly."""
        sig = self.signals
        if sig.world <= 1:
            commit_fn()
            return
        sig.post(self._key("done", sig.rank), "1")
        if sig.rank == 0:
            try:
                for r in range(sig.world):
                    sig.wait(self._key("done", r), self.timeout_ms)
            except Exception as e:
                raise CheckpointIntegrityError(
                    f"checkpoint tag {self.tag!r}: commit barrier timed "
                    f"out waiting for rank done-keys ({e}); the tag was "
                    f"NOT committed") from e
            commit_fn()
            sig.post(self._key("committed"), "1")
            for r in range(sig.world):
                sig.delete(self._key("done", r))
        else:
            try:
                sig.wait(self._key("committed"), self.timeout_ms)
            except Exception as e:
                raise CheckpointIntegrityError(
                    f"checkpoint tag {self.tag!r}: commit barrier timed "
                    f"out waiting for process 0's commit marker ({e})"
                ) from e


# ---------------------------------------------------------------------------
# host conversion + sharded split/reassembly
# ---------------------------------------------------------------------------


def prefetch_to_host(tree) -> None:
    """Start non-blocking D2H transfers for every device leaf (and every
    addressable shard of sharded leaves) so the later np.asarray
    snapshot finds the bytes already on host.  Best-effort: any leaf
    without the async API just pays the copy at snapshot time."""

    def kick(x):
        try:
            if isinstance(x, jax.Array):
                if x.is_fully_replicated or x.is_fully_addressable:
                    x.copy_to_host_async()
                else:
                    for sh in x.addressable_shards:
                        sh.data.copy_to_host_async()
        except Exception:
            pass
        return x

    jax.tree_util.tree_map(kick, tree)


def _to_host(tree):
    def conv(x):
        if isinstance(x, (str, bytes, bool, int, float)) or x is None:
            return x  # plain scalars serialize natively; np.str_ would not
        return np.asarray(x)

    return jax.tree_util.tree_map(conv, tree)


def _is_sharded(x) -> bool:
    try:
        return isinstance(x, jax.Array) and not x.is_fully_replicated
    except Exception:
        return False


def _normalize_index(index, shape):
    return tuple(
        (0 if sl.start is None else int(sl.start),
         int(shape[d]) if sl.stop is None else int(sl.stop))
        for d, sl in enumerate(index))


def _split_sharded(tree, rank_pieces: Dict[int, Dict[str, Any]],
                   prefix: str):
    """Replace device-sharded leaves with markers; deposit each distinct
    shard (piece + index) into its shard-rank's payload. Replicated / host
    leaves come back as host arrays.

    Multi-host: a piece is written by the process owning the
    lowest-device-id replica of that shard, so every piece is written
    exactly once and no process gathers remote data."""

    proc = jax.process_index()

    def visit(path, leaf):
        if not _is_sharded(leaf):
            if isinstance(leaf, (str, bytes, bool, int, float)) or \
                    leaf is None:
                return leaf
            return np.asarray(leaf)
        key = prefix + jax.tree_util.keystr(path)
        imap = leaf.sharding.devices_indices_map(leaf.shape)
        owner = {}
        for dev, index in imap.items():
            idx = _normalize_index(index, leaf.shape)
            if idx not in owner or dev.id < owner[idx].id:
                owner[idx] = dev
        local = {}
        for sh in leaf.addressable_shards:
            idx = _normalize_index(sh.index, leaf.shape)
            if owner[idx].process_index == proc and idx not in local:
                local[idx] = sh.data
        for idx, data in local.items():
            # file index = owner DEVICE id: globally unique, so exactly one
            # process ever writes a given rank file (piece ranks per leaf
            # would collide across processes on mixed 2D shardings — the
            # loader merges pieces by key across all files, so file
            # assignment only needs to be collision-free, not dense)
            rank_pieces.setdefault(owner[idx].id, {})[key] = {
                "index": [list(p) for p in idx],
                "piece": np.asarray(data),
            }
        return {_SHARD_MARKER: True, "key": key,
                "shape": list(leaf.shape), "dtype": str(leaf.dtype),
                "num_pieces": len(owner)}

    return jax.tree_util.tree_map_with_path(visit, tree)


def _is_marker(x) -> bool:
    return isinstance(x, dict) and x.get(_SHARD_MARKER, False)


def _reassemble(tree, pieces_by_key: Dict[str, list], tag=None):
    """Inverse of _split_sharded: markers -> full host arrays."""

    def visit(leaf):
        if not _is_marker(leaf):
            return leaf
        key = leaf["key"]
        got = pieces_by_key.get(key, [])
        if len(got) != int(leaf["num_pieces"]):
            raise CheckpointIntegrityError(
                f"checkpoint tag {tag!r}: sharded leaf {key} has "
                f"{len(got)} of {leaf['num_pieces']} pieces (missing or "
                f"truncated zero_pp_rank_* rank files?)")
        full = np.empty([int(s) for s in leaf["shape"]],
                        dtype=np.dtype(leaf["dtype"]))
        for entry in got:
            sl = tuple(slice(int(a), int(b)) for a, b in entry["index"])
            full[sl] = entry["piece"]
        return full

    return jax.tree_util.tree_map(visit, tree, is_leaf=_is_marker)


def _load_rank_pieces(ckpt_dir: str, mp_rank: int) -> Dict[str, list]:
    import glob as _glob

    pieces: Dict[str, list] = {}
    pattern = os.path.join(
        ckpt_dir, f"zero_pp_rank_*_mp_rank_{mp_rank:02d}_optim_states"
        f".msgpack")
    for path in sorted(_glob.glob(pattern)):
        with open(path, "rb") as f:
            payload = serialization.msgpack_restore(f.read())
        for key, entry in (payload.get("pieces") or {}).items():
            pieces.setdefault(key, []).append(entry)
    return pieces


# ---------------------------------------------------------------------------
# Infinity stream-group files
# ---------------------------------------------------------------------------

_STREAM_PREFIX = "__dstpu_stream__:"


def stream_group_ckpt_name(ckpt_dir: str, group: str) -> str:
    """Per-stream-group checkpoint file (masters + that group's Adam
    moments), the RAM-bounded unit of the Infinity streaming writer.
    Reference capability: swap-aware optimizer save,
    swap_tensor/partitioned_param_swapper.py:223-277."""
    safe = group.replace(":", "_").replace("/", "_")
    return os.path.join(ckpt_dir, f"stream_group_{safe}.msgpack")


def stream_marker(group: str, slot: str) -> str:
    """Marker leaf standing in for streamed data: slot is 'leaf:<j>'
    (master leaf j of the group), 'optim:<key>' (Adam moments of flat
    leaf <key>) or 'acc:<key>' (mid-accumulation grad sink entry)."""
    return f"{_STREAM_PREFIX}{group}|{slot}"


def write_stream_group(ckpt_dir: str, group: str, payload) -> str:
    path = stream_group_ckpt_name(ckpt_dir, group)
    _atomic_write(path,
                  serialization.msgpack_serialize(_to_host(payload)))
    return path


def _read_stream_group(ckpt_dir: str, group: str):
    path = stream_group_ckpt_name(ckpt_dir, group)
    if not os.path.isfile(path):
        raise CheckpointIntegrityError(
            f"checkpoint at {ckpt_dir} is incomplete: streamed group "
            f"file not found: {path}")
    with open(path, "rb") as f:
        return serialization.msgpack_restore(f.read())


def has_stream_markers(tree) -> bool:
    return any(isinstance(l, str) and l.startswith(_STREAM_PREFIX)
               for l in jax.tree_util.tree_leaves(tree))


def resolve_streamed(tree, ckpt_dir: str):
    """Materialize stream markers by reading group files (one cached at a
    time — marker visitation order has group locality, so each file is
    normally read once).  Consumers that must stay RAM-bounded skip this
    and walk the group files themselves (InfinityRuntime.load_streamed)."""
    cache: Dict[str, Any] = {}

    def lookup(marker: str):
        group, slot = marker[len(_STREAM_PREFIX):].split("|", 1)
        if group not in cache:
            cache.clear()
            cache[group] = _read_stream_group(ckpt_dir, group)
        payload = cache[group]
        kind, _, idx = slot.partition(":")
        if kind == "leaf":
            return np.asarray(payload["leaves"][idx])
        if kind == "optim":
            return {k: np.asarray(v)
                    for k, v in payload["optim"][idx].items()}
        if kind == "acc":
            return np.asarray(payload["acc"][idx])
        raise ValueError(f"unknown stream marker slot {slot!r}")

    def visit(node):
        if isinstance(node, str) and node.startswith(_STREAM_PREFIX):
            return lookup(node)
        if isinstance(node, dict):
            return {k: visit(v) for k, v in node.items()}
        if isinstance(node, (list, tuple)):
            return type(node)(visit(v) for v in node)
        return node

    return visit(tree)


# ---------------------------------------------------------------------------
# file naming
# ---------------------------------------------------------------------------


def model_ckpt_name(ckpt_dir: str, mp_rank: int = 0) -> str:
    return os.path.join(ckpt_dir, f"mp_rank_{mp_rank:02d}_model_states.msgpack")


def optim_ckpt_name(ckpt_dir: str, dp_rank: int = 0, mp_rank: int = 0) -> str:
    return os.path.join(
        ckpt_dir,
        f"zero_pp_rank_{dp_rank}_mp_rank_{mp_rank:02d}_optim_states.msgpack")


def layer_ckpt_name(ckpt_dir: str, layer_idx: int, mp_rank: int = 0) -> str:
    """Per-layer pipeline checkpoint file (reference pipe/module.py:520-578
    `layer_{idx:02d}-model_{mp:02d}-model_states.pt`)."""
    return os.path.join(
        ckpt_dir, f"layer_{layer_idx:02d}-model_{mp_rank:02d}-model_states"
        f".msgpack")


# ---------------------------------------------------------------------------
# commit markers / tag state
# ---------------------------------------------------------------------------


def commit_marker_path(load_dir: str, tag) -> str:
    return os.path.join(load_dir, str(tag), COMMIT_MARKER)


def is_tag_committed(load_dir: str, tag) -> bool:
    return os.path.isfile(commit_marker_path(load_dir, tag))


def read_tag_meta(load_dir: str, tag) -> Optional[Dict[str, Any]]:
    """The commit marker's payload ({"tag", "committed_unix", "meta":
    {...saving-run topology...}}), or None for legacy/uncommitted tags."""
    path = commit_marker_path(load_dir, tag)
    if not os.path.isfile(path):
        return None
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        logger.warning(f"unreadable commit marker {path}: {e}")
        return None


def _partition_tags(load_dir: str) -> Tuple[List[str], List[str]]:
    """One directory scan, one marker read per tag dir: (committed tags
    oldest -> newest commit time, uncommitted/corrupt tag dirs sorted
    by name).  Shared by committed_tags/uncommitted_tags so the
    fallback resume path doesn't pay the marker IO twice on slow
    network filesystems."""
    committed, uncommitted = [], []
    try:
        entries = os.listdir(load_dir)
    except OSError:
        return [], []
    for name in entries:
        if not os.path.isdir(os.path.join(load_dir, name)):
            continue
        marker = read_tag_meta(load_dir, name)
        if marker is not None:
            committed.append((float(marker.get("committed_unix", 0.0)),
                              name))
        else:
            uncommitted.append(name)
    return ([name for _, name in sorted(committed)], sorted(uncommitted))


def committed_tags(load_dir: str) -> List[str]:
    """Committed tags under `load_dir`, oldest -> newest commit time."""
    return _partition_tags(load_dir)[0]


def _dir_has_markers(load_dir: str) -> bool:
    try:
        return any(os.path.isfile(os.path.join(load_dir, d, COMMIT_MARKER))
                   for d in os.listdir(load_dir))
    except OSError:
        return False


def write_commit_marker(save_dir: str, tag,
                        meta: Optional[Dict[str, Any]] = None,
                        world_size: int = 1, nbytes: int = 0) -> None:
    """Publish the commit marker for `tag` (atomic rename + dir fsync).
    Call ONLY after every process's files for the tag are durably on
    disk — writers with their own rendezvous (the multi-host pipeline
    engine's collective barrier) call this directly instead of going
    through CommitBarrier."""
    ckpt_dir = os.path.join(save_dir, str(tag))
    marker = {
        "schema_version": COMMIT_SCHEMA_VERSION,
        "tag": str(tag),
        "committed_unix": time.time(),
        "world_size": int(world_size),
        "nbytes_rank0": int(nbytes),
        "meta": dict(meta or {}),
    }
    _atomic_write(commit_marker_path(save_dir, tag),
                  json.dumps(marker, indent=2, sort_keys=True,
                             default=str).encode())
    _fsync_dir(ckpt_dir)


def _barrier_scope(save_dir: str) -> str:
    """Stable per-save-directory namespace for the commit barrier's KV
    keys.  Hashes the save_dir STRING as passed (not realpath: the
    collective contract is that every process passes the same argument,
    while mount-point realpaths can legitimately differ across
    hosts)."""
    import hashlib

    return hashlib.md5(str(save_dir).encode()).hexdigest()[:12]


def _commit(save_dir: str, tag, meta: Optional[Dict[str, Any]],
            save_latest: bool, nbytes: int,
            commit_endpoint=None,
            commit_timeout_ms: int = COMMIT_TIMEOUT_MS,
            seq: int = 0) -> None:
    """Phase 2: rendezvous all processes, then (process 0) publish the
    commit marker and repoint `latest` — both atomic renames, in that
    order, so `latest` can never name an uncommitted tag.  Module-level
    so crash tests can monkeypatch it away, simulating a writer killed
    between the file writes and the commit."""
    fault_point("ckpt.commit")
    barrier = CommitBarrier(str(tag), timeout_ms=commit_timeout_ms,
                            seq=seq, scope=_barrier_scope(save_dir),
                            _endpoint=commit_endpoint)

    def publish():
        write_commit_marker(save_dir, tag, meta,
                            world_size=barrier.world, nbytes=nbytes)
        if save_latest:
            _atomic_write(os.path.join(save_dir, "latest"),
                          str(tag).encode())
            _fsync_dir(save_dir)

    run_commit = publish if jax.process_index() == 0 else (lambda: None)
    barrier.commit(run_commit)
    COUNTERS.add("ckpt.bytes", int(nbytes))


# ---------------------------------------------------------------------------
# save / load
# ---------------------------------------------------------------------------


def save_checkpoint_state(save_dir: str, tag: str, model_state: Dict[str, Any],
                          optim_state: Optional[Dict[str, Any]] = None,
                          save_latest: bool = True, mp_rank: int = 0,
                          dp_rank: int = 0, layer_states=None,
                          tied_states=None, async_save: bool = False,
                          meta: Optional[Dict[str, Any]] = None,
                          commit_endpoint=None,
                          commit_timeout_ms: int = COMMIT_TIMEOUT_MS,
                          device_leaves_are_snapshots: bool = False) -> str:
    """Write one checkpoint tag (two-phase: files -> barrier -> marker ->
    latest).  `meta` (saving-run topology: dp size, hierarchy factor,
    ZeRO stage, ...) is recorded in the commit marker for
    resharding-on-restore.  Returns the tag directory.

    async_save defers serialization to the background, so by default
    device (jax.Array) leaves are still materialized to host on THIS
    thread — a caller's live param buffers may be donated away by a
    later train step before the background thread reads them.  The
    engine passes device_leaves_are_snapshots=True after taking fresh
    device copies (_async_ckpt_snapshot), which skips that blocking
    materialization — only set it if every device leaf is a snapshot no
    later computation can donate."""
    t0 = time.perf_counter()
    # a re-save of the SAME tag must never race the previous background
    # writer over the same files — serialize on it first
    flush_pending(save_dir, tag)
    ckpt_dir = os.path.join(save_dir, str(tag))
    os.makedirs(ckpt_dir, exist_ok=True)
    with _pending_lock:
        seq = _tag_seq[_pending_key(save_dir, tag)] = \
            _tag_seq.get(_pending_key(save_dir, tag), -1) + 1

    if async_save:
        # snapshot in-place-mutating HOST arrays NOW (offload/infinity
        # fp32 masters advance every step; a later background read must
        # not see them).  Device jax.Arrays: materialize here too UNLESS
        # the caller vouches they are donation-safe snapshots — the
        # engine device-copies them right after the step dispatch
        # (device_leaves_are_snapshots=True), which keeps the training
        # thread from blocking on the in-flight step, the exact stall
        # async_save exists to remove.
        def host_snap(x):
            if isinstance(x, np.ndarray):
                return x.copy()
            if not device_leaves_are_snapshots and isinstance(x, jax.Array):
                return np.asarray(x)
            return x

        model_state = jax.tree_util.tree_map(host_snap, model_state)
        if optim_state is not None:
            optim_state = jax.tree_util.tree_map(host_snap, optim_state)
        if layer_states is not None:
            layer_states = jax.tree_util.tree_map(host_snap, layer_states)

    def build_and_write(parallel: bool) -> int:
        """Phase 1: split sharded leaves, serialize, land every file by
        tmp+rename.  `parallel` fans serialization over the writer pool
        (sync path only: a pool thread submitting to its own pool and
        waiting could deadlock at max_workers in-flight saves)."""
        # sharded leaves are split into per-rank piece files; nothing is
        # gathered across hosts — each process serializes only what it
        # owns
        rank_pieces: Dict[int, Dict[str, Any]] = {}
        mstate = _split_sharded(model_state, rank_pieces, "model:")
        optim_skeleton = None
        if optim_state is not None:
            optim_skeleton = _split_sharded(optim_state, rank_pieces,
                                            "optim:")

        def _write(path, payload) -> int:
            return _atomic_write(path,
                                 serialization.msgpack_serialize(payload))

        jobs = []
        if jax.process_index() == 0:
            if layer_states is not None:
                # pipeline layout: layer params go to per-layer files
                # (reference pipe/module.py:520-578); the module file
                # keeps placeholders
                for idx, lp in sorted(layer_states.items()):
                    jobs.append((layer_ckpt_name(ckpt_dir, idx, mp_rank),
                                 _to_host(lp)))
                mstate = dict(mstate)
                mstate["module"] = {
                    "layers": [None] * len(mstate["module"]["layers"]),
                    "tied": _to_host(tied_states or {}),
                    "num_layers": len(mstate["module"]["layers"]),
                }
            jobs.append((model_ckpt_name(ckpt_dir, mp_rank),
                         _to_host(mstate)))
            if optim_skeleton is not None and 0 not in rank_pieces:
                rank_pieces[0] = {}

        for rank, pieces in rank_pieces.items():
            payload: Dict[str, Any] = {"__dstpu_ckpt_v2__": True,
                                       "pieces": pieces}
            if rank == 0 and optim_skeleton is not None:
                payload["state"] = _to_host(optim_skeleton)
            jobs.append((optim_ckpt_name(ckpt_dir, rank, mp_rank), payload))

        if parallel:
            futures = [_writer.submit(_write, path, payload)
                       for path, payload in jobs]
            return sum(f.result() for f in futures)
        return sum(_write(path, payload) for path, payload in jobs)

    def _finish(parallel: bool, chain_after: Optional[Future]):
        # phase 1 (every local file durably renamed), then phase 2: the
        # cross-process commit barrier + marker + latest.  Writes of
        # DIFFERENT tags overlap freely; commits chain in save-call
        # order so `latest` always ends on the newest save (a failed
        # predecessor doesn't block this commit — its own flush
        # surfaces the error).
        fault_point("ckpt.background_write")
        nbytes = build_and_write(parallel)
        if chain_after is not None:
            try:
                chain_after.result()
            except Exception:
                pass
        _commit(save_dir, tag, meta, save_latest, nbytes,
                commit_endpoint=commit_endpoint,
                commit_timeout_ms=commit_timeout_ms, seq=seq)

    root = os.path.realpath(save_dir)
    if async_save:
        with _pending_lock:
            prev = _dir_chain.get(root)
        done = _writer.submit(_finish, False, prev)
        with _pending_lock:
            _dir_chain[root] = done
        _track_pending(save_dir, tag, [done])
        COUNTERS.add("ckpt.pending", pending_count())
    else:
        with _pending_lock:
            prev = _dir_chain.get(root)
        _finish(True, prev)
        COUNTERS.add("ckpt.pending", 0)
    COUNTERS.add("ckpt.stall_ms",
                 int((time.perf_counter() - t0) * 1e6))
    logger.info(f"saved checkpoint {tag} to {ckpt_dir}"
                + (" (async)" if async_save else ""))
    return ckpt_dir


def uncommitted_tags(load_dir: str) -> List[str]:
    """Tag directories under `load_dir` WITHOUT a (readable) commit
    marker — interrupted or corrupt saves the skip-back must never
    resume from.  Only meaningful when the directory uses markers."""
    return _partition_tags(load_dir)[1]


def read_latest_tag(load_dir: str) -> Optional[str]:
    """The tag training should resume from: the `latest` pointer when its
    tag is committed (or the directory predates commit markers), else
    the newest committed tag — a save that died before its commit
    barrier is invisible here by construction.

    Every uncommitted/corrupt tag skipped on the way back is logged by
    name and counted in `ckpt.skipped_tags`, so a post-mortem can see
    HOW MANY saves died (one interrupted save is preemption noise; a
    pile of them is a storage or commit-barrier problem)."""
    tag = None
    latest = os.path.join(load_dir, "latest")
    if os.path.isfile(latest):
        with open(latest) as f:
            tag = f.read().strip() or None
    if tag is not None and is_tag_committed(load_dir, tag):
        return tag
    if not _dir_has_markers(load_dir):
        # legacy layout (pre-commit-marker saves, incl. the multi-host
        # pipeline writer's own barriered format): latest is authoritative
        return tag
    fallback, skipped = _partition_tags(load_dir)
    if skipped:
        COUNTERS.add("ckpt.skipped_tags", calls=len(skipped))
        for name in skipped:
            logger.warning(
                f"checkpoint tag {name!r} in {load_dir} has no commit "
                f"marker (interrupted or corrupt save) — skipped as a "
                f"resume candidate")
    if fallback:
        newest = fallback[-1]
        if tag is not None:
            logger.warning(
                f"checkpoint tag {tag!r} in {load_dir} was never "
                f"committed (interrupted save?); falling back to the "
                f"newest committed tag {newest!r}"
                + (f" (skipped {len(skipped)} uncommitted tag(s): "
                   f"{skipped})" if skipped else ""))
        return newest
    return None


def load_checkpoint_state(load_dir: str, tag: Optional[str] = None,
                          mp_rank: int = 0, dp_rank: int = 0,
                          resolve_streams: bool = True):
    """Returns (ckpt_dir, model_state, optim_state_or_None).

    Raises FileNotFoundError when there is nothing to resume from, and
    CheckpointIntegrityError when the requested tag exists but is
    uncommitted/incomplete (callers must NOT silently start fresh).

    resolve_streams=False leaves Infinity stream markers in place so a
    paged engine can walk the group files RAM-bounded instead of
    materializing the full fp32 set here."""
    # never race an in-flight background save over the same directory
    flush_pending(load_dir)
    explicit = tag is not None
    if tag is None:
        tag = read_latest_tag(load_dir)
        if tag is None:
            raise FileNotFoundError(
                f"no committed checkpoint in {load_dir}; pass an explicit "
                f"tag")
    ckpt_dir = os.path.join(load_dir, str(tag))
    if not os.path.isdir(ckpt_dir):
        raise FileNotFoundError(f"checkpoint tag not found: {ckpt_dir}")
    if explicit and not is_tag_committed(load_dir, tag) and \
            _dir_has_markers(load_dir):
        newest = committed_tags(load_dir)
        raise CheckpointIntegrityError(
            f"checkpoint tag {tag!r} in {load_dir} exists but has no "
            f"commit marker ({COMMIT_MARKER}) — the save was interrupted "
            f"before commit and the tag may be missing files"
            + (f"; newest committed tag is {newest[-1]!r}" if newest
               else ""))
    path = model_ckpt_name(ckpt_dir, mp_rank)
    if not os.path.isfile(path):
        raise CheckpointIntegrityError(
            f"checkpoint tag {tag!r} at {ckpt_dir} is incomplete: "
            f"missing model states file {os.path.basename(path)}")
    with open(path, "rb") as f:
        model_state = serialization.msgpack_restore(f.read())

    # pipeline layout: reassemble per-layer files if present
    module = model_state.get("module")
    if isinstance(module, dict) and "num_layers" in module:
        layers = []
        for i in range(int(module["num_layers"])):
            lpath = layer_ckpt_name(ckpt_dir, i, mp_rank)
            if os.path.isfile(lpath):
                with open(lpath, "rb") as f:
                    layers.append(serialization.msgpack_restore(f.read()))
            else:
                layers.append(None)
        model_state["module"] = {"layers": layers,
                                 "tied": module.get("tied", {})}

    pieces = _load_rank_pieces(ckpt_dir, mp_rank)
    if pieces:
        model_state = _reassemble(model_state, pieces, tag=tag)

    optim_state = None
    opath = optim_ckpt_name(ckpt_dir, dp_rank, mp_rank)
    if os.path.isfile(opath):
        with open(opath, "rb") as f:
            optim_state = serialization.msgpack_restore(f.read())
        if isinstance(optim_state, dict) and \
                optim_state.get("__dstpu_ckpt_v2__"):
            # v2 sharded layout: the skeleton lives in rank 0's file
            optim_state = _reassemble(optim_state.get("state"), pieces,
                                      tag=tag)
    if resolve_streams:
        if has_stream_markers(model_state):
            model_state = resolve_streamed(model_state, ckpt_dir)
        if optim_state is not None and has_stream_markers(optim_state):
            optim_state = resolve_streamed(optim_state, ckpt_dir)
    return ckpt_dir, model_state, optim_state
