"""Checkpoint save/load with reference-compatible layout.

Reference: deepspeed/runtime/engine.py:1462-1890. Layout kept:

    <save_dir>/<tag>/mp_rank_00_model_states.msgpack
    <save_dir>/<tag>/zero_pp_rank_<dp>_mp_rank_00_optim_states.msgpack
    <save_dir>/latest                     (text file holding the tag)

Redesign notes: arrays are gathered to host and serialized with flax's
msgpack (framework-neutral, no pickle). Because the on-disk format is the
FULL (unsharded) pytree, checkpoints are elastic by construction — loading
at a different world size just re-shards via device_put, which subsumes the
reference's ZeRO-1 elastic re-partition logic (zero/stage1.py:924-1155).
Multi-host jobs save from process 0 (params are addressable-replicated or
gathered); a tensorstore-sharded writer is the planned upgrade for >HBM
models.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional

import numpy as np

import jax
from flax import serialization

from ..utils.logging import logger


def _to_host(tree):
    def conv(x):
        if isinstance(x, (str, bytes, bool, int, float)) or x is None:
            return x  # plain scalars serialize natively; np.str_ would not
        return np.asarray(x)

    return jax.tree_util.tree_map(conv, tree)


def model_ckpt_name(ckpt_dir: str, mp_rank: int = 0) -> str:
    return os.path.join(ckpt_dir, f"mp_rank_{mp_rank:02d}_model_states.msgpack")


def optim_ckpt_name(ckpt_dir: str, dp_rank: int = 0, mp_rank: int = 0) -> str:
    return os.path.join(
        ckpt_dir,
        f"zero_pp_rank_{dp_rank}_mp_rank_{mp_rank:02d}_optim_states.msgpack")


def save_checkpoint_state(save_dir: str, tag: str, model_state: Dict[str, Any],
                          optim_state: Optional[Dict[str, Any]] = None,
                          save_latest: bool = True, mp_rank: int = 0,
                          dp_rank: int = 0) -> str:
    ckpt_dir = os.path.join(save_dir, str(tag))
    os.makedirs(ckpt_dir, exist_ok=True)

    # full-pytree format: exactly one writer per file — process 0 (shards
    # are gathered to host there); other processes only participate in the
    # implicit gather
    if jax.process_index() == 0:
        path = model_ckpt_name(ckpt_dir, mp_rank)
        with open(path, "wb") as f:
            f.write(serialization.msgpack_serialize(_to_host(model_state)))

        if optim_state is not None:
            opath = optim_ckpt_name(ckpt_dir, dp_rank, mp_rank)
            with open(opath, "wb") as f:
                f.write(serialization.msgpack_serialize(_to_host(optim_state)))

    if save_latest and jax.process_index() == 0:
        with open(os.path.join(save_dir, "latest"), "w") as f:
            f.write(str(tag))
    logger.info(f"saved checkpoint {tag} to {ckpt_dir}")
    return ckpt_dir


def read_latest_tag(load_dir: str) -> Optional[str]:
    latest = os.path.join(load_dir, "latest")
    if os.path.isfile(latest):
        with open(latest) as f:
            return f.read().strip()
    return None


def load_checkpoint_state(load_dir: str, tag: Optional[str] = None,
                          mp_rank: int = 0, dp_rank: int = 0):
    """Returns (ckpt_dir, model_state, optim_state_or_None)."""
    if tag is None:
        tag = read_latest_tag(load_dir)
        if tag is None:
            raise FileNotFoundError(
                f"no 'latest' file in {load_dir}; pass an explicit tag")
    ckpt_dir = os.path.join(load_dir, str(tag))
    path = model_ckpt_name(ckpt_dir, mp_rank)
    if not os.path.isfile(path):
        raise FileNotFoundError(f"checkpoint file not found: {path}")
    with open(path, "rb") as f:
        model_state = serialization.msgpack_restore(f.read())

    optim_state = None
    opath = optim_ckpt_name(ckpt_dir, dp_rank, mp_rank)
    if os.path.isfile(opath):
        with open(opath, "rb") as f:
            optim_state = serialization.msgpack_restore(f.read())
    return ckpt_dir, model_state, optim_state
