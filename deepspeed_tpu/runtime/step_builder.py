"""Schedule-driven step builder: ONE composition engine for every
jitted training-step program.

The engine used to hand-build three step paths (the fused gas==1
program, the full_scan global-batch program, and the split micro/apply
pair) with triplicated prep/grad/reduce/apply bodies.  This module
rebuilds them from four shared stage closures composed per a declarative
`StepSchedule`:

  prep    master params -> compute params (dtype cast, qwZ gather)
  grad    compute params + micro batch -> local or reduced gradients
  reduce  the DP gradient wire: in-program collectives (serial), or the
          encode half of the host-exchanged overlap wire
  apply   unscale, overflow check, clip, optimizer, ZeRO constraints,
          loss-scale update

Schedules:

  fused   gas==1: prep+grad+reduce+apply as ONE program
  scan    gas>1:  prep + lax.scan(grad+reduce) + apply as ONE program
  split   per-micro grad+reduce programs + an apply program (offload,
          manual forward/backward driving, heterogeneous batches)
  onebit  the compressed-wire fused step (engine._build_onebit_step)

  overlap (comm.overlap, stage<3 bucketed wire): per-micro GRADS
          programs emit encoded wire payloads, the host exchange
          (runtime/comm/overlap.py) moves them while the device runs
          the next micro's program, COMBINE programs reduce with
          bit-identical math, and the apply program is the serial one.
          With ZeRO-3 + quantized_weights the same exchange instead
          carries the qwZ parameter gather (prefetched right behind
          the previous step's apply), and the serial schedules run with
          an EXTERNAL prep: the gathered compute params arrive as a
          program argument.

Per-dispatch wire/qwZ counter accounting lives here too (CountedFn):
each emitted program knows how many gradient-wire reductions and qwZ
gathers one dispatch performs, so the byte math is written once and
holds on every schedule — including overlap, where the same plan bytes
ride the host exchange instead of an XLA collective.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec

from ..monitor.counters import COUNTERS
from ..utils.logging import log_dist
from .utils import clip_grad_norm, has_overflow


class StepSchedule(NamedTuple):
    """The declarative plan `StepBuilder.build` composes programs from."""

    composition: str        # "fused" | "scan" | "split" | "onebit"
    overlap_wire: bool      # grads/exchange/combine pipeline for the
    #                         bucketed gradient wire
    overlap_qwz: bool       # prep is external: the qwZ gather rides the
    #                         host exchange, prefetched across steps
    gas: int

    def describe(self) -> str:
        parts = [f"composition={self.composition}", f"gas={self.gas}"]
        if self.overlap_wire:
            parts.append("gradient wire host-exchanged (overlap)")
        if self.overlap_qwz:
            parts.append("qwZ gather host-exchanged (prefetch)")
        return "StepSchedule: " + ", ".join(parts)


class CountedFn:
    """A jitted step program plus its per-dispatch counter accounting:
    calling it records exactly the wire/qwZ bytes one dispatch moves
    (the engine's monitor picks the deltas up per step).  `.fn` is the
    raw jitted callable for AOT analysis (flops profiling) — analysis
    traces must not bump dispatch counters (and must not land trace
    spans either, for the same reason).

    `trace`: an optional zero-arg callable returning (recorder, step)
    when the in-flight step is sampled, else None — each dispatch then
    lands as a `dispatch.<name>` span on the trace timeline.  Dispatch
    wall only (programs run async): the span bounds the host-side
    enqueue, not device execution."""

    __slots__ = ("fn", "_account", "_trace", "_name")

    def __init__(self, fn, account=None, trace=None, name=None):
        self.fn = fn
        self._account = account
        self._trace = trace
        self._name = name

    def __call__(self, *args):
        if self._account is not None:
            self._account()
        tr = self._trace() if self._trace is not None else None
        if tr is None:
            return self.fn(*args)
        with tr[0].span(f"dispatch.{self._name}", "train", step=tr[1]):
            return self.fn(*args)


class StepBuilder:
    """Builds the engine's `_step_fns` dict from the current config,
    bucket plan, qwZ gather and overlap mode."""

    def __init__(self, engine):
        self.engine = engine

    # -- per-dispatch counter accounting (ONE home for all paths) -----

    def _account_wire(self, plan, events: int):
        """The plan's predicted per-reduction payload, recorded as the
        step executes (unlike the traced-occurrence `bucket.*`
        counters).  Identical math on every schedule: under overlap the
        same bytes ride the host exchange instead of an XLA
        collective."""
        if plan is None:
            return
        COUNTERS.add("grad_wire.reduce",
                     plan.wire_bytes_per_reduction * events,
                     calls=plan.collectives_per_reduction * events)
        COUNTERS.add("grad_wire.reduce_logical",
                     plan.wire_bytes_logical_per_reduction * events,
                     calls=plan.collectives_per_reduction * events)
        if plan.hierarchical:
            for name, nbytes, calls in (
                    ("intra", plan.wire_bytes_intra_per_reduction,
                     plan.collectives_intra_per_reduction),
                    ("intra_logical",
                     plan.wire_bytes_intra_logical_per_reduction,
                     plan.collectives_intra_per_reduction),
                    ("inter", plan.wire_bytes_inter_per_reduction,
                     plan.collectives_inter_per_reduction),
                    ("inter_logical",
                     plan.wire_bytes_inter_logical_per_reduction,
                     plan.collectives_inter_per_reduction)):
                COUNTERS.add(f"grad_wire.{name}", nbytes * events,
                             calls=calls * events)

    def _account_qwz(self, gather, events: int):
        if gather is None:
            return
        COUNTERS.add("qwz.gather",
                     gather.wire_bytes_per_gather * events,
                     calls=gather.collectives_per_gather * events)

    def _counted(self, fn, plan=None, wire_events=0, qwz=None,
                 qwz_events=0, name=None):
        eng = self.engine
        trace = None
        if name is not None:
            # Step fns are built before _init_run_monitor attaches the
            # tracer, so the gate has to live inside the closure.
            def trace():
                tr = getattr(eng, "_dispatch_tracer", None)
                tr = tr() if tr is not None else None
                return None if tr is None else (tr, eng.global_steps + 1)
        if not wire_events and not qwz_events:
            return CountedFn(fn, trace=trace, name=name)
        account = lambda: (self._account_wire(plan, wire_events),
                           self._account_qwz(qwz, qwz_events))
        return CountedFn(fn, account, trace=trace, name=name)

    # -- schedule resolution ------------------------------------------

    def plan_schedule(self) -> StepSchedule:
        eng = self.engine
        gas = eng.gradient_accumulation_steps()
        overlap_wire = (eng._overlap_mode == "wire"
                        and eng.bucket_plan is not None
                        and eng._capture_layers is None)
        overlap_qwz = (eng._overlap_mode == "qwz"
                       and eng._qwz_gather is not None)
        if eng._use_onebit_comm():
            comp = "onebit"
        elif overlap_wire:
            comp = "split"  # per-micro grads dispatches ARE the overlap
        elif gas == 1 and eng._offload is None:
            comp = "fused"
        elif gas > 1 and eng._offload is None:
            comp = "scan"
        else:
            comp = "split"
        return StepSchedule(comp, overlap_wire, overlap_qwz, gas)

    # -- program construction -----------------------------------------

    def build(self) -> dict:
        eng = self.engine
        schedule = self.plan_schedule()
        model = eng.module
        compute_dtype = eng.compute_dtype
        plan = eng.zero_plan
        opt = eng.optimizer
        gas = schedule.gas
        clip = float(eng._config.gradient_clipping or 0.0)
        prescale = eng._config.prescale_gradients
        predivide = float(eng._config.gradient_predivide_factor or 1.0)
        scaler = eng.loss_scaler
        pld_enabled = eng.progressive_layer_drop is not None
        capture = eng._capture_layers
        store_grads = eng._store_gradients
        mesh_info = eng.mesh_info

        def cast(tree, dtype):
            return jax.tree_util.tree_map(
                lambda x: x.astype(dtype) if jnp.issubdtype(
                    x.dtype, jnp.floating) else x, tree)

        qwz = eng._qwz_gather

        # -- prep stage: master params -> the compute-side replica ----
        if schedule.overlap_qwz:
            # external prep: the qwZ gather rides the host exchange and
            # the decoded compute params arrive as a program argument
            prep_params = None
        else:
            def prep_params(params):
                """Master params -> the compute-side replica the loss
                consumes: compute-dtype cast, then (qwZ) the stage-3
                gather rides int8/int4 blocks + fp16 scales and
                dequantizes on device — the master copy itself is never
                quantized."""
                cparams = cast(params, compute_dtype)
                if qwz is not None:
                    cparams = qwz.gather(cparams)
                return cparams

        def run_loss(p, batch, rng, pld_theta, loss_scale):
            """Shared scaled-loss body: returns (scaled_loss,
            (loss, caps)).  caps is {} unless layer-output hooks are
            registered (register_forward_hook) — then the model threads
            the requested block outputs out of the traced program as
            aux."""
            kwargs = {}
            if pld_enabled:
                kwargs = {"progressive_layer_drop": True,
                          "pld_theta": pld_theta}
            if capture is not None:
                kwargs["capture_layers"] = capture
            out = model.loss(p, batch, rng=rng, train=True, **kwargs)
            caps = {}
            if capture is not None:
                out, caps = out
            loss = out[0] if isinstance(out, tuple) else out
            scale_factor = loss_scale / (predivide if prescale else 1.0)
            return loss.astype(jnp.float32) * scale_factor, (loss, caps)

        # -- grad + reduce stage: implicit XLA psum vs the bucketed
        #    wire (in-program), vs the overlap wire's encode half
        wire_plan = eng.bucket_plan if capture is None else None
        if eng.bucket_plan is not None and wire_plan is None:
            log_dist("layer-output capture active: this step program "
                     "rides the implicit gradient wire (captures are "
                     "threaded through the global-loss trace)", ranks=[0])

        def implicit_grads(cparams, batch, rng, pld_theta, loss_scale):
            """Global-mean loss: XLA inserts one psum per grad leaf."""
            grads, (loss, caps) = jax.grad(
                lambda p: run_loss(p, batch, rng, pld_theta, loss_scale),
                has_aux=True)(cparams)
            return cast(grads, jnp.float32), loss, caps

        smap_kwargs = {}
        if wire_plan is not None:
            mesh = mesh_info.mesh
            P = PartitionSpec
            data_axes = mesh_info.data_axes  # outermost first
            batch_spec = mesh_info.data_spec
            inner_size = mesh_info.data_inner_size
            smap_kwargs = dict(mesh=mesh, axis_names=set(data_axes),
                               check_vma=False)

            def _global_dp_rank():
                # linearized rank over the (possibly factored) data
                # axis: outer-major matches the mesh's device order
                if len(data_axes) == 1:
                    return jax.lax.axis_index(data_axes[0])
                return (jax.lax.axis_index(data_axes[0]) * inner_size
                        + jax.lax.axis_index(data_axes[1]))

            def _local_grads(cp, b, r, ls, th):
                # per-shard rng decorrelation: the implicit wire draws
                # ONE global dropout mask; each shard must not repeat it
                r = jax.random.fold_in(r, _global_dp_rank())
                grads, (loss, _) = jax.grad(
                    lambda p: run_loss(p, b, r, th, ls), has_aux=True)(cp)
                buckets = wire_plan.flatten(cast(grads, jnp.float32))
                return buckets, jax.lax.pmean(loss, data_axes)

            def _local_step(cp, b, r, ls, th):
                buckets, loss = _local_grads(cp, b, r, ls, th)
                return wire_plan.reduce(buckets), loss

            smapped = jax.shard_map(
                _local_step,
                in_specs=(P(), P(batch_spec), P(), P(), P()),
                out_specs=(wire_plan.bucket_out_specs(), P()),
                **smap_kwargs)

            def compute_grads(cparams, batch, rng, pld_theta, loss_scale):
                """LOCAL grads under shard_map, mean-reduced through the
                BucketPlan: one fused collective per bucket
                (psum_scatter under ZeRO>=2) instead of one psum per
                leaf."""
                buckets, loss = smapped(cparams, batch, rng, loss_scale,
                                        pld_theta)
                return wire_plan.unflatten(buckets), loss, {}
        else:
            compute_grads = implicit_grads

        # -- apply stage (shared core: fused tail == boundary apply) --

        def apply_core(params, opt_state, scaler_state, grads, lr,
                       gas_div):
            """Unscale -> overflow -> clip -> optimizer -> branchless
            skip-step -> ZeRO constraints -> loss-scale update.  The
            single body behind BOTH the boundary apply program and the
            fused/scan programs' in-program tail (gas_div folds the
            accumulation count into the unscale denominator)."""
            loss_scale = scaler_state["cur_scale"]
            overflow = has_overflow(grads)
            denom = loss_scale * gas_div
            if prescale:
                denom = denom / predivide
            grads = jax.tree_util.tree_map(lambda g: g / denom, grads)
            grad_norm = jnp.asarray(0.0, jnp.float32)
            if clip > 0.0:
                grads, grad_norm = clip_grad_norm(grads, clip)
            extras = {}
            if store_grads:
                # zeroed on overflow: the step is skipped, so consumers
                # (e.g. GradientNoiseScale) must not ingest inf/nan
                extras["grads"] = jax.tree_util.tree_map(
                    lambda g: jnp.where(overflow, 0.0, g), grads)
            # grads here are already DP-averaged, so a 1-bit optimizer
            # on this path runs dense (comm_axis=None).  The compressed
            # hot path is engine._build_onebit_step: a shard_map fused
            # step with LOCAL grads where the optimizer owns the wire.
            new_params, new_opt = opt.update(grads, opt_state, params,
                                             lr=lr)

            # branchless skip-step on overflow (reference: step skipped,
            # scale halved — fp16/loss_scaler + stage2.py:1385-1404)
            sel = lambda new, old: jax.tree_util.tree_map(
                lambda n, o: jnp.where(overflow, o, n), new, old)
            new_params = sel(new_params, params)
            new_opt = sel(new_opt, opt_state)

            new_params = plan.constrain_params(new_params)
            new_opt = plan.constrain_opt_state(new_opt)
            new_scaler = scaler.jit_update(scaler_state, overflow)
            return (new_params, new_opt, new_scaler, overflow, grad_norm,
                    extras)

        def apply_step(params, opt_state, scaler_state, acc, lr):
            (new_params, new_opt, new_scaler, overflow, grad_norm,
             extras) = apply_core(params, opt_state, scaler_state, acc,
                                  lr, gas_div=gas)
            zero_acc = jax.tree_util.tree_map(jnp.zeros_like, acc)
            return (new_params, new_opt, new_scaler, zero_acc, overflow,
                    grad_norm, extras)

        # -- compositions ---------------------------------------------

        def micro_step(cparams_or_params, acc, batch, rng, loss_scale,
                       pld_theta):
            if prep_params is not None:
                cparams = prep_params(cparams_or_params)
            else:
                cparams = cparams_or_params
            grads, loss, caps = compute_grads(cparams, batch, rng,
                                              pld_theta, loss_scale)
            new_acc = jax.tree_util.tree_map(jnp.add, acc, grads)
            new_acc = plan.constrain_grads(new_acc)
            return loss, new_acc, {"layer_outputs": caps}

        def full_step(params, opt_state, scaler_state, batch, rng, lr,
                      pld_theta, cparams=None):
            """Whole training step (fwd+bwd+optimizer+scaler) as ONE
            program — the gas==1 fast path.  The split micro/apply pair
            writes the fp32 gradient tree to HBM at the end of one
            program and reads it back at the start of the next (plus a
            second host dispatch per step — expensive over a tunneled
            runtime); here the gradients never outlive the fused
            program and XLA can overlap the optimizer with the tail of
            the backward."""
            loss_scale = scaler_state["cur_scale"]
            if prep_params is not None:
                cparams = prep_params(params)
            grads, loss, caps = compute_grads(cparams, batch, rng,
                                              pld_theta, loss_scale)
            grads = plan.constrain_grads(grads)
            (new_params, new_opt, new_scaler, overflow, grad_norm,
             extras) = apply_core(params, opt_state, scaler_state, grads,
                                  lr, gas_div=1)
            extras = dict(extras)
            extras["layer_outputs"] = caps
            return (new_params, new_opt, new_scaler, loss, overflow,
                    grad_norm, extras)

        def scan_batch_step(params, opt_state, scaler_state, batches,
                            rngs, lr, pld_theta, cparams=None):
            """Whole GLOBAL batch (gas micro steps + update) as ONE
            program: micro batches arrive stacked on a leading [gas]
            dim and a lax.scan accumulates grads — one host dispatch
            per global batch instead of gas+1 (train_batch uses this
            when the iterator is stackable)."""
            loss_scale = scaler_state["cur_scale"]
            if prep_params is not None:
                # the gather sits OUTSIDE the scan body: 1 event/batch
                cparams = prep_params(params)

            # captured layer outputs ride the scan CARRY (overwritten
            # per micro step — reference hooks overwrite per forward),
            # not the stacked ys: as ys they'd materialize a [gas, ...]
            # buffer per hooked layer only for the last slice to survive
            caps0 = {}
            if capture is not None:
                caps_struct = jax.eval_shape(
                    lambda p, b, r, ls, th: run_loss(p, b, r, th,
                                                     ls)[1][1],
                    cparams,
                    jax.tree_util.tree_map(lambda x: x[0], batches),
                    rngs[0], loss_scale, pld_theta)
                caps0 = jax.tree_util.tree_map(
                    lambda s: jnp.zeros(s.shape, s.dtype), caps_struct)

            def body(carry, inp):
                acc, _ = carry
                batch_i, rng_i = inp
                grads, loss, caps = compute_grads(cparams, batch_i,
                                                  rng_i, pld_theta,
                                                  loss_scale)
                acc = jax.tree_util.tree_map(jnp.add, acc, grads)
                return (plan.constrain_grads(acc), caps), loss

            acc0 = jax.tree_util.tree_map(
                lambda p: jnp.zeros(p.shape, jnp.float32), params)
            acc0 = plan.constrain_grads(acc0)
            (acc, caps), losses = jax.lax.scan(body, (acc0, caps0),
                                               (batches, rngs))
            (new_params, new_opt, new_scaler, zero_acc, overflow,
             grad_norm, extras) = apply_step(params, opt_state,
                                             scaler_state, acc, lr)
            extras = dict(extras)
            extras["layer_outputs"] = caps
            return (new_params, new_opt, new_scaler, jnp.mean(losses),
                    overflow, grad_norm, extras)

        # -- overlap-wire composition: grads -> host exchange ->
        #    combine (runtime/comm/overlap.py drives the exchange) ----

        def build_overlap_fns():
            P = PartitionSpec
            mesh = mesh_info.mesh

            def _encode_local(cp, b, r, ls, th):
                buckets, loss = _local_grads(cp, b, r, ls, th)
                return wire_plan.overlap_encode(buckets), loss

            smapped_enc = jax.shard_map(
                _encode_local,
                in_specs=(P(), P(batch_spec), P(), P(), P()),
                out_specs=(wire_plan.overlap_encode_out_spec(), P()),
                **smap_kwargs)

            def grads_step(params, batch, rng, loss_scale, pld_theta):
                cparams = prep_params(params)
                payload, loss = smapped_enc(cparams, batch, rng,
                                            loss_scale, pld_theta)
                return loss, payload

            smapped_comb = jax.shard_map(
                wire_plan.overlap_combine, in_specs=(P(),),
                out_specs=wire_plan.bucket_out_specs(), **smap_kwargs)

            def combine_step(acc, matrix):
                buckets = smapped_comb(matrix)
                grads = wire_plan.unflatten(buckets)
                new_acc = jax.tree_util.tree_map(jnp.add, acc, grads)
                return plan.constrain_grads(new_acc)

            return (jax.jit(grads_step),
                    jax.jit(combine_step, donate_argnums=(0,)))

        # -- emit per the schedule ------------------------------------

        # under overlap_qwz the gather is EXTERNAL (its own counted
        # encode dispatch) — the serial compositions must not also
        # count a per-dispatch gather event
        qwz_int = None if schedule.overlap_qwz else qwz

        fns = {}
        donate_apply = jax.jit(apply_step, donate_argnums=(0, 1, 2, 3))
        fns["apply"] = self._counted(donate_apply, name="apply")
        # lr=None (optimizer-default) is a static arg value: jit treats
        # None as an empty pytree, giving that case its own single trace

        if schedule.overlap_wire:
            grads_fn, combine_fn = build_overlap_fns()
            fns["grads"] = self._counted(grads_fn, plan=wire_plan,
                                         wire_events=1, name="grads")
            fns["combine"] = self._counted(combine_fn, name="combine")
            log_dist(self._describe(schedule), ranks=[0])
            return fns

        donate_micro = jax.jit(micro_step, donate_argnums=(1,))
        fns["micro"] = self._counted(donate_micro, plan=wire_plan,
                                     wire_events=1, qwz=qwz_int,
                                     qwz_events=1, name="micro")
        if schedule.composition == "onebit":
            fns["full"] = self._counted(eng._build_onebit_step(cast),
                                        name="full")
        elif schedule.composition == "fused":
            # scaler state (arg 2) is NOT donated: it stays readable
            # between the fused forward and step(), so engine.loss_scale
            # keeps reference pre-update semantics until the boundary
            fns["full"] = self._counted(
                jax.jit(full_step, donate_argnums=(0, 1)),
                plan=wire_plan, wire_events=1, qwz=qwz_int, qwz_events=1,
                name="full")
        elif schedule.composition == "scan":
            fns["full_scan"] = self._counted(
                jax.jit(scan_batch_step, donate_argnums=(0, 1)),
                plan=wire_plan, wire_events=gas, qwz=qwz_int,
                qwz_events=1, name="full_scan")
        log_dist(self._describe(schedule), ranks=[0])
        return fns

    def _describe(self, schedule: StepSchedule) -> str:
        """Schedule log line, annotated when this build is the SERIAL
        rebuild after a coordinated runtime demotion of the overlap
        wire — a demoted run's logs must say why its schedule changed
        mid-run, not just that it did."""
        desc = schedule.describe()
        demoted = getattr(self.engine, "_demoted_reason", None)
        if demoted:
            desc += (" [rebuilt on the serial wire by runtime demotion: "
                     f"{demoted}]")
        return desc
