"""Row-sparse ("CSR") tensor for sparse embedding gradients.

Reference: deepspeed/runtime/csr_tensor.py:11 — row-compressed
representation (IndexedSlices-style) used by the engine's
`sparse_gradients` allreduce path (reference engine.py:1397-1449): only
the touched embedding rows travel over the wire.

TPU note: inside jit XLA already averages dense grads with psum; this
class serves the out-of-jit path (host-side grad exchange, e.g. the
offload runtime) and API parity. `add` concatenates (duplicate row
indices accumulate on to_dense via scatter-add) exactly like the
reference.
"""

from __future__ import annotations

from typing import List, Optional

import jax.numpy as jnp


class CSRTensor:
    def __init__(self, dense_tensor=None):
        self.orig_dense_tensor = dense_tensor
        if dense_tensor is not None:
            assert dense_tensor.ndim == 2, "CSRTensor expects [rows, dim]"
            row_mass = jnp.sum(jnp.abs(dense_tensor), axis=1)
            self.indices = jnp.nonzero(row_mass)[0]
            self.values = dense_tensor[self.indices]
            self.dense_size = list(dense_tensor.shape)
        else:
            self.indices = None
            self.values = None
            self.dense_size: Optional[List[int]] = None

    @staticmethod
    def type():
        return "deepspeed.CSRTensor"

    def to_dense(self):
        out = jnp.zeros(self.dense_size, self.values.dtype)
        return out.at[self.indices].add(self.values)

    def sparse_size(self):
        index_size = int(self.indices.shape[0])
        value_size = int(self.values.shape[0] * self.values.shape[1])
        dense_size = self.dense_size[0] * self.dense_size[1]
        return index_size + value_size, dense_size

    def add(self, b: "CSRTensor"):
        assert self.dense_size == b.dense_size
        self.indices = jnp.concatenate([self.indices, b.indices])
        self.values = jnp.concatenate([self.values, b.values])

    def __str__(self):
        sparse_size, dense_size = self.sparse_size()
        return (f"deepspeed_tpu.CSRTensor(indices_size={self.indices.shape}, "
                f"values_size={self.values.shape}, "
                f"dense_size={self.dense_size}, "
                f"reduction_factor={dense_size / max(sparse_size, 1):.2f})")

    __repr__ = __str__
