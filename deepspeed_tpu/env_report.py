"""`ds_report` — environment and op-compatibility report.

Reference: deepspeed/env_report.py:23-109 (op install/compat table, torch
and CUDA versions). TPU version: jax/jaxlib/libtpu versions, device
inventory, native-extension (C++) build status from the op_builder
registry.
"""

from __future__ import annotations

import importlib
import sys

GREEN = "\033[92m"
RED = "\033[91m"
YELLOW = "\033[93m"
END = "\033[0m"
SUCCESS = f"{GREEN}[OKAY]{END}"
WARNING = f"{YELLOW}[WARNING]{END}"
FAIL = f"{RED}[FAIL]{END}"
NO = f"{YELLOW}[NO]{END}"


def op_report(out=sys.stdout):
    from .ops.op_builder import ALL_OPS

    max_dots = 23
    print("-" * 74, file=out)
    print("op name" + "." * (max_dots - len("op name")) +
          " compatible | built", file=out)
    print("-" * 74, file=out)
    for name, builder_cls in sorted(ALL_OPS.items()):
        builder = builder_cls()
        try:
            compatible = builder.is_compatible()
        except Exception:
            compatible = False
        # probe the cached artifact only — a status report must not
        # compile extensions as a side effect
        try:
            built = builder.lib_path().exists()
        except Exception:
            built = False
        status = SUCCESS if compatible else NO
        built_s = SUCCESS if built else (WARNING if compatible else NO)
        print(f"{name}{'.' * (max_dots - len(name))} {status:>18} | "
              f"{built_s}", file=out)
    print("-" * 74, file=out)


def kernel_report(out=sys.stdout):
    """The Pallas kernel registry's probe table (deepspeed_tpu/kernels):
    each registered hot-loop op, whether its Pallas path would engage
    on this fabric, and the registry's reason when it declines — the
    op_builder table's runtime-kernel sibling."""
    from .kernels import probe_report

    max_dots = 23
    print("-" * 74, file=out)
    print("kernel op" + "." * (max_dots - len("kernel op")) +
          " impl | reason", file=out)
    print("-" * 74, file=out)
    for name, verdict, reason in probe_report():
        status = SUCCESS if verdict == "pallas" else NO
        tail = verdict if verdict == "pallas" else f"{verdict}: {reason}"
        print(f"{name}{'.' * (max_dots - len(name))} {status:>18} | "
              f"{tail}", file=out)
    print("-" * 74, file=out)


def serving_report(out=sys.stdout, engine=None):
    """The serving-side status block: whether the paged-attention
    Pallas kernel would engage on this fabric (and the registry's
    reason when it declines), the configured KV storage dtype, the
    prefix-cache switch, and the resident pinned-session count.
    Without a live engine the config rows report `ServeConfig()`
    defaults — what an engine built here WOULD run with."""
    from .kernels import probe_report

    verdict, reason = "unknown", "not registered"
    for name, v, r in probe_report():
        if name == "paged_attention":
            verdict, reason = v, r
            break
    if engine is not None:
        cfg = engine.config
        kv_dtype = engine.kv.quant_wire or (
            str(cfg.kv_dtype) if cfg.kv_dtype is not None else "dense")
        sessions = f"{engine.resident_sessions}"
    else:
        from .serving.engine import ServeConfig

        cfg = ServeConfig()
        kv_dtype = (str(cfg.kv_dtype) if cfg.kv_dtype is not None
                    else "dense") + " (default)"
        sessions = "0 (no live engine)"
    kern_s = SUCCESS if verdict == "pallas" else NO
    kern_tail = verdict if verdict == "pallas" else f"{verdict}: {reason}"
    pfx = "enabled" if cfg.prefix_cache else "disabled"
    rows = [("paged attention kernel", f"{kern_s} {kern_tail}"),
            ("kv cache dtype", kv_dtype),
            ("prefix cache", pfx),
            ("resident sessions", sessions)]
    print("DeepSpeed-TPU serving status:", file=out)
    for name, val in rows:
        print(f"{name} {'.' * max(1, 24 - len(name))} {val}", file=out)
    print("-" * 74, file=out)


def _probe_devices(timeout_s: int = 60):
    """Device inventory via a subprocess with a hard timeout: a status
    report must never hang, and accelerator-plugin backend init CAN hang
    indefinitely when its transport is down (observed with the tunneled
    TPU plugin — same hardening as bench.py's probe)."""
    import json
    import subprocess

    # honor an explicit JAX_PLATFORMS in the child: the ambient
    # sitecustomize may pin another platform via jax.config (which beats
    # the env var), so re-assert the user's choice before first use
    code = ("import os, jax, json\n"
            "p = os.environ.get('JAX_PLATFORMS')\n"
            "if p:\n"
            "    jax.config.update('jax_platforms', p)\n"
            "d = jax.devices()\n"
            "print(json.dumps([jax.default_backend(), len(d), "
            "d[0].device_kind]))")
    try:
        r = subprocess.run([sys.executable, "-c", code],
                           capture_output=True, timeout=timeout_s, text=True)
        if r.returncode == 0:
            backend, n, kind = json.loads(r.stdout.strip().splitlines()[-1])
            return backend, f"{n} x {kind}"
        return None, f"unavailable (rc={r.returncode})"
    except subprocess.TimeoutExpired:
        return None, (f"unavailable (backend init exceeded {timeout_s}s — "
                      "accelerator transport down?)")
    except Exception as e:  # pragma: no cover
        return None, f"unavailable ({type(e).__name__}: {e})"


def debug_report(out=sys.stdout):
    import jax

    rows = [("deepspeed_tpu version",
             importlib.import_module("deepspeed_tpu").__version__),
            ("python version", sys.version.split()[0]),
            ("jax version", jax.__version__)]
    try:
        import jaxlib
        rows.append(("jaxlib version", jaxlib.__version__))
    except Exception:
        pass
    for mod in ("flax", "optax", "numpy"):
        try:
            rows.append((f"{mod} version",
                         importlib.import_module(mod).__version__))
        except Exception:
            rows.append((f"{mod} version", "not installed"))
    backend, devices = _probe_devices()
    if backend is not None:
        rows.append(("backend", backend))
    rows.append(("devices", devices))
    print("DeepSpeed-TPU general environment info:", file=out)
    for name, val in rows:
        print(f"{name} {'.' * max(1, 24 - len(name))} {val}", file=out)


def main(out=sys.stdout):
    op_report(out=out)
    kernel_report(out=out)
    serving_report(out=out)
    debug_report(out=out)


cli_main = main

if __name__ == "__main__":
    main()
