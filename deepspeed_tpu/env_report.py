"""`ds_report` — environment and op-compatibility report.

Reference: deepspeed/env_report.py:23-109 (op install/compat table, torch
and CUDA versions). TPU version: jax/jaxlib/libtpu versions, device
inventory, native-extension (C++) build status from the op_builder
registry.
"""

from __future__ import annotations

import importlib
import sys

GREEN = "\033[92m"
RED = "\033[91m"
YELLOW = "\033[93m"
END = "\033[0m"
SUCCESS = f"{GREEN}[OKAY]{END}"
WARNING = f"{YELLOW}[WARNING]{END}"
FAIL = f"{RED}[FAIL]{END}"
NO = f"{YELLOW}[NO]{END}"


def op_report(out=sys.stdout):
    from .ops.op_builder import ALL_OPS

    max_dots = 23
    print("-" * 74, file=out)
    print("op name" + "." * (max_dots - len("op name")) +
          " compatible | built", file=out)
    print("-" * 74, file=out)
    for name, builder_cls in sorted(ALL_OPS.items()):
        builder = builder_cls()
        try:
            compatible = builder.is_compatible()
        except Exception:
            compatible = False
        # probe the cached artifact only — a status report must not
        # compile extensions as a side effect
        try:
            built = builder.lib_path().exists()
        except Exception:
            built = False
        status = SUCCESS if compatible else NO
        built_s = SUCCESS if built else (WARNING if compatible else NO)
        print(f"{name}{'.' * (max_dots - len(name))} {status:>18} | "
              f"{built_s}", file=out)
    print("-" * 74, file=out)


def debug_report(out=sys.stdout):
    import jax

    rows = [("deepspeed_tpu version",
             importlib.import_module("deepspeed_tpu").__version__),
            ("python version", sys.version.split()[0]),
            ("jax version", jax.__version__)]
    try:
        import jaxlib
        rows.append(("jaxlib version", jaxlib.__version__))
    except Exception:
        pass
    for mod in ("flax", "optax", "numpy"):
        try:
            rows.append((f"{mod} version",
                         importlib.import_module(mod).__version__))
        except Exception:
            rows.append((f"{mod} version", "not installed"))
    try:
        devs = jax.devices()
        rows.append(("backend", jax.default_backend()))
        rows.append(("devices", f"{len(devs)} x {devs[0].device_kind}"))
    except Exception as e:
        rows.append(("devices", f"unavailable ({e})"))
    print("DeepSpeed-TPU general environment info:", file=out)
    for name, val in rows:
        print(f"{name} {'.' * max(1, 24 - len(name))} {val}", file=out)


def main(out=sys.stdout):
    op_report(out=out)
    debug_report(out=out)


cli_main = main

if __name__ == "__main__":
    main()
