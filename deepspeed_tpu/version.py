"""Version info for deepspeed_tpu.

Mirrors the reference's version stamping (/root/reference/version.txt,
deepspeed/git_version_info.py) without requiring a build step.
"""

__version__ = "0.3.0"  # round 5: in-kernel dropout/masks, host-TCP 1-bit wire, streamed BERT CE, on-chip autotune, first TPU-measured BERT rows
version = __version__
git_hash = "unknown"
git_branch = "main"

try:  # best-effort git stamp, mirroring reference git_version_info.py
    import os
    import subprocess

    _repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    git_hash = (
        subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            cwd=_repo, capture_output=True, text=True, timeout=2,
        ).stdout.strip()
        or "unknown"
    )
except Exception:  # pragma: no cover - git not available
    pass

# populated lazily by op_builder registry (reference: installed_ops dict)
installed_ops = {}
compatible_ops = {}
