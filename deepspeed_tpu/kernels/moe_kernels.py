"""Pallas sort-based MoE dispatch/combine (op 3, moe/dispatch.py).

The jnp oracles move tokens with a scatter-add (`sorted_dispatch_ref`)
and a gated gather (`sorted_combine_ref`).  On TPU the scatter lowers
to a serialized HBM update stream; these kernels re-express both
directions as per-slot / per-token GATHERS driven by scalar-prefetched
index tables, which Mosaic turns into plain async block copies:

* dispatch — the oracle's kept destinations are UNIQUE (capacity
  assignment), so the scatter has an exact inverse permutation.  A tiny
  jnp prologue inverts `dest` into `src_tok[slot] -> token | -1`; the
  kernel then copies `x[src_tok[s]]` into slot `s` (zeros when empty).
  Parity is bit-exact: every slot is a verbatim row copy or zeros,
  matching add-into-zeros.
* combine — slot sources `src[a, n]` (the trash row E*C when dropped)
  and fp32 gate weights ride SMEM; each token accumulates its k expert
  rows in ascending assignment order — the same term order as the
  oracle's axis-0 sum.  Parity is tolerance-bounded at ~1 ulp: the
  accumulator's multiply-add may fuse to an FMA where the oracle's
  separate mul/sum rounds twice.

Both oracles are vmapped over batch rows by callers; these wrappers
are shaped identically so `dispatch("moe_dispatch", ...)` drops in
under the same vmap.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..ops.transformer.flash_attention import compiler_params_cls


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _clamp(i):
    return jnp.maximum(i, 0)


# ---------------------------------------------------------------------------
# dispatch: tokens -> [E, C, D] capacity buckets
# ---------------------------------------------------------------------------


def _dispatch_kernel(tok_ref, x_ref, o_ref):
    s = pl.program_id(0)
    # empty slots (tok == -1) read a clamped dummy row; the where zeroes it
    o_ref[...] = jnp.where(tok_ref[s] >= 0, x_ref[...],
                           jnp.zeros_like(o_ref))


def sorted_dispatch_pallas(x, eidx, pos, keep, num_experts: int,
                           capacity: int):
    """Drop-in for `sorted_dispatch_ref` (bit-exact)."""
    k, N = eidx.shape
    D = x.shape[-1]
    E, C = num_experts, capacity
    flat_keep = keep.reshape(-1)
    dest = jnp.where(flat_keep, eidx.reshape(-1) * C + pos.reshape(-1),
                     E * C)
    # invert the (unique-per-slot) scatter: slot -> assignment -> token.
    # assignment a carries token a % N (the oracle's tiled gather order)
    slot_a = jnp.full((E * C + 1,), -1, jnp.int32).at[dest].set(
        jnp.arange(k * N, dtype=jnp.int32))[:E * C]
    src_tok = jnp.where(slot_a >= 0,
                        jax.lax.rem(slot_a, jnp.int32(N)),
                        jnp.int32(-1))

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(E * C,),
        in_specs=[
            pl.BlockSpec((1, D), lambda s, tok: (_clamp(tok[s]), 0)),
        ],
        out_specs=pl.BlockSpec((1, D), lambda s, tok: (s, 0)),
    )
    buf = pl.pallas_call(
        _dispatch_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((E * C, D), x.dtype),
        compiler_params=compiler_params_cls()(
            dimension_semantics=(pltpu.PARALLEL,)),
        interpret=_interpret(),
    )(src_tok, x)
    return buf.reshape(E, C, D)


# ---------------------------------------------------------------------------
# combine: gated gather back to [N, D]
# ---------------------------------------------------------------------------


def _combine_kernel(src_ref, w_ref, flat_ref, o_ref, acc, *, k, N):
    n = pl.program_id(0)
    a = pl.program_id(1)

    @pl.when(a == 0)
    def _init():
        acc[...] = jnp.zeros_like(acc)

    # dropped assignments point src at the zero trash row AND carry
    # w == 0, so the term vanishes exactly like the oracle's
    acc[...] = acc[...] + flat_ref[...].astype(jnp.float32) * w_ref[a, n]

    @pl.when(a == k - 1)
    def _finish():
        o_ref[...] = acc[...].astype(o_ref.dtype)


def sorted_combine_pallas(expert_out, eidx, gate, pos, keep):
    """Drop-in for `sorted_combine_ref` (~1-ulp tolerance parity)."""
    E, C, D = expert_out.shape
    k, N = eidx.shape
    flat = jnp.concatenate(
        [expert_out.reshape(E * C, D),
         jnp.zeros((1, D), expert_out.dtype)])
    src = jnp.where(keep.reshape(-1),
                    eidx.reshape(-1) * C + pos.reshape(-1),
                    E * C).astype(jnp.int32)
    # the oracle weights in expert_out's dtype; replicate the rounding
    # by casting gate*keep through that dtype before the fp32 multiply
    w = (gate * keep).astype(expert_out.dtype).astype(jnp.float32)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(N, k),
        in_specs=[
            pl.BlockSpec((1, D), lambda n, a, src, w: (src[a * N + n], 0)),
        ],
        out_specs=pl.BlockSpec((1, D), lambda n, a, src, w: (n, 0)),
        scratch_shapes=[pltpu.VMEM((1, D), jnp.float32)],
    )
    return pl.pallas_call(
        functools.partial(_combine_kernel, k=k, N=N),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((N, D), expert_out.dtype),
        compiler_params=compiler_params_cls()(
            dimension_semantics=(pltpu.PARALLEL, pltpu.ARBITRARY)),
        interpret=_interpret(),
    )(src, w, flat)
