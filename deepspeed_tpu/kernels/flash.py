"""Registry adapters for dense causal flash attention (op 4).

The heavy lifting already lives in ops/transformer/flash_attention.py
(the Pallas streaming kernel) and ops/transformer/attention.py
(`xla_attention`, the fp32-softmax einsum chain that IS the correctness
oracle).  This module only reconciles the two signatures so
`dispatch("flash_attention", ...)` can run either side with identical
kwargs — parity is tolerance-bounded (different reduction order:
online-softmax tiles vs one fused softmax).

Both sides take BSHD `[batch, seq, heads, head_dim]` and return BSHD.
"""

from __future__ import annotations

from typing import Optional

from ..ops.transformer.attention import xla_attention
from ..ops.transformer.flash_attention import (DEFAULT_BLOCK_K,
                                               DEFAULT_BLOCK_Q,
                                               flash_attention)


def flash_attention_pallas(q, k, v, *, causal: bool = True,
                           scale: Optional[float] = None,
                           block_q: int = DEFAULT_BLOCK_Q,
                           block_k: int = DEFAULT_BLOCK_K,
                           dropout_rate: float = 0.0, dropout_rng=None):
    return flash_attention(q, k, v, causal=causal, scale=scale,
                           block_q=block_q, block_k=block_k,
                           dropout_rate=dropout_rate,
                           dropout_rng=dropout_rng)


def flash_attention_reference(q, k, v, *, causal: bool = True,
                              scale: Optional[float] = None,
                              block_q: int = DEFAULT_BLOCK_Q,
                              block_k: int = DEFAULT_BLOCK_K,
                              dropout_rate: float = 0.0,
                              dropout_rng=None):
    # block sizes are a kernel tuning knob with no oracle meaning —
    # accepted so both impls take the same kwargs, then ignored
    del block_q, block_k
    return xla_attention(q, k, v, causal=causal, scale=scale,
                         dropout_rate=dropout_rate,
                         dropout_rng=dropout_rng,
                         train=dropout_rate > 0.0)
