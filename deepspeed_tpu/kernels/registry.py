"""THE kernel-selection mechanism: op_builder-style registry of Pallas
hot-loop implementations with jnp correctness oracles.

The paper's pitch — "csrc/transformer + sparse_attention kernels
reimplemented as Pallas/XLA ops behind op_builder" — lands here.  Every
hot inner loop that has a Pallas implementation registers a `KernelOp`
with:

* `pallas(...)`   — the Pallas TPU kernel (runs under the Pallas
  interpreter off-TPU, which is how tier-1 pins parity on CPU);
* `oracle(...)`   — the pre-existing jnp expression, kept bit-for-bit
  (it IS the correctness contract: exact for the integer codecs and MoE
  permutations, tolerance-bounded for attention);
* `is_compatible()` / `compatibility_message()` — op_builder-style
  capability probing: Pallas is only *selected* natively on a TPU
  backend, gated per-op by `DS_KERNEL_{NAME}=0` (the `DS_BUILD_*`
  convention from ops/op_builder/builder.py);
* `auto_supports(...)` — the per-call shape heuristic `impl="auto"`
  consults (e.g. sparse attention's block%128 / head-dim tiling rule).

Selection contract (`resolve_impl`):

* `"auto"`  — pallas iff the probe AND the shape heuristic pass (an
  autotuner-recorded winner, keyed per fabric fingerprint, overrides
  the heuristic — see `record_winner`); otherwise the jnp oracle.
* `"pallas"` — the kernel, NO silent fallback: off-TPU this raises
  loudly unless the interpret escape is set (`kernels.interpret=true`
  in the config, or the call-site `interpret_ok=True` that preserves
  `SparseSelfAttention(impl="pallas")`'s historical run-the-kernel-
  under-the-interpreter semantics).
* `"jnp"` (alias `"xla"`) — the oracle, unconditionally.

Every `dispatch()` bumps `kernel.dispatches` (pallas chosen) or
`kernel.fallbacks` (oracle chosen).  Like the `dist.*` family these are
TRACE-time counts — once per compiled program per call site, not per
execution — so a decode program that retraces shows exactly its
per-layer dispatch count (docs/tutorials/kernels.md).

Config install mirrors moe/dispatch.py's wire config: the engine
installs the parsed `"kernels"` block process-globally at initialize();
direct users scope overrides with the `kernel_config(...)` context
manager.  Implementation modules (`flash`, `quant_codec`,
`moe_kernels`, `paged`) are imported lazily from the op methods so the
registry itself stays import-cycle-free (config validation can name
the op set without dragging in jax kernels).
"""

from __future__ import annotations

import contextlib
import dataclasses
import os
from typing import Dict, Mapping, Optional, Tuple

import jax

from ..monitor.counters import COUNTERS
from ..utils.logging import logger

KERNEL_IMPLS = ("auto", "pallas", "jnp")
# legacy spelling accepted at call sites (SparseSelfAttention's
# impl="xla") — normalized to "jnp" before resolution
_IMPL_ALIASES = {"xla": "jnp"}


def _on_tpu() -> bool:
    return jax.default_backend() == "tpu"


# ---------------------------------------------------------------------------
# op classes (op_builder pattern: NAME + compatibility probe per op)
# ---------------------------------------------------------------------------


class KernelOp:
    """One registered hot-loop op.  Subclasses lazily import their
    implementation module inside `pallas()`/`oracle()` — registration
    stays cheap and cycle-free."""

    NAME = "base"
    VARIANTS: Tuple[str, ...] = ("default",)
    # False when pallas<->oracle parity is tolerance-bounded (attention
    # reduction order); True when bit-exact (integer codecs, gathers)
    EXACT = False

    def env_enabled(self) -> bool:
        return os.environ.get(f"DS_KERNEL_{self.NAME.upper()}",
                              "1") != "0"

    def is_compatible(self) -> bool:
        """Pallas-on-TPU probe: native selection needs a TPU backend
        and the op's env switch left on."""
        return self.env_enabled() and _on_tpu()

    def compatibility_message(self) -> str:
        if not self.env_enabled():
            return (f"disabled via DS_KERNEL_{self.NAME.upper()}=0")
        if not _on_tpu():
            return (f"backend is {jax.default_backend()!r}, not 'tpu' "
                    f"(the Pallas kernel only runs natively on TPU; "
                    f"off-TPU it needs the interpret escape)")
        return "compatible"

    def auto_supports(self, variant: str, info: Optional[Mapping]
                      ) -> Tuple[bool, str]:
        """Per-call shape heuristic for impl='auto' (info is the call
        site's shape dict; None = no constraint data, assume yes)."""
        return True, ""

    def check_variant(self, variant: str) -> None:
        if variant not in self.VARIANTS:
            raise ValueError(
                f"kernels.{self.NAME}: unknown variant {variant!r}; "
                f"valid: {self.VARIANTS}")

    def pallas(self, variant: str, *args, **kwargs):
        raise NotImplementedError

    def oracle(self, variant: str, *args, **kwargs):
        raise NotImplementedError


class FlashAttentionOp(KernelOp):
    """Dense causal flash attention blocks (op 4): wraps
    ops/transformer/flash_attention.flash_attention; oracle is the
    plain jnp softmax attention it streams."""

    NAME = "flash_attention"

    def auto_supports(self, variant, info):
        if not info:
            return True, ""
        bq = int(info.get("block_q", 128))
        bk = int(info.get("block_k", 128))
        s, sk = int(info.get("seq_len", bq)), int(info.get("kv_len", bk))
        if s % bq or sk % bk:
            return False, (f"seq lens ({s},{sk}) not divisible by "
                           f"blocks ({bq},{bk})")
        return True, ""

    def pallas(self, variant, *args, **kwargs):
        from . import flash
        return flash.flash_attention_pallas(*args, **kwargs)

    def oracle(self, variant, *args, **kwargs):
        from . import flash
        return flash.flash_attention_reference(*args, **kwargs)


class SparseAttentionOp(KernelOp):
    """Block-sparse attention under a SparsityConfig layout (satellite:
    the ad-hoc impl=auto|pallas|xla selection from
    ops/sparse_attention/sparse_attention.py folded into the registry).
    Pallas = flash_sparse_attention, oracle = block_sparse_attention."""

    NAME = "sparse_attention"

    def auto_supports(self, variant, info):
        if not info:
            return True, ""
        # the historical auto heuristic, verbatim: kernel only for
        # plain (bias-free) calls with MXU-shaped blocks and head dims
        if not info.get("plain", True):
            return False, "biases route to the XLA gather path"
        block = int(info.get("block", 0))
        if block % 128 != 0:
            return False, f"layout block {block} not a multiple of 128"
        d = int(info.get("head_dim", 0))
        if d not in (64, 128, 256):
            return False, f"head_dim {d} not in (64, 128, 256)"
        return True, ""

    def pallas(self, variant, q, k, v, layout, block, *, causal=False,
               key_padding_bias=None, attn_bias=None, dropout_rate=0.0,
               dropout_rng=None):
        from ..ops.sparse_attention.flash_sparse import \
            flash_sparse_attention
        # the kernel has no bias path; auto never selects it with
        # biases and the module wrapper routes biased calls to the
        # oracle (the historical silent-XLA behaviour, now explicit)
        return flash_sparse_attention(
            q, k, v, layout, block, causal=causal,
            dropout_rate=dropout_rate, dropout_rng=dropout_rng)

    def oracle(self, variant, q, k, v, layout, block, *, causal=False,
               key_padding_bias=None, attn_bias=None, dropout_rate=0.0,
               dropout_rng=None):
        from ..ops.sparse_attention.sparse_attention import \
            block_sparse_attention
        return block_sparse_attention(
            q, k, v, layout, block, causal_token_mask=causal,
            key_padding_bias=key_padding_bias, attn_bias=attn_bias,
            dropout_rate=dropout_rate, dropout_rng=dropout_rng)


class PagedAttentionOp(KernelOp):
    """Decode-path paged attention (op 1): fused block-table gather +
    online-softmax attention over the PagedKVCache, with the quantized
    KV dequant fused into the gather.  Oracle = the gather/einsum/
    softmax expression serving/programs.py's `_paged_block` always ran
    (bit-identical serving behaviour wherever the oracle is chosen)."""

    NAME = "paged_attention"

    def auto_supports(self, variant, info):
        if not info:
            return True, ""
        bs = int(info.get("block_size", 0))
        L = int(info.get("kv_len", bs))
        if bs <= 0 or L % bs:
            return False, (f"gathered rows {L} not a whole number of "
                           f"cache blocks of {bs}")
        t = int(info.get("q_len", 1))
        if t > 8:
            return False, (f"q_len {t} too large for the unrolled "
                           f"decode kernel (prefill stays on jnp)")
        d = int(info.get("head_dim", 128))
        if d % 128:
            return False, f"head_dim {d} not lane-aligned (128)"
        return True, ""

    def pallas(self, variant, *args, **kwargs):
        from . import paged
        return paged.paged_attention_pallas(*args, **kwargs)

    def oracle(self, variant, *args, **kwargs):
        from . import paged
        return paged.paged_attention_reference(*args, **kwargs)


class QuantCodecOp(KernelOp):
    """Blockwise int8/int4 quantize/dequantize (op 2, the ZeRO++ wire
    codec from runtime/comm/quant.py).  Variants: "quantize" /
    "dequantize".  Parity is BIT-exact: the kernel runs the oracle's
    op sequence (subnormal flush -> finite amax -> fp16 scale ->
    round/clip -> non-finite marker) tile-by-tile; `pack_wire`/
    `unpack_wire` bitcast glue rides in the wrappers unchanged."""

    NAME = "quant_codec"
    VARIANTS = ("quantize", "dequantize")
    EXACT = True

    def auto_supports(self, variant, info):
        if not info:
            return True, ""
        block = int(info.get("block", 0))
        if block % 128:
            return False, (f"quant block {block} not lane-aligned "
                           f"(128)")
        return True, ""

    def pallas(self, variant, *args, **kwargs):
        from . import quant_codec
        if variant == "quantize":
            return quant_codec.quantize_blockwise_pallas(*args, **kwargs)
        return quant_codec.dequantize_blockwise_pallas(*args, **kwargs)

    def oracle(self, variant, *args, **kwargs):
        from ..runtime.comm import quant
        if variant == "quantize":
            return quant.quantize_blockwise_ref(*args, **kwargs)
        return quant.dequantize_blockwise_ref(*args, **kwargs)


class MoEDispatchOp(KernelOp):
    """Sort-based MoE token movement (op 3, moe/dispatch.py).  Variants:
    "dispatch" (tokens -> [E, C, D] buckets; the kernel reformulates
    the oracle's scatter-add — whose kept destinations are unique — as
    a per-slot gather through a precomputed inverse permutation, so
    parity is BIT-exact) and "combine" (gated gather-back in the same
    term order; ~1-ulp tolerance, the accumulator may fuse an FMA).
    """

    NAME = "moe_dispatch"
    VARIANTS = ("dispatch", "combine")
    EXACT = True

    def auto_supports(self, variant, info):
        if not info:
            return True, ""
        d = int(info.get("model_dim", 128))
        if d % 128:
            return False, f"model dim {d} not lane-aligned (128)"
        return True, ""

    def pallas(self, variant, *args, **kwargs):
        from . import moe_kernels
        if variant == "dispatch":
            return moe_kernels.sorted_dispatch_pallas(*args, **kwargs)
        return moe_kernels.sorted_combine_pallas(*args, **kwargs)

    def oracle(self, variant, *args, **kwargs):
        from ..moe import dispatch as moe_dispatch
        if variant == "dispatch":
            return moe_dispatch.sorted_dispatch_ref(*args, **kwargs)
        return moe_dispatch.sorted_combine_ref(*args, **kwargs)


KERNEL_OPS: Dict[str, KernelOp] = {
    op.NAME: op for op in (FlashAttentionOp(), SparseAttentionOp(),
                           PagedAttentionOp(), QuantCodecOp(),
                           MoEDispatchOp())
}


def get_kernel(name: str) -> KernelOp:
    if name not in KERNEL_OPS:
        raise ValueError(
            f"unknown kernel op {name!r}; valid ops: "
            f"{sorted(KERNEL_OPS)}")
    return KERNEL_OPS[name]


# ---------------------------------------------------------------------------
# config (the validated "kernels" block; installed like the moe wire)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class KernelConfig:
    """Process-global kernel selection.  The default-constructed config
    is the shipping behaviour: auto-probe per op, counters on, no
    interpret escape."""

    impl: str = "auto"                 # global default: auto|pallas|jnp
    ops: Mapping[str, str] = dataclasses.field(default_factory=dict)
    interpret: bool = False            # allow forced pallas off-TPU
    counters: bool = True

    def impl_for(self, name: str) -> str:
        return self.ops.get(name, self.impl)

    def describe(self) -> str:
        per_op = ", ".join(f"{k}={v}" for k, v in sorted(self.ops.items()))
        return (f"kernels: impl={self.impl}"
                + (f", {per_op}" if per_op else "")
                + (", interpret" if self.interpret else ""))


def parse_kernels_config(d) -> KernelConfig:
    """Validate the `"kernels"` config block -> KernelConfig.  Unknown
    keys, unknown OP NAMES, and invalid impl values all raise HERE — at
    config time, naming the valid set, never inside a traced program."""
    d = d or {}
    if not isinstance(d, dict):
        raise ValueError(
            f"kernels must be an object, got {type(d).__name__}")
    known = {"impl", "ops", "interpret", "counters"}
    unknown = set(d) - known
    if unknown:
        raise ValueError(
            f"kernels: unknown key(s) {sorted(unknown)}; expected a "
            f"subset of {sorted(known)}")

    def impl_value(key, v):
        v = str(v).lower()
        v = _IMPL_ALIASES.get(v, v)
        if v not in KERNEL_IMPLS:
            raise ValueError(
                f"kernels.{key} must be one of {KERNEL_IMPLS}, "
                f"got {v!r}")
        return v

    impl = impl_value("impl", d.get("impl", "auto"))

    ops_d = d.get("ops", {})
    if not isinstance(ops_d, dict):
        raise ValueError(
            f"kernels.ops must be an object mapping op name -> impl, "
            f"got {type(ops_d).__name__}")
    ops = {}
    for name, v in ops_d.items():
        if name not in KERNEL_OPS:
            raise ValueError(
                f"kernels.ops: unknown op {name!r}; registered ops: "
                f"{sorted(KERNEL_OPS)}")
        ops[name] = impl_value(f"ops.{name}", v)

    interpret = d.get("interpret", False)
    if not isinstance(interpret, bool):
        raise ValueError(
            f"kernels.interpret must be a bool, got {interpret!r}")
    counters = d.get("counters", True)
    if not isinstance(counters, bool):
        raise ValueError(
            f"kernels.counters must be a bool, got {counters!r}")
    return KernelConfig(impl=impl, ops=ops, interpret=interpret,
                        counters=counters)


_KERNEL_CONFIG = KernelConfig()


def get_kernel_config() -> KernelConfig:
    return _KERNEL_CONFIG


def set_kernel_config(cfg: KernelConfig) -> KernelConfig:
    """Install `cfg` process-globally; returns the previous config.
    Like the moe wire config, selection is read at TRACE time — a
    config swap affects programs traced after it, never cached ones."""
    global _KERNEL_CONFIG
    prev = _KERNEL_CONFIG
    _KERNEL_CONFIG = cfg
    if cfg != prev:
        logger.debug(cfg.describe())
    return prev


@contextlib.contextmanager
def kernel_config(cfg: Optional[KernelConfig] = None, **kwargs):
    """Scoped kernel config for direct users / tests:
    `with kernel_config(impl="jnp"): ...` or
    `with kernel_config(ops={"quant_codec": "pallas"}, interpret=True)`.
    Keyword form routes through the REAL validator."""
    if cfg is None:
        cfg = parse_kernels_config(kwargs)
    prev = set_kernel_config(cfg)
    try:
        yield get_kernel_config()
    finally:
        set_kernel_config(prev)


# ---------------------------------------------------------------------------
# autotuner winner table (the `kernel` scope's output)
# ---------------------------------------------------------------------------

# op name -> {"impl": "pallas"|"jnp", "fingerprint": dict|None}
_WINNERS: Dict[str, Dict] = {}


def record_winner(name: str, impl: str,
                  fingerprint: Optional[Mapping] = None) -> None:
    """Install an autotuner-measured per-op choice.  `fingerprint` is a
    `kernel_fingerprint(...)` dict; at resolution time the winner only
    applies while its `fabric` section still matches the live fabric —
    a backend/device change invalidates it (measured-not-assumed, the
    PR-14 contract)."""
    get_kernel(name)
    impl = _IMPL_ALIASES.get(str(impl).lower(), str(impl).lower())
    if impl not in ("pallas", "jnp"):
        raise ValueError(
            f"kernel winner impl must be 'pallas' or 'jnp', got {impl!r}")
    _WINNERS[name] = {"impl": impl,
                      "fingerprint": dict(fingerprint) if fingerprint
                      else None}


def clear_winners() -> None:
    _WINNERS.clear()


def winner_for(name: str) -> Optional[str]:
    """The recorded winner impl for `name`, or None when absent or
    recorded on a different fabric."""
    w = _WINNERS.get(name)
    if w is None:
        return None
    fp = w["fingerprint"]
    if fp is not None:
        from ..runtime.autotune.fingerprint import fabric_section
        if fp.get("fabric") != fabric_section():
            return None
    return w["impl"]


# ---------------------------------------------------------------------------
# resolution + dispatch
# ---------------------------------------------------------------------------


def resolve_impl(name: str, variant: str = "default",
                 impl: Optional[str] = None, interpret_ok: bool = False,
                 info: Optional[Mapping] = None) -> str:
    """-> the concrete "pallas" | "jnp" this call will run (raises on
    an unsatisfiable forced pallas; see module docstring)."""
    op = get_kernel(name)
    op.check_variant(variant)
    cfg = get_kernel_config()
    choice = impl if impl is not None else cfg.impl_for(name)
    choice = _IMPL_ALIASES.get(str(choice).lower(), str(choice).lower())
    if choice not in KERNEL_IMPLS:
        raise ValueError(
            f"kernels.{name}: impl must be one of {KERNEL_IMPLS}, "
            f"got {choice!r}")
    if choice == "pallas":
        if not (op.is_compatible() or interpret_ok or cfg.interpret):
            raise RuntimeError(
                f"kernels.{name}: impl='pallas' forced but "
                f"{op.compatibility_message()}; use impl='auto' for the "
                f"jnp fallback, or set kernels.interpret=true to run "
                f"the kernel under the Pallas interpreter (tests/bench)")
        return "pallas"
    if choice == "jnp":
        return "jnp"
    # auto: an autotuned winner (fabric-matched) overrides the heuristic
    w = winner_for(name)
    if w == "jnp":
        return "jnp"
    if w == "pallas" and op.is_compatible():
        return "pallas"
    if op.is_compatible() and op.auto_supports(variant, info)[0]:
        return "pallas"
    return "jnp"


def dispatch(name: str, *args, variant: str = "default",
             impl: Optional[str] = None, interpret_ok: bool = False,
             info: Optional[Mapping] = None, **kwargs):
    """Run op `name` through the registry's selection contract.

    Bumps `kernel.dispatches` / `kernel.fallbacks` at trace time (the
    `dist.*` once-per-compiled-program convention)."""
    op = get_kernel(name)
    chosen = resolve_impl(name, variant, impl=impl,
                          interpret_ok=interpret_ok, info=info)
    if get_kernel_config().counters:
        COUNTERS.add("kernel.dispatches" if chosen == "pallas"
                     else "kernel.fallbacks")
    if chosen == "pallas":
        return op.pallas(variant, *args, **kwargs)
    return op.oracle(variant, *args, **kwargs)


def probe_report():
    """[(name, verdict, reason)] for every registered op — verdict is
    "pallas" or "jnp-fallback" with the decline reason (ds_report's
    Kernels section; reason is "" when pallas is selected)."""
    rows = []
    for name in sorted(KERNEL_OPS):
        op = KERNEL_OPS[name]
        if op.is_compatible():
            rows.append((name, "pallas", ""))
        else:
            rows.append((name, "jnp-fallback", op.compatibility_message()))
    return rows
