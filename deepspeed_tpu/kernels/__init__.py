"""deepspeed_tpu.kernels — the Pallas hot-loop op registry.

One kernel-selection mechanism for the whole repo (registry.py):
op_builder-style probed Pallas implementations with their original jnp
expressions kept as pinned correctness oracles.  See
docs/tutorials/kernels.md.
"""

from .registry import (KERNEL_IMPLS, KERNEL_OPS, KernelConfig,
                       clear_winners, dispatch, get_kernel,
                       get_kernel_config, kernel_config,
                       parse_kernels_config, probe_report, record_winner,
                       resolve_impl, set_kernel_config, winner_for)

__all__ = [
    "KERNEL_IMPLS", "KERNEL_OPS", "KernelConfig", "clear_winners",
    "dispatch", "get_kernel", "get_kernel_config", "kernel_config",
    "parse_kernels_config", "probe_report", "record_winner",
    "resolve_impl", "set_kernel_config", "winner_for",
]
