"""Pallas blockwise int8/int4 quantize/dequantize (op 2: the ZeRO++
qwZ/qgZ wire codec from runtime/comm/quant.py).

ZeRO++'s own finding motivates this op: once the wire shrinks 4-8x, the
CODEC becomes the bottleneck — on TPU the amax/scale/round chain should
run as one VMEM-resident pass per block tile instead of the half-dozen
HBM-roundtripping XLA ops the jnp expression lowers to.

Parity contract: BIT-exact with `quantize_blockwise_ref` /
`dequantize_blockwise_ref`.  The kernels replicate the oracle's op
sequence per tile — subnormal flush, finite-masked amax, fp16-rounded
scale reused as the quantization scale, round/clip, the -qmax-1
non-finite marker — using the same jnp primitives, so interpret-mode
CPU runs produce identical bits (pinned in tier-1) and the int4 nibble
pack/unpack stays in the jnp wrappers (pure bit movement XLA handles
fine; the arithmetic is what the kernel owns).

Layout notes (TPU-native): tiles are `_TILE` = 8 block-rows x `block`
lanes, so `block % 128 == 0` tiles cleanly (the registry's auto
heuristic gates on it; DEFAULT_BLOCK_SIZE = 256 qualifies).  Scales
travel through a 128-lane broadcast column — 2 bytes/element of
sideband, negligible next to the payload.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..ops.transformer.flash_attention import compiler_params_cls
from ..runtime.comm.quant import (_F32_MIN_NORMAL, qmax,
                                  validate_block_size)

_TILE = 8  # block-rows per grid program (fp32 sublane tile)


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _params(ndims: int):
    return compiler_params_cls()(
        dimension_semantics=(pltpu.PARALLEL,) * ndims)


def _pad_rows(a, tile: int):
    """Zero-pad leading (row) axis to a whole number of tiles."""
    pad = -a.shape[0] % tile
    if pad:
        a = jnp.concatenate(
            [a, jnp.zeros((pad,) + a.shape[1:], a.dtype)])
    return a


# ---------------------------------------------------------------------------
# quantize
# ---------------------------------------------------------------------------


def _quant_kernel(x_ref, codes_ref, scales_ref, *, q):
    # the oracle's encode chain, verbatim per tile (quant.py):
    # flush -> finite amax -> fp16 scale -> inv -> round/clip -> marker
    blocks = x_ref[...]
    blocks = jnp.where(jnp.abs(blocks) < jnp.float32(_F32_MIN_NORMAL),
                       jnp.float32(0.0), blocks)
    finite = jnp.isfinite(blocks)
    amax = jnp.max(jnp.where(finite, jnp.abs(blocks), 0.0),
                   axis=1, keepdims=True)
    scales = (amax / q).astype(jnp.float16)
    eff = scales.astype(jnp.float32)
    inv = jnp.where((eff > 0) & jnp.isfinite(eff), 1.0 / eff, 0.0)
    codes = jnp.clip(jnp.round(blocks * inv), -q, q).astype(jnp.int8)
    codes_ref[...] = jnp.where(finite, codes, jnp.int8(-q - 1))
    scales_ref[...] = jnp.broadcast_to(scales, scales_ref.shape)


def quantize_blockwise_pallas(x, block: int, wire: str = "int8"):
    """Drop-in for `quantize_blockwise_ref`: flat tensor -> (int8 codes
    | packed int4 nibbles, fp16 scales), bit-identical payload."""
    q = qmax(wire)
    block = validate_block_size(block)

    f32 = x.reshape(-1).astype(jnp.float32)
    pad = -f32.shape[0] % block
    if pad:
        f32 = jnp.concatenate([f32, jnp.zeros((pad,), jnp.float32)])
    blocks = f32.reshape(-1, block)
    nb = blocks.shape[0]
    # pad rows to the tile; a zero row encodes deterministically to
    # (codes 0, scale 0) and is sliced back off
    blocks = _pad_rows(blocks, _TILE)
    grid = (blocks.shape[0] // _TILE,)

    codes, scales = pl.pallas_call(
        functools.partial(_quant_kernel, q=q),
        grid=grid,
        in_specs=[pl.BlockSpec((_TILE, block), lambda i: (i, 0))],
        out_specs=[
            pl.BlockSpec((_TILE, block), lambda i: (i, 0)),
            pl.BlockSpec((_TILE, 128), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(blocks.shape, jnp.int8),
            jax.ShapeDtypeStruct((blocks.shape[0], 128), jnp.float16),
        ],
        compiler_params=_params(1),
        interpret=_interpret(),
    )(blocks)
    codes = codes[:nb]
    scales = scales[:nb, 0]

    if q == 127:
        return codes, scales
    u = codes.astype(jnp.uint8) & jnp.uint8(0x0F)
    packed = u[:, 0::2] | (u[:, 1::2] << 4)
    return packed, scales


# ---------------------------------------------------------------------------
# dequantize
# ---------------------------------------------------------------------------


def _dequant_kernel(codes_ref, scales_ref, out_ref, *, marker):
    codes = codes_ref[...]
    vals = codes.astype(jnp.float32) * scales_ref[:, :1]
    out_ref[...] = jnp.where(codes == marker, jnp.float32(jnp.nan), vals)


def dequantize_blockwise_pallas(payload, scales, wire: str,
                                n_elems: int):
    """Drop-in for `dequantize_blockwise_ref`: fused codes-x-scale with
    the marker -> NaN reconstruction in-kernel; leading batch dims
    (gathered wires arrive [world, nb, w]) fold into the row axis."""
    q = qmax(wire)
    marker = -q - 1
    lead = payload.shape[:-2]
    if q == 127:
        codes = payload.astype(jnp.int8)
    else:
        lo = (payload & jnp.uint8(0x0F)).astype(jnp.int8)
        hi = ((payload >> 4) & jnp.uint8(0x0F)).astype(jnp.int8)
        lo = jnp.where(lo > 7, lo - 16, lo)
        hi = jnp.where(hi > 7, hi - 16, hi)
        codes = jnp.stack([lo, hi], axis=-1).reshape(
            payload.shape[:-1] + (payload.shape[-1] * 2,))
    block = codes.shape[-1]
    codes = codes.reshape(-1, block)
    nb = codes.shape[0]
    s128 = jnp.broadcast_to(
        scales.astype(jnp.float32).reshape(-1, 1), (nb, 128))
    codes = _pad_rows(codes, _TILE)
    s128 = _pad_rows(s128, _TILE)
    grid = (codes.shape[0] // _TILE,)

    vals = pl.pallas_call(
        functools.partial(_dequant_kernel, marker=marker),
        grid=grid,
        in_specs=[
            pl.BlockSpec((_TILE, block), lambda i: (i, 0)),
            pl.BlockSpec((_TILE, 128), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((_TILE, block), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct(codes.shape, jnp.float32),
        compiler_params=_params(1),
        interpret=_interpret(),
    )(codes, s128)
    flat = vals[:nb].reshape(lead + (-1,))
    return flat[..., :n_elems]
