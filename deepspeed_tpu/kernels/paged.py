"""Pallas decode-path paged attention (op 1): fused block-table gather
+ online-softmax attention over the PagedKVCache, with the PR-15
quantized-KV dequant fused into the gather.

The jnp oracle (`paged_attention_reference`) is the EXACT expression
serving/programs.py's `_paged_block` always ran — gather the table's
rows, dequantize if the cache is quantized, one fp32 einsum/softmax/
einsum chain under the `q_pos >= k_idx` mask.  Wherever the registry
picks the oracle (all of tier-1 on CPU) serving output stays
bit-identical to the pre-registry code, which is what keeps the
serving-vs-generate pins green.

The kernel removes the materialised `[B, L, H, Dh]` gather: each
(slot·head) program walks the slot's block table a cache block at a
time — the table rides scalar prefetch, so the BlockSpec index map
turns each step into a direct async copy of ONE `[block_size, Dh]`
cache tile into VMEM (the fused gather), streamed through the same
online-softmax accumulator as ops/transformer/flash_attention.py.  For
quantized caches the tile arrives as (codes, scales) and dequantizes
in-register — int4 nibble decode included — so the HBM read is the
COMPRESSED cache, the whole point of quantized KV.

Parity: tolerance-bounded (online-softmax tiling vs one fused softmax),
the attention-op contract.  Trash/garbage blocks beyond a slot's length
are killed by the mask in both impls: the oracle's softmax underflows
their NEG_INF scores to exactly 0, the kernel zeroes fully-masked
tiles explicitly (`p = where(s <= NEG_INF/2, 0, p)` — the
flash_attention bias-path guard, since a tile past the horizon has no
live key to anchor the running max).

TPU-native layout: caches are viewed as `[rows, H * width]` (a free
reshape) so each gathered tile is a `(block_size, width)` block —
lane-dim clean when `Dh % 128 == 0`; the registry's auto heuristic
gates on that plus small T (the q rows unroll over scalar-prefetched
positions).  Scales ride a `(block_size, 1)` block — sub-lane, fine
under the interpreter, flagged for Mosaic in docs/tutorials/kernels.md.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..models.generation import NEG_INF
from ..ops.transformer.flash_attention import compiler_params_cls


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _clamp(i):
    return jnp.maximum(i, 0)


def _params():
    return compiler_params_cls()(
        dimension_semantics=(pltpu.PARALLEL, pltpu.ARBITRARY))


def kv_read(c, rows, kv_mode: str = "dense"):
    """Gather cache rows `rows` [B, L] -> [B, L, H, Dh].  Dense reads
    come back at the cache dtype; quantized caches ((payload, scales)
    pairs) dequantize the gathered rows to fp32.  THE gather the oracle
    and serving/programs.py share."""
    if kv_mode == "dense":
        return c[rows]
    from ..runtime.comm.quant import dequantize_rows

    payload, scales = c
    return dequantize_rows(payload[rows], scales[rows], kv_mode)


def paged_attention_reference(q, ck, cv, rows, q_pos, *,
                              kv_mode: str = "dense",
                              block_size: int = 0):
    """The `_paged_block` attention core, verbatim: q [B, T, H, Dh],
    caches addressed by flat rows [B, L], q_pos [B, T] absolute
    positions -> attn [B, T, H, Dh] (at the cache/dequant dtype)."""
    del block_size  # kernel tiling knob; the gather needs only rows
    Dh = q.shape[-1]
    keys = kv_read(ck, rows, kv_mode)      # [B, L, H, Dh]
    vals = kv_read(cv, rows, kv_mode)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                        keys.astype(jnp.float32)) * (Dh ** -0.5)
    L = rows.shape[1]
    k_idx = jnp.arange(L)[None, None, :]
    mask = q_pos[:, :, None] >= k_idx            # [B, T, L]
    scores = jnp.where(mask[:, None, :, :], scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs.astype(vals.dtype), vals)


# ---------------------------------------------------------------------------
# kernel
# ---------------------------------------------------------------------------


def _decode_nibbles(raw, width, full):
    """uint8 [rows, width] -> int8 codes [rows, full] (quant.py's
    low-nibble-first two's-complement decode)."""
    lo = (raw & jnp.uint8(0x0F)).astype(jnp.int8)
    hi = ((raw >> 4) & jnp.uint8(0x0F)).astype(jnp.int8)
    lo = jnp.where(lo > 7, lo - 16, lo)
    hi = jnp.where(hi > 7, hi - 16, hi)
    return jnp.stack([lo, hi], axis=-1).reshape(raw.shape[0], full)


def _tile_kv(ref, s_ref, kv_mode, Dh, marker):
    """One gathered cache tile -> fp32 [block_size, Dh], dequantized
    in-register for quantized caches (the fused dequant)."""
    raw = ref[...]
    if kv_mode == "dense":
        return raw.astype(jnp.float32)
    if kv_mode == "int4":
        codes = _decode_nibbles(raw, raw.shape[-1], Dh)
    else:
        codes = raw.astype(jnp.int8)
    vals = codes.astype(jnp.float32) * s_ref[...].astype(jnp.float32)
    return jnp.where(codes == marker, jnp.float32(jnp.nan), vals)


def _paged_kernel(tbl, qp, q_ref, *rest, scale, bs, W, H, T, Dh,
                  kv_mode, marker):
    if kv_mode == "dense":
        k_ref, v_ref, o_ref, acc, m_s, l_s = rest
        ks_ref = vs_ref = None
    else:
        k_ref, ks_ref, v_ref, vs_ref, o_ref, acc, m_s, l_s = rest
    bh = pl.program_id(0)
    a = pl.program_id(1)
    r = jax.lax.div(bh, H)

    @pl.when(a == 0)
    def _init():
        acc[...] = jnp.zeros_like(acc)
        m_s[...] = jnp.full_like(m_s, NEG_INF)
        l_s[...] = jnp.zeros_like(l_s)

    q = q_ref[0].astype(jnp.float32) * scale          # (T, Dh)
    k = _tile_kv(k_ref, ks_ref, kv_mode, Dh, marker)  # (bs, Dh)
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
    kidx = a * bs + jax.lax.broadcasted_iota(jnp.int32, (T, bs), 1)
    # T is tiny (1 decode, draft+1 verify): unroll the scalar position
    # reads instead of carrying a [T]-shaped operand through VMEM
    qpos = jnp.stack([qp[r, t] for t in range(T)])
    s = jnp.where(qpos[:, None] >= kidx, s, NEG_INF)

    m_prev = m_s[:, :1]
    l_prev = l_s[:, :1]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
    p = jnp.exp(s - m_new)
    # a tile fully past the causal horizon leaves m_new at NEG_INF and
    # exp(s - m_new) = 1 everywhere — zero it (flash_attention's guard)
    p = jnp.where(s <= NEG_INF * 0.5, 0.0, p)
    alpha = jnp.exp(m_prev - m_new)
    l_new = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
    v = _tile_kv(v_ref, vs_ref, kv_mode, Dh, marker)
    acc[...] = acc[...] * alpha + jnp.dot(
        p, v, preferred_element_type=jnp.float32)
    m_s[:, :1] = m_new
    l_s[:, :1] = l_new

    @pl.when(a == W - 1)
    def _finish():
        l = l_s[:, :1]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc[...] / safe_l).astype(o_ref.dtype)


def paged_attention_pallas(q, ck, cv, rows, q_pos, *,
                           kv_mode: str = "dense", block_size: int):
    """Drop-in for `paged_attention_reference` (tolerance parity)."""
    B, T, H, Dh = q.shape
    L = rows.shape[1]
    bs = int(block_size)
    if bs <= 0 or L % bs:
        raise ValueError(
            f"paged attention kernel needs rows ([{B}, {L}]) to cover "
            f"whole cache blocks of {bs}")
    W = L // bs
    # the gathered rows ARE table walks (programs.py builds them as
    # table*bs + arange(bs)); recover the table for scalar prefetch
    tables = (rows[:, ::bs] // bs).astype(jnp.int32)
    qp = q_pos.astype(jnp.int32)

    if kv_mode == "dense":
        marker = 0
        out_dtype = ck.dtype
        width = Dh

        def views(c):
            return (c.reshape(c.shape[0], H * Dh),)

        kv_specs = [
            pl.BlockSpec((bs, width),
                         lambda b, a, t, s: (_clamp(t[b // H, a]),
                                             jax.lax.rem(b, H))),
        ]
        operands = [*views(ck), *views(cv)]
        kv_specs = kv_specs * 2
    else:
        from ..runtime.comm.quant import qmax

        marker = -qmax(kv_mode) - 1
        out_dtype = jnp.float32
        pk, sk = ck
        pv, sv = cv
        width = pk.shape[-1]  # Dh (int8) or Dh // 2 (int4 nibbles)

        payload_spec = pl.BlockSpec(
            (bs, width), lambda b, a, t, s: (_clamp(t[b // H, a]),
                                             jax.lax.rem(b, H)))
        scale_spec = pl.BlockSpec(
            (bs, 1), lambda b, a, t, s: (_clamp(t[b // H, a]),
                                         jax.lax.rem(b, H)))
        kv_specs = [payload_spec, scale_spec, payload_spec, scale_spec]
        operands = [pk.reshape(pk.shape[0], H * width), sk,
                    pv.reshape(pv.shape[0], H * width), sv]

    qf = q.transpose(0, 2, 1, 3).reshape(B * H, T, Dh)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B * H, W),
        in_specs=[
            pl.BlockSpec((1, T, Dh), lambda b, a, t, s: (b, 0, 0)),
            *kv_specs,
        ],
        out_specs=pl.BlockSpec((1, T, Dh), lambda b, a, t, s: (b, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((T, Dh), jnp.float32),
            pltpu.VMEM((T, 128), jnp.float32),
            pltpu.VMEM((T, 128), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(_paged_kernel, scale=Dh ** -0.5, bs=bs, W=W,
                          H=H, T=T, Dh=Dh, kv_mode=kv_mode,
                          marker=marker),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B * H, T, Dh), out_dtype),
        compiler_params=_params(),
        interpret=_interpret(),
    )(tables, qp, qf, *operands)
    return out.reshape(B, H, T, Dh).transpose(0, 2, 1, 3)
