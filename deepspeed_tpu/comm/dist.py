"""`dist`-shaped facade over XLA collectives.

The reference talks to torch.distributed (NCCL) directly:
broadcast / all_reduce / reduce / reduce_scatter / all_gather /
all_to_all_single, plus p2p emulated by 2-rank broadcast groups
(/root/reference/deepspeed/runtime/pipe/p2p.py:31-75,
 deepspeed/utils/distributed.py:12-51).

Here the same call-sites map to `jax.lax` collectives over named mesh axes.
Two usage modes:

1. *In-jit* (inside `shard_map`/`pmap` with a bound axis name): the functions
   below are thin wrappers over lax.psum / all_gather / psum_scatter /
   ppermute / all_to_all. This is the hot path — XLA lowers these onto ICI.
2. *Host-level* (single-controller): `init_distributed`, `barrier`,
   `get_rank`/`get_world_size` — process bootstrap via
   `jax.distributed.initialize` instead of a MASTER_ADDR NCCL rendezvous.
"""

from __future__ import annotations

import os
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..constants import TORCH_DISTRIBUTED_DEFAULT_PORT
from ..utils.logging import logger
from . import mesh as mesh_mod

_INITIALIZED = False


class ReduceOp:
    """torch.distributed.ReduceOp parity."""

    SUM = "sum"
    AVG = "avg"
    MAX = "max"
    MIN = "min"
    PROD = "prod"


# ---------------------------------------------------------------------------
# Host-level bootstrap (reference: deepspeed.init_distributed,
# utils/distributed.py:12-51 incl. MPI discovery :54-96)
# ---------------------------------------------------------------------------

def init_distributed(
    dist_backend: str = "xla",
    auto_mpi_discovery: bool = True,
    distributed_port: int = TORCH_DISTRIBUTED_DEFAULT_PORT,
    verbose: bool = True,
    timeout=None,
    init_method: Optional[str] = None,
):
    """Initialize multi-process JAX if a coordinator is configured.

    Signature mirrors reference `deepspeed.init_distributed`; the backend
    string is accepted for compatibility but the transport is always XLA
    over ICI/DCN. Single-process (or already-initialized) calls are no-ops.

    Coordinator discovery order:
      1. explicit env: DSTPU_COORDINATOR / DSTPU_NUM_PROCESSES / DSTPU_PROCESS_ID
      2. torch-style env: MASTER_ADDR(+distributed_port) / WORLD_SIZE / RANK
      3. OMPI env (auto_mpi_discovery): OMPI_COMM_WORLD_SIZE/RANK
      4. TPU-pod metadata (jax.distributed.initialize() auto-detect)
    """
    global _INITIALIZED
    if _INITIALIZED:
        return

    coord = os.environ.get("DSTPU_COORDINATOR")
    nprocs = os.environ.get("DSTPU_NUM_PROCESSES")
    pid = os.environ.get("DSTPU_PROCESS_ID")

    if coord is None and os.environ.get("MASTER_ADDR"):
        coord = f"{os.environ['MASTER_ADDR']}:{os.environ.get('MASTER_PORT', distributed_port)}"
        nprocs = nprocs or os.environ.get("WORLD_SIZE")
        pid = pid or os.environ.get("RANK")

    if coord is None and auto_mpi_discovery and os.environ.get("OMPI_COMM_WORLD_SIZE"):
        nprocs = nprocs or os.environ.get("OMPI_COMM_WORLD_SIZE")
        pid = pid or os.environ.get("OMPI_COMM_WORLD_RANK")
        coord = os.environ.get("DSTPU_COORDINATOR", "127.0.0.1:%d" % distributed_port)

    try:
        if coord is not None and nprocs is not None and int(nprocs) > 1:
            jax.distributed.initialize(
                coordinator_address=coord,
                num_processes=int(nprocs),
                process_id=int(pid or 0),
            )
            if verbose:
                logger.info(
                    f"jax.distributed initialized: coordinator={coord} "
                    f"process {pid}/{nprocs}"
                )
    except RuntimeError as e:  # already initialized by launcher
        logger.debug(f"jax.distributed.initialize skipped: {e}")
    from .._compat import install_cpu_collectives

    install_cpu_collectives()
    _INITIALIZED = True


def is_initialized() -> bool:
    return _INITIALIZED or jax.process_count() >= 1


def get_world_size(group: Optional[str] = None) -> int:
    """Global device count, or the size of one mesh axis (`group` = axis name).

    Reference process groups become mesh-axis handles."""
    if group is None:
        return jax.device_count()
    return mesh_mod.get_current_mesh().axis_size(group)


def get_rank(group: Optional[str] = None) -> int:
    """Host-level: process index (reference torch.distributed.get_rank)."""
    if group is None:
        return jax.process_index()
    raise ValueError(
        "per-axis rank is only meaningful inside shard_map; use axis_index(axis)"
    )


def get_local_rank() -> int:
    return int(os.environ.get("DSTPU_LOCAL_RANK", 0))


def barrier():
    """Cross-process barrier (reference torch.distributed.barrier)."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices("deepspeed_tpu.barrier")


# ---------------------------------------------------------------------------
# In-jit collectives (must run under shard_map/pmap with bound axis names).
# These are the TPU-native equivalents of the reference's NCCL calls; XLA
# schedules them on ICI and overlaps with compute automatically — no
# hand-managed side streams (contrast zero/stage2.py:680-686).
# ---------------------------------------------------------------------------

def axis_index(axis: str):
    """This shard's coordinate along `axis` (reference: group rank)."""
    return lax.axis_index(axis)


def _record_volume(kind: str, x) -> None:
    """Collective-volume counter (monitor/counters.py).  These wrappers
    execute under jit/shard_map TRACING, so each record counts one traced
    occurrence per compiled program (the per-program collective volume),
    not one per device execution — hence the `dist.` prefix, distinct
    from the per-dispatch `p2p.*` counters.  Never raises into a trace."""
    try:
        from ..monitor.counters import COUNTERS, tree_bytes

        COUNTERS.add(f"dist.{kind}", tree_bytes(x))
    except Exception:
        pass


def all_reduce(x, axis: str, op: str = ReduceOp.SUM):
    _record_volume("all_reduce", x)
    if op == ReduceOp.SUM:
        return lax.psum(x, axis)
    if op == ReduceOp.AVG:
        return lax.pmean(x, axis)
    if op == ReduceOp.MAX:
        return lax.pmax(x, axis)
    if op == ReduceOp.MIN:
        return lax.pmin(x, axis)
    if op == ReduceOp.PROD:
        return jnp.exp(lax.psum(jnp.log(x), axis))
    raise ValueError(f"unknown reduce op {op}")


def all_gather(x, axis: str, *, tiled: bool = True, gather_axis: int = 0):
    """Gather shards along `axis`; tiled=True concatenates along gather_axis
    (torch all_gather + cat), False stacks a new leading dim."""
    _record_volume("all_gather", x)
    return lax.all_gather(x, axis, axis=gather_axis, tiled=tiled)


def reduce_scatter(x, axis: str, *, scatter_axis: int = 0, tiled: bool = True):
    """Sum across `axis` then keep this shard's slice — the ZeRO gradient
    primitive (reference zero/stage1.py:629 reduce_scatter_gradients)."""
    _record_volume("reduce_scatter", x)
    return lax.psum_scatter(x, axis, scatter_dimension=scatter_axis, tiled=tiled)


def broadcast(x, axis: str, src: int = 0):
    """Every shard gets shard `src`'s value (reference dist.broadcast)."""
    _record_volume("broadcast", x)
    gathered = lax.all_gather(x, axis, axis=0, tiled=False)
    return jax.tree_util.tree_map(lambda g: g[src], gathered)


def ppermute(x, axis: str, perm):
    """Point-to-point ring/pair exchange — replaces the reference's
    2-rank-broadcast-group p2p (pipe/p2p.py:31-75) with ICI collective
    permute."""
    _record_volume("ppermute", x)
    return lax.ppermute(x, axis, perm)


def send_recv_next(x, axis: str):
    """Shift +1 along a ring: stage i -> stage i+1 (pipeline activations)."""
    _record_volume("ppermute", x)
    n = lax.axis_size(axis)
    return lax.ppermute(x, axis, [(i, (i + 1) % n) for i in range(n)])


def send_recv_prev(x, axis: str):
    """Shift -1 along a ring (pipeline gradients)."""
    _record_volume("ppermute", x)
    n = lax.axis_size(axis)
    return lax.ppermute(x, axis, [(i, (i - 1) % n) for i in range(n)])


def all_to_all(x, axis: str, *, split_axis: int, concat_axis: int):
    """reference dist.all_to_all_single (comm/nccl.py:99) — Ulysses-style
    head<->sequence scatter rides this on ICI."""
    _record_volume("all_to_all", x)
    return lax.all_to_all(x, axis, split_axis=split_axis, concat_axis=concat_axis,
                          tiled=True)
