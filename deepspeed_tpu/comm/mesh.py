"""Device-mesh construction and registry — the comm substrate.

This replaces the reference's process-group bootstrap
(/root/reference/deepspeed/utils/distributed.py:12-51) with a TPU-native
design: instead of NCCL process groups, every parallelism axis is a named
axis of one `jax.sharding.Mesh` laid out over ICI (within a pod slice) and
DCN (across slices). Process groups in the reference map to mesh axes here:

    data parallel group   -> axis "data"   (ZeRO shards over this axis too)
    model parallel group  -> axis "model"  (tensor parallelism; reference
                                            delegates this to Megatron's mpu,
                                            here it is first-class)
    pipe parallel group   -> axis "pipe"   (pipeline stages)
    sequence parallelism  -> axis "seq"    (ring attention / long context;
                                            absent in the reference v0.3.15,
                                            first-class here)
    expert parallelism    -> axis "expert" (MoE; flattened into "data" when
                                            unused)

Axis order is chosen for ICI locality: "model" is innermost (adjacent
devices — per-layer collectives ride single-hop ICI), then "seq", then
"data"; "pipe" is outermost (only nearest-neighbor p2p traffic).

Hierarchical data axis (ZeRO++ / hpZ-style two-level reduction): when a
pod slice spans DCN (or processes talk over TCP), the `data` axis can be
factored into `("data_outer", "data_inner")` sub-axes — ICI-adjacent
ranks inner, cross-slice/cross-process outer — so the gradient wire can
reduce-scatter on the fast fabric, run the slow-fabric collective on the
1/inner shard only, and gather back on the fast fabric
(runtime/comm/bucketing.py).  Every consumer that thinks in terms of
"the data axis" goes through `MeshInfo.data_spec` / `data_axes`, which
collapse to plain `"data"` on a flat mesh — `data_outer == 1` is
EXACTLY today's layout.
"""

from __future__ import annotations

import contextlib
import math
from dataclasses import dataclass, field
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ..utils.logging import logger

# Canonical axis names, outermost-to-innermost in ICI terms.
PIPE_AXIS = "pipe"
DATA_AXIS = "data"
SEQ_AXIS = "seq"
MODEL_AXIS = "model"
EXPERT_AXIS = "expert"

# Hierarchical factorization of the data axis (flat meshes never carry
# these names; `MeshInfo.data_axes` is the portable way to address "the
# data axis" on either layout).
DATA_OUTER_AXIS = "data_outer"  # slow fabric: cross-slice / cross-process
DATA_INNER_AXIS = "data_inner"  # fast fabric: ICI-adjacent ranks

AXIS_ORDER = (PIPE_AXIS, DATA_AXIS, SEQ_AXIS, MODEL_AXIS)
HIER_AXIS_ORDER = (PIPE_AXIS, DATA_OUTER_AXIS, DATA_INNER_AXIS, SEQ_AXIS,
                   MODEL_AXIS)

_CURRENT_MESH: Optional["MeshInfo"] = None


@dataclass
class MeshInfo:
    """A constructed mesh plus axis metadata.

    Plays the role of the reference's `PipelineParallelGrid`
    (/root/reference/deepspeed/runtime/pipe/topology.py:257-466): exposes
    per-axis sizes/ranks without torch process groups.
    """

    mesh: Mesh
    axis_sizes: Dict[str, int] = field(default_factory=dict)
    # (outer, inner) factorization of the data axis; None on flat meshes.
    # axis_sizes always keeps the LOGICAL "data" size (the product), so
    # every existing axis_size(DATA_AXIS) caller is layout-agnostic.
    data_hierarchy: Optional[Tuple[int, int]] = None

    @property
    def size(self) -> int:
        return int(np.prod([max(1, s) for s in self.axis_sizes.values()]))

    def axis_size(self, axis: str) -> int:
        if self.data_hierarchy is not None:
            if axis == DATA_OUTER_AXIS:
                return self.data_hierarchy[0]
            if axis == DATA_INNER_AXIS:
                return self.data_hierarchy[1]
        return self.axis_sizes.get(axis, 1)

    # -- hierarchical-data-axis surface -------------------------------

    @property
    def hierarchical(self) -> bool:
        return self.data_hierarchy is not None

    @property
    def data_axes(self) -> Tuple[str, ...]:
        """Mesh axis names the data dimension actually lives on,
        outermost first — `("data",)` flat, `("data_outer",
        "data_inner")` hierarchical.  Collectives over the whole dp
        group take this tuple (lax.psum/pmean accept it)."""
        if self.data_hierarchy is not None:
            return (DATA_OUTER_AXIS, DATA_INNER_AXIS)
        return (DATA_AXIS,)

    @property
    def data_spec(self):
        """The PartitionSpec entry for "sharded over the data axis":
        the plain axis name flat, the sub-axis tuple hierarchical."""
        return DATA_AXIS if self.data_hierarchy is None else \
            (DATA_OUTER_AXIS, DATA_INNER_AXIS)

    @property
    def data_outer_size(self) -> int:
        return self.data_hierarchy[0] if self.data_hierarchy else 1

    @property
    def data_inner_size(self) -> int:
        return (self.data_hierarchy[1] if self.data_hierarchy
                else self.axis_size(DATA_AXIS))

    # Reference-parity aliases (pipe/topology.py get_*_parallel_world_size)
    def get_data_parallel_world_size(self) -> int:
        return self.axis_size(DATA_AXIS)

    def get_model_parallel_world_size(self) -> int:
        return self.axis_size(MODEL_AXIS)

    def get_pipe_parallel_world_size(self) -> int:
        return self.axis_size(PIPE_AXIS)

    def get_seq_parallel_world_size(self) -> int:
        return self.axis_size(SEQ_AXIS)

    def sharding(self, *spec) -> NamedSharding:
        return NamedSharding(self.mesh, PartitionSpec(*spec))

    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, PartitionSpec())


def _resolve_sizes(n_devices: int, sizes: Dict[str, int]) -> Dict[str, int]:
    """Resolve -1 ("take the rest") axis sizes against the device count."""
    resolved = {a: int(sizes.get(a, 1)) for a in AXIS_ORDER}
    free = [a for a, s in resolved.items() if s == -1]
    fixed = int(np.prod([s for s in resolved.values() if s != -1]))
    if n_devices % fixed != 0:
        raise ValueError(
            f"device count {n_devices} not divisible by fixed axis product {fixed} "
            f"(sizes={sizes})"
        )
    rest = n_devices // fixed
    if not free:
        if fixed != n_devices:
            raise ValueError(
                f"axis sizes {resolved} use {fixed} devices but {n_devices} are present"
            )
    elif len(free) == 1:
        resolved[free[0]] = rest
    else:
        raise ValueError("at most one axis size may be -1")
    return resolved


def derive_data_outer(dp_size: int) -> int:
    """Topology-derived outer factor for a hierarchical data axis: one
    outer group per jax process (the fast/slow fabric boundary — devices
    within a process share an address space / ICI, processes talk over
    DCN/TCP).  Returns 1 (flat) whenever a two-level wire cannot win:
    single process, dp not divisible by the process count, one device
    per process (inner groups of 1 reduce nothing on the fast fabric),
    or HETEROGENEOUS local device counts — make_mesh's contiguous
    reshape would then put a process boundary INSIDE an inner group,
    silently routing "fast-fabric" collectives over the slow link."""
    try:
        procs = jax.process_count()
    except Exception:
        procs = 1
    if procs <= 1 or dp_size % procs != 0 or dp_size // procs <= 1:
        return 1
    inner = dp_size // procs
    try:
        devs = jax.devices()
    except Exception:
        return 1
    if len(devs) == dp_size:
        # pure-DP (the only shape the hierarchy engages on): every
        # contiguous inner-sized run must sit inside ONE process
        for g in range(procs):
            owners = {getattr(d, "process_index", 0)
                      for d in devs[g * inner:(g + 1) * inner]}
            if len(owners) != 1:
                logger.warning(
                    f"comm.hierarchy auto: inner groups of {inner} do not "
                    f"align with process boundaries (processes contribute "
                    f"unequal local device counts) — keeping the flat "
                    f"data axis")
                return 1
    return procs


def elastic_device_slice(n_needed: int,
                         devices: Optional[Sequence] = None):
    """The device set for an elastic (shrunken-world) mesh: the first
    `n_needed` devices in `jax.devices()` order.

    In a true multi-process elastic restart the supervisor relaunched
    only the survivors, so the device count already matches and this is
    the identity.  When MORE devices are visible than the surviving
    world needs (a single-process virtual mesh simulating the shrink,
    or a host that kept its local devices while a peer died), the mesh
    is built over the leading contiguous slice — process-major order,
    so the surviving mesh keeps whole processes and the fast-fabric
    adjacency the hierarchy depends on."""
    devices = list(devices) if devices is not None else list(jax.devices())
    n_needed = int(n_needed)
    if n_needed < 1:
        raise ValueError(f"elastic world needs >= 1 device, got {n_needed}")
    if len(devices) < n_needed:
        raise ValueError(
            f"elastic world needs {n_needed} device(s) but only "
            f"{len(devices)} are visible — DSTPU_SURVIVING_WORLD cannot "
            f"exceed the relaunched job's capacity")
    if len(devices) > n_needed:
        logger.warning(
            f"elastic world: building the mesh over the first "
            f"{n_needed} of {len(devices)} visible devices "
            f"(surviving-world slice)")
    return devices[:n_needed]


def make_mesh(
    data: int = -1,
    model: int = 1,
    pipe: int = 1,
    seq: int = 1,
    data_outer: int = 1,
    devices: Optional[Sequence] = None,
    set_current: bool = True,
) -> MeshInfo:
    """Build a Mesh over the given axis sizes. -1 means "all remaining devices".

    Replaces reference `init_distributed` + mpu/topology plumbing
    (utils/distributed.py, pipe/topology.py) with one mesh.

    data_outer > 1 factors the data axis into ("data_outer",
    "data_inner") sub-axes for the hierarchical gradient wire: outer
    groups are contiguous runs of `jax.devices()` order (process-major,
    so with data_outer == process_count each process IS one inner
    group).  data_outer == 1 is exactly the flat layout.
    """
    devices = list(devices) if devices is not None else list(jax.devices())
    sizes = _resolve_sizes(len(devices), {
        DATA_AXIS: data, MODEL_AXIS: model, PIPE_AXIS: pipe, SEQ_AXIS: seq,
    })
    data_outer = int(data_outer)
    hierarchy = None
    if data_outer > 1:
        dp = sizes[DATA_AXIS]
        if dp % data_outer != 0:
            raise ValueError(
                f"data axis hierarchy: data_outer={data_outer} does not "
                f"divide the data-parallel size {dp} "
                f"(data_inner would be {dp / data_outer:g})")
        inner = dp // data_outer
        if inner == 1:
            # outer == dp: every "inner group" is one rank — nothing to
            # reduce on the fast fabric; flatten back to today's layout
            logger.debug(
                f"data hierarchy ({data_outer}, 1) is degenerate; "
                "using the flat data axis")
        else:
            hierarchy = (data_outer, inner)
    if hierarchy is not None:
        shape = (sizes[PIPE_AXIS], hierarchy[0], hierarchy[1],
                 sizes[SEQ_AXIS], sizes[MODEL_AXIS])
        # plain reshape, NOT mesh_utils: outer groups must stay
        # contiguous in jax.devices() order (process-major), which is
        # the fast/slow fabric boundary the hierarchy exists for —
        # a topology-optimizing permutation would scramble it
        dev_array = np.asarray(devices).reshape(shape)
        mesh = Mesh(dev_array, HIER_AXIS_ORDER)
        info = MeshInfo(mesh=mesh, axis_sizes=sizes,
                        data_hierarchy=hierarchy)
        if set_current:
            set_current_mesh(info)
        logger.debug(f"hierarchical mesh constructed: {sizes} with "
                     f"data=(outer {hierarchy[0]} x inner {hierarchy[1]}) "
                     f"over {len(devices)} devices")
        return info
    shape = tuple(sizes[a] for a in AXIS_ORDER)
    try:
        from jax.experimental import mesh_utils

        dev_array = mesh_utils.create_device_mesh(shape, devices=devices)
    except Exception:  # heterogeneous/virtual platforms: plain reshape
        dev_array = np.asarray(devices).reshape(shape)
    mesh = Mesh(dev_array, AXIS_ORDER)
    info = MeshInfo(mesh=mesh, axis_sizes=sizes)
    if set_current:
        set_current_mesh(info)
    logger.debug(f"mesh constructed: {sizes} over {len(devices)} devices")
    return info


def set_current_mesh(info: MeshInfo) -> None:
    global _CURRENT_MESH
    _CURRENT_MESH = info


def peek_mesh() -> Optional["MeshInfo"]:
    """Current mesh or None — never constructs one (unlike
    get_current_mesh)."""
    return _CURRENT_MESH


def get_current_mesh() -> MeshInfo:
    global _CURRENT_MESH
    if _CURRENT_MESH is None:
        _CURRENT_MESH = make_mesh(set_current=False)
    return _CURRENT_MESH


@contextlib.contextmanager
def use_mesh(info: MeshInfo):
    global _CURRENT_MESH
    prev = _CURRENT_MESH
    _CURRENT_MESH = info
    try:
        with info.mesh:
            yield info
    finally:
        _CURRENT_MESH = prev


def largest_divisible_axis(shape: Sequence[int], size: int) -> Optional[int]:
    """Pick the best dimension to shard `size`-ways: the largest dim divisible
    by `size` (ties -> earliest). None if nothing divides."""
    best = None
    best_len = 0
    for i, d in enumerate(shape):
        if size > 0 and d % size == 0 and d > best_len:
            best, best_len = i, d
    return best
