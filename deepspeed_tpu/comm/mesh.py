"""Device-mesh construction and registry — the comm substrate.

This replaces the reference's process-group bootstrap
(/root/reference/deepspeed/utils/distributed.py:12-51) with a TPU-native
design: instead of NCCL process groups, every parallelism axis is a named
axis of one `jax.sharding.Mesh` laid out over ICI (within a pod slice) and
DCN (across slices). Process groups in the reference map to mesh axes here:

    data parallel group   -> axis "data"   (ZeRO shards over this axis too)
    model parallel group  -> axis "model"  (tensor parallelism; reference
                                            delegates this to Megatron's mpu,
                                            here it is first-class)
    pipe parallel group   -> axis "pipe"   (pipeline stages)
    sequence parallelism  -> axis "seq"    (ring attention / long context;
                                            absent in the reference v0.3.15,
                                            first-class here)
    expert parallelism    -> axis "expert" (MoE; flattened into "data" when
                                            unused)

Axis order is chosen for ICI locality: "model" is innermost (adjacent
devices — per-layer collectives ride single-hop ICI), then "seq", then
"data"; "pipe" is outermost (only nearest-neighbor p2p traffic).
"""

from __future__ import annotations

import contextlib
import math
from dataclasses import dataclass, field
from typing import Dict, Optional, Sequence

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ..utils.logging import logger

# Canonical axis names, outermost-to-innermost in ICI terms.
PIPE_AXIS = "pipe"
DATA_AXIS = "data"
SEQ_AXIS = "seq"
MODEL_AXIS = "model"
EXPERT_AXIS = "expert"

AXIS_ORDER = (PIPE_AXIS, DATA_AXIS, SEQ_AXIS, MODEL_AXIS)

_CURRENT_MESH: Optional["MeshInfo"] = None


@dataclass
class MeshInfo:
    """A constructed mesh plus axis metadata.

    Plays the role of the reference's `PipelineParallelGrid`
    (/root/reference/deepspeed/runtime/pipe/topology.py:257-466): exposes
    per-axis sizes/ranks without torch process groups.
    """

    mesh: Mesh
    axis_sizes: Dict[str, int] = field(default_factory=dict)

    @property
    def size(self) -> int:
        return int(np.prod([max(1, s) for s in self.axis_sizes.values()]))

    def axis_size(self, axis: str) -> int:
        return self.axis_sizes.get(axis, 1)

    # Reference-parity aliases (pipe/topology.py get_*_parallel_world_size)
    def get_data_parallel_world_size(self) -> int:
        return self.axis_size(DATA_AXIS)

    def get_model_parallel_world_size(self) -> int:
        return self.axis_size(MODEL_AXIS)

    def get_pipe_parallel_world_size(self) -> int:
        return self.axis_size(PIPE_AXIS)

    def get_seq_parallel_world_size(self) -> int:
        return self.axis_size(SEQ_AXIS)

    def sharding(self, *spec) -> NamedSharding:
        return NamedSharding(self.mesh, PartitionSpec(*spec))

    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, PartitionSpec())


def _resolve_sizes(n_devices: int, sizes: Dict[str, int]) -> Dict[str, int]:
    """Resolve -1 ("take the rest") axis sizes against the device count."""
    resolved = {a: int(sizes.get(a, 1)) for a in AXIS_ORDER}
    free = [a for a, s in resolved.items() if s == -1]
    fixed = int(np.prod([s for s in resolved.values() if s != -1]))
    if n_devices % fixed != 0:
        raise ValueError(
            f"device count {n_devices} not divisible by fixed axis product {fixed} "
            f"(sizes={sizes})"
        )
    rest = n_devices // fixed
    if not free:
        if fixed != n_devices:
            raise ValueError(
                f"axis sizes {resolved} use {fixed} devices but {n_devices} are present"
            )
    elif len(free) == 1:
        resolved[free[0]] = rest
    else:
        raise ValueError("at most one axis size may be -1")
    return resolved


def make_mesh(
    data: int = -1,
    model: int = 1,
    pipe: int = 1,
    seq: int = 1,
    devices: Optional[Sequence] = None,
    set_current: bool = True,
) -> MeshInfo:
    """Build a Mesh over the given axis sizes. -1 means "all remaining devices".

    Replaces reference `init_distributed` + mpu/topology plumbing
    (utils/distributed.py, pipe/topology.py) with one mesh.
    """
    devices = list(devices) if devices is not None else list(jax.devices())
    sizes = _resolve_sizes(len(devices), {
        DATA_AXIS: data, MODEL_AXIS: model, PIPE_AXIS: pipe, SEQ_AXIS: seq,
    })
    shape = tuple(sizes[a] for a in AXIS_ORDER)
    try:
        from jax.experimental import mesh_utils

        dev_array = mesh_utils.create_device_mesh(shape, devices=devices)
    except Exception:  # heterogeneous/virtual platforms: plain reshape
        dev_array = np.asarray(devices).reshape(shape)
    mesh = Mesh(dev_array, AXIS_ORDER)
    info = MeshInfo(mesh=mesh, axis_sizes=sizes)
    if set_current:
        set_current_mesh(info)
    logger.debug(f"mesh constructed: {sizes} over {len(devices)} devices")
    return info


def set_current_mesh(info: MeshInfo) -> None:
    global _CURRENT_MESH
    _CURRENT_MESH = info


def peek_mesh() -> Optional["MeshInfo"]:
    """Current mesh or None — never constructs one (unlike
    get_current_mesh)."""
    return _CURRENT_MESH


def get_current_mesh() -> MeshInfo:
    global _CURRENT_MESH
    if _CURRENT_MESH is None:
        _CURRENT_MESH = make_mesh(set_current=False)
    return _CURRENT_MESH


@contextlib.contextmanager
def use_mesh(info: MeshInfo):
    global _CURRENT_MESH
    prev = _CURRENT_MESH
    _CURRENT_MESH = info
    try:
        with info.mesh:
            yield info
    finally:
        _CURRENT_MESH = prev


def largest_divisible_axis(shape: Sequence[int], size: int) -> Optional[int]:
    """Pick the best dimension to shard `size`-ways: the largest dim divisible
    by `size` (ties -> earliest). None if nothing divides."""
    best = None
    best_len = 0
    for i, d in enumerate(shape):
        if size > 0 and d % size == 0 and d > best_len:
            best, best_len = i, d
    return best
