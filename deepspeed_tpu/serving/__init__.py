"""deepspeed_tpu.serving — the continuous-batching inference engine.

The headline serving scenario (ROADMAP item 1): a paged, mesh-sharded
KV cache with block-level prefix caching (`kv_cache.py`), in-flight
admission with chunked prefill (`scheduler.py`), compiled
prefill/decode programs built StepBuilder-style (`programs.py`), the
engine + worker loop with pinned sessions (`engine.py`), and a
multi-replica fleet router (`router.py`).  Benchmarked by
`tools/serve_bench.py`; tutorial at docs/tutorials/serving.md.
"""

from .engine import ServeConfig, ServeEngine, ServeWorker, SessionPin
from .kv_cache import (KV_QUANT_WIRES, TRASH_BLOCK, PagedKVCache,
                       kv_block_bytes, resolve_kv_dtype)
from .programs import (KV_MODES, ServeProgramBuilder, ServeSchedule,
                       dequantize_params, quantize_params, sample_token)
from .router import FleetRouter, build_fleet
from .scheduler import (ADMISSION_POLICIES, ERROR, FINISHED, PREFILL,
                        RUNNING, WAITING, Request, Scheduler)

__all__ = [
    "ServeConfig", "ServeEngine", "ServeWorker", "SessionPin",
    "PagedKVCache", "TRASH_BLOCK", "KV_QUANT_WIRES", "KV_MODES",
    "kv_block_bytes", "resolve_kv_dtype", "ServeProgramBuilder",
    "ServeSchedule", "sample_token", "quantize_params",
    "dequantize_params", "Request", "Scheduler", "ADMISSION_POLICIES",
    "WAITING", "PREFILL", "RUNNING", "FINISHED", "ERROR", "FleetRouter",
    "build_fleet",
]
