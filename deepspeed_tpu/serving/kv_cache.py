"""Paged KV cache: fixed-size blocks, a refcounted free-list allocator,
per-request block tables, and a block-level prefix cache.

The serving problem the static cache in models/generation.py cannot
solve: a decode batch whose membership changes every step.  A contiguous
[B, L, H, Dh] cache ties a request's KV memory to its batch slot and its
maximum length — admitting a request mid-flight or finishing one early
strands memory.  Paging (vLLM's PagedAttention recipe, PAPERS.md) breaks
the cache into fixed-size blocks owned by a host-side free list; a
request holds exactly the blocks its current length needs, a finished
request returns them the same step, and the decode program addresses KV
through a per-request block table — so fragmentation is bounded at one
partially-filled block per request and admission is a free-list check,
not a compaction.

Prefix cache (PR 19): a FULL, immutable block's content is named by a
token-id chain hash `h_i = H(h_{i-1}, tokens_in_block_i)` salted with
the model fingerprint + kv storage mode, so two requests sharing a
prompt prefix resolve to the same hash chain.  Blocks become refcounted:
N requests alias ONE physical block by putting the same id in their
tables (the paged-attention gather cannot tell — `serving/programs.py`
is untouched on the read path, which is what keeps greedy serving
bitwise-identical to `generate()` with the cache on).  A finished
holder's registered blocks are not freed but parked in an LRU of
refcount-0 blocks: still matchable, evicted (hash deregistered, block
reused) only when the free list runs dry — never a live holder.  The
partially-filled tail block is always private (only full blocks are
hashed), and the one write that can land in a shared block — the
recompute of the final prompt token when the whole prompt is cached —
goes copy-on-write: sole registered holder is adopted in place, a
live-shared block is row-copied to a private block first
(`kv.cow_copies`).  Only prefill-written rows are ever registered;
decode-written rows (whose bitwise equality with a prefill recompute is
not pinned) stay private to their request/session.

Session pins ride the same refcounts: `pin(owner, rid)` takes one extra
reference on a finished request's blocks so a follow-up turn can adopt
them wholesale (`alloc_from_pin` transfers ownership, no copies) and
re-prefill only its new tokens.

Device layout: per layer, K and V each live in ONE flat array
`[num_blocks * block_size, block_size-major]` -> shaped
`[num_blocks * block_size, H, Dh]`.  The flat first dimension makes both
program-side accesses a single primitive: the decode write is a batched
row scatter at `table[pos // bs] * bs + pos % bs`, the attention read a
row gather of the table's blocks.  On a mesh the head dimension is
sharded over the `model` axis (the same Megatron TP layout as the
weights), so each TP rank holds its heads' share of every block and the
gather/scatter stay local to the row dimension.

Quantized storage (`dtype="int8" | "int4"`): each K/V entry becomes a
(payload, scales) pair — int8/uint8 codes `[rows, H, Dh | Dh/2]` plus
one fp16 scale per (row, head) through the PR-7 row kernels
(runtime/comm/quant.py `quantize_rows`).  The scale granularity is one
row, FINER than one cache block, so a decode scatter-write touches
exactly its own rows' payload and scales (block-local, no
read-modify-write of a shared block scale) and the TP head split
shards scales `[rows, H]` alongside the payload.  The programs
dequantize gathered rows to fp32 in-program (serving/programs.py) —
at matched kv_dtype both the speculative and the plain decode path
read identical quantized rows, which is what keeps the spec-decode
parity pin exact even at int4.  Quantized rows are pure functions of
the token prefix like dense rows, so prefix aliasing stays bitwise at
int8/int4 too (the chain hash is salted with the storage mode, so a
dense block is never served to an int8 engine).

Block 0 is the reserved TRASH block: the allocator never hands it out,
block tables are padded with it, and inactive decode slots write to it —
so the jitted programs need no branches for "this slot/table entry is
not real"; bogus traffic lands in (and is read from) a block whose
contents are never attended unmasked.

Counters (monitor/counters.py): `kv.blocks_in_use` is sampled by the
engine each step (bytes += in-use blocks, mean = bytes/calls, the
input.queue_depth convention); `kv.evictions` counts blocks reclaimed
from requests that did NOT finish naturally (shed / errored), i.e.
forced frees — a healthy run keeps it at zero.  The prefix cache adds
`kv.prefix_hits` (admissions that aliased cached blocks; bytes =
blocks aliased), `kv.prefix_hit_tokens` (bytes = prompt tokens whose
prefill was skipped), `kv.cow_copies` (bytes = device bytes copied),
`kv.session_pins` (bytes = blocks pinned) and `kv.prefix_evictions`
(refcount-0 cached blocks LRU-evicted to serve an allocation).
"""

from __future__ import annotations

import hashlib
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from ..monitor.counters import COUNTERS

TRASH_BLOCK = 0

# quantized storage modes (PR-7 kernels, runtime/comm/quant.py) — the
# cache stores (payload, scales) per K/V entry instead of a dense array
KV_QUANT_WIRES = ("int8", "int4")

# accepted string spellings for dense kv dtypes
_KV_DTYPE_ALIASES = {
    "bf16": jnp.bfloat16, "bfloat16": jnp.bfloat16,
    "fp16": jnp.float16, "float16": jnp.float16,
    "fp32": jnp.float32, "float32": jnp.float32,
}


def resolve_kv_dtype(dtype):
    """Normalize a kv_dtype spec -> ("dense", jnp dtype) or
    ("int8" | "int4", None).  Accepts quant-wire strings, dense dtype
    name strings ("bf16", "float32", ...), or dtype-likes."""
    if isinstance(dtype, str):
        name = dtype.lower()
        if name in KV_QUANT_WIRES:
            return name, None
        if name in _KV_DTYPE_ALIASES:
            return "dense", _KV_DTYPE_ALIASES[name]
        raise ValueError(
            f"kv_dtype {dtype!r} not understood; use one of "
            f"{sorted(_KV_DTYPE_ALIASES)} or {KV_QUANT_WIRES}")
    return "dense", dtype


def rows_for_tables(tables, block_size: int):
    """Block tables [R, W] -> flat cache row indices [R, W * block_size]
    (row-major walk of each slot's blocks).  THE addressing the serving
    programs attend through and the paged-attention kernel inverts
    (`rows[:, ::block_size] // block_size` recovers the table), so the
    two stay in lockstep by sharing this one definition."""
    R, W = tables.shape
    return (tables[:, :, None] * block_size +
            jnp.arange(block_size)[None, None, :]).reshape(R, -1)


def kv_block_bytes(num_layers: int, num_heads: int, head_dim: int,
                   block_size: int, kv_dtype) -> int:
    """Device bytes ONE block costs across all layers (K and V) — the
    equal-pool-bytes sizing rule serve_bench's resident-sessions lanes
    ride: int8 stores head_dim payload bytes + 2 scale bytes per
    (row, head), int4 halves the payload."""
    mode, dense = resolve_kv_dtype(kv_dtype)
    if mode == "dense":
        per_row = num_heads * head_dim * jnp.dtype(dense).itemsize
    elif mode == "int8":
        per_row = num_heads * (head_dim + 2)
    else:  # int4: two codes per byte + the fp16 scale
        per_row = num_heads * (head_dim // 2 + 2)
    return 2 * num_layers * block_size * per_row


class PagedKVCache:
    """Device block pool + host allocator for one serving engine.

    `caches` is the functional state the jitted programs thread: a list
    of (k, v) per layer, each `[num_blocks * block_size, H, Dh]`.  The
    engine passes it into a program and stores the returned (donated)
    arrays back; this object owns the allocator book-keeping only.

    Owners are opaque hashable keys: the scheduler uses request rids,
    the session store uses `("session", sid)` tuples — both walk the
    same refcount/free paths.
    """

    def __init__(self, num_layers: int, num_heads: int, head_dim: int,
                 num_blocks: int, block_size: int, table_width: int,
                 dtype=jnp.float32, mesh_info=None,
                 prefix_cache: bool = True, min_match_blocks: int = 1,
                 prefix_salt: str = ""):
        if num_blocks < 2:
            raise ValueError(
                f"num_blocks must be >= 2 (block 0 is the reserved trash "
                f"block), got {num_blocks}")
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        if table_width < 1:
            raise ValueError(f"table_width must be >= 1, got {table_width}")
        if int(min_match_blocks) < 1:
            raise ValueError(
                f"min_match_blocks must be >= 1, got {min_match_blocks}")
        self.num_layers = int(num_layers)
        self.num_heads = int(num_heads)
        self.head_dim = int(head_dim)
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self.table_width = int(table_width)
        self.dtype = dtype
        mode, dense_dtype = resolve_kv_dtype(dtype)
        # "int8"/"int4" when blocks are stored quantized, else None
        self.quant_wire = mode if mode in KV_QUANT_WIRES else None
        self.dense_dtype = dense_dtype
        if self.quant_wire == "int4" and self.head_dim % 2:
            raise ValueError(
                f"int4 KV packs two codes per byte and needs an even "
                f"head_dim, got {self.head_dim}")
        self._sharding = self._kv_sharding(mesh_info)
        self._scale_sharding = self._scale_kv_sharding(mesh_info)
        self.caches = self._init_caches()
        # block 0 reserved as trash; LIFO free list so the fragmentation
        # tests exercise immediate reuse of just-freed blocks
        self._free: List[int] = list(range(self.num_blocks - 1, 0, -1))
        self._owned: Dict[Any, List[int]] = {}
        # holders per block (live requests + session pins); absent = 0
        self._ref: Dict[int, int] = {}
        self.evictions = 0
        # -- prefix cache state ---------------------------------------
        self.prefix_enabled = bool(prefix_cache)
        self.min_match_blocks = int(min_match_blocks)
        mode_name = self.quant_wire or jnp.dtype(self.dense_dtype).name
        self._salt = hashlib.blake2b(
            f"{prefix_salt}|{mode_name}|{self.block_size}".encode(),
            digest_size=16).digest()
        self._hash_index: Dict[bytes, int] = {}   # chain hash -> block
        self._block_hash: Dict[int, bytes] = {}   # block -> chain hash
        # refcount-0 registered blocks, oldest first (the eviction order)
        self._lru: "OrderedDict[int, None]" = OrderedDict()
        self.cow_copies = 0
        self.prefix_evictions = 0
        self._copy_fn = None                      # lazy jitted block copy

    # -- device state -------------------------------------------------

    def _kv_sharding(self, mesh_info):
        """Heads sharded over the TP `model` axis when a mesh is in
        scope and divides them; None otherwise (plain local arrays)."""
        if mesh_info is None:
            return None
        from ..comm.mesh import MODEL_AXIS

        tp = mesh_info.axis_size(MODEL_AXIS)
        if tp <= 1:
            return None
        if self.num_heads % tp:
            from ..utils.logging import logger

            logger.warning(
                f"serving KV cache: model axis {tp} does not divide "
                f"num_heads {self.num_heads}; cache stays unsharded")
            return None
        return mesh_info.sharding(None, MODEL_AXIS, None)

    def _scale_kv_sharding(self, mesh_info):
        """Scales are [rows, H] — same head split as the payload."""
        if self._sharding is None:
            return None
        from ..comm.mesh import MODEL_AXIS

        return mesh_info.sharding(None, MODEL_AXIS)

    def _init_caches(self):
        rows = self.num_blocks * self.block_size
        if self.quant_wire is None:
            shape = (rows, self.num_heads, self.head_dim)

            def mk():
                z = jnp.zeros(shape, self.dense_dtype)
                return (z if self._sharding is None
                        else jax.device_put(z, self._sharding))
        else:
            width = (self.head_dim if self.quant_wire == "int8"
                     else self.head_dim // 2)
            pdt = jnp.int8 if self.quant_wire == "int8" else jnp.uint8

            def mk():
                # zero payload + zero scale dequantizes to exact zero,
                # matching the dense cache's zero init
                payload = jnp.zeros((rows, self.num_heads, width), pdt)
                scales = jnp.zeros((rows, self.num_heads), jnp.float16)
                if self._sharding is not None:
                    payload = jax.device_put(payload, self._sharding)
                    scales = jax.device_put(scales, self._scale_sharding)
                return (payload, scales)

        return [(mk(), mk()) for _ in range(self.num_layers)]

    def nbytes(self) -> int:
        return sum(int(a.size) * a.dtype.itemsize
                   for a in jax.tree_util.tree_leaves(self.caches))

    def bytes_per_block(self) -> int:
        """Device bytes one block costs across all layers (K and V)."""
        return kv_block_bytes(self.num_layers, self.num_heads,
                              self.head_dim, self.block_size, self.dtype)

    # -- allocator ----------------------------------------------------

    @property
    def capacity_blocks(self) -> int:
        """Allocatable blocks (the trash block is not capacity)."""
        return self.num_blocks - 1

    @property
    def blocks_in_use(self) -> int:
        """Blocks with a live holder (request or session pin).
        Refcount-0 cached blocks parked in the LRU are NOT in use —
        they are reclaimable the moment an allocation needs them."""
        return self.capacity_blocks - len(self._free) - len(self._lru)

    @property
    def free_blocks(self) -> int:
        """Allocatable blocks: the free list plus the refcount-0
        cached blocks the LRU would evict to serve an allocation."""
        return len(self._free) + len(self._lru)

    @property
    def cached_blocks(self) -> int:
        """Hash-registered blocks (live holders + LRU residents)."""
        return len(self._hash_index)

    def blocks_needed(self, n_tokens: int) -> int:
        return -(-int(n_tokens) // self.block_size)

    def _take_free(self) -> int:
        """Pop one allocatable block, evicting the coldest refcount-0
        cached block when the free list is dry.  Callers check
        `free_blocks` first; eviction never touches a live holder."""
        if self._free:
            return self._free.pop()
        block, _ = self._lru.popitem(last=False)   # oldest first
        h = self._block_hash.pop(block, None)
        if h is not None:
            self._hash_index.pop(h, None)
        self.prefix_evictions += 1
        COUNTERS.add("kv.prefix_evictions")
        return block

    def alloc(self, rid, n_blocks: int,
              shared: Optional[Sequence[int]] = None,
              privatize_last: bool = False) -> Optional[np.ndarray]:
        """Allocate `n_blocks` table entries for request `rid`; returns
        the padded block table `[table_width] int32` (unused entries
        point at the trash block) or None when the pool cannot cover
        the FRESH share.  `shared` aliases already-cached blocks (from
        `match_prefix`) as the table's leading entries — each gains a
        reference instead of costing a fresh block.  `privatize_last`
        handles the whole-prompt-cached case, where prefill must
        rewrite the final prompt token inside the last shared block:
        a refcount-0 (LRU) block is adopted in place, a live-shared
        block is copied to a private block first (copy-on-write)."""
        n_blocks = int(n_blocks)
        shared = list(shared or ())
        if rid in self._owned:
            raise ValueError(f"request {rid} already holds blocks")
        if n_blocks > self.table_width:
            raise ValueError(
                f"request {rid} needs {n_blocks} blocks > table width "
                f"{self.table_width} (engine capacity "
                f"{self.table_width * self.block_size} tokens)")
        if len(shared) > n_blocks:
            raise ValueError(
                f"request {rid}: {len(shared)} shared blocks exceed the "
                f"{n_blocks}-block table")
        cow = (privatize_last and bool(shared)
               and self._ref.get(shared[-1], 0) > 0)
        fresh = n_blocks - len(shared) + (1 if cow else 0)
        # `free_blocks` counts LRU-parked refcount-0 residents as
        # allocatable — but the matched blocks can BE those residents
        # (including the adopt-in-place last block).  Aliasing removes
        # them from the LRU, so they must not also be counted as
        # capacity for the fresh share, or _take_free would drain an
        # empty pool mid-allocation.
        lru_shared = sum(1 for b in set(shared)
                         if self._ref.get(b, 0) == 0)
        if fresh > len(self._free) + len(self._lru) - lru_shared:
            return None
        blocks: List[int] = []
        cow_pair = None
        for i, b in enumerate(shared):
            if privatize_last and i == len(shared) - 1:
                if self._ref.get(b, 0) == 0:
                    # sole cached holder: adopt the block in place (it
                    # keeps its hash — the rewrite of the final prompt
                    # token is bitwise-identical by the chunk-invariance
                    # pin, so the registration stays truthful)
                    self._lru.pop(b, None)
                    self._ref[b] = 1
                    blocks.append(b)
                else:
                    nb = self._take_free()
                    self._ref[nb] = 1
                    cow_pair = (b, nb)
                    blocks.append(nb)
                continue
            if self._ref.get(b, 0) == 0:
                self._lru.pop(b, None)
            self._ref[b] = self._ref.get(b, 0) + 1
            blocks.append(b)
        for _ in range(n_blocks - len(shared)):
            nb = self._take_free()
            self._ref[nb] = 1
            blocks.append(nb)
        self._owned[rid] = blocks
        if cow_pair is not None:
            self._cow_copy(*cow_pair)
        table = np.full((self.table_width,), TRASH_BLOCK, np.int32)
        table[:n_blocks] = blocks
        return table

    def blocks_of(self, rid) -> List[int]:
        return list(self._owned.get(rid, ()))

    def free(self, rid, evicted: bool = False) -> int:
        """Drop `rid`'s references.  A block whose refcount reaches
        zero returns to the free list — unless it is hash-registered,
        in which case it parks in the LRU (still matchable, reclaimed
        only under pressure).  `evicted=True` marks a FORCED reclaim
        (shed/errored request) and bumps `kv.evictions` for every block
        actually released (a still-shared block survives its evicted
        holder); natural completion does not."""
        blocks = self._owned.pop(rid, None)
        if not blocks:
            return 0
        released = 0
        for b in reversed(blocks):
            r = self._ref.get(b, 1) - 1
            if r > 0:
                self._ref[b] = r
                continue
            self._ref.pop(b, None)
            released += 1
            if b in self._block_hash:
                self._lru[b] = None            # park at the MRU end
            else:
                self._free.append(b)
        if evicted and released:
            self.evictions += released
            COUNTERS.add("kv.evictions", calls=released)
        return len(blocks)

    # -- prefix cache -------------------------------------------------

    def prefix_hashes(self, tokens: Sequence[int]) -> List[bytes]:
        """Chain hashes of `tokens`' FULL blocks: `h_i = H(h_{i-1},
        block_i_tokens)`, seeded with the (model, kv storage mode,
        block size) salt.  The partial tail block is never hashed —
        only immutable, full blocks are shareable."""
        if not self.prefix_enabled:
            return []
        bs = self.block_size
        out: List[bytes] = []
        h = self._salt
        for i in range(len(tokens) // bs):
            blk = np.asarray(tokens[i * bs:(i + 1) * bs], np.int64)
            h = hashlib.blake2b(h + blk.tobytes(),
                                digest_size=16).digest()
            out.append(h)
        return out

    def match_prefix(self, hashes: Sequence[bytes]) -> List[int]:
        """The longest registered prefix of `hashes` -> block ids.
        Matches shorter than `min_match_blocks` return empty (below
        that, aliasing buys less than its book-keeping costs)."""
        if not self.prefix_enabled:
            return []
        blocks: List[int] = []
        for h in hashes:
            b = self._hash_index.get(h)
            if b is None:
                break
            blocks.append(b)
        if len(blocks) < self.min_match_blocks:
            return []
        return blocks

    def register_prefix(self, rid, hashes: Sequence[bytes],
                        start: int = 0) -> int:
        """Publish `rid`'s blocks `start..len(hashes)-1` under their
        chain hashes (first registration wins — a concurrent identical
        prompt keeps the incumbent).  Only prefill-written rows are
        pinned bitwise against recomputation, so only blocks from a
        pure-prefill chain are safe to publish: a request that adopted
        decode-written rows (session pins) must not call this at all —
        everything it prefills attends over those rows.  `start` skips
        the leading blocks that are already registered (the matched
        prefix)."""
        if not self.prefix_enabled:
            return 0
        blocks = self._owned.get(rid)
        if not blocks:
            return 0
        n = 0
        for i in range(int(start), min(len(hashes), len(blocks))):
            h = hashes[i]
            if h in self._hash_index:
                continue
            b = blocks[i]
            old = self._block_hash.get(b)
            if old is not None and old != h:
                continue
            self._hash_index[h] = b
            self._block_hash[b] = h
            n += 1
        return n

    def _cow_copy(self, src: int, dst: int) -> None:
        """Device row copy of one block (every layer, K and V, payload
        and scales) — the copy-on-write servicing a write into a
        live-shared block."""
        if self._copy_fn is None:
            def fn(caches, src_rows, dst_rows):
                return jax.tree_util.tree_map(
                    lambda a: a.at[dst_rows].set(a[src_rows]), caches)

            self._copy_fn = jax.jit(fn, donate_argnums=(0,))
        bs = self.block_size
        rows = np.arange(bs, dtype=np.int32)
        self.caches = self._copy_fn(self.caches,
                                    jnp.asarray(rows + src * bs),
                                    jnp.asarray(rows + dst * bs))
        self.cow_copies += 1
        COUNTERS.add("kv.cow_copies", nbytes=self.bytes_per_block())

    # -- session pins -------------------------------------------------

    def pin(self, owner, rid) -> int:
        """Take one extra reference on `rid`'s blocks under `owner` (a
        session key) so they survive the request's `free()` — the
        resident-session mechanism.  Returns the pinned block count."""
        if owner in self._owned:
            raise ValueError(f"owner {owner!r} already holds blocks")
        blocks = self._owned.get(rid)
        if not blocks:
            return 0
        for b in blocks:
            self._ref[b] = self._ref.get(b, 0) + 1
        self._owned[owner] = list(blocks)
        return len(blocks)

    def alloc_from_pin(self, rid, n_blocks: int,
                       pin_owner) -> Optional[np.ndarray]:
        """Transfer a session pin's blocks to request `rid` wholesale
        (references move, nothing is copied — the pin's partial tail
        block arrives private and writable) and top up with fresh
        blocks to `n_blocks`.  Returns the table, or None (pin left
        intact) when the fresh share cannot be covered."""
        if rid in self._owned:
            raise ValueError(f"request {rid} already holds blocks")
        blocks = self._owned.get(pin_owner)
        if not blocks:
            return None
        n_blocks = max(int(n_blocks), len(blocks))
        if n_blocks > self.table_width:
            raise ValueError(
                f"request {rid} needs {n_blocks} blocks > table width "
                f"{self.table_width}")
        fresh = n_blocks - len(blocks)
        if fresh > self.free_blocks:
            return None
        self._owned.pop(pin_owner)
        blocks = list(blocks)
        for _ in range(fresh):
            nb = self._take_free()
            self._ref[nb] = 1
            blocks.append(nb)
        self._owned[rid] = blocks
        table = np.full((self.table_width,), TRASH_BLOCK, np.int32)
        table[:n_blocks] = blocks
        return table

    # -- telemetry ----------------------------------------------------

    def sample_occupancy(self) -> None:
        """Per-step occupancy sample (mean = bytes/calls in the
        report, the input.queue_depth convention)."""
        COUNTERS.add("kv.blocks_in_use", nbytes=self.blocks_in_use)

    def describe(self) -> str:
        mode = (self.quant_wire if self.quant_wire
                else jnp.dtype(self.dense_dtype).name)
        return (f"PagedKVCache(layers={self.num_layers}, "
                f"blocks={self.num_blocks} x {self.block_size} tok, "
                f"table_width={self.table_width}, heads={self.num_heads}, "
                f"head_dim={self.head_dim}, kv={mode}, "
                f"prefix_cache={'on' if self.prefix_enabled else 'off'}, "
                f"sharded={self._sharding is not None}, "
                f"{self.nbytes() / (1 << 20):.2f} MiB)")
