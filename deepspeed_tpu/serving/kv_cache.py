"""Paged KV cache: fixed-size blocks, a free-list allocator, per-request
block tables.

The serving problem the static cache in models/generation.py cannot
solve: a decode batch whose membership changes every step.  A contiguous
[B, L, H, Dh] cache ties a request's KV memory to its batch slot and its
maximum length — admitting a request mid-flight or finishing one early
strands memory.  Paging (vLLM's PagedAttention recipe, PAPERS.md) breaks
the cache into fixed-size blocks owned by a host-side free list; a
request holds exactly the blocks its current length needs, a finished
request returns them the same step, and the decode program addresses KV
through a per-request block table — so fragmentation is bounded at one
partially-filled block per request and admission is a free-list check,
not a compaction.

Device layout: per layer, K and V each live in ONE flat array
`[num_blocks * block_size, block_size-major]` -> shaped
`[num_blocks * block_size, H, Dh]`.  The flat first dimension makes both
program-side accesses a single primitive: the decode write is a batched
row scatter at `table[pos // bs] * bs + pos % bs`, the attention read a
row gather of the table's blocks.  On a mesh the head dimension is
sharded over the `model` axis (the same Megatron TP layout as the
weights), so each TP rank holds its heads' share of every block and the
gather/scatter stay local to the row dimension.

Quantized storage (`dtype="int8" | "int4"`): each K/V entry becomes a
(payload, scales) pair — int8/uint8 codes `[rows, H, Dh | Dh/2]` plus
one fp16 scale per (row, head) through the PR-7 row kernels
(runtime/comm/quant.py `quantize_rows`).  The scale granularity is one
row, FINER than one cache block, so a decode scatter-write touches
exactly its own rows' payload and scales (block-local, no
read-modify-write of a shared block scale) and the TP head split
shards scales `[rows, H]` alongside the payload.  The programs
dequantize gathered rows to fp32 in-program (serving/programs.py) —
at matched kv_dtype both the speculative and the plain decode path
read identical quantized rows, which is what keeps the spec-decode
parity pin exact even at int4.

Block 0 is the reserved TRASH block: the allocator never hands it out,
block tables are padded with it, and inactive decode slots write to it —
so the jitted programs need no branches for "this slot/table entry is
not real"; bogus traffic lands in (and is read from) a block whose
contents are never attended unmasked.

Counters (monitor/counters.py): `kv.blocks_in_use` is sampled by the
engine each step (bytes += in-use blocks, mean = bytes/calls, the
input.queue_depth convention); `kv.evictions` counts blocks reclaimed
from requests that did NOT finish naturally (shed / errored), i.e.
forced frees — a healthy run keeps it at zero.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

import jax
import jax.numpy as jnp

from ..monitor.counters import COUNTERS

TRASH_BLOCK = 0

# quantized storage modes (PR-7 kernels, runtime/comm/quant.py) — the
# cache stores (payload, scales) per K/V entry instead of a dense array
KV_QUANT_WIRES = ("int8", "int4")

# accepted string spellings for dense kv dtypes
_KV_DTYPE_ALIASES = {
    "bf16": jnp.bfloat16, "bfloat16": jnp.bfloat16,
    "fp16": jnp.float16, "float16": jnp.float16,
    "fp32": jnp.float32, "float32": jnp.float32,
}


def resolve_kv_dtype(dtype):
    """Normalize a kv_dtype spec -> ("dense", jnp dtype) or
    ("int8" | "int4", None).  Accepts quant-wire strings, dense dtype
    name strings ("bf16", "float32", ...), or dtype-likes."""
    if isinstance(dtype, str):
        name = dtype.lower()
        if name in KV_QUANT_WIRES:
            return name, None
        if name in _KV_DTYPE_ALIASES:
            return "dense", _KV_DTYPE_ALIASES[name]
        raise ValueError(
            f"kv_dtype {dtype!r} not understood; use one of "
            f"{sorted(_KV_DTYPE_ALIASES)} or {KV_QUANT_WIRES}")
    return "dense", dtype


def rows_for_tables(tables, block_size: int):
    """Block tables [R, W] -> flat cache row indices [R, W * block_size]
    (row-major walk of each slot's blocks).  THE addressing the serving
    programs attend through and the paged-attention kernel inverts
    (`rows[:, ::block_size] // block_size` recovers the table), so the
    two stay in lockstep by sharing this one definition."""
    R, W = tables.shape
    return (tables[:, :, None] * block_size +
            jnp.arange(block_size)[None, None, :]).reshape(R, -1)


def kv_block_bytes(num_layers: int, num_heads: int, head_dim: int,
                   block_size: int, kv_dtype) -> int:
    """Device bytes ONE block costs across all layers (K and V) — the
    equal-pool-bytes sizing rule serve_bench's resident-sessions lanes
    ride: int8 stores head_dim payload bytes + 2 scale bytes per
    (row, head), int4 halves the payload."""
    mode, dense = resolve_kv_dtype(kv_dtype)
    if mode == "dense":
        per_row = num_heads * head_dim * jnp.dtype(dense).itemsize
    elif mode == "int8":
        per_row = num_heads * (head_dim + 2)
    else:  # int4: two codes per byte + the fp16 scale
        per_row = num_heads * (head_dim // 2 + 2)
    return 2 * num_layers * block_size * per_row


class PagedKVCache:
    """Device block pool + host allocator for one serving engine.

    `caches` is the functional state the jitted programs thread: a list
    of (k, v) per layer, each `[num_blocks * block_size, H, Dh]`.  The
    engine passes it into a program and stores the returned (donated)
    arrays back; this object owns the allocator book-keeping only.
    """

    def __init__(self, num_layers: int, num_heads: int, head_dim: int,
                 num_blocks: int, block_size: int, table_width: int,
                 dtype=jnp.float32, mesh_info=None):
        if num_blocks < 2:
            raise ValueError(
                f"num_blocks must be >= 2 (block 0 is the reserved trash "
                f"block), got {num_blocks}")
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        if table_width < 1:
            raise ValueError(f"table_width must be >= 1, got {table_width}")
        self.num_layers = int(num_layers)
        self.num_heads = int(num_heads)
        self.head_dim = int(head_dim)
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self.table_width = int(table_width)
        self.dtype = dtype
        mode, dense_dtype = resolve_kv_dtype(dtype)
        # "int8"/"int4" when blocks are stored quantized, else None
        self.quant_wire = mode if mode in KV_QUANT_WIRES else None
        self.dense_dtype = dense_dtype
        if self.quant_wire == "int4" and self.head_dim % 2:
            raise ValueError(
                f"int4 KV packs two codes per byte and needs an even "
                f"head_dim, got {self.head_dim}")
        self._sharding = self._kv_sharding(mesh_info)
        self._scale_sharding = self._scale_kv_sharding(mesh_info)
        self.caches = self._init_caches()
        # block 0 reserved as trash; LIFO free list so the fragmentation
        # tests exercise immediate reuse of just-freed blocks
        self._free: List[int] = list(range(self.num_blocks - 1, 0, -1))
        self._owned: Dict[int, List[int]] = {}
        self.evictions = 0

    # -- device state -------------------------------------------------

    def _kv_sharding(self, mesh_info):
        """Heads sharded over the TP `model` axis when a mesh is in
        scope and divides them; None otherwise (plain local arrays)."""
        if mesh_info is None:
            return None
        from ..comm.mesh import MODEL_AXIS

        tp = mesh_info.axis_size(MODEL_AXIS)
        if tp <= 1:
            return None
        if self.num_heads % tp:
            from ..utils.logging import logger

            logger.warning(
                f"serving KV cache: model axis {tp} does not divide "
                f"num_heads {self.num_heads}; cache stays unsharded")
            return None
        return mesh_info.sharding(None, MODEL_AXIS, None)

    def _scale_kv_sharding(self, mesh_info):
        """Scales are [rows, H] — same head split as the payload."""
        if self._sharding is None:
            return None
        from ..comm.mesh import MODEL_AXIS

        return mesh_info.sharding(None, MODEL_AXIS)

    def _init_caches(self):
        rows = self.num_blocks * self.block_size
        if self.quant_wire is None:
            shape = (rows, self.num_heads, self.head_dim)

            def mk():
                z = jnp.zeros(shape, self.dense_dtype)
                return (z if self._sharding is None
                        else jax.device_put(z, self._sharding))
        else:
            width = (self.head_dim if self.quant_wire == "int8"
                     else self.head_dim // 2)
            pdt = jnp.int8 if self.quant_wire == "int8" else jnp.uint8

            def mk():
                # zero payload + zero scale dequantizes to exact zero,
                # matching the dense cache's zero init
                payload = jnp.zeros((rows, self.num_heads, width), pdt)
                scales = jnp.zeros((rows, self.num_heads), jnp.float16)
                if self._sharding is not None:
                    payload = jax.device_put(payload, self._sharding)
                    scales = jax.device_put(scales, self._scale_sharding)
                return (payload, scales)

        return [(mk(), mk()) for _ in range(self.num_layers)]

    def nbytes(self) -> int:
        return sum(int(a.size) * a.dtype.itemsize
                   for a in jax.tree_util.tree_leaves(self.caches))

    def bytes_per_block(self) -> int:
        """Device bytes one block costs across all layers (K and V)."""
        return kv_block_bytes(self.num_layers, self.num_heads,
                              self.head_dim, self.block_size, self.dtype)

    # -- allocator ----------------------------------------------------

    @property
    def capacity_blocks(self) -> int:
        """Allocatable blocks (the trash block is not capacity)."""
        return self.num_blocks - 1

    @property
    def blocks_in_use(self) -> int:
        return self.capacity_blocks - len(self._free)

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    def blocks_needed(self, n_tokens: int) -> int:
        return -(-int(n_tokens) // self.block_size)

    def alloc(self, rid: int, n_blocks: int) -> Optional[np.ndarray]:
        """Allocate `n_blocks` for request `rid`; returns the padded
        block table `[table_width] int32` (unused entries point at the
        trash block) or None when the free list cannot cover it."""
        n_blocks = int(n_blocks)
        if rid in self._owned:
            raise ValueError(f"request {rid} already holds blocks")
        if n_blocks > self.table_width:
            raise ValueError(
                f"request {rid} needs {n_blocks} blocks > table width "
                f"{self.table_width} (engine capacity "
                f"{self.table_width * self.block_size} tokens)")
        if n_blocks > len(self._free):
            return None
        blocks = [self._free.pop() for _ in range(n_blocks)]
        self._owned[rid] = blocks
        table = np.full((self.table_width,), TRASH_BLOCK, np.int32)
        table[:n_blocks] = blocks
        return table

    def blocks_of(self, rid: int) -> List[int]:
        return list(self._owned.get(rid, ()))

    def free(self, rid: int, evicted: bool = False) -> int:
        """Return `rid`'s blocks to the free list.  `evicted=True`
        marks a FORCED reclaim (shed/errored request) and bumps
        `kv.evictions`; natural completion does not."""
        blocks = self._owned.pop(rid, None)
        if not blocks:
            return 0
        self._free.extend(reversed(blocks))
        if evicted:
            self.evictions += len(blocks)
            COUNTERS.add("kv.evictions", calls=len(blocks))
        return len(blocks)

    def sample_occupancy(self) -> None:
        """Per-step occupancy sample (mean = bytes/calls in the
        report, the input.queue_depth convention)."""
        COUNTERS.add("kv.blocks_in_use", nbytes=self.blocks_in_use)

    def describe(self) -> str:
        mode = (self.quant_wire if self.quant_wire
                else jnp.dtype(self.dense_dtype).name)
        return (f"PagedKVCache(layers={self.num_layers}, "
                f"blocks={self.num_blocks} x {self.block_size} tok, "
                f"table_width={self.table_width}, heads={self.num_heads}, "
                f"head_dim={self.head_dim}, kv={mode}, "
                f"sharded={self._sharding is not None}, "
                f"{self.nbytes() / (1 << 20):.2f} MiB)")
