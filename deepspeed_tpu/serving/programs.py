"""Compiled serving programs: ONE jitted prefill and ONE jitted decode,
built through StepBuilder-style schedule composition.

Like runtime/step_builder.py collapsed the three training step paths
into one composition engine, serving lowers its two phases into two
fixed-shape programs built once from a declarative `ServeSchedule`
(`describe()` logged at build, the schedule-log contract):

* **prefill** — one prompt CHUNK of static length `prefill_chunk` for
  one request: embeds, writes the chunk's K/V through the block table,
  attends causally against everything cached so far, and (for the final
  chunk) samples the request's FIRST token in-program.  Chunking is what
  keeps a long prompt from stalling the decode batch: the scheduler
  interleaves one chunk per engine step with full decode steps.
* **decode** — one token for every slot of the packed batch
  `[max_batch]`: per-slot block-table write + gather-based paged
  attention + per-slot sampling.  Every operation is row-wise
  (layernorm, per-row attention gather, per-row matmul dots, per-row
  RNG), which is the batching-invariance contract tier-1 pins: a
  request's tokens do not depend on WHICH other requests share the
  batch, so joining mid-flight is token-identical to decoding alone.
* **verify** — the speculative-decoding forward: decode at
  `draft_len + 1` tokens per slot, scoring a slot's drafted candidates
  in ONE dispatch.  Position-keyed sampling at every row makes the
  accepted prefix bit-identical to sequential decode — the engine's
  accept/reject loop (`engine._verify_step`) rides this.

KV storage (`ServeSchedule.kv_dtype`): "dense" keeps K/V rows at the
cache arrays' dtype (param dtype or an explicit bf16 cache);
"int8"/"int4" store (payload, per-(row, head) fp16 scale) pairs via
runtime/comm/quant.py's row kernels and dequantize gathered rows to
fp32 in-program.  The surrounding attention math is shared, so parity
contracts hold at matched kv_dtype.

The attention math deliberately mirrors models/generation.py
`_block_with_cache` op for op (fp32 scores, the same einsum strings,
NEG_INF masking, probs cast to the cache dtype) so greedy serving output
is bit-identical to `generate()` when the cache lengths agree — pinned
in tests/test_serving.py.

Sampling determinism: the key for the token generated at absolute
position p is `fold_in(PRNGKey(request.seed), p)` — a pure function of
the request, never of the batch composition or the step count, so
sampled output is identical-under-seed across batch join/leave too.

qwZ weights (`quantized="int8"|"int4"`): weights are stored blockwise
quantized (runtime/comm/quant.py, the PR-7 kernels) and dequantized at
program entry — KV/weight memory headroom at rest at the cost of a
transient full-precision copy during the step (the ZeRO++ qwZ trade,
see docs/tutorials/serving.md).
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..models.gpt import GPT, layer_norm
from ..utils.logging import logger
from .kv_cache import rows_for_tables

QUANT_MODES = ("none", "int8", "int4")

# how the cache stores K/V: "dense" = at the cache arrays' own dtype
# (the dtype is a runtime property of the arrays, not program
# structure), "int8"/"int4" = (payload, scales) rows quantized through
# runtime/comm/quant.py and dequantized in-program at every gather
KV_MODES = ("dense", "int8", "int4")


class ServeSchedule(NamedTuple):
    """Declarative description of the serving program pair (the
    StepSchedule analogue; `describe()` is logged at build time)."""

    max_batch: int
    prefill_chunk: int
    block_size: int
    num_blocks: int
    table_width: int
    quantized: str = "none"        # "none" | "int8" | "int4"
    quant_block: int = 256
    kv_dtype: str = "dense"        # "dense" | "int8" | "int4"
    draft_len: int = 0             # speculative candidates per verify

    def describe(self) -> str:
        cap = self.table_width * self.block_size
        q = "" if self.quantized == "none" else f", qwZ={self.quantized}"
        kv = "" if self.kv_dtype == "dense" else f", kv={self.kv_dtype}"
        spec = "" if not self.draft_len else \
            f", spec draft {self.draft_len}"
        return (f"serve schedule: decode[{self.max_batch} slots] + "
                f"prefill[chunk {self.prefill_chunk}], paged KV "
                f"{self.num_blocks} x {self.block_size} tok "
                f"(per-request cap {cap}){q}{kv}{spec}")

    def program_key(self):
        """The fields the COMPILED programs actually depend on.
        `num_blocks` is not one of them: the cache arrays are runtime
        inputs, a different pool size just retraces — so engines with
        different pool sizes can share one program pair."""
        return self._replace(num_blocks=0)


# -- sampling ---------------------------------------------------------------


def sample_token(logits, temperature, top_k, key):
    """One row: greedy at temperature 0, else temperature + optional
    top-k truncation, sampled with the caller's key.  `top_k`/
    `temperature` are per-request ARRAYS (not static), so one compiled
    program serves every request mix."""
    greedy = jnp.argmax(logits, axis=-1)
    v = logits.shape[-1]
    t = jnp.where(temperature > 0, temperature, 1.0)
    scaled = logits.astype(jnp.float32) / t
    # dynamic top-k: value-threshold against the k-th largest logit
    # (ties at the threshold survive, the HF semantics generation.py
    # documents); top_k <= 0 disables the filter
    sorted_desc = jnp.sort(scaled, axis=-1)[..., ::-1]
    kth = sorted_desc[jnp.clip(top_k, 1, v) - 1]
    filtered = jnp.where((top_k > 0) & (scaled < kth), -jnp.inf, scaled)
    sampled = jax.random.categorical(key, filtered, axis=-1)
    return jnp.where(temperature > 0, sampled, greedy).astype(jnp.int32)


def _row_key(seed, position):
    """THE sampling-key rule: the token generated at absolute position
    p uses fold_in(PRNGKey(seed), p) — shared by prefill (first token)
    and decode so batch composition can never reach the RNG stream."""
    return jax.random.fold_in(jax.random.PRNGKey(seed), position)


# -- paged attention block (mirrors generation._block_with_cache) -----------


def _gather_rows(table, block_size):
    """Block table [W] -> flat cache row indices [W * block_size]."""
    return (table[:, None] * block_size +
            jnp.arange(block_size)[None, :]).reshape(-1)


def _kv_write(c, idx, val, kv_mode):
    """Scatter `val` [N, H, Dh] into cache entry `c` at flat rows
    `idx`.  Dense: a plain row scatter at the cache's own dtype.
    Quantized: the rows are quantized through the PR-7 row kernels and
    BOTH the payload and the per-(row, head) scales scatter at the same
    indices — the write never touches another row's scale."""
    if kv_mode == "dense":
        return c.at[idx].set(val.astype(c.dtype))
    from ..runtime.comm.quant import quantize_rows

    payload, scales = c
    codes, s = quantize_rows(val.astype(jnp.float32), kv_mode)
    return (payload.at[idx].set(codes), scales.at[idx].set(s))


def _kv_read(c, rows, kv_mode):
    """Gather cache rows `rows` [B, L] -> [B, L, H, Dh].  Dense reads
    come back at the cache dtype (the downstream casts mirror
    generation._block_with_cache); quantized reads dequantize the
    gathered rows to fp32 in-program.  The single definition lives in
    kernels/paged.py — it doubles as the paged-attention oracle's
    gather, which is what keeps the registry's jnp path bit-identical
    to this program."""
    from ..kernels.paged import kv_read

    return kv_read(c, rows, kv_mode)


def _paged_block(p, cfg, x, ck, cv, write_idx, rows, q_pos,
                 kv_mode="dense", block_size=0):
    """One decoder block over x [B, T, D] with paged KV.

    `write_idx` [B*T] flat cache rows this chunk's K/V land in, `rows`
    [B, L] flat cache rows the attention reads (the gathered block
    table), `q_pos` [B, T] absolute positions of x's tokens.  Op-for-op
    the math of generation._block_with_cache; only the cache addressing
    differs (scatter/gather through the table instead of
    dynamic_update_slice on a contiguous cache).  `kv_mode` picks the
    storage codec: "dense" stores rows at the cache arrays' dtype,
    "int8"/"int4" stores (payload, scales) pairs dequantized at the
    gather — the surrounding math is identical either way, so parity
    pins hold AT MATCHED kv_mode.
    """
    B, T, D = x.shape
    H, Dh = cfg.num_heads, cfg.head_dim
    h = layer_norm(x, p["ln1"], cfg.layer_norm_eps)
    qkv = h @ p["attn"]["qkv"]["w"].astype(h.dtype) + \
        p["attn"]["qkv"]["b"].astype(h.dtype)
    q, k, v = jnp.split(qkv, 3, axis=-1)
    shape = lambda t: t.reshape(B, T, H, Dh)
    q, k, v = shape(q), shape(k), shape(v)
    ck = _kv_write(ck, write_idx, k.reshape(B * T, H, Dh), kv_mode)
    cv = _kv_write(cv, write_idx, v.reshape(B * T, H, Dh), kv_mode)
    # attention core through the kernel registry: the jnp oracle
    # (kernels/paged.py paged_attention_reference) is this block's
    # pre-registry gather/einsum/softmax chain op-for-op — wherever the
    # oracle is chosen, serving output is bit-identical; the Pallas
    # kernel fuses the table gather (+ quantized-KV dequant) into an
    # online-softmax sweep over cache blocks
    from ..kernels import registry

    attn = registry.dispatch(
        "paged_attention", q, ck, cv, rows, q_pos,
        info={"block_size": block_size, "kv_len": rows.shape[1],
              "q_len": T, "head_dim": Dh},
        kv_mode=kv_mode, block_size=block_size)
    attn = attn.reshape(B, T, D)
    attn = attn @ p["attn"]["proj"]["w"].astype(h.dtype) + \
        p["attn"]["proj"]["b"].astype(h.dtype)
    x = x + attn
    h = layer_norm(x, p["ln2"], cfg.layer_norm_eps)
    h = h @ p["mlp"]["fc1"]["w"].astype(h.dtype) + \
        p["mlp"]["fc1"]["b"].astype(h.dtype)
    h = jax.nn.gelu(h, approximate=True)
    h = h @ p["mlp"]["fc2"]["w"].astype(h.dtype) + \
        p["mlp"]["fc2"]["b"].astype(h.dtype)
    return x + h, ck, cv


def _proj_logits(cfg, params, x_rows):
    """[B, D] hidden rows -> fp32 logits [B, V] (generation.py's head)."""
    w = (params["wte"].T if cfg.tie_embeddings else params["lm_head"])
    return (x_rows @ w.astype(x_rows.dtype)).astype(jnp.float32)


# -- qwZ weight store -------------------------------------------------------


@jax.tree_util.register_pytree_node_class
class QuantLeaf:
    """One blockwise-quantized weight: (payload, scales) ride the tree
    as array children, (shape, dtype) as static aux data — so a
    quantized params tree is a normal jit argument."""

    def __init__(self, payload, scales, shape, dtype):
        self.payload = payload
        self.scales = scales
        self.shape = tuple(shape)
        self.dtype = jnp.dtype(dtype)

    def tree_flatten(self):
        return (self.payload, self.scales), (self.shape, self.dtype)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], children[1], aux[0], aux[1])


def quantize_params(params, wire: str, block: int):
    """Blockwise-quantize every matmul-sized leaf (ndim >= 2) of a
    params tree into a `QuantLeaf`; small vectors (biases, layernorm
    scales) stay exact."""
    from ..runtime.comm.quant import quantize_blockwise

    def q(leaf):
        if getattr(leaf, "ndim", 0) < 2:
            return leaf
        payload, scales = quantize_blockwise(leaf, block, wire)
        return QuantLeaf(payload, scales, leaf.shape, leaf.dtype)

    return jax.tree_util.tree_map(
        q, params, is_leaf=lambda x: hasattr(x, "ndim"))


def dequantize_params(qparams, wire: str, block: int):
    """Inverse of quantize_params, usable under jit (program entry)."""
    from ..runtime.comm.quant import dequantize_blockwise

    def dq(node):
        if isinstance(node, QuantLeaf):
            n = 1
            for s in node.shape:
                n *= int(s)
            flat = dequantize_blockwise(node.payload, node.scales, wire, n)
            return flat.reshape(node.shape).astype(node.dtype)
        return node

    return jax.tree_util.tree_map(
        dq, qparams, is_leaf=lambda x: isinstance(x, QuantLeaf))


# -- builder ----------------------------------------------------------------


class ServeProgramBuilder:
    """Builds the jitted {prefill, decode} pair for one (model,
    schedule).  Programs are pure: (params, caches, batch state) ->
    (outputs, caches), caches donated — the engine threads the
    returned arrays back through PagedKVCache.caches."""

    def __init__(self, model: GPT, schedule: ServeSchedule):
        cfg = model.config
        if cfg.num_experts > 1 or cfg.pipeline_stages > 1:
            raise NotImplementedError(
                "the serving engine supports plain dense GPT configs "
                "(no MoE layers, no pipeline-stacked blocks) — the "
                "generate() contract")
        if schedule.quantized not in QUANT_MODES:
            raise ValueError(
                f"serving quantized_weights must be one of {QUANT_MODES}, "
                f"got {schedule.quantized!r}")
        if schedule.kv_dtype not in KV_MODES:
            raise ValueError(
                f"serving schedule kv_dtype must be one of {KV_MODES}, "
                f"got {schedule.kv_dtype!r}")
        if schedule.kv_dtype == "int4" and cfg.head_dim % 2:
            raise ValueError(
                f"int4 KV packs two codes per byte and needs an even "
                f"head_dim, got {cfg.head_dim}")
        if int(schedule.draft_len) < 0:
            raise ValueError(
                f"serving draft_len must be >= 0, got "
                f"{schedule.draft_len}")
        self.model = model
        self.schedule = schedule

    def build(self) -> dict:
        logger.info(self.schedule.describe())
        return {"schedule": self.schedule,
                "prefill": self._build_prefill(),
                "decode": self._build_decode(),
                "verify": self._build_verify(),
                "prepare_params": self._prepare_params}

    def _prepare_params(self, params):
        """Engine-side one-time weight prep for the schedule's quant
        mode (identity for "none")."""
        s = self.schedule
        if s.quantized == "none":
            return params
        # eager one-time prep (the tree carries shape/dtype metadata
        # beside the arrays, so it is not a jittable return value)
        qp = quantize_params(params, wire=s.quantized, block=s.quant_block)
        logger.info(f"serving qwZ weights: matmul leaves stored "
                    f"{s.quantized} blockwise (block {s.quant_block}), "
                    f"dequantized at program entry")
        return qp

    def _maybe_dequant(self, params):
        s = self.schedule
        if s.quantized == "none":
            return params
        return dequantize_params(params, s.quantized, s.quant_block)

    def _build_prefill(self):
        cfg = self.model.config
        s = self.schedule
        C, bs, W = s.prefill_chunk, s.block_size, s.table_width

        @partial(jax.jit, donate_argnums=(1,))
        def prefill(params, caches, tokens, pos, n_valid, table,
                    temperature, top_k, seed):
            """tokens [1, C] (zero-padded past n_valid) at absolute
            position `pos`; writes the chunk's K/V through `table`
            [W] and returns (first-token sample, last-valid-row
            logits, caches).  The sample is only meaningful on the
            FINAL chunk (the engine ignores it otherwise)."""
            params = self._maybe_dequant(params)
            abs_pos = pos + jnp.arange(C)
            # per-row gather, NOT dynamic_slice_in_dim(wpe, pos, C):
            # when the final chunk's pad rows run past the wpe table,
            # a dynamic slice CLAMPS its start backwards and shifts the
            # VALID rows onto wrong positional embeddings (silently
            # breaking the ==generate() contract); the gather keeps
            # every valid row exact and only pad rows (overwritten
            # before read / masked) see the clamped last entry
            wpe_rows = params["wpe"][
                jnp.clip(abs_pos, 0, params["wpe"].shape[0] - 1)]
            x = params["wte"][tokens] + wpe_rows[None]
            blk_i = abs_pos // bs
            # positions past the table (pad rows of the final chunk)
            # write to the trash block, never a neighbour's memory
            blk = jnp.where(blk_i < W, table[jnp.clip(blk_i, 0, W - 1)], 0)
            write_idx = blk * bs + abs_pos % bs
            rows = _gather_rows(table, bs)[None, :]
            q_pos = abs_pos[None, :]
            new_caches = []
            for bp, (ck, cv) in zip(params["blocks"], caches):
                x, ck, cv = _paged_block(bp, cfg, x, ck, cv, write_idx,
                                         rows, q_pos,
                                         kv_mode=s.kv_dtype,
                                         block_size=bs)
                new_caches.append((ck, cv))
            x = layer_norm(x, params["ln_f"], cfg.layer_norm_eps)
            last = jax.lax.dynamic_slice_in_dim(x, n_valid - 1, 1, axis=1)
            logits = _proj_logits(cfg, params, last[:, 0, :])  # [1, V]
            key = _row_key(seed, pos + n_valid)
            tok = sample_token(logits[0], temperature, top_k, key)
            return tok, logits[0], new_caches

        return prefill

    def _build_decode(self):
        cfg = self.model.config
        s = self.schedule
        bs = s.block_size

        @partial(jax.jit, donate_argnums=(1,))
        def decode(params, caches, tokens, positions, active, tables,
                   temperatures, top_ks, seeds):
            """One token for every slot: tokens [R] (each slot's last
            token), positions [R] (its write position = current cached
            length), active [R] bool, tables [R, W], sampling params
            [R].  Inactive slots write to the trash block and their
            outputs are discarded by the engine — all slot math is
            row-wise, THE batching-invariance contract."""
            params = self._maybe_dequant(params)
            R = tokens.shape[0]
            x = (params["wte"][tokens] +
                 params["wpe"][positions])[:, None, :]       # [R, 1, D]
            blk_i = positions // bs
            blk = jnp.take_along_axis(
                tables, jnp.clip(blk_i, 0, s.table_width - 1)[:, None],
                axis=1)[:, 0]
            write_idx = jnp.where(active, blk * bs + positions % bs, 0)
            rows = rows_for_tables(tables, bs)
            q_pos = positions[:, None]
            new_caches = []
            for bp, (ck, cv) in zip(params["blocks"], caches):
                x, ck, cv = _paged_block(bp, cfg, x, ck, cv, write_idx,
                                         rows, q_pos,
                                         kv_mode=s.kv_dtype,
                                         block_size=bs)
                new_caches.append((ck, cv))
            x = layer_norm(x, params["ln_f"], cfg.layer_norm_eps)
            logits = _proj_logits(cfg, params, x[:, -1, :])  # [R, V]
            keys = jax.vmap(_row_key)(seeds, positions + 1)
            toks = jax.vmap(sample_token)(logits, temperatures, top_ks,
                                          keys)
            return toks, new_caches

        return decode

    def _build_verify(self):
        """The speculative batched forward: decode's math at T =
        draft_len + 1 tokens per slot instead of one.  Row i of a slot
        holds its (i-1)-th DRAFT candidate (row 0 the last committed
        token); the program writes all candidate K/V through the table,
        attends causally (row i sees rows <= i plus everything cached)
        and samples the target token at EVERY position with the same
        `_row_key(seed, position + 1)` rule decode uses — so
        `toks[r, i]` is bit-identical to what `draft_len` sequential
        decode steps would have produced given the same prefix, which
        is the whole accept/reject correctness argument.  Rejected
        rows need no undo: the engine simply rewinds its position and
        the stale rows are re-written (same scatter indices) before
        any later query's causal mask can reach them."""
        cfg = self.model.config
        s = self.schedule
        bs, W = s.block_size, s.table_width
        T = int(s.draft_len) + 1

        @partial(jax.jit, donate_argnums=(1,))
        def verify(params, caches, tokens, positions, n_draft, active,
                   tables, temperatures, top_ks, seeds):
            """tokens [R, T] = column 0 each slot's last committed
            token, columns 1..draft_len its drafted candidates (pad
            past n_draft[r] ignored); positions [R] = the committed
            token's write position.  Returns (target samples [R, T],
            caches): toks[r, i] is the token the target emits at
            absolute position positions[r] + 1 + i given the prefix
            through column i."""
            params = self._maybe_dequant(params)
            R = tokens.shape[0]
            abs_pos = positions[:, None] + jnp.arange(T)[None, :]
            # per-row gather with a clip, the prefill rule: pad rows
            # past the wpe table clamp (their writes land in trash and
            # their samples are discarded by the engine)
            wpe_rows = params["wpe"][
                jnp.clip(abs_pos, 0, params["wpe"].shape[0] - 1)]
            x = params["wte"][tokens] + wpe_rows          # [R, T, D]
            blk_i = abs_pos // bs
            valid = (active[:, None] &
                     (jnp.arange(T)[None, :] <= n_draft[:, None]) &
                     (blk_i < W))
            blk = jnp.take_along_axis(tables,
                                      jnp.clip(blk_i, 0, W - 1), axis=1)
            # rows past a slot's drafts (and inactive slots) write to
            # the trash block, the decode convention
            write_idx = jnp.where(valid, blk * bs + abs_pos % bs,
                                  0).reshape(R * T)
            rows = rows_for_tables(tables, bs)
            q_pos = abs_pos
            new_caches = []
            for bp, (ck, cv) in zip(params["blocks"], caches):
                x, ck, cv = _paged_block(bp, cfg, x, ck, cv, write_idx,
                                         rows, q_pos,
                                         kv_mode=s.kv_dtype,
                                         block_size=bs)
                new_caches.append((ck, cv))
            x = layer_norm(x, params["ln_f"], cfg.layer_norm_eps)
            logits = _proj_logits(
                cfg, params,
                x.reshape(R * T, -1)).reshape(R, T, -1)   # [R, T, V]
            keys = jax.vmap(jax.vmap(_row_key, in_axes=(None, 0)))(
                seeds, abs_pos + 1)
            toks = jax.vmap(jax.vmap(
                sample_token, in_axes=(0, None, None, 0)))(
                logits, temperatures, top_ks, keys)
            return toks, new_caches

        return verify
