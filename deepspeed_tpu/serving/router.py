"""Fleet router: admission over N in-process ServeEngine replicas.

One excellent serving node (PR 12-18) is not a fleet.  The router is
the smallest thing that makes N of them act like one endpoint:

* **Least-loaded dispatch** — a request lands on the replica holding
  the fewest live KV blocks (`kv.blocks_in_use`, queue depth as the
  tiebreak).  Block occupancy is the honest load signal for a paged
  engine: it is what actually gates admission, so balancing it
  balances time-to-first-token.
* **Session affinity** — a pinned session's KV blocks are resident on
  exactly one replica, so a request carrying that `session_id` MUST
  land there (and does, even over the queue limit — re-prefilling the
  whole history elsewhere costs more than queueing).  The router
  learns the mapping at dispatch; at the NEXT dispatch for that
  session it probes the replica (`ServeEngine.session_active`) and
  drops a stale mapping — pin expired, pressure-released, or chain
  errored — falling back to least-loaded.  The map is additionally
  swept of stale entries whenever it outgrows `affinity_cap`, so
  many distinct one-shot session ids cannot grow it without bound.
* **Queue spill-over** — when the least-loaded pick's waiting queue is
  at `queue_limit`, the request spills to the next-least-loaded
  replica with room (`router.spills`).
* **Shed-on-saturation** — when EVERY replica's queue is full the
  request is refused immediately in state "error" (`router.shed`)
  instead of deepening every queue: the serving analogue of the
  engine-level watchdog shed, load-shedding at the front door.

Replicas are in-process engines sharing ONE compiled program pair
(`build_fleet` builds the first engine, the rest reuse its programs —
`ServeSchedule.program_key` zeroes the pool size precisely so engines
with different pool sizes can share), each with its OWN PagedKVCache
and therefore its own prefix cache.  Cross-replica prefix reuse —
live KV block migration — is explicitly out of scope (next PR); the
router's session affinity is what keeps the per-replica caches hot.

Counters (`router.*`, excluded from the comm byte table like the
other serving families): `router.dispatches` — requests dispatched
(bytes += the chosen replica's `kv.blocks_in_use` at dispatch, so
bytes/calls is the mean load a dispatch landed on);
`router.spills` — dispatches deflected from a full queue;
`router.shed` — requests refused with every queue full.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Sequence

from ..monitor.counters import COUNTERS
from ..utils.logging import logger
from .engine import ServeConfig, ServeEngine, ServeWorker
from .scheduler import ERROR, Request


def build_fleet(model, params, config: Optional[ServeConfig] = None,
                replicas: int = 2, mesh_info=None, programs=None,
                clock=time.monotonic) -> List[ServeEngine]:
    """N ServeEngine replicas sharing one compiled program pair: the
    first engine compiles (or adopts `programs`, e.g. a bench's warmed
    pair), the rest reuse (same schedule -> same program_key, the
    prebuilt-programs path ServeEngine already validates).  Each
    replica owns its KV pool and prefix cache."""
    if int(replicas) < 1:
        raise ValueError(f"fleet replicas must be >= 1, got {replicas}")
    first = ServeEngine(model, params, config, mesh_info=mesh_info,
                        programs=programs, clock=clock)
    engines = [first]
    for _ in range(int(replicas) - 1):
        engines.append(ServeEngine(model, params, config,
                                   mesh_info=mesh_info,
                                   programs=first.programs, clock=clock))
    return engines


class FleetRouter:
    """Front door over a list of ServeEngine replicas.  `submit()` is
    the whole API a frontend needs — safe from any thread (a mutex
    serializes choose/dispatch/affinity, so concurrent first turns of
    one session land on ONE replica); `start()`/`close()` run one
    ServeWorker per replica so the engines decode concurrently (XLA
    releases the GIL during execution, so replicas overlap even in one
    process), and `run()` drives them synchronously for tests."""

    def __init__(self, engines: Sequence[ServeEngine],
                 queue_limit: int = 64, session_affinity: bool = True,
                 affinity_cap: int = 1024):
        if not engines:
            raise ValueError("FleetRouter needs at least one engine")
        if int(queue_limit) < 1:
            raise ValueError(
                f"fleet queue_limit must be >= 1, got {queue_limit}")
        if int(affinity_cap) < 1:
            raise ValueError(
                f"fleet affinity_cap must be >= 1, got {affinity_cap}")
        self.engines: List[ServeEngine] = list(engines)
        self.queue_limit = int(queue_limit)
        self.session_affinity = bool(session_affinity)
        self.affinity_cap = int(affinity_cap)
        self._session_replica: Dict[Any, int] = {}
        self._lock = threading.Lock()
        self._workers: List[ServeWorker] = []
        self.dispatched = 0
        self.spilled = 0
        self.shed = 0

    # -- dispatch ------------------------------------------------------

    def _load(self, i: int):
        eng = self.engines[i]
        return (eng.kv.blocks_in_use, eng.scheduler.n_waiting, i)

    def _queue_depth(self, i: int) -> int:
        return self.engines[i].scheduler.n_waiting

    def _choose(self, session_id) -> Optional[int]:
        """The replica this request lands on, or None (saturated)."""
        if self.session_affinity and session_id is not None:
            i = self._session_replica.get(session_id)
            if i is not None:
                if self.engines[i].session_active(session_id):
                    # hard affinity: the pin's blocks live there; even a
                    # full queue beats re-prefilling the history cold
                    return i
                # pin expired / pressure-released / chain errored:
                # nothing to be warm on — route by load like a cold turn
                del self._session_replica[session_id]
        order = sorted(range(len(self.engines)), key=self._load)
        first_choice = order[0]
        for i in order:
            if self._queue_depth(i) < self.queue_limit:
                if i != first_choice:
                    self.spilled += 1
                    COUNTERS.add("router.spills")
                return i
        return None

    def submit(self, prompt: Sequence[int], max_new_tokens: int,
               temperature: float = 0.0, top_k: int = 0, seed: int = 0,
               eos_token: Optional[int] = None,
               session_id: Optional[Any] = None) -> Request:
        """Route one request.  Returns the live Request from the chosen
        replica — or, with every queue at the limit, a Request already
        in state "error" that was never enqueued anywhere."""
        with self._lock:
            i = self._choose(session_id)
            if i is None:
                self.shed += 1
                COUNTERS.add("router.shed")
                req = Request(prompt=[int(t) for t in prompt],
                              max_new_tokens=int(max_new_tokens),
                              session_id=session_id)
                req.state = ERROR
                req.error = (f"fleet saturated: every replica queue >= "
                             f"{self.queue_limit}")
                logger.warning(
                    f"fleet router: shed a request ({req.error})")
                return req
            eng = self.engines[i]
            COUNTERS.add("router.dispatches", nbytes=eng.kv.blocks_in_use)
            self.dispatched += 1
            req = eng.submit(prompt, max_new_tokens,
                             temperature=temperature, top_k=top_k,
                             seed=seed, eos_token=eos_token,
                             session_id=session_id)
            if self.session_affinity and session_id is not None:
                # recorded AFTER eng.submit so the sweep's liveness
                # probe already sees this session's waiting request
                self._session_replica[session_id] = i
                if len(self._session_replica) > self.affinity_cap:
                    self._sweep_affinity()
        req.replica = i
        return req

    def _sweep_affinity(self) -> None:
        """Drop every mapping whose replica no longer has the session
        active (caller holds the lock) — the bound that keeps many
        distinct one-shot session ids from growing the map forever."""
        self._session_replica = {
            sid: i for sid, i in self._session_replica.items()
            if self.engines[i].session_active(sid)}

    # -- driving -------------------------------------------------------

    def has_work(self) -> bool:
        return any(e.has_work() for e in self.engines)

    def step_all(self) -> bool:
        did = False
        for e in self.engines:
            if e.has_work():
                did = e.step() or did
        return did

    def run(self) -> None:
        """Synchronous drive: step every replica until the fleet is
        idle (tests and the dry lanes; the bench uses workers)."""
        while self.has_work():
            self.step_all()

    def start(self) -> None:
        """One ServeWorker daemon per replica — concurrent decoding."""
        if self._workers:
            return
        for e in self.engines:
            w = ServeWorker(e)
            w.start()
            self._workers.append(w)

    def close(self) -> None:
        for w in self._workers:
            w.stop()
        self._workers = []
        for e in self.engines:
            e.close()

    # -- telemetry -----------------------------------------------------

    @property
    def resident_sessions(self) -> int:
        return sum(e.resident_sessions for e in self.engines)

    def describe(self) -> str:
        return (f"FleetRouter({len(self.engines)} replicas, "
                f"queue_limit={self.queue_limit}, session_affinity="
                f"{'on' if self.session_affinity else 'off'})")
