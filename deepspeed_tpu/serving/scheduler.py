"""Continuous-batching scheduler: in-flight admission over the paged
KV cache.

The scheduling loop the engine drives once per `step()`:

1. **admit** — move waiting requests into free decode slots whenever
   the free list can cover their whole KV budget:
   ceil((prompt + max_new + draft_len) / block_size) blocks, clamped to
   the table width.  The `draft_len` tail matters under speculative
   decoding: a verify step writes up to `draft_len` candidate K/V rows
   PAST the committed length, and without the reservation those rows
   would spill into the trash-padded tail of the block table — an
   accepted draft's K/V silently living in the trash block, corrupting
   every later attention read (the off-by-draft starvation
   tests/test_spec_decode.py pins).  Admission policy:

   * `"continuous"` (the subsystem's reason to exist): a request joins
     the RUNNING batch at ANY decode step, and a finished request frees
     its slot + blocks the same step — the decode batch stays full
     under load instead of draining to the longest request.
   * `"static"` (the baseline serve_bench beats): a new batch is
     admitted only when every slot is empty — classic static batching,
     head-of-line blocked on the longest request of the previous batch.

2. **prefill** — admitted requests stream their prompt through the
   chunked prefill program, at most `max_prefill_chunks_per_step`
   chunks per engine step, so a long prompt never stalls the decode
   batch for more than one chunk's worth of compute.

3. **decode** — every RUNNING slot advances one token.

Requests own their block table for their whole life; finishing
(naturally or shed) frees the blocks immediately.
"""

from __future__ import annotations

import itertools
import threading
import time
from dataclasses import dataclass, field
from typing import List, Optional

from .kv_cache import PagedKVCache

WAITING = "waiting"
PREFILL = "prefill"
RUNNING = "running"
FINISHED = "finished"
ERROR = "error"

ADMISSION_POLICIES = ("continuous", "static")


@dataclass
class Request:
    """One generation request and its whole lifecycle."""

    prompt: List[int]
    max_new_tokens: int
    temperature: float = 0.0
    top_k: int = 0
    seed: int = 0
    eos_token: Optional[int] = None
    rid: int = -1
    state: str = WAITING
    out: List[int] = field(default_factory=list)
    error: Optional[str] = None
    # engine book-keeping
    slot: Optional[int] = None
    table = None                      # np.int32 [table_width]
    prefill_pos: int = 0              # tokens already prefilled
    cached_len: int = 0               # cache positions written (real)
    # timestamps (engine clock)
    t_submit: float = 0.0
    t_first_token: Optional[float] = None
    t_finish: Optional[float] = None
    token_times: List[float] = field(default_factory=list)

    @property
    def done(self) -> bool:
        return self.state in (FINISHED, ERROR)

    @property
    def ttft_s(self) -> Optional[float]:
        if self.t_first_token is None:
            return None
        return self.t_first_token - self.t_submit


class Scheduler:
    """Slot + admission book-keeping for one ServeEngine.  Thread-safe
    submission (the bench submits from an arrival thread while a worker
    thread drives steps); everything else runs on the engine thread."""

    def __init__(self, kv: PagedKVCache, max_batch: int,
                 admission: str = "continuous",
                 clock=time.monotonic, draft_len: int = 0):
        if admission not in ADMISSION_POLICIES:
            raise ValueError(
                f"admission must be one of {ADMISSION_POLICIES}, got "
                f"{admission!r}")
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        if int(draft_len) < 0:
            raise ValueError(f"draft_len must be >= 0, got {draft_len}")
        self.kv = kv
        self.max_batch = int(max_batch)
        self.admission = admission
        self.draft_len = int(draft_len)
        self.clock = clock
        self.slots: List[Optional[Request]] = [None] * self.max_batch
        self._waiting: List[Request] = []
        self._lock = threading.Lock()
        self._rid = itertools.count()
        self.requests: List[Request] = []
        # monitor.tracing.TraceRecorder (or None) — set by
        # ServeEngine.attach_tracing; admit() emits one `queue_wait`
        # complete event per sampled admitted request.
        self.tracer = None

    # -- submission (any thread) --------------------------------------

    def submit(self, req: Request) -> Request:
        req.rid = next(self._rid)
        req.t_submit = self.clock()
        needed = self.kv.blocks_needed(len(req.prompt) + req.max_new_tokens)
        if needed > self.kv.table_width:
            raise ValueError(
                f"request needs {needed} KV blocks > table width "
                f"{self.kv.table_width}: prompt {len(req.prompt)} + "
                f"max_new {req.max_new_tokens} exceeds the engine's "
                f"{self.kv.table_width * self.kv.block_size}-token "
                f"per-request capacity")
        reserved = self.blocks_reserved(req)
        if reserved > self.kv.capacity_blocks:
            raise ValueError(
                f"request needs {reserved} KV blocks (incl. the "
                f"{self.draft_len}-token speculative tail) but the cache "
                f"only has {self.kv.capacity_blocks}")
        with self._lock:
            self._waiting.append(req)
            self.requests.append(req)
        return req

    def blocks_reserved(self, req: Request) -> int:
        """The request's whole-life block budget INCLUDING the
        speculative tail: verify writes up to `draft_len` candidate
        rows past the committed length, so those rows must be backed
        by real blocks (never the trash-padded table tail) or an
        accepted draft's K/V would be silently lost.  Clamped to the
        table width — the engine clamps per-step draft proposals to
        the allocated rows, so the cap is never overrun."""
        tokens = min(len(req.prompt) + req.max_new_tokens + self.draft_len,
                     self.kv.table_width * self.kv.block_size)
        return self.kv.blocks_needed(tokens)

    # -- engine-thread scheduling -------------------------------------

    def admit(self) -> List[Request]:
        """Admission pass; returns the newly admitted requests."""
        if self.admission == "static" and any(
                s is not None for s in self.slots):
            return []
        admitted = []
        with self._lock:
            while self._waiting:
                free_slots = [i for i, s in enumerate(self.slots)
                              if s is None]
                if not free_slots:
                    break
                req = self._waiting[0]
                needed = self.blocks_reserved(req)
                table = self.kv.alloc(req.rid, needed)
                if table is None:
                    break  # FIFO: never starve the head of the queue
                self._waiting.pop(0)
                req.table = table
                req.slot = free_slots[0]
                req.state = PREFILL
                self.slots[req.slot] = req
                admitted.append(req)
        tr = self.tracer
        if tr is not None and admitted:
            # Queue wait is measured on the SCHEDULER clock (injectable
            # for tests) and back-dated onto the tracer clock so the
            # span ends at the admission instant.
            now = self.clock()
            for req in admitted:
                if not tr.sampled(f"rid:{req.rid}"):
                    continue
                dur_us = max(0, int((now - req.t_submit) * 1e6))
                tr.add_complete("queue_wait", "serve",
                                ts_us=tr.now_us() - dur_us,
                                dur_us=dur_us, rid=req.rid,
                                prompt=len(req.prompt))
        return admitted

    def prefilling(self) -> List[Request]:
        return [r for r in self.slots if r is not None and
                r.state == PREFILL]

    def running(self) -> List[Request]:
        return [r for r in self.slots if r is not None and
                r.state == RUNNING]

    def occupied(self) -> List[Request]:
        return [r for r in self.slots if r is not None]

    def finish(self, req: Request, state: str = FINISHED,
               error: Optional[str] = None) -> None:
        """Terminal transition: free the slot and the KV blocks NOW —
        immediate reclaim is what lets the next waiting request join
        at the very next step."""
        req.state = state
        req.error = error
        req.t_finish = self.clock()
        if req.slot is not None:
            self.slots[req.slot] = None
            req.slot = None
        self.kv.free(req.rid, evicted=(state == ERROR))

    def has_work(self) -> bool:
        with self._lock:
            waiting = bool(self._waiting)
        return waiting or any(s is not None for s in self.slots)

    @property
    def n_waiting(self) -> int:
        with self._lock:
            return len(self._waiting)
