"""Continuous-batching scheduler: in-flight admission over the paged
KV cache.

The scheduling loop the engine drives once per `step()`:

1. **admit** — move waiting requests into free decode slots whenever
   the pool can cover their UNSHARED KV budget.  The whole-life budget
   is ceil((prompt + max_new + draft_len) / block_size) blocks, clamped
   to the table width, but the prefix cache discounts it: blocks whose
   chain hash is already registered are aliased (one refcount, zero
   fresh blocks) and a request arriving with a live session pin adopts
   the pin's blocks outright — admission charges only what is actually
   new.  Prefill then starts at the first non-cached position.  The
   `draft_len` tail matters under speculative decoding: a verify step
   writes up to `draft_len` candidate K/V rows PAST the committed
   length, and without the reservation those rows would spill into the
   trash-padded tail of the block table — an accepted draft's K/V
   silently living in the trash block, corrupting every later
   attention read (the off-by-draft starvation
   tests/test_spec_decode.py pins).  Admission policy:

   * `"continuous"` (the subsystem's reason to exist): a request joins
     the RUNNING batch at ANY decode step, and a finished request frees
     its slot + blocks the same step — the decode batch stays full
     under load instead of draining to the longest request.
   * `"static"` (the baseline serve_bench beats): a new batch is
     admitted only when every slot is empty — classic static batching,
     head-of-line blocked on the longest request of the previous batch.

2. **prefill** — admitted requests stream their prompt through the
   chunked prefill program, at most `max_prefill_chunks_per_step`
   chunks per engine step, so a long prompt never stalls the decode
   batch for more than one chunk's worth of compute.  A prefix-cached
   request's stream starts at its first non-cached token.

3. **decode** — every RUNNING slot advances one token.

Requests own their block table for their whole life; finishing
(naturally or shed) drops their references immediately — a block a
finished request shared with a live holder survives, its private
blocks return to the pool (registered ones park in the prefix LRU).
"""

from __future__ import annotations

import itertools
import threading
import time
from dataclasses import dataclass, field
from typing import Any, List, Optional

from ..monitor.counters import COUNTERS
from .kv_cache import PagedKVCache

WAITING = "waiting"
PREFILL = "prefill"
RUNNING = "running"
FINISHED = "finished"
ERROR = "error"

ADMISSION_POLICIES = ("continuous", "static")


@dataclass
class Request:
    """One generation request and its whole lifecycle."""

    prompt: List[int]
    max_new_tokens: int
    temperature: float = 0.0
    top_k: int = 0
    seed: int = 0
    eos_token: Optional[int] = None
    session_id: Optional[Any] = None  # pin blocks for a follow-up turn
    rid: int = -1
    state: str = WAITING
    out: List[int] = field(default_factory=list)
    error: Optional[str] = None
    # engine book-keeping
    slot: Optional[int] = None
    table = None                      # np.int32 [table_width]
    prefill_pos: int = 0              # tokens already prefilled
    cached_len: int = 0               # cache positions written (real)
    prefix_cached_tokens: int = 0     # prompt tokens skipped at admit
    block_hashes: List[bytes] = field(default_factory=list)
    # timestamps (engine clock)
    t_submit: float = 0.0
    t_first_token: Optional[float] = None
    t_finish: Optional[float] = None
    token_times: List[float] = field(default_factory=list)

    @property
    def done(self) -> bool:
        return self.state in (FINISHED, ERROR)

    @property
    def ttft_s(self) -> Optional[float]:
        if self.t_first_token is None:
            return None
        return self.t_first_token - self.t_submit


class Scheduler:
    """Slot + admission book-keeping for one ServeEngine.  Thread-safe
    submission (the bench submits from an arrival thread while a worker
    thread drives steps); everything else runs on the engine thread."""

    def __init__(self, kv: PagedKVCache, max_batch: int,
                 admission: str = "continuous",
                 clock=time.monotonic, draft_len: int = 0):
        if admission not in ADMISSION_POLICIES:
            raise ValueError(
                f"admission must be one of {ADMISSION_POLICIES}, got "
                f"{admission!r}")
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        if int(draft_len) < 0:
            raise ValueError(f"draft_len must be >= 0, got {draft_len}")
        self.kv = kv
        self.max_batch = int(max_batch)
        self.admission = admission
        self.draft_len = int(draft_len)
        self.clock = clock
        self.slots: List[Optional[Request]] = [None] * self.max_batch
        self._waiting: List[Request] = []
        self._lock = threading.Lock()
        self._rid = itertools.count()
        self.requests: List[Request] = []
        # monitor.tracing.TraceRecorder (or None) — set by
        # ServeEngine.attach_tracing; admit() emits one `queue_wait`
        # complete event per sampled admitted request.
        self.tracer = None
        # session hooks (set by ServeEngine when sessions are enabled):
        # session_lookup(req) -> pin info or None; session_consumed(req,
        # pin) runs after the pin's blocks transferred to the request.
        self.session_lookup = None
        self.session_consumed = None

    # -- submission (any thread) --------------------------------------

    def submit(self, req: Request) -> Request:
        req.rid = next(self._rid)
        req.t_submit = self.clock()
        needed = self.kv.blocks_needed(len(req.prompt) + req.max_new_tokens)
        if needed > self.kv.table_width:
            raise ValueError(
                f"request needs {needed} KV blocks > table width "
                f"{self.kv.table_width}: prompt {len(req.prompt)} + "
                f"max_new {req.max_new_tokens} exceeds the engine's "
                f"{self.kv.table_width * self.kv.block_size}-token "
                f"per-request capacity")
        reserved = self.blocks_reserved(req)
        if reserved > self.kv.capacity_blocks:
            raise ValueError(
                f"request needs {reserved} KV blocks (incl. the "
                f"{self.draft_len}-token speculative tail) but the cache "
                f"only has {self.kv.capacity_blocks}")
        with self._lock:
            self._waiting.append(req)
            self.requests.append(req)
        return req

    def blocks_reserved(self, req: Request) -> int:
        """The request's whole-life block budget INCLUDING the
        speculative tail: verify writes up to `draft_len` candidate
        rows past the committed length, so those rows must be backed
        by real blocks (never the trash-padded table tail) or an
        accepted draft's K/V would be silently lost.  Clamped to the
        table width — the engine clamps per-step draft proposals to
        the allocated rows, so the cap is never overrun.  This is the
        TABLE budget; the prefix cache discounts what admission
        actually charges against the pool."""
        tokens = min(len(req.prompt) + req.max_new_tokens + self.draft_len,
                     self.kv.table_width * self.kv.block_size)
        return self.kv.blocks_needed(tokens)

    # -- engine-thread scheduling -------------------------------------

    def _try_alloc(self, req: Request):
        """One admission attempt: session-pin adoption first, then the
        hash-chain prefix match, then a plain allocation.  Returns the
        block table or None; on success the request's cached offsets
        and registration hashes are set."""
        needed = self.blocks_reserved(req)
        pin = None
        if req.session_id is not None and self.session_lookup is not None:
            pin = self.session_lookup(req)
        if pin is not None:
            table = self.kv.alloc_from_pin(req.rid, needed, pin.owner)
            if table is None:
                return None
            # block_hashes stays EMPTY: the adopted blocks hold
            # decode-written rows, which are not pinned bitwise against
            # a cold-prefill recompute, and every block this request
            # prefills attends over them — so none of its blocks may be
            # published under token-only chain hashes for third-party
            # matching (the cache-on/off exactness contract)
            req.cached_len = req.prefill_pos = pin.cached_len
            req.prefix_cached_tokens = pin.cached_len
            if pin.cached_len:
                COUNTERS.add("kv.prefix_hit_tokens",
                             nbytes=pin.cached_len)
            if self.session_consumed is not None:
                self.session_consumed(req, pin)
            return table
        hashes = self.kv.prefix_hashes(req.prompt)
        matched = self.kv.match_prefix(hashes)
        m = len(matched)
        # a fully-cached, block-aligned prompt still recomputes its
        # final token (prefill samples the first output there) — that
        # write lands in the last shared block, the one COW case
        privatize = bool(m) and m * self.kv.block_size >= len(req.prompt)
        table = self.kv.alloc(req.rid, needed, shared=matched,
                              privatize_last=privatize)
        if table is None:
            return None
        req.block_hashes = hashes
        if m:
            skipped = min(m * self.kv.block_size, len(req.prompt) - 1)
            req.cached_len = req.prefill_pos = skipped
            req.prefix_cached_tokens = skipped
            COUNTERS.add("kv.prefix_hits", nbytes=m)
            COUNTERS.add("kv.prefix_hit_tokens", nbytes=skipped)
        return table

    def admit(self) -> List[Request]:
        """Admission pass; returns the newly admitted requests."""
        if self.admission == "static" and any(
                s is not None for s in self.slots):
            return []
        admitted = []
        with self._lock:
            while self._waiting:
                free_slots = [i for i, s in enumerate(self.slots)
                              if s is None]
                if not free_slots:
                    break
                req = self._waiting[0]
                table = self._try_alloc(req)
                if table is None:
                    break  # FIFO: never starve the head of the queue
                self._waiting.pop(0)
                req.table = table
                req.slot = free_slots[0]
                req.state = PREFILL
                self.slots[req.slot] = req
                admitted.append(req)
        tr = self.tracer
        if tr is not None and admitted:
            # Queue wait is measured on the SCHEDULER clock (injectable
            # for tests) and back-dated onto the tracer clock so the
            # span ends at the admission instant.
            now = self.clock()
            for req in admitted:
                if not tr.sampled(f"rid:{req.rid}"):
                    continue
                dur_us = max(0, int((now - req.t_submit) * 1e6))
                tr.add_complete("queue_wait", "serve",
                                ts_us=tr.now_us() - dur_us,
                                dur_us=dur_us, rid=req.rid,
                                prompt=len(req.prompt),
                                cached=req.prefix_cached_tokens)
        return admitted

    def prefilling(self) -> List[Request]:
        return [r for r in self.slots if r is not None and
                r.state == PREFILL]

    def running(self) -> List[Request]:
        return [r for r in self.slots if r is not None and
                r.state == RUNNING]

    def occupied(self) -> List[Request]:
        return [r for r in self.slots if r is not None]

    def finish(self, req: Request, state: str = FINISHED,
               error: Optional[str] = None) -> None:
        """Terminal transition: free the slot and drop the KV
        references NOW — immediate reclaim is what lets the next
        waiting request join at the very next step."""
        req.state = state
        req.error = error
        req.t_finish = self.clock()
        if req.slot is not None:
            self.slots[req.slot] = None
            req.slot = None
        self.kv.free(req.rid, evicted=(state == ERROR))

    def has_work(self) -> bool:
        with self._lock:
            waiting = bool(self._waiting)
        return waiting or any(s is not None for s in self.slots)

    def has_session(self, sid) -> bool:
        """A live (waiting or slot-resident) request carries `sid`."""
        with self._lock:
            if any(r.session_id == sid for r in self._waiting):
                return True
        return any(r is not None and r.session_id == sid
                   for r in self.slots)

    @property
    def n_waiting(self) -> int:
        with self._lock:
            return len(self._waiting)
