"""The continuous-batching serving engine.

One `ServeEngine` owns: a `PagedKVCache` (block pool + free list), a
`Scheduler` (admission + slots), and the jitted {prefill, decode}
program pair from `ServeProgramBuilder`.  `step()` is the whole serving
loop body — admit, prefill one chunk round, decode one token for every
running slot — and everything else (the bench's Poisson arrival thread,
`generate()`'s synchronous loop, a `ServeWorker` daemon) just drives
`step()`.

Resilience contract (the PR-8 machinery, applied to serving):

* `fault_point` sites `serve.step` / `serve.admit` / `serve.prefill` /
  `serve.decode` make the engine chaos-testable like every other layer.
* `attach_watchdog(wd)` registers the serving worker thread as a
  StepWatchdog thread group and beats the watchdog at every step
  boundary; wiring the watchdog's `on_trip` to `request_shed()` closes
  the loop: a wedged decode step trips the deadline, the trip handler
  flags the engine, and the moment the engine thread is live again it
  SHEDS the in-flight batch — those requests finish in state "error"
  with their KV blocks reclaimed (`kv.evictions`), waiting requests are
  admitted and complete normally.  Shedding the stuck work instead of
  hanging the fleet is the serving analogue of the supervisor's
  SIGTERM-first restart.

Counters (monitor/counters.py "Serving" section): `serve.requests`
(completed; bytes = generated tokens), `serve.tokens`,
`serve.decode_steps` (bytes = active slots -> mean batch occupancy),
`serve.prefill_chunks` (bytes = prompt tokens prefetched),
`serve.ttft_ms` (µs in the bytes slot, the ckpt.stall_ms convention),
`serve.shed`, plus `kv.blocks_in_use` / `kv.evictions` from the cache.
Speculative decoding adds `serve.draft_tokens` (candidates proposed),
`serve.accepted_tokens` (drafts accepted AND emitted — the
acceptance-rate numerator; accepted/decode_steps is the extra
tokens/step speculation bought), and `kv.dequant_ms` (µs-in-bytes:
decode-family dispatch wall time against a QUANTIZED cache).

Prefix caching + pinned sessions (PR 19): admission aliases the
request's already-cached full prompt blocks (serving/kv_cache.py chain
hashes) so prefill starts at the first non-cached position, and a
request submitted with a `session_id` keeps its blocks resident after
finishing (`SessionPin`, TTL + pressure-released) so the conversation's
next turn re-prefills only its new tokens.  Both are table-entry
aliasing — the programs are untouched, which is what keeps greedy
output bitwise-identical with the cache on or off.  Counters:
`kv.prefix_hits`, `kv.prefix_hit_tokens`, `kv.cow_copies`,
`kv.session_pins`, `kv.prefix_evictions`.

Speculative decoding (`draft_len > 0`): each decode step becomes a
verify step — a host-side n-gram drafter proposes up to `draft_len`
candidates per slot from the request's own emitted tokens, the batched
`verify` program scores all draft_len+1 positions through the paged
cache in one dispatch, and the engine emits the longest matching
prefix plus the target's own next token.  Because verify samples with
the same position-keyed RNG rule as decode, output is token-identical
to the non-speculative engine at matched kv_dtype (and to `generate()`
at dense KV) — speculation changes WHEN tokens arrive, never WHICH.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, List, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from ..models.gpt import GPT
from ..monitor.counters import COUNTERS
from ..runtime.resilience import fault_point
from ..utils.logging import logger
from .kv_cache import PagedKVCache, TRASH_BLOCK
from .programs import ServeProgramBuilder, ServeSchedule
from .scheduler import (ADMISSION_POLICIES, ERROR, FINISHED, RUNNING,
                        Request, Scheduler)


@dataclasses.dataclass
class ServeConfig:
    """Serving knobs (validated at construction; see
    docs/tutorials/serving.md for sizing guidance)."""

    block_size: int = 16              # tokens per KV block
    num_blocks: int = 64              # pool size INCLUDING the trash block
    max_batch: int = 8                # decode slots
    prefill_chunk: int = 32           # prompt tokens per prefill call
    max_seq_len: Optional[int] = None  # per-request cap; default model's
    admission: str = "continuous"     # "continuous" | "static"
    max_prefill_chunks_per_step: int = 1
    quantized_weights: Any = False    # False | "int8" | "int4"
    kv_dtype: Any = None              # None (param dtype) | "bf16" |
    #                                   "int8" | "int4" | dtype-like
    draft_len: int = 0                # speculative candidates per step
    spec_ngram: int = 3               # suffix n-gram the drafter matches
    prefix_cache: bool = True         # block-level prefix sharing
    prefix_min_match_blocks: int = 1  # shortest chain worth aliasing
    session_ttl_s: float = 120.0      # pinned-session residency window

    def __post_init__(self):
        for name in ("block_size", "max_batch", "prefill_chunk",
                     "max_prefill_chunks_per_step"):
            if int(getattr(self, name)) < 1:
                raise ValueError(
                    f"serving {name} must be >= 1, got "
                    f"{getattr(self, name)}")
        if int(self.num_blocks) < 2:
            raise ValueError(
                f"serving num_blocks must be >= 2 (block 0 is reserved), "
                f"got {self.num_blocks}")
        if self.admission not in ADMISSION_POLICIES:
            raise ValueError(
                f"serving admission must be one of {ADMISSION_POLICIES}, "
                f"got {self.admission!r}")
        q = self.quantized_weights
        if q not in (False, None, "int8", "int4"):
            raise ValueError(
                f"serving quantized_weights must be False, 'int8' or "
                f"'int4', got {q!r}")
        if self.kv_dtype is not None:
            from .kv_cache import resolve_kv_dtype

            resolve_kv_dtype(self.kv_dtype)  # raises on typos, loudly
        if int(self.draft_len) < 0:
            raise ValueError(
                f"serving draft_len must be >= 0, got {self.draft_len}")
        if int(self.spec_ngram) < 1:
            raise ValueError(
                f"serving spec_ngram must be >= 1, got {self.spec_ngram}")
        if int(self.prefix_min_match_blocks) < 1:
            raise ValueError(
                f"serving prefix_min_match_blocks must be >= 1, got "
                f"{self.prefix_min_match_blocks}")
        if float(self.session_ttl_s) <= 0:
            raise ValueError(
                f"serving session_ttl_s must be > 0, got "
                f"{self.session_ttl_s}")

    @property
    def quant_mode(self) -> str:
        return self.quantized_weights if self.quantized_weights else "none"


@dataclasses.dataclass
class SessionPin:
    """One resident session: a finished request's KV blocks held by an
    extra reference so the next turn re-prefills only its new tokens.
    `tokens` is the full history (prompt + output) the pin's blocks
    encode; `cached_len` the rows actually written (the final emitted
    token's K/V never is — its row is recomputed by the next turn's
    prefill)."""

    sid: Any
    owner: Any                        # the kv allocator's owner key
    tokens: List[int]
    cached_len: int
    blocks: int
    expires: float


class ServeEngine:
    """Continuous-batching decode engine over a mesh-sharded paged KV
    cache.  Single engine thread drives `step()`; `submit()` is safe
    from any thread."""

    def __init__(self, model: GPT, params, config: Optional[ServeConfig]
                 = None, mesh_info=None, programs: Optional[dict] = None,
                 clock=time.monotonic):
        self.model = model
        self.config = config or ServeConfig()
        self.clock = clock
        cfg = model.config
        c = self.config
        self.max_seq_len = int(c.max_seq_len or cfg.max_seq_len)
        if self.max_seq_len > cfg.max_seq_len:
            raise ValueError(
                f"serving max_seq_len {self.max_seq_len} exceeds the "
                f"model's positional table ({cfg.max_seq_len})")
        table_width = -(-self.max_seq_len // c.block_size)
        if mesh_info is None:
            from ..comm.mesh import peek_mesh

            mesh_info = peek_mesh()
        self.mesh_info = mesh_info
        # the chain-hash salt: anything that changes K/V block CONTENT
        # for the same token ids must key the prefix cache (the kv
        # storage mode is folded in by the cache itself)
        prefix_salt = (f"{cfg.num_layers}|{cfg.num_heads}|{cfg.head_dim}|"
                       f"{cfg.vocab_size}|{cfg.max_seq_len}|{c.quant_mode}")
        self.kv = PagedKVCache(
            num_layers=cfg.num_layers, num_heads=cfg.num_heads,
            head_dim=cfg.head_dim, num_blocks=c.num_blocks,
            block_size=c.block_size, table_width=table_width,
            dtype=(cfg.param_dtype if c.kv_dtype is None else c.kv_dtype),
            mesh_info=mesh_info, prefix_cache=c.prefix_cache,
            min_match_blocks=c.prefix_min_match_blocks,
            prefix_salt=prefix_salt)
        self.scheduler = Scheduler(self.kv, c.max_batch,
                                   admission=c.admission, clock=clock,
                                   draft_len=int(c.draft_len))
        # resident sessions (sid -> SessionPin), insertion-ordered so
        # pressure release walks oldest-pinned first
        self._sessions: "dict[Any, SessionPin]" = {}
        if c.prefix_cache:
            self.scheduler.session_lookup = self._session_lookup
            self.scheduler.session_consumed = self._session_consumed
        schedule = ServeSchedule(
            max_batch=c.max_batch, prefill_chunk=c.prefill_chunk,
            block_size=c.block_size, num_blocks=c.num_blocks,
            table_width=table_width, quantized=c.quant_mode,
            kv_dtype=(self.kv.quant_wire or "dense"),
            draft_len=int(c.draft_len))
        if programs is None:
            programs = ServeProgramBuilder(model, schedule).build()
        elif programs["schedule"].program_key() != schedule.program_key():
            raise ValueError(
                f"prebuilt programs were compiled for "
                f"{programs['schedule'].describe()!r} but this engine "
                f"needs {schedule.describe()!r}")
        self.programs = programs
        self.params = programs["prepare_params"](
            self._place_params(params))
        logger.info(f"serving engine up: {schedule.describe()}; "
                    f"{self.kv.describe()}")
        # packed decode-batch state (one row per slot)
        R, W = c.max_batch, table_width
        self._tokens = np.zeros((R,), np.int32)
        self._positions = np.zeros((R,), np.int32)
        self._active = np.zeros((R,), bool)
        self._tables = np.full((R, W), TRASH_BLOCK, np.int32)
        self._temps = np.zeros((R,), np.float32)
        self._topks = np.zeros((R,), np.int32)
        self._seeds = np.zeros((R,), np.uint32)
        self.steps = 0
        self.peak_blocks_in_use = 0
        self.peak_resident = 0        # max concurrent block-holding reqs
        self._shed_reason: Optional[str] = None
        self._watchdog = None
        self._worker: Optional["ServeWorker"] = None
        self._wake = threading.Event()
        self._tracer = None               # monitor.tracing.TraceRecorder
        self._slo = None                  # monitor.tracing.ServingSLO

    # -- placement ----------------------------------------------------

    def _place_params(self, params):
        """Best-effort TP placement: when a mesh with model > 1 is in
        scope, put each leaf at its GPT param_spec so the programs run
        Megatron-sharded; otherwise leave leaves where they are."""
        info = self.mesh_info
        if info is None:
            return params
        from ..comm.mesh import MODEL_AXIS

        if info.axis_size(MODEL_AXIS) <= 1:
            return params
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        try:
            return jax.tree_util.tree_map(
                lambda leaf, spec: jax.device_put(
                    leaf, NamedSharding(info.mesh, spec)),
                params, self.model.param_specs,
                is_leaf=lambda x: hasattr(x, "ndim"))
        except Exception as e:
            logger.warning(
                f"serving TP param placement failed ({e}); weights stay "
                f"replicated")
            return params

    # -- submission ---------------------------------------------------

    def submit(self, prompt: Sequence[int], max_new_tokens: int,
               temperature: float = 0.0, top_k: int = 0, seed: int = 0,
               eos_token: Optional[int] = None,
               session_id: Optional[Any] = None) -> Request:
        prompt = [int(t) for t in prompt]
        if not prompt:
            raise ValueError("prompt must be non-empty")
        if int(max_new_tokens) < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}")
        if len(prompt) + int(max_new_tokens) > self.max_seq_len:
            raise ValueError(
                f"prompt {len(prompt)} + max_new {max_new_tokens} "
                f"exceeds the engine's max_seq_len {self.max_seq_len}")
        if int(top_k) < 0 or float(temperature) < 0.0:
            raise ValueError(
                f"top_k must be >= 0 and temperature >= 0, got "
                f"{top_k}, {temperature}")
        req = Request(prompt=prompt, max_new_tokens=int(max_new_tokens),
                      temperature=float(temperature), top_k=int(top_k),
                      seed=int(seed), eos_token=eos_token,
                      session_id=session_id)
        self.scheduler.submit(req)
        self._wake.set()
        return req

    # -- pinned sessions ----------------------------------------------

    @property
    def resident_sessions(self) -> int:
        return len(self._sessions)

    def session_active(self, sid) -> bool:
        """True while routing `sid` here still wins: a resident pin,
        or a live request carrying the session (whose natural finish
        will re-pin it).  Safe from any thread — the fleet router's
        affinity-staleness probe."""
        if sid in self._sessions:
            return True
        return self.scheduler.has_session(sid)

    def _session_lookup(self, req: Request):
        """Scheduler hook: the pin `req` can adopt, or None.  A pin is
        only served when its history is a PREFIX of the new prompt —
        anything else (edited history, expired TTL) releases the pin
        and falls back to chain-hash matching, which still catches the
        registered full blocks."""
        s = self._sessions.get(req.session_id)
        if s is None:
            return None
        n = len(s.tokens)
        if (s.expires <= self.clock() or n > len(req.prompt)
                or req.prompt[:n] != s.tokens):
            self.release_session(s.sid)
            return None
        return s

    def _session_consumed(self, req: Request, pin: SessionPin) -> None:
        """Scheduler hook: the pin's blocks now belong to `req`."""
        self._sessions.pop(pin.sid, None)

    def _pin_session(self, req: Request) -> None:
        """Keep a naturally-finished session request's blocks resident
        (one extra reference each) so turn k+1 re-prefills only its new
        tokens.  Called BEFORE scheduler.finish drops the request's own
        references — net effect: the blocks stay held by the pin."""
        sid = req.session_id
        old = self._sessions.pop(sid, None)
        if old is not None:
            self.kv.free(old.owner)
        owner = ("session", sid, req.rid)
        n = self.kv.pin(owner, req.rid)
        if not n:
            return
        self._sessions[sid] = SessionPin(
            sid=sid, owner=owner, tokens=req.prompt + req.out,
            cached_len=req.cached_len, blocks=n,
            expires=self.clock() + float(self.config.session_ttl_s))
        COUNTERS.add("kv.session_pins", nbytes=n)

    def release_session(self, sid) -> bool:
        """Drop a session's pin (its registered blocks stay matchable
        from the prefix LRU until evicted).  Returns True if held."""
        s = self._sessions.pop(sid, None)
        if s is None:
            return False
        self.kv.free(s.owner)
        return True

    def _expire_sessions(self) -> None:
        now = self.clock()
        for sid in [sid for sid, s in self._sessions.items()
                    if s.expires <= now]:
            self.release_session(sid)

    def _session_pressure_release(self) -> None:
        """KV-pressure valve: while admission is starving the queue
        head with a decode slot free (so the shortfall is blocks, not
        slots), release pinned sessions oldest-first and retry — a
        waiting request always outranks a resident session."""
        sch = self.scheduler
        while (sch.n_waiting and self._sessions
               and any(s is None for s in sch.slots)
               and not (sch.admission == "static"
                        and any(s is not None for s in sch.slots))):
            oldest = next(iter(self._sessions))
            self.release_session(oldest)
            if sch.admit():
                break

    # -- tracing / SLO telemetry --------------------------------------

    def attach_tracing(self, tracer=None, slo=None) -> None:
        """Attach a `monitor.tracing.TraceRecorder` and/or a
        `monitor.tracing.ServingSLO` aggregator.  The tracer records
        the per-request lifecycle (`queue_wait` at admission,
        `prefill_chunk` spans, a `first_token` instant, per-step
        `decode_step`/`verify_step` spans with batch occupancy and
        draft accept counts, `finish`/`shed` instants — all cat
        "serve"); request-scoped events are sampled per rid, step
        spans per engine step, so a loaded engine stays within the
        recorder's byte budget.  The SLO aggregator is fed UNSAMPLED
        (TTFT, tokens, queue depth, accept rate, sheds) and ticked at
        every step boundary so its windows never have sampling holes.
        When a watchdog is attached (before or after this call) the
        tracer's tail doubles as its trip-snapshot flight recorder."""
        self._tracer = tracer
        self._slo = slo
        self.scheduler.tracer = tracer
        if slo is not None and getattr(slo, "tracer", None) is None:
            slo.tracer = tracer
        if tracer is not None and self._watchdog is not None:
            self._watchdog.set_flight_recorder(tracer.last_events)

    def _req_tracer(self, req: Request):
        """The tracer, iff this request's rid is sampled in."""
        tr = self._tracer
        if tr is not None and tr.sampled(f"rid:{req.rid}"):
            return tr
        return None

    # -- shedding (watchdog escalation target) ------------------------

    def request_shed(self, reason: str = "watchdog trip") -> None:
        """Flag the in-flight batch for shedding; safe from any thread
        (the watchdog's on_trip handler).  Consumed at the next point
        the engine thread is live — the requests wedged in the stuck
        step finish in state 'error', everything waiting proceeds."""
        self._shed_reason = str(reason)

    def _check_shed(self) -> bool:
        reason = self._shed_reason
        if reason is None:
            return False
        self._shed_reason = None
        victims = self.scheduler.occupied()
        for req in victims:
            slot = req.slot
            self.scheduler.finish(req, ERROR, error=reason)
            if slot is not None:
                self._active[slot] = False
                self._tables[slot] = TRASH_BLOCK
        if victims:
            COUNTERS.add("serve.shed", calls=len(victims))
            if self._slo is not None:
                self._slo.observe_shed(len(victims))
            if self._tracer is not None:
                self._tracer.instant("shed", "serve", n=len(victims),
                                     reason=reason)
            logger.error(
                f"serving: SHED {len(victims)} in-flight request(s) "
                f"({reason}); {self.kv.blocks_in_use} blocks still held, "
                f"{self.scheduler.n_waiting} waiting proceed")
        return bool(victims)

    # -- the serving loop body ----------------------------------------

    def step(self) -> bool:
        """One engine iteration: admit -> prefill chunk round -> decode.
        Returns True when any work was done (callers idle otherwise)."""
        fault_point("serve.step")
        self._check_shed()
        if self._watchdog is not None:
            self._watchdog.beat(self.steps)
        fault_point("serve.admit")
        self._expire_sessions()
        self.scheduler.admit()
        self._session_pressure_release()
        if self._slo is not None:
            # depth AFTER admission = backlog the cache/slots could not
            # absorb this step, the saturation signal SLO windows want
            self._slo.observe_queue_depth(self.scheduler.n_waiting)
        did = False
        for req in self.scheduler.prefilling()[
                :self.config.max_prefill_chunks_per_step]:
            fault_point("serve.prefill")
            if self._check_shed():
                return True
            self._prefill_chunk(req)
            did = True
        running = self.scheduler.running()
        if running:
            fault_point("serve.decode")
            if self._check_shed():
                return True
            self._decode_step(running)
            did = True
        if did:
            self.steps += 1
            self.kv.sample_occupancy()
            self.peak_blocks_in_use = max(self.peak_blocks_in_use,
                                          self.kv.blocks_in_use)
            self.peak_resident = max(self.peak_resident,
                                     len(self.scheduler.occupied()))
            if self._slo is not None:
                self._slo.tick()
        return did

    def has_work(self) -> bool:
        return self.scheduler.has_work() or self._shed_reason is not None

    def run(self) -> None:
        """Drive step() until every submitted request is terminal."""
        while self.scheduler.has_work():
            self.step()

    def generate(self, prompts: Sequence[Sequence[int]],
                 max_new_tokens: int, temperature: float = 0.0,
                 top_k: int = 0, seeds: Optional[Sequence[int]] = None,
                 eos_token: Optional[int] = None) -> List[List[int]]:
        """Synchronous convenience: submit all, run to completion,
        return the token lists (raises if any request errored)."""
        reqs = [self.submit(p, max_new_tokens, temperature=temperature,
                            top_k=top_k,
                            seed=(seeds[i] if seeds is not None else 0),
                            eos_token=eos_token)
                for i, p in enumerate(prompts)]
        self.run()
        for r in reqs:
            if r.state == ERROR:
                raise RuntimeError(f"request {r.rid} failed: {r.error}")
        return [r.out for r in reqs]

    # -- phases --------------------------------------------------------

    def _prefill_chunk(self, req: Request) -> None:
        C = self.config.prefill_chunk
        chunk = req.prompt[req.prefill_pos:req.prefill_pos + C]
        n_valid = len(chunk)
        pos0 = req.prefill_pos
        tr = self._req_tracer(req)
        tus0 = tr.now_us() if tr is not None else 0
        tokens = np.zeros((1, C), np.int32)
        tokens[0, :n_valid] = chunk
        tok, _logits, caches = self.programs["prefill"](
            self.params, self.kv.caches, jnp.asarray(tokens),
            np.int32(req.prefill_pos), np.int32(n_valid),
            jnp.asarray(req.table), np.float32(req.temperature),
            np.int32(req.top_k), np.uint32(req.seed))
        self.kv.caches = caches
        req.prefill_pos += n_valid
        req.cached_len = req.prefill_pos
        COUNTERS.add("serve.prefill_chunks", nbytes=n_valid)
        if tr is not None:
            # cached/computed: the prefix-cache outcome per request —
            # how many prompt tokens this request never prefilled
            tr.add_complete("prefill_chunk", "serve", ts_us=tus0,
                            dur_us=tr.now_us() - tus0, rid=req.rid,
                            pos=pos0, n=n_valid,
                            cached=req.prefix_cached_tokens,
                            computed=(len(req.prompt)
                                      - req.prefix_cached_tokens))
        if req.prefill_pos < len(req.prompt):
            return
        # final chunk committed: publish the prompt's full blocks under
        # their chain hashes.  Pin-adopted requests carry NO hashes
        # (scheduler._try_alloc): their prefill attended over
        # decode-written rows, so nothing they wrote is safe to serve
        # to third parties.  `start` skips the already-registered
        # matched prefix.
        if req.block_hashes:
            start = -(-req.prefix_cached_tokens // self.kv.block_size)
            self.kv.register_prefix(req.rid, req.block_hashes, start)
        # the program sampled the request's FIRST token
        first = int(tok)
        now = self.clock()
        req.t_first_token = now
        req.token_times.append(now)
        req.out.append(first)
        COUNTERS.add("serve.tokens")
        COUNTERS.add("serve.ttft_ms", nbytes=int(req.ttft_s * 1e6))
        if self._slo is not None:
            self._slo.observe_ttft(req.ttft_s)
        if tr is not None:
            tr.instant("first_token", "serve", rid=req.rid,
                       ttft_ms=round(req.ttft_s * 1e3, 3))
        if self._is_finished(req, first):
            self._finish(req)
            return
        req.state = RUNNING
        slot = req.slot
        self._tokens[slot] = first
        # the first decode step writes this token's K/V at position P
        self._positions[slot] = len(req.prompt)
        self._active[slot] = True
        self._tables[slot] = req.table
        self._temps[slot] = req.temperature
        self._topks[slot] = req.top_k
        self._seeds[slot] = np.uint32(req.seed)

    def _decode_step(self, running: List[Request]) -> None:
        if int(self.config.draft_len) > 0:
            self._verify_step(running)
            return
        tr = self._step_tracer()
        tus0 = tr.now_us() if tr is not None else 0
        t0 = time.perf_counter()
        toks, caches = self.programs["decode"](
            self.params, self.kv.caches, jnp.asarray(self._tokens),
            jnp.asarray(self._positions), jnp.asarray(self._active),
            jnp.asarray(self._tables), jnp.asarray(self._temps),
            jnp.asarray(self._topks), jnp.asarray(self._seeds))
        self.kv.caches = caches
        toks = np.asarray(toks)
        self._record_dequant(t0)
        now = self.clock()
        COUNTERS.add("serve.decode_steps", nbytes=len(running))
        for req in running:
            slot = req.slot
            tok = int(toks[slot])
            req.out.append(tok)
            req.token_times.append(now)
            req.cached_len += 1
            COUNTERS.add("serve.tokens")
            if self._is_finished(req, tok):
                self._finish(req)
                self._active[slot] = False
                self._tables[slot] = TRASH_BLOCK
            else:
                self._tokens[slot] = tok
                self._positions[slot] += 1
        if self._slo is not None:
            self._slo.observe_tokens(len(running))
        if tr is not None:
            tr.add_complete("decode_step", "serve", ts_us=tus0,
                            dur_us=tr.now_us() - tus0, step=self.steps,
                            batch=len(running))

    def _step_tracer(self):
        """The tracer, iff this engine step's index is sampled in
        (decode/verify spans are per-step, not per-request)."""
        tr = self._tracer
        if tr is not None and tr.sampled(f"step:{self.steps}"):
            return tr
        return None

    def _record_dequant(self, t0: float) -> None:
        """`kv.dequant_ms` (µs-in-bytes): wall time of decode-family
        dispatches against a QUANTIZED cache — the in-program
        dequantize is XLA-fused into the attention gather, so the
        honest measurement is the whole dispatch; A/B against the
        dense-kv lane of the same bench isolates the dequant cost."""
        if self.kv.quant_wire:
            COUNTERS.add("kv.dequant_ms",
                         nbytes=int((time.perf_counter() - t0) * 1e6))

    # -- speculative decoding -----------------------------------------

    def _propose_draft(self, req: Request) -> List[int]:
        """Self-speculative n-gram draft, host-side, no extra model:
        find the most recent EARLIER occurrence of the request's last
        `spec_ngram` tokens in its own prompt + output and propose the
        continuation that followed it (falling back to repeating the
        last token).  Clamped so drafts never run past max_new_tokens
        or the request's ALLOCATED cache rows — the verify program
        writes candidate K/V at positions P+1..P+k, and every one of
        those rows must be backed by a real block."""
        c = self.config
        P = int(self._positions[req.slot])
        alloc_rows = len(self.kv.blocks_of(req.rid)) * self.kv.block_size
        k = min(int(c.draft_len),
                req.max_new_tokens - len(req.out) - 1,
                alloc_rows - 1 - P)
        if k <= 0:
            return []
        ctx = req.prompt + req.out
        n = min(int(c.spec_ngram), len(ctx))
        suffix = ctx[-n:]
        # Prefer the LATEST earlier occurrence whose continuation is a
        # full k tokens.  Once greedy output settles into a short cycle
        # (the common repetitive-suffix case), the nearest match sits
        # only cycle-length before the tail, so its continuation is
        # truncated by end-of-context and the draft collapses to ~1
        # token even at 100% acceptance.  An earlier full-window match
        # carries the same cycle with k tokens of runway.  If every
        # match is tail-truncated, keep the longest continuation seen.
        best: List[int] = []
        for j in range(len(ctx) - n - 1, -1, -1):
            if ctx[j:j + n] == suffix:
                d = ctx[j + n:j + n + k]
                if len(d) >= k:
                    return [int(t) for t in d]
                if len(d) > len(best):
                    best = [int(t) for t in d]
        if best:
            return best
        return [int(ctx[-1])] * k

    def _verify_step(self, running: List[Request]) -> None:
        """One speculative step for every running slot: propose up to
        draft_len candidates, score all draft_len+1 positions in ONE
        batched target forward, accept the longest matching prefix and
        emit the target's own sample as the bonus/correction token.

        Greedy pinning: verify samples every position with the same
        position-keyed RNG rule as sequential decode, so the emitted
        stream is token-identical to the non-speculative engine (and,
        at dense KV, to `generate()`) no matter how many drafts hit.
        Rollback is a host-side rewind: rejected rows' K/V stay stale
        in the cache but their positions are >= the rewound front, so
        they are re-written (same scatter rows) before any later
        query's causal mask can attend them — no scatter undo."""
        R = self.config.max_batch
        k = int(self.config.draft_len)
        tr = self._step_tracer()
        tus0 = tr.now_us() if tr is not None else 0
        drafts = np.zeros((R, k), np.int32)
        n_draft = np.zeros((R,), np.int32)
        for req in running:
            d = self._propose_draft(req)
            n_draft[req.slot] = len(d)
            if d:
                drafts[req.slot, :len(d)] = d
                COUNTERS.add("serve.draft_tokens", calls=len(d))
        tokens = np.concatenate([self._tokens[:, None], drafts], axis=1)
        t0 = time.perf_counter()
        toks, caches = self.programs["verify"](
            self.params, self.kv.caches, jnp.asarray(tokens),
            jnp.asarray(self._positions), jnp.asarray(n_draft),
            jnp.asarray(self._active), jnp.asarray(self._tables),
            jnp.asarray(self._temps), jnp.asarray(self._topks),
            jnp.asarray(self._seeds))
        self.kv.caches = caches
        toks = np.asarray(toks)                     # [R, draft_len + 1]
        self._record_dequant(t0)
        now = self.clock()
        COUNTERS.add("serve.decode_steps", nbytes=len(running))
        tot_emitted = 0
        tot_accepted = 0
        for req in running:
            slot = req.slot
            nd = int(n_draft[slot])
            # accept while draft i matches the target's sample for the
            # same position; the first sample past the matching prefix
            # is the bonus (nd == m) or correction (draft rejected)
            m = 0
            while m < nd and int(drafts[slot, m]) == int(toks[slot, m]):
                m += 1
            emitted = 0
            finished = False
            for i in range(m + 1):
                tok = int(toks[slot, i])
                req.out.append(tok)
                req.token_times.append(now)
                req.cached_len += 1
                emitted += 1
                COUNTERS.add("serve.tokens")
                if self._is_finished(req, tok):
                    finished = True
                    break
            if emitted > 1:
                # emitted - 1 DRAFT tokens were accepted and used (the
                # final emitted token is always the target's own)
                COUNTERS.add("serve.accepted_tokens", calls=emitted - 1)
                tot_accepted += emitted - 1
            tot_emitted += emitted
            if finished:
                self._finish(req)
                self._active[slot] = False
                self._tables[slot] = TRASH_BLOCK
            else:
                self._tokens[slot] = int(toks[slot, emitted - 1])
                self._positions[slot] += emitted
        if self._slo is not None:
            self._slo.observe_tokens(tot_emitted)
            self._slo.observe_accept(tot_accepted, int(n_draft.sum()))
        if tr is not None:
            tr.add_complete("verify_step", "serve", ts_us=tus0,
                            dur_us=tr.now_us() - tus0, step=self.steps,
                            batch=len(running),
                            drafted=int(n_draft.sum()),
                            accepted=tot_accepted)

    def _is_finished(self, req: Request, last_tok: int) -> bool:
        if req.eos_token is not None and last_tok == req.eos_token:
            return True
        return len(req.out) >= req.max_new_tokens

    def _finish(self, req: Request) -> None:
        COUNTERS.add("serve.requests", nbytes=len(req.out))
        tr = self._req_tracer(req)
        if tr is not None:
            tr.instant("finish", "serve", rid=req.rid,
                       tokens=len(req.out))
        if req.session_id is not None and self.config.prefix_cache:
            self._pin_session(req)
        self.scheduler.finish(req, FINISHED)

    # -- watchdog / worker integration ---------------------------------

    def attach_watchdog(self, watchdog) -> None:
        """Register with a runtime.resilience.StepWatchdog: the engine
        beats it at every step boundary and its serving worker thread
        (when one is attached) reports as the 'serving' thread group in
        trip snapshots.  Wire the watchdog's `on_trip` to
        `request_shed` to get shed-instead-of-hang behavior.

        Idle semantics: a ServeWorker beats the watchdog from its idle
        loop too (no traffic != wedged).  When driving step() yourself
        without a worker, either keep calling step()/beating during
        quiet periods or only arm the watchdog while work is in
        flight."""
        self._watchdog = watchdog
        if self._tracer is not None:
            watchdog.set_flight_recorder(self._tracer.last_events)
        watchdog.register_threads(
            "serving",
            lambda: [t for t in (self._worker,)
                     if t is not None and t.is_alive()])

    def close(self) -> None:
        for sid in list(self._sessions):
            self.release_session(sid)
        if self._worker is not None:
            self._worker.stop()
            self._worker = None
        if self._watchdog is not None:
            self._watchdog.unregister_threads("serving")
            self._watchdog = None


class ServeWorker(threading.Thread):
    """Daemon thread driving engine.step() while work is pending —
    what the bench (and a real frontend) runs so submission and
    decoding overlap.  Exceptions terminate every in-flight and
    waiting request loudly (state 'error'), never silently."""

    def __init__(self, engine: ServeEngine, idle_wait_s: float = 0.002):
        super().__init__(name="dstpu-serve-worker", daemon=True)
        self.engine = engine
        self.idle_wait_s = float(idle_wait_s)
        self._halt = threading.Event()
        self.error: Optional[BaseException] = None
        engine._worker = self

    def run(self) -> None:
        eng = self.engine
        try:
            while not self._halt.is_set():
                if eng.has_work():
                    eng.step()
                else:
                    # idle is not wedged: keep beating the watchdog so
                    # a quiet traffic period never trips it.  A truly
                    # wedged step blocks THIS thread inside step(), so
                    # the idle beat can never mask a real hang.
                    if eng._watchdog is not None:
                        eng._watchdog.beat(eng.steps)
                    eng._wake.wait(self.idle_wait_s)
                    eng._wake.clear()
        except BaseException as e:  # noqa: BLE001 — reported, not hidden
            self.error = e
            logger.error(f"serving worker died: {type(e).__name__}: {e}")
            eng.request_shed(f"serving worker died: {e}")
            for req in eng.scheduler.requests:
                if not req.done:
                    eng.scheduler.finish(req, ERROR,
                                         error=f"worker died: {e}")

    def stop(self, timeout: float = 10.0) -> None:
        self._halt.set()
        self.engine._wake.set()
        self.join(timeout=timeout)
        if self.error is not None:
            raise RuntimeError(
                f"serving worker failed: {self.error}") from self.error
