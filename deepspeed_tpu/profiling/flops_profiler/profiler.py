"""FLOPS profiler — XLA cost-analysis based.

Reference: deepspeed/profiling/flops_profiler/profiler.py monkey-patches
torch.nn.functional (:501-596) to count MACs per call and attaches per-
module duration hooks (:11-341). Neither is possible nor necessary under
XLA: the compiler already knows the FLOPs of the compiled program.

Design: lower + compile the step function once, read
`compiled.cost_analysis()` (flops / bytes accessed), and break the program
down by traversing the jaxpr — grouping matmul/conv/elementwise primitive
FLOPs by the user's `jax.named_scope`/function name stack, which plays the
role of the reference's module tree. Duration comes from timing the jitted
call (block_until_ready), utilization from flops/duration vs the chip peak.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ...utils.logging import log_dist, logger

# per-chip peak bf16 FLOPS for utilization reporting (public figures);
# host CPU fallback uses 0 -> utilization omitted
_PEAK_FLOPS = {
    "TPU v4": 275e12, "TPU v5 lite": 197e12, "TPU v5e": 197e12,
    "TPU v5p": 459e12, "TPU v6 lite": 918e12, "TPU v6e": 918e12,
}


def _device_peak_flops() -> float:
    try:
        kind = jax.local_devices()[0].device_kind
    except Exception:
        return 0.0
    for name, peak in _PEAK_FLOPS.items():
        if name.lower() in kind.lower():
            return peak
    return 0.0


def _count_params(params) -> int:
    return sum(int(np.prod(l.shape))
               for l in jax.tree_util.tree_leaves(params)
               if hasattr(l, "shape"))


# ---------------------------------------------------------------------------
# jaxpr walk: FLOPs by primitive and by name-stack scope
# ---------------------------------------------------------------------------

def _prim_flops(eqn) -> int:
    """Analytic FLOPs for the hot primitives (dot_general dominates; the
    reference similarly counts only F.linear/conv/attention MACs)."""
    prim = eqn.primitive.name
    try:
        if prim == "dot_general":
            dnums = eqn.params["dimension_numbers"]
            (lc, rc), (lb, rb) = dnums
            lhs = eqn.invars[0].aval
            rhs = eqn.invars[1].aval
            out = eqn.outvars[0].aval
            k = int(np.prod([lhs.shape[i] for i in lc])) or 1
            return 2 * int(np.prod(out.shape)) * k
        if prim in ("conv_general_dilated",):
            out = eqn.outvars[0].aval
            rhs = eqn.invars[1].aval
            return 2 * int(np.prod(out.shape)) * int(np.prod(rhs.shape[:-1]))
        if prim in ("add", "mul", "sub", "div", "max", "min", "exp", "log",
                    "tanh", "logistic", "rsqrt", "erf"):
            return int(np.prod(eqn.outvars[0].aval.shape))
        if prim == "reduce_sum" or prim.startswith("reduce_"):
            return int(np.prod(eqn.invars[0].aval.shape))
    except Exception:
        return 0
    return 0


def _walk_jaxpr(jaxpr, scope: Tuple[str, ...], by_scope, by_prim,
                mult: int = 1):
    for eqn in jaxpr.eqns:
        # descend into sub-jaxprs (pjit/remat/scan/cond carry inner jaxprs)
        inner = [v for k, v in eqn.params.items()
                 if k in ("jaxpr", "call_jaxpr", "cond_jaxpr", "body_jaxpr")]
        name = eqn.params.get("name")
        sub_scope = scope + ((name,) if isinstance(name, str) else ())
        if inner:
            sub_mult = mult
            if eqn.primitive.name == "scan":
                sub_mult = mult * int(eqn.params.get("length", 1))
            for sj in inner:
                _walk_jaxpr(getattr(sj, "jaxpr", sj), sub_scope, by_scope,
                            by_prim, sub_mult)
            continue
        branches = eqn.params.get("branches")
        if branches:
            # cond: one branch executes; count only the largest branch
            best_scope, best_prim, best_total = {}, {}, -1
            for br in branches:
                bs: Dict[str, int] = {}
                bp: Dict[str, int] = {}
                _walk_jaxpr(getattr(br, "jaxpr", br), sub_scope, bs, bp, mult)
                total = sum(bp.values())
                if total > best_total:
                    best_scope, best_prim, best_total = bs, bp, total
            for k, v in best_scope.items():
                by_scope[k] = by_scope.get(k, 0) + v
            for k, v in best_prim.items():
                by_prim[k] = by_prim.get(k, 0) + v
            continue
        f = _prim_flops(eqn)
        if f:
            f *= mult
            key = "/".join(scope) or "<top>"
            by_scope[key] = by_scope.get(key, 0) + f
            p = eqn.primitive.name
            by_prim[p] = by_prim.get(p, 0) + f


def analyze_fn(fn: Callable, *args) -> Dict[str, Any]:
    """Static analysis of `fn(*args)`: total flops (XLA cost analysis when
    available, jaxpr estimate otherwise) + per-primitive breakdown."""
    closed = jax.make_jaxpr(fn)(*args)
    by_scope: Dict[str, int] = {}
    by_prim: Dict[str, int] = {}
    _walk_jaxpr(closed.jaxpr, (), by_scope, by_prim)
    est = sum(by_prim.values())

    xla_flops = None
    try:
        # a jitted fn lowers AOT against its own cache (no second
        # compilation mid-training); plain fns get a throwaway jit
        lowered = (fn.lower(*args) if hasattr(fn, "lower")
                   else jax.jit(fn).lower(*args))
        cost = lowered.compile().cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        if cost and "flops" in cost:
            xla_flops = float(cost["flops"])
    except Exception as e:  # pragma: no cover
        logger.debug(f"cost_analysis unavailable: {e}")
    return {
        "flops": xla_flops if xla_flops else float(est),
        "flops_estimated": float(est),
        "by_primitive": by_prim,
        "by_scope": by_scope,
    }


# ---------------------------------------------------------------------------


class FlopsProfiler:
    """API parity with reference profiler.py:11-341.

    Usage (also driven by the engine at flops_profiler.profile_step):
        prof = FlopsProfiler()
        prof.start_profile()
        out = step_fn(...)          # any jitted callables
        prof.stop_profile(step_fn, args, params=engine.params)
        prof.print_model_profile()
    """

    def __init__(self, model=None, config=None):
        self.model = model
        self.config = config
        self.started = False
        self.stats: Dict[str, Any] = {}

    def start_profile(self, ignore_list=None):
        self.started = True
        self._t0 = time.time()

    def stop_profile(self, fn: Optional[Callable] = None, args: Tuple = (),
                     params=None, sync=None):
        if not self.started:
            return
        if sync is not None:  # async dispatch: block before reading the clock
            jax.block_until_ready(sync)
        self.duration = time.time() - self._t0
        if fn is not None:
            self.stats = analyze_fn(fn, *args)
        if params is not None:
            self.stats["params"] = _count_params(params)
        self.started = False

    def end_profile(self):
        self.stats = {}

    # accessors (reference get_total_* :220-260)
    def get_total_flops(self, as_string=False):
        f = self.stats.get("flops", 0.0)
        return number_to_string(f, "FLOPs") if as_string else f

    def get_total_params(self, as_string=False):
        p = self.stats.get("params", 0)
        return number_to_string(p, "params") if as_string else p

    def get_total_duration(self, as_string=False):
        d = getattr(self, "duration", 0.0)
        return f"{d * 1000:.2f} ms" if as_string else d

    def print_model_profile(self, profile_step=None, module_depth=-1,
                            top_modules=3, detailed=True, output_file=None):
        lines = ["", "-" * 26 + " flops profiler " + "-" * 26]
        if profile_step is not None:
            lines.append(f"profile step:                   {profile_step}")
        if "params" in self.stats:
            lines.append(f"params:                         "
                         f"{number_to_string(self.stats['params'], '')}")
        lines.append(f"fwd+bwd flops per step:         "
                     f"{number_to_string(self.stats.get('flops', 0), 'FLOPs')}")
        dur = getattr(self, "duration", 0.0)
        if dur > 0:
            lines.append(f"step latency:                   {dur*1000:.2f} ms")
            achieved = self.stats.get("flops", 0) / dur
            lines.append(f"achieved:                       "
                         f"{number_to_string(achieved, 'FLOPS')}")
            peak = _device_peak_flops()
            if peak:
                lines.append(f"utilization (bf16 peak):        "
                             f"{100.0 * achieved / peak:.1f} %")
        if detailed and self.stats.get("by_primitive"):
            lines.append("flops by primitive:")
            total = max(sum(self.stats["by_primitive"].values()), 1)
            for prim, f in sorted(self.stats["by_primitive"].items(),
                                  key=lambda kv: -kv[1])[:max(top_modules, 3)]:
                lines.append(f"  {prim:<28} {number_to_string(f, ''):>10} "
                             f"({100.0 * f / total:.1f}%)")
        if detailed and self.stats.get("by_scope"):
            scopes = {k: v for k, v in self.stats["by_scope"].items()}
            if len(scopes) > 1:
                lines.append("flops by scope:")
                for scope, f in sorted(scopes.items(),
                                       key=lambda kv: -kv[1])[:top_modules]:
                    lines.append(f"  {scope:<28} {number_to_string(f, ''):>10}")
        lines.append("-" * 68)
        text = "\n".join(lines)
        if output_file:
            with open(output_file, "w") as fh:
                fh.write(text)
        log_dist(text, ranks=[0])
        return text


def number_to_string(num, unit="") -> str:
    num = float(num)
    for mag, suffix in ((1e12, "T"), (1e9, "G"), (1e6, "M"), (1e3, "K")):
        if abs(num) >= mag:
            return f"{num / mag:.2f} {suffix}{unit}"
    return f"{num:.2f} {unit}".rstrip()


def get_model_profile(model, batch, rng=None, as_string=False):
    """One-call profile (reference profiler.py:599-685 get_model_profile):
    returns (flops, macs, params) for model.loss on `batch`."""
    params = model.init(rng if rng is not None else jax.random.PRNGKey(0))

    def fn(p, b):
        out = model.loss(p, b, train=False)
        return out[0] if isinstance(out, tuple) else out

    stats = analyze_fn(fn, params, batch)
    flops = stats["flops"]
    macs = flops / 2.0
    nparams = _count_params(params)
    if as_string:
        return (number_to_string(flops, "FLOPs"),
                number_to_string(macs, "MACs"),
                number_to_string(nparams, "params"))
    return flops, macs, nparams
