from .profiler import (FlopsProfiler, analyze_fn, get_model_profile,
                       number_to_string)

__all__ = ["FlopsProfiler", "analyze_fn", "get_model_profile",
           "number_to_string"]
