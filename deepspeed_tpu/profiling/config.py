"""FLOPS profiler config (reference: deepspeed/profiling/config.py)."""

from ..runtime.config_utils import DeepSpeedConfigObject, get_scalar_param

FLOPS_PROFILER = "flops_profiler"
FLOPS_PROFILER_ENABLED = "enabled"
FLOPS_PROFILER_PROFILE_STEP = "profile_step"
FLOPS_PROFILER_MODULE_DEPTH = "module_depth"
FLOPS_PROFILER_TOP_MODULES = "top_modules"
FLOPS_PROFILER_DETAILED = "detailed"


class DeepSpeedFlopsProfilerConfig(DeepSpeedConfigObject):
    def __init__(self, param_dict):
        super().__init__()
        d = param_dict.get(FLOPS_PROFILER, {}) or {}
        self.enabled = get_scalar_param(d, FLOPS_PROFILER_ENABLED, False)
        self.profile_step = get_scalar_param(d, FLOPS_PROFILER_PROFILE_STEP, 1)
        self.module_depth = get_scalar_param(d, FLOPS_PROFILER_MODULE_DEPTH, -1)
        self.top_modules = get_scalar_param(d, FLOPS_PROFILER_TOP_MODULES, 3)
        self.detailed = get_scalar_param(d, FLOPS_PROFILER_DETAILED, True)
