"""deepspeed_tpu — TPU-native training framework with DeepSpeed capabilities.

API facade mirroring /root/reference/deepspeed/__init__.py: the product is
`initialize()` (returns an engine wrapping the user model) plus a launcher,
re-designed for JAX/XLA: parallelism is a `jax.sharding.Mesh`, ZeRO stages
are sharding specs, kernels are Pallas/XLA.
"""

from . import _compat  # noqa: F401  (jax.shard_map shim — must run first)
from .version import __version__, git_hash  # noqa: F401
from . import comm  # noqa: F401
from . import module_inject  # noqa: F401
from . import ops  # noqa: F401
from .comm import init_distributed  # noqa: F401
from .runtime.activation_checkpointing import checkpointing  # noqa: F401
from .runtime import zero  # noqa: F401
# top-level names a reference user reaches for (reference __init__.py:7-23)
from .runtime.engine import DeepSpeedEngine  # noqa: F401
from .runtime.pipe.engine import PipelineEngine  # noqa: F401
from .runtime.pipe.module import (PipelineModule, LayerSpec,  # noqa: F401
                                  TiedLayerSpec)
from . import pipe  # noqa: F401  (the deepspeed.pipe parity package —
#                    NOT runtime.pipe, which would shadow it)
from .runtime.lr_schedules import add_tuning_arguments  # noqa: F401
from .runtime.config import DeepSpeedConfig, DeepSpeedConfigError  # noqa: F401
from .runtime.constants import (ADAM_OPTIMIZER,  # noqa: F401
                                LAMB_OPTIMIZER)
from .ops.transformer import (DeepSpeedTransformerLayer,  # noqa: F401
                              DeepSpeedTransformerConfig)
from .utils.logging import log_dist  # noqa: F401

version = __version__
__git_hash__ = git_hash
__git_branch__ = "main"


def initialize(args=None,
               model=None,
               optimizer=None,
               model_parameters=None,
               training_data=None,
               lr_scheduler=None,
               mpu=None,
               dist_init_required=None,
               collate_fn=None,
               config=None,
               config_params=None):
    """Initialize the training engine (reference: deepspeed/__init__.py:52-145).

    Returns a tuple of ``(engine, optimizer, training_dataloader, lr_scheduler)``.
    """
    from .runtime.engine import DeepSpeedEngine
    from .runtime.pipe.module import PipelineModule

    if config is None and config_params is not None:
        config = config_params
    if config is None and args is not None:
        config = getattr(args, "deepspeed_config", None)

    if isinstance(model, PipelineModule):
        from .runtime.pipe.engine import PipelineEngine

        engine = PipelineEngine(args=args,
                                model=model,
                                optimizer=optimizer,
                                model_parameters=model_parameters,
                                training_data=training_data,
                                lr_scheduler=lr_scheduler,
                                mpu=model.mpu() if mpu is None else mpu,
                                dist_init_required=dist_init_required,
                                collate_fn=collate_fn,
                                config_params=config)
    else:
        engine = DeepSpeedEngine(args=args,
                                 model=model,
                                 optimizer=optimizer,
                                 model_parameters=model_parameters,
                                 training_data=training_data,
                                 lr_scheduler=lr_scheduler,
                                 mpu=mpu,
                                 dist_init_required=dist_init_required,
                                 collate_fn=collate_fn,
                                 config_params=config)

    return (engine, engine.optimizer, engine.training_dataloader,
            engine.lr_scheduler)


def add_config_arguments(parser):
    """Add --deepspeed / --deepspeed_config args (reference __init__.py:148-212)."""
    group = parser.add_argument_group("DeepSpeed", "DeepSpeed configurations")
    group.add_argument("--deepspeed", default=False, action="store_true",
                       help="Enable DeepSpeed (helper flag, parity only)")
    group.add_argument("--deepspeed_config", default=None, type=str,
                       help="Path to the deepspeed json configuration file")
    group.add_argument("--deepscale", default=False, action="store_true",
                       help=argparse_suppress())
    group.add_argument("--deepscale_config", default=None, type=str,
                       help=argparse_suppress())
    return parser


def argparse_suppress():
    import argparse

    return argparse.SUPPRESS
