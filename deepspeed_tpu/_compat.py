"""JAX version compatibility shims.

The codebase targets the current public API surface (`jax.shard_map`
with `check_vma=`); on older jax (≤0.4.x) that entry point lives at
`jax.experimental.shard_map.shard_map` and the replication-check kwarg
is named `check_rep`.  Installing the shim at package import keeps every
call site written against the modern spelling (same policy as the
`pltpu.CompilerParams`/`TPUCompilerParams` fallback in
ops/transformer/flash_attention.py).
"""

from __future__ import annotations

import functools
import inspect


def install_shard_map() -> None:
    """Make `jax.shard_map(f, mesh=..., in_specs=..., out_specs=...,
    check_vma=...)` work on every supported jax version.  Idempotent;
    no-op when the public API already accepts `check_vma`."""
    import jax

    target = getattr(jax, "shard_map", None)
    if target is None:
        from jax.experimental.shard_map import shard_map as target
    try:
        params = inspect.signature(target).parameters
    except (TypeError, ValueError):  # C-accelerated or wrapped: assume new
        return
    if "check_vma" in params:
        if getattr(jax, "shard_map", None) is not target:
            jax.shard_map = target
        return
    translate = "check_rep" in params
    has_axis_names = "axis_names" in params

    @functools.wraps(target)
    def shard_map(f, *args, **kwargs):
        if "check_vma" in kwargs:
            v = kwargs.pop("check_vma")
            if translate:
                kwargs["check_rep"] = v
        elif translate:
            # bodies are written for the new varying-type system (pcast
            # below is an identity here), which the legacy replication
            # checker cannot follow — it is a static checker only, so
            # disabling it does not change numerics
            kwargs.setdefault("check_rep", False)
        # axis_names declares the manual subset; the complement stays
        # automatic.  Old jax spells that `auto=<complement>`, but its
        # partial-auto lowering hard-crashes XLA:CPU SPMD (PartitionId /
        # IsManualSubgroup check), so we run FULL manual instead: the
        # body never references non-manual axes (the new API enforces
        # that), so the in/out specs — which do not mention them —
        # all-gather those axes at entry and the body computes the same
        # global function, just replicated across the would-be-auto
        # groups.  Identical numerics; redundant compute on legacy jax
        # only.
        if not has_axis_names:
            kwargs.pop("axis_names", None)
        return target(f, *args, **kwargs)

    jax.shard_map = shard_map


def install_pcast() -> None:
    """`jax.lax.pcast(x, axes, to=...)` adjusts the manual-mode varying
    TYPE of a value — a static annotation for the new vma checker with no
    runtime semantics.  Old jax has neither the primitive nor the checker
    (install_shard_map disables the legacy rep checker), so the identity
    is the faithful shim."""
    import jax

    if hasattr(jax.lax, "pcast"):
        return

    def pcast(x, axis_names=(), *, to=None):
        return x

    jax.lax.pcast = pcast


def install_axis_size() -> None:
    """`jax.lax.axis_size(name)` is spelled `psum(1, name)` on old jax —
    a Python-constant reduction the tracer folds to a concrete int, so
    callers building static ppermute rings keep working."""
    import jax

    if hasattr(jax.lax, "axis_size"):
        return

    def axis_size(axis_name):
        return jax.lax.psum(1, axis_name)

    jax.lax.axis_size = axis_size


def install_cpu_collectives() -> None:
    """Multi-process CPU meshes need a cross-process collectives backend.
    New jax selects gloo automatically; old jax defaults to "none", and
    then EVERY multiprocess computation — including the consistency
    check device_put runs when placing a host value onto a global
    sharding — dies with "Multiprocess computations aren't implemented
    on the CPU backend".  Select gloo before the CPU client is created.
    Gated on a live distributed client: single-process runs keep the
    stock client.  Called at package import and again from
    comm.init_distributed (whichever runs after jax.distributed comes up
    wins; the update is a no-op once the backend is live)."""
    try:
        from jax._src import distributed

        if distributed.global_state.client is None:
            return
        from jax._src import xla_bridge as xb

        flag = getattr(xb, "CPU_COLLECTIVES_IMPLEMENTATION", None)
        if flag is not None and flag.value == "none":
            flag._set("gloo")  # a Flag, not a config State: no
            #                    jax.config.update entry point exists
            # this jaxlib's gloo tcp transport aborts when two
            # differently-sized in-flight transfers interleave on one
            # pair ("op.preamble.length <= op.nbytes"); serializing CPU
            # dispatch keeps at most one collective in flight
            adflag = getattr(xb, "_CPU_ENABLE_ASYNC_DISPATCH", None)
            if adflag is not None and adflag.value:
                adflag._set(False)
            # a mere jax.process_count()/device_count() before this shim
            # ran already instantiated the CPU client WITH "none"
            # collectives — the flag flip can't retrofit a live client
            # (and rebuilding one re-publishes its local topology to the
            # coordination service, which rejects the duplicate key), so
            # every multiprocess computation will die with "Multiprocess
            # computations aren't implemented on the CPU backend".  Warn
            # with the fix instead of leaving the user to decode that.
            if "cpu" in (getattr(xb, "_backends", None) or {}):
                import warnings

                warnings.warn(
                    "deepspeed_tpu: the CPU backend was created before the "
                    "gloo collectives flag could be set — multiprocess CPU "
                    "collectives WILL fail.  Import deepspeed_tpu (or call "
                    "deepspeed_tpu.comm.init_distributed) immediately after "
                    "jax.distributed.initialize, before any "
                    "jax.device_count()/process_count() call.")
    except (ImportError, AttributeError):  # new jax: gloo is the default
        pass


def install_no_device_put_assert_equal() -> None:
    """Old jax guards device_put(host_value, global_sharding) with
    multihost_utils.assert_equal — a cross-process broadcast of the
    value.  New jax performs no such check (the caller owns the
    same-value-everywhere contract, as this codebase does for its
    replicated param/batch placements), and on 4+ CPU processes the
    check itself aborts inside gloo's tcp transport (preamble.length
    mismatch, a C++ crash no except can catch).  Align old jax with the
    new contract for THAT call path only: assert_equal stays fully
    functional for direct users; the skip applies solely when the caller
    is jax's own dispatch module.  Only installed alongside the other
    legacy shims (new jax never calls it from device_put)."""
    import sys

    import jax

    if hasattr(jax, "shard_map") and not hasattr(
            jax.shard_map, "__wrapped__"):
        return  # new jax: public shard_map, no dispatch-time check
    from jax.experimental import multihost_utils

    orig = multihost_utils.assert_equal

    def assert_equal(in_tree, fail_message: str = ""):
        caller = sys._getframe(1).f_globals.get("__name__", "")
        if caller == "jax._src.dispatch":
            return None
        return orig(in_tree, fail_message)

    multihost_utils.assert_equal = assert_equal


install_shard_map()
install_pcast()
install_axis_size()
install_cpu_collectives()
install_no_device_put_assert_equal()
