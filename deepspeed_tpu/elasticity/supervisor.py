"""Restart supervisor — keep an elastic training job alive across
failures.

Capability beyond the reference (SURVEY.md §5: v0.3.15 has no in-run
failure detector or rendezvous — its recovery story is "the launcher
kills the local group on any child failure" + elastic checkpoints that
resume at a different world size). This supervisor closes the loop: it
runs the training command, and when the command dies it relaunches it
with exponential backoff, relying on the framework's elastic
checkpoints ("latest" tag) for the resumed process to pick up where it
left off — at whatever world size the new launch discovers.

Usage (also `ds_elastic supervise -- ...`):

    python -m deepspeed_tpu.elasticity.supervisor \
        [--max-restarts 10] [--backoff 5] [--success-window 300] \
        -- deepspeed --hostfile hostfile train.py --deepspeed_config c.json

Exit code: 0 if the command eventually succeeds; once restarts are
exhausted, the last child exit code (signal-killed children map to the
conventional 128+signum); 128+signum when the supervisor itself is
stopped by SIGINT/SIGTERM (operator signals stop the loop, they are
never retried).
"""

from __future__ import annotations

import argparse
import signal
import subprocess
import sys
import time

from ..utils.logging import logger


def supervise(command, max_restarts: int = 10, backoff: float = 5.0,
              backoff_cap: float = 300.0, success_window: float = 300.0):
    """Run `command` (list) until it exits 0 or restarts are exhausted.

    A child that stays alive longer than `success_window` seconds resets
    the restart budget and the backoff (long-running training that dies
    after hours should get its full retry budget back, not inherit the
    count from startup flakes)."""
    restarts_left = max_restarts
    delay = backoff
    attempt = 0
    child = None
    stop_signal = None

    def forward(signum, _frame):
        # an operator/scheduler signal means STOP, not "restart harder":
        # remember it so the loop exits instead of relaunching
        nonlocal stop_signal
        stop_signal = signum
        if child is not None and child.poll() is None:
            child.send_signal(signum)

    def to_exit_code(rc):
        # negative Popen rc (signal-killed child) -> conventional
        # 128+signum so sys.exit doesn't wrap it mod 256 into noise
        return 128 - rc if rc < 0 else rc

    def interruptible_sleep(seconds):
        # PEP 475 restarts time.sleep after a handled signal — sleep in
        # slices so a stop signal ends the backoff promptly
        end = time.monotonic() + seconds
        while stop_signal is None:
            left = end - time.monotonic()
            if left <= 0:
                return
            time.sleep(min(left, 0.5))

    old_int = signal.signal(signal.SIGINT, forward)
    old_term = signal.signal(signal.SIGTERM, forward)
    try:
        while True:
            if stop_signal is not None:  # landed before (re)launch
                logger.info(f"supervisor: stopping on signal {stop_signal}")
                return 128 + int(stop_signal)
            attempt += 1
            start = time.monotonic()
            logger.info(f"supervisor: launching attempt {attempt}: "
                        f"{' '.join(command)}")
            child = subprocess.Popen(command)
            if stop_signal is not None:
                # raced the launch: the handler saw the OLD child; pass
                # the stop on to the one we just started
                child.send_signal(stop_signal)
            rc = child.wait()
            ran_for = time.monotonic() - start
            if rc == 0:
                logger.info(f"supervisor: command succeeded after "
                            f"{attempt} attempt(s)")
                return 0
            if stop_signal is not None:
                logger.info(f"supervisor: stopping on signal "
                            f"{stop_signal} (child exit {rc})")
                return 128 + int(stop_signal)
            if ran_for >= success_window:
                restarts_left = max_restarts
                delay = backoff
            if restarts_left <= 0:
                logger.error(f"supervisor: giving up after {attempt} "
                             f"attempt(s); last exit code {rc}")
                return to_exit_code(rc)
            restarts_left -= 1
            logger.warning(
                f"supervisor: exit code {rc} after {ran_for:.1f}s; "
                f"relaunching in {delay:.1f}s "
                f"({restarts_left} restart(s) left)")
            interruptible_sleep(delay)
            if stop_signal is not None:  # signal arrived during backoff
                logger.info(f"supervisor: stopping on signal {stop_signal}")
                return 128 + int(stop_signal)
            delay = min(delay * 2, backoff_cap)
    finally:
        signal.signal(signal.SIGINT, old_int)
        signal.signal(signal.SIGTERM, old_term)


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="restart supervisor for elastic training jobs")
    parser.add_argument("--max-restarts", type=int, default=10)
    parser.add_argument("--backoff", type=float, default=5.0,
                        help="initial relaunch delay (doubles per failure)")
    parser.add_argument("--backoff-cap", type=float, default=300.0)
    parser.add_argument("--success-window", type=float, default=300.0,
                        help="children alive this long reset the budget")
    parser.add_argument("command", nargs=argparse.REMAINDER,
                        help="-- training command")
    args = parser.parse_args(argv)
    command = args.command
    if command and command[0] == "--":
        command = command[1:]
    if not command:
        parser.error("no command given (use: supervisor [opts] -- cmd ...)")
    return supervise(command, max_restarts=args.max_restarts,
                     backoff=args.backoff, backoff_cap=args.backoff_cap,
                     success_window=args.success_window)


if __name__ == "__main__":
    sys.exit(main())
