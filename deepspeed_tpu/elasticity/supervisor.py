"""Restart supervisor — keep an elastic training job alive across
failures.

Capability beyond the reference (SURVEY.md §5: v0.3.15 has no in-run
failure detector or rendezvous — its recovery story is "the launcher
kills the local group on any child failure" + elastic checkpoints that
resume at a different world size). This supervisor closes the loop in
two ways:

* **exit-driven**: when the training command dies it is relaunched
  with exponential backoff + jitter under a restart budget (at most
  `max_restarts` failures per rolling `restart_window` seconds, then
  give up with the child's nonzero exit code) — `RestartPolicy` is the
  unit-testable state machine.
* **heartbeat-driven**: with `--monitor-dir` pointing at a RunMonitor
  run directory (docs/tutorials/monitoring.md), `HeartbeatWatcher`
  tails the per-rank event streams.  A run that stops writing events
  for `--stall-timeout` seconds (hung collective, dead coordinator) or
  a rank flagged straggler in `--straggler-strikes` consecutive
  heartbeats triggers a SUPERVISED restart even though the process is
  still "alive": the child gets SIGTERM first (save-if-possible — the
  checkpoint layer's two-phase commit means an interrupted save can
  never corrupt the resume point), then SIGKILL after `--grace`
  seconds, and the relaunch carries `DSTPU_ELASTIC_RESTART=1`,
  `DSTPU_ELASTIC_REASON`, and — when the trigger identifies dead or
  straggling ranks — `DSTPU_DEAD_RANKS` / `DSTPU_SURVIVING_WORLD`, so
  the launcher can re-form the job at the surviving world size and the
  framework's elastic checkpoints ("latest" committed tag) resume it
  there.

With `--elastic-shrink` the env handoff becomes POLICY, not just
advice (`plan_world_transition`): a trigger naming dead ranks (per-rank
stream forensics on a stall, straggler strikes, or a launcher-written
`elastic_report.json`) relaunches on the survivors at the shrunken
world size — never below `--min-world` — and the engine reboots there
through resharding-on-restore (runtime/engine.py consumes the env via
elasticity/elastic_env.py); a later restart with no dead ranks grows
back to the full width.  Every relaunch exports `DSTPU_INCARNATION`,
which namespaces the entire coordination-service KV surface
(runtime/comm/hostwire.scoped_key) so a survivor generation never
consumes a dead generation's write-once keys.

Beside the env handoff, every restart decision is appended to
`restarts.jsonl` in the monitor dir (reason, dead ranks, backoff
chosen, watchdog diagnostics path if any), and the in-process
StepWatchdog's `watchdog_trip.json` escalation (runtime/resilience.py)
is polled as a third trigger — a hung step inside a still-"alive"
process restarts promptly with its diagnostic snapshot linked from the
ledger.

Usage (also `ds_elastic supervise -- ...`):

    python -m deepspeed_tpu.elasticity.supervisor \
        [--max-restarts 10] [--backoff 5] [--restart-window 3600] \
        [--monitor-dir runs/myjob] [--stall-timeout 600] \
        -- deepspeed --hostfile hostfile train.py --deepspeed_config c.json

Exit code: 0 if the command eventually succeeds; once the restart
budget is exhausted, the last child exit code (signal-killed children
map to the conventional 128+signum); 128+signum when the supervisor
itself is stopped by SIGINT/SIGTERM (operator signals stop the loop,
they are never retried).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import random
import signal
import subprocess
import sys
import time
from collections import deque
from typing import Dict, List, Optional

from ..runtime.resilience import WATCHDOG_TRIP_FILE, read_watchdog_trip
from ..utils.logging import logger
from .elastic_env import (DEAD_RANKS_ENV, ELASTIC_ENV_VARS,
                          ELASTIC_REASON_ENV, ELASTIC_RESTART_ENV,
                          INCARNATION_ENV, SURVIVING_WORLD_ENV)

RESTART_LEDGER = "restarts.jsonl"

# Dead-rank report a LAUNCHER leaves beside the monitor streams when it
# can identify the victim itself (it spawned the workers, so a worker
# exit names the rank precisely — no heartbeat forensics needed):
# {"dead_ranks": [1], "reason": "..."}.  `supervise()` consumes (and
# deletes) it after a child failure as an elastic trigger.
ELASTIC_REPORT = "elastic_report.json"


def plan_world_transition(current_world: Optional[int],
                          full_world: Optional[int],
                          dead_ranks: List[int], *,
                          elastic_shrink: bool = False,
                          min_world: int = 1):
    """The shrink-to-survivors policy, as a pure decision function:
    given the world the dying child ran at, the job's full width, and
    the ranks the trigger identified as dead, return
    ``(to_world, transition)`` for the relaunch, where `transition` is
    ``"shrink"``, ``"regrow"``, or None (relaunch at the same width).

    * dead ranks named and `elastic_shrink` on: relaunch the survivors
      at ``current - len(dead)`` — unless that breaches the
      ``min_world`` floor, in which case the job relaunches at its
      CURRENT width and keeps spinning for the lost host (the
      pre-elastic behavior, now a bounded fallback instead of the only
      option).
    * no dead ranks named (plain exit, whole-job stall, watchdog trip)
      while running shrunken: the failure was not a missing host, so
      capacity is presumed back — grow to the full width and let the
      resharding-on-restore path re-partition upward.
    * anything else: stay put.

    Unit-testable and shared with the chaos campaigns, so the policy
    the fleet runs is the policy the tests pin."""
    if current_world is None:
        current_world = full_world
    if current_world is None:
        return None, None
    if dead_ranks and elastic_shrink:
        target = current_world - len(set(dead_ranks))
        if target >= max(1, int(min_world)):
            return target, ("shrink" if target < current_world else None)
        logger.warning(
            f"supervisor: shrinking to {target} survivor(s) would "
            f"breach --min-world {min_world}; relaunching at world "
            f"{current_world} and waiting for capacity instead")
        return current_world, None
    if not dead_ranks and full_world is not None and \
            current_world < full_world:
        return full_world, "regrow"
    return current_world, None


def _ledger_append(path: Optional[str], entry: Dict) -> None:
    """Append one JSON line to the restart ledger (post-mortems must
    not depend on supervisor scrollback).  Best-effort: a full disk
    must not take the supervisor down with it."""
    if path is None:
        return
    try:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "a") as f:
            f.write(json.dumps(entry, default=str) + "\n")
            f.flush()
    except OSError as e:
        logger.warning(f"supervisor: restart ledger write failed: {e}")


class RestartPolicy:
    """Relaunch state machine: exponential backoff with jitter under a
    rolling restart-budget window.

    * `record_failure(ran_for)` -> the relaunch delay in seconds, or
      None when the budget is exhausted (give up).
    * budget: at most `max_restarts` failures inside the trailing
      `restart_window` seconds (window 0 = no time horizon: the count
      only clears when a child survives `success_window`).
    * backoff: starts at `backoff`, doubles per failure up to
      `backoff_cap`, multiplied by a uniform jitter in
      [1-jitter, 1+jitter] so a fleet of supervisors does not relaunch
      in lockstep against the same coordinator/filesystem.
    * a child that stayed alive >= `success_window` seconds earns its
      full budget back and resets the backoff (long-running training
      that dies after hours must not inherit the count from startup
      flakes).

    `rng`/`clock` are injectable for tests."""

    def __init__(self, max_restarts: int = 10, backoff: float = 5.0,
                 backoff_cap: float = 300.0, jitter: float = 0.25,
                 restart_window: float = 0.0,
                 success_window: float = 300.0,
                 rng=None, clock=time.monotonic):
        if not 0.0 <= float(jitter) < 1.0:
            raise ValueError(f"jitter must be in [0, 1), got {jitter}")
        self.max_restarts = int(max_restarts)
        self.backoff = float(backoff)
        self.backoff_cap = float(backoff_cap)
        self.jitter = float(jitter)
        self.restart_window = float(restart_window)
        self.success_window = float(success_window)
        self._rng = rng if rng is not None else random.Random()
        self._clock = clock
        self._delay = self.backoff
        self._failures: deque = deque()  # clock() stamps of failures

    @property
    def failures_in_window(self) -> int:
        self._prune(self._clock())
        return len(self._failures)

    def _prune(self, now: float) -> None:
        if self.restart_window > 0:
            while self._failures and \
                    now - self._failures[0] > self.restart_window:
                self._failures.popleft()

    def record_failure(self, ran_for: float) -> Optional[float]:
        """A child died after `ran_for` seconds: the delay before the
        relaunch, or None = budget exhausted, give up."""
        now = self._clock()
        if ran_for >= self.success_window:
            self._failures.clear()
            self._delay = self.backoff
        self._failures.append(now)
        self._prune(now)
        if len(self._failures) > self.max_restarts:
            return None
        delay = self._delay * self._rng.uniform(1.0 - self.jitter,
                                                1.0 + self.jitter)
        self._delay = min(self._delay * 2.0, self.backoff_cap)
        return max(0.0, delay)


class HeartbeatWatcher:
    """Health view over a RunMonitor run directory (monitor/monitor.py):
    per-rank `events.rank*.jsonl` streams + the rank-0 `heartbeat`
    events the monitor emits every `heartbeat_interval` steps.

    `check()` returns None while the run looks healthy, else a dict
    {"reason": str, "dead_ranks": [...], "surviving_world": int|None}:

    * **stall** — no event file grew for `stall_timeout` seconds.  A
      hung collective / dead coordinator stops EVERY rank's stream, so
      this is the dead-rank detector that works even when the victim
      cannot say goodbye.  On a stall the watcher additionally compares
      PER-RANK stream mtimes: a rank whose stream went quiet more than
      `dead_rank_margin` seconds before the newest stream is named in
      `dead_ranks` (the victim dies first; the survivors wedge in the
      next collective and keep their later mtimes) — the signal the
      `--elastic-shrink` policy needs to relaunch on the survivors.
      When every stream stopped together (coordinator death, whole-job
      hang) no rank is singled out and the restart stays full-width.
    * **straggler** — a rank flagged by `straggler_factor` x median in
      `straggler_strikes` CONSECUTIVE heartbeat events (one slow step
      is noise; a persistently slow rank is a failing host).
    * **watchdog trip** — the in-process StepWatchdog
      (runtime/resilience.py) detected a hung step/barrier and wrote
      `watchdog_trip.json` into the run dir with a machine-readable
      reason + diagnostic-snapshot path.  This escalation path fires
      as soon as the trip file appears instead of waiting out the
      (much longer) stall-timeout, and carries the diagnostics path
      into the restart ledger.

    `reset()` re-arms the liveness clock after a relaunch."""

    def __init__(self, run_dir: str, stall_timeout: float,
                 straggler_strikes: int = 3, clock=time.time,
                 dead_rank_margin: Optional[float] = None):
        self.run_dir = run_dir
        self.stall_timeout = float(stall_timeout)
        self.straggler_strikes = int(straggler_strikes)
        # margin separating "died first" from "wedged with the rest";
        # defaults to a quarter of the stall window, 0 disables
        self.dead_rank_margin = (self.stall_timeout / 4.0
                                 if dead_rank_margin is None
                                 else float(dead_rank_margin))
        self._clock = clock
        self._strikes: Dict[int, int] = {}
        self._hb_offset = 0  # byte cursor into the rank-0 event stream
        self._armed_at = self._clock()

    def _stream_size(self) -> int:
        files = self._event_files()
        if not files:
            return 0
        try:
            return os.path.getsize(files[0])
        except OSError:
            return 0

    def reset(self) -> None:
        """Re-arm after a relaunch: clear strikes, skip everything
        already in the stream (the heartbeats that justified the LAST
        restart must not re-trigger against the fresh child — the
        relaunched run appends to the same files), floor the liveness
        clock at now, and CONSUME any watchdog trip file.  Deleting the
        trip file (not just mtime-guarding it) matters: the mtime lives
        in the filesystem's clock domain while `_armed_at` lives in
        `clock`'s — on a skewed NFS server a stale trip would otherwise
        out-date every re-arm and restart each healthy child on sight.
        The diagnostic snapshot it points at stays on disk (the ledger
        recorded the path)."""
        self._strikes.clear()
        self._hb_offset = self._stream_size()
        self._armed_at = self._clock()
        try:
            os.remove(os.path.join(self.run_dir, WATCHDOG_TRIP_FILE))
        except OSError:
            pass

    def _world_size(self) -> Optional[int]:
        try:
            with open(os.path.join(self.run_dir, "manifest.json")) as f:
                return int(json.load(f).get("world_size"))
        except (OSError, ValueError, TypeError, json.JSONDecodeError):
            return None

    def _event_files(self) -> List[str]:
        return sorted(glob.glob(os.path.join(self.run_dir,
                                             "events.rank*.jsonl")))

    def _rank_mtimes(self) -> Dict[int, float]:
        """Per-rank event-stream mtimes keyed by rank id."""
        out: Dict[int, float] = {}
        for path in self._event_files():
            base = os.path.basename(path)
            try:
                rank = int(base[len("events.rank"):-len(".jsonl")])
                out[rank] = os.path.getmtime(path)
            except (ValueError, OSError):
                continue
        return out

    def _last_activity(self) -> Optional[float]:
        """Newest mtime across event streams (None: no files yet)."""
        stamps = self._rank_mtimes().values()
        return max(stamps) if stamps else None

    def _dead_ranks_on_stall(self) -> List[int]:
        """On a stall: the ranks whose streams went quiet distinctly
        EARLIER than the newest stream (a dead rank stops writing first;
        its peers wedge in the next collective and carry later mtimes).
        Only streams that wrote SINCE the last (re)arm participate:
        the relaunched run appends to the same run dir, so a rank a
        previous shrink already removed owns a frozen file that would
        otherwise read as "dead" on every later stall — and a rank of
        THIS generation that never wrote is simply not named (the
        restart stays full-width, the safe fallback).  Empty when the
        margin is off, fewer than two live streams exist, or every
        live stream stopped together (whole-job stall — no victim to
        shed)."""
        if self.dead_rank_margin <= 0:
            return []
        stamps = {r: m for r, m in self._rank_mtimes().items()
                  if m >= self._armed_at}
        if len(stamps) < 2:
            return []
        newest = max(stamps.values())
        dead = sorted(r for r, m in stamps.items()
                      if newest - m > self.dead_rank_margin)
        if not dead or len(dead) == len(stamps):
            return []
        return dead

    def _latest_heartbeats(self, tail_bytes: int = 1 << 16) -> List[dict]:
        """NEW heartbeat events from the rank-0 stream since the last
        read, oldest first.  A byte cursor (`_hb_offset`) makes each
        event count exactly once across check()/reset() calls; the read
        is additionally bounded to the last `tail_bytes` so an
        arbitrarily long backlog never stalls the poll loop."""
        files = self._event_files()
        if not files:
            return []
        path = files[0]
        try:
            with open(path, "rb") as f:
                f.seek(0, os.SEEK_END)
                size = f.tell()
                if size <= self._hb_offset:
                    return []
                f.seek(max(self._hb_offset, size - tail_bytes))
                chunk = f.read().decode("utf-8", errors="replace")
        except OSError:
            return []
        self._hb_offset = size
        out = []
        for line in chunk.splitlines():
            if '"heartbeat"' not in line:
                continue
            try:
                e = json.loads(line)
            except json.JSONDecodeError:
                continue  # torn first line of the window
            if e.get("type") == "heartbeat":
                out.append(e)
        return out

    def _watchdog_trigger(self) -> Optional[dict]:
        """A StepWatchdog escalation newer than the last (re)arm, as a
        restart trigger dict (None otherwise)."""
        path = os.path.join(self.run_dir, WATCHDOG_TRIP_FILE)
        try:
            mtime = os.path.getmtime(path)
        except OSError:
            return None
        if mtime <= self._armed_at:
            return None  # a previous incarnation's trip; reset() re-arms
        trip = read_watchdog_trip(self.run_dir)
        if trip is None:
            return None
        return {
            "reason": (f"watchdog trip on rank {trip.get('rank', '?')}: "
                       f"{trip.get('reason', 'step deadline exceeded')}"),
            "dead_ranks": [],
            "surviving_world": None,
            "diagnostics": trip.get("snapshot"),
        }

    def check(self) -> Optional[dict]:
        now = self._clock()
        # in-process watchdog escalation beats the coarse stall clock
        trip = self._watchdog_trigger()
        if trip is not None:
            return trip
        # liveness: SOME stream must keep growing
        if self.stall_timeout > 0:
            last = self._last_activity()
            # _armed_at floors the anchor: right after (re)arming, stale
            # pre-relaunch file mtimes must not trigger instantly — the
            # fresh child gets a full stall_timeout to show life
            anchor = (self._armed_at if last is None
                      else max(last, self._armed_at))
            if now - anchor > self.stall_timeout:
                dead = self._dead_ranks_on_stall()
                world = self._world_size() if dead else None
                return {
                    "reason": (f"no monitor events in "
                               f"{now - anchor:.0f}s (> stall-timeout "
                               f"{self.stall_timeout:.0f}s) under "
                               f"{self.run_dir}"
                               + (f"; rank(s) {dead} went quiet "
                                  f"first" if dead else "")),
                    "dead_ranks": dead,
                    "surviving_world": (world - len(dead)
                                        if world is not None else None),
                }
        # straggler strikes: consecutive heartbeat flags per rank
        for hb in self._latest_heartbeats():
            flagged = set(hb.get("stragglers") or [])
            for r in flagged:
                self._strikes[r] = self._strikes.get(r, 0) + 1
            for r in list(self._strikes):
                if r not in flagged:
                    del self._strikes[r]  # consecutive only
        dead = sorted(r for r, n in self._strikes.items()
                      if n >= self.straggler_strikes)
        if dead:
            world = self._world_size()
            return {
                "reason": (f"rank(s) {dead} straggling in "
                           f"{self.straggler_strikes} consecutive "
                           f"heartbeats"),
                "dead_ranks": dead,
                "surviving_world": (world - len(dead)
                                    if world is not None else None),
            }
        return None


def _consume_elastic_report(report_dir: Optional[str]) -> Optional[dict]:
    """Read AND delete a launcher-written dead-rank report
    (`elastic_report.json`).  Consumed once: a stale report must never
    shrink a later, unrelated restart."""
    if report_dir is None:
        return None
    path = os.path.join(report_dir, ELASTIC_REPORT)
    try:
        with open(path) as f:
            report = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    try:
        os.remove(path)
    except OSError:
        pass
    dead = report.get("dead_ranks") or []
    if not isinstance(dead, list) or \
            not all(isinstance(r, int) for r in dead):
        logger.warning(f"supervisor: malformed {ELASTIC_REPORT} "
                       f"(dead_ranks={dead!r}) — ignored")
        return None
    return {"reason": str(report.get("reason")
                          or f"launcher reported rank(s) {dead} dead"),
            "dead_ranks": sorted(set(dead)),
            "surviving_world": None}


def supervise(command, max_restarts: int = 10, backoff: float = 5.0,
              backoff_cap: float = 300.0, success_window: float = 300.0,
              jitter: float = 0.25, restart_window: float = 0.0,
              monitor_dir: Optional[str] = None,
              stall_timeout: float = 0.0, straggler_strikes: int = 3,
              grace: float = 15.0, poll_interval: float = 0.5,
              policy: Optional[RestartPolicy] = None,
              watcher: Optional[HeartbeatWatcher] = None,
              ledger_path: Optional[str] = None,
              elastic_shrink: bool = False, min_world: int = 1,
              world: Optional[int] = None):
    """Run `command` (list) until it exits 0 or the restart budget is
    exhausted.  See the module docstring for the exit-driven and
    heartbeat-driven restart paths; `policy`/`watcher` may be passed
    pre-built (tests, custom clocks).

    With `elastic_shrink=True` a trigger that names dead ranks (per-rank
    stream forensics, straggler strikes, or a launcher
    `elastic_report.json`) relaunches on the SURVIVORS: the child env
    carries `DSTPU_SURVIVING_WORLD`/`DSTPU_DEAD_RANKS` and the launcher
    re-forms the job at the shrunken width (never below `min_world`);
    a later restart with no dead ranks grows back to the full width
    (`world`, or the monitor manifest's world_size, or inferred from
    the first shrink trigger).  Every relaunch exports
    `DSTPU_INCARNATION` — the relaunch counter that namespaces the
    whole coordination-service KV surface (hostwire.scoped_key), so a
    survivor generation never consumes a dead generation's write-once
    keys.

    Every restart decision (and the final give-up) is appended to
    `restarts.jsonl` in the monitor dir (override with `ledger_path`) —
    reason, dead ranks, the world transition (`from_world` ->
    `to_world`), backoff chosen, watchdog diagnostics path if any — so
    post-mortems read a machine-parsable ledger instead of supervisor
    scrollback; `tools/run_report.py` renders it (incl. the "Elastic
    transitions" block)."""
    if policy is None:
        policy = RestartPolicy(max_restarts=max_restarts, backoff=backoff,
                               backoff_cap=backoff_cap, jitter=jitter,
                               restart_window=restart_window,
                               success_window=success_window)
    if watcher is None and monitor_dir is not None:
        # stall_timeout 0 turns off only the liveness check — straggler
        # detection still runs off the heartbeat events
        watcher = HeartbeatWatcher(monitor_dir, stall_timeout,
                                   straggler_strikes=straggler_strikes)
    ledger_dir = monitor_dir or (watcher.run_dir
                                 if watcher is not None else None)
    if ledger_path is None and ledger_dir is not None:
        ledger_path = os.path.join(ledger_dir, RESTART_LEDGER)
    attempt = 0
    child = None
    stop_signal = None
    elastic: Optional[dict] = None  # last elastic trigger, for env
    # world bookkeeping for the shrink/grow policy: `full_world` is the
    # job's nominal width (explicit arg > monitor manifest > inferred
    # from the first trigger), `current_world` what the NEXT launch runs
    full_world = world
    if full_world is None and watcher is not None:
        full_world = watcher._world_size()
    current_world: Optional[int] = full_world

    def forward(signum, _frame):
        # an operator/scheduler signal means STOP, not "restart harder":
        # remember it so the loop exits instead of relaunching
        nonlocal stop_signal
        stop_signal = signum
        if child is not None and child.poll() is None:
            child.send_signal(signum)

    def to_exit_code(rc):
        # negative Popen rc (signal-killed child) -> conventional
        # 128+signum so sys.exit doesn't wrap it mod 256 into noise
        return 128 - rc if rc < 0 else rc

    def interruptible_sleep(seconds):
        # PEP 475 restarts time.sleep after a handled signal — sleep in
        # slices so a stop signal ends the backoff promptly
        end = time.monotonic() + seconds
        while stop_signal is None:
            left = end - time.monotonic()
            if left <= 0:
                return
            time.sleep(min(left, 0.5))

    def child_env():
        # fresh handoff every launch: inherited elastic vars (nested
        # supervisors, operator shells) must never leak into the child
        env = dict(os.environ)
        for var in ELASTIC_ENV_VARS:
            env.pop(var, None)
        # relaunch counter -> KV-key namespace (attempt is 1-based;
        # the first launch is incarnation 0, i.e. unprefixed keys —
        # identical to an unsupervised run)
        env[INCARNATION_ENV] = str(attempt - 1)
        if elastic is not None:
            env[ELASTIC_RESTART_ENV] = "1"
            env[ELASTIC_REASON_ENV] = elastic["reason"]
            if elastic.get("dead_ranks"):
                env[DEAD_RANKS_ENV] = ",".join(
                    str(r) for r in elastic["dead_ranks"])
        # a shrunken width persists across relaunches until the policy
        # grows back — not just on the launch right after the trigger
        if current_world is not None and full_world is not None \
                and current_world < full_world:
            env[SURVIVING_WORLD_ENV] = str(current_world)
        return env

    def wait_with_watcher():
        """Block until the child exits OR the watcher triggers; returns
        (rc, trigger_or_None).  On a trigger the child is torn down
        SIGTERM-first (save-if-possible), SIGKILL after `grace`."""
        while True:
            rc = child.poll()
            if rc is not None:
                return rc, None
            if stop_signal is not None:
                return child.wait(), None
            trigger = watcher.check() if watcher is not None else None
            if trigger is not None:
                logger.warning(
                    f"supervisor: heartbeat trigger — {trigger['reason']}; "
                    f"stopping the job for an elastic restart "
                    f"(SIGTERM, SIGKILL after {grace:.0f}s)")
                child.send_signal(signal.SIGTERM)
                try:
                    rc = child.wait(timeout=grace)
                except subprocess.TimeoutExpired:
                    child.kill()
                    rc = child.wait()
                return rc, trigger
            time.sleep(poll_interval)

    old_int = signal.signal(signal.SIGINT, forward)
    old_term = signal.signal(signal.SIGTERM, forward)
    try:
        while True:
            if stop_signal is not None:  # landed before (re)launch
                logger.info(f"supervisor: stopping on signal {stop_signal}")
                return 128 + int(stop_signal)
            attempt += 1
            start = time.monotonic()
            logger.info(f"supervisor: launching attempt {attempt}: "
                        f"{' '.join(command)}")
            child = subprocess.Popen(command, env=child_env())
            if stop_signal is not None:
                # raced the launch: the handler saw the OLD child; pass
                # the stop on to the one we just started
                child.send_signal(stop_signal)
            rc, trigger = wait_with_watcher()
            ran_for = time.monotonic() - start
            if rc == 0 and trigger is None:
                logger.info(f"supervisor: command succeeded after "
                            f"{attempt} attempt(s)")
                return 0
            if stop_signal is not None:
                logger.info(f"supervisor: stopping on signal "
                            f"{stop_signal} (child exit {rc})")
                return 128 + int(stop_signal)
            if trigger is None or not trigger.get("dead_ranks"):
                # the launcher may know the victim precisely even when
                # the heartbeat forensics don't (it spawned the workers)
                # — merge INTO the trigger so its diagnostics (watchdog
                # snapshot path) survive into the ledger
                report = _consume_elastic_report(ledger_dir)
                if report is not None:
                    merged = dict(trigger or {})
                    merged["dead_ranks"] = report["dead_ranks"]
                    merged["surviving_world"] = (
                        report.get("surviving_world")
                        or merged.get("surviving_world"))
                    merged["reason"] = (f"{trigger['reason']}; "
                                        f"{report['reason']}"
                                        if trigger else report["reason"])
                    trigger = merged
            dead = (trigger or {}).get("dead_ranks") or []
            if full_world is None:
                # last-resort inference: the trigger knows the world it
                # observed (survivors + victims)
                sw = (trigger or {}).get("surviving_world")
                if sw is not None:
                    full_world = int(sw) + len(dead)
                    current_world = current_world or full_world
                elif watcher is not None:
                    full_world = watcher._world_size()
                    current_world = current_world or full_world
            from_world = current_world
            to_world, transition = plan_world_transition(
                current_world, full_world, dead,
                elastic_shrink=elastic_shrink, min_world=min_world)
            if transition is not None:
                logger.warning(
                    f"supervisor: elastic {transition} — relaunching at "
                    f"world {to_world} (was {from_world}; "
                    f"dead ranks {dead or '—'})")
            current_world = to_world if to_world is not None \
                else current_world
            elastic = trigger or None
            delay = policy.record_failure(ran_for)
            ledger_entry = {
                "t": time.time(),
                "attempt": attempt,
                "ran_for_s": round(ran_for, 3),
                "exit_code": rc,
                "reason": (trigger["reason"] if trigger
                           else f"exit code {rc}"),
                "dead_ranks": dead,
                "surviving_world": (current_world
                                    if transition == "shrink" else
                                    (trigger or {}).get(
                                        "surviving_world")),
                "from_world": from_world,
                "to_world": current_world,
                "transition": transition,
                "incarnation": attempt,  # the RELAUNCH's incarnation id
                "diagnostics": (trigger or {}).get("diagnostics"),
                "restarts_used": policy.failures_in_window,
            }
            if delay is None:
                _ledger_append(ledger_path, dict(
                    ledger_entry, event="give_up", backoff_s=None))
                logger.error(
                    f"supervisor: restart budget exhausted "
                    f"({policy.max_restarts} restart(s)"
                    + (f" per {policy.restart_window:.0f}s"
                       if policy.restart_window > 0 else "")
                    + f") after {attempt} attempt(s); last exit code {rc}")
                return to_exit_code(rc) or 1  # never exit 0 on give-up
            _ledger_append(ledger_path, dict(
                ledger_entry, event="restart", backoff_s=round(delay, 3)))
            logger.warning(
                f"supervisor: "
                + (f"elastic trigger ({trigger['reason']})" if trigger
                   else f"exit code {rc}")
                + f" after {ran_for:.1f}s; relaunching in {delay:.1f}s "
                f"({policy.failures_in_window}/{policy.max_restarts} "
                f"restarts used)")
            interruptible_sleep(delay)
            if stop_signal is not None:  # signal arrived during backoff
                logger.info(f"supervisor: stopping on signal {stop_signal}")
                return 128 + int(stop_signal)
            if watcher is not None:
                watcher.reset()  # re-arm liveness for the fresh child
    finally:
        signal.signal(signal.SIGINT, old_int)
        signal.signal(signal.SIGTERM, old_term)


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="restart supervisor for elastic training jobs")
    parser.add_argument("--max-restarts", type=int, default=10)
    parser.add_argument("--backoff", type=float, default=5.0,
                        help="initial relaunch delay (doubles per failure, "
                        "with +/- jitter)")
    parser.add_argument("--backoff-cap", type=float, default=300.0)
    parser.add_argument("--jitter", type=float, default=0.25,
                        help="uniform backoff jitter fraction in [0, 1)")
    parser.add_argument("--restart-window", type=float, default=0.0,
                        help="rolling budget window in seconds: give up "
                        "after max-restarts failures within it (0: no "
                        "time horizon)")
    parser.add_argument("--success-window", type=float, default=300.0,
                        help="children alive this long reset the budget")
    parser.add_argument("--monitor-dir", type=str, default=None,
                        help="RunMonitor run directory to watch for "
                        "heartbeats/liveness (docs/tutorials/monitoring.md)")
    parser.add_argument("--stall-timeout", type=float, default=0.0,
                        help="restart when no monitor events appear for "
                        "this many seconds (0: off)")
    parser.add_argument("--straggler-strikes", type=int, default=3,
                        help="consecutive straggler heartbeats before an "
                        "elastic restart")
    parser.add_argument("--grace", type=float, default=15.0,
                        help="seconds between SIGTERM and SIGKILL on a "
                        "heartbeat-triggered teardown")
    parser.add_argument("--elastic-shrink", action="store_true",
                        help="when a trigger names dead ranks, relaunch "
                        "on the SURVIVORS at the shrunken world size "
                        "(DSTPU_SURVIVING_WORLD) instead of spinning at "
                        "full width for the lost host; a later restart "
                        "with no dead ranks grows back to full width")
    parser.add_argument("--min-world", type=int, default=1,
                        help="floor for --elastic-shrink: never relaunch "
                        "below this many ranks (breaching triggers a "
                        "full-width relaunch that waits for capacity)")
    parser.add_argument("--world", type=int, default=None,
                        help="the job's full world size (default: the "
                        "monitor manifest's world_size, else inferred "
                        "from the first shrink trigger)")
    parser.add_argument("command", nargs=argparse.REMAINDER,
                        help="-- training command")
    args = parser.parse_args(argv)
    command = args.command
    if command and command[0] == "--":
        command = command[1:]
    if not command:
        parser.error("no command given (use: supervisor [opts] -- cmd ...)")
    return supervise(command, max_restarts=args.max_restarts,
                     backoff=args.backoff, backoff_cap=args.backoff_cap,
                     jitter=args.jitter, restart_window=args.restart_window,
                     success_window=args.success_window,
                     monitor_dir=args.monitor_dir,
                     stall_timeout=args.stall_timeout,
                     straggler_strikes=args.straggler_strikes,
                     grace=args.grace,
                     elastic_shrink=args.elastic_shrink,
                     min_world=args.min_world, world=args.world)


if __name__ == "__main__":
    sys.exit(main())
