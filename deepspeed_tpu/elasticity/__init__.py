from .elasticity import (  # noqa: F401
    ElasticityConfig,
    ElasticityConfigError,
    ElasticityError,
    ElasticityIncompatibleWorldSize,
    compute_elastic_config,
    elasticity_enabled,
    ensure_immutable_elastic_config,
    get_compatible_gpus_v01,
)
from .supervisor import (  # noqa: F401
    HeartbeatWatcher,
    RestartPolicy,
    supervise,
)
