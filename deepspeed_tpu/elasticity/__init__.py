from .elasticity import (  # noqa: F401
    ElasticityConfig,
    ElasticityConfigError,
    ElasticityError,
    ElasticityIncompatibleWorldSize,
    compute_elastic_config,
    elasticity_enabled,
    ensure_immutable_elastic_config,
    get_compatible_gpus_v01,
)
from .elastic_env import (  # noqa: F401
    DEAD_RANKS_ENV,
    ELASTIC_REASON_ENV,
    ELASTIC_RESTART_ENV,
    INCARNATION_ENV,
    SURVIVING_WORLD_ENV,
    ElasticEnv,
    read_elastic_env,
)
from .supervisor import (  # noqa: F401
    HeartbeatWatcher,
    RestartPolicy,
    plan_world_transition,
    supervise,
)
