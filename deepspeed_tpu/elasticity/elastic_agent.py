"""`ds_elastic` CLI (reference bin/ds_elastic + elasticity API):
given a DeepSpeed config with an `elasticity` block, print the computed
compatible global batch sizes / micro-batch / world-size combinations."""

from __future__ import annotations

import argparse
import json

from .elasticity import compute_elastic_config


def main(args=None):
    import sys

    argv = list(sys.argv[1:] if args is None else args)
    if argv and argv[0] == "supervise":
        # `ds_elastic supervise [opts] -- cmd ...`: restart supervisor
        # (relaunch-on-failure + elastic-checkpoint resume)
        from .supervisor import main as supervise_main

        return supervise_main(argv[1:])
    args = argv
    parser = argparse.ArgumentParser(description="DeepSpeed elasticity")
    parser.add_argument("-c", "--config", type=str, required=True,
                        help="DeepSpeed config json")
    parser.add_argument("-w", "--world-size", type=int, default=0,
                        help="intended world size (0: show all)")
    args = parser.parse_args(args=args)
    with open(args.config) as fh:
        ds_config = json.load(fh)

    if args.world_size > 0:
        batch, _valid, micro = compute_elastic_config(
            ds_config, world_size=args.world_size)
        grad_acc = batch // (micro * args.world_size)
        print(f"world_size={args.world_size}: train_batch_size={batch}, "
              f"micro_batch_per_gpu={micro}, grad_acc_steps={grad_acc}")
    else:
        batch, valid = compute_elastic_config(ds_config)
        print(f"final batch size: {batch}")
        print(f"valid world sizes: {sorted(valid)}")
    return 0


if __name__ == "__main__":
    main()
