"""Elastic batch-size solver (reference: deepspeed/elasticity/elasticity.py:240-334).

Pure arithmetic, hardware-agnostic: choose a global batch size compatible
with many accelerator counts so a restarted job can resume at a different
world size with identical convergence. "gpus" in names kept for schema
parity; on TPU a "gpu" is a chip.
"""

from ..utils.logging import logger
from ..version import __version__
from . import constants as ec


class ElasticityError(Exception):
    pass


class ElasticityConfigError(ElasticityError):
    pass


class ElasticityIncompatibleWorldSize(ElasticityError):
    pass


class ElasticityConfig:
    """Schema-parity config holder (reference elasticity/config.py)."""

    def __init__(self, param_dict):
        self.enabled = param_dict.get(ec.ENABLED, ec.ENABLED_DEFAULT)
        if ec.MAX_ACCEPTABLE_BATCH_SIZE not in param_dict and self.enabled:
            raise ElasticityConfigError(
                f"'{ec.MAX_ACCEPTABLE_BATCH_SIZE}' is required in elasticity config")
        self.max_acceptable_batch_size = param_dict.get(
            ec.MAX_ACCEPTABLE_BATCH_SIZE, ec.MAX_ACCEPTABLE_BATCH_SIZE_DEFAULT)
        self.micro_batches = param_dict.get(ec.MICRO_BATCHES, ec.MICRO_BATCHES_DEFAULT)
        if not isinstance(self.micro_batches, list) or not self.micro_batches:
            raise ElasticityConfigError(
                f"'{ec.MICRO_BATCHES}' must be a non-empty list")
        if any((not isinstance(m, int)) or m <= 0 for m in self.micro_batches):
            raise ElasticityConfigError(
                f"'{ec.MICRO_BATCHES}' must contain positive ints, got "
                f"{self.micro_batches}")
        self.min_gpus = param_dict.get(ec.MIN_GPUS, ec.MIN_GPUS_DEFAULT)
        self.max_gpus = param_dict.get(ec.MAX_GPUS, ec.MAX_GPUS_DEFAULT)
        if self.min_gpus < 1 or self.max_gpus < self.min_gpus:
            raise ElasticityConfigError(
                f"invalid gpu range [{self.min_gpus}, {self.max_gpus}]")
        self.min_time = param_dict.get(ec.MIN_TIME, ec.MIN_TIME_DEFAULT)
        self.version = param_dict.get(ec.VERSION, ec.VERSION_DEFAULT)
        self.prefer_larger_batch_size = param_dict.get(
            ec.PREFER_LARGER_BATCH, ec.PREFER_LARGER_BATCH_DEFAULT)
        self.ignore_non_elastic_batch_info = param_dict.get(
            ec.IGNORE_NON_ELASTIC_BATCH_INFO, ec.IGNORE_NON_ELASTIC_BATCH_INFO_DEFAULT)

    def repr(self):
        return self.__dict__


# Highly composite numbers: batch sizes built from these divide evenly for
# many world sizes (same table idea as the reference; supports ~720K batch).
_HIGHLY_COMPOSITE = [
    1, 2, 4, 6, 12, 24, 36, 48, 60, 120, 180, 240, 360, 720, 840, 1260, 1680,
    2520, 5040, 7560, 10080, 15120, 20160, 25200, 27720, 45360, 50400, 55440,
    83160, 110880, 166320, 221760, 277200, 332640, 498960, 554400, 665280,
    720720,
]


def _candidate_batch_sizes(micro_batches, max_acceptable):
    """Largest micro*HCN <= max_acceptable, per micro batch size."""
    out = set()
    for m in micro_batches:
        best = m
        for h in _HIGHLY_COMPOSITE:
            if m * h > max_acceptable:
                break
            best = m * h
        out.add(best)
    return sorted(out)


def _valid_gpus(batch_size, micro_batches, min_gpus, max_gpus):
    """All world sizes g with batch_size == micro * acc * g for some micro in
    the list and integer acc >= 1 — i.e. divisors of batch_size/micro."""
    valid = set()
    for m in micro_batches:
        if batch_size % m:
            continue
        quotient = batch_size // m
        d = 1
        while d * d <= quotient:
            if quotient % d == 0:
                for g in (d, quotient // d):
                    if min_gpus <= g <= max_gpus:
                        valid.add(g)
            d += 1
    return sorted(valid)


def _best_candidate(candidates, micro_batches, min_gpus, max_gpus, prefer_larger):
    best_bs, best_valid = int(min(micro_batches)), []
    for bs in candidates:
        valid = _valid_gpus(bs, micro_batches, min_gpus, max_gpus)
        better_count = len(valid) > len(best_valid)
        tie_break = (len(valid) == len(best_valid) and
                     (bs > best_bs if prefer_larger else bs < best_bs))
        if better_count or tie_break:
            best_bs, best_valid = bs, valid
    return best_bs, best_valid


def _version_lt(a: str, b: str) -> bool:
    def parts(v):
        return [int(x) for x in str(v).split("+")[0].split(".")[:3]]

    return parts(a) < parts(b)


def get_compatible_gpus_v01(micro_batches, max_acceptable_batch_size,
                            min_gpus=1, max_gpus=None, prefer_larger=True):
    """v0.1 algorithm surface (reference elasticity.py:61-171)."""
    max_gpus = max_gpus or max_acceptable_batch_size // min(micro_batches)
    candidates = _candidate_batch_sizes(micro_batches, max_acceptable_batch_size)
    return _best_candidate(candidates, micro_batches, min_gpus, max_gpus,
                           prefer_larger)


def elasticity_enabled(ds_config: dict) -> bool:
    return ds_config.get(ec.ELASTICITY, {}).get(ec.ENABLED, ec.ENABLED_DEFAULT)


def ensure_immutable_elastic_config(runtime_elastic_config_dict):
    """Cross-restart immutability guard (reference elasticity.py:207-239):
    the scheduler pins the original elastic config in an env var; any
    divergence on restart would silently change convergence."""
    import json
    import os

    if ec.DEEPSPEED_ELASTICITY_CONFIG in os.environ:
        scheduler_config = json.loads(os.environ[ec.DEEPSPEED_ELASTICITY_CONFIG])
        scheduler = ElasticityConfig(scheduler_config)
        runtime = ElasticityConfig(runtime_elastic_config_dict)
        err = "Elastic config '{}={}' from the scheduler does not match the " \
              "runtime value '{}'"
        for key in ("max_acceptable_batch_size", "micro_batches", "min_gpus",
                    "max_gpus", "version"):
            if getattr(scheduler, key) != getattr(runtime, key):
                raise ElasticityConfigError(
                    err.format(key, getattr(scheduler, key), getattr(runtime, key)))


def compute_elastic_config(ds_config: dict, target_deepspeed_version: str = None,
                           world_size: int = 0):
    """Resolve (final_batch_size, valid_world_sizes[, micro_batch]) from an
    elastic config dict (reference elasticity.py:240-334)."""
    if not isinstance(ds_config, dict):
        raise ValueError("ds_config must be a dict")
    elastic_config_dict = ds_config.get(ec.ELASTICITY)
    if not elastic_config_dict:
        raise ElasticityConfigError(
            f"'{ec.ELASTICITY}' is missing from config json")
    elastic_config = ElasticityConfig(elastic_config_dict)
    if not elastic_config.enabled:
        raise ElasticityError(
            "Elasticity is not enabled; set 'elasticity': {'enabled': true, ...}")
    if float(elastic_config.version) > ec.LATEST_ELASTICITY_VERSION:
        raise ElasticityConfigError(
            f"Unsupported elasticity version {elastic_config.version}; latest is "
            f"{ec.LATEST_ELASTICITY_VERSION}")
    if target_deepspeed_version is not None and \
            _version_lt(target_deepspeed_version, ec.MINIMUM_DEEPSPEED_VERSION):
        raise ElasticityError(
            f"target version {target_deepspeed_version} is older than the "
            f"minimum elasticity-capable version {ec.MINIMUM_DEEPSPEED_VERSION}")

    final_batch_size, valid_gpus = get_compatible_gpus_v01(
        micro_batches=elastic_config.micro_batches,
        max_acceptable_batch_size=elastic_config.max_acceptable_batch_size,
        min_gpus=elastic_config.min_gpus,
        max_gpus=elastic_config.max_gpus,
        prefer_larger=elastic_config.prefer_larger_batch_size)
    logger.info(f"elasticity: final_batch_size={final_batch_size}, "
                f"valid world sizes={valid_gpus}")

    if world_size > 0:
        if world_size not in valid_gpus:
            raise ElasticityIncompatibleWorldSize(
                f"world size {world_size} is not in the valid set {valid_gpus}")
        # largest compatible micro batch for this world size
        candidates = [m for m in elastic_config.micro_batches
                      if final_batch_size % (m * world_size) == 0]
        if not candidates:
            raise ElasticityIncompatibleWorldSize(
                f"no micro batch in {elastic_config.micro_batches} divides "
                f"{final_batch_size} at world size {world_size}")
        micro_batch = max(candidates)
        return final_batch_size, valid_gpus, micro_batch

    return final_batch_size, valid_gpus
