"""The elastic-restart environment contract between the supervisor and
the engine.

The supervisor (elasticity/supervisor.py) relaunches a failed job with
a small env-var handshake; the engine reads and VALIDATES it at init —
a garbled value must fail loudly at boot, not silently train at the
wrong world size:

    DSTPU_ELASTIC_RESTART=1      this launch is a supervised relaunch
    DSTPU_ELASTIC_REASON=...     human-readable trigger (stall, straggler,
                                 watchdog trip, worker death)
    DSTPU_DEAD_RANKS=1,3         ranks the trigger identified as dead
    DSTPU_SURVIVING_WORLD=3      the dp world size this launch must run
                                 at (--elastic-shrink policy: relaunch
                                 on the survivors instead of spinning
                                 for the lost host)
    DSTPU_INCARNATION=2          relaunch counter; namespaces every
                                 coordination-service KV key
                                 (runtime/comm/hostwire.scoped_key) so a
                                 survivor generation never consumes the
                                 dead generation's write-once keys

`read_elastic_env()` is the single reader: every consumer (engine mesh
build, logging, counters) goes through the validated view.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import List, Optional

from ..runtime.comm.hostwire import INCARNATION_ENV

ELASTIC_RESTART_ENV = "DSTPU_ELASTIC_RESTART"
ELASTIC_REASON_ENV = "DSTPU_ELASTIC_REASON"
DEAD_RANKS_ENV = "DSTPU_DEAD_RANKS"
SURVIVING_WORLD_ENV = "DSTPU_SURVIVING_WORLD"

ELASTIC_ENV_VARS = (ELASTIC_RESTART_ENV, ELASTIC_REASON_ENV,
                    DEAD_RANKS_ENV, SURVIVING_WORLD_ENV, INCARNATION_ENV)


@dataclass
class ElasticEnv:
    """Validated view of the supervisor's relaunch environment."""

    restart: bool = False
    reason: Optional[str] = None
    dead_ranks: List[int] = field(default_factory=list)
    surviving_world: Optional[int] = None
    incarnation: int = 0

    @property
    def active(self) -> bool:
        """True when ANY elastic signal is present — the engine logs the
        handoff even before the full shrink path engages."""
        return bool(self.restart or self.dead_ranks
                    or self.surviving_world is not None
                    or self.incarnation > 0)

    def describe(self) -> str:
        bits = [f"incarnation {self.incarnation}"]
        if self.surviving_world is not None:
            bits.append(f"surviving_world {self.surviving_world}")
        if self.dead_ranks:
            bits.append(f"dead_ranks {self.dead_ranks}")
        if self.reason:
            bits.append(f"reason {self.reason!r}")
        return "elastic restart: " + ", ".join(bits)


def _parse_int(environ, var: str, minimum: int) -> Optional[int]:
    raw = environ.get(var)
    if raw is None or not str(raw).strip():
        return None
    try:
        val = int(str(raw).strip())
    except ValueError:
        raise ValueError(
            f"elastic env: {var}={raw!r} is not an integer — the "
            f"supervisor exports numeric values; a garbled handoff "
            f"must not silently pick a world size")
    if val < minimum:
        raise ValueError(
            f"elastic env: {var}={val} must be >= {minimum}")
    return val


def read_elastic_env(environ=None) -> ElasticEnv:
    """Read + validate the supervisor handoff.  Raises ValueError on
    non-numeric or inconsistent values (duplicate/negative dead ranks, a
    surviving world too small to have lost those ranks) — loud by
    contract, even before the full elastic path engages."""
    environ = os.environ if environ is None else environ
    restart = str(environ.get(ELASTIC_RESTART_ENV, "")).strip() == "1"
    reason = environ.get(ELASTIC_REASON_ENV) or None
    surviving = _parse_int(environ, SURVIVING_WORLD_ENV, minimum=1)
    incarnation = _parse_int(environ, INCARNATION_ENV, minimum=0) or 0

    dead: List[int] = []
    raw = environ.get(DEAD_RANKS_ENV)
    if raw is not None and str(raw).strip():
        for tok in str(raw).split(","):
            tok = tok.strip()
            if not tok:
                continue
            try:
                r = int(tok)
            except ValueError:
                raise ValueError(
                    f"elastic env: {DEAD_RANKS_ENV}={raw!r} must be a "
                    f"comma-separated list of ranks (bad entry {tok!r})")
            if r < 0:
                raise ValueError(
                    f"elastic env: {DEAD_RANKS_ENV} contains negative "
                    f"rank {r}")
            dead.append(r)
        if len(set(dead)) != len(dead):
            raise ValueError(
                f"elastic env: {DEAD_RANKS_ENV}={raw!r} lists a rank "
                f"twice — the supervisor's survivor math would be wrong")
        dead = sorted(dead)

    if surviving is not None and dead:
        # the dead ranks must have existed in the pre-shrink world of
        # surviving + len(dead) ranks
        pre_shrink = surviving + len(dead)
        too_big = [r for r in dead if r >= pre_shrink]
        if too_big:
            raise ValueError(
                f"elastic env: inconsistent handoff — dead rank(s) "
                f"{too_big} cannot exist in a pre-shrink world of "
                f"{pre_shrink} ({SURVIVING_WORLD_ENV}={surviving} + "
                f"{len(dead)} dead)")
    return ElasticEnv(restart=restart, reason=reason, dead_ranks=dead,
                      surviving_world=surviving, incarnation=incarnation)
