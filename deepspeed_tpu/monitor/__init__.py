"""deepspeed_tpu.monitor — structured run telemetry.

One subsystem unifying the observability shims (utils/timer,
utils/tensorboard, profiling/flops_profiler) into a single pipeline:

* `RunMonitor` — per-rank schema-versioned JSONL event stream + manifest
  + end-of-run summaries, TensorBoard as one sink beside it, multi-host
  heartbeats with rank-0 straggler detection.
* `Span` / `TraceWindow` — async-dispatch-aware timing (close on a
  block_until_ready marker) and the config-driven `jax.profiler.trace`
  capture window.
* `COUNTERS` — process-global comm/dispatch counters threaded through
  the p2p channels, the compiled pipeline executor, the collective
  wrappers, and the hostwire.
* `report` — renders any run's JSONL back into a BENCH.md-style table
  (CLI: tools/run_report.py).
"""

from .config import MONITOR, DeepSpeedMonitorConfig  # noqa: F401
from .counters import (COUNTERS, US_IN_BYTES_COUNTERS,  # noqa: F401
                       CounterRegistry, tree_bytes)
from .monitor import (SCHEMA_VERSION, RunMonitor,  # noqa: F401
                      device_memory_stats)
from .spans import Span, SpanSet, TraceWindow  # noqa: F401
from .tracing import (TRACE_SCHEMA_VERSION, ServingSLO,  # noqa: F401
                      TraceRecorder, percentile_nearest_rank,
                      read_trace_file)
