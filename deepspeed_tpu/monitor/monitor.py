"""RunMonitor — the structured telemetry pipeline.

One instance per engine per process.  Every training step produces one
schema-versioned JSONL event on every rank (`events.rank*.jsonl` in the
run directory), carrying the wall-time breakdown (async-aware spans),
throughput, achieved TFLOPs, loss-scale/overflow bookkeeping, device
memory stats aggregated over all local devices, and the per-step comm
counter deltas (monitor/counters.py).  A manifest written at
construction makes the run self-describing; `tools/run_report.py`
renders any run dir back into a BENCH.md-style table.

Sinks: the JSONL stream is primary; an attached `TensorBoardMonitor`
(utils/tensorboard.py) receives the scalar subset of every event.

Multi-host: every rank writes its own event stream (no cross-process
traffic per step).  With `heartbeat_interval > 0`, every N steps all
ranks exchange a tiny summary over the coordination-service KV wire
(runtime/comm/hostwire.py — a collective call, naturally aligned since
train steps are already collective) and rank 0 flags stragglers whose
step time exceeds `straggler_factor` x the median.  `close()` writes a
per-rank summary; under multi-host it also merges all ranks' summaries
into one `summary.json` on rank 0.
"""

from __future__ import annotations

import json
import math
import os
import time
from typing import Any, Dict, Optional

import jax

from ..utils.logging import log_dist, logger
from .config import DeepSpeedMonitorConfig
from .counters import COUNTERS
from .spans import Span, SpanSet, TraceWindow
from .tracing import TraceRecorder

SCHEMA_VERSION = 1


def device_memory_stats() -> Dict[str, Any]:
    """in_use/peak bytes aggregated over ALL local devices (sum and
    per-device max).  Empty dict when the backend exposes no stats
    (CPU)."""
    try:
        devices = jax.local_devices()
    except Exception:
        return {}
    in_use, peak = [], []
    for d in devices:
        try:
            stats = d.memory_stats() or {}
        except Exception:
            stats = {}
        in_use.append(int(stats.get("bytes_in_use", 0)))
        peak.append(int(stats.get("peak_bytes_in_use", 0)))
    if not any(in_use) and not any(peak):
        return {}
    return {
        "n_devices": len(devices),
        "bytes_in_use_sum": sum(in_use),
        "bytes_in_use_max": max(in_use),
        "peak_bytes_in_use_sum": sum(peak),
        "peak_bytes_in_use_max": max(peak),
    }


def _finite(x) -> Optional[float]:
    try:
        x = float(x)
    except (TypeError, ValueError):
        return None
    return x if math.isfinite(x) else None


class RunMonitor:
    def __init__(self, config: Optional[DeepSpeedMonitorConfig] = None,
                 rank: Optional[int] = None, world: Optional[int] = None,
                 manifest_extra: Optional[Dict[str, Any]] = None,
                 tensorboard=None, hostwire_endpoint=None):
        """config: the parsed "monitor" block (defaults when None).
        rank/world default to this process's jax identity.
        tensorboard: an optional utils.tensorboard.TensorBoardMonitor
        sink.  hostwire_endpoint: test hook — (client, rank, world)
        tuple driving the heartbeat wire over a fake KV store."""
        self.config = config or DeepSpeedMonitorConfig({})
        self.rank = jax.process_index() if rank is None else int(rank)
        self.world = jax.process_count() if world is None else int(world)
        self.tensorboard = tensorboard
        self._hostwire_endpoint = hostwire_endpoint
        self._hostwire = None
        self.spans = SpanSet()
        self.flops_per_step: Optional[float] = None
        # baseline counter snapshot at CONSTRUCTION: activity between
        # engine init and the first step (a resumed checkpoint's load —
        # incl. elastic.shrinks/regrows and ckpt.skipped_tags) attributes
        # to the first step event instead of vanishing before the first
        # step_start's lazy snapshot
        self._counter_snap = COUNTERS.snapshot()
        self._step_t0 = None
        self._events_since_flush = 0
        self._n_events = 0
        self._step_walls = []  # rolling per-step wall seconds (summary)
        self._last_event: Optional[Dict[str, Any]] = None
        self._closed = False

        self.run_dir = os.path.join(self.config.output_path,
                                    self.config.job_name)
        os.makedirs(self.run_dir, exist_ok=True)
        self._events_path = os.path.join(
            self.run_dir, f"events.rank{self.rank:05d}.jsonl")
        self._f = open(self._events_path, "a")

        prof_dir = self.config.profiler_output_dir or \
            os.path.join(self.run_dir, "profile")
        self.trace_window = TraceWindow(self.config.profiler_start_step,
                                        self.config.profiler_num_steps,
                                        prof_dir)
        # span tracing (monitor/tracing.py): constructed ONLY when
        # enabled — a disabled run creates zero trace files and zero
        # threads.  With >1 process the recorder's init allgather (the
        # clock-skew sync) is collective, like close().
        self.tracer = None
        if getattr(self.config, "tracing_enabled", False):
            wire = None
            if self.world > 1 or self._hostwire_endpoint is not None:
                wire = self._wire()
            self.tracer = TraceRecorder(
                self.run_dir, rank=self.rank, world=self.world,
                buffer_events=self.config.tracing_buffer_events,
                max_file_bytes=self.config.tracing_max_file_bytes,
                sample_rate=self.config.tracing_sample_rate,
                seed=self.config.tracing_seed,
                flush_interval_s=self.config.tracing_flush_interval_s,
                wire=wire)
        if self.rank == 0:
            self._write_manifest(manifest_extra or {})

    # ------------------------------------------------------------------
    # manifest / event plumbing
    # ------------------------------------------------------------------

    def _write_manifest(self, extra: Dict[str, Any]) -> None:
        try:
            backend = jax.default_backend()
            n_dev = jax.device_count()
        except Exception:
            backend, n_dev = "unknown", 0
        manifest = {
            "schema_version": SCHEMA_VERSION,
            "created_unix": time.time(),
            "world_size": self.world,
            "backend": backend,
            "device_count": n_dev,
            "monitor_config": {
                k: v for k, v in sorted(self.config.__dict__.items())},
            **extra,
        }
        path = os.path.join(self.run_dir, "manifest.json")
        with open(path, "w") as f:
            json.dump(manifest, f, indent=2, sort_keys=True, default=str)

    def emit(self, event_type: str, payload: Dict[str, Any]) -> None:
        event = {"v": SCHEMA_VERSION, "type": event_type, "rank": self.rank,
                 "t": round(time.time(), 6), **payload}
        self._f.write(json.dumps(event, default=str) + "\n")
        self._n_events += 1
        self._events_since_flush += 1
        if self._events_since_flush >= max(1, self.config.flush_interval):
            self._f.flush()
            self._events_since_flush = 0
        self._last_event = event

    # ------------------------------------------------------------------
    # step lifecycle
    # ------------------------------------------------------------------

    def span(self, name: str) -> Span:
        return self.spans.span(name)

    @property
    def sync_timing(self) -> bool:
        return self.config.sync_timing

    def step_start(self, step: int) -> None:
        """Call at the start of a global batch (accumulation boundary).
        The counter snapshot carries over from the previous step_end
        when one exists, so work BETWEEN steps (checkpoint saves, user
        collectives) is attributed to the next step event instead of
        vanishing into the gap."""
        self.trace_window.tick(step)
        if self._counter_snap is None:
            self._counter_snap = COUNTERS.snapshot()
        self._step_t0 = time.perf_counter()

    def step_end(self, step: int, **metrics) -> None:
        """Emit one step event.  Accepted metric keys (all optional):
        loss, lr, loss_scale, grad_norm, overflow, skipped_steps,
        samples_per_sec, flops_per_step, pipe (dict of pipeline
        accounting).  Unknown keys pass through verbatim."""
        wall = (time.perf_counter() - self._step_t0
                if self._step_t0 is not None else None)
        self._step_t0 = None
        payload: Dict[str, Any] = {"step": int(step)}
        if wall is not None:
            payload["wall_ms"] = round(wall * 1000.0, 3)
            self._step_walls.append(wall)
        spans_ms = self.spans.drain_ms()
        if spans_ms:
            payload["spans_ms"] = spans_ms
        comm = COUNTERS.delta_since(self._counter_snap)
        # re-snapshot HERE (not at the next step_start) so inter-step
        # counter activity lands in the next event's delta
        self._counter_snap = COUNTERS.snapshot()
        if comm:
            payload["comm"] = comm
        mem = device_memory_stats()
        if mem:
            payload["memory"] = mem

        flops = metrics.pop("flops_per_step", None) or self.flops_per_step
        sps = metrics.get("samples_per_sec")
        if sps is not None and self.config.tokens_per_sample:
            payload["tokens_per_sec"] = round(
                float(sps) * float(self.config.tokens_per_sample), 1)
        if flops and wall:
            payload["tflops"] = float(f"{flops / wall / 1e12:.4g}")
        for k, v in metrics.items():
            if v is None:
                continue
            payload[k] = _finite(v) if isinstance(v, float) else v
        self.emit("step", payload)
        self._emit_tensorboard(step, payload)
        hb = self.config.heartbeat_interval
        if hb > 0 and step > 0 and step % hb == 0:
            self.heartbeat(step, wall)

    def _emit_tensorboard(self, step: int, payload: Dict[str, Any]) -> None:
        # step-scoped Train/Step/* tags ONLY: the engine's own
        # _emit_monitor_scalars writes Train/Samples/* at x=global_samples;
        # reusing those tags here (x=step) would zigzag the shared series
        tb = self.tensorboard
        if tb is None:
            return
        for key, tag in (("loss", "Train/Step/loss"),
                         ("lr", "Train/Step/lr"),
                         ("loss_scale", "Train/Step/loss_scale"),
                         ("wall_ms", "Train/Step/wall_ms"),
                         ("tflops", "Train/Step/tflops")):
            v = payload.get(key)
            if v is not None:
                tb.add_scalar(tag, v, step)

    # ------------------------------------------------------------------
    # multi-host aggregation
    # ------------------------------------------------------------------

    def _wire(self):
        if self._hostwire is None:
            from ..runtime.comm.hostwire import HostWire

            self._hostwire = HostWire(tag="dstpu-monitor",
                                      _endpoint=self._hostwire_endpoint)
        return self._hostwire

    def heartbeat(self, step: int, wall_s: Optional[float]) -> None:
        """Collective: every rank ships (rank, step, step wall time);
        rank 0 merges, flags stragglers, and emits a heartbeat event.
        Aligned by construction — train steps are already collective."""
        if self.world <= 1 and self._hostwire_endpoint is None:
            return
        mine = {"rank": self.rank, "step": int(step),
                "wall_s": wall_s, "t": time.time()}
        try:
            parts = self._wire().allgather_bytes(
                json.dumps(mine).encode("utf-8"))
        except Exception as e:
            logger.warning(f"monitor heartbeat failed: {e}")
            return
        if self.rank != 0:
            return
        beats = []
        for p in parts:
            try:
                beats.append(json.loads(p.decode("utf-8")))
            except Exception:
                continue
        walls = sorted(b["wall_s"] for b in beats
                       if b.get("wall_s") is not None)
        stragglers = []
        if len(walls) >= 2:
            median = walls[len(walls) // 2]
            if median > 0:
                stragglers = [b["rank"] for b in beats
                              if (b.get("wall_s") or 0)
                              > self.config.straggler_factor * median]
        min_step = min((b["step"] for b in beats), default=step)
        self.emit("heartbeat", {"step": int(step), "beats": beats,
                                "stragglers": stragglers,
                                "min_step": min_step})
        if stragglers:
            log_dist(f"monitor: straggler rank(s) {stragglers} at step "
                     f"{step} (> {self.config.straggler_factor}x median "
                     f"step time)", ranks=[0])

    # ------------------------------------------------------------------
    # shutdown
    # ------------------------------------------------------------------

    def _local_summary(self) -> Dict[str, Any]:
        walls = self._step_walls
        mean = sum(walls) / len(walls) if walls else None
        return {
            "rank": self.rank,
            "steps": len(walls),
            "events": self._n_events,
            "mean_step_ms": round(mean * 1000.0, 3) if mean else None,
            "counters": COUNTERS.totals(),
        }

    def close(self) -> None:
        """Flush the event stream and write end-of-run summaries.  Under
        multi-host this is COLLECTIVE (rank summaries merge over the
        hostwire) — call it on every rank or not at all."""
        if self._closed:
            return
        self._closed = True
        self.trace_window.close()
        if self.tracer is not None:
            self.tracer.close()
        summary = self._local_summary()
        merged = [summary]
        if self.world > 1 or self._hostwire_endpoint is not None:
            try:
                parts = self._wire().allgather_bytes(
                    json.dumps(summary, default=str).encode("utf-8"))
                merged = [json.loads(p.decode("utf-8")) for p in parts]
            except Exception as e:
                logger.warning(f"monitor summary merge failed: {e}")
        with open(os.path.join(
                self.run_dir, f"summary.rank{self.rank:05d}.json"),
                "w") as f:
            json.dump(summary, f, indent=2, sort_keys=True, default=str)
        if self.rank == 0:
            with open(os.path.join(self.run_dir, "summary.json"), "w") as f:
                json.dump({"schema_version": SCHEMA_VERSION,
                           "ranks": merged}, f, indent=2, sort_keys=True,
                          default=str)
        self.emit("run_end", {"summary": summary})
        self._f.flush()
        self._f.close()
