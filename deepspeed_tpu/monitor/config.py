"""Monitor config block (TPU addition — no reference analogue; the
reference's observability is TensorBoard scalars + rank-0 log lines).

JSON schema:

    "monitor": {
        "enabled": true,
        "output_path": "runs",          # run dirs land under here
        "job_name": "my_run",           # -> <output_path>/<job_name>/
        "flush_interval": 10,           # steps between event-file flushes
        "sync_timing": true,            # block_until_ready before reading
                                        # span clocks (real step time; costs
                                        # one device sync per step)
        "flops": true,                  # achieved-TFLOPs via the flops
                                        # profiler's cost analysis (one
                                        # lowering at first step)
        "tokens_per_sample": 1024,      # optional: emit tokens/s
        "heartbeat_interval": 0,        # steps; >0 enables multi-host
                                        # heartbeats over the hostwire KV
        "straggler_factor": 1.5,        # rank-0 straggler flag threshold
        "profiler": {                   # jax.profiler.trace window
            "start_step": -1,           # -1: disabled
            "num_steps": 1,
            "output_dir": ""            # default: <run_dir>/profile
        },
        "tracing": {                    # monitor/tracing.py TraceRecorder
            "enabled": false,           # off by default: zero files,
                                        # zero threads when disabled
            "buffer_events": 2048,      # flight-recorder ring capacity
            "max_file_bytes": 16777216, # per-rank trace file byte bound
            "sample_rate": 1.0,         # fraction of steps/requests
                                        # traced (seeded, deterministic)
            "seed": 0,
            "flush_interval_s": 0.5,    # background writer cadence
            "slo": {                    # serving SLO window (ServingSLO)
                "window_s": 10.0,
                "emit_interval_s": 2.0
            }
        }
    }

Unlike the tolerant top-level monitor keys (which predate the strict
convention), the `tracing` block validates like the serving/autotune
blocks: unknown keys and out-of-range values raise at config time.
"""

from ..runtime.config_utils import DeepSpeedConfigObject, get_scalar_param

MONITOR = "monitor"
MONITOR_ENABLED = "enabled"
MONITOR_OUTPUT_PATH = "output_path"
MONITOR_JOB_NAME = "job_name"
MONITOR_FLUSH_INTERVAL = "flush_interval"
MONITOR_SYNC_TIMING = "sync_timing"
MONITOR_FLOPS = "flops"
MONITOR_TOKENS_PER_SAMPLE = "tokens_per_sample"
MONITOR_HEARTBEAT_INTERVAL = "heartbeat_interval"
MONITOR_STRAGGLER_FACTOR = "straggler_factor"
MONITOR_PROFILER = "profiler"
MONITOR_PROFILER_START_STEP = "start_step"
MONITOR_PROFILER_NUM_STEPS = "num_steps"
MONITOR_PROFILER_OUTPUT_DIR = "output_dir"
MONITOR_TRACING = "tracing"
MONITOR_TRACING_ENABLED = "enabled"
MONITOR_TRACING_BUFFER_EVENTS = "buffer_events"
MONITOR_TRACING_MAX_FILE_BYTES = "max_file_bytes"
MONITOR_TRACING_SAMPLE_RATE = "sample_rate"
MONITOR_TRACING_SEED = "seed"
MONITOR_TRACING_FLUSH_INTERVAL_S = "flush_interval_s"
MONITOR_TRACING_SLO = "slo"
MONITOR_TRACING_SLO_WINDOW_S = "window_s"
MONITOR_TRACING_SLO_EMIT_INTERVAL_S = "emit_interval_s"

MONITOR_TRACING_ENABLED_DEFAULT = False
MONITOR_TRACING_BUFFER_EVENTS_DEFAULT = 2048
MONITOR_TRACING_MAX_FILE_BYTES_DEFAULT = 16 << 20
MONITOR_TRACING_SAMPLE_RATE_DEFAULT = 1.0
MONITOR_TRACING_SEED_DEFAULT = 0
MONITOR_TRACING_FLUSH_INTERVAL_S_DEFAULT = 0.5
MONITOR_TRACING_SLO_WINDOW_S_DEFAULT = 10.0
MONITOR_TRACING_SLO_EMIT_INTERVAL_S_DEFAULT = 2.0

_TRACING_VALID_KEYS = frozenset((
    MONITOR_TRACING_ENABLED, MONITOR_TRACING_BUFFER_EVENTS,
    MONITOR_TRACING_MAX_FILE_BYTES, MONITOR_TRACING_SAMPLE_RATE,
    MONITOR_TRACING_SEED, MONITOR_TRACING_FLUSH_INTERVAL_S,
    MONITOR_TRACING_SLO))
_TRACING_SLO_VALID_KEYS = frozenset((
    MONITOR_TRACING_SLO_WINDOW_S, MONITOR_TRACING_SLO_EMIT_INTERVAL_S))


def _tracing_int(d, key, default, lo):
    v = d.get(key, default)
    if isinstance(v, bool) or not isinstance(v, int):
        raise ValueError(
            f"monitor.tracing.{key} must be an int, got {v!r}")
    if v < lo:
        raise ValueError(f"monitor.tracing.{key} must be >= {lo}, got {v}")
    return v


def _tracing_float(d, key, default, lo, hi=None, prefix="monitor.tracing"):
    v = d.get(key, default)
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        raise ValueError(f"{prefix}.{key} must be a number, got {v!r}")
    v = float(v)
    if v <= lo or (hi is not None and v > hi):
        bound = f"in ({lo}, {hi}]" if hi is not None else f"> {lo}"
        raise ValueError(f"{prefix}.{key} must be {bound}, got {v}")
    return v


class DeepSpeedMonitorConfig(DeepSpeedConfigObject):
    def __init__(self, param_dict):
        super().__init__()
        d = param_dict.get(MONITOR, {}) or {}
        self.enabled = bool(get_scalar_param(d, MONITOR_ENABLED, False))
        self.output_path = get_scalar_param(d, MONITOR_OUTPUT_PATH, "runs")
        self.job_name = get_scalar_param(d, MONITOR_JOB_NAME, "run")
        self.flush_interval = int(get_scalar_param(
            d, MONITOR_FLUSH_INTERVAL, 10))
        self.sync_timing = bool(get_scalar_param(
            d, MONITOR_SYNC_TIMING, True))
        self.flops = bool(get_scalar_param(d, MONITOR_FLOPS, True))
        self.tokens_per_sample = get_scalar_param(
            d, MONITOR_TOKENS_PER_SAMPLE, None)
        self.heartbeat_interval = int(get_scalar_param(
            d, MONITOR_HEARTBEAT_INTERVAL, 0))
        self.straggler_factor = float(get_scalar_param(
            d, MONITOR_STRAGGLER_FACTOR, 1.5))
        prof = d.get(MONITOR_PROFILER, {}) or {}
        self.profiler_start_step = int(get_scalar_param(
            prof, MONITOR_PROFILER_START_STEP, -1))
        self.profiler_num_steps = int(get_scalar_param(
            prof, MONITOR_PROFILER_NUM_STEPS, 1))
        self.profiler_output_dir = get_scalar_param(
            prof, MONITOR_PROFILER_OUTPUT_DIR, "")
        self._parse_tracing(d)

    def _parse_tracing(self, d):
        tr = d.get(MONITOR_TRACING, {}) or {}
        if not isinstance(tr, dict):
            raise ValueError(
                f"monitor.tracing must be an object, got {tr!r}")
        unknown = set(tr) - _TRACING_VALID_KEYS
        if unknown:
            raise ValueError(
                f"monitor.tracing: unknown key(s) {sorted(unknown)}; "
                f"valid keys: {sorted(_TRACING_VALID_KEYS)}")
        enabled = tr.get(MONITOR_TRACING_ENABLED,
                         MONITOR_TRACING_ENABLED_DEFAULT)
        if not isinstance(enabled, bool):
            raise ValueError("monitor.tracing.enabled must be a bool, "
                             f"got {enabled!r}")
        self.tracing_enabled = enabled
        if enabled and not self.enabled:
            raise ValueError(
                "monitor.tracing.enabled requires monitor.enabled: the "
                "trace files land in the monitor run dir")
        self.tracing_buffer_events = _tracing_int(
            tr, MONITOR_TRACING_BUFFER_EVENTS,
            MONITOR_TRACING_BUFFER_EVENTS_DEFAULT, 16)
        self.tracing_max_file_bytes = _tracing_int(
            tr, MONITOR_TRACING_MAX_FILE_BYTES,
            MONITOR_TRACING_MAX_FILE_BYTES_DEFAULT, 4096)
        self.tracing_sample_rate = _tracing_float(
            tr, MONITOR_TRACING_SAMPLE_RATE,
            MONITOR_TRACING_SAMPLE_RATE_DEFAULT, 0.0, 1.0)
        self.tracing_seed = _tracing_int(
            tr, MONITOR_TRACING_SEED, MONITOR_TRACING_SEED_DEFAULT, 0)
        self.tracing_flush_interval_s = _tracing_float(
            tr, MONITOR_TRACING_FLUSH_INTERVAL_S,
            MONITOR_TRACING_FLUSH_INTERVAL_S_DEFAULT, 0.0)
        slo = tr.get(MONITOR_TRACING_SLO, {}) or {}
        if not isinstance(slo, dict):
            raise ValueError(
                f"monitor.tracing.slo must be an object, got {slo!r}")
        unknown = set(slo) - _TRACING_SLO_VALID_KEYS
        if unknown:
            raise ValueError(
                f"monitor.tracing.slo: unknown key(s) {sorted(unknown)}; "
                f"valid keys: {sorted(_TRACING_SLO_VALID_KEYS)}")
        self.tracing_slo_window_s = _tracing_float(
            slo, MONITOR_TRACING_SLO_WINDOW_S,
            MONITOR_TRACING_SLO_WINDOW_S_DEFAULT, 0.0,
            prefix="monitor.tracing.slo")
        self.tracing_slo_emit_interval_s = _tracing_float(
            slo, MONITOR_TRACING_SLO_EMIT_INTERVAL_S,
            MONITOR_TRACING_SLO_EMIT_INTERVAL_S_DEFAULT, 0.0,
            prefix="monitor.tracing.slo")
