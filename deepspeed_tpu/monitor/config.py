"""Monitor config block (TPU addition — no reference analogue; the
reference's observability is TensorBoard scalars + rank-0 log lines).

JSON schema:

    "monitor": {
        "enabled": true,
        "output_path": "runs",          # run dirs land under here
        "job_name": "my_run",           # -> <output_path>/<job_name>/
        "flush_interval": 10,           # steps between event-file flushes
        "sync_timing": true,            # block_until_ready before reading
                                        # span clocks (real step time; costs
                                        # one device sync per step)
        "flops": true,                  # achieved-TFLOPs via the flops
                                        # profiler's cost analysis (one
                                        # lowering at first step)
        "tokens_per_sample": 1024,      # optional: emit tokens/s
        "heartbeat_interval": 0,        # steps; >0 enables multi-host
                                        # heartbeats over the hostwire KV
        "straggler_factor": 1.5,        # rank-0 straggler flag threshold
        "profiler": {                   # jax.profiler.trace window
            "start_step": -1,           # -1: disabled
            "num_steps": 1,
            "output_dir": ""            # default: <run_dir>/profile
        }
    }
"""

from ..runtime.config_utils import DeepSpeedConfigObject, get_scalar_param

MONITOR = "monitor"
MONITOR_ENABLED = "enabled"
MONITOR_OUTPUT_PATH = "output_path"
MONITOR_JOB_NAME = "job_name"
MONITOR_FLUSH_INTERVAL = "flush_interval"
MONITOR_SYNC_TIMING = "sync_timing"
MONITOR_FLOPS = "flops"
MONITOR_TOKENS_PER_SAMPLE = "tokens_per_sample"
MONITOR_HEARTBEAT_INTERVAL = "heartbeat_interval"
MONITOR_STRAGGLER_FACTOR = "straggler_factor"
MONITOR_PROFILER = "profiler"
MONITOR_PROFILER_START_STEP = "start_step"
MONITOR_PROFILER_NUM_STEPS = "num_steps"
MONITOR_PROFILER_OUTPUT_DIR = "output_dir"


class DeepSpeedMonitorConfig(DeepSpeedConfigObject):
    def __init__(self, param_dict):
        super().__init__()
        d = param_dict.get(MONITOR, {}) or {}
        self.enabled = bool(get_scalar_param(d, MONITOR_ENABLED, False))
        self.output_path = get_scalar_param(d, MONITOR_OUTPUT_PATH, "runs")
        self.job_name = get_scalar_param(d, MONITOR_JOB_NAME, "run")
        self.flush_interval = int(get_scalar_param(
            d, MONITOR_FLUSH_INTERVAL, 10))
        self.sync_timing = bool(get_scalar_param(
            d, MONITOR_SYNC_TIMING, True))
        self.flops = bool(get_scalar_param(d, MONITOR_FLOPS, True))
        self.tokens_per_sample = get_scalar_param(
            d, MONITOR_TOKENS_PER_SAMPLE, None)
        self.heartbeat_interval = int(get_scalar_param(
            d, MONITOR_HEARTBEAT_INTERVAL, 0))
        self.straggler_factor = float(get_scalar_param(
            d, MONITOR_STRAGGLER_FACTOR, 1.5))
        prof = d.get(MONITOR_PROFILER, {}) or {}
        self.profiler_start_step = int(get_scalar_param(
            prof, MONITOR_PROFILER_START_STEP, -1))
        self.profiler_num_steps = int(get_scalar_param(
            prof, MONITOR_PROFILER_NUM_STEPS, 1))
        self.profiler_output_dir = get_scalar_param(
            prof, MONITOR_PROFILER_OUTPUT_DIR, "")
