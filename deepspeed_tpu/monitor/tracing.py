"""Trace timelines: a bounded, sampled span recorder + serving SLO
windows (TPU addition — no reference analogue; the reference's timeline
story is external profilers).

`TraceRecorder` answers *where the time went* on a per-rank timeline:
structured span events (input host waits, grads dispatches, exposed
exchange waits, apply, ckpt stalls, autotune probes, per-request serving
lifecycle) land in a rank-local `trace.rank*.jsonl` inside the monitor
run dir.  `tools/trace_report.py` merges all ranks into one
Chrome/Perfetto trace-event JSON (pid=rank, tid=subsystem) with
cross-rank clock-skew alignment estimated over the hostwire KV at init.

Always-on-safe by construction:

  * off by default — the recorder only exists when
    `"monitor": {"tracing": {"enabled": true}}`; disabled runs create
    zero files and zero threads, and no instrumentation site ever
    synchronizes a device value (dispatch-side walls only), so traced
    and untraced runs are bitwise identical.
  * sampled — `sample_rate` gates whole steps / requests through a
    seeded hash (deterministic: same seed + schedule => the same event
    sequence, the FaultPlan convention).
  * byte-bounded — the rank file stops growing at `max_file_bytes`
    (dropped writes are counted, never raised).
  * ring-buffered — the last `buffer_events` events survive in memory
    regardless of the file cap; `StepWatchdog` dumps this flight
    recorder into its trip snapshot so a wedged step ships a timeline.

Counters (µs-in-bytes convention does NOT apply here — these are real
bytes/calls): `trace.events` (calls=events recorded, bytes=bytes
written), `trace.dropped` (calls=events the byte cap rejected),
`slo.windows` (calls=slo events emitted).

`ServingSLO` rides the same clock: a sliding window over request
lifecycle observations (TTFT, emitted tokens, queue depth, speculative
accepts, sheds) emitting periodic `slo` monitor events; the p50/p99
are NEAREST-RANK percentiles — the exact definition serve_bench pins —
so the report's "Serving SLO" section reproduces the bench's numbers
when the window covers the lane.
"""

from __future__ import annotations

import collections
import json
import threading
import time
import zlib
from typing import Any, Callable, Dict, List, Optional

from .counters import COUNTERS

TRACE_SCHEMA_VERSION = 1
TRACE_FILE_PREFIX = "trace.rank"

# subsystem categories (the merged trace's tid lanes)
TRACE_CATEGORIES = ("train", "input", "wire", "ckpt", "autotune",
                    "watchdog", "serve", "slo")


def percentile_nearest_rank(sorted_vals: List[float], q: float) -> float:
    """Nearest-rank percentile over an ALREADY-SORTED list — the same
    definition tools/serve_bench.py pins for its TTFT table, duplicated
    here so the SLO window reproduces the bench bit-for-bit."""
    if not sorted_vals:
        return 0.0
    import math

    k = max(0, math.ceil(q / 100.0 * len(sorted_vals)) - 1)
    return sorted_vals[min(k, len(sorted_vals) - 1)]


def _sample_hash(seed: int, key) -> float:
    """Deterministic [0, 1) hash of (seed, key) — crc32, stable across
    processes and runs (unlike hash())."""
    return zlib.crc32(f"{seed}:{key}".encode()) / 2**32


class _SpanCtx:
    """Context manager recording one complete ("X") event on exit."""

    __slots__ = ("_rec", "_name", "_cat", "_args", "_t0")

    def __init__(self, rec: "TraceRecorder", name: str, cat: str,
                 args: Dict[str, Any]):
        self._rec = rec
        self._name = name
        self._cat = cat
        self._args = args
        self._t0 = rec.now_us()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        t0 = self._t0
        self._rec.add_complete(self._name, self._cat, ts_us=t0,
                               dur_us=self._rec.now_us() - t0,
                               **self._args)
        return False


class TraceRecorder:
    """Bounded span recorder; one per rank, owned by RunMonitor.

    `wire`: an optional HostWire — when given, construction performs ONE
    collective allgather so every rank captures its (wall, mono) clock
    pair at an approximately simultaneous instant; the merger aligns
    rank timelines on those sync points, cancelling wall-clock skew.
    `clock`/`wall` are injectable for deterministic tests.
    """

    def __init__(self, run_dir: str, rank: int = 0, world: int = 1, *,
                 buffer_events: int = 2048,
                 max_file_bytes: int = 16 << 20,
                 sample_rate: float = 1.0,
                 seed: int = 0,
                 flush_interval_s: float = 0.5,
                 wire=None,
                 clock: Callable[[], float] = time.perf_counter,
                 wall: Callable[[], float] = time.time):
        import os

        self.rank = int(rank)
        self.world = int(world)
        self.sample_rate = float(sample_rate)
        self.seed = int(seed)
        self.max_file_bytes = int(max_file_bytes)
        self._clock = clock
        self._wall = wall
        self._lock = threading.Lock()
        self._ring: collections.deque = collections.deque(
            maxlen=max(16, int(buffer_events)))
        self._pending: List[str] = []
        self._bytes_written = 0
        self._n_events = 0
        self._n_dropped = 0
        self._closed = False

        os.makedirs(run_dir, exist_ok=True)
        self.path = os.path.join(
            run_dir, f"{TRACE_FILE_PREFIX}{self.rank:05d}.jsonl")
        self._f = open(self.path, "a")

        skew_est_s = self._clock_sync(wire)
        meta = {"type": "trace_meta", "v": TRACE_SCHEMA_VERSION,
                "rank": self.rank, "world": self.world,
                "sync_mono_us": self._sync_mono_us,
                "sync_wall": self._sync_wall,
                "skew_est_s": skew_est_s,
                "sample_rate": self.sample_rate, "seed": self.seed}
        self._f.write(json.dumps(meta) + "\n")
        self._f.flush()

        self._stop = threading.Event()
        self._flush_interval_s = max(0.05, float(flush_interval_s))
        self._thread = threading.Thread(
            target=self._flush_loop, name="dstpu-trace-flush", daemon=True)
        self._thread.start()

    # -- clocks --------------------------------------------------------

    def now_us(self) -> int:
        return int(self._clock() * 1e6)

    def _clock_sync(self, wire) -> Optional[float]:
        """Capture the (wall, mono) pair defining this rank's timeline
        origin.  With a wire, all ranks allgather first so the capture
        happens right after a collective returns — an approximately
        simultaneous instant on every rank (within wire latency), which
        is what lets the merger cancel wall-clock skew."""
        skew_est_s = None
        if wire is not None:
            try:
                payload = json.dumps(
                    {"rank": self.rank, "wall": self._wall()}).encode()
                parts = wire.allgather_bytes(payload)
                peers = []
                for p in parts:
                    try:
                        peers.append(json.loads(p.decode()))
                    except Exception:
                        continue
                sends = [p["wall"] for p in peers if "wall" in p]
                if sends:
                    # my send-time offset from the earliest sender: a
                    # rough per-rank skew indicator for the report (the
                    # ALIGNMENT itself uses the sync instant below)
                    skew_est_s = round(
                        dict((p["rank"], p["wall"]) for p in peers)
                        .get(self.rank, min(sends)) - min(sends), 6)
            except Exception:
                pass  # tracing must never take the run down
        self._sync_wall = self._wall()
        self._sync_mono_us = self.now_us()
        return skew_est_s

    # -- sampling ------------------------------------------------------

    def sampled(self, key) -> bool:
        """Deterministic per-step / per-request gate: same seed + same
        key sequence => the same decisions on every run and rank."""
        if self.sample_rate >= 1.0:
            return True
        return _sample_hash(self.seed, key) < self.sample_rate

    # -- recording -----------------------------------------------------

    def span(self, name: str, cat: str = "train", **args) -> _SpanCtx:
        """Measure a host-side block as one complete event.  Dispatch
        walls only — never synchronizes device values."""
        return _SpanCtx(self, name, cat, args)

    def add_complete(self, name: str, cat: str = "train",
                     ts_us: Optional[int] = None, dur_us: int = 0,
                     **args) -> None:
        """An externally-measured span (e.g. a queue wait whose start
        predates the recording site)."""
        if ts_us is None:
            ts_us = self.now_us() - int(dur_us)
        self._record({"ph": "X", "name": name, "cat": cat,
                      "ts": int(ts_us), "dur": max(0, int(dur_us)),
                      **({"args": args} if args else {})})

    def instant(self, name: str, cat: str = "train", **args) -> None:
        self._record({"ph": "i", "name": name, "cat": cat,
                      "ts": self.now_us(),
                      **({"args": args} if args else {})})

    def _record(self, event: Dict[str, Any]) -> None:
        if self._closed:
            return
        with self._lock:
            self._ring.append(event)
            self._pending.append(json.dumps(event))
            self._n_events += 1

    def last_events(self, n: Optional[int] = None) -> List[Dict[str, Any]]:
        """The flight recorder: a snapshot of the newest events in the
        ring (newest last).  Safe to call from the watchdog thread."""
        with self._lock:
            tail = list(self._ring)
        return tail if n is None else tail[-int(n):]

    # -- writer --------------------------------------------------------

    def _flush_loop(self) -> None:
        while not self._stop.wait(self._flush_interval_s):
            self.flush()

    def flush(self) -> None:
        with self._lock:
            pending, self._pending = self._pending, []
        if not pending:
            return
        wrote = dropped = nbytes = 0
        for line in pending:
            ln = len(line) + 1
            if self._bytes_written + ln > self.max_file_bytes:
                dropped += 1
                continue
            try:
                self._f.write(line + "\n")
            except ValueError:  # closed file under teardown races
                return
            self._bytes_written += ln
            wrote += 1
            nbytes += ln
        if wrote:
            try:
                self._f.flush()
            except ValueError:
                return
            COUNTERS.add("trace.events", nbytes, calls=wrote)
        if dropped:
            self._n_dropped += dropped
            COUNTERS.add("trace.dropped", calls=dropped)

    def close(self) -> None:
        """Stop the flush thread, drain, and write the footer summary.
        Idempotent; the footer rides past the byte cap so a capped file
        still ends with its own accounting."""
        if self._closed:
            return
        self._stop.set()
        self._thread.join(timeout=10)
        self.flush()
        self._closed = True
        footer = {"type": "trace_summary", "rank": self.rank,
                  "events": self._n_events, "dropped": self._n_dropped,
                  "bytes": self._bytes_written}
        try:
            self._f.write(json.dumps(footer) + "\n")
            self._f.flush()
            self._f.close()
        except ValueError:
            pass


def read_trace_file(path: str):
    """Parse one rank's trace JSONL into ([(meta, events), ...],
    summary).  A restarted run appends a fresh meta line; events belong
    to the meta that precedes them (one segment per process lifetime,
    each with its own clock origin), so the merger aligns per
    segment."""
    segments = []
    meta, events, summary = None, [], None
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
            except Exception:
                continue
            t = obj.get("type")
            if t == "trace_meta":
                if meta is not None:
                    segments.append((meta, events))
                meta, events = obj, []
            elif t == "trace_summary":
                summary = obj
            elif "ph" in obj:
                events.append(obj)
    if meta is not None:
        segments.append((meta, events))
    return segments, summary


class ServingSLO:
    """Sliding-window serving telemetry: p50/p99 TTFT (nearest-rank,
    the serve_bench definition), tokens/s, mean queue depth, speculative
    acceptance rate, shed count.  `tick()` (called from the serve loop)
    emits an `slo` monitor event every `emit_interval_s`; `force()`
    emits unconditionally (lane teardown).  Clock injectable — serving
    tests drive a fake clock."""

    def __init__(self, emit: Optional[Callable[[Dict[str, Any]], None]]
                 = None, window_s: float = 10.0,
                 emit_interval_s: float = 2.0,
                 clock: Callable[[], float] = time.monotonic,
                 tracer: Optional[TraceRecorder] = None):
        if window_s <= 0 or emit_interval_s <= 0:
            raise ValueError("ServingSLO: window_s and emit_interval_s "
                             "must be > 0")
        self.emit = emit
        self.window_s = float(window_s)
        self.emit_interval_s = float(emit_interval_s)
        self.clock = clock
        self.tracer = tracer
        self._ttft: collections.deque = collections.deque()
        self._tokens: collections.deque = collections.deque()
        self._queue: collections.deque = collections.deque()
        self._accept: collections.deque = collections.deque()
        self._shed: collections.deque = collections.deque()
        self._last_emit: Optional[float] = None
        self.windows_emitted = 0

    # -- observations --------------------------------------------------

    def _now(self, t: Optional[float]) -> float:
        return self.clock() if t is None else float(t)

    def observe_ttft(self, ttft_s: float, t: Optional[float] = None):
        self._ttft.append((self._now(t), float(ttft_s) * 1e3))

    def observe_tokens(self, n: int, t: Optional[float] = None):
        if n:
            self._tokens.append((self._now(t), int(n)))

    def observe_queue_depth(self, depth: int, t: Optional[float] = None):
        self._queue.append((self._now(t), int(depth)))

    def observe_accept(self, accepted: int, drafted: int,
                       t: Optional[float] = None):
        self._accept.append((self._now(t), int(accepted), int(drafted)))

    def observe_shed(self, n: int = 1, t: Optional[float] = None):
        self._shed.append((self._now(t), int(n)))

    def _trim(self, now: float) -> None:
        cutoff = now - self.window_s
        for dq in (self._ttft, self._tokens, self._queue, self._accept,
                   self._shed):
            while dq and dq[0][0] < cutoff:
                dq.popleft()

    # -- window math ---------------------------------------------------

    def snapshot(self, t: Optional[float] = None) -> Dict[str, Any]:
        now = self._now(t)
        self._trim(now)
        ttfts = sorted(ms for _, ms in self._ttft)
        toks = sum(n for _, n in self._tokens)
        # tokens/s over the span the window actually covers, not the
        # nominal width — a 2 s old lane must not read as 1/5 the rate
        tmin = min((dq[0][0] for dq in (self._tokens, self._ttft)
                    if dq), default=now)
        span = min(self.window_s, max(now - tmin, 1e-9))
        depths = [d for _, d in self._queue]
        acc = sum(a for _, a, _d in self._accept)
        drafted = sum(d for _, _a, d in self._accept)
        return {
            "window_s": self.window_s,
            "requests": len(ttfts),
            "ttft_ms": {
                "p50": round(percentile_nearest_rank(ttfts, 50), 3),
                "p99": round(percentile_nearest_rank(ttfts, 99), 3),
                "n": len(ttfts)},
            "tok_per_s": round(toks / span, 2) if toks else 0.0,
            "queue_depth_mean": (round(sum(depths) / len(depths), 2)
                                 if depths else 0.0),
            "accept_rate": (round(acc / drafted, 4) if drafted else None),
            "drafted": drafted,
            "shed": sum(n for _, n in self._shed),
        }

    # -- emission ------------------------------------------------------

    def tick(self, t: Optional[float] = None) -> Optional[Dict[str, Any]]:
        now = self._now(t)
        if self._last_emit is None:
            self._last_emit = now
            return None
        if now - self._last_emit < self.emit_interval_s:
            return None
        return self.force(now)

    def force(self, t: Optional[float] = None) -> Dict[str, Any]:
        now = self._now(t)
        snap = self.snapshot(now)
        self._last_emit = now
        self.windows_emitted += 1
        COUNTERS.add("slo.windows", calls=1)
        if self.tracer is not None:
            self.tracer.instant("slo_window", "slo",
                                p99_ttft_ms=snap["ttft_ms"]["p99"],
                                tok_per_s=snap["tok_per_s"])
        if self.emit is not None:
            self.emit(snap)
        return snap
