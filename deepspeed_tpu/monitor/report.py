"""Run-report rendering: JSONL event stream -> BENCH.md-style table.

Shared by `tools/run_report.py` (CLI) and the tests; keeps every schema
assumption in one place next to the writer (monitor.py)."""

from __future__ import annotations

import glob
import json
import os
from typing import Any, Dict, List, Optional

SCHEMA_VERSION = 1

# required keys per event type; value is the required python type(s)
_STEP_REQUIRED = {"v": int, "type": str, "rank": int, "t": (int, float),
                  "step": int}


def validate_event(event: Dict[str, Any]) -> List[str]:
    """Return a list of schema violations (empty = valid)."""
    errs = []
    if not isinstance(event, dict):
        return ["event is not an object"]
    for key, typ in _STEP_REQUIRED.items():
        if event.get("type") != "step" and key == "step":
            continue
        if key not in event:
            errs.append(f"missing key {key!r}")
        elif not isinstance(event[key], typ):
            errs.append(f"key {key!r} has type {type(event[key]).__name__}")
    if isinstance(event.get("v"), int) and event["v"] > SCHEMA_VERSION:
        errs.append(f"schema version {event['v']} is newer than reader "
                    f"({SCHEMA_VERSION})")
    if event.get("type") == "slo" and not isinstance(event.get("slo"),
                                                     dict):
        errs.append("slo event missing its 'slo' snapshot object")
    return errs


def read_events(path: str) -> List[Dict[str, Any]]:
    events = []
    with open(path) as f:
        for i, line in enumerate(f):
            line = line.strip()
            if not line:
                continue
            try:
                events.append(json.loads(line))
            except json.JSONDecodeError as e:
                raise ValueError(f"{path}:{i + 1}: invalid JSON: {e}")
    return events


def load_run(run_dir: str) -> Dict[str, Any]:
    """Load a run directory: manifest (optional) + every rank's events
    + the supervisor restart ledger and watchdog trip file when
    present (elasticity/supervisor.py, runtime/resilience.py)."""
    manifest = None
    mpath = os.path.join(run_dir, "manifest.json")
    if os.path.exists(mpath):
        with open(mpath) as f:
            manifest = json.load(f)
    ranks: Dict[int, List[Dict[str, Any]]] = {}
    for path in sorted(glob.glob(os.path.join(run_dir,
                                              "events.rank*.jsonl"))):
        events = read_events(path)
        rank = int(os.path.basename(path)[len("events.rank"):-len(".jsonl")])
        ranks[rank] = events
    # a serving-bench run dir (tools/serve_bench.py) carries its lane
    # table as serving.json — with it present, telemetry event streams
    # are optional (a pure serving run has no training steps to report)
    serving = None
    serving_err = None
    spath = os.path.join(run_dir, "serving.json")
    if os.path.exists(spath):
        try:
            with open(spath) as f:
                serving = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            serving_err = e
    if not ranks and serving is None:
        if serving_err is not None:
            # a serving-only dir with a torn serving.json: name the
            # REAL defect instead of claiming telemetry is missing
            raise ValueError(
                f"{spath}: unreadable serving.json "
                f"({type(serving_err).__name__}: {serving_err}) and no "
                f"events.rank*.jsonl to fall back on")
        raise FileNotFoundError(
            f"no events.rank*.jsonl under {run_dir!r}")
    restarts = _read_jsonl_ledger(os.path.join(run_dir, "restarts.jsonl"))
    watchdog_trip = None
    wpath = os.path.join(run_dir, "watchdog_trip.json")
    if os.path.exists(wpath):
        try:
            with open(wpath) as f:
                watchdog_trip = json.load(f)
        except (OSError, json.JSONDecodeError):
            watchdog_trip = None
    # the autotune ledger (runtime/autotune/runtime.py, rank 0):
    # search/cache_hit/retune/swap events, rendered as the "Autotune"
    # section's event table
    autotune = _read_jsonl_ledger(os.path.join(run_dir, "autotune.jsonl"))
    return {"dir": run_dir, "manifest": manifest, "ranks": ranks,
            "restarts": restarts, "watchdog_trip": watchdog_trip,
            "serving": serving, "autotune": autotune}


def _read_jsonl_ledger(path: str) -> List[Dict[str, Any]]:
    """Best-effort append-only ledger reader (restarts.jsonl,
    autotune.jsonl): blank lines and the torn tail of a live writer are
    skipped, a missing file is an empty ledger."""
    rows: List[Dict[str, Any]] = []
    if not os.path.exists(path):
        return rows
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rows.append(json.loads(line))
            except json.JSONDecodeError:
                continue  # torn tail of a live ledger
    return rows


def _mean(xs):
    xs = [x for x in xs if x is not None]
    return sum(xs) / len(xs) if xs else None


def summarize(events: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Aggregate one rank's event list."""
    steps = [e for e in events if e.get("type") == "step"]
    hbs = [e for e in events if e.get("type") == "heartbeat"]
    comm: Dict[str, Dict[str, int]] = {}
    for e in steps:
        for name, d in (e.get("comm") or {}).items():
            acc = comm.setdefault(name, {"calls": 0, "bytes": 0})
            acc["calls"] += int(d.get("calls", 0))
            acc["bytes"] += int(d.get("bytes", 0))
    spans: Dict[str, float] = {}
    for e in steps:
        for name, ms in (e.get("spans_ms") or {}).items():
            spans[name] = spans.get(name, 0.0) + float(ms)
    losses = [e.get("loss") for e in steps if e.get("loss") is not None]
    mems = [e.get("memory") for e in steps if e.get("memory")]
    peak = max((m.get("peak_bytes_in_use_sum", 0) for m in mems),
               default=None) if mems else None
    pipe = next((e.get("pipe") for e in reversed(steps)
                 if e.get("pipe")), None)
    stragglers = sorted({r for e in hbs for r in (e.get("stragglers") or [])})
    return {
        "n_steps": len(steps),
        "first_step": steps[0]["step"] if steps else None,
        "last_step": steps[-1]["step"] if steps else None,
        "mean_wall_ms": _mean([e.get("wall_ms") for e in steps]),
        "mean_samples_per_sec": _mean([e.get("samples_per_sec")
                                       for e in steps]),
        "mean_tokens_per_sec": _mean([e.get("tokens_per_sec")
                                      for e in steps]),
        "mean_tflops": _mean([e.get("tflops") for e in steps]),
        "first_loss": losses[0] if losses else None,
        "last_loss": losses[-1] if losses else None,
        "skipped_steps": max((e.get("skipped_steps", 0) for e in steps),
                             default=0),
        "comm": comm,
        "spans_ms_total": spans,
        "peak_bytes_in_use_sum": peak,
        "pipe": pipe,
        "stragglers": stragglers,
    }


def _fmt(x, nd=2, unit=""):
    if x is None:
        return "—"
    if isinstance(x, float):
        return f"{x:,.{nd}f}{unit}"
    return f"{x:,}{unit}"


def _fmt_bytes(b):
    if b is None:
        return "—"
    for mag, suffix in ((1 << 30, "GiB"), (1 << 20, "MiB"), (1 << 10, "KiB")):
        if b >= mag:
            return f"{b / mag:.2f} {suffix}"
    return f"{b} B"


def render_markdown(run: Dict[str, Any]) -> str:
    """BENCH.md-style report for a loaded run (load_run output)."""
    lines = [f"# Run report: `{run['dir']}`", ""]
    man = run.get("manifest")
    if man:
        lines.append(f"schema v{man.get('schema_version', '?')} · "
                     f"backend {man.get('backend', '?')} · "
                     f"{man.get('device_count', '?')} device(s) · "
                     f"world {man.get('world_size', '?')}")
        lines.append("")
    if run["ranks"]:
        lines.append("| rank | steps | wall ms/step | samples/s | tokens/s "
                     "| TFLOPs | loss first→last | skipped | peak mem |")
        lines.append("|---|---|---|---|---|---|---|---|---|")
    summaries = {}
    for rank in sorted(run["ranks"]):
        s = summarize(run["ranks"][rank])
        summaries[rank] = s
        loss = (f"{_fmt(s['first_loss'], 4)} → {_fmt(s['last_loss'], 4)}"
                if s["first_loss"] is not None else "—")
        lines.append(
            f"| {rank} | {s['n_steps']} | {_fmt(s['mean_wall_ms'])} | "
            f"{_fmt(s['mean_samples_per_sec'], 1)} | "
            f"{_fmt(s['mean_tokens_per_sec'], 1)} | "
            f"{_fmt(s['mean_tflops'])} | {loss} | {s['skipped_steps']} | "
            f"{_fmt_bytes(s['peak_bytes_in_use_sum'])} |")
    lines.append("")

    any_comm = {}
    for s in summaries.values():
        for name, d in s["comm"].items():
            acc = any_comm.setdefault(name, {"calls": 0, "bytes": 0})
            acc["calls"] += d["calls"]
            acc["bytes"] += d["bytes"]
    # input.*/ckpt.*/fault.*/watchdog.* counters carry pipeline/
    # checkpoint/resilience metrics (µs, queue depths, injection
    # counts), not wire bytes — split them out of the comm table into
    # their own sections
    input_counters = {k: v for k, v in any_comm.items()
                      if k.startswith("input.")}
    ckpt_counters = {k: v for k, v in any_comm.items()
                     if k.startswith("ckpt.")}
    # grad_wire.exposed_ms / qwz.prefetch_hits carry µs (the
    # ckpt.stall_ms convention), not wire bytes — they render in the
    # gradient-wire section below, not the comm byte table
    _WIRE_TIME_COUNTERS = ("grad_wire.exposed_ms", "qwz.prefetch_hits")
    # elastic.* counts world-size transitions (shrinks/regrows), not
    # wire bytes — Resilience rows, like fault.*; serve.*/kv.* carry
    # serving-engine metrics (tokens, µs, block occupancy) and render
    # as the "Serving" section below
    # moe.* carries MoE-wire metrics (hop bytes, µs, drop counts, ppm
    # occupancy) and renders as the "MoE wire" section below
    # autotune.* carries search/retune bookkeeping (probe µs in the
    # bytes slot, swap/rejection counts) and renders as the "Autotune"
    # section below
    # trace.*/slo.* carry trace-recorder bookkeeping (JSONL bytes,
    # drop counts, SLO window counts), not wire bytes — rendered as
    # the "Serving SLO" section's Tracing rows below
    # kernel.* counts registry dispatches (Pallas vs jnp-fallback
    # resolutions), not wire bytes — the "Kernels" section below
    wire_counters = {k: v for k, v in any_comm.items()
                     if not k.startswith(("input.", "ckpt.", "fault.",
                                          "watchdog.", "exchange.",
                                          "elastic.", "serve.", "kv.",
                                          "router.", "moe.", "autotune.",
                                          "trace.", "slo.", "kernel."))
                     and k not in _WIRE_TIME_COUNTERS}
    if wire_counters:
        lines.append("## Comm counters (all ranks, whole run)")
        lines.append("")
        lines.append("| counter | calls | bytes |")
        lines.append("|---|---|---|")
        for name in sorted(wire_counters):
            d = wire_counters[name]
            lines.append(f"| `{name}` | {d['calls']:,} | "
                         f"{_fmt_bytes(d['bytes'])} |")
        lines.append("")

    if input_counters:
        lines.append("## Input pipeline")
        lines.append("")
        lines.append("| metric | value |")
        lines.append("|---|---|")
        hw = input_counters.get("input.host_wait_ms")
        if hw:
            total_ms = hw["bytes"] / 1000.0  # stored as integer µs
            per = total_ms / hw["calls"] if hw["calls"] else 0.0
            lines.append(f"| host wait (batch fetch) | {total_ms:,.1f} ms "
                         f"total over {hw['calls']:,} fetches "
                         f"({per:.2f} ms/fetch) |")
        h2d = input_counters.get("input.h2d_bytes")
        if h2d:
            lines.append(f"| H2D batch transfer | "
                         f"{_fmt_bytes(h2d['bytes'])} over "
                         f"{h2d['calls']:,} device_put dispatches |")
        qd = input_counters.get("input.queue_depth")
        if qd and qd["calls"]:
            lines.append(f"| mean prefetch queue depth | "
                         f"{qd['bytes'] / qd['calls']:.2f} "
                         f"(sampled at {qd['calls']:,} pops) |")
        rep = input_counters.get("input.replicated_batches")
        if rep:
            lines.append(f"| replicated (indivisible) batches | "
                         f"{rep['calls']:,} x dp-replicated, "
                         f"{_fmt_bytes(rep['bytes'])} |")
        lines.append("")

    if ckpt_counters:
        lines.append("## Checkpointing")
        lines.append("")
        lines.append("| metric | value |")
        lines.append("|---|---|")
        stall = ckpt_counters.get("ckpt.stall_ms")
        if stall:
            total_ms = stall["bytes"] / 1000.0  # stored as integer µs
            per = total_ms / stall["calls"] if stall["calls"] else 0.0
            lines.append(f"| training stall (blocked in save) | "
                         f"{total_ms:,.1f} ms total over "
                         f"{stall['calls']:,} saves "
                         f"({per:.2f} ms/save) |")
        cb = ckpt_counters.get("ckpt.bytes")
        if cb:
            lines.append(f"| committed checkpoint bytes | "
                         f"{_fmt_bytes(cb['bytes'])} over {cb['calls']:,} "
                         f"committed tag(s) |")
        pend = ckpt_counters.get("ckpt.pending")
        if pend and pend["calls"]:
            lines.append(f"| mean async writer queue depth | "
                         f"{pend['bytes'] / pend['calls']:.2f} "
                         f"(sampled at {pend['calls']:,} saves) |")
        lines.append("")

    # serving engine counters (deepspeed_tpu/serving): requests/tokens
    # decoded, batch occupancy, KV block pressure — their own section,
    # like input.*/ckpt.*
    serve_counters = {k: v for k, v in any_comm.items()
                      if k.startswith(("serve.", "kv."))}
    if serve_counters:
        lines.append("## Serving")
        lines.append("")
        lines.append("| metric | value |")
        lines.append("|---|---|")
        reqs = serve_counters.get("serve.requests")
        if reqs:
            lines.append(f"| requests completed | {reqs['calls']:,} "
                         f"({reqs['bytes']:,} tokens generated) |")
        toks = serve_counters.get("serve.tokens")
        if toks:
            lines.append(f"| tokens decoded | {toks['calls']:,} |")
        dec = serve_counters.get("serve.decode_steps")
        if dec and dec["calls"]:
            lines.append(f"| decode steps | {dec['calls']:,} (mean batch "
                         f"occupancy {dec['bytes'] / dec['calls']:.2f} "
                         f"slots) |")
        pre = serve_counters.get("serve.prefill_chunks")
        if pre:
            lines.append(f"| prefill chunks | {pre['calls']:,} "
                         f"({pre['bytes']:,} prompt tokens) |")
        ttft = serve_counters.get("serve.ttft_ms")
        if ttft and ttft["calls"]:
            total_ms = ttft["bytes"] / 1000.0  # stored as integer µs
            lines.append(f"| mean time-to-first-token | "
                         f"{total_ms / ttft['calls']:.2f} ms over "
                         f"{ttft['calls']:,} first tokens |")
        blk = serve_counters.get("kv.blocks_in_use")
        if blk and blk["calls"]:
            lines.append(f"| mean KV blocks in use | "
                         f"{blk['bytes'] / blk['calls']:.2f} "
                         f"(sampled at {blk['calls']:,} steps) |")
        ev = serve_counters.get("kv.evictions")
        if ev:
            lines.append(f"| KV blocks force-reclaimed (evictions) | "
                         f"{ev['calls']:,} |")
        shed = serve_counters.get("serve.shed")
        if shed:
            lines.append(f"| requests shed (wedged decode) | "
                         f"{shed['calls']:,} |")
        # speculative decoding (serve.draft_tokens/accepted_tokens,
        # kv.dequant_ms) — rendered as sub-rows of the same table
        drafts = serve_counters.get("serve.draft_tokens")
        acc = serve_counters.get("serve.accepted_tokens")
        dq = serve_counters.get("kv.dequant_ms")
        if drafts or acc or dq:
            lines.append("| **Speculative decoding** | |")
            if drafts:
                rate = (f" ({acc['calls'] / drafts['calls']:.0%} accepted)"
                        if acc and drafts["calls"] else "")
                lines.append(f"| draft tokens proposed | "
                             f"{drafts['calls']:,}{rate} |")
            if acc:
                per = ""
                if dec and dec["calls"]:
                    per = (f" (+{acc['calls'] / dec['calls']:.2f} bonus "
                           f"tokens/step)")
                lines.append(f"| draft tokens accepted | "
                             f"{acc['calls']:,}{per} |")
            if dq and dq["calls"]:
                total_ms = dq["bytes"] / 1000.0  # stored as integer µs
                lines.append(f"| quantized-KV decode dispatch | "
                             f"{total_ms:,.1f} ms total over "
                             f"{dq['calls']:,} dispatches "
                             f"({total_ms / dq['calls']:.2f} ms each) |")
        # prefix caching + pinned sessions (kv.prefix_*, kv.cow_copies,
        # kv.session_pins) — sub-rows like speculative decoding
        hits = serve_counters.get("kv.prefix_hits")
        hit_tok = serve_counters.get("kv.prefix_hit_tokens")
        cow = serve_counters.get("kv.cow_copies")
        pins = serve_counters.get("kv.session_pins")
        pev = serve_counters.get("kv.prefix_evictions")
        if hits or hit_tok or cow or pins or pev:
            lines.append("| **Prefix cache** | |")
            if hits:
                lines.append(f"| prefix-hit admissions | "
                             f"{hits['calls']:,} "
                             f"({hits['bytes']:,} blocks aliased) |")
            if hit_tok:
                rate = ""
                if pre and (hit_tok["bytes"] + pre["bytes"]):
                    frac = (hit_tok["bytes"] /
                            (hit_tok["bytes"] + pre["bytes"]))
                    rate = f" ({frac:.0%} of prefill tokens)"
                lines.append(f"| prompt tokens skipped | "
                             f"{hit_tok['bytes']:,}{rate} |")
            if cow:
                lines.append(f"| copy-on-write privatizations | "
                             f"{cow['calls']:,} "
                             f"({_fmt_bytes(cow['bytes'])} copied) |")
            if pins:
                lines.append(f"| session pins | {pins['calls']:,} "
                             f"({pins['bytes']:,} blocks held) |")
            if pev:
                lines.append(f"| cached blocks reclaimed (LRU) | "
                             f"{pev['calls']:,} |")
        lines.append("")

    # fleet router counters (serving/router.py): dispatch balance,
    # queue spill-over, front-door shedding — their own section
    router_counters = {k: v for k, v in any_comm.items()
                      if k.startswith("router.")}
    if router_counters:
        lines.append("## Fleet router")
        lines.append("")
        lines.append("| metric | value |")
        lines.append("|---|---|")
        disp = router_counters.get("router.dispatches")
        if disp and disp["calls"]:
            lines.append(f"| requests dispatched | {disp['calls']:,} "
                         f"(mean load at dispatch "
                         f"{disp['bytes'] / disp['calls']:.2f} KV "
                         f"blocks) |")
        spill = router_counters.get("router.spills")
        if spill:
            lines.append(f"| queue spill-overs | {spill['calls']:,} |")
        rshed = router_counters.get("router.shed")
        if rshed:
            lines.append(f"| requests shed at front door | "
                         f"{rshed['calls']:,} |")
        lines.append("")

    # live SLO telemetry: monitor.tracing.ServingSLO windows land in
    # the event stream as type="slo" events; trace.*/slo.* counters
    # (excluded from the comm byte table above) ride along as the
    # Tracing rows
    slo_events = [e for rank in sorted(run["ranks"])
                  for e in run["ranks"][rank]
                  if e.get("type") == "slo"
                  and isinstance(e.get("slo"), dict)]
    trace_counters = {k: v for k, v in any_comm.items()
                      if k.startswith(("trace.", "slo."))}
    if slo_events or trace_counters:
        lines.append("## Serving SLO")
        lines.append("")
        lines.append("| metric | value |")
        lines.append("|---|---|")
        if slo_events:
            last = slo_events[-1]["slo"]
            ttft = last.get("ttft_ms") or {}
            p99s = [(e["slo"].get("ttft_ms") or {}).get("p99")
                    for e in slo_events]
            p99s = [p for p in p99s if p is not None]
            lines.append(f"| SLO windows emitted | {len(slo_events):,} "
                         f"({last.get('window_s', '?')} s sliding) |")
            lines.append(f"| last window: requests | "
                         f"{last.get('requests', 0):,} |")
            if ttft.get("p50") is not None:
                lines.append(f"| last window: TTFT p50/p99 | "
                             f"{_fmt(ttft.get('p50'))} / "
                             f"{_fmt(ttft.get('p99'))} ms "
                             f"(n={ttft.get('n', 0)}) |")
            if last.get("tok_per_s") is not None:
                lines.append(f"| last window: decode throughput | "
                             f"{_fmt(last['tok_per_s'])} tokens/s |")
            if last.get("queue_depth_mean") is not None:
                lines.append(f"| last window: mean admission queue "
                             f"depth | {_fmt(last['queue_depth_mean'])} |")
            if last.get("accept_rate") is not None:
                lines.append(f"| last window: draft accept rate | "
                             f"{100.0 * last['accept_rate']:.1f}% "
                             f"({last.get('drafted', 0):,} drafted) |")
            if last.get("shed"):
                lines.append(f"| last window: requests shed | "
                             f"{last['shed']:,} |")
            if p99s:
                lines.append(f"| worst window TTFT p99 | "
                             f"{_fmt(max(p99s))} ms |")
        if trace_counters:
            lines.append("| **Tracing** | |")
            tev = trace_counters.get("trace.events")
            if tev:
                lines.append(f"| trace events recorded | {tev['calls']:,} "
                             f"({_fmt_bytes(tev['bytes'])} JSONL) |")
            tdr = trace_counters.get("trace.dropped")
            if tdr:
                lines.append(f"| trace events dropped (byte cap) | "
                             f"{tdr['calls']:,} |")
            wnd = trace_counters.get("slo.windows")
            if wnd:
                lines.append(f"| SLO windows aggregated | "
                             f"{wnd['calls']:,} |")
        lines.append("")

    # serving-bench lane table (serving.json from tools/serve_bench.py)
    sv = run.get("serving")
    if sv and sv.get("lanes"):
        lines.append("## Serving bench (continuous batching)")
        lines.append("")
        m = sv.get("model") or {}
        if m:
            lines.append(f"model: {m.get('layers', '?')}L x "
                         f"d{m.get('d_model', '?')} x "
                         f"{m.get('heads', '?')}h, vocab "
                         f"{m.get('vocab', '?')} · "
                         f"{sv.get('n_requests', '?')} requests, Poisson "
                         f"{sv.get('rate_hz', '?')}/s")
            lines.append("")
        lines.append("| lane | done | tokens | tokens/s | TTFT p50/p99 ms "
                     "| ITL p50/p99 ms | KV blocks mean/peak | shed |")
        lines.append("|---|---|---|---|---|---|---|---|")
        for name in sorted(sv["lanes"]):
            lane = sv["lanes"][name]
            if "requests" not in lane:
                continue  # session lanes render below, not as ?/? rows
            ttft_l, itl = lane.get("ttft_ms", {}), lane.get("itl_ms", {})
            kvb = lane.get("kv_blocks", {})
            lines.append(
                f"| {name} | {lane.get('completed', '?')}/"
                f"{lane.get('requests', '?')} | "
                f"{_fmt(lane.get('tokens'), 0)} | "
                f"{_fmt(lane.get('tokens_per_sec'))} | "
                f"{_fmt(ttft_l.get('p50'))} / {_fmt(ttft_l.get('p99'))} | "
                f"{_fmt(itl.get('p50'))} / {_fmt(itl.get('p99'))} | "
                f"{_fmt(kvb.get('mean'))} / {_fmt(kvb.get('peak'), 0)} "
                f"(cap {_fmt(kvb.get('capacity'), 0)}) | "
                f"{lane.get('shed', 0)} |")
        spec_lanes = {n: l for n, l in sv["lanes"].items()
                      if l.get("accepted_per_step") is not None}
        if spec_lanes:
            lines.append("")
            lines.append("Speculative decoding lanes (extra accepted "
                         "draft tokens per decode step):")
            for name in sorted(spec_lanes):
                lane = spec_lanes[name]
                lines.append(f"- {name}: "
                             f"+{lane['accepted_per_step']:.2f} tok/step "
                             f"(kv {lane.get('kv_dtype', 'dense')}, "
                             f"draft {lane.get('draft_len', 0)})")
        pfx_lanes = {n: l for n, l in sv["lanes"].items()
                     if l.get("prefix_hit_rate") is not None
                     and "requests" in l}
        if any(l["prefix_hit_rate"] > 0 for l in pfx_lanes.values()):
            lines.append("")
            lines.append("Prefix-cache lanes (fraction of prompt tokens "
                         "served from cache):")
            for name in sorted(pfx_lanes):
                lane = pfx_lanes[name]
                per = lane.get("dispatch_per_replica")
                lines.append(
                    f"- {name}: {lane['prefix_hit_rate']:.1%} hit rate"
                    + (f", dispatches/replica {per}" if per else ""))
        ses_lanes = {n: l for n, l in sv["lanes"].items()
                     if "turn2plus_ttft_ms" in l}
        if ses_lanes:
            lines.append("")
            lines.append("Session lanes (multi-turn; TTFT on turns >= 2):")
            for name in sorted(ses_lanes):
                lane = ses_lanes[name]
                t = lane["turn2plus_ttft_ms"]
                lines.append(
                    f"- {name}: TTFT p50 {_fmt(t.get('p50'))} ms, "
                    f"prefill tokens computed "
                    f"{_fmt(lane.get('prefill_tokens_computed'), 0)}, "
                    f"served from cache "
                    f"{_fmt(lane.get('prefix_hit_tokens'), 0)}")
        cont = sv["lanes"].get("continuous")
        stat = sv["lanes"].get("static")
        if cont and stat and cont.get("tokens_per_sec") and \
                stat.get("tokens_per_sec"):
            lines.append("")
            lines.append(
                f"continuous vs static batching: "
                f"{cont['tokens_per_sec'] / stat['tokens_per_sec']:.2f}x "
                f"tokens/s at p99 TTFT "
                f"{_fmt(cont.get('ttft_ms', {}).get('p99'))} vs "
                f"{_fmt(stat.get('ttft_ms', {}).get('p99'))} ms")
        lines.append("")

    # resilience: fault injection + transient-retry + watchdog activity
    # (runtime/resilience.py) — a run that absorbed faults should say
    # so in its report, not hide it in the counter soup
    res_rows = []
    inj = any_comm.get("fault.injected")
    if inj:
        res_rows.append(f"| faults injected | {inj['calls']:,} |")
    ret = any_comm.get("fault.retried")
    if ret:
        res_rows.append(f"| transient retries | {ret['calls']:,} |")
    rec = any_comm.get("fault.recovered_ms")
    if rec:
        total_ms = rec["bytes"] / 1000.0  # stored as integer µs
        res_rows.append(f"| time to recover (retry backoff, wall) | "
                        f"{total_ms:,.1f} ms over {rec['calls']:,} "
                        f"recovered op(s) |")
    trips = any_comm.get("watchdog.trips")
    if trips:
        res_rows.append(f"| watchdog trips | {trips['calls']:,} |")
    resp = any_comm.get("input.worker_respawns")
    if resp:
        res_rows.append(f"| prefetch workers respawned | "
                        f"{resp['calls']:,} |")
    skip = any_comm.get("ckpt.skipped_tags")
    if skip:
        res_rows.append(f"| uncommitted checkpoint tags skipped | "
                        f"{skip['calls']:,} |")
    # overlap-exchange self-healing (runtime/comm/overlap.py): healed
    # connection drops, replayed frames, and coordinated demotions to
    # the serial wire — `exchange.resends` bytes are replayed payload
    recon = any_comm.get("exchange.reconnects")
    if recon:
        res_rows.append(f"| exchange connections healed (reconnects) | "
                        f"{recon['calls']:,} |")
    rsnd = any_comm.get("exchange.resends")
    if rsnd:
        res_rows.append(f"| exchange frames resent after reconnect | "
                        f"{rsnd['calls']:,} ({rsnd['bytes']:,} B "
                        f"replayed) |")
    dem = any_comm.get("exchange.demotions")
    if dem:
        res_rows.append(f"| overlap wire demotions to the serial path | "
                        f"{dem['calls']:,} |")
    # elastic world-size transitions consumed on restore
    # (engine._log_checkpoint_reshard; the supervisor side renders in
    # the "Elastic transitions" ledger block below)
    shr = any_comm.get("elastic.shrinks")
    if shr:
        res_rows.append(f"| elastic shrinks (resumed at a smaller dp) | "
                        f"{shr['calls']:,} |")
    reg = any_comm.get("elastic.regrows")
    if reg:
        res_rows.append(f"| elastic regrows (resumed at a larger dp) | "
                        f"{reg['calls']:,} |")
    wd = run.get("watchdog_trip")
    if wd:
        res_rows.append(f"| last watchdog trip | rank "
                        f"{wd.get('rank', '?')}: "
                        f"{wd.get('reason', '?')} (snapshot: "
                        f"`{wd.get('snapshot', '—')}`) |")
    if res_rows:
        lines.append("## Resilience")
        lines.append("")
        lines.append("| metric | value |")
        lines.append("|---|---|")
        lines.extend(res_rows)
        lines.append("")

    # supervisor restart ledger (elasticity/supervisor.py restarts.jsonl)
    restarts = run.get("restarts") or []
    if restarts:
        lines.append("## Restarts (supervisor ledger)")
        lines.append("")
        lines.append("| # | event | reason | ran for | exit | "
                     "dead ranks | backoff | diagnostics |")
        lines.append("|---|---|---|---|---|---|---|---|")
        for i, r in enumerate(restarts):
            dead = ",".join(str(d) for d in (r.get("dead_ranks") or [])) \
                or "—"
            backoff = (f"{r['backoff_s']:.1f}s"
                       if r.get("backoff_s") is not None else "—")
            diag = f"`{r['diagnostics']}`" if r.get("diagnostics") else "—"
            lines.append(
                f"| {i + 1} | {r.get('event', 'restart')} | "
                f"{r.get('reason', '?')} | "
                f"{_fmt(r.get('ran_for_s'), 1, 's')} | "
                f"{r.get('exit_code', '—')} | {dead} | {backoff} | "
                f"{diag} |")
        lines.append("")

    # elastic world-size transitions out of the same ledger
    # (supervisor --elastic-shrink: relaunch on the survivors, grow
    # back when capacity returns) — their own block beside Restarts so
    # the shrink->grow story reads without grepping reasons
    transitions = [r for r in restarts
                   if r.get("transition") in ("shrink", "regrow")
                   or (r.get("from_world") is not None
                       and r.get("to_world") is not None
                       and r["from_world"] != r["to_world"])]
    if transitions:
        lines.append("## Elastic transitions")
        lines.append("")
        lines.append("| # | transition | world | dead ranks | "
                     "incarnation | reason | resharding |")
        lines.append("|---|---|---|---|---|---|---|")
        for i, r in enumerate(transitions):
            f_w, t_w = r.get("from_world"), r.get("to_world")
            kind = r.get("transition") or (
                "shrink" if (f_w or 0) > (t_w or 0) else "regrow")
            dead = ",".join(str(d) for d in (r.get("dead_ranks") or [])) \
                or "—"
            lines.append(
                f"| {i + 1} | {kind} | {f_w if f_w is not None else '?'} "
                f"→ {t_w if t_w is not None else '?'} | {dead} | "
                f"{r.get('incarnation', '—')} | {r.get('reason', '?')} | "
                f"ZeRO state re-partitions dp {f_w}→{t_w} on restore |")
        lines.append("")

    # hierarchical gradient wire: the per-level (fast/slow fabric) byte
    # split the two-level plan exists to produce — surfaced as its own
    # section so the slow-fabric saving is legible without arithmetic
    intra = any_comm.get("grad_wire.intra")
    inter = any_comm.get("grad_wire.inter")
    exposed = any_comm.get("grad_wire.exposed_ms")
    hits = any_comm.get("qwz.prefetch_hits")
    if (intra or inter) and not (exposed or hits):
        lines.append("## Gradient wire levels (hierarchical reduction)")
    elif intra or inter or exposed or hits:
        lines.append("## Gradient wire levels")
        if not (intra or inter):
            lines.append("")
    if intra or inter:
        lines.append("")
        lines.append("| level | fabric | collectives | wire bytes | "
                     "logical payload |")
        lines.append("|---|---|---|---|---|")

        def _logical(name):
            d = any_comm.get(name)
            # wire bytes include inner/block padding; the logical twin
            # prices the same wire pad-free (absent on pre-quant runs)
            return _fmt_bytes(d["bytes"]) if d else "—"

        if intra:
            lines.append(f"| intra-group | fast (ICI/intra-process) | "
                         f"{intra['calls']:,} | "
                         f"{_fmt_bytes(intra['bytes'])} | "
                         f"{_logical('grad_wire.intra_logical')} |")
        if inter:
            lines.append(f"| inter-group | slow (DCN/TCP) | "
                         f"{inter['calls']:,} | "
                         f"{_fmt_bytes(inter['bytes'])} | "
                         f"{_logical('grad_wire.inter_logical')} |")
        if intra and inter and inter["bytes"]:
            lines.append("")
            lines.append(f"slow-fabric share of grad-wire traffic: "
                         f"{100.0 * inter['bytes'] / (intra['bytes'] + inter['bytes']):.1f}%")
        lines.append("")

    if exposed:
        # µs stored in the bytes slot (the ckpt.stall_ms convention):
        # host time blocked on the overlapped wire AFTER the backward —
        # the non-hidden remainder comm.overlap exists to shrink
        total_ms = exposed["bytes"] / 1000.0
        per = total_ms / exposed["calls"] if exposed["calls"] else 0.0
        lines.append(f"exposed (non-overlapped) wire time: "
                     f"{total_ms:,.1f} ms over {exposed['calls']:,} "
                     f"step drain(s) ({per:.2f} ms/step)")
        lines.append("")
    if hits:
        head_ms = hits["bytes"] / 1000.0
        lines.append(f"qwZ prefetch hits: {hits['calls']:,} gather(s) "
                     f"ready before the forward asked "
                     f"({head_ms:,.1f} ms total head start)")
        lines.append("")

    # MoE wire (moe/dispatch.py): the expert all-to-all's byte/fabric
    # split, capacity discipline and exposed time — its own section,
    # like the gradient-wire levels (moe.* is excluded from the comm
    # byte table above)
    moe_counters = {k: v for k, v in any_comm.items()
                    if k.startswith("moe.")}
    if moe_counters:
        lines.append("## MoE wire (expert all-to-all)")
        lines.append("")
        lines.append("| metric | value |")
        lines.append("|---|---|")
        a2a = moe_counters.get("moe.a2a_bytes")
        if a2a:
            lines.append(f"| a2a wire bytes (all local ranks) | "
                         f"{_fmt_bytes(a2a['bytes'])} over "
                         f"{a2a['calls']:,} hop(s) |")
        inter = moe_counters.get("moe.a2a_inter")
        if inter and a2a and a2a["bytes"]:
            lines.append(f"| slow-fabric (inter-group) share | "
                         f"{_fmt_bytes(inter['bytes'])} "
                         f"({100.0 * inter['bytes'] / a2a['bytes']:.1f}%) |")
        elif a2a:
            # zero either because inner placement pinned the exchange
            # to data_inner or because the mesh is flat (one fabric)
            lines.append("| slow-fabric (inter-group) share | 0 B "
                         "(no data_outer hop: flat mesh or inner "
                         "placement) |")
        exp = moe_counters.get("moe.a2a_exposed_ms")
        if exp and exp["calls"]:
            total_ms = exp["bytes"] / 1000.0  # stored as integer µs
            lines.append(f"| exposed a2a time | {total_ms:,.1f} ms over "
                         f"{exp['calls']:,} step(s) "
                         f"({total_ms / exp['calls']:.2f} ms/step) |")
        drop = moe_counters.get("moe.dropped_tokens")
        if drop:
            lines.append(f"| tokens dropped at capacity | "
                         f"{drop['bytes']:,} over {drop['calls']:,} "
                         f"dispatch(es) |")
        frac = moe_counters.get("moe.capacity_frac")
        if frac and frac["calls"]:
            # ppm-in-bytes: mean utilisation % = bytes / calls / 1e4
            lines.append(f"| mean expert-bucket utilisation | "
                         f"{frac['bytes'] / frac['calls'] / 1e4:.1f}% "
                         f"(sampled at {frac['calls']:,} dispatches) |")
        lines.append("")

    # the self-tuning runtime (runtime/autotune/): probe/swap counters
    # + the rank-0 search/retune ledger — its own section, excluded
    # from the comm byte table like the other bookkeeping counters
    at_counters = {k: v for k, v in any_comm.items()
                   if k.startswith("autotune.")}
    at_ledger = run.get("autotune") or []
    if at_counters or at_ledger:
        lines.append("## Autotune")
        lines.append("")
        if at_counters:
            lines.append("| metric | value |")
            lines.append("|---|---|")
            probes = at_counters.get("autotune.probes")
            if probes:
                total_ms = probes["bytes"] / 1000.0  # µs in the bytes slot
                lines.append(f"| candidate probes | {probes['calls']:,} "
                             f"({total_ms:,.1f} ms probing) |")
            hits = at_counters.get("autotune.cache_hits")
            if hits:
                lines.append(f"| winner-cache hits (zero probes) | "
                             f"{hits['calls']:,} |")
            rej = at_counters.get("autotune.rejected")
            if rej:
                lines.append(f"| candidates pruned by config validators | "
                             f"{rej['calls']:,} |")
            ret = at_counters.get("autotune.retunes")
            if ret:
                lines.append(f"| online retunes (sustained regression) | "
                             f"{ret['calls']:,} |")
            swaps = at_counters.get("autotune.swaps")
            if swaps:
                lines.append(f"| live config swaps applied | "
                             f"{swaps['calls']:,} |")
            lines.append("")
        events = [e for e in at_ledger
                  if e.get("event") in ("search", "cache_hit", "retune",
                                        "swap")]
        if events:
            lines.append("| # | event | step | detail |")
            lines.append("|---|---|---|---|")
            for i, e in enumerate(events):
                ev = e.get("event")
                if ev == "swap":
                    detail = (f"-> `{e.get('candidate', '?')}` "
                              f"({e.get('reason', '?')})")
                elif ev == "retune":
                    detail = (f"{e.get('reason', '?')}; "
                              f"{e.get('probes', 0)} probe(s), "
                              + ("swapped to "
                                 f"`{e.get('winner', '?')}`"
                                 if e.get("swapped")
                                 else "incumbent stands"))
                elif ev == "cache_hit":
                    detail = (f"`{e.get('candidate', '?')}` (fingerprint "
                              f"{e.get('fingerprint', '?')})")
                else:
                    detail = (f"{e.get('probes', 0)} probe(s), baseline "
                              f"{_fmt(e.get('baseline_ms'))} ms/step")
                lines.append(f"| {i + 1} | {ev} | {e.get('step', '—')} | "
                             f"{detail} |")
            lines.append("")

    # the Pallas kernel registry (deepspeed_tpu/kernels): trace-time
    # dispatch resolutions — how often a hot loop ran its Pallas path
    # vs its jnp oracle fallback (kernel.* is excluded from the comm
    # byte table above)
    kern_counters = {k: v for k, v in any_comm.items()
                     if k.startswith("kernel.")}
    if kern_counters:
        lines.append("## Kernels")
        lines.append("")
        lines.append("| metric | value |")
        lines.append("|---|---|")
        disp = kern_counters.get("kernel.dispatches")
        if disp:
            lines.append(f"| Pallas kernel dispatches (trace-time) | "
                         f"{disp['calls']:,} |")
        falls = kern_counters.get("kernel.fallbacks")
        if falls:
            lines.append(f"| jnp oracle fallbacks (trace-time) | "
                         f"{falls['calls']:,} |")
        lines.append("")

    qwz = any_comm.get("qwz.gather")
    if qwz:
        lines.append("## qwZ quantized parameter gather (ZeRO-3)")
        lines.append("")
        lines.append(f"Stage-3 parameters gathered as quantized blocks + "
                     f"fp16 scales: {_fmt_bytes(qwz['bytes'])} over "
                     f"{qwz['calls']:,} collectives (master weights stay "
                     f"full precision).")
        lines.append("")

    pipe = next((s["pipe"] for s in summaries.values() if s["pipe"]), None)
    if pipe and pipe.get("occupancy"):
        lines.append("## Pipeline occupancy (schedule ticks)")
        lines.append("")
        lines.append("| stage | ticks | compute ticks | bubble |")
        lines.append("|---|---|---|---|")
        for st in pipe["occupancy"]:
            lines.append(f"| {st['stage']} | {st['ticks']} | "
                         f"{st['compute_ticks']} | "
                         f"{100.0 * st['bubble_frac']:.1f}% |")
        lines.append("")

    spans = {}
    for s in summaries.values():
        for name, ms in s["spans_ms_total"].items():
            spans[name] = spans.get(name, 0.0) + ms
    if spans:
        lines.append("## Wall-time by span (all ranks, whole run)")
        lines.append("")
        lines.append("| span | total ms |")
        lines.append("|---|---|")
        for name in sorted(spans, key=lambda k: -spans[k]):
            lines.append(f"| `{name}` | {spans[name]:,.1f} |")
        lines.append("")

    stragglers = sorted({r for s in summaries.values()
                         for r in s["stragglers"]})
    if stragglers:
        lines.append(f"**Stragglers flagged:** ranks {stragglers}")
        lines.append("")
    return "\n".join(lines)
