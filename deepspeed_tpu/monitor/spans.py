"""Async-dispatch-aware timing spans + the config-driven profiler window.

JAX dispatch is asynchronous: wall-clocking a region that ends in device
work measures *dispatch* unless the caller blocks on that work's output.
`_Timer.stop(sync=)` (utils/timer.py) hard-codes that pattern for two
named timers; `Span` generalizes it — any region, any sink, close on a
`block_until_ready` marker — and `TraceWindow` turns the hand-edited
`jax.profiler.trace` scripts into a config key (start step / num steps /
output dir).
"""

from __future__ import annotations

import os
import time
from typing import Callable, Dict, Optional

import jax

from ..utils.logging import log_dist, logger


class Span:
    """One timed region, started at construction.

    close(sync=x) blocks on x (jax.block_until_ready) before reading the
    clock, so the span covers the device work that produced x, not just
    its dispatch.  close(sync=None) reads the clock immediately — the
    honest measurement is then host/dispatch time, which is what you
    want for regions that are pure Python.  Also usable as a context
    manager (no sync on exit — pass the marker to close() instead for
    device-bounded regions)."""

    __slots__ = ("name", "t0", "elapsed", "_sink", "_closed")

    def __init__(self, name: str,
                 sink: Optional[Callable[[str, float], None]] = None):
        self.name = name
        self._sink = sink
        self.elapsed = 0.0
        self._closed = False
        self.t0 = time.perf_counter()

    def close(self, sync=None) -> float:
        if self._closed:
            return self.elapsed
        if sync is not None:
            jax.block_until_ready(sync)
        self.elapsed = time.perf_counter() - self.t0
        self._closed = True
        if self._sink is not None:
            self._sink(self.name, self.elapsed)
        return self.elapsed

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


class SpanSet:
    """Step-scoped span accumulator: name -> (seconds, count).  The
    monitor drains it into each step event."""

    __slots__ = ("_acc",)

    def __init__(self):
        self._acc: Dict[str, list] = {}

    def record(self, name: str, seconds: float) -> None:
        e = self._acc.get(name)
        if e is None:
            self._acc[name] = [seconds, 1]
        else:
            e[0] += seconds
            e[1] += 1

    def span(self, name: str) -> Span:
        return Span(name, sink=self.record)

    def drain_ms(self) -> Dict[str, float]:
        out = {k: round(v[0] * 1000.0, 3) for k, v in self._acc.items()}
        self._acc.clear()
        return out


class TraceWindow:
    """Config-driven `jax.profiler.trace` capture: starts at
    `start_step`, stops after `num_steps` steps (or at close()).  Feed
    it every step via tick(step); it is a no-op outside the window and
    after completion, and any profiler failure disables it loudly rather
    than killing the run."""

    def __init__(self, start_step: int, num_steps: int, output_dir: str):
        self.start_step = int(start_step)
        self.num_steps = max(1, int(num_steps))
        self.output_dir = output_dir
        self.active = False
        self.done = self.start_step < 0

    def tick(self, step: int) -> None:
        if self.done:
            return
        if not self.active and step >= self.start_step:
            try:
                os.makedirs(self.output_dir, exist_ok=True)
                jax.profiler.start_trace(self.output_dir)
                self.active = True
                log_dist(f"profiler trace started at step {step} -> "
                         f"{self.output_dir}", ranks=[0])
            except Exception as e:
                logger.warning(f"profiler trace failed to start: {e}")
                self.done = True
                return
        elif self.active and step >= self.start_step + self.num_steps:
            self._stop(step)

    def _stop(self, step) -> None:
        try:
            jax.profiler.stop_trace()
            log_dist(f"profiler trace stopped at step {step} "
                     f"({self.output_dir})", ranks=[0])
        except Exception as e:
            logger.warning(f"profiler trace failed to stop: {e}")
        self.active = False
        self.done = True

    def close(self) -> None:
        if self.active:
            self._stop(self.start_step + self.num_steps)
