"""Process-global comm/dispatch counters.

The reference attributes step time by reading NCCL byte counts out of
band; here every hot-path comm primitive increments a named counter
(calls + bytes) as it dispatches, and the telemetry layer reads *deltas*
per step (`RunMonitor.step_start` snapshots, `step_end` diffs).  The
increment is two integer adds on a plain dict entry — cheap enough to
stay unconditional, so the counters are always truthful whether or not
a monitor is attached.

Instrumented sites:

* `runtime/pipe/p2p.py` — `Channel.transfer` (interpreted walk),
  `ChannelPlan.__call__` (fused compiled-executor transfer),
  `GlobalScalars.sum`: per-dispatch send/recv bytes.
* `runtime/pipe/compiler.py` — the single-controller xfer closures
  (`pipe.xfer_act` / `pipe.xfer_grad` device_put reshards).
* `comm/dist.py` — the in-jit collective wrappers.  Those run under
  `jit`/`shard_map` tracing, so each record is a *traced* occurrence
  (once per compiled program), not a per-execution count; the name
  prefix `dist.` marks that distinction.
* `runtime/comm/hostwire.py` — KV-wire payload bytes per allgather.
* `runtime/comm/bucketing.py` — `bucket.*` per-bucket collective payloads
  (traced occurrences, like `dist.*`; hierarchical plans tag them
  `bucket.intra.*` / `bucket.inter.*` per level); the engine
  additionally records per-dispatch `grad_wire.reduce` totals from the
  BucketPlan's static accounting, which tests pin against the plan
  exactly — plus, for hierarchical plans, the per-fabric split
  `grad_wire.intra` (fast-fabric scatter/gather legs) and
  `grad_wire.inter` (the slow-fabric hop on the 1/inner-size shard).
* the input pipeline (`input.*`, rendered by monitor/report.py as its
  own "Input pipeline" section rather than the comm table):
  `input.host_wait_ms` — wall time the engine's Python thread spent
  blocked pulling a batch from the host iterator (bytes slot carries
  integer MICROSECONDS; the report divides back to ms), recorded by
  `runtime/dataloader.timed_next` on every engine-side pull so
  prefetch-on/off lanes are directly comparable;
  `input.h2d_bytes` — batch bytes actually `device_put` by
  `engine._shard_batch`/`_shard_batch_stacked` (already-placed arrays
  are skipped and not counted); `input.queue_depth` — PrefetchLoader
  queue occupancy sampled at each pop (mean = bytes/calls);
  `input.replicated_batches` — batches whose dim 0 didn't divide the
  data axis and were replicated (dp x compute for that batch; the
  dataloader's wraparound tail padding exists to keep this at zero).
* checkpointing (`ckpt.*`, rendered by monitor/report.py as a
  "Checkpointing" section, like `input.*` kept out of the comm table):
  `ckpt.stall_ms` — wall time the TRAINING thread spent blocked inside
  `save_checkpoint_state` (bytes slot carries integer MICROSECONDS;
  with async_save this is the host snapshot only, without it the full
  serialize+write+commit); `ckpt.bytes` — serialized bytes per
  COMMITTED tag (added by the commit job, so an interrupted save never
  counts); `ckpt.pending` — background writer-queue depth sampled at
  each save (mean = bytes/calls, like input.queue_depth);
  `ckpt.skipped_tags` — uncommitted/corrupt tags read_latest_tag
  skipped back over while resolving a resume point.
* the chaos runtime (`fault.*` / `watchdog.*`, runtime/resilience.py,
  rendered by monitor/report.py as the "Resilience" section):
  `fault.injected` — FaultPlan injections fired; `fault.retried` —
  retry_transient attempts after a transient failure;
  `fault.recovered_ms` — wall time ops spent recovering before
  eventually succeeding (bytes slot carries integer MICROSECONDS);
  `watchdog.trips` — StepWatchdog deadline trips (each one also dumps
  a diagnostic snapshot + supervisor escalation file);
  `input.worker_respawns` — dead prefetch workers replaced by the
  consumer (counted under input.* but rendered with Resilience).
* the serving engine (`serve.*` / `kv.*`, deepspeed_tpu/serving/,
  rendered by monitor/report.py as the "Serving" section and excluded
  from the comm byte table): `serve.requests` — requests completed
  naturally (bytes = generated tokens); `serve.tokens` — tokens
  decoded (prefill first tokens included); `serve.decode_steps` —
  decode dispatches (bytes = active slots, so bytes/calls is the mean
  batch occupancy continuous batching exists to maximize);
  `serve.prefill_chunks` — chunked-prefill dispatches (bytes = prompt
  tokens); `serve.ttft_ms` — time-to-first-token (integer MICROSECONDS
  in the bytes slot, the ckpt.stall_ms convention; one call per first
  token); `serve.shed` — in-flight requests shed after a wedged decode
  step (watchdog escalation, state 'error'); `kv.blocks_in_use` —
  paged-KV occupancy sampled once per engine step (mean =
  bytes/calls); `kv.evictions` — KV blocks FORCIBLY reclaimed from
  shed/errored requests (natural completion frees blocks without
  counting here — a healthy run keeps this at zero).  Speculative
  decoding (rendered as the section's "Speculative decoding" rows):
  `serve.draft_tokens` — draft candidates proposed to the verify
  program (calls); `serve.accepted_tokens`
  — drafts accepted AND emitted (calls; a draft accepted by verify but
  cut by max_new/EOS does not count — the counter is the exact number
  of extra tokens speculation bought, so accepted/decode_steps is the
  bonus tokens-per-step and accepted/draft is the acceptance rate);
  `kv.dequant_ms` — µs-in-bytes (the ckpt.stall_ms convention): wall
  time of decode-family dispatches against a QUANTIZED kv cache (XLA
  fuses the row dequant into the attention gather, so the cost is only
  isolable by A/B against a dense lane — serve_bench does exactly
  that); zero when kv_dtype is dense.  Prefix caching + sessions
  (rendered as the section's "Prefix cache" rows): `kv.prefix_hits` —
  admissions that aliased at least one cached block (bytes = blocks
  aliased instead of recomputed); `kv.prefix_hit_tokens` — prompt
  tokens whose prefill was SKIPPED because their KV rows were already
  resident (bytes; counted for both hash-matched and session-pinned
  admissions — the numerator of the cache hit rate);
  `kv.cow_copies` — copy-on-write block privatizations when a
  full-prompt hit must recompute its final token into a LIVE-shared
  block (bytes = device bytes copied); `kv.session_pins` — session
  pin events at request finish (bytes = blocks held resident);
  `kv.prefix_evictions` — refcount-0 cached blocks reclaimed LRU-first
  by the allocator under pool pressure (distinct from `kv.evictions`,
  which counts FORCED frees of errored requests' live blocks).
  Fleet routing (`router.*`, serving/router.py, rendered as the
  "Fleet router" rows; excluded from the comm byte table like the
  rest of the serving families): `router.dispatches` — requests
  dispatched to a replica (bytes += the chosen replica's
  `kv.blocks_in_use` at dispatch, so bytes/calls is the mean load a
  dispatch landed on); `router.spills` — dispatches deflected from
  the least-loaded pick because its queue was full;
  `router.shed` — requests refused at the front door with every
  replica queue saturated (returned in state 'error', never
  enqueued).
* the MoE wire (`moe.*`, moe/dispatch.py sorted dispatch + explicit
  expert all-to-all; rendered by monitor/report.py as the "MoE wire"
  section, excluded from the comm byte table).  Recorded per EXECUTION
  via async `jax.debug.callback` from inside the traced program — one
  callback per LOCAL mesh rank per event (the 8-device virtual test
  mesh fires 8 per a2a hop; a real deployment sums its local devices),
  never bumped by AOT lowering or flops analysis; read after
  `jax.effects_barrier()` for exact totals:
  `moe.a2a_bytes` — wire bytes per a2a hop (all local ranks; a
  training dispatch runs 4 traversals: forward dispatch+combine and
  the mirrored backward), pinned byte-exact against
  `dispatch.A2APlan` in tier-1; `moe.a2a_inter` — the subset crossing
  the slow fabric (`data_outer` hops; ZERO under inner placement —
  the number the hierarchy-aware placement exists to minimize);
  `moe.a2a_exposed_ms` — µs-in-bytes (the ckpt.stall_ms convention):
  a2a wall time on the critical path, measured by the
  `tools/moe_a2a_bench.py` wire-on/wire-off lanes (the in-program a2a
  is consumed by the very next expert matmul, so today ALL of it is
  exposed — this is what a future chunked overlap would hide);
  `moe.dropped_tokens` — assignments past expert capacity (bytes;
  calls = dispatches), zero in dropless mode while the overflow
  bucket holds; `moe.capacity_frac` — ppm-in-bytes occupancy of the
  [E, C] expert buckets per dispatch (mean utilisation % =
  bytes / calls / 1e4).
* the self-tuning runtime (`autotune.*`, runtime/autotune/; rendered
  by monitor/report.py as the "Autotune" section beside the
  `autotune.jsonl` ledger, excluded from the comm byte table):
  `autotune.probes` — candidate probes run (bytes = probe wall time in
  integer MICROSECONDS, the ckpt.stall_ms convention; probe dispatches
  go through the raw `.fn` programs so they never bump the
  `grad_wire.*` per-dispatch counters); `autotune.cache_hits` — winner
  cache hits (a hit applies with ZERO probes); `autotune.rejected` —
  candidate compositions pruned by the config validators before any
  probe; `autotune.retunes` — online retunes triggered by sustained
  regression (step-time or exposed-wire creep); `autotune.swaps` —
  live config swaps applied through the StepBuilder rebuild (search
  winners, cached winners and online retune winners all count here).
* the Pallas kernel registry (`kernel.*`, deepspeed_tpu/kernels;
  rendered by monitor/report.py as the "Kernels" section, excluded
  from the comm byte table): `kernel.dispatches` — registry
  resolutions that took an op's Pallas path (counted at TRACE time,
  once per jit trace, not per step); `kernel.fallbacks` — resolutions
  that ran the jnp oracle instead (incompatible fabric, declined
  shape, or an explicit jnp pin).
* trace/SLO telemetry (`trace.*` / `slo.*`, monitor/tracing.py;
  rendered by monitor/report.py as the "Tracing" rows of the Serving
  SLO section, excluded from the comm byte table): `trace.events` —
  span events flushed to the rank-local trace file (bytes = JSONL
  bytes written, bounded by `max_file_bytes`); `trace.dropped` —
  events the byte cap rejected (the ring buffer still holds them for
  the watchdog flight recorder); `slo.windows` — periodic `slo`
  monitor events emitted by the ServingSLO sliding window.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np


def tree_bytes(tree: Any) -> int:
    """Total byte size of a pytree of arrays / ShapeDtypeStructs /
    tracers (anything with .shape and .dtype). Best-effort: leaves
    without a static shape contribute 0 — a counter must never raise
    into the hot path."""
    import jax

    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        try:
            shape = getattr(leaf, "shape", None)
            dtype = getattr(leaf, "dtype", None)
            if shape is None or dtype is None:
                continue
            total += int(np.prod(shape, dtype=np.int64)) * \
                np.dtype(dtype).itemsize
        except Exception:
            continue
    return total


class CounterRegistry:
    """Named (calls, bytes) accumulators with snapshot/delta reads."""

    __slots__ = ("_c",)

    def __init__(self):
        self._c: Dict[str, list] = {}

    def add(self, name: str, nbytes: int = 0, calls: int = 1) -> None:
        e = self._c.get(name)
        if e is None:
            self._c[name] = [calls, nbytes]
        else:
            e[0] += calls
            e[1] += nbytes

    def snapshot(self) -> Dict[str, tuple]:
        return {k: (v[0], v[1]) for k, v in self._c.items()}

    def delta_since(self, snap: Optional[Dict[str, tuple]]) -> Dict[str, dict]:
        snap = snap or {}
        out = {}
        for k, v in self._c.items():
            c0, b0 = snap.get(k, (0, 0))
            dc, db = v[0] - c0, v[1] - b0
            if dc or db:
                out[k] = {"calls": dc, "bytes": db}
        return out

    def totals(self) -> Dict[str, dict]:
        return {k: {"calls": v[0], "bytes": v[1]} for k, v in self._c.items()}

    def reset(self) -> None:
        self._c.clear()


# THE process-global registry every instrumented site writes to.
COUNTERS = CounterRegistry()

# Counters whose bytes slot carries integer MICROSECONDS (the
# ckpt.stall_ms convention) instead of real bytes.  The counter/doc
# lint test (tests/test_tracing.py) cross-checks this registry against
# docs/tutorials/monitoring.md so every µs-in-bytes counter stays
# flagged as such wherever it is documented.
US_IN_BYTES_COUNTERS = frozenset((
    "input.host_wait_ms",
    "ckpt.stall_ms",
    "fault.recovered_ms",
    "grad_wire.exposed_ms",
    "serve.ttft_ms",
    "kv.dequant_ms",
    "moe.a2a_exposed_ms",
    "autotune.probes",
))
