"""Durable bench/run artifacts.

Round-5 post-mortem: the on-TPU artifacts that proved a 0.41x regression
were later deleted from the tree (commit 53f94f7), leaving docs pointing
at files that no longer exist.  This module gives bench.py (and any
other tool) ONE write path that always lands results in a committed,
manifest-indexed directory: `bench_artifacts/runs/<stamp>_<metric>.json`
plus an append-only `manifest.jsonl` — deleting a result now requires
editing the manifest too, which review catches."""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, Optional

from .monitor import SCHEMA_VERSION


def record_bench_result(result: Dict[str, Any],
                        root: Optional[str] = None,
                        name: Optional[str] = None) -> str:
    """Write `result` as a durable artifact; returns the path relative
    to `root`'s parent (repo-relative when root is the default).  Never
    raises into the caller's hot path beyond filesystem errors — bench
    wraps this in its own try/except."""
    if root is None:
        here = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        root = os.path.join(here, "bench_artifacts", "runs")
    os.makedirs(root, exist_ok=True)
    stamp = time.strftime("%Y%m%d-%H%M%S", time.gmtime())
    metric = name or str(result.get("metric", "result"))
    fname = f"{stamp}_{metric}.json"
    path = os.path.join(root, fname)
    record = {"schema_version": SCHEMA_VERSION, "written_unix": time.time(),
              "result": result}
    with open(path, "w") as f:
        json.dump(record, f, indent=2, sort_keys=True, default=str)
    with open(os.path.join(root, "manifest.jsonl"), "a") as f:
        f.write(json.dumps({
            "file": fname, "metric": metric,
            "platform": result.get("platform"),
            "value": result.get("value"), "unit": result.get("unit"),
            "written_unix": record["written_unix"]}, default=str) + "\n")
    return os.path.join(os.path.basename(os.path.dirname(root)),
                        os.path.basename(root), fname)
