"""Fused projection + softmax cross-entropy as Pallas TPU kernels.

The LM-head loss is the last big HBM consumer in the training step: even
the chunked XLA path (models/gpt.py::_softmax_xent_from_hidden) writes
each [rows, V] logits chunk to HBM once in forward and recomputes it in
backward. These kernels stream vocab blocks through VMEM with an online
logsumexp — logits NEVER exist in HBM:

  forward   grid (row_blk, v_blk):   lse/label-logit accumulators in VMEM
  backward  dx: grid (row_blk, v_blk) accumulating dl @ w_blk^T
            dw: grid (v_blk, row_blk) accumulating x_blk^T @ dl
  where dl = g * valid * (exp(logit - lse) - onehot) is re-formed
  blockwise from the saved per-row lse (flash-attention-style recompute
  applied to the classifier).

Wire cost per step: read x twice, read w three times, write dx + dw —
~2 GB at GPT-2-small shapes vs ~5-6 GB for the chunked XLA form.
Opt-in via GPTConfig.loss_impl="pallas" until measured on a real chip;
not valid under vocab-parallel TP (the online lse is row-global here).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_BLOCK_ROWS = 256
DEFAULT_BLOCK_V = 512
NEG_INF = -1e30


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _params():
    from .flash_attention import compiler_params_cls

    return compiler_params_cls()(
        dimension_semantics=(pltpu.PARALLEL, pltpu.ARBITRARY))


# ---------------------------------------------------------------------------
# forward: per-row (logsumexp, label logit)
# ---------------------------------------------------------------------------

def _fwd_kernel(x_ref, w_ref, lab_ref, lse_ref, ll_ref, m_s, l_s, ll_s, *,
                bv, nv):
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        m_s[:] = jnp.full_like(m_s, NEG_INF)
        l_s[:] = jnp.zeros_like(l_s)
        ll_s[:] = jnp.zeros_like(ll_s)

    logits = jax.lax.dot_general(
        x_ref[...].astype(jnp.float32), w_ref[...].astype(jnp.float32),
        (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
    m_prev = m_s[:, :1]
    m_new = jnp.maximum(m_prev, jnp.max(logits, axis=1, keepdims=True))
    l_s[:, :1] = l_s[:, :1] * jnp.exp(m_prev - m_new) + \
        jnp.sum(jnp.exp(logits - m_new), axis=1, keepdims=True)
    m_s[:, :1] = m_new
    vidx = j * bv + jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1)
    onehot = (vidx == lab_ref[...][:, :1])
    ll_s[:, :1] += jnp.sum(jnp.where(onehot, logits, 0.0), axis=1,
                           keepdims=True)

    @pl.when(j == nv - 1)
    def _finish():
        lse_ref[...] = jnp.broadcast_to(
            m_s[:, :1] + jnp.log(l_s[:, :1]), lse_ref.shape)
        ll_ref[...] = jnp.broadcast_to(ll_s[:, :1], ll_ref.shape)


def _fwd(x, w, labels, br, bv) -> Tuple[jax.Array, jax.Array]:
    N, D = x.shape
    V = w.shape[1]
    nr, nv = N // br, V // bv
    lse, ll = pl.pallas_call(
        functools.partial(_fwd_kernel, bv=bv, nv=nv),
        grid=(nr, nv),
        in_specs=[
            pl.BlockSpec((br, D), lambda i, j: (i, 0)),
            pl.BlockSpec((D, bv), lambda i, j: (0, j)),
            pl.BlockSpec((br, 1), lambda i, j: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((br, 128), lambda i, j: (i, 0)),
            pl.BlockSpec((br, 128), lambda i, j: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((N, 128), jnp.float32),
            jax.ShapeDtypeStruct((N, 128), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((br, 128), jnp.float32),
            pltpu.VMEM((br, 128), jnp.float32),
            pltpu.VMEM((br, 128), jnp.float32),
        ],
        compiler_params=_params(),
        interpret=_interpret(),
    )(x, w, labels[:, None])
    return lse[:, 0], ll[:, 0]


# ---------------------------------------------------------------------------
# backward: dl = coef * (softmax - onehot), streamed
# ---------------------------------------------------------------------------

def _dl_block(x_ref, w_ref, lab_ref, lse_ref, coef_ref, j, bv):
    logits = jax.lax.dot_general(
        x_ref[...].astype(jnp.float32), w_ref[...].astype(jnp.float32),
        (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
    p = jnp.exp(logits - lse_ref[...][:, :1])
    vidx = j * bv + jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1)
    onehot = (vidx == lab_ref[...][:, :1]).astype(jnp.float32)
    return (p - onehot) * coef_ref[...][:, :1]


def _dx_kernel(x_ref, w_ref, lab_ref, lse_ref, coef_ref, dx_ref, acc, *,
               bv, nv):
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)

    dl = _dl_block(x_ref, w_ref, lab_ref, lse_ref, coef_ref, j, bv)
    acc[:] += jax.lax.dot_general(
        dl, w_ref[...].astype(jnp.float32), (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(j == nv - 1)
    def _finish():
        dx_ref[...] = acc[:].astype(dx_ref.dtype)


def _dw_kernel(x_ref, w_ref, lab_ref, lse_ref, coef_ref, dw_ref, acc, *,
               bv, nr):
    i = pl.program_id(1)
    j = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)

    dl = _dl_block(x_ref, w_ref, lab_ref, lse_ref, coef_ref, j, bv)
    acc[:] += jax.lax.dot_general(
        x_ref[...].astype(jnp.float32), dl, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(i == nr - 1)
    def _finish():
        dw_ref[...] = acc[:].astype(dw_ref.dtype)


def _bwd(br, bv, res, g):
    x, w, labels, valid, lse = res
    N, D = x.shape
    V = w.shape[1]
    nr, nv = N // br, V // bv
    coef = (g * valid.astype(jnp.float32))[:, None]  # [N, 1]
    lab = labels[:, None]

    dx = pl.pallas_call(
        functools.partial(_dx_kernel, bv=bv, nv=nv),
        grid=(nr, nv),
        in_specs=[
            pl.BlockSpec((br, D), lambda i, j: (i, 0)),
            pl.BlockSpec((D, bv), lambda i, j: (0, j)),
            pl.BlockSpec((br, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((br, 128), lambda i, j: (i, 0)),
            pl.BlockSpec((br, 1), lambda i, j: (i, 0)),
        ],
        out_specs=pl.BlockSpec((br, D), lambda i, j: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((N, D), x.dtype),
        scratch_shapes=[pltpu.VMEM((br, D), jnp.float32)],
        compiler_params=_params(),
        interpret=_interpret(),
    )(x, w, lab, jnp.broadcast_to(lse[:, None], (N, 128)), coef)

    dw = pl.pallas_call(
        functools.partial(_dw_kernel, bv=bv, nr=nr),
        grid=(nv, nr),
        in_specs=[
            pl.BlockSpec((br, D), lambda j, i: (i, 0)),
            pl.BlockSpec((D, bv), lambda j, i: (0, j)),
            pl.BlockSpec((br, 1), lambda j, i: (i, 0)),
            pl.BlockSpec((br, 128), lambda j, i: (i, 0)),
            pl.BlockSpec((br, 1), lambda j, i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((D, bv), lambda j, i: (0, j)),
        out_shape=jax.ShapeDtypeStruct((D, V), w.dtype),
        scratch_shapes=[pltpu.VMEM((D, bv), jnp.float32)],
        compiler_params=_params(),
        interpret=_interpret(),
    )(x, w, lab, jnp.broadcast_to(lse[:, None], (N, 128)), coef)
    return dx, dw, None, None


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def fused_softmax_xent_sum(x, w, labels, valid,
                           block_rows: int = DEFAULT_BLOCK_ROWS,
                           block_v: int = DEFAULT_BLOCK_V):
    """Sum over valid rows of (logsumexp(x @ w) - (x @ w)[label]).

    x [N, D], w [D, V], labels [N] int32 (in-range), valid [N] bool.
    Requires N % block_rows == 0 and V % block_v == 0. NOT valid when w
    is vocab-sharded (lse is computed row-globally in-kernel)."""
    lse, ll = _fwd(x, w, labels, block_rows, block_v)
    return jnp.sum(jnp.where(valid, lse - ll, 0.0))


def _fwd_rule(x, w, labels, valid, block_rows, block_v):
    lse, ll = _fwd(x, w, labels, block_rows, block_v)
    out = jnp.sum(jnp.where(valid, lse - ll, 0.0))
    return out, (x, w, labels, valid, lse)


def _bwd_rule(block_rows, block_v, res, g):
    return _bwd(block_rows, block_v, res, g)


fused_softmax_xent_sum.defvjp(_fwd_rule, _bwd_rule)
