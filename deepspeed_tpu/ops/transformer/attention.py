"""Multi-head attention dispatch — Pallas flash attention on TPU, fused XLA
elsewhere.

Reference: the fused CUDA transformer kernel's attention core
(/root/reference/csrc/transformer/ds_transformer_cuda.cpp:147-295 — QKV
strided-batch GEMM + softmax kernels + dropout). TPU-native design: one
flash-attention Pallas kernel (ops/transformer/flash_attention.py) computes
softmax(QK^T)V in VMEM-resident tiles without materialising the [S, S]
score matrix; off-TPU (and for shapes the kernel doesn't tile) an XLA
einsum path that the compiler fuses.

Shapes follow [batch, seq, heads, head_dim] (BSHD).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

_FLASH_MIN_SEQ = 256  # below this the [S,S] buffer fits easily; XLA wins


def _on_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


_AD_TRACER_NAMES = ("JVPTracer", "LinearizeTracer")


def _is_ad_tracer(x) -> bool:
    """True when x is being differentiated (a JVP/linearize tracer at ANY
    nesting depth).

    The flash kernel's VJP returns no cotangent for its key-bias operand,
    so a bias that itself needs gradients (e.g. a learnable per-key bias)
    must stay on the XLA path; a constant padding mask — even inside jit
    or under grad-w.r.t.-params, where it is an ArrayImpl or a plain
    DynamicJaxprTracer — still takes the kernel.

    Transform stacks WRAP the AD tracer: under vmap(grad(f)) the bias is
    a BatchTracer whose payload is the JVPTracer, so checking only the
    outermost type would silently route a differentiated bias to the
    kernel and return a zero cotangent.  Walk the nesting (BatchTracer
    carries `.val`, JVP/Linearize carry `.primal`) until an AD tracer is
    found or the payload stops being a tracer."""
    from jax.core import Tracer

    for _ in range(32):  # transform stacks are shallow; bound the walk
        if type(x).__name__ in _AD_TRACER_NAMES:
            return True
        if not isinstance(x, Tracer):
            return False
        inner = getattr(x, "val", None)
        if inner is None:
            inner = getattr(x, "primal", None)
        if inner is None or inner is x:
            return False
        x = inner
    return False


def xla_attention(q, k, v, causal=True, bias=None, dropout_rate=0.0,
                  dropout_rng=None, train=False, scale=None):
    """Reference attention in pure XLA. [B,S,H,D] -> [B,S,H,D].

    fp32 softmax regardless of input dtype (parity with the reference's
    softmax kernel which upcasts — csrc/transformer/softmax_kernels.cu).
    """
    B, S, H, D = q.shape
    Sk = k.shape[1]
    scale = (D ** -0.5) if scale is None else scale
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if bias is not None:
        scores = scores + bias.astype(scores.dtype)
    if causal:
        qi = jnp.arange(S)[:, None] + (Sk - S)  # offset for cached decoding
        ki = jnp.arange(Sk)[None, :]
        scores = jnp.where(qi >= ki, scores, jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(scores, axis=-1)
    if train and dropout_rate > 0.0 and dropout_rng is not None:
        # counter-hash mask (dropout.py): the [B,H,S,S] probability
        # tensor is the single largest per-element threefry bill in the
        # model — the hash mask costs ~6 fused int ops instead
        from .dropout import hash_dropout

        probs = hash_dropout(probs, dropout_rate, dropout_rng)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs.astype(v.dtype), v)
    return out.astype(q.dtype)


def multihead_attention(q, k, v, causal: bool = True, impl: str = "auto",
                        bias=None, dropout_rate: float = 0.0,
                        dropout_rng=None, train: bool = False,
                        scale: Optional[float] = None,
                        block_q: Optional[int] = None,
                        block_k: Optional[int] = None,
                        bh_offset=0):
    """Dispatching attention entry point used by the GPT family and the
    DeepSpeedTransformerLayer.

    impl: "auto" (pallas on TPU when tileable), "pallas", "xla".
    The Pallas path applies probability dropout in-kernel (hash-generated
    tile masks, no [S, S] materialisation) and accepts per-key additive
    biases ([B, 1, 1, Sk] — the BERT padding-mask shape) in-kernel too;
    only a full [.., S, Sk] bias (e.g. relative-position) routes to XLA.
    """
    B, S, D = q.shape[0], q.shape[1], q.shape[3]
    Sk = k.shape[1]
    want_dropout = train and dropout_rate > 0.0 and dropout_rng is not None
    key_bias = None
    if bias is not None and getattr(bias, "ndim", 0) == 4 \
            and bias.shape[1] == 1 and bias.shape[2] == 1 \
            and bias.shape[3] == Sk and bias.shape[0] in (1, B) \
            and not _is_ad_tracer(bias):
        key_bias = bias
    use_pallas = False
    if impl == "pallas":
        # the flash kernel carries per-key biases only; honoring a full
        # [.., S, Sk] bias wins over the impl request (silently dropping
        # a mask is numerically wrong)
        use_pallas = bias is None or key_bias is not None
    elif impl == "auto":
        use_pallas = (_on_tpu() and (bias is None or key_bias is not None)
                      and S >= _FLASH_MIN_SEQ and S % 128 == 0
                      and Sk % 128 == 0 and D in (64, 128, 256))
    if use_pallas:
        from .flash_attention import (DEFAULT_BLOCK_K, DEFAULT_BLOCK_Q,
                                      flash_attention)

        bq = block_q or DEFAULT_BLOCK_Q
        bk = block_k or DEFAULT_BLOCK_K
        if S % bq == 0 and k.shape[1] % bk == 0:
            return flash_attention(
                q, k, v, causal=causal, scale=scale, block_q=bq, block_k=bk,
                dropout_rate=dropout_rate if want_dropout else 0.0,
                dropout_rng=dropout_rng if want_dropout else None,
                key_bias=key_bias, bh_offset=bh_offset)
        if block_q or block_k:
            # explicit tuning request that cannot tile: say so instead of
            # silently paying the O(S^2) XLA path
            from ...utils.logging import logger

            logger.warning(
                f"flash blocks ({bq},{bk}) do not divide seq lens "
                f"({S},{k.shape[1]}); falling back to XLA attention")
    try:
        offset_zero = int(bh_offset) == 0  # any concrete zero is a no-op
    except Exception:  # traced (e.g. axis_index): unknowable at dispatch
        offset_zero = False
    if want_dropout and not offset_zero:
        # the XLA path's dropout has no shard-offset notion — silently
        # dropping it would re-correlate the shard masks the caller is
        # explicitly decorrelating
        raise ValueError(
            "bh_offset is only honored by the flash kernel; this call "
            "dispatched to XLA attention (non-TPU platform, untileable "
            "shapes, a full bias, or a differentiated bias) with dropout "
            "active — use impl='pallas' with tileable shapes, or drop "
            "bh_offset")
    return xla_attention(q, k, v, causal=causal, bias=bias,
                         dropout_rate=dropout_rate, dropout_rng=dropout_rng,
                         train=train, scale=scale)
