"""Flash attention as Pallas TPU kernels (forward + backward).

TPU-native replacement for the reference's attention kernel chain
(/root/reference/csrc/transformer/ds_transformer_cuda.cpp:147-295: QKV
strided-batch cuBLAS GEMMs + softmax_kernels.cu + dropout): instead of
materialising the [S, S] score matrix in HBM, each (batch·head, q-block)
program streams k/v blocks through VMEM with an online-softmax accumulator,
so HBM traffic is O(S·D) and the MXU sees dense 128×128 tiles.

Layout: kernels operate on [BH, S, D]; the public entry accepts BSHD.
Backward is the standard flash recomputation: forward saves only
out + logsumexp; dq and dk/dv kernels re-form each score block on the fly.

Grid iteration relies on the TPU's sequential innermost grid dimension:
(bh, q_block) are parallel, the k-block sweep is `ARBITRARY` so the VMEM
scratch accumulators persist across it.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128
NEG_INF = -1e30  # large-negative instead of -inf: keeps masked rows NaN-free


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


# ---------------------------------------------------------------------------
# probability dropout
# ---------------------------------------------------------------------------
# The reference's attention core applies dropout to the softmax
# probabilities inside the fused kernel (csrc/transformer/dropout_kernels.cu
# via ds_transformer_cuda.cpp). Flash kernels keep probabilities implicit,
# so the mask is REGENERATED tile-by-tile — in the forward and in both
# backward kernels — from (seed, batch·head, global q idx, global k idx)
# with a counter-based integer hash. Pure uint32 arithmetic: identical
# values under the Pallas interpreter (CPU tests) and Mosaic (TPU), and no
# hardware-PRNG state to thread across grid programs. The hash is over
# GLOBAL indices, so the mask is invariant to block-size tuning.

def fmix32(h):
    """THE murmur3-style finalizer — one definition for every hash mask
    (in-kernel tile masks here and in flash_sparse.py, activation
    dropout in dropout.py). Changing the mixing changes which elements
    drop everywhere at once, never in one site only."""
    u = jnp.uint32
    h = h ^ (h >> 15)
    h = h * u(0x2C1B3C6D)
    h = h ^ (h >> 12)
    h = h * u(0x297A2D39)
    h = h ^ (h >> 15)
    return h


def keep_threshold(rate) -> "jnp.uint32":
    """uint32 threshold: keep iff hash < keep·2^32."""
    return jnp.uint32(min(0xFFFFFFFF, int((1.0 - rate) * 4294967296.0)))


def _keep_mask(seed, bh, q0, k0, bq, bk, rate):
    """fp32 {0, 1/keep} matrix for the (bq, bk) tile at rows q0+, cols k0+.

    E[mask] = 1, so attention stays unbiased (inverted-dropout
    scaling)."""
    u = jnp.uint32
    qi = q0.astype(u) + jax.lax.broadcasted_iota(u, (bq, bk), 0)
    ki = k0.astype(u) + jax.lax.broadcasted_iota(u, (bq, bk), 1)
    h = fmix32((seed.astype(u) * u(0x9E3779B1))
               ^ (bh.astype(u) * u(0x7FEB352D))
               ^ (qi * u(0x85EBCA6B)) ^ (ki * u(0xC2B2AE35)))
    return (h < keep_threshold(rate)).astype(jnp.float32) * \
        (1.0 / (1.0 - rate))


def derive_seed(dropout_rate, dropout_rng):
    """(seed array, static rate) for the dropout kernels — ONE definition,
    shared with the sparse flash kernel: the hash-mask contract depends on
    identical seed derivation everywhere."""
    if dropout_rate > 0.0 and dropout_rng is not None:
        seed = jax.random.randint(dropout_rng, (1,), 0,
                                  jnp.iinfo(jnp.int32).max, dtype=jnp.int32)
        return seed, float(dropout_rate)
    return jnp.zeros((1,), jnp.int32), 0.0


def compiler_params_cls():
    # jax renamed TPUCompilerParams -> CompilerParams; accept either so
    # the kernels run across the jax versions the repo supports (shared
    # by every Pallas kernel in the repo — fix renames HERE only)
    return (getattr(pltpu, "CompilerParams", None)
            or getattr(pltpu, "TPUCompilerParams"))


def _compiler_params():
    return compiler_params_cls()(
        dimension_semantics=(pltpu.PARALLEL, pltpu.PARALLEL, pltpu.ARBITRARY))


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _fwd_kernel(seed_ref, q_ref, k_ref, v_ref, *rest, scale, causal, bq,
                bk, nk, rate, has_bias):
    if has_bias:
        kb_ref, o_ref, lse_ref, acc, m_s, l_s = rest
    else:
        o_ref, lse_ref, acc, m_s, l_s = rest
    bh = pl.program_id(0)
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)
        m_s[:] = jnp.full_like(m_s, NEG_INF)
        l_s[:] = jnp.zeros_like(l_s)

    live = (ki * bk <= qi * bq + bq - 1) if causal else (ki >= 0)

    @pl.when(live)
    def _compute():
        q = q_ref[0].astype(jnp.float32) * scale
        k = k_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            qidx = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            kidx = ki * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            s = jnp.where(qidx >= kidx, s, NEG_INF)
        if has_bias:
            s = s + kb_ref[...]  # (1, bk) per-key additive bias, row-bcast
        m_prev = m_s[:, :1]
        l_prev = l_s[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        if has_bias:
            # a fully-masked tile leaves m_new at ~NEG_INF, where
            # exp(s - m_new) = 1 for every masked entry — zero them
            # explicitly (the causal-only path never hits this: the
            # diagonal tile always has a live entry per row)
            p = jnp.where(s <= NEG_INF * 0.5, 0.0, p)
        alpha = jnp.exp(m_prev - m_new)
        # the softmax denominator accumulates the UNdropped p (dropout acts
        # on normalized probabilities); only the value accumulation sees the
        # dropped, 1/keep-rescaled probabilities
        l_new = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
        if rate > 0.0:
            p = p * _keep_mask(seed_ref[0], bh + seed_ref[1],
                               qi * bq, ki * bk, bq, bk, rate)
        acc[:] = acc[:] * alpha + jnp.dot(
            p.astype(v_ref.dtype), v_ref[0],
            preferred_element_type=jnp.float32)
        m_s[:, :1] = m_new
        l_s[:, :1] = l_new

    last = (ki == qi * bq // bk + (bq - 1) // bk) if causal else (ki == nk - 1)

    @pl.when(last)
    def _finish():
        l = l_s[:, :1]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc[:] / safe_l).astype(o_ref.dtype)
        # lse carries a broadcast 128-lane trailing dim (TPU tiling: the
        # lane dimension must be 128; same layout as jax's in-tree kernel)
        lse_ref[0] = jnp.broadcast_to(m_s[:, :1] + jnp.log(safe_l),
                                      (bq, 128))


def _fwd(q, k, v, seed, kb, causal, scale, bq, bk, rate, n_heads):
    BH, S, D = q.shape
    Sk = k.shape[1]
    nq, nk = S // bq, Sk // bk
    has_bias = kb is not None
    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               bq=bq, bk=bk, nk=nk, rate=rate,
                               has_bias=has_bias)
    in_specs = [
        pl.BlockSpec(memory_space=pltpu.SMEM),
        pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0)),
        pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0)),
    ]
    operands = [seed, q, k, v]
    if has_bias:
        # [B, Sk] per-key bias; BH programs map back to batch b // H
        in_specs.append(
            pl.BlockSpec((1, bk), lambda b, i, j: (b // n_heads, j)))
        operands.append(kb)
    out, lse = pl.pallas_call(
        kernel,
        grid=(BH, nq, nk),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bq, 128), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, S, D), q.dtype),
            jax.ShapeDtypeStruct((BH, S, 128), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, D), jnp.float32),
            pltpu.VMEM((bq, 128), jnp.float32),
            pltpu.VMEM((bq, 128), jnp.float32),
        ],
        compiler_params=_compiler_params(),
        interpret=_interpret(),
    )(*operands)
    return out, lse


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------

def _dq_kernel(seed_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
               *rest, scale, causal, bq, bk, nk, rate, has_bias):
    if has_bias:
        kb_ref, dq_ref, dq_acc = rest
    else:
        dq_ref, dq_acc = rest
    bh = pl.program_id(0)
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    live = (ki * bk <= qi * bq + bq - 1) if causal else (ki >= 0)

    @pl.when(live)
    def _compute():
        q = q_ref[0].astype(jnp.float32) * scale
        k = k_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            qidx = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            kidx = ki * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            s = jnp.where(qidx >= kidx, s, NEG_INF)
        if has_bias:
            s = s + kb_ref[...]
        p = jnp.exp(s - lse_ref[0][:, :1])
        if has_bias:
            # fully-masked rows carry lse ≈ NEG_INF; exp(s - lse) would
            # resurrect masked entries — zero them like the forward does
            p = jnp.where(s <= NEG_INF * 0.5, 0.0, p)
        do = do_ref[0].astype(jnp.float32)
        dp = jax.lax.dot_general(do, v_ref[0].astype(jnp.float32),
                                 (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        if rate > 0.0:
            # dS = P ∘ (mask/keep ∘ dPd − delta); delta = rowsum(dO∘O)
            # equals rowsum(Pd∘dPd), so the no-dropout delta trick holds
            dp = dp * _keep_mask(seed_ref[0], bh + seed_ref[1],
                                 qi * bq, ki * bk, bq, bk, rate)
        ds = p * (dp - delta_ref[0][:, :1])
        dq_acc[:] += scale * jnp.dot(ds.astype(k_ref.dtype), k_ref[0],
                                     preferred_element_type=jnp.float32)

    last = (ki == qi * bq // bk + (bq - 1) // bk) if causal else (ki == nk - 1)

    @pl.when(last)
    def _finish():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _dkv_kernel(seed_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                *rest, scale, causal, bq, bk, nq, rate, has_bias):
    if has_bias:
        kb_ref, dk_ref, dv_ref, dk_acc, dv_acc = rest
    else:
        dk_ref, dv_ref, dk_acc, dv_acc = rest
    bh = pl.program_id(0)
    ki = pl.program_id(1)
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    live = (qi * bq + bq - 1 >= ki * bk) if causal else (qi >= 0)

    @pl.when(live)
    def _compute():
        q = q_ref[0].astype(jnp.float32) * scale
        k = k_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            qidx = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            kidx = ki * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            s = jnp.where(qidx >= kidx, s, NEG_INF)
        if has_bias:
            s = s + kb_ref[...]
        p = jnp.exp(s - lse_ref[0][:, :1])              # (bq, bk)
        if has_bias:
            p = jnp.where(s <= NEG_INF * 0.5, 0.0, p)
        do = do_ref[0].astype(jnp.float32)             # (bq, D)
        if rate > 0.0:
            # same (seed, bh, global q, global k) hash as the forward —
            # this kernel's grid swaps (ki, qi) but the mask arguments
            # stay in global-index order, so the tiles agree
            mask = _keep_mask(seed_ref[0], bh + seed_ref[1],
                              qi * bq, ki * bk, bq, bk, rate)
            pd = p * mask
            dp_scale = mask
        else:
            pd = p
            dp_scale = None
        dv_acc[:] += jax.lax.dot_general(
            pd, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)        # Pd^T @ do
        dp = jax.lax.dot_general(do, v_ref[0].astype(jnp.float32),
                                 (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        if dp_scale is not None:
            dp = dp * dp_scale
        ds = p * (dp - delta_ref[0][:, :1])
        dk_acc[:] += scale * jax.lax.dot_general(
            ds, q_ref[0].astype(jnp.float32), (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)        # ds^T @ q (unscaled q)
    last = qi == nq - 1

    @pl.when(last)
    def _finish():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _bwd(causal, scale, bq, bk, rate, n_heads, res, dout):
    q, k, v, seed, kb, out, lse = res
    BH, S, D = q.shape
    Sk = k.shape[1]
    nq, nk = S // bq, Sk // bk
    has_bias = kb is not None
    delta = jnp.sum(dout.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1)  # (BH, S)
    delta = jnp.broadcast_to(delta[..., None], (*delta.shape, 128))

    dq_specs = [
        pl.BlockSpec(memory_space=pltpu.SMEM),
        pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0)),
        pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0)),
        pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, bq, 128), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, bq, 128), lambda b, i, j: (b, i, 0)),
    ]
    dq_operands = [seed, q, k, v, dout, lse, delta]
    if has_bias:
        dq_specs.append(
            pl.BlockSpec((1, bk), lambda b, i, j: (b // n_heads, j)))
        dq_operands.append(kb)
    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal,
                          bq=bq, bk=bk, nk=nk, rate=rate,
                          has_bias=has_bias),
        grid=(BH, nq, nk),
        in_specs=dq_specs,
        out_specs=pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((BH, S, D), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32)],
        compiler_params=_compiler_params(),
        interpret=_interpret(),
    )(*dq_operands)

    dkv_specs = [
        pl.BlockSpec(memory_space=pltpu.SMEM),
        pl.BlockSpec((1, bq, D), lambda b, j, i: (b, i, 0)),
        pl.BlockSpec((1, bk, D), lambda b, j, i: (b, j, 0)),
        pl.BlockSpec((1, bk, D), lambda b, j, i: (b, j, 0)),
        pl.BlockSpec((1, bq, D), lambda b, j, i: (b, i, 0)),
        pl.BlockSpec((1, bq, 128), lambda b, j, i: (b, i, 0)),
        pl.BlockSpec((1, bq, 128), lambda b, j, i: (b, i, 0)),
    ]
    dkv_operands = [seed, q, k, v, dout, lse, delta]
    if has_bias:
        dkv_specs.append(
            pl.BlockSpec((1, bk), lambda b, j, i: (b // n_heads, j)))
        dkv_operands.append(kb)
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, causal=causal,
                          bq=bq, bk=bk, nq=nq, rate=rate,
                          has_bias=has_bias),
        grid=(BH, nk, nq),
        in_specs=dkv_specs,
        out_specs=[
            pl.BlockSpec((1, bk, D), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, bk, D), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, Sk, D), k.dtype),
            jax.ShapeDtypeStruct((BH, Sk, D), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, D), jnp.float32),
            pltpu.VMEM((bk, D), jnp.float32),
        ],
        compiler_params=_compiler_params(),
        interpret=_interpret(),
    )(*dkv_operands)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# public entry (BSHD) with custom VJP
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9, 10))
def _flash_bhsd(q, k, v, seed, kb, causal, scale, bq, bk, rate, n_heads):
    out, _ = _fwd(q, k, v, seed, kb, causal, scale, bq, bk, rate, n_heads)
    return out


def _flash_fwd_rule(q, k, v, seed, kb, causal, scale, bq, bk, rate,
                    n_heads):
    out, lse = _fwd(q, k, v, seed, kb, causal, scale, bq, bk, rate, n_heads)
    return out, (q, k, v, seed, kb, out, lse)


def _flash_bwd_rule(causal, scale, bq, bk, rate, n_heads, res, dout):
    return (*_bwd(causal, scale, bq, bk, rate, n_heads, res, dout),
            None, None)


_flash_bhsd.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def flash_attention(q, k, v, causal: bool = True,
                    scale: Optional[float] = None,
                    block_q: int = DEFAULT_BLOCK_Q,
                    block_k: int = DEFAULT_BLOCK_K,
                    dropout_rate: float = 0.0,
                    dropout_rng=None,
                    key_bias=None,
                    bh_offset=0):
    """Flash attention over [B, S, H, D] inputs (BSHD), causal or full.

    Requires S % block_q == 0 and S_k % block_k == 0 (the dispatcher in
    attention.py falls back to XLA otherwise).

    dropout_rate > 0 with a dropout_rng applies probability dropout inside
    the kernel (reference: attention-probability dropout in the fused CUDA
    layer, csrc/transformer/dropout_kernels.cu) — the mask is hash-generated
    per tile from a per-call seed, never materialised at [S, S], and
    regenerated identically in the backward kernels.

    key_bias is a per-key additive bias, [B, Sk] or [B, 1, 1, Sk] fp32
    (the BERT padding-mask convention: 0 keep, large-negative masked;
    reference adds it pre-softmax in softmax_kernels.cu). Rows whose keys
    are ALL masked produce zero output (the XLA path's softmax yields a
    uniform don't-care row there instead).

    bh_offset shifts the dropout hash's batch·head coordinate to the
    GLOBAL index: the in-kernel mask hashes (seed, bh, q, k) with bh the
    kernel-local program id, so when the inputs are a shard of a larger
    batch/head space (DP batch shards, Ulysses head shards under
    shard_map) every shard would otherwise draw the IDENTICAL mask
    pattern for its local slots.  Manual-partition callers pass
    `jax.lax.axis_index(axis) * local_BH` (may be traced — it rides the
    SMEM seed operand) and shards become decorrelated while matching
    the unsharded run bit-for-bit.
    """
    B, S, H, D = q.shape
    Sk = k.shape[1]
    if S % block_q or Sk % block_k:
        raise ValueError(f"seq lens ({S},{Sk}) not divisible by blocks "
                         f"({block_q},{block_k})")
    if not 0.0 <= dropout_rate < 1.0:
        raise ValueError(f"dropout_rate must be in [0, 1), got "
                         f"{dropout_rate}")
    scale = (D ** -0.5) if scale is None else scale
    seed, rate = derive_seed(dropout_rate, dropout_rng)
    # seed row 1 carries the global batch·head offset for the hash
    seed = jnp.concatenate(
        [seed, jnp.asarray(bh_offset, jnp.int32).reshape(1)])
    kb = None
    if key_bias is not None:
        kb = jnp.asarray(key_bias, jnp.float32).reshape(-1, Sk)
        kb = jnp.broadcast_to(kb, (B, Sk))
        # clamp so s + bias stays finite (finfo.min would NaN the exp)
        kb = jnp.maximum(kb, NEG_INF)
    to_bhsd = lambda t: t.transpose(0, 2, 1, 3).reshape(B * H, t.shape[1], D)
    out = _flash_bhsd(to_bhsd(q), to_bhsd(k), to_bhsd(v), seed, kb, causal,
                      scale, block_q, block_k, rate, H)
    return out.reshape(B, H, S, D).transpose(0, 2, 1, 3)
