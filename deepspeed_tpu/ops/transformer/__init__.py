from .attention import multihead_attention, xla_attention
from .flash_attention import flash_attention

__all__ = ["multihead_attention", "xla_attention", "flash_attention"]
