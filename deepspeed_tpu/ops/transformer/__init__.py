from .attention import multihead_attention, xla_attention
from .flash_attention import flash_attention
from .transformer import (DeepSpeedTransformerConfig,
                          DeepSpeedTransformerLayer,
                          init_transformer_params,
                          transformer_layer_forward)

__all__ = ["multihead_attention", "xla_attention", "flash_attention",
           "DeepSpeedTransformerConfig", "DeepSpeedTransformerLayer",
           "init_transformer_params", "transformer_layer_forward"]
