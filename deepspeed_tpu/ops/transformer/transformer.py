"""Fused BERT-style transformer layer — TPU-native equivalent of the
reference's largest native component.

Reference: deepspeed/ops/transformer/transformer.py (DeepSpeedTransformerConfig
:95, DeepSpeedTransformerLayer :485) backed by ~6700 LoC of CUDA
(csrc/transformer/ds_transformer_cuda.cpp:48-587 BertTransformerLayer, plus
normalize/dropout/softmax/transform/gelu kernel files). That design exists
because cuBLAS-era torch couldn't fuse; on TPU one jitted function of plain
jnp ops compiles to the same fused program the CUDA version hand-writes:

* QKV is ONE [h, 3h] matmul (reference strided-batch GEMM) -> MXU;
* attention dispatches through ops.transformer.multihead_attention
  (Pallas flash kernel on TPU, fused-XLA softmax path otherwise);
* bias+gelu, bias+dropout+residual, layernorm all fuse into the
  surrounding matmuls under XLA (reference: gelu_kernels.cu,
  dropout_kernels.cu, normalize_kernels.cu);
* `gelu_checkpoint` / `attn_dropout_checkpoint` / `normalize_invertible`
  become rematerialisation choices (jax.checkpoint) instead of
  save-fewer-tensors autograd bookkeeping — same memory effect, compiler
  does the recompute scheduling;
* `stochastic_mode`'s "up to 2% faster but non-deterministic" trade has no
  TPU analogue (XLA is deterministic); accepted and ignored.

Parameter names match the reference layer exactly (attn_qkvw, attn_qkvb,
attn_ow, attn_ob, attn_nw, attn_nb, inter_w, inter_b, output_w, output_b,
norm_w, norm_b — reference transformer.py:498-517) so module_inject can map
weights 1:1 in either direction.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from .attention import multihead_attention


@dataclasses.dataclass
class DeepSpeedTransformerConfig:
    """Config surface mirrors reference transformer.py:19-139.

    TPU notes: `batch_size`/`max_seq_length` were CUDA workspace-sizing
    hints (context.h workspace); XLA shapes are per-call, so they are
    accepted but only used as defaults for initialization helpers.
    `fp16` generalizes to `dtype` (bfloat16 preferred on TPU).
    """
    batch_size: int = -1
    hidden_size: int = -1
    intermediate_size: int = -1
    max_seq_length: int = -1
    heads: int = -1
    attn_dropout_ratio: float = -1
    hidden_dropout_ratio: float = -1
    num_hidden_layers: int = -1
    initializer_range: float = -1
    layer_norm_eps: float = 1e-12
    local_rank: int = -1
    seed: int = -1
    fp16: bool = False
    pre_layer_norm: bool = True
    normalize_invertible: bool = False
    gelu_checkpoint: bool = False
    adjust_init_range: bool = True
    attn_dropout_checkpoint: bool = False
    stochastic_mode: bool = False
    huggingface: bool = False
    training: bool = True
    # TPU-native extensions
    dtype: Any = None                 # compute dtype; None -> bf16 if fp16 else fp32
    attn_impl: str = "auto"           # auto|pallas|xla (ops/transformer)
    layer_id: int = -1
    # block-sparse attention (SparseAttentionUtils.replace_model_self_
    # attention_with_sparse_self_attention sets this)
    sparsity_config: Any = None

    def __post_init__(self):
        if self.intermediate_size in (-1, None) and self.hidden_size > 0:
            self.intermediate_size = 4 * self.hidden_size
        if self.dtype is None:
            self.dtype = jnp.bfloat16 if self.fp16 else jnp.float32

    @classmethod
    def from_dict(cls, json_object: Dict[str, Any]) -> "DeepSpeedTransformerConfig":
        """reference transformer.py:141-146."""
        fields = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in json_object.items() if k in fields})

    @classmethod
    def from_json_file(cls, json_file: str) -> "DeepSpeedTransformerConfig":
        """reference transformer.py:148-151."""
        with open(json_file, "r", encoding="utf-8") as reader:
            return cls.from_dict(json.loads(reader.read()))


def _layer_norm(x, w, b, eps):
    """fp32 statistics regardless of activation dtype (parity with the
    reference's normalize_kernels.cu which accumulates in fp32)."""
    xf = x.astype(jnp.float32)
    mu = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(xf - mu), axis=-1, keepdims=True)
    y = (xf - mu) * jax.lax.rsqrt(var + eps)
    return (y * w.astype(jnp.float32) + b.astype(jnp.float32)).astype(x.dtype)


def _dropout(x, rate, rng, train):
    # counter-hash mask, not bernoulli/threefry — see dropout.py for why
    from .dropout import hash_dropout

    return hash_dropout(x, rate, rng, train)


def init_transformer_params(config: DeepSpeedTransformerConfig, rng,
                            param_dtype=jnp.float32) -> Dict[str, jnp.ndarray]:
    """Weight init mirroring reference transformer.py:519-527: normal(0,
    initializer_range), with the output-facing matrices rescaled by
    1/sqrt(2*num_layers) when adjust_init_range (the Megatron-style
    residual-accumulation correction the reference applies via
    `output_std = initializer_range / sqrt(2.0 * num_layers)`)."""
    h = config.hidden_size
    ffn = config.intermediate_size
    std = config.initializer_range if config.initializer_range > 0 else 0.02
    out_std = std
    if config.adjust_init_range and config.num_hidden_layers > 0:
        out_std = std / (2.0 * config.num_hidden_layers) ** 0.5
    ks = jax.random.split(rng, 4)
    z = lambda *s: jnp.zeros(s, param_dtype)
    n = lambda k, s, sd: (sd * jax.random.normal(k, s)).astype(param_dtype)
    return {
        "attn_qkvw": n(ks[0], (h, 3 * h), std),
        "attn_qkvb": z(3 * h),
        "attn_ow": n(ks[1], (h, h), out_std),
        "attn_ob": z(h),
        "attn_nw": jnp.ones((h,), param_dtype),
        "attn_nb": z(h),
        "inter_w": n(ks[2], (h, ffn), std),
        "inter_b": z(ffn),
        "output_w": n(ks[3], (ffn, h), out_std),
        "output_b": z(h),
        "norm_w": jnp.ones((h,), param_dtype),
        "norm_b": z(h),
    }


def transformer_layer_forward(params: Dict[str, jnp.ndarray],
                              hidden_states: jnp.ndarray,
                              attention_mask: Optional[jnp.ndarray] = None,
                              *,
                              config: DeepSpeedTransformerConfig,
                              rng=None,
                              train: bool = False) -> jnp.ndarray:
    """One fused encoder layer. [B, S, H] -> [B, S, H].

    attention_mask follows the BERT additive convention: broadcastable to
    [B, heads, S, S], large-negative at masked positions (the reference's
    softmax kernel adds it pre-softmax, softmax_kernels.cu).

    Execution order matches reference ds_transformer_cuda.cpp:147-293
    (Forward): [pre-LN?] -> QKV gemm -> attention -> proj -> dropout ->
    +residual -> [post-LN?] -> LN -> FFN gemm -> gelu -> gemm -> dropout ->
    +residual -> [post-LN?].
    """
    cfg = config
    dtype = cfg.dtype
    x = hidden_states.astype(dtype)
    B, S, H = x.shape
    heads = cfg.heads
    hd = H // heads
    if rng is None:
        r_attn = r_hid1 = r_hid2 = None
    else:
        r_attn, r_hid1, r_hid2 = jax.random.split(rng, 3)

    def attention_block(x):
        inp = _layer_norm(x, params["attn_nw"], params["attn_nb"],
                          cfg.layer_norm_eps) if cfg.pre_layer_norm else x
        qkv = inp @ params["attn_qkvw"].astype(dtype) + \
            params["attn_qkvb"].astype(dtype)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        shape = (B, S, heads, hd)
        if cfg.sparsity_config is not None:
            from ..sparse_attention import SparseSelfAttention

            # the BERT additive mask [B,1,1,S] is a per-key bias: feed it
            # to the sparse kernel as an (already-additive) padding bias
            sparse = SparseSelfAttention(cfg.sparsity_config,
                                         key_padding_mask_mode="add")
            kpm = None
            if attention_mask is not None:
                kpm = jnp.broadcast_to(
                    jnp.asarray(attention_mask, jnp.float32),
                    (B, 1, 1, S))[:, 0, 0, :]
            ctx = sparse(q.reshape(shape), k.reshape(shape),
                         v.reshape(shape), key_padding_mask=kpm,
                         dropout_rate=(float(max(cfg.attn_dropout_ratio, 0.0))
                                       if train else 0.0),
                         dropout_rng=r_attn)
        else:
            ctx = multihead_attention(
                q.reshape(shape), k.reshape(shape), v.reshape(shape),
                causal=False, impl=cfg.attn_impl, bias=attention_mask,
                dropout_rate=float(max(cfg.attn_dropout_ratio, 0.0)),
                dropout_rng=r_attn, train=train)
        ctx = ctx.reshape(B, S, H)
        out = ctx @ params["attn_ow"].astype(dtype) + \
            params["attn_ob"].astype(dtype)
        out = _dropout(out, float(max(cfg.hidden_dropout_ratio, 0.0)),
                       r_hid1, train)
        out = out + x
        if not cfg.pre_layer_norm:
            out = _layer_norm(out, params["attn_nw"], params["attn_nb"],
                              cfg.layer_norm_eps)
        return out

    def ffn_block(a):
        inp = _layer_norm(a, params["norm_w"], params["norm_b"],
                          cfg.layer_norm_eps) if cfg.pre_layer_norm else a
        inter = inp @ params["inter_w"].astype(dtype) + \
            params["inter_b"].astype(dtype)
        inter = jax.nn.gelu(inter, approximate=True)
        out = inter @ params["output_w"].astype(dtype) + \
            params["output_b"].astype(dtype)
        out = _dropout(out, float(max(cfg.hidden_dropout_ratio, 0.0)),
                       r_hid2, train)
        out = out + a
        if not cfg.pre_layer_norm:
            out = _layer_norm(out, params["norm_w"], params["norm_b"],
                              cfg.layer_norm_eps)
        return out

    # memory-saving modes -> rematerialisation (reference saves fewer
    # tensors in autograd ctx, transformer.py:171-460; same working-set
    # effect here via jax.checkpoint)
    if cfg.attn_dropout_checkpoint or cfg.normalize_invertible:
        attention_block = jax.checkpoint(attention_block)
    if cfg.gelu_checkpoint or cfg.normalize_invertible:
        ffn_block = jax.checkpoint(ffn_block)

    return ffn_block(attention_block(x)).astype(hidden_states.dtype)


class DeepSpeedTransformerLayer:
    """API-parity wrapper (reference transformer.py:463-614).

    Functional use:
        layer = DeepSpeedTransformerLayer(config)
        params = layer.init(rng)                  # or adopt external weights
        y = layer(params, x, attention_mask, rng=rng, train=True)

    `initial_weights`/`initial_biases` adopt an existing layer's tensors in
    the reference order [qkvw|q,k,v split, ow, nw, inter_w, output_w,
    norm_w] (reference transformer.py:485-545, huggingface mode splits QKV).
    A 6-tensor list is taken as this framework's [in, out] layout; an
    8-tensor list (separate q/k/v) is the huggingface/torch nn.Linear
    layout with [out, in] matrices and is transposed on adoption.
    """

    layer_id = 0  # class-level running id, parity with reference :483

    def __init__(self, config: DeepSpeedTransformerConfig,
                 initial_weights=None, initial_biases=None):
        self.config = config
        self.config.layer_id = DeepSpeedTransformerLayer.layer_id
        DeepSpeedTransformerLayer.layer_id += 1
        self._initial = None
        if initial_weights is not None and initial_biases is not None:
            self._initial = (initial_weights, initial_biases)

    def init(self, rng, param_dtype=jnp.float32) -> Dict[str, jnp.ndarray]:
        if self._initial is not None:
            ws, bs = self._initial
            ws = [jnp.asarray(w) for w in ws]
            bs = [jnp.asarray(b) for b in bs]
            if len(ws) == 8:  # q,k,v separate: torch [out, in] layout
                ws = [w.T if w.ndim == 2 else w for w in ws]
                qkvw = jnp.concatenate(ws[0:3], axis=-1)
                qkvb = jnp.concatenate(bs[0:3], axis=-1)
                ws = [qkvw] + ws[3:]
                bs = [qkvb] + bs[3:]
            names = ["attn_qkv", "attn_o", "attn_n", "inter_", "output_",
                     "norm_"]
            out = {}
            for name, w, b in zip(names, ws, bs):
                out[name + "w"] = w.astype(param_dtype)
                out[name + "b"] = b.astype(param_dtype)
            return out
        return init_transformer_params(self.config, rng, param_dtype)

    def __call__(self, params, hidden_states, attention_mask=None,
                 rng=None, train: Optional[bool] = None):
        train = self.config.training if train is None else train
        return transformer_layer_forward(
            params, hidden_states, attention_mask,
            config=self.config, rng=rng, train=train)

    # torch-API compat shim
    def forward(self, params, hidden_states, attention_mask=None,
                rng=None, train: Optional[bool] = None):
        return self(params, hidden_states, attention_mask, rng, train)
