"""Counter-hash activation dropout — the TPU-cheap mask generator.

jax.random.bernoulli runs the full threefry block cipher per element;
on the VPU that is a long serial multiply/rotate chain that can rival
the surrounding matmul at BERT-recipe activation sizes. The reference
never pays this: its fused kernels draw from curand Philox — one cheap
per-launch seed plus a counter (csrc/transformer/dropout_kernels.cu).
This is the same design in XLA: ONE tiny threefry call derives a scalar
seed from the caller's PRNG key (so jax.random semantics — split,
fold_in — still govern stream independence), then a murmur3-finalizer
hash over the element counter produces the mask in ~6 fused integer ops
per element. Mixing constants shared with the flash kernels' in-kernel
masks (ops/transformer/flash_attention.py _keep_mask).

Determinism: same key -> same mask (the hash is pure); backward sees
the identical mask through ordinary AD of the where().
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def hash_dropout(x, rate, rng, train: bool = True):
    """Inverted dropout on x: zero with probability `rate`, survivors
    scaled by 1/keep. No-op when not training / rate 0 / rng None."""
    if not train or rate <= 0.0 or rng is None:
        return x
    if not 0.0 <= rate < 1.0:
        raise ValueError(f"dropout rate must be in [0, 1), got {rate}")
    keep = 1.0 - rate
    if x.size >= 1 << 32:
        # the uint32 element counter would wrap and repeat masks across
        # the tensor; tensors this large (>4.3e9 elements) are rare
        # enough that the threefry path's cost is acceptable
        mask = jax.random.bernoulli(rng, keep, x.shape)
        return jnp.where(mask, x / keep, 0).astype(x.dtype)
    from .flash_attention import derive_seed, fmix32, keep_threshold

    seed, _ = derive_seed(rate, rng)
    u = jnp.uint32
    idx = jax.lax.iota(u, x.size)
    h = fmix32((seed[0].astype(u) * u(0x9E3779B1)) ^ (idx * u(0x85EBCA6B)))
    mask = (h < keep_threshold(rate)).reshape(x.shape)
    return jnp.where(mask, x / keep, 0).astype(x.dtype)
