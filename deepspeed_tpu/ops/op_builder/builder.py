"""Native-op build system: JIT-compile C++ sources to shared libs, ctypes.

Reference: /root/reference/op_builder/builder.py (OpBuilder/CUDAOpBuilder —
per-op builder classes with is_compatible(), JIT load via
torch.utils.cpp_extension, DS_BUILD_* env switches). TPU-native version:
device kernels are Pallas/XLA (no build step), so this builder only covers
the HOST-native C++ components (cpu_adam, aio, flatten); it compiles with
g++ -O3 -march=native -fopenmp into a content-hashed cache and loads the
result with ctypes (pybind11 is not in this image).
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import shutil
import subprocess
from pathlib import Path
from typing import Dict, List, Optional

from ...utils.logging import logger

REPO_ROOT = Path(__file__).resolve().parents[3]
CSRC = REPO_ROOT / "csrc"


def _cache_dir() -> Path:
    base = os.environ.get("DSTPU_OPS_CACHE",
                          os.path.join(os.path.expanduser("~"), ".cache",
                                       "deepspeed_tpu", "ops"))
    p = Path(base)
    p.mkdir(parents=True, exist_ok=True)
    return p


class OpBuilder:
    NAME: str = "base"

    def sources(self) -> List[str]:
        """Source paths relative to csrc/."""
        raise NotImplementedError

    def extra_cflags(self) -> List[str]:
        return []

    def extra_ldflags(self) -> List[str]:
        return []

    def compiler(self) -> Optional[str]:
        return shutil.which(os.environ.get("CXX", "g++"))

    def is_compatible(self) -> bool:
        if os.environ.get(f"DS_BUILD_{self.NAME.upper()}", "1") == "0":
            return False
        return self.compiler() is not None

    def compatibility_message(self) -> str:
        if self.compiler() is None:
            return "no C++ compiler found"
        return "compatible"

    def _hash(self, srcs: List[Path]) -> str:
        h = hashlib.sha256()
        for s in srcs:
            h.update(s.read_bytes())
        h.update(" ".join(self.extra_cflags() + self.extra_ldflags()).encode())
        return h.hexdigest()[:16]

    def lib_path(self) -> Path:
        srcs = [CSRC / s for s in self.sources()]
        return _cache_dir() / f"{self.NAME}_{self._hash(srcs)}.so"

    def build(self) -> Path:
        srcs = [CSRC / s for s in self.sources()]
        out = self.lib_path()
        if out.exists():
            return out
        cxx = self.compiler()
        if cxx is None:
            raise RuntimeError(f"op {self.NAME}: no C++ compiler")
        cmd = [cxx, "-O3", "-shared", "-fPIC", "-std=c++17",
               "-march=native", "-fopenmp",
               *self.extra_cflags(),
               *[str(s) for s in srcs],
               "-o", str(out),
               *self.extra_ldflags()]
        logger.info(f"building native op {self.NAME}: {' '.join(cmd)}")
        try:
            subprocess.run(cmd, check=True, capture_output=True, text=True)
        except subprocess.CalledProcessError as e:
            raise RuntimeError(
                f"op {self.NAME} build failed:\n{e.stderr}") from e
        return out

    def load(self) -> ctypes.CDLL:
        if not self.is_compatible():
            raise RuntimeError(
                f"op {self.NAME} unavailable: {self.compatibility_message()}")
        lib = ctypes.CDLL(str(self.build()))
        self._bind(lib)
        return lib

    def _bind(self, lib: ctypes.CDLL) -> None:
        """Set argtypes/restype on the loaded library (subclass hook)."""


class CPUAdamBuilder(OpBuilder):
    NAME = "cpu_adam"

    def sources(self):
        return ["adam/cpu_adam.cpp"]

    def _bind(self, lib):
        f32p = ctypes.POINTER(ctypes.c_float)
        u16p = ctypes.POINTER(ctypes.c_uint16)
        lib.ds_adam_step.restype = None
        lib.ds_adam_step.argtypes = [
            ctypes.c_int64, f32p, f32p, f32p, f32p,
            ctypes.c_float, ctypes.c_float, ctypes.c_float, ctypes.c_float,
            ctypes.c_float, ctypes.c_int, ctypes.c_float, ctypes.c_float]
        lib.ds_adam_step_bf16.restype = None
        lib.ds_adam_step_bf16.argtypes = [
            ctypes.c_int64, f32p, f32p, f32p, f32p, u16p,
            ctypes.c_float, ctypes.c_float, ctypes.c_float, ctypes.c_float,
            ctypes.c_float, ctypes.c_int, ctypes.c_float, ctypes.c_float]


class AsyncIOBuilder(OpBuilder):
    NAME = "async_io"

    def sources(self):
        return ["aio/ds_aio.cpp"]

    def extra_ldflags(self):
        return ["-lpthread"]

    def _bind(self, lib):
        lib.aio_handle_create.restype = ctypes.c_void_p
        lib.aio_handle_create.argtypes = [ctypes.c_int, ctypes.c_int,
                                          ctypes.c_int]
        lib.aio_handle_create2.restype = ctypes.c_void_p
        lib.aio_handle_create2.argtypes = [ctypes.c_int, ctypes.c_int,
                                           ctypes.c_int, ctypes.c_int]
        lib.aio_uring_supported.restype = ctypes.c_int
        lib.aio_uring_supported.argtypes = []
        lib.aio_handle_engine.restype = ctypes.c_int
        lib.aio_handle_engine.argtypes = [ctypes.c_void_p]
        lib.aio_handle_destroy.restype = None
        lib.aio_handle_destroy.argtypes = [ctypes.c_void_p]
        common = [ctypes.c_void_p, ctypes.c_void_p, ctypes.c_char_p,
                  ctypes.c_int64, ctypes.c_int64, ctypes.c_int]
        lib.aio_pwrite.restype = ctypes.c_int
        lib.aio_pwrite.argtypes = common
        lib.aio_pread.restype = ctypes.c_int
        lib.aio_pread.argtypes = common
        lib.aio_wait.restype = ctypes.c_int
        lib.aio_wait.argtypes = [ctypes.c_void_p]


class UtilsBuilder(OpBuilder):
    NAME = "utils"

    def sources(self):
        return ["utils/flatten.cpp"]

    def _bind(self, lib):
        vpp = ctypes.POINTER(ctypes.c_void_p)
        i64p = ctypes.POINTER(ctypes.c_int64)
        lib.ds_flatten.restype = None
        lib.ds_flatten.argtypes = [ctypes.c_int64, vpp, i64p, ctypes.c_void_p]
        lib.ds_unflatten.restype = None
        lib.ds_unflatten.argtypes = [ctypes.c_int64, vpp, i64p,
                                     ctypes.c_void_p]


ALL_OPS: Dict[str, type] = {
    CPUAdamBuilder.NAME: CPUAdamBuilder,
    AsyncIOBuilder.NAME: AsyncIOBuilder,
    UtilsBuilder.NAME: UtilsBuilder,
}

_LOADED: Dict[str, ctypes.CDLL] = {}


def get_op(name: str) -> ctypes.CDLL:
    """Load (building if needed) a native op library, cached per process."""
    if name not in _LOADED:
        _LOADED[name] = ALL_OPS[name]().load()
    return _LOADED[name]
