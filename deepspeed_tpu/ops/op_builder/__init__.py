from .builder import (ALL_OPS, AsyncIOBuilder, CPUAdamBuilder, OpBuilder,
                      UtilsBuilder, get_op)

__all__ = ["ALL_OPS", "OpBuilder", "CPUAdamBuilder", "AsyncIOBuilder",
           "UtilsBuilder", "get_op"]
