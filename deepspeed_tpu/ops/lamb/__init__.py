from .fused_lamb import FusedLamb  # noqa: F401
