"""FusedLamb — LAMB with per-tensor trust ratio as a fused jitted update.

Reference: deepspeed/ops/lamb/fused_lamb.py + csrc/lamb/fused_lamb_cuda_kernel.cu.
The CUDA kernel's reduction workspace (for ||p|| and ||update||) is XLA's
problem here; semantics kept: trust ratio = ||p|| / ||adam_update + wd*p||
clamped to [min_coeff, max_coeff], applied per tensor.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


class FusedLamb:
    name = "FusedLamb"

    def __init__(self, params=None, lr=1e-3, bias_correction=True,
                 betas=(0.9, 0.999), eps=1e-8, eps_inside_sqrt=False,
                 weight_decay=0.0, max_grad_norm=0.0, max_coeff=10.0,
                 min_coeff=0.01, amsgrad=False):
        if amsgrad:
            raise RuntimeError("FusedLamb does not support the AMSGrad variant.")
        self.defaults = dict(lr=lr, betas=betas, eps=eps,
                             weight_decay=weight_decay,
                             bias_correction=bias_correction,
                             max_coeff=max_coeff, min_coeff=min_coeff)
        self.param_groups = [dict(self.defaults)]
        self.eps_inside_sqrt = eps_inside_sqrt

    @property
    def lr(self):
        return self.param_groups[0]["lr"]

    def init(self, params):
        zeros = lambda p: jnp.zeros(p.shape, dtype=jnp.float32)
        return {
            "step": jnp.zeros((), dtype=jnp.int32),
            "exp_avg": jax.tree_util.tree_map(zeros, params),
            "exp_avg_sq": jax.tree_util.tree_map(zeros, params),
        }

    def update(self, grads, state, params, lr=None):
        g = self.param_groups[0]
        lr = g["lr"] if lr is None else lr
        beta1, beta2 = g["betas"]
        eps = g["eps"]
        wd = g["weight_decay"]
        max_coeff, min_coeff = g["max_coeff"], g["min_coeff"]
        step = state["step"] + 1

        if g["bias_correction"]:
            bc1 = 1.0 - beta1 ** step.astype(jnp.float32)
            bc2 = 1.0 - beta2 ** step.astype(jnp.float32)
        else:
            bc1 = bc2 = jnp.asarray(1.0, jnp.float32)

        def upd(p, grad, m, v):
            grad = grad.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            m = beta1 * m + (1.0 - beta1) * grad
            v = beta2 * v + (1.0 - beta2) * grad * grad
            if self.eps_inside_sqrt:
                denom = jnp.sqrt(v / bc2 + eps)
            else:
                denom = jnp.sqrt(v / bc2) + eps
            adam_step = (m / bc1) / denom
            if wd:
                adam_step = adam_step + wd * p32
            p_norm = jnp.linalg.norm(p32.reshape(-1))
            u_norm = jnp.linalg.norm(adam_step.reshape(-1))
            trust = jnp.where(u_norm > 0.0, p_norm / jnp.maximum(u_norm, 1e-12),
                              1.0)
            trust = jnp.where(p_norm > 0.0, trust, 1.0)
            trust = jnp.clip(trust, min_coeff, max_coeff)
            new_p = p32 - lr * trust * adam_step
            return new_p.astype(p.dtype), m, v

        p_leaves, treedef = jax.tree_util.tree_flatten(params)
        g_leaves = treedef.flatten_up_to(grads)
        m_leaves = treedef.flatten_up_to(state["exp_avg"])
        v_leaves = treedef.flatten_up_to(state["exp_avg_sq"])
        out = [upd(p, g_, m, v) for p, g_, m, v
               in zip(p_leaves, g_leaves, m_leaves, v_leaves)]
        unflat = lambda i: jax.tree_util.tree_unflatten(treedef,
                                                        [t[i] for t in out])
        return unflat(0), {"step": step, "exp_avg": unflat(1),
                           "exp_avg_sq": unflat(2)}

    def state_dict(self):
        return {"param_groups": self.param_groups}

    def load_state_dict(self, sd):
        self.param_groups = sd["param_groups"]
