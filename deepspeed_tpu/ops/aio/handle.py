"""Python wrapper over the native async file-I/O engine.

Reference API: /root/reference/csrc/aio/py_lib/deepspeed_py_aio_handle.cpp
(aio_handle with read/write/pread/pwrite + wait) and ops/aio. Backing
engines (csrc/aio/ds_aio.cpp): a raw-syscall io_uring engine (the
kernel-async analogue of the reference's libaio io_submit path) with a
std::thread pread/pwrite pool as the portable fallback.
"""

from __future__ import annotations

import ctypes
import os
from typing import Optional

import numpy as np

from ..op_builder import get_op


def uring_supported() -> bool:
    """True iff an io_uring ring can be created (kernel + seccomp)."""
    return bool(get_op("async_io").aio_uring_supported())


def alloc_aligned(nbytes: int, dtype=np.uint8, align: int = 4096):
    """Buffer whose data pointer is `align`-aligned — O_DIRECT needs
    4 KiB-aligned address/length/offset or the engine silently degrades
    that op to buffered I/O."""
    dt = np.dtype(dtype)
    raw = np.empty(nbytes + align, np.uint8)
    off = (-raw.ctypes.data) % align
    return raw[off:off + nbytes].view(dt)


class AsyncIOHandle:
    """Submit async reads/writes of numpy buffers against files.

    Usage:
        h = AsyncIOHandle(n_threads=4)
        h.async_pwrite(arr, "/ssd/shard0.bin")
        ... overlap compute ...
        h.wait()

    engine: "auto" (io_uring when the kernel allows it, else the thread
    pool — override with DSTPU_AIO_ENGINE), "uring", or "threads".
    n_threads doubles as the io_uring SQ depth.
    """

    def __init__(self, n_threads: int = 4, block_size: int = 1 << 20,
                 o_direct: bool = False, engine: str = "auto"):
        self._lib = get_op("async_io")
        if engine == "auto":  # env steers only the default, never an
            engine = os.environ.get("DSTPU_AIO_ENGINE",  # explicit arg
                                    engine).lower()
        codes = {"auto": 0, "threads": 1, "uring": 2}
        if engine not in codes:
            raise ValueError(f"unknown aio engine {engine!r}; "
                             f"use auto | threads | uring")
        self._h = self._lib.aio_handle_create2(int(n_threads),
                                               int(block_size),
                                               1 if o_direct else 0,
                                               codes[engine])
        if not self._h:
            raise RuntimeError(
                f"aio engine {engine!r} unavailable "
                f"(io_uring blocked by kernel/seccomp?)")
        # what was ACTUALLY built (auto may fall back mid-construction)
        self.engine = {1: "threads",
                       2: "uring"}[self._lib.aio_handle_engine(self._h)]
        self._pinned = []  # keep submitted buffers alive until wait()

    def _buf(self, arr: np.ndarray):
        assert arr.flags["C_CONTIGUOUS"], "aio requires contiguous buffers"
        self._pinned.append(arr)
        return arr.ctypes.data_as(ctypes.c_void_p), arr.nbytes

    def async_pwrite(self, arr: np.ndarray, path: str, file_offset: int = 0):
        ptr, nbytes = self._buf(arr)
        rc = self._lib.aio_pwrite(self._h, ptr, path.encode(), nbytes,
                                  file_offset, 1)
        if rc != 0:
            raise IOError(f"aio_pwrite submit failed for {path}")

    def async_pread(self, arr: np.ndarray, path: str, file_offset: int = 0):
        ptr, nbytes = self._buf(arr)
        rc = self._lib.aio_pread(self._h, ptr, path.encode(), nbytes,
                                 file_offset, 1)
        if rc != 0:
            raise IOError(f"aio_pread submit failed for {path}")

    def sync_pwrite(self, arr: np.ndarray, path: str, file_offset: int = 0):
        self.async_pwrite(arr, path, file_offset)
        self.wait()

    def sync_pread(self, arr: np.ndarray, path: str, file_offset: int = 0):
        self.async_pread(arr, path, file_offset)
        self.wait()

    def wait(self):
        errors = self._lib.aio_wait(self._h)
        self._pinned.clear()
        if errors:
            raise IOError(f"aio: {errors} operation(s) failed")

    def close(self):
        if self._h is not None:
            self._lib.aio_handle_destroy(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


_DEFAULT: Optional[AsyncIOHandle] = None


def _default() -> AsyncIOHandle:
    global _DEFAULT
    if _DEFAULT is None:
        _DEFAULT = AsyncIOHandle()
    return _DEFAULT


def aio_write(arr: np.ndarray, path: str):
    """Blocking convenience write (reference deepspeed_py_aio.cpp)."""
    _default().sync_pwrite(arr, path)


def aio_read(arr: np.ndarray, path: str):
    _default().sync_pread(arr, path)
