"""Python wrapper over the native async file-I/O engine.

Reference API: /root/reference/csrc/aio/py_lib/deepspeed_py_aio_handle.cpp
(aio_handle with read/write/pread/pwrite + wait) and ops/aio. Backing
engine: csrc/aio/ds_aio.cpp (thread pool + pread/pwrite, O_DIRECT when the
filesystem supports it — this image has no libaio headers).
"""

from __future__ import annotations

import ctypes
from typing import Optional

import numpy as np

from ..op_builder import get_op


class AsyncIOHandle:
    """Submit async reads/writes of numpy buffers against files.

    Usage:
        h = AsyncIOHandle(n_threads=4)
        h.async_pwrite(arr, "/ssd/shard0.bin")
        ... overlap compute ...
        h.wait()
    """

    def __init__(self, n_threads: int = 4, block_size: int = 1 << 20,
                 o_direct: bool = False):
        self._lib = get_op("async_io")
        self._h = self._lib.aio_handle_create(int(n_threads), int(block_size),
                                              1 if o_direct else 0)
        self._pinned = []  # keep submitted buffers alive until wait()

    def _buf(self, arr: np.ndarray):
        assert arr.flags["C_CONTIGUOUS"], "aio requires contiguous buffers"
        self._pinned.append(arr)
        return arr.ctypes.data_as(ctypes.c_void_p), arr.nbytes

    def async_pwrite(self, arr: np.ndarray, path: str, file_offset: int = 0):
        ptr, nbytes = self._buf(arr)
        rc = self._lib.aio_pwrite(self._h, ptr, path.encode(), nbytes,
                                  file_offset, 1)
        if rc != 0:
            raise IOError(f"aio_pwrite submit failed for {path}")

    def async_pread(self, arr: np.ndarray, path: str, file_offset: int = 0):
        ptr, nbytes = self._buf(arr)
        rc = self._lib.aio_pread(self._h, ptr, path.encode(), nbytes,
                                 file_offset, 1)
        if rc != 0:
            raise IOError(f"aio_pread submit failed for {path}")

    def sync_pwrite(self, arr: np.ndarray, path: str, file_offset: int = 0):
        self.async_pwrite(arr, path, file_offset)
        self.wait()

    def sync_pread(self, arr: np.ndarray, path: str, file_offset: int = 0):
        self.async_pread(arr, path, file_offset)
        self.wait()

    def wait(self):
        errors = self._lib.aio_wait(self._h)
        self._pinned.clear()
        if errors:
            raise IOError(f"aio: {errors} operation(s) failed")

    def close(self):
        if self._h is not None:
            self._lib.aio_handle_destroy(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


_DEFAULT: Optional[AsyncIOHandle] = None


def _default() -> AsyncIOHandle:
    global _DEFAULT
    if _DEFAULT is None:
        _DEFAULT = AsyncIOHandle()
    return _DEFAULT


def aio_write(arr: np.ndarray, path: str):
    """Blocking convenience write (reference deepspeed_py_aio.cpp)."""
    _default().sync_pwrite(arr, path)


def aio_read(arr: np.ndarray, path: str):
    _default().sync_pread(arr, path)
