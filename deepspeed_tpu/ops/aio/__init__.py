from .handle import (AsyncIOHandle, aio_read, aio_write, alloc_aligned,
                     uring_supported)
from ..op_builder import AsyncIOBuilder  # reference ops/aio exports it

__all__ = ["AsyncIOHandle", "aio_read", "aio_write", "AsyncIOBuilder",
           "alloc_aligned", "uring_supported"]
