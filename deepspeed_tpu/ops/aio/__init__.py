from .handle import AsyncIOHandle, aio_read, aio_write
from ..op_builder import AsyncIOBuilder  # reference ops/aio exports it

__all__ = ["AsyncIOHandle", "aio_read", "aio_write", "AsyncIOBuilder"]
