from .handle import AsyncIOHandle, aio_read, aio_write

__all__ = ["AsyncIOHandle", "aio_read", "aio_write"]
