"""FusedAdam — Adam/AdamW as one fused jitted update.

Reference: deepspeed/ops/adam/fused_adam.py:15 + csrc/adam/multi_tensor_adam.cu.
The CUDA version exists to batch many small param updates into one kernel
launch; under XLA a single jitted pytree update compiles to fused kernels
already, so the TPU-native design is a pure function over the whole param
pytree. API (ctor args, param_groups, adam_w_mode) matches the reference.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


class FusedAdam:
    """Adam optimizer with decoupled (AdamW, default) or L2 weight decay.

    Functional usage inside the engine's jitted step:
        state = opt.init(params)
        new_params, new_state = opt.update(grads, state, params, lr=lr)
    """

    name = "FusedAdam"

    def __init__(self, params=None, lr=1e-3, bias_correction=True,
                 betas=(0.9, 0.999), eps=1e-8, adam_w_mode=True,
                 weight_decay=0.0, amsgrad=False, set_grad_none=True):
        if amsgrad:
            raise RuntimeError("FusedAdam does not support the AMSGrad variant.")
        self.defaults = dict(lr=lr, betas=betas, eps=eps,
                             weight_decay=weight_decay,
                             bias_correction=bias_correction)
        # param_groups kept for scheduler API parity (reference torch optim)
        self.param_groups = [dict(self.defaults)]
        self.adam_w_mode = adam_w_mode
        self.set_grad_none = set_grad_none

    @property
    def lr(self):
        return self.param_groups[0]["lr"]

    def init(self, params):
        zeros = lambda p: jnp.zeros(p.shape, dtype=jnp.float32)
        return {
            "step": jnp.zeros((), dtype=jnp.int32),
            "exp_avg": jax.tree_util.tree_map(zeros, params),
            "exp_avg_sq": jax.tree_util.tree_map(zeros, params),
        }

    def update(self, grads, state, params, lr=None):
        """Pure fused update. lr may be a traced scalar (from the scheduler)."""
        g = self.param_groups[0]
        lr = g["lr"] if lr is None else lr
        beta1, beta2 = g["betas"]
        eps = g["eps"]
        wd = g["weight_decay"]
        step = state["step"] + 1

        if g["bias_correction"]:
            bc1 = 1.0 - beta1 ** step.astype(jnp.float32)
            bc2 = 1.0 - beta2 ** step.astype(jnp.float32)
        else:
            bc1 = bc2 = jnp.asarray(1.0, jnp.float32)

        def upd(p, grad, m, v):
            grad = grad.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            if not self.adam_w_mode and wd:
                grad = grad + wd * p32
            m = beta1 * m + (1.0 - beta1) * grad
            v = beta2 * v + (1.0 - beta2) * grad * grad
            denom = jnp.sqrt(v / bc2) + eps
            new_p = p32 - lr * (m / bc1) / denom
            if self.adam_w_mode and wd:
                new_p = new_p - lr * wd * p32
            return new_p.astype(p.dtype), m, v

        p_leaves, treedef = jax.tree_util.tree_flatten(params)
        g_leaves = treedef.flatten_up_to(grads)
        m_leaves = treedef.flatten_up_to(state["exp_avg"])
        v_leaves = treedef.flatten_up_to(state["exp_avg_sq"])
        out = [upd(p, g_, m, v) for p, g_, m, v
               in zip(p_leaves, g_leaves, m_leaves, v_leaves)]
        unflat = lambda i: jax.tree_util.tree_unflatten(treedef,
                                                        [t[i] for t in out])
        return unflat(0), {"step": step, "exp_avg": unflat(1),
                           "exp_avg_sq": unflat(2)}

    # checkpoint parity -------------------------------------------------
    def state_dict(self):
        return {"param_groups": self.param_groups,
                "adam_w_mode": self.adam_w_mode}

    def load_state_dict(self, sd):
        self.param_groups = sd["param_groups"]
        self.adam_w_mode = sd.get("adam_w_mode", self.adam_w_mode)


class DeepSpeedCPUAdam(FusedAdam):
    """Host-offload Adam (reference ops/adam/cpu_adam.py).

    Falls back to the jitted device update until the native C++ SIMD
    extension (csrc/cpu_adam) is used by the offload runtime; the class
    exists so configs naming it resolve.
    """

    name = "DeepSpeedCPUAdam"
