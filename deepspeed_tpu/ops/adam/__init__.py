from .fused_adam import DeepSpeedCPUAdam, FusedAdam  # noqa: F401
