"""DeepSpeedCPUAdam — native host Adam for optimizer-state offload.

Reference: /root/reference/ops/adam/cpu_adam.py + csrc/adam/cpu_adam.cpp
(AVX/OpenMP host Adam used by ZeRO-Offload). The TPU build keeps fp32
master params + moments in host RAM (numpy), runs the vectorized C++ step
(csrc/adam/cpu_adam.cpp via ctypes), and hands bf16/fp32 weights back for
device upload — freeing HBM of all optimizer state.
"""

from __future__ import annotations

import ctypes
from typing import Dict

import numpy as np

from ..op_builder import CPUAdamBuilder, get_op


def _f32p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


class HostAdam:
    """Flat-buffer host Adam over numpy arrays (one buffer per param leaf).

    Not a jax optimizer: it mutates host state in place — by design, this
    is the offload path that keeps optimizer state out of HBM.
    """

    def __init__(self, lr=1e-3, betas=(0.9, 0.999), eps=1e-8,
                 weight_decay=0.0, adam_w_mode=True, bias_correction=True):
        self.lr = lr
        self.betas = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.adam_w_mode = adam_w_mode
        self.bias_correction = bias_correction
        self.step_count = 0
        self._lib = get_op(CPUAdamBuilder.NAME)
        self._state: Dict[int, Dict[str, np.ndarray]] = {}

    def state_for(self, key: int, n: int):
        if key not in self._state:
            self._state[key] = {
                "m": np.zeros(n, np.float32),
                "v": np.zeros(n, np.float32),
            }
        return self._state[key]

    def begin_step(self):
        self.step_count += 1
        if self.bias_correction:
            self._bc1 = 1.0 - self.betas[0] ** self.step_count
            self._bc2 = 1.0 - self.betas[1] ** self.step_count
        else:
            self._bc1 = self._bc2 = 1.0

    def update_flat(self, key: int, params: np.ndarray, grads: np.ndarray,
                    lr=None, out_bf16: np.ndarray = None):
        """In-place Adam on one flat fp32 buffer; optionally emit bf16."""
        assert params.dtype == np.float32 and grads.dtype == np.float32
        n = params.size
        st = self.state_for(key, n)
        args = (n, _f32p(params), _f32p(grads), _f32p(st["m"]),
                _f32p(st["v"]))
        hp = (ctypes.c_float(self.lr if lr is None else lr),
              ctypes.c_float(self.betas[0]), ctypes.c_float(self.betas[1]),
              ctypes.c_float(self.eps), ctypes.c_float(self.weight_decay),
              1 if self.adam_w_mode else 0,
              ctypes.c_float(self._bc1), ctypes.c_float(self._bc2))
        if out_bf16 is not None:
            assert out_bf16.dtype == np.uint16 and out_bf16.size == n
            u16 = out_bf16.ctypes.data_as(ctypes.POINTER(ctypes.c_uint16))
            self._lib.ds_adam_step_bf16(*args, u16, *hp)
        else:
            self._lib.ds_adam_step(*args, *hp)

    # checkpointing ----------------------------------------------------
    def state_dict(self):
        # string keys: msgpack (checkpoint wire format) rejects int map keys
        return {"step": self.step_count,
                "state": {str(k): {kk: vv.copy() for kk, vv in s.items()}
                          for k, s in self._state.items()}}

    def load_state_dict(self, sd):
        self.step_count = int(sd["step"])
        self._state = {int(k): {kk: np.asarray(vv, np.float32)
                                for kk, vv in s.items()}
                       for k, s in sd["state"].items()}
