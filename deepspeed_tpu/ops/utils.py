"""Native flatten/unflatten of numpy tensor lists.

Reference: /root/reference/csrc/utils/flatten_unflatten.cpp:21-24 (loaded by
engine.py:218-220 and ZeRO stage2.py:122-124 for contiguous grad buffers).
On TPU the jitted step keeps device tensors unflattened (XLA fuses); this
native path serves the HOST side: staging offload shards contiguously for
aio writes and host-Adam steps.
"""

from __future__ import annotations

import ctypes
from typing import List, Sequence

import numpy as np

from .op_builder import UtilsBuilder, get_op


def _ptr_array(arrs: Sequence[np.ndarray], writable: bool):
    n = len(arrs)
    ptrs = (ctypes.c_void_p * n)()
    sizes = (ctypes.c_int64 * n)()
    for i, a in enumerate(arrs):
        assert a.flags["C_CONTIGUOUS"]
        if writable:
            assert a.flags["WRITEABLE"]
        ptrs[i] = a.ctypes.data
        sizes[i] = a.nbytes
    return ptrs, sizes


def flatten(tensors: Sequence[np.ndarray]) -> np.ndarray:
    """Pack tensors into one contiguous 1-D byte-compatible buffer (same
    dtype required)."""
    dtype = tensors[0].dtype
    assert all(t.dtype == dtype for t in tensors)
    total = sum(t.size for t in tensors)
    out = np.empty(total, dtype)
    lib = get_op(UtilsBuilder.NAME)
    ptrs, sizes = _ptr_array(tensors, writable=False)
    lib.ds_flatten(len(tensors),
                   ctypes.cast(ptrs, ctypes.POINTER(ctypes.c_void_p)),
                   sizes, out.ctypes.data_as(ctypes.c_void_p))
    return out


def unflatten(flat: np.ndarray, like: Sequence[np.ndarray]) -> List[np.ndarray]:
    """Split a flat buffer back into tensors shaped like `like`."""
    outs = [np.empty_like(t) for t in like]
    lib = get_op(UtilsBuilder.NAME)
    ptrs, sizes = _ptr_array(outs, writable=True)
    lib.ds_unflatten(len(outs),
                     ctypes.cast(ptrs, ctypes.POINTER(ctypes.c_void_p)),
                     sizes, flat.ctypes.data_as(ctypes.c_void_p))
    return outs
