"""Block-sparse FLASH attention — Pallas TPU kernels driven by a static
SparsityConfig layout.

Reference: the Triton block-sparse kernel family
(/root/reference/deepspeed/ops/sparse_attention/matmul.py:749 SDD/DSD/DDS,
softmax.py:315, trsrc/*.tr) behind sparse_self_attention.py:14. The
XLA path (sparse_attention.py) gathers key blocks and materialises
[.., W, blk, blk] score tiles in HBM; this kernel streams them: each
(batch·head, q-block) program walks ONLY its layout row's active k-blocks
(a scalar-prefetched index table — the TPU analogue of the reference's
LUTs from csrc/sparse_attention/utils.cpp) with an online-softmax
accumulator in VMEM. HBM traffic is O(S·W·blk) with no score tensor at
all, and every tile is MXU-shaped.

Tables: layout [H, nq, nk] ->
  fwd  table [H, nq, W]  (active k-block ids, -1 padded)
  bwd  table [H, nk, Wq] (reverse: q-blocks touching each k-block)
Both ride pltpu.PrefetchScalarGridSpec scalar prefetch, so BlockSpec
index maps select the k/v (or q/do) block to DMA per grid step; padded
slots clamp to block 0 and are masked in-kernel.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..transformer.flash_attention import (_compiler_params, _keep_mask,
                                           derive_seed)

NEG_INF = -1e30


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def layout_tables(layout: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """[H, nq, nk] 0/1 -> (fwd [H, nq, W], rev [H, nk, Wq]), -1 padded."""
    layout = np.asarray(layout)
    H, nq, nk = layout.shape
    W = max(1, int(layout.sum(-1).max()))
    Wq = max(1, int(layout.sum(-2).max()))
    fwd = np.full((H, nq, W), -1, np.int32)
    rev = np.full((H, nk, Wq), -1, np.int32)
    for h in range(H):
        for i in range(nq):
            nz = np.nonzero(layout[h, i])[0]
            fwd[h, i, :len(nz)] = nz
        for j in range(nk):
            nz = np.nonzero(layout[h, :, j])[0]
            rev[h, j, :len(nz)] = nz
    return fwd, rev


def _causal_mask(s, qi, kj, blk):
    qidx = qi * blk + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
    kidx = kj * blk + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    return jnp.where(qidx >= kidx, s, NEG_INF)


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _fwd_kernel(tbl, seed, q_ref, k_ref, v_ref, o_ref, lse_ref, acc, m_s,
                l_s, *, scale, causal, blk, W, H, rate):
    b = pl.program_id(0)
    qi = pl.program_id(1)
    a = pl.program_id(2)
    h = jax.lax.rem(b, H)
    kj = tbl[h, qi, a]

    @pl.when(a == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)
        m_s[:] = jnp.full_like(m_s, NEG_INF)
        l_s[:] = jnp.zeros_like(l_s)

    @pl.when(kj >= 0)
    def _compute():
        q = q_ref[0].astype(jnp.float32) * scale
        k = k_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            s = _causal_mask(s, qi, kj, blk)
        m_prev = m_s[:, :1]
        l_prev = l_s[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_s[:, :1] = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
        if rate > 0.0:
            # same global-index hash as the dense flash kernel: the mask
            # depends on token coordinates (via the layout table), so the
            # dq/dkv walks regenerate identical tiles
            p = p * _keep_mask(seed[0], b, qi * blk, kj * blk, blk, blk,
                               rate)
        acc[:] = acc[:] * alpha + jnp.dot(
            p.astype(v_ref.dtype), v_ref[0],
            preferred_element_type=jnp.float32)
        m_s[:, :1] = m_new

    @pl.when(a == W - 1)
    def _finish():
        l = l_s[:, :1]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc[:] / safe_l).astype(o_ref.dtype)
        lse_ref[0] = jnp.broadcast_to(
            jnp.where(l == 0.0, NEG_INF, m_s[:, :1] + jnp.log(safe_l)),
            lse_ref[0].shape)


def _fwd(q, k, v, tbl, seed, causal, scale, blk, H, rate):
    BH, S, D = q.shape
    nq = S // blk
    W = tbl.shape[-1]

    def clamp(j):
        return jnp.maximum(j, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(BH, nq, W),
        in_specs=[
            pl.BlockSpec((1, blk, D), lambda b, i, a, t, sd: (b, i, 0)),
            pl.BlockSpec((1, blk, D),
                         lambda b, i, a, t, sd: (
                             b, clamp(t[jax.lax.rem(b, H), i, a]), 0)),
            pl.BlockSpec((1, blk, D),
                         lambda b, i, a, t, sd: (
                             b, clamp(t[jax.lax.rem(b, H), i, a]), 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, blk, D), lambda b, i, a, t, sd: (b, i, 0)),
            pl.BlockSpec((1, blk, 128), lambda b, i, a, t, sd: (b, i, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((blk, D), jnp.float32),
            pltpu.VMEM((blk, 128), jnp.float32),
            pltpu.VMEM((blk, 128), jnp.float32),
        ],
    )
    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               blk=blk, W=W, H=H, rate=rate)
    out, lse = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((BH, S, D), q.dtype),
            jax.ShapeDtypeStruct((BH, S, 128), jnp.float32),
        ],
        compiler_params=_compiler_params(),
        interpret=_interpret(),
    )(tbl, seed, q, k, v)
    return out, lse


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------

def _dq_kernel(tbl, seed, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
               dq_ref, dq_acc, *, scale, causal, blk, W, H, rate):
    b = pl.program_id(0)
    qi = pl.program_id(1)
    a = pl.program_id(2)
    h = jax.lax.rem(b, H)
    kj = tbl[h, qi, a]

    @pl.when(a == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    @pl.when(kj >= 0)
    def _compute():
        q = q_ref[0].astype(jnp.float32) * scale
        k = k_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            s = _causal_mask(s, qi, kj, blk)
        p = jnp.exp(s - lse_ref[0][:, :1])
        do = do_ref[0].astype(jnp.float32)
        dp = jax.lax.dot_general(do, v_ref[0].astype(jnp.float32),
                                 (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        if rate > 0.0:
            dp = dp * _keep_mask(seed[0], b, qi * blk, kj * blk, blk, blk,
                                 rate)
        ds = p * (dp - delta_ref[0][:, :1])
        dq_acc[:] += scale * jnp.dot(ds.astype(k_ref.dtype), k_ref[0],
                                     preferred_element_type=jnp.float32)

    @pl.when(a == W - 1)
    def _finish():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _dkv_kernel(tbl, seed, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, dk_acc, dv_acc, *, scale, causal, blk, Wq,
                H, rate):
    b = pl.program_id(0)
    kjg = pl.program_id(1)
    a = pl.program_id(2)
    h = jax.lax.rem(b, H)
    qi = tbl[h, kjg, a]

    @pl.when(a == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    @pl.when(qi >= 0)
    def _compute():
        q = q_ref[0].astype(jnp.float32) * scale
        k = k_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            s = _causal_mask(s, qi, kjg, blk)
        p = jnp.exp(s - lse_ref[0][:, :1])
        do = do_ref[0].astype(jnp.float32)
        if rate > 0.0:
            mask = _keep_mask(seed[0], b, qi * blk, kjg * blk, blk, blk,
                              rate)
            pd = p * mask
        else:
            mask = None
            pd = p
        dv_acc[:] += jax.lax.dot_general(
            pd, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v_ref[0].astype(jnp.float32),
                                 (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        if mask is not None:
            dp = dp * mask
        ds = p * (dp - delta_ref[0][:, :1])
        dk_acc[:] += scale * jax.lax.dot_general(
            ds, q_ref[0].astype(jnp.float32), (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(a == Wq - 1)
    def _finish():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _bwd(causal, scale, blk, H, rate, tables, res, dout):
    fwd_tbl, rev_tbl = tables
    q, k, v, seed, out, lse = res
    BH, S, D = q.shape
    nq = S // blk
    W = fwd_tbl.shape[-1]
    Wq = rev_tbl.shape[-1]
    delta = jnp.sum(dout.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1)
    delta = jnp.broadcast_to(delta[..., None], (*delta.shape, 128))

    def clamp(j):
        return jnp.maximum(j, 0)

    dq_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(BH, nq, W),
        in_specs=[
            pl.BlockSpec((1, blk, D), lambda b, i, a, t, sd: (b, i, 0)),
            pl.BlockSpec((1, blk, D),
                         lambda b, i, a, t, sd: (
                             b, clamp(t[jax.lax.rem(b, H), i, a]), 0)),
            pl.BlockSpec((1, blk, D),
                         lambda b, i, a, t, sd: (
                             b, clamp(t[jax.lax.rem(b, H), i, a]), 0)),
            pl.BlockSpec((1, blk, D), lambda b, i, a, t, sd: (b, i, 0)),
            pl.BlockSpec((1, blk, 128), lambda b, i, a, t, sd: (b, i, 0)),
            pl.BlockSpec((1, blk, 128), lambda b, i, a, t, sd: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, blk, D), lambda b, i, a, t, sd: (b, i, 0)),
        scratch_shapes=[pltpu.VMEM((blk, D), jnp.float32)],
    )
    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal, blk=blk,
                          W=W, H=H, rate=rate),
        grid_spec=dq_spec,
        out_shape=jax.ShapeDtypeStruct((BH, S, D), q.dtype),
        compiler_params=_compiler_params(),
        interpret=_interpret(),
    )(fwd_tbl, seed, q, k, v, dout, lse, delta)

    dkv_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(BH, nq, Wq),
        in_specs=[
            pl.BlockSpec((1, blk, D),
                         lambda b, j, a, t, sd: (
                             b, clamp(t[jax.lax.rem(b, H), j, a]), 0)),
            pl.BlockSpec((1, blk, D), lambda b, j, a, t, sd: (b, j, 0)),
            pl.BlockSpec((1, blk, D), lambda b, j, a, t, sd: (b, j, 0)),
            pl.BlockSpec((1, blk, D),
                         lambda b, j, a, t, sd: (
                             b, clamp(t[jax.lax.rem(b, H), j, a]), 0)),
            pl.BlockSpec((1, blk, 128),
                         lambda b, j, a, t, sd: (
                             b, clamp(t[jax.lax.rem(b, H), j, a]), 0)),
            pl.BlockSpec((1, blk, 128),
                         lambda b, j, a, t, sd: (
                             b, clamp(t[jax.lax.rem(b, H), j, a]), 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, blk, D), lambda b, j, a, t, sd: (b, j, 0)),
            pl.BlockSpec((1, blk, D), lambda b, j, a, t, sd: (b, j, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((blk, D), jnp.float32),
            pltpu.VMEM((blk, D), jnp.float32),
        ],
    )
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, causal=causal, blk=blk,
                          Wq=Wq, H=H, rate=rate),
        grid_spec=dkv_spec,
        out_shape=[
            jax.ShapeDtypeStruct((BH, S, D), k.dtype),
            jax.ShapeDtypeStruct((BH, S, D), v.dtype),
        ],
        compiler_params=_compiler_params(),
        interpret=_interpret(),
    )(rev_tbl, seed, q, k, v, dout, lse, delta)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# public entry (BSHD) with custom VJP
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8, 9, 10))
def _flash_sparse_bhsd(q, k, v, seed, fwd_tbl, rev_tbl, causal, scale, blk,
                       H, rate):
    out, _ = _fwd(q, k, v, jnp.asarray(fwd_tbl), seed, causal, scale, blk,
                  H, rate)
    return out


def _fwd_rule(q, k, v, seed, fwd_tbl, rev_tbl, causal, scale, blk, H, rate):
    out, lse = _fwd(q, k, v, jnp.asarray(fwd_tbl), seed, causal, scale, blk,
                    H, rate)
    return out, (q, k, v, seed, out, lse)


def _bwd_rule(fwd_tbl, rev_tbl, causal, scale, blk, H, rate, res, dout):
    return (*_bwd(causal, scale, blk, H, rate,
                  (jnp.asarray(fwd_tbl), jnp.asarray(rev_tbl)), res, dout),
            None)


_flash_sparse_bhsd.defvjp(_fwd_rule, _bwd_rule)


def flash_sparse_attention(q, k, v, layout: np.ndarray, block: int,
                           causal: bool = False,
                           scale: Optional[float] = None,
                           dropout_rate: float = 0.0,
                           dropout_rng=None):
    """Block-sparse flash attention over [B, S, H, D] (BSHD).

    layout: STATIC numpy [H, S/block, S/block] 0/1 (SparsityConfig
    layouts are block-granular; `causal=True` additionally token-masks
    the diagonal blocks). The kernel tiles at the LAYOUT's block size —
    SparsityConfig blocks of 128 map 1:1 onto MXU tiles; smaller layout
    blocks still run (interpret/compat) but waste lanes.

    dropout_rate > 0 with a dropout_rng applies probability dropout
    in-kernel — the same global-index hash mask as the dense flash
    kernel (ops/transformer/flash_attention.py), regenerated in both
    backward walks, never materialised at [S, S].
    """
    B, S, Hh, D = q.shape
    nb = S // block
    assert S % block == 0, (S, block)
    layout = np.asarray(layout)
    assert layout.shape == (Hh, nb, nb), (layout.shape, (Hh, nb, nb))
    if not 0.0 <= dropout_rate < 1.0:
        raise ValueError(f"dropout_rate must be in [0, 1), got "
                         f"{dropout_rate}")
    fwd_tbl, rev_tbl = layout_tables(layout)
    scale = (D ** -0.5) if scale is None else scale
    seed, rate = derive_seed(dropout_rate, dropout_rng)
    to_bhsd = lambda t: t.transpose(0, 2, 1, 3).reshape(B * Hh, S, D)
    # hashable static tables for the custom-vjp nondiff args
    fwd_key = tuple(map(tuple, fwd_tbl.reshape(Hh * nb, -1)))
    rev_key = tuple(map(tuple, rev_tbl.reshape(Hh * nb, -1)))
    out = _flash_sparse_bhsd(
        to_bhsd(q), to_bhsd(k), to_bhsd(v), seed,
        _Table(fwd_key, (Hh, nb, fwd_tbl.shape[-1])),
        _Table(rev_key, (Hh, nb, rev_tbl.shape[-1])),
        causal, scale, block, Hh, rate)
    return out.reshape(B, Hh, S, D).transpose(0, 2, 1, 3)


class _Table:
    """Hashable static wrapper so layout tables can ride custom_vjp
    nondiff_argnums; __array__ lets jnp.asarray recover the int32 data."""

    def __init__(self, key, shape):
        self._key = key
        self._shape = shape

    def __hash__(self):
        return hash((self._key, self._shape))

    def __eq__(self, other):
        return isinstance(other, _Table) and self._key == other._key and \
            self._shape == other._shape

    def __array__(self, dtype=None):
        return np.asarray(self._key, np.int32).reshape(self._shape)
