"""Sparse-attention model adaptation helpers.

Reference: deepspeed/ops/sparse_attention/sparse_attention_utils.py (225
LoC) — pad/unpad sequences to the block size, extend position embeddings
for longer contexts, swap a BERT model's dense self-attention for
block-sparse. Functional equivalents here operate on params pytrees and
configs instead of mutating torch modules.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from .sparse_attention import SparseSelfAttention
from .sparsity_config import SparsityConfig


class BertSparseSelfAttention:
    """BERT-style self-attention over block-sparse scores (reference
    bert_sparse_self_attention.py): q/k/v projections + SparseSelfAttention.

    params: {"query": {"kernel","bias"}, "key": {...}, "value": {...}}
    with [hidden, hidden] kernels.
    """

    def __init__(self, num_attention_heads: int, hidden_size: int,
                 sparsity_config: Optional[SparsityConfig] = None,
                 key_padding_mask_mode: str = "mul"):
        if hidden_size % num_attention_heads:
            raise ValueError(
                f"hidden size {hidden_size} not a multiple of heads "
                f"{num_attention_heads}")
        self.num_attention_heads = num_attention_heads
        self.hidden_size = hidden_size
        self.head_dim = hidden_size // num_attention_heads
        # default "mul": attention_mask here is the BERT 0/1 keep mask
        # (converted to large-negative bias); pass "add" for pre-built
        # additive biases
        self.sparse_self_attention = SparseSelfAttention(
            sparsity_config or SparsityConfig(num_heads=num_attention_heads),
            key_padding_mask_mode=key_padding_mask_mode)

    def init(self, rng, param_dtype=jnp.float32):
        ks = jax.random.split(rng, 3)
        h = self.hidden_size
        mk = lambda k: {"kernel": (0.02 * jax.random.normal(k, (h, h)))
                        .astype(param_dtype),
                        "bias": jnp.zeros((h,), param_dtype)}
        return {"query": mk(ks[0]), "key": mk(ks[1]), "value": mk(ks[2])}

    def __call__(self, params, hidden_states, attention_mask=None):
        B, S, H = hidden_states.shape
        heads, hd = self.num_attention_heads, self.head_dim

        def proj(p):
            y = hidden_states @ p["kernel"].astype(hidden_states.dtype) + \
                p["bias"].astype(hidden_states.dtype)
            return y.reshape(B, S, heads, hd)

        q, k, v = proj(params["query"]), proj(params["key"]), \
            proj(params["value"])
        ctx = self.sparse_self_attention(
            q, k, v, key_padding_mask=attention_mask)
        return ctx.reshape(B, S, H)


class SparseAttentionUtils:
    """reference sparse_attention_utils.py — all @staticmethod surface."""

    @staticmethod
    def extend_position_embedding(position_embeddings,
                                  max_position: int):
        """Tile an existing [old_max, d] position table to `max_position`
        (reference :38-73 repeats the learned table). Accepts the raw
        array; returns the extended array."""
        pe = jnp.asarray(position_embeddings)
        old_max = pe.shape[0]
        if max_position <= old_max:
            return pe[:max_position]
        reps = int(np.ceil(max_position / old_max))
        return jnp.tile(pe, (reps, 1))[:max_position]

    @staticmethod
    def update_tokenizer_model_max_length(tokenizer, max_position: int):
        """reference :75-88."""
        tokenizer.model_max_length = max_position
        if hasattr(tokenizer, "init_kwargs"):
            tokenizer.init_kwargs["model_max_length"] = max_position
        return tokenizer

    @staticmethod
    def replace_model_self_attention_with_sparse_self_attention(
            config, sparsity_config: SparsityConfig):
        """reference :90-128 swaps nn.Module attention layers in place; the
        functional analog flips the model/layer CONFIG so its attention
        dispatch routes through SparseSelfAttention (see
        DeepSpeedTransformerConfig.sparsity_config /
        BertConfig.sparsity_config). Returns the updated config."""
        config.sparsity_config = sparsity_config
        return config

    @staticmethod
    def pad_to_block_size(block_size: int, input_ids, attention_mask=None,
                          token_type_ids=None, position_ids=None,
                          inputs_embeds=None, pad_token_id: int = 0,
                          model_embeddings=None):
        """reference :130-200: right-pad sequence tensors to a multiple of
        the sparsity block size. Returns (pad_len, padded tensors...)."""
        seq_len = (input_ids.shape[1] if input_ids is not None
                   else inputs_embeds.shape[1])
        pad_len = (block_size - seq_len % block_size) % block_size
        if pad_len == 0:
            return (0, input_ids, attention_mask, token_type_ids,
                    position_ids, inputs_embeds)

        def pad(x, value=0):
            if x is None:
                return None
            widths = [(0, 0), (0, pad_len)] + \
                [(0, 0)] * (x.ndim - 2)
            return jnp.pad(x, widths, constant_values=value)

        input_ids = pad(input_ids, pad_token_id)
        attention_mask = pad(attention_mask, 0)
        token_type_ids = pad(token_type_ids, 0)
        position_ids = pad(position_ids, 0)
        if inputs_embeds is not None:
            if model_embeddings is not None:
                # pad with the pad token's embedding (reference :180-189),
                # not zeros; model_embeddings is the [vocab, d] table
                pad_vec = jnp.asarray(model_embeddings)[pad_token_id]
                tail = jnp.broadcast_to(
                    pad_vec, (inputs_embeds.shape[0], pad_len,
                              inputs_embeds.shape[2]))
                inputs_embeds = jnp.concatenate([inputs_embeds, tail], axis=1)
            else:
                widths = [(0, 0), (0, pad_len), (0, 0)]
                inputs_embeds = jnp.pad(inputs_embeds, widths)
        return (pad_len, input_ids, attention_mask, token_type_ids,
                position_ids, inputs_embeds)

    @staticmethod
    def unpad_sequence_output(pad_len: int, sequence_output):
        """reference :202-214."""
        if pad_len > 0:
            return sequence_output[:, :-pad_len]
        return sequence_output
