"""Block-sparse attention layout generators.

Reference API: /root/reference/deepspeed/ops/sparse_attention/sparsity_config.py
(SparsityConfig :9, Dense :63, Fixed :94, Variable :244, BigBird :422,
BSLongformer :552, LocalSlidingWindow :678). Layouts are
[num_heads, num_blocks, num_blocks] 0/1 matrices over block-granular
attention; the TPU kernel (sparse_attention.py) consumes them as static
gather indices. Implementation here is numpy (the reference uses torch
tensors; semantics are identical — see each class's docstring contract).
"""

from __future__ import annotations

import random
from typing import List, Optional

import numpy as np


class SparsityConfig:
    """Base: block size, head count, per-head layout toggle."""

    def __init__(self, num_heads, block=16, different_layout_per_head=False):
        self.num_heads = num_heads
        self.block = block
        self.different_layout_per_head = different_layout_per_head
        self.num_layout_heads = num_heads if different_layout_per_head else 1

    def setup_layout(self, seq_len: int) -> np.ndarray:
        if seq_len % self.block != 0:
            raise ValueError(
                f"sequence length {seq_len} must be divisible by block size "
                f"{self.block}")
        num_blocks = seq_len // self.block
        return np.zeros((self.num_heads, num_blocks, num_blocks), np.int64)

    def check_and_propagate_first_head_layout(self, layout: np.ndarray):
        if not self.different_layout_per_head:
            layout[1:] = layout[0]
        return layout

    def make_layout(self, seq_len: int) -> np.ndarray:
        raise NotImplementedError


class DenseSparsityConfig(SparsityConfig):
    """All-ones layout (testing/fallback; reference :63)."""

    def make_layout(self, seq_len):
        layout = self.setup_layout(seq_len)
        layout[:] = 1
        return layout


def _set_sliding_window(h: int, layout: np.ndarray,
                        num_sliding_window_blocks: int) -> np.ndarray:
    """Symmetric block sliding window around the diagonal (shared by
    BigBird / BSLongformer / LocalSlidingWindow configs)."""
    nb = layout.shape[1]
    if num_sliding_window_blocks > nb:
        raise ValueError("window wider than the sequence")
    w = num_sliding_window_blocks // 2
    for row in range(nb):
        lo = max(0, row - w)
        hi = min(nb, row + w + 1)
        layout[h, row, lo:hi] = 1
    return layout


def _apply_unidirectional(layout: np.ndarray) -> np.ndarray:
    """Zero the strict upper block-triangle (autoregressive masking)."""
    nb = layout.shape[1]
    tril = np.tril(np.ones((nb, nb), np.int64))
    return layout * tril[None]


class FixedSparsityConfig(SparsityConfig):
    """Fixed pattern (Sparse Transformers): local windows of
    `num_local_blocks`, plus global attention to the last
    `num_global_blocks` representative block(s) of each preceding window
    (reference :94-242)."""

    def __init__(self, num_heads, block=16, different_layout_per_head=False,
                 num_local_blocks=4, num_global_blocks=1,
                 attention="bidirectional", horizontal_global_attention=False,
                 num_different_global_patterns=1):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_local_blocks = num_local_blocks
        if num_global_blocks > 0 and num_local_blocks % num_global_blocks:
            raise ValueError(
                f"num_local_blocks {num_local_blocks} must be divisible by "
                f"num_global_blocks {num_global_blocks}")
        self.num_global_blocks = num_global_blocks
        if attention not in ("unidirectional", "bidirectional"):
            raise NotImplementedError(
                "only uni/bidirectional attention supported")
        self.attention = attention
        if attention != "bidirectional" and horizontal_global_attention:
            raise ValueError("horizontal global attention requires "
                             "bidirectional attention")
        self.horizontal_global_attention = horizontal_global_attention
        if num_different_global_patterns > 1 and not different_layout_per_head:
            raise ValueError("multiple global patterns require "
                             "different_layout_per_head=True")
        if num_global_blocks > 0 and num_different_global_patterns > \
                num_local_blocks // num_global_blocks:
            raise ValueError("too many global patterns for window size")
        self.num_different_global_patterns = num_different_global_patterns

    def set_local_layout(self, h, layout):
        nb = layout.shape[1]
        for start in range(0, nb, self.num_local_blocks):
            end = min(start + self.num_local_blocks, nb)
            layout[h, start:end, start:end] = 1
        return layout

    def set_global_layout(self, h, layout):
        nb = layout.shape[1]
        if self.num_global_blocks == 0:
            return layout
        # representative blocks: a num_global_blocks-wide slice of each
        # local window, version selected per head pattern (reference
        # sparsity_config.py:176-224). Vertical global attention is visible
        # to ALL rows; make_layout's trailing tril restores causality for
        # unidirectional attention.
        version = h % self.num_different_global_patterns
        first = (self.num_local_blocks -
                 (version + 1) * self.num_global_blocks)
        full_end = nb - (nb % self.num_local_blocks)
        starts = list(range(first, full_end, self.num_local_blocks))
        if full_end < nb:  # short last window still gets a representative
            starts.append(max(0, min(full_end + first,
                                     nb - self.num_global_blocks)))
        for start in starts:
            end = min(start + self.num_global_blocks, nb)
            layout[h, :, start:end] = 1
            if self.horizontal_global_attention:
                layout[h, start:end, :] = 1
        return layout

    def make_layout(self, seq_len):
        layout = self.setup_layout(seq_len)
        for h in range(self.num_layout_heads):
            self.set_local_layout(h, layout)
            self.set_global_layout(h, layout)
        layout = self.check_and_propagate_first_head_layout(layout)
        if self.attention == "unidirectional":
            layout = _apply_unidirectional(layout)
        return layout


class VariableSparsityConfig(SparsityConfig):
    """Variable-size local windows + explicit global block indices +
    random blocks (reference :244-420)."""

    def __init__(self, num_heads, block=16, different_layout_per_head=False,
                 num_random_blocks=0, local_window_blocks: List[int] = None,
                 global_block_indices: List[int] = None,
                 global_block_end_indices: Optional[List[int]] = None,
                 attention="bidirectional",
                 horizontal_global_attention=False):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_random_blocks = num_random_blocks
        self.local_window_blocks = local_window_blocks or [4]
        self.global_block_indices = global_block_indices or [0]
        if global_block_end_indices is not None:
            if len(self.global_block_indices) != len(global_block_end_indices):
                raise ValueError("global start/end index lists must have "
                                 "equal length")
            for s, e in zip(self.global_block_indices,
                            global_block_end_indices):
                if s >= e:
                    raise ValueError(f"global start {s} must precede end {e}")
        self.global_block_end_indices = global_block_end_indices
        if attention not in ("unidirectional", "bidirectional"):
            raise NotImplementedError
        self.attention = attention
        if attention != "bidirectional" and horizontal_global_attention:
            raise ValueError("horizontal global attention requires "
                             "bidirectional attention")
        self.horizontal_global_attention = horizontal_global_attention

    def set_random_layout(self, h, layout):
        nb = layout.shape[1]
        if self.num_random_blocks > nb:
            raise ValueError(f"num_random_blocks {self.num_random_blocks} "
                             f"exceeds {nb} blocks")
        for row in range(nb):
            cols = random.sample(range(nb), self.num_random_blocks)
            layout[h, row, cols] = 1
        return layout

    def set_local_layout(self, h, layout):
        nb = layout.shape[1]
        start = 0
        for i, w in enumerate(self.local_window_blocks):
            end = min(start + w, nb)
            layout[h, start:end, start:end] = 1
            start = end
        # last window size repeats for the remainder
        w = self.local_window_blocks[-1]
        while start < nb:
            end = min(start + w, nb)
            layout[h, start:end, start:end] = 1
            start = end
        return layout

    def set_global_layout(self, h, layout):
        nb = layout.shape[1]
        if self.global_block_end_indices is None:
            for idx in self.global_block_indices:
                if idx < nb:
                    layout[h, :, idx] = 1
                    if self.horizontal_global_attention:
                        layout[h, idx, :] = 1
        else:
            for s, e in zip(self.global_block_indices,
                            self.global_block_end_indices):
                e = min(e, nb)
                layout[h, :, s:e] = 1
                if self.horizontal_global_attention:
                    layout[h, s:e, :] = 1
        return layout

    def make_layout(self, seq_len):
        layout = self.setup_layout(seq_len)
        for h in range(self.num_layout_heads):
            self.set_random_layout(h, layout)
            self.set_local_layout(h, layout)
            self.set_global_layout(h, layout)
        layout = self.check_and_propagate_first_head_layout(layout)
        if self.attention == "unidirectional":
            layout = _apply_unidirectional(layout)
        return layout


class BigBirdSparsityConfig(SparsityConfig):
    """BigBird: random + sliding window + global blocks (reference
    :422-550)."""

    def __init__(self, num_heads, block=16, different_layout_per_head=False,
                 num_random_blocks=1, num_sliding_window_blocks=3,
                 num_global_blocks=1, attention="bidirectional"):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_random_blocks = num_random_blocks
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.num_global_blocks = num_global_blocks
        self.attention = attention

    def set_random_layout(self, h, layout):
        nb = layout.shape[1]
        if self.num_random_blocks > nb:
            raise ValueError("more random blocks than blocks in the row")
        for row in range(nb):
            if self.attention == "unidirectional":
                pool = range(row + 1)
                k = min(self.num_random_blocks, row + 1)
            else:
                pool = range(nb)
                k = self.num_random_blocks
            cols = random.sample(pool, k)
            layout[h, row, cols] = 1
        return layout

    def set_sliding_window_layout(self, h, layout):
        return _set_sliding_window(h, layout, self.num_sliding_window_blocks)

    def set_global_layout_itc(self, h, layout):
        nb = layout.shape[1]
        if self.num_global_blocks > nb:
            raise ValueError("more global blocks than blocks")
        g = self.num_global_blocks
        layout[h, :g, :] = 1
        layout[h, :, :g] = 1
        return layout

    def make_layout(self, seq_len):
        layout = self.setup_layout(seq_len)
        for h in range(self.num_layout_heads):
            self.set_random_layout(h, layout)
            self.set_sliding_window_layout(h, layout)
            self.set_global_layout_itc(h, layout)
        layout = self.check_and_propagate_first_head_layout(layout)
        if self.attention == "unidirectional":
            layout = _apply_unidirectional(layout)
        return layout


class BSLongformerSparsityConfig(SparsityConfig):
    """Block-sparse Longformer: sliding window + global indices
    (reference :552-676)."""

    def __init__(self, num_heads, block=16, different_layout_per_head=False,
                 num_sliding_window_blocks=3, global_block_indices=None,
                 global_block_end_indices=None, attention="bidirectional"):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.global_block_indices = global_block_indices or [0]
        if global_block_end_indices is not None:
            if len(self.global_block_indices) != len(global_block_end_indices):
                raise ValueError("global start/end index length mismatch")
            for s, e in zip(self.global_block_indices,
                            global_block_end_indices):
                if s >= e:
                    raise ValueError("global start must precede end")
        self.global_block_end_indices = global_block_end_indices
        self.attention = attention

    def set_sliding_window_layout(self, h, layout):
        return _set_sliding_window(h, layout, self.num_sliding_window_blocks)

    def set_global_layout(self, h, layout):
        nb = layout.shape[1]
        if self.global_block_end_indices is None:
            for idx in self.global_block_indices:
                if idx < nb:
                    layout[h, :, idx] = 1
                    layout[h, idx, :] = 1
        else:
            for s, e in zip(self.global_block_indices,
                            self.global_block_end_indices):
                e = min(e, nb)
                layout[h, :, s:e] = 1
                layout[h, s:e, :] = 1
        return layout

    def make_layout(self, seq_len):
        layout = self.setup_layout(seq_len)
        for h in range(self.num_layout_heads):
            self.set_sliding_window_layout(h, layout)
            self.set_global_layout(h, layout)
        layout = self.check_and_propagate_first_head_layout(layout)
        if self.attention == "unidirectional":
            layout = _apply_unidirectional(layout)
        return layout


class LocalSlidingWindowSparsityConfig(SparsityConfig):
    """Pure sliding-window attention (reference :678)."""

    def __init__(self, num_heads, block=16, num_sliding_window_blocks=3,
                 attention="unidirectional"):
        super().__init__(num_heads, block)
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.attention = attention

    def make_layout(self, seq_len):
        layout = self.setup_layout(seq_len)
        for h in range(self.num_layout_heads):
            _set_sliding_window(h, layout, self.num_sliding_window_blocks)
        layout = self.check_and_propagate_first_head_layout(layout)
        if self.attention == "unidirectional":
            layout = _apply_unidirectional(layout)
        return layout
