from .sparsity_config import (BigBirdSparsityConfig, BSLongformerSparsityConfig,
                              DenseSparsityConfig, FixedSparsityConfig,
                              LocalSlidingWindowSparsityConfig, SparsityConfig,
                              VariableSparsityConfig)
from .sparse_attention import (SparseSelfAttention, block_sparse_attention,
                               layout_to_gather)
from .flash_sparse import flash_sparse_attention
from .sparse_attention_utils import (BertSparseSelfAttention,
                                     SparseAttentionUtils)

__all__ = ["SparsityConfig", "DenseSparsityConfig", "FixedSparsityConfig",
           "VariableSparsityConfig", "BigBirdSparsityConfig",
           "BSLongformerSparsityConfig", "LocalSlidingWindowSparsityConfig",
           "SparseSelfAttention", "block_sparse_attention",
           "layout_to_gather", "flash_sparse_attention", "BertSparseSelfAttention",
           "SparseAttentionUtils"]
