"""Block-sparse attention compute for SparsityConfig layouts.

Reference: the Triton SDD/DSD/DDS matmuls + block-sparse softmax
(/root/reference/deepspeed/ops/sparse_attention/matmul.py:749,
softmax.py:315, trsrc/*.tr) driven by
sparse_self_attention.py:14. TPU-native design: the layout is STATIC, so
each (head, query-block) row's nonzero key-block indices become a static
gather; XLA then runs dense [blk x W*blk] attention per row — compute and
memory O(S * W * blk) instead of O(S^2), tiled on the MXU. No Triton, no
LUT C++ helper (csrc/sparse_attention/utils.cpp): the gather indices ARE
the LUT.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

import jax
import jax.numpy as jnp

from .sparsity_config import SparsityConfig

NEG_INF = -1e30


def layout_to_gather(layout: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """[H, nb, nb] 0/1 layout -> (idx [H, nb, W], valid [H, nb, W]).

    W = max nonzeros per row; rows pad with index 0 + valid=False."""
    layout = np.asarray(layout)
    H, nb, _ = layout.shape
    counts = layout.sum(-1)
    W = max(1, int(counts.max()))
    idx = np.zeros((H, nb, W), np.int32)
    valid = np.zeros((H, nb, W), bool)
    for h in range(H):
        for i in range(nb):
            nz = np.nonzero(layout[h, i])[0]
            idx[h, i, :len(nz)] = nz
            valid[h, i, :len(nz)] = True
    return idx, valid


def block_sparse_attention(q, k, v, layout, block: int,
                           causal_token_mask: bool = False,
                           scale=None, key_padding_bias=None,
                           attn_bias=None, dropout_rate: float = 0.0,
                           dropout_rng=None):
    """Sparse attention over [B, S, H, D] inputs.

    layout: [H, nb, nb] numpy array (static — from SparsityConfig).
    causal_token_mask: additionally mask within-block future tokens
    (unidirectional layouts handle block granularity; this handles the
    diagonal block's token granularity).
    key_padding_bias: [B, S] additive fp32 bias on key positions
    (large-negative at padded keys).
    attn_bias: [S, S] or [Hb, S, S] additive bias (relative position
    embeddings / arbitrary attention masks, reference
    sparse_self_attention.py forward rpe/attn_mask); gathered along the
    key axis with the same static indices as K/V.
    """
    B, S, H, D = q.shape
    nb = S // block
    assert S % block == 0
    assert layout.shape == (H, nb, nb), (layout.shape, (H, nb, nb))
    scale = (D ** -0.5) if scale is None else scale

    idx_np, valid_np = layout_to_gather(layout)
    W = idx_np.shape[-1]
    idx = jnp.asarray(idx_np)
    valid = jnp.asarray(valid_np)

    # [B, H, nb, blk, D]
    to_blocks = lambda t: t.transpose(0, 2, 1, 3).reshape(B, H, nb, block, D)
    qb, kb, vb = to_blocks(q), to_blocks(k), to_blocks(v)

    h_ix = jnp.arange(H)[:, None, None]
    kg = kb[:, h_ix, idx]  # [B, H, nb, W, blk, D]
    vg = vb[:, h_ix, idx]

    scores = jnp.einsum("bhiqd,bhiwkd->bhiqwk", qb.astype(jnp.float32),
                        kg.astype(jnp.float32),
                        preferred_element_type=jnp.float32) * scale

    if key_padding_bias is not None:
        kpb = jnp.asarray(key_padding_bias, jnp.float32) \
            .reshape(B, nb, block)[:, idx]          # [B, H, nb, W, blk]
        scores = scores + kpb[:, :, :, None, :, :]
    if attn_bias is not None:
        ab = jnp.asarray(attn_bias, jnp.float32)
        if ab.ndim == 2:
            ab = ab[None]
        # [Hb, nb, blk_q, nb, blk_k] -> gather key blocks per (h, i, w)
        abb = ab.reshape(ab.shape[0], nb, block, nb, block)
        abb = abb[jnp.arange(H) % ab.shape[0]]      # broadcast heads
        gathered = jnp.take_along_axis(
            abb, idx[:, :, None, :, None], axis=3)  # [H, nb, blk_q, W, blk_k]
        scores = scores + gathered[None]

    mask = valid[None, :, :, None, :, None]  # block-level validity
    if causal_token_mask:
        qpos = (jnp.arange(nb)[:, None] * block +
                jnp.arange(block)[None, :])              # [nb, blk]
        kpos = idx[..., None] * block + jnp.arange(block)  # [H, nb, W, blk]
        tok = qpos[None, :, :, None, None] >= kpos[:, :, None, :, :]
        mask = jnp.logical_and(mask, tok[None])
    scores = jnp.where(mask, scores, NEG_INF)

    flat = scores.reshape(B, H, nb, block, W * block)
    probs = jax.nn.softmax(flat, axis=-1).reshape(scores.shape)
    probs = jnp.where(mask, probs, 0.0)  # fully-masked rows -> zero output
    if dropout_rate > 0.0 and dropout_rng is not None:
        # counter-hash mask instead of per-element threefry (dropout.py)
        from ..transformer.dropout import hash_dropout

        probs = hash_dropout(probs, dropout_rate, dropout_rng)

    out = jnp.einsum("bhiqwk,bhiwkd->bhiqd", probs,
                     vg.astype(jnp.float32),
                     preferred_element_type=jnp.float32)
    return out.reshape(B, H, S, D).transpose(0, 2, 1, 3).astype(q.dtype)


class SparseSelfAttention:
    """Module-level wrapper (reference sparse_self_attention.py:14).

    Computes softmax(QK^T)V under a SparsityConfig layout; inputs BSHD.
    The layout (and its gather indices) is computed once per seq_len and
    cached — it is static compile-time structure.
    """

    def __init__(self, sparsity_config: SparsityConfig = None,
                 key_padding_mask_mode: str = "add",
                 attn_mask_mode: str = "mul", impl: str = "auto"):
        self.sparsity_config = sparsity_config or SparsityConfig(num_heads=4)
        self.key_padding_mask_mode = key_padding_mask_mode
        self.attn_mask_mode = attn_mask_mode
        self.impl = impl  # auto|pallas|xla
        self._layouts = {}

    def get_layout(self, seq_len: int) -> np.ndarray:
        if seq_len not in self._layouts:
            self._layouts[seq_len] = self.sparsity_config.make_layout(seq_len)
        return self._layouts[seq_len]

    def __call__(self, query, key, value, rpe=None, key_padding_mask=None,
                 attn_mask=None, dropout_rate: float = 0.0,
                 dropout_rng=None):
        """reference sparse_self_attention.py forward(query, key, value,
        rpe, key_padding_mask, attn_mask). Masks follow the configured
        modes: "add" = already-additive float bias, "mul" = 0/1 keep
        mask converted to additive large-negative."""
        B, S, H, D = query.shape
        layout = self.get_layout(S)
        causal = getattr(self.sparsity_config, "attention",
                         "bidirectional") == "unidirectional"

        def to_additive(m, mode):
            m = jnp.asarray(m)
            if mode == "mul" or m.dtype == jnp.bool_:
                return (1.0 - m.astype(jnp.float32)) * NEG_INF
            return m.astype(jnp.float32)

        key_padding_bias = None
        if key_padding_mask is not None:
            key_padding_bias = to_additive(key_padding_mask,
                                           self.key_padding_mask_mode)
        attn_bias = None
        if attn_mask is not None:
            attn_bias = to_additive(attn_mask, self.attn_mask_mode)
        if rpe is not None:
            rpe = jnp.asarray(rpe, jnp.float32)
            attn_bias = rpe if attn_bias is None else attn_bias + rpe

        # Selection lives in the kernel registry (kernels/registry.py) —
        # ONE mechanism for every op.  Pallas = flash_sparse (streams
        # only active layout blocks through VMEM, in-kernel hash
        # dropout); the jnp oracle is block_sparse_attention above.
        # Historical semantics preserved: the module-level impl="pallas"
        # runs the kernel even off-TPU (under the Pallas interpreter —
        # interpret_ok), and biased calls always take the oracle (the
        # kernel has no bias path; silently dropping a mask would be
        # numerically wrong).
        from ...kernels import registry

        plain = key_padding_bias is None and attn_bias is None
        impl = None if self.impl == "auto" else self.impl
        if not plain and impl == "pallas":
            impl = "jnp"
        return registry.dispatch(
            "sparse_attention", query, key, value, layout,
            self.sparsity_config.block,
            impl=impl, interpret_ok=True,
            info={"plain": plain, "block": self.sparsity_config.block,
                  "head_dim": D},
            causal=causal, key_padding_bias=key_padding_bias,
            attn_bias=attn_bias, dropout_rate=dropout_rate,
            dropout_rng=dropout_rng)
