"""HuggingFace interop: load transformer checkpoints into the in-tree
model families.

The reference integrates with HF via module_inject (kernel injection into
an existing torch module, deepspeed/module_inject/replace_module.py); the
TPU-native equivalent converts the WEIGHTS into the pure-pytree GPT
family, after which every engine feature (ZeRO, pipeline, offload,
Infinity streaming) applies unchanged. GPT-2's layout maps 1:1: HF Conv1D
stores [in, out] weights, which is exactly this GPT's `x @ w` convention.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

import jax.numpy as jnp

from .gpt import GPT, GPTConfig


def gpt2_config_from_hf(hf_config, **overrides) -> GPTConfig:
    """Map a transformers GPT2Config onto GPTConfig.

    Raises on HF options this architecture cannot represent (silently
    wrong logits are worse than a refusal)."""
    act = getattr(hf_config, "activation_function", "gelu_new")
    if act != "gelu_new":
        raise ValueError(
            f"activation_function={act!r} unsupported: gpt_block computes "
            f"gelu_new (tanh-approximate gelu) only")
    for flag in ("scale_attn_by_inverse_layer_idx",
                 "reorder_and_upcast_attn"):
        if getattr(hf_config, flag, False):
            raise ValueError(f"GPT2Config.{flag} has no equivalent here")
    attn_p = getattr(hf_config, "attn_pdrop", 0.0) or 0.0
    resid_p = getattr(hf_config, "resid_pdrop", 0.0) or 0.0
    if attn_p != resid_p:
        from ..utils.logging import logger

        logger.warning(
            f"GPT2Config attn_pdrop={attn_p} != resid_pdrop={resid_p}: "
            f"GPTConfig has one dropout knob (applied to attention probs "
            f"and residual paths); using resid_pdrop={resid_p}")
    base = dict(
        vocab_size=hf_config.vocab_size,
        max_seq_len=hf_config.n_positions,
        num_layers=hf_config.n_layer,
        num_heads=hf_config.n_head,
        d_model=hf_config.n_embd,
        d_ff=getattr(hf_config, "n_inner", None) or 4 * hf_config.n_embd,
        layer_norm_eps=hf_config.layer_norm_epsilon,
        dropout=resid_p,
        embed_dropout=getattr(hf_config, "embd_pdrop", 0.0) or 0.0,
        tie_embeddings=getattr(hf_config, "tie_word_embeddings", True),
    )
    base.update(overrides)
    return GPTConfig(**base)


def load_hf_gpt2(hf_model, **config_overrides):
    """(GPT, params) from a transformers GPT2LMHeadModel.

    Usage:
        from transformers import GPT2LMHeadModel
        hf = GPT2LMHeadModel.from_pretrained("gpt2")   # or local files
        model, params = load_hf_gpt2(hf)
        engine, *_ = deepspeed_tpu.initialize(model=model,
                                              model_parameters=params, ...)
    """
    import torch

    # float() first: torch .numpy() rejects bfloat16, and the values are
    # re-cast to cfg.param_dtype below anyway
    sd = {k: np.asarray(v.detach().to(torch.float32).cpu().numpy())
          for k, v in hf_model.state_dict().items()}
    cfg = gpt2_config_from_hf(hf_model.config, **config_overrides)
    model = GPT(cfg)
    params = hf_gpt2_state_dict_to_params(sd, cfg)
    return model, params


def hf_gpt2_state_dict_to_params(sd: Dict[str, Any],
                                 cfg: GPTConfig):
    """Torch GPT-2 state_dict (numpy values) -> GPT params pytree."""
    g = lambda k: jnp.asarray(sd[k], cfg.param_dtype)

    def block(i):
        p = f"transformer.h.{i}."
        return {
            "ln1": {"scale": g(p + "ln_1.weight"),
                    "bias": g(p + "ln_1.bias")},
            "attn": {
                "qkv": {"w": g(p + "attn.c_attn.weight"),
                        "b": g(p + "attn.c_attn.bias")},
                "proj": {"w": g(p + "attn.c_proj.weight"),
                         "b": g(p + "attn.c_proj.bias")},
            },
            "ln2": {"scale": g(p + "ln_2.weight"),
                    "bias": g(p + "ln_2.bias")},
            "mlp": {
                "fc1": {"w": g(p + "mlp.c_fc.weight"),
                        "b": g(p + "mlp.c_fc.bias")},
                "fc2": {"w": g(p + "mlp.c_proj.weight"),
                        "b": g(p + "mlp.c_proj.bias")},
            },
        }

    params = {
        "wte": g("transformer.wte.weight"),
        "wpe": g("transformer.wpe.weight"),
        "blocks": [block(i) for i in range(cfg.num_layers)],
        "ln_f": {"scale": g("transformer.ln_f.weight"),
                 "bias": g("transformer.ln_f.bias")},
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = g("lm_head.weight").T
    return params
