"""HuggingFace interop: load transformer checkpoints into the in-tree
model families.

The reference integrates with HF via module_inject (kernel injection into
an existing torch module, deepspeed/module_inject/replace_module.py); the
TPU-native equivalent converts the WEIGHTS into the pure-pytree GPT
family, after which every engine feature (ZeRO, pipeline, offload,
Infinity streaming) applies unchanged. GPT-2's layout maps 1:1: HF Conv1D
stores [in, out] weights, which is exactly this GPT's `x @ w` convention.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

import jax.numpy as jnp

from .gpt import GPT, GPTConfig


def gpt2_config_from_hf(hf_config, **overrides) -> GPTConfig:
    """Map a transformers GPT2Config onto GPTConfig.

    Raises on HF options this architecture cannot represent (silently
    wrong logits are worse than a refusal)."""
    act = getattr(hf_config, "activation_function", "gelu_new")
    if act != "gelu_new":
        raise ValueError(
            f"activation_function={act!r} unsupported: gpt_block computes "
            f"gelu_new (tanh-approximate gelu) only")
    for flag in ("scale_attn_by_inverse_layer_idx",
                 "reorder_and_upcast_attn"):
        if getattr(hf_config, flag, False):
            raise ValueError(f"GPT2Config.{flag} has no equivalent here")
    attn_p = getattr(hf_config, "attn_pdrop", 0.0) or 0.0
    resid_p = getattr(hf_config, "resid_pdrop", 0.0) or 0.0
    if attn_p != resid_p:
        from ..utils.logging import logger

        logger.warning(
            f"GPT2Config attn_pdrop={attn_p} != resid_pdrop={resid_p}: "
            f"GPTConfig has one dropout knob (applied to attention probs "
            f"and residual paths); using resid_pdrop={resid_p}")
    base = dict(
        vocab_size=hf_config.vocab_size,
        max_seq_len=hf_config.n_positions,
        num_layers=hf_config.n_layer,
        num_heads=hf_config.n_head,
        d_model=hf_config.n_embd,
        d_ff=getattr(hf_config, "n_inner", None) or 4 * hf_config.n_embd,
        layer_norm_eps=hf_config.layer_norm_epsilon,
        dropout=resid_p,
        embed_dropout=getattr(hf_config, "embd_pdrop", 0.0) or 0.0,
        tie_embeddings=getattr(hf_config, "tie_word_embeddings", True),
    )
    base.update(overrides)
    return GPTConfig(**base)


def _torch_sd_to_numpy(hf_model):
    """state_dict -> float32 numpy. float() first: torch .numpy() rejects
    bfloat16, and values are re-cast to cfg.param_dtype by the loaders."""
    import torch

    return {k: np.asarray(v.detach().to(torch.float32).cpu().numpy())
            for k, v in hf_model.state_dict().items()}


def load_hf_gpt2(hf_model, **config_overrides):
    """(GPT, params) from a transformers GPT2LMHeadModel.

    Usage:
        from transformers import GPT2LMHeadModel
        hf = GPT2LMHeadModel.from_pretrained("gpt2")   # or local files
        model, params = load_hf_gpt2(hf)
        engine, *_ = deepspeed_tpu.initialize(model=model,
                                              model_parameters=params, ...)
    """
    sd = _torch_sd_to_numpy(hf_model)
    cfg = gpt2_config_from_hf(hf_model.config, **config_overrides)
    model = GPT(cfg)
    params = hf_gpt2_state_dict_to_params(sd, cfg)
    return model, params


def hf_gpt2_state_dict_to_params(sd: Dict[str, Any],
                                 cfg: GPTConfig):
    """Torch GPT-2 state_dict (numpy values) -> GPT params pytree."""
    g = lambda k: jnp.asarray(sd[k], cfg.param_dtype)

    def block(i):
        p = f"transformer.h.{i}."
        return {
            "ln1": {"scale": g(p + "ln_1.weight"),
                    "bias": g(p + "ln_1.bias")},
            "attn": {
                "qkv": {"w": g(p + "attn.c_attn.weight"),
                        "b": g(p + "attn.c_attn.bias")},
                "proj": {"w": g(p + "attn.c_proj.weight"),
                         "b": g(p + "attn.c_proj.bias")},
            },
            "ln2": {"scale": g(p + "ln_2.weight"),
                    "bias": g(p + "ln_2.bias")},
            "mlp": {
                "fc1": {"w": g(p + "mlp.c_fc.weight"),
                        "b": g(p + "mlp.c_fc.bias")},
                "fc2": {"w": g(p + "mlp.c_proj.weight"),
                        "b": g(p + "mlp.c_proj.bias")},
            },
        }

    params = {
        "wte": g("transformer.wte.weight"),
        "wpe": g("transformer.wpe.weight"),
        "blocks": [block(i) for i in range(cfg.num_layers)],
        "ln_f": {"scale": g("transformer.ln_f.weight"),
                 "bias": g("transformer.ln_f.bias")},
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = g("lm_head.weight").T
    return params


def bert_config_from_hf(hf_config, **overrides):
    """Map a transformers BertConfig onto BertConfig (post-LN BERT).

    hidden_act="gelu" (erf) is accepted with a warning: the encoder
    computes tanh-approximate gelu — the SAME substitution the
    reference's kernel injection makes when swapping HF layers for
    DeepSpeedTransformerLayer (module_inject), shifting logits ~1e-3.
    "gelu_new" matches exactly. Anything else is refused."""
    from .bert import BertConfig

    act = getattr(hf_config, "hidden_act", "gelu")
    if act == "gelu":
        from ..utils.logging import logger

        logger.warning(
            "HF hidden_act='gelu' (erf): encoder computes tanh-approx "
            "gelu — logits shift ~1e-3, the same substitution the "
            "reference kernel injection makes")
    elif act != "gelu_new":
        raise ValueError(f"hidden_act={act!r} unsupported (gelu/gelu_new)")
    if getattr(hf_config, "position_embedding_type",
               "absolute") != "absolute":
        raise ValueError("only absolute position embeddings supported")
    base = dict(
        vocab_size=hf_config.vocab_size,
        max_seq_len=hf_config.max_position_embeddings,
        num_layers=hf_config.num_hidden_layers,
        num_heads=hf_config.num_attention_heads,
        d_model=hf_config.hidden_size,
        d_ff=hf_config.intermediate_size,
        type_vocab_size=hf_config.type_vocab_size,
        layer_norm_eps=hf_config.layer_norm_eps,
        attn_dropout=hf_config.attention_probs_dropout_prob,
        hidden_dropout=hf_config.hidden_dropout_prob,
        pre_layer_norm=False,  # stock HF BERT is post-LN
    )
    base.update(overrides)
    return BertConfig(**base)


def load_hf_bert(hf_model, **config_overrides):
    """(Bert, params) from a transformers BertForPreTraining.

    The second cross-framework oracle (alongside load_hf_gpt2): the
    whole encoder + MLM/NSP heads import with logit parity, and every
    engine feature then applies to the imported model."""
    from .bert import Bert

    if not getattr(hf_model.config, "tie_word_embeddings", True):
        # Bert.apply computes MLM logits from embeddings.word.T — an
        # independent decoder matrix cannot be represented; refuse
        # rather than import silently wrong predictions
        raise ValueError(
            "untied MLM decoder (tie_word_embeddings=False) unsupported: "
            "the Bert family ties the decoder to the word embeddings")
    sd = _torch_sd_to_numpy(hf_model)
    cfg = bert_config_from_hf(hf_model.config, **config_overrides)
    model = Bert(cfg)
    g = lambda k: jnp.asarray(sd[k], cfg.param_dtype)
    gT = lambda k: jnp.asarray(sd[k].T, cfg.param_dtype)  # torch [out,in]

    def layer(i):
        p = f"bert.encoder.layer.{i}."
        qkv_w = np.concatenate([sd[p + f"attention.self.{m}.weight"].T
                                for m in ("query", "key", "value")], axis=1)
        qkv_b = np.concatenate([sd[p + f"attention.self.{m}.bias"]
                                for m in ("query", "key", "value")])
        return {
            "attn_qkvw": jnp.asarray(qkv_w, cfg.param_dtype),
            "attn_qkvb": jnp.asarray(qkv_b, cfg.param_dtype),
            "attn_ow": gT(p + "attention.output.dense.weight"),
            "attn_ob": g(p + "attention.output.dense.bias"),
            "attn_nw": g(p + "attention.output.LayerNorm.weight"),
            "attn_nb": g(p + "attention.output.LayerNorm.bias"),
            "inter_w": gT(p + "intermediate.dense.weight"),
            "inter_b": g(p + "intermediate.dense.bias"),
            "output_w": gT(p + "output.dense.weight"),
            "output_b": g(p + "output.dense.bias"),
            "norm_w": g(p + "output.LayerNorm.weight"),
            "norm_b": g(p + "output.LayerNorm.bias"),
        }

    D = cfg.d_model
    params = {
        "embeddings": {
            "word": g("bert.embeddings.word_embeddings.weight"),
            "position": g("bert.embeddings.position_embeddings.weight"),
            "token_type": g("bert.embeddings.token_type_embeddings.weight"),
            "ln_w": g("bert.embeddings.LayerNorm.weight"),
            "ln_b": g("bert.embeddings.LayerNorm.bias"),
        },
        "layers": [layer(i) for i in range(cfg.num_layers)],
        # post-LN BERT has no final LN; identity values stay unused
        "final_ln_w": jnp.ones((D,), cfg.param_dtype),
        "final_ln_b": jnp.zeros((D,), cfg.param_dtype),
        "pooler": {"w": gT("bert.pooler.dense.weight"),
                   "b": g("bert.pooler.dense.bias")},
        "mlm_head": {
            "w": gT("cls.predictions.transform.dense.weight"),
            "b": g("cls.predictions.transform.dense.bias"),
            "ln_w": g("cls.predictions.transform.LayerNorm.weight"),
            "ln_b": g("cls.predictions.transform.LayerNorm.bias"),
            "decoder_b": g("cls.predictions.bias"),
        },
        "nsp_head": {"w": gT("cls.seq_relationship.weight"),
                     "b": g("cls.seq_relationship.bias")},
    }
    return model, params
