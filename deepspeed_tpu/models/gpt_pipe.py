"""GPT as a PipelineModule — routes the flagship model family through
the 1F1B TrainSchedule engine (heterogeneous LayerSpec executor,
runtime/pipe/engine.py), including tied embeddings and interleaved
virtual stages.

This complements GPTConfig.pipeline_stages (the SPMD GPipe scan in
parallel/pipeline.py, which requires homogeneous stacked blocks and
compiles the whole pipeline into one jit): the LayerSpec form trades
whole-program compilation for the 1F1B schedule's lower bubble/memory
and per-layer checkpoint files.

Note: the last stage materializes [B, S, V] logits for the loss (the
engine's loss_fn contract, reference pipe semantics); the resident
GPT.loss's chunked/streaming CE does not apply here."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..runtime.pipe.module import LayerSpec, PipelineModule, TiedLayerSpec
from .gpt import (GPTConfig, _dropout, _init_block, gpt_block,
                  init_final_ln, init_lm_head, init_wpe, init_wte,
                  layer_norm)


class GPTTokenEmbed:
    """Token embedding — ONLY the wte table, so the tied head neither
    carries nor ships a useless wpe copy."""

    def __init__(self, cfg: GPTConfig):
        self.cfg = cfg

    def init(self, rng):
        return {"wte": init_wte(rng, self.cfg)}

    def apply(self, p, tokens, rng=None, train=True):
        return p["wte"][tokens]


class GPTPosEmbed:
    """Position embedding + embed dropout (the rest of gpt.py's _trunk
    entry, applied after the tied token lookup)."""

    def __init__(self, cfg: GPTConfig):
        self.cfg = cfg

    def init(self, rng):
        return {"wpe": init_wpe(rng, self.cfg)}

    def apply(self, p, x, rng=None, train=True):
        S = x.shape[1]
        x = x + p["wpe"][:S][None, :, :]
        return _dropout(x, self.cfg.embed_dropout, rng, train)


class GPTBlock:
    def __init__(self, cfg: GPTConfig, layer_idx: int):
        self.cfg = cfg
        self.layer_idx = layer_idx

    def init(self, rng):
        return _init_block(rng, self.cfg, self.layer_idx)

    def apply(self, p, x, rng=None, train=True):
        out, _aux = gpt_block(x, p, self.cfg, rng, train)
        return out


class GPTFinalNorm:
    def __init__(self, cfg: GPTConfig):
        self.cfg = cfg

    def init(self, rng):
        return init_final_ln(self.cfg)

    def apply(self, p, x, rng=None, train=True):
        return layer_norm(x, p, self.cfg.layer_norm_eps)


class GPTHead:
    """Untied LM head (tie_embeddings=False)."""

    def __init__(self, cfg: GPTConfig):
        self.cfg = cfg

    def init(self, rng):
        return {"w": init_lm_head(rng, self.cfg)}

    def apply(self, p, x, rng=None, train=True):
        return x @ p["w"].astype(x.dtype)


def _tied_head_forward(layer, p, x):
    """Tied head: project with the embedding table transposed."""
    return x @ p["wte"].astype(x.dtype).T


def gpt_ce_loss(logits, labels):
    """Masked next-token CE ((tokens, labels) batches; -100 masked)."""
    valid = labels >= 0
    safe = jnp.where(valid, labels, 0)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, safe[..., None], axis=-1)[..., 0]
    return jnp.where(valid, nll, 0.0).sum() / jnp.maximum(valid.sum(), 1)


def gpt_pipeline_module(cfg: GPTConfig, num_stages: int,
                        interleave: int = 1,
                        partition_method: str = "parameters",
                        activation_checkpoint_interval: int = 0
                        ) -> PipelineModule:
    """Build the GPT stack as LayerSpecs for the 1F1B engine.

    cfg.pipeline_stages must stay 1 (that flag selects the SPMD GPipe
    executor inside GPT.loss; here staging is the engine's job)."""
    if cfg.pipeline_stages > 1:
        raise ValueError("leave cfg.pipeline_stages=1: gpt_pipeline_module "
                         "stages through the 1F1B engine instead")
    if cfg.num_experts > 1:
        raise NotImplementedError("MoE blocks are not supported in the "
                                  "LayerSpec pipeline form yet")
    if cfg.sequence_parallel:
        raise NotImplementedError(
            "sequence_parallel needs a `seq` mesh axis; the 1F1B engine's "
            "per-stage meshes are data-only — use the SPMD executor "
            "(cfg.pipeline_stages) or drop SP for the LayerSpec form")
    layers = [TiedLayerSpec("embed", GPTTokenEmbed, cfg)
              if cfg.tie_embeddings else LayerSpec(GPTTokenEmbed, cfg)]
    layers += [LayerSpec(GPTPosEmbed, cfg)]
    layers += [LayerSpec(GPTBlock, cfg, i) for i in range(cfg.num_layers)]
    layers += [LayerSpec(GPTFinalNorm, cfg)]
    if cfg.tie_embeddings:
        layers += [TiedLayerSpec("embed", GPTTokenEmbed, cfg,
                                 forward_fn=_tied_head_forward)]
    else:
        layers += [LayerSpec(GPTHead, cfg)]
    return PipelineModule(
        layers, num_stages=num_stages, loss_fn=gpt_ce_loss,
        partition_method=partition_method,
        activation_checkpoint_interval=activation_checkpoint_interval,
        interleave=interleave)
