"""BERT model family, built on the fused DeepSpeedTransformerLayer.

The reference's fused transformer kernels target BERT pretraining
(BASELINE.md rows 1-3; docs/_tutorials/bert-pretraining.md) but ship no
in-tree model — tests carry full BERT modeling copies
(reference tests/unit/modeling.py / modelingpreln.py). Here BERT is a
first-class in-tree family: embeddings + N fused encoder layers + MLM/NSP
heads, expressed as a TrainModule so deepspeed_tpu.initialize() drives it
directly.

TPU-first choices: bf16 activations; one [h,3h] QKV matmul per layer
(MXU-friendly); tensor parallelism via PartitionSpecs on the `model` axis
(column-parallel qkv/inter, row-parallel proj/output — XLA inserts psum);
per-layer rematerialisation behind `remat`.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..comm.mesh import MODEL_AXIS
from ..ops.transformer.transformer import (DeepSpeedTransformerConfig,
                                           init_transformer_params,
                                           transformer_layer_forward)
from ..runtime.module import TrainModule


@dataclasses.dataclass
class BertConfig:
    vocab_size: int = 30528          # 30522 padded to a 64 multiple
    max_seq_len: int = 512
    num_layers: int = 12
    num_heads: int = 12
    d_model: int = 768
    d_ff: Optional[int] = None
    type_vocab_size: int = 2
    attn_dropout: float = 0.1
    hidden_dropout: float = 0.1
    layer_norm_eps: float = 1e-12
    initializer_range: float = 0.02
    pre_layer_norm: bool = True      # reference ships both (modelingpreln.py)
    sparsity_config: Any = None      # block-sparse attention (SparseAttentionUtils)
    remat: bool = False
    attn_impl: str = "auto"
    loss_chunks: int = 0             # MLM CE chunking: 0 auto, 1 off, n chunks
    param_dtype: Any = jnp.float32
    compute_dtype: Any = jnp.bfloat16

    def __post_init__(self):
        if self.d_ff is None:
            self.d_ff = 4 * self.d_model
        assert self.d_model % self.num_heads == 0

    def layer_config(self) -> DeepSpeedTransformerConfig:
        return DeepSpeedTransformerConfig(
            hidden_size=self.d_model,
            intermediate_size=self.d_ff,
            heads=self.num_heads,
            attn_dropout_ratio=self.attn_dropout,
            hidden_dropout_ratio=self.hidden_dropout,
            num_hidden_layers=self.num_layers,
            initializer_range=self.initializer_range,
            layer_norm_eps=self.layer_norm_eps,
            pre_layer_norm=self.pre_layer_norm,
            attn_impl=self.attn_impl,
            sparsity_config=self.sparsity_config,
            dtype=self.compute_dtype)


# bert-large @ seq 128/512 is the reference's headline benchmark config
# (docs/_tutorials/bert-pretraining.md:387)
BERT_SIZES = {
    "bert-tiny": dict(num_layers=2, num_heads=2, d_model=64,
                      vocab_size=512, max_seq_len=128),
    "bert-base": dict(num_layers=12, num_heads=12, d_model=768),
    "bert-large": dict(num_layers=24, num_heads=16, d_model=1024),
}


def bert_config(name: str = "bert-base", **overrides) -> BertConfig:
    return BertConfig(**{**BERT_SIZES[name], **overrides})


class Bert(TrainModule):
    """Masked-LM + next-sentence-prediction BERT.

    batch dict: input_ids [B,S], token_type_ids [B,S] (optional),
    attention_mask [B,S] 1=keep (optional), mlm_labels [B,S] with -100 at
    unmasked positions, nsp_labels [B] (optional).
    """

    def __init__(self, config: BertConfig):
        self.config = config
        self.param_specs = self._build_param_specs()

    # ------------------------------------------------------------------
    def init(self, rng):
        cfg = self.config
        pd = cfg.param_dtype
        k_emb, k_layers, k_pool = jax.random.split(rng, 3)
        std = cfg.initializer_range
        n = lambda k, s: (std * jax.random.normal(k, s)).astype(pd)
        ke = jax.random.split(k_emb, 3)
        layer_cfg = cfg.layer_config()
        layers = [init_transformer_params(layer_cfg, k, pd)
                  for k in jax.random.split(k_layers, cfg.num_layers)]
        kp = jax.random.split(k_pool, 3)
        return {
            "embeddings": {
                "word": n(ke[0], (cfg.vocab_size, cfg.d_model)),
                "position": n(ke[1], (cfg.max_seq_len, cfg.d_model)),
                "token_type": n(ke[2], (cfg.type_vocab_size, cfg.d_model)),
                "ln_w": jnp.ones((cfg.d_model,), pd),
                "ln_b": jnp.zeros((cfg.d_model,), pd),
            },
            "layers": layers,
            "final_ln_w": jnp.ones((cfg.d_model,), pd),
            "final_ln_b": jnp.zeros((cfg.d_model,), pd),
            "pooler": {"w": n(kp[0], (cfg.d_model, cfg.d_model)),
                       "b": jnp.zeros((cfg.d_model,), pd)},
            "mlm_head": {"w": n(kp[1], (cfg.d_model, cfg.d_model)),
                         "b": jnp.zeros((cfg.d_model,), pd),
                         "ln_w": jnp.ones((cfg.d_model,), pd),
                         "ln_b": jnp.zeros((cfg.d_model,), pd),
                         "decoder_b": jnp.zeros((cfg.vocab_size,), pd)},
            "nsp_head": {"w": n(kp[2], (cfg.d_model, 2)),
                         "b": jnp.zeros((2,), pd)},
        }

    def _build_param_specs(self):
        """Megatron-style TP over the `model` axis for the per-layer
        matrices; embeddings vocab-parallel."""
        m = MODEL_AXIS
        layer = {
            "attn_qkvw": P(None, m), "attn_qkvb": P(m),
            "attn_ow": P(m, None), "attn_ob": P(),
            "attn_nw": P(), "attn_nb": P(),
            "inter_w": P(None, m), "inter_b": P(m),
            "output_w": P(m, None), "output_b": P(),
            "norm_w": P(), "norm_b": P(),
        }
        return {
            "embeddings": {"word": P(m, None), "position": P(),
                           "token_type": P(), "ln_w": P(), "ln_b": P()},
            "layers": [dict(layer) for _ in range(self.config.num_layers)],
            "final_ln_w": P(), "final_ln_b": P(),
            "pooler": {"w": P(), "b": P()},
            "mlm_head": {"w": P(), "b": P(), "ln_w": P(), "ln_b": P(),
                         "decoder_b": P(m)},
            "nsp_head": {"w": P(), "b": P()},
        }

    # ------------------------------------------------------------------
    def _ln(self, x, w, b):
        eps = self.config.layer_norm_eps
        xf = x.astype(jnp.float32)
        mu = jnp.mean(xf, axis=-1, keepdims=True)
        var = jnp.mean(jnp.square(xf - mu), axis=-1, keepdims=True)
        y = (xf - mu) * jax.lax.rsqrt(var + eps)
        return (y * w.astype(jnp.float32) + b.astype(jnp.float32)).astype(x.dtype)

    def encode(self, params, input_ids, token_type_ids=None,
               attention_mask=None, rng=None, train=False):
        cfg = self.config
        dtype = cfg.compute_dtype
        B, S = input_ids.shape
        emb = params["embeddings"]
        x = emb["word"][input_ids] + emb["position"][:S][None, :, :]
        if token_type_ids is not None:
            x = x + emb["token_type"][token_type_ids]
        x = self._ln(x.astype(dtype), emb["ln_w"], emb["ln_b"])

        bias = None
        if attention_mask is not None:
            # additive mask broadcastable to [B, heads, S, S]
            bias = (1.0 - attention_mask[:, None, None, :].astype(
                jnp.float32)) * jnp.finfo(jnp.float32).min

        layer_cfg = cfg.layer_config()
        rngs = (jax.random.split(rng, cfg.num_layers)
                if rng is not None else [None] * cfg.num_layers)

        def block(x, lp, r):
            return transformer_layer_forward(
                lp, x, bias, config=layer_cfg, rng=r, train=train)

        if cfg.remat:
            block = jax.checkpoint(block)
        for lp, r in zip(params["layers"], rngs):
            x = block(x, lp, r)
        if cfg.pre_layer_norm:
            x = self._ln(x, params["final_ln_w"], params["final_ln_b"])
        return x

    def _mlm_hidden(self, params, x):
        """MLM-head transform (gelu + LN) shared by apply() and loss()."""
        mh = params["mlm_head"]
        h = jax.nn.gelu(x @ mh["w"].astype(x.dtype) + mh["b"].astype(x.dtype),
                        approximate=True)
        return self._ln(h, mh["ln_w"], mh["ln_b"])

    def _nsp_logits(self, params, x):
        pooled = jnp.tanh(x[:, 0, :] @ params["pooler"]["w"].astype(x.dtype) +
                          params["pooler"]["b"].astype(x.dtype))
        return pooled @ params["nsp_head"]["w"].astype(x.dtype) + \
            params["nsp_head"]["b"].astype(x.dtype)

    def apply(self, params, batch, rng=None, train=False):
        x = self.encode(params, batch["input_ids"],
                        batch.get("token_type_ids"),
                        batch.get("attention_mask"), rng=rng, train=train)
        h = self._mlm_hidden(params, x)
        # tied decoder: embeddings.word^T (reference BERT ties MLM decoder)
        logits = h @ params["embeddings"]["word"].astype(x.dtype).T + \
            params["mlm_head"]["decoder_b"].astype(x.dtype)
        return logits, self._nsp_logits(params, x)

    def loss(self, params, batch, rng=None, train=True):
        # streamed MLM cross entropy: hidden states and the tied decoder
        # weight go straight to summed NLL via the GPT family's fused
        # projection+CE (logsumexp − label logit) — no [B, S, V] fp32
        # log-softmax is materialised (~2 GB at the reference's seq-128
        # micro-64 pretraining recipe). apply() keeps returning full
        # logits for inference and the HF parity oracle.
        from .gpt import _softmax_xent_from_hidden

        x = self.encode(params, batch["input_ids"],
                        batch.get("token_type_ids"),
                        batch.get("attention_mask"), rng=rng, train=train)
        h = self._mlm_hidden(params, x)
        labels = batch["mlm_labels"]
        mask = (labels != -100)
        safe = jnp.where(mask, labels, 0)
        B, S, D = h.shape
        w = params["embeddings"]["word"].astype(h.dtype).T  # tied decoder
        total = _softmax_xent_from_hidden(
            h.reshape(B * S, D), w, safe.reshape(-1), mask.reshape(-1),
            self.config.loss_chunks,
            bias=params["mlm_head"]["decoder_b"])
        denom = jnp.maximum(mask.sum(), 1)
        loss = total / denom
        if "nsp_labels" in batch:
            nsp = self._nsp_logits(params, x)
            nsp_logp = jax.nn.log_softmax(nsp.astype(jnp.float32), axis=-1)
            loss = loss - jnp.mean(
                jnp.take_along_axis(nsp_logp,
                                    batch["nsp_labels"][:, None], axis=-1))
        return loss
