"""Model zoo — TPU-native model families (the reference has none in-tree;
its model tests drive an external Megatron GPT-2, SURVEY.md §1)."""

from .bert import Bert, BertConfig, bert_config, BERT_SIZES
from .gpt import GPT, GPTConfig, gpt2_config, GPT2_SIZES
from .gpt_pipe import gpt_pipeline_module
from .generation import generate
from .hf import (bert_config_from_hf, gpt2_config_from_hf,
                 load_hf_bert, load_hf_gpt2)

__all__ = ["GPT", "GPTConfig", "gpt2_config", "GPT2_SIZES",
           "gpt_pipeline_module",
           "Bert", "BertConfig", "bert_config", "BERT_SIZES",
           "load_hf_gpt2", "gpt2_config_from_hf",
           "load_hf_bert", "bert_config_from_hf", "generate"]
