"""Model zoo — TPU-native model families (the reference has none in-tree;
its model tests drive an external Megatron GPT-2, SURVEY.md §1)."""

from .gpt import GPT, GPTConfig, gpt2_config, GPT2_SIZES

__all__ = ["GPT", "GPTConfig", "gpt2_config", "GPT2_SIZES"]
