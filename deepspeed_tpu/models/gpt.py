"""GPT model family — the framework's flagship decoder-only transformer.

The reference ships no model zoo (SURVEY.md §1: "There is no model zoo...");
its model tests drive an external Megatron GPT-2
(/root/reference/tests/model/Megatron_GPT2/). This framework is standalone,
so the GPT family lives in-tree, built TPU-first:

* pure-function params pytree (nested dicts), bf16-friendly, static shapes;
* Megatron-style tensor parallelism expressed as `PartitionSpec`s over the
  `model` mesh axis (column-parallel QKV/fc1, row-parallel proj/fc2,
  vocab-parallel embedding) — XLA inserts the psums the reference delegates
  to Megatron's mpu (reference engine.py:622-641 just *accepts* an mpu);
* sequence sharding of activations over the `seq` axis
  (with_sharding_constraint), ring attention optional via
  deepspeed_tpu.parallel.ring_attention;
* `jax.checkpoint` rematerialisation per block (the analogue of
  activation_checkpointing/checkpointing.py) behind `remat=True`;
* attention dispatches through ops.transformer.attention (Pallas flash
  attention on TPU, fused-XLA fallback elsewhere).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..comm.mesh import DATA_AXIS, MODEL_AXIS, PIPE_AXIS, SEQ_AXIS
from ..ops.transformer.attention import multihead_attention
from ..runtime.module import TrainModule


@dataclasses.dataclass
class GPTConfig:
    vocab_size: int = 50304          # GPT-2 50257 padded to a 128 multiple
    max_seq_len: int = 1024
    num_layers: int = 12
    num_heads: int = 12
    d_model: int = 768
    d_ff: Optional[int] = None       # default 4*d_model
    dropout: float = 0.0
    embed_dropout: float = 0.0
    attn_dropout: float = -1.0       # attention-probability dropout;
                                     # -1 -> follow `dropout` (reference
                                     # transformer config keeps the two
                                     # ratios separate too)
    layer_norm_eps: float = 1e-5
    tie_embeddings: bool = True
    loss_chunks: int = 0             # CE chunking: 0 auto, 1 off, n chunks
    loss_impl: str = "auto"          # auto/xla: chunked XLA CE; pallas:
                                     # fused streaming kernel (no logits in
                                     # HBM; invalid with vocab-parallel TP)
    remat: bool = False              # per-block rematerialisation
    shard_activations: bool = True   # seq/data sharding constraints
    attn_impl: str = "auto"          # auto|pallas|xla (ops/transformer)
    flash_block_q: int = 0           # 0 -> kernel default
    flash_block_k: int = 0
    param_dtype: Any = jnp.float32
    pipeline_stages: int = 1         # >1: stack blocks + pipeline over `pipe`
    pipeline_micro_batches: int = 0  # 0 -> default (= pipe size)
    sequence_parallel: bool = False  # SP attention over the `seq` axis
    sequence_parallel_impl: str = "ring"  # ring | ring_zigzag | ulysses
    # Mixture-of-Experts (beyond-parity; reference has no MoE, SURVEY §2.2)
    num_experts: int = 1             # >1: MoE FFN every moe_layer_freq layers
    moe_top_k: int = 1
    moe_layer_freq: int = 2          # MoE on layers with idx % freq == 1
    moe_capacity_factor: float = 1.25
    moe_aux_loss_weight: float = 1e-2

    def __post_init__(self):
        if self.d_ff is None:
            self.d_ff = 4 * self.d_model
        assert self.d_model % self.num_heads == 0
        if self.num_experts > 1 and self.pipeline_stages > 1:
            raise ValueError("MoE and pipeline mode are mutually exclusive "
                             "for now (stacked stage params must be uniform)")

    def is_moe_layer(self, idx: int) -> bool:
        # freq f -> layers f-1, 2f-1, ... (f=1: every layer; f=2: odd layers)
        return (self.num_experts > 1 and
                idx % self.moe_layer_freq == self.moe_layer_freq - 1)

    def moe_config(self):
        from ..moe.layer import MoEConfig

        return MoEConfig(d_model=self.d_model, d_ff=self.d_ff,
                         num_experts=self.num_experts, top_k=self.moe_top_k,
                         capacity_factor=self.moe_capacity_factor)

    @property
    def head_dim(self):
        return self.d_model // self.num_heads


# Standard GPT-2 sizes; "xl" is the 1.5B north-star model (BASELINE.md).
GPT2_SIZES: Dict[str, Dict[str, int]] = {
    "nano":   dict(num_layers=3,  num_heads=3,  d_model=48,  max_seq_len=128,
                   vocab_size=256),
    "small":  dict(num_layers=12, num_heads=12, d_model=768),
    "medium": dict(num_layers=24, num_heads=16, d_model=1024),
    "large":  dict(num_layers=36, num_heads=20, d_model=1280),
    "xl":     dict(num_layers=48, num_heads=25, d_model=1600),
}


def gpt2_config(size: str = "small", **overrides) -> GPTConfig:
    base = dict(GPT2_SIZES[size])
    base.update(overrides)
    return GPTConfig(**base)


# ---------------------------------------------------------------------------
# init
# ---------------------------------------------------------------------------

def init_wte(rng, cfg: GPTConfig):
    """Token-embedding table — THE single definition of its init scale;
    GPT.init, the streaming init, and the LayerSpec pipeline form
    (gpt_pipe.py) all share it so their initializations cannot drift."""
    return (jax.random.normal(rng, (cfg.vocab_size, cfg.d_model))
            * 0.02).astype(cfg.param_dtype)


def init_wpe(rng, cfg: GPTConfig):
    return (jax.random.normal(rng, (cfg.max_seq_len, cfg.d_model))
            * 0.01).astype(cfg.param_dtype)


def init_final_ln(cfg: GPTConfig):
    return {"scale": jnp.ones((cfg.d_model,), cfg.param_dtype),
            "bias": jnp.zeros((cfg.d_model,), cfg.param_dtype)}


def init_lm_head(rng, cfg: GPTConfig):
    return (jax.random.normal(rng, (cfg.d_model, cfg.vocab_size))
            * 0.02).astype(cfg.param_dtype)


def _init_block(rng, cfg: GPTConfig, layer_idx: int = 0):
    k = jax.random.split(rng, 5)
    d, f = cfg.d_model, cfg.d_ff
    std = 0.02
    proj_std = std / math.sqrt(2 * cfg.num_layers)  # GPT-2 residual scaling
    dt = cfg.param_dtype
    return {
        "ln1": {"scale": jnp.ones((d,), dt), "bias": jnp.zeros((d,), dt)},
        "attn": {
            "qkv": {"w": (jax.random.normal(k[0], (d, 3 * d)) * std).astype(dt),
                    "b": jnp.zeros((3 * d,), dt)},
            "proj": {"w": (jax.random.normal(k[1], (d, d)) * proj_std).astype(dt),
                     "b": jnp.zeros((d,), dt)},
        },
        "ln2": {"scale": jnp.ones((d,), dt), "bias": jnp.zeros((d,), dt)},
    } | (
        {"moe": _moe(cfg).init(k[4], param_dtype=dt)}
        if cfg.is_moe_layer(layer_idx) else
        {"mlp": {
            "fc1": {"w": (jax.random.normal(k[2], (d, f)) * std).astype(dt),
                    "b": jnp.zeros((f,), dt)},
            "fc2": {"w": (jax.random.normal(k[3], (f, d)) * proj_std).astype(dt),
                    "b": jnp.zeros((d,), dt)},
        }})


def _moe(cfg: GPTConfig):
    from ..moe.layer import MoE

    return MoE(cfg.moe_config())


def _block_specs(cfg: GPTConfig, layer_idx: int = 0):
    """Megatron TP layout: column-parallel qkv/fc1 (shard output dim over
    `model`), row-parallel proj/fc2 (shard input dim). MoE layers swap the
    MLP specs for expert-parallel ones (expert dim over `data`)."""
    if cfg.is_moe_layer(layer_idx):
        from ..moe.layer import MoE

        return {
            "ln1": {"scale": P(), "bias": P()},
            "attn": {
                "qkv": {"w": P(None, MODEL_AXIS), "b": P(MODEL_AXIS)},
                "proj": {"w": P(MODEL_AXIS, None), "b": P()},
            },
            "ln2": {"scale": P(), "bias": P()},
            "moe": MoE.param_specs(),
        }
    return {
        "ln1": {"scale": P(), "bias": P()},
        "attn": {
            "qkv": {"w": P(None, MODEL_AXIS), "b": P(MODEL_AXIS)},
            "proj": {"w": P(MODEL_AXIS, None), "b": P()},
        },
        "ln2": {"scale": P(), "bias": P()},
        "mlp": {
            "fc1": {"w": P(None, MODEL_AXIS), "b": P(MODEL_AXIS)},
            "fc2": {"w": P(MODEL_AXIS, None), "b": P()},
        },
    }


# ---------------------------------------------------------------------------
# forward pieces (pure functions)
# ---------------------------------------------------------------------------

def layer_norm(x, p, eps):
    x32 = x.astype(jnp.float32)
    mu = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.var(x32, axis=-1, keepdims=True)
    y = (x32 - mu) * jax.lax.rsqrt(var + eps)
    return (y * p["scale"].astype(jnp.float32) +
            p["bias"].astype(jnp.float32)).astype(x.dtype)


def _dropout(x, rate, rng, train):
    # counter-hash mask, not bernoulli/threefry — see
    # ops/transformer/dropout.py for why
    from ..ops.transformer.dropout import hash_dropout

    return hash_dropout(x, rate, rng, train)


def _constrain(x, cfg: GPTConfig, spec):
    if not cfg.shard_activations:
        return x
    from ..comm.mesh import peek_mesh

    info = peek_mesh()
    if info is not None and info.hierarchical:
        # the literal "data" axis does not exist on a hierarchical mesh
        # (comm.hierarchy factors it into data_outer/data_inner): expand
        # it so the constraint binds instead of being swallowed below
        spec = P(*[info.data_spec if s == DATA_AXIS else s for s in spec])
    try:
        return jax.lax.with_sharding_constraint(x, spec)
    except (ValueError, RuntimeError):
        # no mesh in scope (e.g. plain jit in unit tests)
        return x


def gpt_block(x, p, cfg: GPTConfig, rng=None, train=True):
    """One pre-LN transformer block. x: [B, S, D]."""
    B, S, D = x.shape
    H = cfg.num_heads
    r1 = r2 = r3 = None
    if rng is not None:
        r1, r2, r3 = jax.random.split(rng, 3)

    h = layer_norm(x, p["ln1"], cfg.layer_norm_eps)
    attn_rate = cfg.dropout if cfg.attn_dropout < 0 else cfg.attn_dropout
    qkv = h @ p["attn"]["qkv"]["w"].astype(h.dtype) + \
        p["attn"]["qkv"]["b"].astype(h.dtype)
    q, kk, v = jnp.split(qkv, 3, axis=-1)
    split_heads = lambda t: t.reshape(B, S, H, D // H)
    if cfg.sequence_parallel and cfg.sequence_parallel_impl == "ulysses":
        from ..parallel.ulysses import ulysses_attention

        # every device holds the full sequence for its heads, so
        # probability dropout works exactly as on the dense path
        attn = ulysses_attention(
            split_heads(q), split_heads(kk), split_heads(v),
            multihead_attention, causal=True, impl=cfg.attn_impl,
            dropout_rate=attn_rate, dropout_rng=r1, train=train,
            block_q=cfg.flash_block_q or None,
            block_k=cfg.flash_block_k or None)
    elif cfg.sequence_parallel:
        if cfg.sequence_parallel_impl not in ("ring", "ring_zigzag"):
            raise ValueError(
                f"unknown sequence_parallel_impl "
                f"{cfg.sequence_parallel_impl!r}; use 'ring', "
                f"'ring_zigzag' or 'ulysses'")
        if train and attn_rate > 0.0 and r1 is not None:
            # the ring formulation has no attention-probability dropout
            # (its block walk keeps probabilities implicit and carries no
            # mask state) — failing is honest, silently skipping is not;
            # ulysses runs dropout in-kernel. rng=None configs (e.g. the
            # SPMD pipeline trunk) treat dropout as inert on every path.
            raise ValueError(
                "attention-probability dropout is not supported on the "
                "ring/ring_zigzag sequence-parallel path; use "
                "sequence_parallel_impl='ulysses', or attn_dropout=0.0 "
                "to keep residual/MLP dropout without it")
        from ..parallel.ring_attention import ring_attention

        # ring_zigzag: the trunk permuted the sequence into the zigzag
        # layout once after the embedding, so every block's attention
        # runs the load-balanced causal ring (~2x fewer FLOPs)
        attn = ring_attention(
            split_heads(q), split_heads(kk), split_heads(v), causal=True,
            layout=("zigzag" if cfg.sequence_parallel_impl == "ring_zigzag"
                    else "contiguous"),
            # same config knob as the flash kernel: bounds per-step score
            # memory at [B, H, block_q, chunk]
            block_q=cfg.flash_block_q)
    else:
        attn = multihead_attention(split_heads(q), split_heads(kk),
                                   split_heads(v), causal=True,
                                   impl=cfg.attn_impl,
                                   dropout_rate=attn_rate,
                                   dropout_rng=r1, train=train,
                                   block_q=cfg.flash_block_q or None,
                                   block_k=cfg.flash_block_k or None)
    attn = attn.reshape(B, S, D)
    attn = attn @ p["attn"]["proj"]["w"].astype(h.dtype) + \
        p["attn"]["proj"]["b"].astype(h.dtype)
    x = x + _dropout(attn, cfg.dropout, r2, train)
    x = _constrain(x, cfg, P(DATA_AXIS, SEQ_AXIS, None))

    h = layer_norm(x, p["ln2"], cfg.layer_norm_eps)
    aux = jnp.zeros((), jnp.float32)
    if "moe" in p:
        r_moe = None
        if r3 is not None:
            r_moe, r3 = jax.random.split(r3)
        h, aux = _moe(cfg)(p["moe"], h, rng=r_moe, train=train)
    else:
        h = h @ p["mlp"]["fc1"]["w"].astype(h.dtype) + \
            p["mlp"]["fc1"]["b"].astype(h.dtype)
        h = jax.nn.gelu(h, approximate=True)
        h = _constrain(h, cfg, P(DATA_AXIS, SEQ_AXIS, MODEL_AXIS))
        h = h @ p["mlp"]["fc2"]["w"].astype(h.dtype) + \
            p["mlp"]["fc2"]["b"].astype(h.dtype)
    x = x + _dropout(h, cfg.dropout, r3, train)
    return _constrain(x, cfg, P(DATA_AXIS, SEQ_AXIS, None)), aux


def _ce_rows(logits32, labels, valid):
    """Sum of masked next-token NLL over rows, from fp32 logits.

    `logsumexp - label_logit` instead of materialising the [N, V] fp32
    log-softmax the previous implementation wrote to HBM — backward is the
    standard softmax-minus-onehot XLA derives from this form."""
    lse = jax.nn.logsumexp(logits32, axis=-1)
    ll = jnp.take_along_axis(logits32, labels[:, None], axis=-1)[:, 0]
    return jnp.sum(jnp.where(valid, lse - ll, 0.0))


def _softmax_xent_from_hidden(x, w, labels, valid, n_chunks=0,
                              impl="auto", bias=None):
    """Fused projection + cross entropy: hidden states [N, D] and the [D, V]
    head weight go straight to summed NLL without a [N, V] activation
    surviving the loss.

    The projection runs with fp32 MXU accumulation (preferred_element_type)
    so no separate bf16-logits buffer + fp32 cast is materialised — the
    single biggest HBM cost of the naive CE at GPT-2 vocab (N·V·4 bytes,
    ~1.6 GB at micro 8 / seq 1024). With n_chunks > 1 the rows are processed
    by a rematerialised lax.scan, so peak memory holds one [N/c, V] chunk;
    backward recomputes each chunk's logits (flash-attention-style,
    applied to the LM head).

    n_chunks: 0 = auto (chunks of ~2048 rows for large-vocab models),
    1 = single fused matmul, n = explicit chunk count (must divide N).
    """
    N, D = x.shape
    V = w.shape[-1]

    if impl == "pallas" and bias is not None:
        from ..utils.logging import logger

        logger.warning("loss_impl='pallas': fused kernel carries no "
                       "decoder bias; using the XLA path")
        impl = "xla"
    if impl == "pallas":
        from ..comm.mesh import peek_mesh
        from ..ops.transformer.fused_xent import fused_softmax_xent_sum

        info = peek_mesh()
        if info is not None and info.mesh.shape.get("model", 1) > 1:
            raise ValueError(
                "loss_impl='pallas' is invalid with vocab-parallel TP "
                "(model axis > 1): the kernel's logsumexp is row-global")
        # block sizes must divide the shapes; vocab 50304 = 393*128 takes
        # 384, the padded-to-128 GPT-2 family always has a lane-aligned
        # divisor
        br = next((b for b in (256, 128) if N % b == 0), None)
        bv = next((b for b in (512, 448, 384, 256, 128) if V % b == 0),
                  None)
        if br and bv:
            return fused_softmax_xent_sum(x, jnp.asarray(w), labels, valid,
                                          br, bv)
        from ..utils.logging import logger

        logger.warning(f"loss_impl='pallas': shapes N={N}, V={V} have no "
                       f"lane-aligned block divisor; using the XLA path")

    def project(rows):
        out = jax.lax.dot_general(rows, w, (((1,), (0,)), ((), ())),
                                  preferred_element_type=jnp.float32)
        if bias is not None:
            out = out + bias.astype(jnp.float32)
        return out

    if n_chunks == 0:  # auto: only chunk when the logits buffer is large
        # enough to matter against TPU HBM (16 GB on v5e) — chunking costs
        # a full logit recompute in backward, so below ~4 GB of fp32
        # logits the single fused matmul wins; GPT-2 at micro 8 / seq 1024
        # (1.6 GB) and the BERT-large seq-128 recipe (1 GB) stay unchunked.
        # Above the threshold, chunk count is sized from the SAME bytes
        # (≈2 GB per chunk) so the decision and the count can't disagree
        # at small N / huge V
        total = N * V * 4
        n_chunks = -(-total // (2 << 30)) if total > 4 << 30 else 1
    # clamp BEFORE the fix-up walk: a requested count above N (e.g.
    # loss_chunks=100 at N=32) has no divisor of N above it, so the
    # upward search below would spin forever at trace time; N itself is
    # always reachable (chunks of one row)
    n_chunks = min(n_chunks, N)
    # fix up to a divisor of N by adding chunks (smaller chunks — never
    # backslide below the byte-derived count, which could silently undo
    # the chunking decision at awkward N)
    while n_chunks > 1 and N % n_chunks:
        n_chunks += 1
    if n_chunks <= 1:
        return _ce_rows(project(x), labels, valid)

    def body(carry, inp):
        rows, lc, vc = inp
        return carry + _ce_rows(project(rows), lc, vc), None

    total, _ = jax.lax.scan(
        jax.checkpoint(body),
        jnp.zeros((), jnp.float32),
        (x.reshape(n_chunks, N // n_chunks, D),
         labels.reshape(n_chunks, -1), valid.reshape(n_chunks, -1)))
    return total


class GPT(TrainModule):
    """Decoder-only LM implementing the engine's TrainModule protocol."""

    def __init__(self, config: GPTConfig):
        self.config = config
        self.param_specs = self._build_specs()

    # -- init ----------------------------------------------------------
    def init(self, rng):
        cfg = self.config
        keys = jax.random.split(rng, cfg.num_layers + 3)
        params = {
            "wte": init_wte(keys[0], cfg),
            "wpe": init_wpe(keys[1], cfg),
            "blocks": self._init_blocks(keys[2:2 + cfg.num_layers], cfg),
            "ln_f": init_final_ln(cfg),
        }
        if not cfg.tie_embeddings:
            params["lm_head"] = init_lm_head(keys[-1], cfg)
        return params

    def _init_blocks(self, keys, cfg):
        blocks = [_init_block(k, cfg, i) for i, k in enumerate(keys)]
        if cfg.pipeline_stages > 1:
            from ..parallel.pipeline import stack_stage_params

            return stack_stage_params(blocks)
        return blocks

    def _build_specs(self):
        cfg = self.config
        if cfg.pipeline_stages > 1:
            # stacked blocks: leading layer dim sharded over `pipe`
            blocks = jax.tree_util.tree_map(
                lambda s: P(PIPE_AXIS, *s), _block_specs(cfg),
                is_leaf=lambda x: isinstance(x, P))
        else:
            blocks = [_block_specs(cfg, i) for i in range(cfg.num_layers)]
        specs = {
            "wte": P(MODEL_AXIS, None),   # vocab-parallel embedding
            "wpe": P(),
            "blocks": blocks,
            "ln_f": {"scale": P(), "bias": P()},
        }
        if not cfg.tie_embeddings:
            specs["lm_head"] = P(None, MODEL_AXIS)
        return specs

    # -- forward -------------------------------------------------------
    def _trunk(self, params, tokens, rng=None, train=False, pld_mask=None,
               capture_layers=None):
        """Everything up to (and including) the final layer norm.
        tokens [B, S] int32 -> ([B, S, D] hidden states, MoE aux loss,
        {layer_idx: block output} for capture_layers).

        capture_layers is the TPU-native form of the reference's
        layer-output forward hooks (reference engine.py:227-254): JAX has
        no module hooks, so requested per-block outputs flow out of the
        traced program as explicit extra outputs instead."""
        cfg = self.config
        aux_total = jnp.zeros((), jnp.float32)
        captures = {}
        B, S = tokens.shape
        x = params["wte"][tokens] + params["wpe"][:S][None, :, :]
        if rng is not None:
            rng, sub = jax.random.split(rng)
            x = _dropout(x, cfg.embed_dropout, sub, train)
        x = _constrain(x, cfg, P(DATA_AXIS, SEQ_AXIS, None))

        zig_inv = None
        n_seq = self._stream_zigzag_n()
        if n_seq:
            # ONE layout change for the whole trunk (a static-index
            # gather XLA lowers to a single resharding collective), so
            # every block's ring attention runs mask-free load-balanced;
            # inverted before ln_f — the model's external contract stays
            # contiguous
            if cfg.pipeline_stages > 1:
                raise NotImplementedError(
                    "ring_zigzag + SPMD pipeline is not wired up")
            from ..parallel.ring_attention import zigzag_order

            perm, inv = zigzag_order(S, n_seq)
            zig_inv = jnp.asarray(inv)
            x = _constrain(x[:, jnp.asarray(perm)], cfg,
                           P(DATA_AXIS, SEQ_AXIS, None))

        if cfg.pipeline_stages > 1:
            if capture_layers:
                raise NotImplementedError(
                    "layer-output capture is not supported in SPMD pipeline "
                    "mode (block outputs live on their owning stage)")
            from ..comm.mesh import get_current_mesh
            from ..parallel.pipeline import spmd_pipeline

            x = spmd_pipeline(
                lambda p, h: gpt_block(h, p, cfg, None, train)[0],
                params["blocks"], x, get_current_mesh(),
                num_micro=cfg.pipeline_micro_batches, remat=cfg.remat)
        else:
            block_fn = gpt_block
            if cfg.remat:
                block_fn = jax.checkpoint(
                    gpt_block, static_argnums=(2, 4),
                    policy=jax.checkpoint_policies.nothing_saveable)

            for i, bp in enumerate(params["blocks"]):
                sub = None
                if rng is not None:
                    rng, sub = jax.random.split(rng)
                out, aux = block_fn(x, bp, cfg, sub, train)
                if pld_mask is not None:
                    # progressive layer drop (reference engine.py:972-973):
                    # a dropped layer contributes neither output nor aux
                    aux = jnp.where(pld_mask[i], aux, 0.0)
                    out = jnp.where(pld_mask[i], out, x)
                aux_total = aux_total + aux
                x = out
                if capture_layers is not None and \
                        (capture_layers == "all" or i in capture_layers):
                    # captured in contiguous order even under zigzag
                    captures[i] = x if zig_inv is None else x[:, zig_inv]

        if zig_inv is not None:
            x = _constrain(x[:, zig_inv], cfg, P(DATA_AXIS, SEQ_AXIS, None))
        return (layer_norm(x, params["ln_f"], cfg.layer_norm_eps), aux_total,
                captures)

    def _proj_weight(self, params):
        """[D, V] projection weight in the trunk's compute dtype."""
        if self.config.tie_embeddings:
            return params["wte"].T
        return params["lm_head"]

    def apply(self, params, tokens, rng=None, train=False, pld_mask=None,
              with_aux=False):
        """tokens [B, S] int32 -> logits [B, S, V] (with_aux: also the
        summed MoE load-balancing loss)."""
        x, aux_total, _ = self._trunk(params, tokens, rng=rng, train=train,
                                      pld_mask=pld_mask)
        logits = x @ self._proj_weight(params).astype(x.dtype)
        if with_aux:
            return logits, aux_total
        return logits

    def loss(self, params, batch, rng=None, train=True,
             progressive_layer_drop=False, pld_theta=None,
             capture_layers=None):
        """Next-token cross entropy. batch: (tokens, labels) or dict with
        input_ids/labels; labels == -100 positions are masked (HF parity).

        capture_layers ("all" | iterable of layer indices): also return
        {idx: block output} — the engine's register_forward_hook path."""
        if isinstance(batch, dict):
            tokens = batch["input_ids"]
            labels = batch.get("labels")
        else:
            tokens, labels = batch
        if labels is None:
            tokens, labels = tokens[:, :-1], tokens[:, 1:]

        pld_mask = None
        if progressive_layer_drop and pld_theta is not None and train:
            # per-layer keep gates drawn once per micro step
            if rng is None:
                rng = jax.random.PRNGKey(0)
            rng, sub = jax.random.split(rng)
            pld_mask = jax.random.bernoulli(
                sub, pld_theta, (self.config.num_layers,))

        x, moe_aux, captures = self._trunk(params, tokens, rng=rng,
                                           train=train, pld_mask=pld_mask,
                                           capture_layers=capture_layers)
        valid = (labels >= 0)
        safe_labels = jnp.where(valid, labels, 0)
        B, S, D = x.shape
        nll_sum = _softmax_xent_from_hidden(
            x.reshape(B * S, D), self._proj_weight(params),
            safe_labels.reshape(-1), valid.reshape(-1),
            self.config.loss_chunks, impl=self.config.loss_impl)
        ce = nll_sum / jnp.maximum(jnp.sum(valid), 1)
        if self.config.num_experts > 1 and train:
            # aux applies to the training objective only — eval loss stays
            # pure CE so perplexity comparisons are unbiased
            ce = ce + self.config.moe_aux_loss_weight * moe_aux
        if capture_layers is not None:
            return ce, captures
        return ce

    # -- ZeRO-Infinity streaming protocol ------------------------------
    # (runtime/zero/infinity.py trains larger-than-HBM models by holding
    # only one block's params in device memory at a time; these methods
    # expose the model as embed -> blocks -> head pure stages plus
    # group-wise host init. Reference capability: zero/stage3.py param
    # paging + swap_tensor/partitioned_param_swapper.py.)

    def stream_supported(self) -> bool:
        cfg = self.config
        return (cfg.num_experts == 1 and cfg.pipeline_stages == 1
                and cfg.dropout == 0.0 and cfg.embed_dropout == 0.0)

    def _stream_zigzag_n(self) -> int:
        """seq-axis size when zigzag layout is active, else 0 — THE
        gating rule, shared by the trunk's one-shot layout change
        (_trunk) and the streamed boundary (stream_embed permutes,
        stream_head_loss inverts), so the two paths cannot drift and
        long-context + larger-than-HBM compose."""
        cfg = self.config
        if not (cfg.sequence_parallel
                and cfg.sequence_parallel_impl == "ring_zigzag"):
            return 0
        from ..comm.mesh import get_current_mesh

        n = get_current_mesh().axis_size(SEQ_AXIS)
        return n if n > 1 else 0

    def stream_init(self, rng):
        """Yield (group_name, host_numpy_subtree) with only ONE group ever
        materialized on device — init for models that don't fit in HBM."""
        import numpy as _np

        cfg = self.config
        keys = jax.random.split(rng, cfg.num_layers + 3)
        to_host = lambda t: jax.tree_util.tree_map(
            lambda a: _np.asarray(a), t)

        def embed_init(k0, k1):
            return {"wte": init_wte(k0, cfg), "wpe": init_wpe(k1, cfg)}

        yield "embed", to_host(jax.jit(embed_init)(keys[0], keys[1]))
        for i in range(cfg.num_layers):
            yield f"block:{i}", to_host(
                jax.jit(lambda k, i=i: _init_block(k, cfg, i))(keys[2 + i]))
        head = {"ln_f": init_final_ln(cfg)}
        if not cfg.tie_embeddings:
            head["lm_head"] = jax.jit(
                lambda k: init_lm_head(k, cfg))(keys[-1])
        yield "head", to_host(head)

    def stream_groups(self, params):
        """Disjoint group cover of a full params tree (inverse of
        assemble_groups)."""
        groups = [("embed", {"wte": params["wte"], "wpe": params["wpe"]})]
        for i, bp in enumerate(params["blocks"]):
            groups.append((f"block:{i}", bp))
        head = {"ln_f": params["ln_f"]}
        if not self.config.tie_embeddings:
            head["lm_head"] = params["lm_head"]
        groups.append(("head", head))
        return groups

    def assemble_groups(self, groups: Dict[str, Any]):
        params = {"wte": groups["embed"]["wte"],
                  "wpe": groups["embed"]["wpe"],
                  "blocks": [groups[f"block:{i}"]
                             for i in range(self.config.num_layers)],
                  "ln_f": groups["head"]["ln_f"]}
        if not self.config.tie_embeddings:
            params["lm_head"] = groups["head"]["lm_head"]
        return params

    def stream_embed(self, embed_p, tokens):
        S = tokens.shape[1]
        x = embed_p["wte"][tokens] + embed_p["wpe"][:S][None, :, :]
        n = self._stream_zigzag_n()
        if n:
            from ..parallel.ring_attention import zigzag_order

            perm, _ = zigzag_order(S, n)
            x = _constrain(x[:, jnp.asarray(perm)], self.config,
                           P(DATA_AXIS, SEQ_AXIS, None))
        return x

    def stream_block(self, block_p, x):
        return gpt_block(x, block_p, self.config, None, True)[0]

    def stream_head_loss(self, head_p, wte_or_lm_head, x, labels, valid):
        """ln_f + fused projection CE. `wte_or_lm_head`: the tied wte
        ([V, D]) or lm_head ([D, V]) — tied grads flow to the caller.
        Under zigzag SP, x arrives in the zigzag layout (stream_embed
        permuted it) and is inverted here — labels stay contiguous, the
        same contract as the trunk's pre-ln_f inverse."""
        cfg = self.config
        n = self._stream_zigzag_n()
        if n:
            from ..parallel.ring_attention import zigzag_order

            _, inv = zigzag_order(x.shape[1], n)
            x = _constrain(x[:, jnp.asarray(inv)], cfg,
                           P(DATA_AXIS, SEQ_AXIS, None))
        x = layer_norm(x, head_p["ln_f"], cfg.layer_norm_eps)
        w = (wte_or_lm_head.T if cfg.tie_embeddings else wte_or_lm_head)
        B, S, D = x.shape
        nll = _softmax_xent_from_hidden(
            x.reshape(B * S, D), w, labels.reshape(-1), valid.reshape(-1),
            cfg.loss_chunks, impl=cfg.loss_impl)
        return nll / jnp.maximum(jnp.sum(valid), 1)

    # -- convenience ---------------------------------------------------
    def num_params(self, params=None) -> int:
        if params is None:
            shapes = jax.eval_shape(self.init, jax.random.PRNGKey(0))
            return sum(int(np_prod(l.shape))
                       for l in jax.tree_util.tree_leaves(shapes))
        return sum(int(l.size) for l in jax.tree_util.tree_leaves(params))


def np_prod(shape):
    out = 1
    for s in shape:
        out *= int(s)
    return out
