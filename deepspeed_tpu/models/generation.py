"""Autoregressive generation for the GPT family with a static KV cache.

The reference ships no generation loop (its inference engine arrived in
later versions); this is the TPU-native one: a prefill pass caches K/V per
block, then a `lax.scan` decodes one token per step against fixed-shape
caches (dynamic_update_slice writes, position-masked attention) — fully
jittable, no dynamic shapes, MXU-friendly single-token matmuls batched
over B.

Greedy decoding parity against HuggingFace's generate() is pinned in
tests/test_generation.py via the models/hf.py weight import.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

from .gpt import GPT, layer_norm

NEG_INF = -1e30


def _split_qkv(h, qkv_p, B, T, H, Dh):
    qkv = h @ qkv_p["w"].astype(h.dtype) + qkv_p["b"].astype(h.dtype)
    q, k, v = jnp.split(qkv, 3, axis=-1)
    shape = lambda t: t.reshape(B, T, H, Dh)
    return shape(q), shape(k), shape(v)


def _block_with_cache(p, cfg, x, ck, cv, pos):
    """One decoder block over x [B, T, D]; returns output + updated
    caches. `pos` = index of x's first token in the sequence; attention
    sees cache positions <= pos + t (causal)."""
    B, T, D = x.shape
    H, Dh = cfg.num_heads, cfg.head_dim
    L = ck.shape[1]
    h = layer_norm(x, p["ln1"], cfg.layer_norm_eps)
    q, k, v = _split_qkv(h, p["attn"]["qkv"], B, T, H, Dh)
    # cast to the cache dtype on write (identity when they agree): a
    # bf16 cache under fp32 params stores rounded K/V, mirroring the
    # serving engine's kv_dtype="bf16" dense store bit-for-bit
    ck = jax.lax.dynamic_update_slice(ck, k.astype(ck.dtype),
                                      (0, pos, 0, 0))
    cv = jax.lax.dynamic_update_slice(cv, v.astype(cv.dtype),
                                      (0, pos, 0, 0))
    scores = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                        ck.astype(jnp.float32)) * (Dh ** -0.5)
    q_idx = pos + jnp.arange(T)[:, None]
    k_idx = jnp.arange(L)[None, :]
    scores = jnp.where(q_idx >= k_idx, scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    attn = jnp.einsum("bhqk,bkhd->bqhd", probs.astype(cv.dtype), cv)
    attn = attn.reshape(B, T, D)
    attn = attn @ p["attn"]["proj"]["w"].astype(h.dtype) + \
        p["attn"]["proj"]["b"].astype(h.dtype)
    x = x + attn
    h = layer_norm(x, p["ln2"], cfg.layer_norm_eps)
    h = h @ p["mlp"]["fc1"]["w"].astype(h.dtype) + \
        p["mlp"]["fc1"]["b"].astype(h.dtype)
    h = jax.nn.gelu(h, approximate=True)
    h = h @ p["mlp"]["fc2"]["w"].astype(h.dtype) + \
        p["mlp"]["fc2"]["b"].astype(h.dtype)
    return x + h, ck, cv


def _forward_cached(model: GPT, params, tokens, caches, pos):
    """tokens [B, T] at absolute position `pos` -> (last-token logits,
    updated caches)."""
    cfg = model.config
    B, T = tokens.shape
    x = params["wte"][tokens] + \
        jax.lax.dynamic_slice_in_dim(params["wpe"], pos, T, axis=0)[None]
    new_caches = []
    for bp, (ck, cv) in zip(params["blocks"], caches):
        x, ck, cv = _block_with_cache(bp, cfg, x, ck, cv, pos)
        new_caches.append((ck, cv))
    x = layer_norm(x, params["ln_f"], cfg.layer_norm_eps)
    w = (params["wte"].T if cfg.tie_embeddings else params["lm_head"])
    logits = x[:, -1, :] @ w.astype(x.dtype)
    return logits.astype(jnp.float32), new_caches


def _init_caches(model: GPT, B, L, dtype):
    cfg = model.config
    z = lambda: jnp.zeros((B, L, cfg.num_heads, cfg.head_dim), dtype)
    return [(z(), z()) for _ in range(cfg.num_layers)]


@partial(jax.jit, static_argnums=(0, 3, 5, 6, 7, 8, 9))
def _generate_jit(model, params, prompt, max_new_tokens, rng, temperature,
                  cache_len, top_k, top_p, cache_dtype=None):
    B, T = prompt.shape
    caches = _init_caches(
        model, B, cache_len,
        params["wte"].dtype if cache_dtype is None else cache_dtype)
    logits, caches = _forward_cached(model, params, prompt, caches, 0)

    flat, treedef = jax.tree_util.tree_flatten(caches)

    def sample(logits, rng):
        greedy = jnp.argmax(logits, axis=-1)
        if temperature == 0.0:
            return greedy
        logits = logits.astype(jnp.float32) / temperature
        V = logits.shape[-1]
        if top_k > 0 or top_p < 1.0:
            # ONE descending sort serves both filters (HF semantics:
            # k-truncate first, then nucleus over the renormalized
            # survivors — masking the sorted tail reproduces the sort of
            # the masked logits exactly)
            sorted_desc = jnp.sort(logits, axis=-1)[..., ::-1]
            if top_k > 0:
                k = min(top_k, V)  # clamp like HF for generous defaults
                kth = sorted_desc[..., k - 1][..., None]
                logits = jnp.where(logits < kth, -jnp.inf, logits)
                # mask the sorted copy by VALUE, not position: ties at the
                # k-th logit survive the live mask above (HF semantics),
                # so they must stay in the nucleus computation too
                sorted_desc = jnp.where(sorted_desc < kth, -jnp.inf,
                                        sorted_desc)
            if top_p < 1.0:
                # nucleus: keep the smallest set with cum prob > top_p.
                # Boundary semantics match modern HF TopPLogitsWarper, which
                # removes (ascending sort) where cumsum <= 1-top_p — i.e.
                # keep while the PREVIOUS descending cumulative is strictly
                # < top_p. Exact-boundary ties drop the marginal token.
                probs = jax.nn.softmax(sorted_desc, axis=-1)
                cum = jnp.cumsum(probs, axis=-1)
                keep = jnp.sum(cum - probs < top_p, axis=-1, keepdims=True)
                cutoff = jnp.take_along_axis(sorted_desc, keep - 1, axis=-1)
                logits = jnp.where(logits < cutoff, -jnp.inf, logits)
        return jax.random.categorical(rng, logits, axis=-1)

    def step(carry, _):
        logits, flat_caches, pos, rng = carry
        rng, sub = jax.random.split(rng)
        tok = sample(logits, sub)
        caches = jax.tree_util.tree_unflatten(treedef, flat_caches)
        logits, caches = _forward_cached(
            model, params, tok[:, None], caches, pos)
        flat_caches = jax.tree_util.tree_leaves(caches)
        return (logits, flat_caches, pos + 1, rng), tok

    (_, _, _, _), toks = jax.lax.scan(
        step, (logits, flat, jnp.asarray(T), rng),
        None, length=max_new_tokens)
    return toks.T  # [B, max_new_tokens]


def generate(model: GPT, params, prompt, max_new_tokens: int,
             temperature: float = 0.0, rng: Optional[jax.Array] = None,
             cache_len: Optional[int] = None, top_k: int = 0,
             top_p: float = 1.0, cache_dtype=None):
    """Generate continuations. prompt [B, T] int32; returns
    [B, max_new_tokens]. temperature 0 = greedy; otherwise categorical
    sampling with `rng`, optionally truncated to the top_k highest
    logits and/or the top_p nucleus (HF-style semantics: k first, then
    p). The model's dropout must be 0 (inference).  `cache_dtype`
    overrides the KV cache's storage dtype (default: the param dtype);
    a bf16 cache under fp32 params is the oracle for the serving
    engine's kv_dtype="bf16" parity pin."""
    cfg = model.config
    if cfg.num_experts > 1 or cfg.pipeline_stages > 1:
        raise NotImplementedError(
            "generate() supports plain dense GPT configs (no MoE layers, "
            "no pipeline-stacked blocks)")
    B, T = prompt.shape
    L = cache_len or min(cfg.max_seq_len, T + max_new_tokens)
    if T + max_new_tokens > cfg.max_seq_len:
        raise ValueError(f"prompt {T} + new {max_new_tokens} exceeds "
                         f"max_seq_len {cfg.max_seq_len}")
    if T + max_new_tokens > L:
        # an undersized cache would CLAMP dynamic_update_slice writes and
        # silently corrupt late tokens
        raise ValueError(f"cache_len {L} < prompt {T} + new "
                         f"{max_new_tokens}")
    if rng is None:
        rng = jax.random.PRNGKey(0)
    if top_k < 0 or not 0.0 < top_p <= 1.0:
        raise ValueError(f"top_k must be >= 0 and 0 < top_p <= 1, got "
                         f"{top_k}, {top_p}")
    if cache_dtype is not None:
        # canonicalize to a hashable np.dtype for the static argnum
        cache_dtype = jnp.zeros((), cache_dtype).dtype
    return _generate_jit(model, params, jnp.asarray(prompt),
                         int(max_new_tokens), rng, float(temperature),
                         int(L), int(top_k), float(top_p), cache_dtype)
