from .layer import MoE, MoEConfig, top_k_gating

__all__ = ["MoE", "MoEConfig", "top_k_gating"]
