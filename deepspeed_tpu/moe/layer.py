"""Mixture-of-Experts layer with expert parallelism.

BEYOND-PARITY: the reference (v0.3.15) has no MoE (SURVEY.md §2.2 "EP:
absent"); upstream DeepSpeed grew deepspeed.moe later. Built TPU-first:

* experts are STACKED on a leading dim [E, ...] and sharded over the
  `data` mesh axis (DeepSpeed-style expert parallelism: EP group == DP
  group).  On a PR-4 factored mesh with `comm.moe` inner placement the
  expert dim rides `data_inner` only (replicated across outer groups)
  so the token exchange never leaves the fast fabric.
* TWO dispatch engines selected by the process-global wire config
  (moe/dispatch.py, the `"comm": {"moe": ...}` block):
  - "dense" (default, the seed path): GShard one-hot dispatch/combine
    tensors + einsum token movement — O(N·E·C·D), exchange implicit.
  - "sorted": fused sort-based dispatch — tokens argsorted by expert
    id, capacity-bucketed via segment positions (optionally dropless
    through a second-pass overflow bucket), moved by gather/scatter
    permutes — O(N log N + k·N·D), optionally over an EXPLICIT
    quantized all-to-all wire with per-level dtypes.
  Both engines share ONE routing core (dispatch.topk_routing), so
  expert choice, gate weights and capacity drops are identical.
* load-balancing aux loss (Switch Transformer eq. 4) returned alongside
  the output for the model to add to its objective.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..comm.mesh import DATA_AXIS
from . import dispatch as _dsp


@dataclasses.dataclass
class MoEConfig:
    d_model: int
    d_ff: int
    num_experts: int
    top_k: int = 1
    capacity_factor: float = 1.25
    eval_capacity_factor: float = 2.0
    min_capacity: int = 4
    noisy_gate_std: float = 1e-2   # jitter on gate logits during training

    def __post_init__(self):
        if self.top_k > self.num_experts:
            raise ValueError(
                f"top_k ({self.top_k}) cannot exceed num_experts "
                f"({self.num_experts}): after masking every expert once, "
                f"further rounds would re-route to expert 0")


def top_k_gating(logits, k: int, capacity: int, rng=None,
                 noise_std: float = 0.0):
    """GShard top-k gating with capacity (the dense one-hot form).

    logits: [N, E] -> (combine [N, E, C] fp32, dispatch [N, E, C] bool,
    aux_loss scalar). Tokens beyond an expert's capacity are dropped
    (their combine weights are zero -> residual passthrough upstream).
    Routing (expert choice, queue positions, drops) comes from the
    shared sort-based core — positions in exact int32, not the seed's
    fp32 cumsum."""
    N, E = logits.shape
    if rng is not None and noise_std > 0.0:
        logits = logits + noise_std * jax.random.normal(rng, logits.shape)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    eidx, gate, pos, keep, aux = _dsp.topk_routing(probs, k, capacity)

    combine = jnp.zeros((N, E, capacity), jnp.float32)
    dispatch = jnp.zeros((N, E, capacity), bool)
    for r in range(k):
        onehot = jax.nn.one_hot(eidx[r], E, dtype=jnp.float32)   # [N, E]
        slot = jax.nn.one_hot(jnp.where(keep[r], pos[r], capacity),
                              capacity + 1,
                              dtype=jnp.float32)[:, :capacity]   # [N, C]
        contrib = onehot[:, :, None] * slot[:, None, :]
        combine = combine + (gate[r] * keep[r])[:, None, None] * contrib
        dispatch = jnp.logical_or(dispatch, contrib > 0)
    return combine, dispatch, aux


class MoE:
    """Functional MoE FFN: __call__(params, x, rng, train) -> (y, aux)."""

    def __init__(self, config: MoEConfig):
        self.config = config

    def init(self, rng, param_dtype=jnp.float32):
        cfg = self.config
        k1, k2, k3 = jax.random.split(rng, 3)
        d, f, E = cfg.d_model, cfg.d_ff, cfg.num_experts
        n = lambda k, s, sd: (sd * jax.random.normal(k, s)).astype(param_dtype)
        return {
            "gate": {"w": n(k1, (d, E), 0.02)},
            "experts": {
                "w1": n(k2, (E, d, f), d ** -0.5),
                "b1": jnp.zeros((E, f), param_dtype),
                "w2": n(k3, (E, f, d), f ** -0.5),
                "b2": jnp.zeros((E, d), param_dtype),
            },
        }

    @staticmethod
    def param_specs():
        """Expert-parallel: the expert dim rides the data axis.  (Under
        `comm.moe` inner placement on a factored mesh the runtime's
        sharding plan narrows the translation of this logical axis to
        `data_inner` — zero/partition.py — keeping these specs
        layout-agnostic.)"""
        return {
            "gate": {"w": P()},
            "experts": {"w1": P(DATA_AXIS, None, None),
                        "b1": P(DATA_AXIS, None),
                        "w2": P(DATA_AXIS, None, None),
                        "b2": P(DATA_AXIS, None)},
        }

    def capacity(self, tokens_per_group: int, train: bool) -> int:
        """Per-expert slot count for one token group.  CEILING division:
        the seed's int() truncation dropped tokens in small groups even
        at capacity_factor >= 1.0 (e.g. S=6, E=4, factor=1.25 -> 1.875
        truncated to 1 slot while a balanced top-1 routing needs 2)."""
        cfg = self.config
        factor = cfg.capacity_factor if train else cfg.eval_capacity_factor
        cap = int(math.ceil(factor * tokens_per_group * cfg.top_k /
                            max(cfg.num_experts, 1) - 1e-9))
        return max(cap, cfg.min_capacity)

    def __call__(self, params, x, rng=None, train=True
                 ) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """Grouped (GShard-style) dispatch: gating runs per batch row, so
        per-row buckets have C ~ S/E — memory linear in tokens (a single
        global group would make them quadratic)."""
        cfg = self.config
        wcfg = _dsp.get_wire_config()
        B, S, D = x.shape
        cap = self.capacity(S, train)
        noise = cfg.noisy_gate_std if (train and rng is not None) else 0.0
        keys = (jax.random.split(rng, B) if noise > 0.0
                else jnp.zeros((B, 2), jnp.uint32))

        if wcfg.dispatch == "sorted":
            engaged = _dsp.wire_engagement(wcfg, cfg.num_experts, B)
            if engaged is not None:
                return self._sorted_wire(params, x, keys, noise, cap,
                                         train, wcfg, *engaged)
            return self._sorted_local(params, x, keys, noise, cap,
                                      train, wcfg)
        return self._dense(params, x, keys, noise, cap, train)

    # -- shared pieces -------------------------------------------------

    def _route(self, logits, key, noise, cap):
        """Per-row routing: noisy logits -> shared sort-based core."""
        cfg = self.config
        if noise > 0.0:
            logits = logits + noise * jax.random.normal(key, logits.shape)
        probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
        return _dsp.topk_routing(probs, cfg.top_k, cap)

    def _expert_ffn(self, expert_in, params, dtype):
        """[E, B, C, D] expert compute — the SAME einsums on both
        dispatch engines, so parity reduces to the token movement."""
        w1 = params["experts"]["w1"].astype(dtype)
        b1 = params["experts"]["b1"].astype(dtype)
        w2 = params["experts"]["w2"].astype(dtype)
        b2 = params["experts"]["b2"].astype(dtype)
        h = jnp.einsum("ebcd,edf->ebcf", expert_in, w1) + \
            b1[:, None, None, :]
        h = jax.nn.gelu(h, approximate=True)
        return jnp.einsum("ebcf,efd->ebcd", h, w2) + b2[:, None, None, :]

    # -- dense one-hot engine (the seed path, byte-for-byte) -----------

    def _dense(self, params, x, keys, noise, cap, train):
        cfg = self.config
        logits = jnp.einsum("bsd,de->bse", x,
                            params["gate"]["w"].astype(x.dtype))
        combine, dispatch, aux = jax.vmap(
            lambda lg, k: top_k_gating(lg, cfg.top_k, cap,
                                       rng=k if noise > 0.0 else None,
                                       noise_std=noise))(logits, keys)
        aux = jnp.mean(aux)
        # dispatch: [B,S,E,C] x [B,S,D] -> [E,B,C,D] (all_to_all under
        # sharding: tokens sharded over data, experts sharded over data)
        expert_in = jnp.einsum("bsec,bsd->ebcd",
                               dispatch.astype(x.dtype), x)
        expert_out = self._expert_ffn(expert_in, params, x.dtype)
        # combine: [B,S,E,C] x [E,B,C,D] -> [B,S,D]
        y = jnp.einsum("bsec,ebcd->bsd", combine.astype(x.dtype), expert_out)
        return y, aux.astype(jnp.float32)

    # -- sorted (fused permute) engine, implicit exchange --------------

    def _sorted_local(self, params, x, keys, noise, cap, train, wcfg):
        cfg = self.config
        B, S, D = x.shape
        E = cfg.num_experts
        logits = jnp.einsum("bsd,de->bse", x,
                            params["gate"]["w"].astype(x.dtype))
        eidx, gate, pos, keep, aux = jax.vmap(
            lambda lg, k: self._route(lg, k, noise, cap))(logits, keys)
        aux = jnp.mean(aux)
        expert_in = jax.vmap(
            lambda xr, er, pr, kr: _dsp.sorted_dispatch(xr, er, pr, kr,
                                                        E, cap)
        )(x, eidx, pos, keep)                       # [B, E, C, D]
        expert_out = self._expert_ffn(expert_in.transpose(1, 0, 2, 3),
                                      params, x.dtype)
        out = expert_out.transpose(1, 0, 2, 3)      # [B, E, C, D]
        y = jax.vmap(_dsp.sorted_combine)(out, eidx, gate, pos, keep)

        dropped = jnp.sum(~keep)
        if wcfg.dropless:
            ov_cap = _dsp.overflow_capacity(cfg.top_k, S,
                                            wcfg.overflow_factor)
            w1 = params["experts"]["w1"].astype(x.dtype)
            b1 = params["experts"]["b1"].astype(x.dtype)
            w2 = params["experts"]["w2"].astype(x.dtype)
            b2 = params["experts"]["b2"].astype(x.dtype)

            def row_overflow(xr, er, gr, pr, kr):
                buf, ov_e, ov_keep, ov_dest = _dsp.overflow_dispatch(
                    xr, er, pr, kr, ov_cap)
                ov_out = _dsp.overflow_ffn(buf, ov_e, w1, b1, w2, b2)
                y_ov = _dsp.overflow_combine(ov_out, gr, ov_keep,
                                             ov_dest, S)
                return y_ov, jnp.sum(kr.reshape(-1) | ov_keep)

            y_ov, served = jax.vmap(row_overflow)(x, eidx, gate, pos, keep)
            y = y + y_ov
            dropped = B * cfg.top_k * S - jnp.sum(served)
        if wcfg.counters:
            _dsp.record_dispatch_stats(dropped, jnp.sum(keep),
                                       B * E * cap)
        return y, aux.astype(jnp.float32)

    # -- sorted engine over the explicit all-to-all wire ---------------

    def _sorted_wire(self, params, x, keys, noise, cap, train, wcfg,
                     mesh_info, axes):
        cfg = self.config
        B, S, D = x.shape
        E = cfg.num_experts
        dp = mesh_info.axis_size(DATA_AXIS)
        plan = _dsp.build_a2a_plan(wcfg, mesh_info, E, B // dp, cap, D)
        ep = plan.ep
        El = E // ep
        grid = tuple(mesh_info.axis_size(a) for a in axes)  # hop worlds
        data_spec = mesh_info.data_spec
        expert_spec = axes[0] if len(axes) == 1 else tuple(axes)

        gate_w = params["gate"]["w"]
        experts = params["experts"]

        def body(gw, ex, xl, keysl):
            Bl = xl.shape[0]
            logits = jnp.einsum("bsd,de->bse", xl, gw.astype(xl.dtype))
            eidx, gate, pos, keep, aux = jax.vmap(
                lambda lg, k: self._route(lg, k, noise, cap))(logits, keysl)
            expert_in = jax.vmap(
                lambda xr, er, pr, kr: _dsp.sorted_dispatch(
                    xr, er, pr, kr, E, cap))(xl, eidx, pos, keep)
            buf = expert_in.transpose(1, 0, 2, 3)       # [E, Bl, C, D]
            buf = buf.reshape(grid + (El, Bl, cap, D))
            buf = _dsp.wire_all_to_all(buf, plan, reverse=False,
                                       record=wcfg.counters)
            # leading grid dims now index SOURCE ranks, rank-major
            buf = buf.reshape(ep, El, Bl, cap, D)
            buf = buf.transpose(1, 0, 2, 3, 4).reshape(El, ep * Bl,
                                                       cap, D)
            out = self._expert_ffn(buf, {"experts": {
                k: v.astype(xl.dtype) for k, v in ex.items()}}, xl.dtype)
            out = out.reshape(El, ep, Bl, cap, D).transpose(1, 0, 2, 3, 4)
            out = out.reshape(grid + (El, Bl, cap, D))
            out = _dsp.wire_all_to_all(out, plan, reverse=True,
                                       record=wcfg.counters)
            out = out.reshape(E, Bl, cap, D).transpose(1, 0, 2, 3)
            y = jax.vmap(_dsp.sorted_combine)(out, eidx, gate, pos, keep)
            if wcfg.counters:
                _dsp.record_dispatch_stats(jnp.sum(~keep), jnp.sum(keep),
                                           Bl * E * cap)
            return y, aux

        expert_in_specs = {"w1": P(expert_spec, None, None),
                           "b1": P(expert_spec, None),
                           "w2": P(expert_spec, None, None),
                           "b2": P(expert_spec, None)}
        axis_names = set(mesh_info.data_axes)
        smapped = jax.shard_map(
            body, mesh=mesh_info.mesh,
            in_specs=(P(), expert_in_specs, P(data_spec, None, None),
                      P(data_spec, None)),
            out_specs=(P(data_spec, None, None), P(data_spec)),
            axis_names=axis_names, check_vma=False)
        y, aux = smapped(gate_w, experts, x, keys)
        return y, jnp.mean(aux).astype(jnp.float32)
