"""Mixture-of-Experts layer with expert parallelism.

BEYOND-PARITY: the reference (v0.3.15) has no MoE (SURVEY.md §2.2 "EP:
absent"); upstream DeepSpeed grew deepspeed.moe later. Built TPU-first:

* experts are STACKED on a leading dim [E, ...] and sharded over the
  `data` mesh axis (DeepSpeed-style expert parallelism: EP group == DP
  group). Tokens are sharded over `data` too, so the dispatch einsum's
  contraction makes XLA insert the all_to_all that MPI/NCCL MoE stacks
  hand-write.
* GShard/Switch dense dispatch: top-k gating with capacity, one-hot
  dispatch/combine tensors, einsum expert compute — static shapes, MXU
  batched matmuls, no data-dependent control flow.
* load-balancing aux loss (Switch Transformer eq. 4) returned alongside
  the output for the model to add to its objective.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..comm.mesh import DATA_AXIS


@dataclasses.dataclass
class MoEConfig:
    d_model: int
    d_ff: int
    num_experts: int
    top_k: int = 1
    capacity_factor: float = 1.25
    eval_capacity_factor: float = 2.0
    min_capacity: int = 4
    noisy_gate_std: float = 1e-2   # jitter on gate logits during training

    def __post_init__(self):
        if self.top_k > self.num_experts:
            raise ValueError(
                f"top_k ({self.top_k}) cannot exceed num_experts "
                f"({self.num_experts}): after masking every expert once, "
                f"further rounds would re-route to expert 0")


def top_k_gating(logits, k: int, capacity: int, rng=None,
                 noise_std: float = 0.0):
    """GShard top-k gating with capacity.

    logits: [N, E] -> (combine [N, E, C] fp32, dispatch [N, E, C] bool,
    aux_loss scalar). Tokens beyond an expert's capacity are dropped
    (their combine weights are zero -> residual passthrough upstream).
    """
    N, E = logits.shape
    if rng is not None and noise_std > 0.0:
        logits = logits + noise_std * jax.random.normal(rng, logits.shape)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)

    combine = jnp.zeros((N, E, capacity), jnp.float32)
    dispatch = jnp.zeros((N, E, capacity), bool)
    masked = probs
    # fill per-expert slots k rounds in priority order; counts carry over
    base_counts = jnp.zeros((E,), jnp.int32)
    aux_frac = jnp.zeros((), jnp.float32)
    for _ in range(k):
        idx = jnp.argmax(masked, axis=-1)                     # [N]
        onehot = jax.nn.one_hot(idx, E, dtype=jnp.float32)    # [N, E]
        # position of each token within its chosen expert's queue
        pos_in_e = (jnp.cumsum(onehot, axis=0) - 1.0) * onehot  # [N, E]
        pos = (pos_in_e.sum(-1) + base_counts[idx]).astype(jnp.int32)  # [N]
        keep = pos < capacity
        gate = jnp.take_along_axis(probs, idx[:, None], 1)[:, 0] * keep
        slot = jax.nn.one_hot(jnp.where(keep, pos, capacity), capacity + 1,
                              dtype=jnp.float32)[:, :capacity]  # [N, C]
        contrib = onehot[:, :, None] * slot[:, None, :]
        combine = combine + gate[:, None, None] * contrib
        dispatch = jnp.logical_or(dispatch, contrib > 0)
        base_counts = base_counts + onehot.sum(0).astype(jnp.int32)
        aux_frac = aux_frac + jnp.mean(onehot, axis=0).dot(
            jnp.mean(probs, axis=0)) * E
        masked = masked * (1.0 - onehot)  # next round picks a new expert
    aux_loss = aux_frac / k
    return combine, dispatch, aux_loss


class MoE:
    """Functional MoE FFN: __call__(params, x, rng, train) -> (y, aux)."""

    def __init__(self, config: MoEConfig):
        self.config = config

    def init(self, rng, param_dtype=jnp.float32):
        cfg = self.config
        k1, k2, k3 = jax.random.split(rng, 3)
        d, f, E = cfg.d_model, cfg.d_ff, cfg.num_experts
        n = lambda k, s, sd: (sd * jax.random.normal(k, s)).astype(param_dtype)
        return {
            "gate": {"w": n(k1, (d, E), 0.02)},
            "experts": {
                "w1": n(k2, (E, d, f), d ** -0.5),
                "b1": jnp.zeros((E, f), param_dtype),
                "w2": n(k3, (E, f, d), f ** -0.5),
                "b2": jnp.zeros((E, d), param_dtype),
            },
        }

    @staticmethod
    def param_specs():
        """Expert-parallel: the expert dim rides the data axis."""
        return {
            "gate": {"w": P()},
            "experts": {"w1": P(DATA_AXIS, None, None),
                        "b1": P(DATA_AXIS, None),
                        "w2": P(DATA_AXIS, None, None),
                        "b2": P(DATA_AXIS, None)},
        }

    def capacity(self, tokens_per_group: int, train: bool) -> int:
        cfg = self.config
        factor = cfg.capacity_factor if train else cfg.eval_capacity_factor
        cap = int(factor * tokens_per_group * cfg.top_k /
                  max(cfg.num_experts, 1))
        return max(cap, cfg.min_capacity)

    def __call__(self, params, x, rng=None, train=True
                 ) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """Grouped (GShard-style) dispatch: gating runs per batch row, so
        dispatch/combine are [B, S, E, C] with C ~ S/E — memory linear in
        tokens (a single global group would make them quadratic)."""
        cfg = self.config
        B, S, D = x.shape
        logits = jnp.einsum("bsd,de->bse", x,
                            params["gate"]["w"].astype(x.dtype))
        cap = self.capacity(S, train)
        noise = cfg.noisy_gate_std if (train and rng is not None) else 0.0
        keys = (jax.random.split(rng, B) if noise > 0.0
                else jnp.zeros((B, 2), jnp.uint32))
        combine, dispatch, aux = jax.vmap(
            lambda lg, k: top_k_gating(lg, cfg.top_k, cap,
                                       rng=k if noise > 0.0 else None,
                                       noise_std=noise))(logits, keys)
        aux = jnp.mean(aux)

        w1 = params["experts"]["w1"].astype(x.dtype)
        b1 = params["experts"]["b1"].astype(x.dtype)
        w2 = params["experts"]["w2"].astype(x.dtype)
        b2 = params["experts"]["b2"].astype(x.dtype)
        # dispatch: [B,S,E,C] x [B,S,D] -> [E,B,C,D] (all_to_all under
        # sharding: tokens sharded over data, experts sharded over data)
        expert_in = jnp.einsum("bsec,bsd->ebcd",
                               dispatch.astype(x.dtype), x)
        h = jnp.einsum("ebcd,edf->ebcf", expert_in, w1) + \
            b1[:, None, None, :]
        h = jax.nn.gelu(h, approximate=True)
        expert_out = jnp.einsum("ebcf,efd->ebcd", h, w2) + \
            b2[:, None, None, :]
        # combine: [B,S,E,C] x [E,B,C,D] -> [B,S,D]
        y = jnp.einsum("bsec,ebcd->bsd", combine.astype(x.dtype), expert_out)
        return y, aux.astype(jnp.float32)
