"""Fused sort-based MoE dispatch/combine + the explicit expert
all-to-all wire.

The seed-era MoE path (layer.py) routes with GShard's dense one-hot
machinery: dispatch/combine are materialized [N, E, C] tensors and both
token movements are O(N·E·C·D) einsums — for what is fundamentally a
PERMUTATION.  This module rebuilds token movement as explicit,
instrumented, compressible data flow:

* `topk_routing` — the single routing core BOTH dispatch paths share:
  iterative top-k expert selection (GShard priority order), then queue
  positions from ONE stable argsort over the round-major assignment
  list.  Positions are exact INT32 throughout (the seed computed them
  via an fp32 `cumsum(onehot)`, which silently loses integer exactness
  past 2^24 tokens); the sort rank within an expert's segment equals
  the seed's round-carrying cumsum by construction, so the dense and
  sorted paths route IDENTICALLY.
* `sorted_dispatch` / `sorted_combine` — gather tokens into [E, C, D]
  expert buckets through one scatter-add (capacity-overflowing
  assignments land on a reserved trash slot, serving/kv_cache style:
  branch-free, static shapes) and scatter-combine back with the gate
  weights: O(N log N + k·N·D) instead of O(N·E·C·D).
* dropless mode — a second-pass SHARED overflow bucket: assignments
  past an expert's capacity take rank-ordered slots in one [O, D]
  bucket processed with per-row gathered expert weights, so capacity
  overflow degrades into a small dense matmul instead of dropped
  tokens (exactly-once accounting pinned in tests).  The bucket is
  static-shaped; assignments past BOTH buckets still drop (counted).
* the explicit expert a2a wire — a `shard_map`-level `lax.all_to_all`
  with its own per-level wire dtypes (`fp32`/`bf16`/`int8`/`int4`,
  the int wires riding runtime/comm/quant.py's blockwise kernels with
  payload+scales fused into ONE uint8 buffer per chunk), hierarchy
  aware two ways on a PR-4 factored mesh: `placement` "inner" keeps
  experts on `data_inner` (replicated across outer groups) so the
  whole exchange stays on the fast fabric, while placement "data"
  decomposes the global a2a into an inner hop + an outer hop so the
  slow hop can compress independently.  The backward wire mirrors the
  forward through a custom_vjp (cotangents ride the same quantized
  a2a — the qgZ straight-through convention; fp32 stays the exact
  transpose).
* counters — `moe.a2a_bytes` / `moe.a2a_inter` / `moe.dropped_tokens`
  / `moe.capacity_frac` recorded per EXECUTION via async
  `jax.debug.callback` (never at trace time, so AOT lowering and flops
  analysis can't bump them), pinned byte-exact against `a2a_plan` in
  tier-1.  Counting is per LOCAL mesh rank: on the 8-device virtual
  test mesh one dispatch fires 8 callbacks — the counter totals the
  local fabric traffic, mirroring how a real per-process deployment
  sums its local devices.

Accuracy contract vs the dense path: routing (expert choice, gate
weights, capacity drops) is IDENTICAL by construction.  The combined
output differs only by floating-point reduction order: exact for
top_k <= 2 (a two-term sum is commutative) and per-token tolerance for
k > 2; the quantized wires add one quantization error per hop
(documented in docs/tutorials/moe.md).
"""

from __future__ import annotations

import contextlib
import dataclasses
import functools
import math
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..comm.mesh import (DATA_AXIS, DATA_INNER_AXIS, DATA_OUTER_AXIS,
                         MODEL_AXIS, PIPE_AXIS, SEQ_AXIS, MeshInfo,
                         peek_mesh)
from ..monitor.counters import COUNTERS
from ..runtime.comm.quant import (DEFAULT_BLOCK_SIZE, dequantize_blockwise,
                                  pack_wire, payload_bytes, quantize_blockwise,
                                  unpack_wire, validate_block_size)
from ..utils.logging import logger

DISPATCH_MODES = ("dense", "sorted")
A2A_WIRES = ("fp32", "bf16", "int8", "int4")
PLACEMENT_MODES = ("auto", "data", "inner")
OVERLAP_MODES = ("none", "auto", "on")

_WIRE_ITEMSIZE = {"fp32": 4, "bf16": 2}


# ---------------------------------------------------------------------------
# wire configuration (the validated `comm.moe` block)
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class MoEWireConfig:
    """Process-global MoE token-movement selection.

    The default-constructed config is EXACTLY the seed behaviour: dense
    one-hot dispatch, token exchange left implicit to XLA, no counters.
    The engine installs a parsed config at initialize() from the
    `"comm": {"moe": {...}}` block; direct layer users select modes
    with the `moe_wire(...)` context manager."""

    dispatch: str = "dense"            # "dense" | "sorted"
    a2a_wire_dtype: Optional[str] = None   # None -> implicit XLA a2a
    a2a_wire_dtype_inner: Optional[str] = None  # default: a2a_wire_dtype
    a2a_wire_dtype_outer: Optional[str] = None
    placement: str = "auto"            # "auto" | "data" | "inner"
    dropless: bool = False
    overflow_factor: float = 0.25      # overflow bucket = ceil(f * k * N)
    quant_block_size: int = DEFAULT_BLOCK_SIZE
    overlap: str = "none"
    counters: bool = True

    @property
    def explicit(self) -> bool:
        # a per-level override alone also selects the explicit wire
        # (parse_moe_config normalizes the base to fp32; direct
        # constructor users get the same semantics)
        return (self.a2a_wire_dtype is not None
                or self.a2a_wire_dtype_inner is not None
                or self.a2a_wire_dtype_outer is not None)

    def wire_inner(self) -> str:
        return self.a2a_wire_dtype_inner or self.a2a_wire_dtype or "fp32"

    def wire_outer(self) -> str:
        return self.a2a_wire_dtype_outer or self.a2a_wire_dtype or "fp32"

    def describe(self) -> str:
        if not self.explicit:
            return (f"moe wire: dispatch={self.dispatch}, a2a=implicit "
                    f"(XLA), dropless={self.dropless}")
        return (f"moe wire: dispatch={self.dispatch}, a2a=explicit "
                f"inner={self.wire_inner()} outer={self.wire_outer()} "
                f"placement={self.placement} block={self.quant_block_size}")


def parse_moe_config(d, default_block: int = DEFAULT_BLOCK_SIZE
                     ) -> MoEWireConfig:
    """Validate the `comm.moe` dict -> MoEWireConfig.  Every invalid or
    inherited-invalid combination is rejected HERE, naming the key and
    the valid set — never left to fail inside a traced step program."""
    d = d or {}
    if not isinstance(d, dict):
        raise ValueError(
            f"comm.moe must be an object, got {type(d).__name__}")
    known = {"dispatch", "a2a_wire_dtype", "a2a_wire_dtype_inner",
             "a2a_wire_dtype_outer", "placement", "dropless",
             "overflow_factor", "quant_block_size", "overlap", "counters"}
    unknown = set(d) - known
    if unknown:
        raise ValueError(
            f"comm.moe: unknown key(s) {sorted(unknown)}; expected a "
            f"subset of {sorted(known)}")

    def wire_param(key):
        w = d.get(key)
        if w is None:
            return None
        w = str(w).lower()
        if w not in A2A_WIRES:
            extra = ""
            if w == "split":
                extra = (" (the 24-bit frexp split wire carries two "
                         "sidebands and has no all-to-all lowering; the "
                         "fused int8/int4 blockwise wires are the "
                         "compressed a2a options)")
            raise ValueError(
                f"comm.moe.{key} must be one of {A2A_WIRES}, "
                f"got {w!r}{extra}")
        return w

    base = wire_param("a2a_wire_dtype")
    inner = wire_param("a2a_wire_dtype_inner")
    outer = wire_param("a2a_wire_dtype_outer")
    if base is None and (inner is not None or outer is not None):
        # per-level overrides imply the explicit wire; the unnamed level
        # stays exact
        base = "fp32"

    # dispatch default: the seed's dense path — EXCEPT when an explicit
    # a2a wire is requested, which only the sorted engine can feed
    dispatch = str(d.get("dispatch",
                         "sorted" if base is not None else "dense")).lower()
    if dispatch not in DISPATCH_MODES:
        raise ValueError(
            f"comm.moe.dispatch must be one of {DISPATCH_MODES}, "
            f"got {dispatch!r}")
    if base is not None and "dispatch" in d and dispatch != "sorted":
        raise ValueError(
            "comm.moe.a2a_wire_dtype requires comm.moe.dispatch='sorted': "
            "the explicit all-to-all wire moves sort-dispatched [E, C, D] "
            "expert buckets; the dense one-hot path leaves the exchange "
            f"to XLA (got dispatch={dispatch!r}; valid: ('sorted',))")

    placement = str(d.get("placement", "auto")).lower()
    if placement not in PLACEMENT_MODES:
        raise ValueError(
            f"comm.moe.placement must be one of {PLACEMENT_MODES}, "
            f"got {placement!r}")
    if placement != "auto" and base is None:
        raise ValueError(
            f"comm.moe.placement={placement!r} only applies to the "
            "explicit a2a wire; set comm.moe.a2a_wire_dtype (valid: "
            f"{A2A_WIRES}) or leave placement 'auto'")

    dropless = d.get("dropless", False)
    if not isinstance(dropless, bool):
        raise ValueError(
            f"comm.moe.dropless must be a bool, got {dropless!r}")
    if dropless and dispatch != "sorted":
        raise ValueError(
            "comm.moe.dropless requires comm.moe.dispatch='sorted' (the "
            "overflow bucket is a second sort-dispatch pass; the dense "
            "one-hot path has no overflow machinery)")
    if dropless and base is not None:
        raise ValueError(
            "comm.moe.dropless cannot ride the explicit a2a wire: the "
            "shared overflow bucket holds tokens for ARBITRARY experts, "
            "which an expert-sharded all-to-all cannot route; use "
            "dropless with the implicit exchange, or size capacity_factor "
            "for the wire (valid: dropless with a2a_wire_dtype null)")

    of = d.get("overflow_factor", 0.25)
    if isinstance(of, bool) or not isinstance(of, (int, float)) or of <= 0:
        raise ValueError(
            f"comm.moe.overflow_factor must be a number > 0, got {of!r}")

    overlap = d.get("overlap", "none")
    if isinstance(overlap, bool):
        overlap = "on" if overlap else "none"
    overlap = str(overlap).lower()
    if overlap not in OVERLAP_MODES:
        raise ValueError(
            f"comm.moe.overlap must be one of {OVERLAP_MODES} (or a "
            f"bool), got {d.get('overlap')!r}")

    block = d.get("quant_block_size", default_block)
    try:
        block = validate_block_size(block)
    except ValueError as e:
        raise ValueError(f"comm.moe.quant_block_size: {e}")

    counters = d.get("counters", True)
    if not isinstance(counters, bool):
        raise ValueError(
            f"comm.moe.counters must be a bool, got {counters!r}")

    return MoEWireConfig(
        dispatch=dispatch, a2a_wire_dtype=base,
        a2a_wire_dtype_inner=inner, a2a_wire_dtype_outer=outer,
        placement=placement, dropless=dropless,
        overflow_factor=float(of), quant_block_size=block,
        overlap=overlap, counters=bool(counters))


_WIRE_CONFIG = MoEWireConfig()


def get_wire_config() -> MoEWireConfig:
    return _WIRE_CONFIG


def set_wire_config(cfg: MoEWireConfig) -> MoEWireConfig:
    """Install `cfg` process-globally; returns the previous config."""
    global _WIRE_CONFIG
    prev = _WIRE_CONFIG
    _WIRE_CONFIG = cfg
    if cfg != prev:
        logger.debug(cfg.describe())
    return prev


@contextlib.contextmanager
def moe_wire(cfg: Optional[MoEWireConfig] = None, **kwargs):
    """Scoped wire config for direct layer users / tests:
    `with moe_wire(dispatch="sorted", a2a_wire_dtype="int8"): ...`"""
    prev = set_wire_config(cfg if cfg is not None
                           else MoEWireConfig(**kwargs))
    try:
        yield get_wire_config()
    finally:
        set_wire_config(prev)


# ---------------------------------------------------------------------------
# routing core (shared by the dense one-hot and sorted paths)
# ---------------------------------------------------------------------------

def topk_routing(probs, k: int, capacity: int):
    """GShard top-k routing for one token group.

    probs [N, E] fp32 -> (eidx, gate, pos, keep) all [k, N] round-major
    + aux scalar.  Expert selection is the seed's iterative
    argmax-and-mask (round r picks each token's r-th expert); queue
    positions come from ONE stable argsort of the round-major assignment
    list, whose within-segment rank equals the seed's round-carrying
    `cumsum(onehot) + base_counts` — in exact int32, with no fp32
    integer ceiling.  `keep` marks assignments inside `capacity`."""
    N, E = probs.shape
    masked = probs
    eidxs, gates = [], []
    aux = jnp.zeros((), jnp.float32)
    for _ in range(k):
        idx = jnp.argmax(masked, axis=-1).astype(jnp.int32)   # [N]
        onehot = jax.nn.one_hot(idx, E, dtype=jnp.float32)    # [N, E]
        gates.append(jnp.take_along_axis(probs, idx[:, None], 1)[:, 0])
        eidxs.append(idx)
        aux = aux + jnp.mean(onehot, axis=0).dot(
            jnp.mean(probs, axis=0)) * E
        masked = masked * (1.0 - onehot)  # next round picks a new expert
    eidx = jnp.stack(eidxs)   # [k, N]
    gate = jnp.stack(gates)   # [k, N] fp32 (pre-capacity)

    # queue positions: stable sort by expert id over the [k*N]
    # round-major assignments; rank within the expert's segment is the
    # per-round arrival order with earlier rounds queued first —
    # exactly GShard's priority discipline
    e_flat = eidx.reshape(-1)                              # [kN]
    order = jnp.argsort(e_flat, stable=True)
    counts = jnp.bincount(e_flat, length=E).astype(jnp.int32)
    starts = jnp.cumsum(counts) - counts                   # [E]
    rank = (jnp.arange(k * N, dtype=jnp.int32)
            - starts[e_flat[order]].astype(jnp.int32))
    pos = jnp.zeros((k * N,), jnp.int32).at[order].set(rank)
    pos = pos.reshape(k, N)
    keep = pos < capacity
    return eidx, gate, pos, keep, aux / k


# ---------------------------------------------------------------------------
# sorted dispatch / combine (one token group; vmapped over batch rows)
# ---------------------------------------------------------------------------

def _assignment_tokens(k: int, N: int):
    """Token index of round-major assignment a = r*N + n."""
    return jnp.tile(jnp.arange(N, dtype=jnp.int32), k)


def sorted_dispatch(x, eidx, pos, keep, num_experts: int, capacity: int):
    """Registry-dispatching entry (kernels/registry.py): the Pallas
    gather kernel when probing selects it (bit-exact), otherwise
    `sorted_dispatch_ref` below.  Same shapes/contract either way."""
    from ..kernels import registry

    return registry.dispatch(
        "moe_dispatch", x, eidx, pos, keep, num_experts, capacity,
        variant="dispatch", info={"model_dim": x.shape[-1]})


def sorted_combine(expert_out, eidx, gate, pos, keep):
    """Registry-dispatching entry; see `sorted_combine_ref`."""
    from ..kernels import registry

    return registry.dispatch(
        "moe_dispatch", expert_out, eidx, gate, pos, keep,
        variant="combine", info={"model_dim": expert_out.shape[-1]})


def sorted_dispatch_ref(x, eidx, pos, keep, num_experts: int,
                        capacity: int):
    """x [N, D] + routing [k, N] -> expert inputs [E, C, D].

    One gather of the selected token rows + one scatter-add into the
    flattened [E*C (+1 trash), D] bucket buffer; kept destinations are
    unique by construction, dropped assignments land on the trash row
    (sliced off), so the program is branch-free with static shapes."""
    k, N = eidx.shape
    D = x.shape[-1]
    E, C = num_experts, capacity
    flat_keep = keep.reshape(-1)
    dest = jnp.where(flat_keep,
                     eidx.reshape(-1) * C + pos.reshape(-1),
                     E * C)                                   # trash slot
    vals = x[_assignment_tokens(k, N)]                        # [kN, D]
    vals = vals * flat_keep[:, None].astype(x.dtype)
    buf = jnp.zeros((E * C + 1, D), x.dtype).at[dest].add(vals)
    return buf[:E * C].reshape(E, C, D)


def sorted_combine_ref(expert_out, eidx, gate, pos, keep):
    """expert outputs [E, C, D] + routing -> y [N, D].

    Gathers each kept assignment's slot and sums the k rounds' gated
    contributions per token (a k=1/2 sum is order-exact vs the dense
    einsum; k>2 differs only by fp reduction order)."""
    E, C, D = expert_out.shape
    k, N = eidx.shape
    flat = jnp.concatenate(
        [expert_out.reshape(E * C, D),
         jnp.zeros((1, D), expert_out.dtype)])
    src = jnp.where(keep.reshape(-1),
                    eidx.reshape(-1) * C + pos.reshape(-1), E * C)
    picked = flat[src].reshape(k, N, D)
    w = (gate * keep).astype(expert_out.dtype)                # [k, N]
    return jnp.sum(picked * w[:, :, None], axis=0)


def overflow_capacity(k: int, tokens: int, factor: float) -> int:
    """Static size of the dropless shared overflow bucket for one token
    group: ceil(factor * k * tokens), factor 1.0 = guaranteed dropless
    (the bucket can hold every assignment)."""
    return max(1, int(math.ceil(factor * k * tokens - 1e-9)))


def overflow_dispatch(x, eidx, pos, keep, ov_cap: int):
    """Second-pass dropless bucket: assignments past their expert's
    capacity take rank-ordered slots in ONE shared [O, D] bucket.
    Returns (bucket [O, D], bucket expert ids [O], ov_keep [k*N],
    ov_dest [k*N])."""
    k, N = eidx.shape
    D = x.shape[-1]
    ov_mask = ~keep.reshape(-1)                               # [kN]
    ov_rank = jnp.cumsum(ov_mask.astype(jnp.int32)) - 1
    ov_keep = ov_mask & (ov_rank < ov_cap)
    dest = jnp.where(ov_keep, ov_rank, ov_cap)
    vals = x[_assignment_tokens(k, N)]
    vals = vals * ov_keep[:, None].astype(x.dtype)
    buf = jnp.zeros((ov_cap + 1, D), x.dtype).at[dest].add(vals)
    e_buf = jnp.zeros((ov_cap + 1,), jnp.int32).at[dest].add(
        jnp.where(ov_keep, eidx.reshape(-1), 0))
    return buf[:ov_cap], e_buf[:ov_cap], ov_keep, dest


def overflow_ffn(xov, ov_e, w1, b1, w2, b2):
    """Expert FFN over the shared overflow bucket: each row selects its
    expert's weights through a one-hot contraction (cost O·E·d·f — the
    bucket is small, sized by overflow_factor)."""
    E = w1.shape[0]
    onehot = jax.nn.one_hot(ov_e, E, dtype=xov.dtype)         # [O, E]
    h = jnp.einsum("od,edf,oe->of", xov, w1, onehot) + b1[ov_e]
    h = jax.nn.gelu(h, approximate=True)
    return jnp.einsum("of,efd,oe->od", h, w2, onehot) + b2[ov_e]


def overflow_combine(ov_out, gate, ov_keep, ov_dest, N: int):
    """Gather overflow-bucket outputs back to tokens, gated like the
    primary combine (only overflow-kept assignments contribute; the
    primary bucket's keeps already combined through sorted_combine)."""
    O, D = ov_out.shape
    k = gate.shape[0]
    flat = jnp.concatenate([ov_out, jnp.zeros((1, D), ov_out.dtype)])
    picked = flat[jnp.where(ov_keep, ov_dest, O)].reshape(k, N, D)
    w = (gate * ov_keep.reshape(k, N)).astype(ov_out.dtype)
    return jnp.sum(picked * w[:, :, None], axis=0)


# ---------------------------------------------------------------------------
# counters (async debug callbacks: per-execution, per local mesh rank)
# ---------------------------------------------------------------------------

def _bump_a2a(nbytes: int, inter: bool) -> None:
    COUNTERS.add("moe.a2a_bytes", nbytes)
    if inter:
        COUNTERS.add("moe.a2a_inter", nbytes)


def _bump_stats(dropped, used, total_slots: int) -> None:
    COUNTERS.add("moe.dropped_tokens", int(dropped))
    # ppm-in-bytes convention: mean utilisation % = bytes / calls / 1e4
    COUNTERS.add("moe.capacity_frac",
                 int(round(1e6 * float(used) / max(total_slots, 1))))


def record_dispatch_stats(dropped, used, total_slots: int) -> None:
    """Emit the data-dependent routing stats from inside a traced
    program (async callback; fires per execution, never per trace)."""
    jax.debug.callback(
        functools.partial(_bump_stats, total_slots=int(total_slots)),
        dropped, used)


# ---------------------------------------------------------------------------
# the explicit expert all-to-all wire
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class A2AHop:
    axis: str        # mesh axis name
    dim: int         # which leading buffer dim this hop exchanges
    world: int
    wire: str        # fp32 | bf16 | int8 | int4
    inter: bool      # True = slow-fabric (data_outer) hop


@dataclasses.dataclass(frozen=True)
class A2APlan:
    """Static description of one MoE layer's expert exchange on this
    mesh: the hop sequence (fast->slow on dispatch) plus EXACT per-hop
    wire bytes for one shard-level traversal — the number the
    `moe.a2a_bytes` counter is pinned against byte-for-byte."""
    hops: Tuple[A2AHop, ...]
    ep: int                  # expert-parallel width (product of worlds)
    local_elems: int         # buffer elements per shard (constant per hop)
    quant_block: int

    def hop_bytes(self, hop: A2AHop) -> int:
        if hop.wire in _WIRE_ITEMSIZE:
            return self.local_elems * _WIRE_ITEMSIZE[hop.wire]
        chunk = self.local_elems // hop.world
        return hop.world * payload_bytes(chunk, hop.wire, self.quant_block)

    @property
    def bytes_per_traversal(self) -> int:
        """Wire bytes one shard moves in ONE direction (dispatch or
        combine).  A training dispatch runs 4 traversals (forward
        dispatch+combine and their mirrored backward); eval runs 2."""
        return sum(self.hop_bytes(h) for h in self.hops)

    @property
    def inter_bytes_per_traversal(self) -> int:
        return sum(self.hop_bytes(h) for h in self.hops if h.inter)

    @property
    def hops_per_traversal(self) -> int:
        return len(self.hops)

    def describe(self) -> str:
        legs = ", ".join(
            f"{h.axis}[{h.world}]={h.wire}"
            f"{' (slow)' if h.inter else ''}" for h in self.hops)
        return (f"moe a2a: ep={self.ep}, {legs}, "
                f"{self.bytes_per_traversal} B/traversal/shard")


def resolve_placement(wcfg: MoEWireConfig, mesh_info: MeshInfo) -> str:
    """"inner" keeps experts on data_inner (exchange never leaves the
    fast fabric) whenever the factored mesh is active; flat meshes and
    placement="data" use the full data group."""
    if wcfg.placement == "inner":
        if not mesh_info.hierarchical:
            return "data"  # no inner axis to pin to; logged by caller
        return "inner"
    if wcfg.placement == "data":
        return "data"
    return "inner" if mesh_info.hierarchical else "data"


def expert_axes(wcfg: MoEWireConfig, mesh_info: MeshInfo
                ) -> Tuple[str, ...]:
    """Mesh axis names the expert dim is sharded over under the
    explicit wire (= the a2a hop axes, outermost first)."""
    if resolve_placement(wcfg, mesh_info) == "inner":
        return (DATA_INNER_AXIS,)
    return mesh_info.data_axes


def build_a2a_plan(wcfg: MoEWireConfig, mesh_info: MeshInfo,
                   num_experts: int, local_rows: int, capacity: int,
                   d_model: int) -> A2APlan:
    """The static wire plan for one MoE layer's exchange: `local_rows`
    is this shard's batch-row count (B / dp), buffer elements are
    E * local_rows * C * D and stay constant across hops (an a2a
    permutes, never grows)."""
    axes = expert_axes(wcfg, mesh_info)
    local_elems = num_experts * local_rows * capacity * d_model
    hops = []
    if len(axes) == 1:
        wire = (wcfg.wire_inner() if axes[0] == DATA_INNER_AXIS
                else wcfg.wire_outer() if axes[0] == DATA_OUTER_AXIS
                else (wcfg.a2a_wire_dtype or "fp32"))
        hops.append(A2AHop(axis=axes[0], dim=0,
                           world=mesh_info.axis_size(axes[0]), wire=wire,
                           inter=axes[0] == DATA_OUTER_AXIS))
    else:
        # dispatch runs fast hop first (regroup locally, then one
        # aggregated slow exchange — the hierarchical a2a decomposition)
        outer_ax, inner_ax = axes
        hops.append(A2AHop(axis=inner_ax, dim=1,
                           world=mesh_info.axis_size(inner_ax),
                           wire=wcfg.wire_inner(), inter=False))
        hops.append(A2AHop(axis=outer_ax, dim=0,
                           world=mesh_info.axis_size(outer_ax),
                           wire=wcfg.wire_outer(), inter=True))
    ep = 1
    for a in axes:
        ep *= mesh_info.axis_size(a)
    return A2APlan(hops=tuple(hops), ep=ep, local_elems=local_elems,
                   quant_block=wcfg.quant_block_size)


def _hop_a2a(buf, hop: A2AHop, plan: A2APlan, record: bool):
    """One all-to-all hop on `buf` (leading dims = hop grid).  The int
    wires quantize per DESTINATION CHUNK so each received chunk carries
    its own blockwise fp16 scales, fused with the payload into one
    uint8 buffer per chunk — 1 collective per hop, like the qgZ wire."""
    if record:
        jax.debug.callback(functools.partial(
            _bump_a2a, nbytes=plan.hop_bytes(hop), inter=hop.inter))
    if hop.wire == "fp32":
        return jax.lax.all_to_all(buf.astype(jnp.float32), hop.axis,
                                  hop.dim, hop.dim,
                                  tiled=True).astype(buf.dtype)
    if hop.wire == "bf16":
        return jax.lax.all_to_all(buf.astype(jnp.bfloat16), hop.axis,
                                  hop.dim, hop.dim,
                                  tiled=True).astype(buf.dtype)
    # int8/int4: moveaxis the hop dim out front, flatten chunks,
    # quantize+pack per chunk, exchange the fused uint8 buffer,
    # unpack+dequantize per source chunk
    shape = buf.shape
    chunks = jnp.moveaxis(buf, hop.dim, 0).reshape(hop.world, -1)
    chunk_elems = chunks.shape[1]

    def enc(c):
        payload, scales = quantize_blockwise(c, plan.quant_block, hop.wire)
        return pack_wire(payload, scales)

    wire_buf = jax.vmap(enc)(chunks.astype(jnp.float32))
    wire_buf = jax.lax.all_to_all(wire_buf, hop.axis, 0, 0, tiled=True)

    def dec(c):
        p, s = unpack_wire(c, hop.wire, plan.quant_block, chunk_elems)
        return dequantize_blockwise(p, s, hop.wire, chunk_elems)

    out = jax.vmap(dec)(wire_buf).astype(buf.dtype)
    moved = tuple(shape[hop.dim:hop.dim + 1]
                  + shape[:hop.dim] + shape[hop.dim + 1:])
    return jnp.moveaxis(out.reshape(moved), 0, hop.dim)


def wire_all_to_all(buf, plan: A2APlan, reverse: bool, record: bool):
    """The full (possibly two-hop) exchange with a mirrored backward:
    the custom_vjp routes cotangents through the SAME per-hop wire
    dtypes in the opposite direction — quantized wires use the qgZ
    straight-through convention (each hop's quantization error applies
    once per crossing, never accumulated in the narrow domain), fp32 is
    the exact transpose.  `buf` leading dims must be the hop grid
    ([outer, inner, ...] hierarchical, [ep, ...] flat)."""
    hops = tuple(reversed(plan.hops)) if reverse else plan.hops

    def run(x, hop_seq):
        for hop in hop_seq:
            x = _hop_a2a(x, hop, plan, record)
        return x

    @jax.custom_vjp
    def xchg(x):
        return run(x, hops)

    def fwd(x):
        return run(x, hops), None

    def bwd(_, g):
        # an a2a hop is involutive on its own dim; reversing the hop
        # ORDER routes the cotangent back along the same fabric legs
        return (run(g, tuple(reversed(hops))),)

    xchg.defvjp(fwd, bwd)
    return xchg(buf)


# ---------------------------------------------------------------------------
# engagement checks
# ---------------------------------------------------------------------------

_warned: set = set()


def _warn_once(key: str, msg: str) -> None:
    if key not in _warned:
        _warned.add(key)
        logger.warning(msg)


def _inside_manual_region(axes: Sequence[str]) -> bool:
    """True when a data mesh axis is already bound (we are being traced
    inside another shard_map, e.g. the bucketed gradient wire's local-
    grads region, which passes params REPLICATED — local dispatch is
    then the correct lowering)."""
    for a in axes:
        try:
            jax.lax.axis_index(a)
            return True
        except Exception:
            continue
    return False


def wire_engagement(wcfg: MoEWireConfig, num_experts: int, batch: int
                    ) -> Optional[Tuple[MeshInfo, Tuple[str, ...]]]:
    """Decide (at trace time) whether the explicit a2a wire can serve
    this call: returns (mesh_info, expert axes) or None with the reason
    logged ONCE — the engine's fallback contract: never silent."""
    if not wcfg.explicit:
        return None
    mesh_info = peek_mesh()
    if mesh_info is None:
        _warn_once("no-mesh", "comm.moe a2a wire requested but no mesh "
                   "is current — falling back to the implicit exchange")
        return None
    for ax in (MODEL_AXIS, SEQ_AXIS, PIPE_AXIS):
        if mesh_info.axis_size(ax) > 1:
            _warn_once(
                f"axis-{ax}",
                f"comm.moe a2a wire requires a pure-DP mesh ({ax} axis "
                f"is {mesh_info.axis_size(ax)}); legacy-jax full-manual "
                "shard_map would silently replicate the non-data axes — "
                "falling back to the implicit exchange")
            return None
    axes = expert_axes(wcfg, mesh_info)
    if wcfg.placement == "inner" and not mesh_info.hierarchical:
        _warn_once("inner-flat",
                   "comm.moe.placement='inner' on a flat mesh: no "
                   "data_inner axis exists — the exchange runs over the "
                   "full data axis")
    ep = 1
    for a in axes:
        ep *= mesh_info.axis_size(a)
    dp = mesh_info.axis_size(DATA_AXIS)
    if dp <= 1 or ep <= 1:
        # name the REAL degenerate axis: on a hier mesh with inner
        # placement, ep can be 1 (inner groups of 1) while dp is wide
        reason = ("data-parallel width is 1" if dp <= 1 else
                  f"the expert-parallel width over {'/'.join(axes)} "
                  f"is 1 (dp={dp})")
        _warn_once(f"ep1-{dp}-{ep}",
                   f"comm.moe a2a wire: {reason} — nothing to "
                   "exchange, running the local dispatch")
        return None
    if num_experts % ep != 0:
        _warn_once(
            f"experts-{num_experts}-{ep}",
            f"comm.moe a2a wire: num_experts={num_experts} is not "
            f"divisible by the expert-parallel width {ep} — falling "
            "back to the implicit exchange")
        return None
    if batch % dp != 0:
        _warn_once(
            f"batch-{batch}-{dp}",
            f"comm.moe a2a wire: batch rows {batch} not divisible by "
            f"the data width {dp} — falling back to the implicit "
            "exchange")
        return None
    if _inside_manual_region(mesh_info.data_axes):
        _warn_once(
            "manual-region",
            "comm.moe a2a wire: already inside a manual collective "
            "region (the bucketed gradient wire computes with "
            "replicated experts in-program) — running the local "
            "dispatch there")
        return None
    if wcfg.overlap in ("auto", "on"):
        level = logger.warning if wcfg.overlap == "on" else logger.info
        key = f"overlap-{wcfg.overlap}"
        if key not in _warned:
            _warned.add(key)
            level(
                "comm.moe.overlap: the expert a2a is consumed by the "
                "very next expert matmul INSIDE the step program — a "
                "dependent mid-layer collective has no independent "
                "compute to hide behind, and the PR-9 host exchange "
                "can only ride BETWEEN dispatched programs; running "
                "the serial in-program wire (the bench's "
                "moe.a2a_exposed_ms quantifies what a chunked overlap "
                "would hide)")
    return mesh_info, axes
