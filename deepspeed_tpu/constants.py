"""Top-level distributed constants (reference deepspeed/constants.py).

The NCCL rendezvous port becomes the jax.distributed coordinator port;
the process-group timeout maps to the coordinator's initialization
timeout (jax.distributed.initialize initialization_timeout)."""

from datetime import timedelta

TORCH_DISTRIBUTED_DEFAULT_PORT = 29500  # kept name for config parity
DEFAULT_COORDINATOR_PORT = TORCH_DISTRIBUTED_DEFAULT_PORT

default_pg_timeout = timedelta(minutes=30)
