"""Benchmark: GPT-2 training throughput through the DeepSpeed-TPU engine.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Metric is tokens/sec/chip training GPT-2 (ZeRO-2, bf16) — the BASELINE.json
north-star axis. vs_baseline converts the achieved model FLOPS/chip
(6 * params * tokens/sec) against the reference's headline 64 TFLOPS/GPU
(BASELINE.md row 1, docs/_tutorials/bert-pretraining.md:387) — the only
published absolute compute-rate number in the reference docs.

Hardened against TPU backend-init failure (round-1 BENCH rc=1 / MULTICHIP
rc=124 post-mortem): the TPU plugin can either raise or *hang* during
backend setup, so availability is probed in a subprocess with a hard
timeout; on probe failure the parent pins the CPU platform before its own
first JAX use and still emits a (clearly labelled) smoke-mode JSON line.
Any later exception also produces a JSON line rather than a bare rc=1.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REFERENCE_TFLOPS = 64.0  # reference headline TFLOPS/GPU (BASELINE.md)
PROBE_TIMEOUT_S = 120
PROBE_ATTEMPTS = 2


def _probe_tpu() -> bool:
    """Check in a subprocess (with timeout) that the TPU backend comes up.

    Backend init happens in the child, so a hung plugin retry loop (the
    round-1 MULTICHIP failure mode) cannot wedge this process.
    """
    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        return False
    # the TPU plugin may register under a non-'tpu' platform name (here:
    # 'axon'), so accept any non-cpu accelerator backend
    code = "import jax; assert jax.default_backend() != 'cpu'; print('ok')"
    for attempt in range(PROBE_ATTEMPTS):
        try:
            r = subprocess.run(
                [sys.executable, "-c", code],
                capture_output=True, timeout=PROBE_TIMEOUT_S,
            )
            if r.returncode == 0:
                return True
        except subprocess.TimeoutExpired:
            pass
        if attempt + 1 < PROBE_ATTEMPTS:
            time.sleep(5)
    return False


def _pin_cpu() -> None:
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass


def _dense_peak_tflops(n=4096, iters=100) -> float:
    """Achievable bf16 MXU rate on this chip — the MFU denominator.

    Twin of tools/perf_sweep.py chip_matmul_tflops (bench.py must stay a
    standalone single file for the driver) — fix both together.

    The iteration chain lives INSIDE one jit (lax.fori_loop with a data
    dependency between matmuls), so the whole measurement is a single
    dispatch. The earlier one-dispatch-per-matmul loop measured tunnel
    RTT, not the MXU (18.6 "TFLOPS" on a chip whose model step was
    simultaneously achieving 26+ — an MFU denominator below the
    numerator)."""
    import jax
    import jax.numpy as jnp

    x = jnp.ones((n, n), jnp.bfloat16)

    @jax.jit
    def chain(y, x):
        return jax.lax.fori_loop(
            0, iters, lambda i, y: jax.lax.dot(y, x), y)

    y = chain(x, x).block_until_ready()  # compile
    best = float("inf")
    for _ in range(2):
        t0 = time.perf_counter()
        chain(y, x).block_until_ready()
        best = min(best, time.perf_counter() - t0)
    return iters * 2 * n**3 / best / 1e12


def _last_tpu_artifact():
    """Newest committed hardware datum, for cpu-smoke fallbacks.

    Scans `BENCH_r*.json` (driver round captures) and every json under
    `bench_artifacts/` (incl. the telemetry-manifest `runs/` dir and the
    restored round dirs) for the NEWEST entry (by file mtime) whose
    platform is a real accelerator, so a smoke-mode JSON line carries
    the last on-TPU measurement instead of silently erasing hardware
    history (VERDICT r5 #3)."""
    import glob

    here = os.path.dirname(os.path.abspath(__file__))
    best = None
    best_mtime = -1.0
    for path in (glob.glob(os.path.join(here, "BENCH_r*.json")) +
                 glob.glob(os.path.join(here, "bench_artifacts",
                                        "**", "*.json"), recursive=True)):
        try:
            mtime = os.path.getmtime(path)
            if mtime <= best_mtime:
                continue
            with open(path) as f:
                d = json.load(f)
        except Exception:
            continue
        if not isinstance(d, dict):
            continue
        # unwrap driver captures ({"parsed": ...}) and telemetry
        # artifacts ({"result": ...}) to the raw bench line
        r = d.get("parsed", d.get("result", d))
        if not isinstance(r, dict):
            continue
        plat = r.get("platform")
        if not plat or str(plat).startswith("cpu"):
            continue
        best = (path, r)
        best_mtime = mtime
    if best is None:
        return None
    path, r = best
    return {k: r.get(k) for k in ("metric", "value", "unit", "platform",
                                  "vs_baseline", "tflops_per_chip",
                                  "mfu_pct") if r.get(k) is not None} | {
        "source": os.path.basename(path)}


def _attach_last_tpu(out: dict) -> dict:
    if out.get("platform") == "cpu-smoke":
        last = _last_tpu_artifact()
        if last:
            out["last_tpu"] = last
    return out


def _time_config(size, seq, micro, remat, steps, warmup=2,
                 attn_impl="auto"):
    """Build an engine for one config and time `steps` steps. Returns the
    measurement dict, with every engine reference dropped afterwards so
    the next (possibly larger) config starts from a clean HBM."""
    import gc

    import jax

    import deepspeed_tpu
    from deepspeed_tpu.models import GPT, gpt2_config

    n_dev = jax.device_count()
    cfg = gpt2_config(size, max_seq_len=seq,
                      shard_activations=n_dev > 1, remat=remat,
                      attn_impl=attn_impl)
    model = GPT(cfg)
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config_params={
        "train_batch_size": micro * n_dev,
        "train_micro_batch_size_per_gpu": micro,
        "bf16": {"enabled": True},
        "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
        "zero_optimization": {"stage": 2},
        "mesh": {"data": n_dev},
        "steps_per_print": 0,
    })
    n_params = model.num_params()
    global_batch = micro * n_dev
    tokens = jax.random.randint(jax.random.PRNGKey(0),
                                (global_batch, seq + 1), 0, cfg.vocab_size)
    batch = (tokens[:, :-1], tokens[:, 1:])

    def step():
        loss = engine.forward(batch)
        engine.backward()
        engine.step()
        return loss

    try:
        for _ in range(warmup):
            step().block_until_ready()
        t0 = time.perf_counter()
        for _ in range(steps):
            loss = step()
        loss.block_until_ready()
        dt = time.perf_counter() - t0
    finally:
        # drop every engine/closure/array reference (same discipline as
        # run_headroom) before the caller builds the next engine
        try:
            del step, loss
        except UnboundLocalError:
            pass
        del engine, batch, tokens, model
        gc.collect()

    tok_s_chip = steps * global_batch * seq / dt / n_dev
    return {
        "size": size, "seq": seq, "micro": micro, "remat": remat,
        "attn_impl": attn_impl,
        "n_params": n_params, "n_dev": n_dev,
        "tok_s_chip": tok_s_chip,
        "tflops": 6.0 * n_params * tok_s_chip / 1e12,
    }


# headline candidates for the on-chip autotune probe: the fused
# single-chip step's MFU depends on model size x batch x remat in ways
# only hardware can rank (BERT-large at micro 64 measured 2x the MFU of
# GPT-2 small at micro 8 — BENCH.md 07-31). Probed cheaply (3 steps),
# winner gets the full measurement.
AUTOTUNE_CANDIDATES = (
    ("small", 8, False),   # the historical headline config
    ("small", 32, False),  # bigger batch, same model
    ("medium", 8, False),  # bigger matmuls, no recompute (if it fits)
    ("medium", 16, True),  # bigger matmuls + batch, remat for headroom
)


def run_bench(on_tpu: bool) -> dict:
    import jax

    if on_tpu:
        size, seq, micro, steps, remat = "small", 1024, 8, 20, False
    else:  # smoke mode for CPU dev runs / TPU-unavailable fallback
        size, seq, micro, steps, remat = "nano", 128, 4, 5, False
    # sweep overrides (tools/perf_sweep.py drives these) pin the config
    # and disable the autotune probe
    pinned = any(k in os.environ for k in
                 ("DSTPU_BENCH_SIZE", "DSTPU_BENCH_MICRO",
                  "DSTPU_BENCH_SEQ"))
    size = os.environ.get("DSTPU_BENCH_SIZE", size)
    seq = int(os.environ.get("DSTPU_BENCH_SEQ", seq))
    micro = int(os.environ.get("DSTPU_BENCH_MICRO", micro))
    autotune = (on_tpu and not pinned
                and os.environ.get("DSTPU_BENCH_AUTOTUNE", "1") != "0")
    attn_impl = "auto"

    probes = []
    cached_hit = False
    cache_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "bench_artifacts", "autotune.json")
    # invalidation key: a cache probed under different candidates, seq,
    # or backend must not pin this run (e.g. new TPU generation)
    def _cache_fingerprint():
        import jax

        return {"candidates": [list(c) for c in AUTOTUNE_CANDIDATES],
                "seq": seq, "backend": jax.default_backend()}

    # the probe/cache state machine now lives in runtime/autotune
    # (SearchDriver: budgeted, failure-tolerant probe loop; WinnerCache
    # mode="single" keeps this exact autotune.json artifact format, so
    # committed bench artifacts stay comparable across rounds)
    from deepspeed_tpu.runtime.autotune import SearchDriver, WinnerCache

    if autotune:
        # a previous on-TPU session already probed: reuse its winner so
        # the driver's end-of-round run doesn't pay 3 extra compiles
        # against an unknown timeout budget
        cached = WinnerCache(cache_path,
                             mode="single").lookup(_cache_fingerprint())
        if cached is not None:
            try:
                # parse into temporaries FIRST: a truncated entry must
                # never half-clobber the default config before the
                # validation error fires
                c_size = cached["size"]
                c_micro = int(cached["micro"])
                c_remat = bool(cached["remat"])
                c_attn = cached.get("attn_impl", "auto")
            except (KeyError, TypeError, ValueError):
                pass  # foreign/truncated cache entry: re-probe below
            else:
                size, micro, remat, attn_impl = (c_size, c_micro, c_remat,
                                                 c_attn)
                autotune = False
                cached_hit = True
    if autotune:
        budget_s = float(os.environ.get("DSTPU_AUTOTUNE_BUDGET_S", "420"))

        def _probe(cand):
            return _time_config(cand["size"], seq, cand["micro"],
                                cand["remat"], steps=3, warmup=1,
                                attn_impl=cand.get("attn_impl", "auto"))

        def _fmt(res):
            """Format-stable probes-list entry (the committed artifact
            shape): success = the rounded metrics, failure/skip = the
            candidate + why (A/B entries carry attn_impl only)."""
            cand = dict(res.candidate)
            ab = "attn_impl" in cand
            if res.skipped is not None:
                return {**cand, "skipped": res.skipped}
            if res.error is not None:
                if ab:
                    return {"attn_impl": cand["attn_impl"],
                            "failed": res.error}
                return {**cand, "failed": res.error, "oom": res.oom}
            return {k: (round(v, 2) if isinstance(v, float) else v)
                    for k, v in res.metrics.items()
                    if k not in ("n_params", "n_dev")}

        driver = SearchDriver(_probe, score_fn=lambda m: m["tflops"],
                              budget_s=budget_s)
        best = driver.search([{"size": c_size, "micro": c_micro,
                               "remat": c_remat}
                              for c_size, c_micro, c_remat in
                              AUTOTUNE_CANDIDATES])
        if best is not None:
            size, micro, remat = (best.metrics["size"],
                                  best.metrics["micro"],
                                  best.metrics["remat"])
            # kernel-choice A/B at the winning shape: the flash-vs-XLA
            # attention question has no hardware datum yet (the 07-31
            # sweeps were lost to the tunnel drop) — one extra probe
            # settles it for the final measurement
            if not driver.budget_exhausted():
                r_ab = driver.probe({"size": size, "micro": micro,
                                     "remat": remat, "attn_impl": "xla"})
                if r_ab.ok and r_ab.metrics["tflops"] > \
                        best.metrics["tflops"]:
                    attn_impl = "xla"
        probes = [_fmt(r) for r in driver.results]
        if best is not None and driver.complete:
            # never pin future rounds to a degraded probe set
            WinnerCache(cache_path, mode="single").store(
                _cache_fingerprint(),
                {"size": size, "micro": micro, "remat": remat,
                 "attn_impl": attn_impl}, probes)

    try:
        r = _time_config(size, seq, micro, remat, steps=steps,
                         attn_impl=attn_impl)
    except Exception:
        # a cached/probed winner that no longer runs (chip change, OOM)
        # must not kill the headline: fall back to the known-good default
        if (size, micro, remat) == ("small", 8, False) or not on_tpu:
            raise
        size, micro, remat = "small", 8, False
        cached_hit = False
        attn_impl = "auto"
        r = _time_config(size, seq, micro, remat, steps=steps)
    tokens_per_sec_chip = r["tok_s_chip"]
    achieved_tflops = r["tflops"]
    peak = _dense_peak_tflops() if on_tpu else 0.0

    out = {
        "metric": f"gpt2_{size}_zero2_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec_chip, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(achieved_tflops / REFERENCE_TFLOPS, 4),
        "platform": jax.default_backend() if on_tpu else "cpu-smoke",
        "tflops_per_chip": round(achieved_tflops, 2),
        "world_size": r["n_dev"],
        "micro_batch": micro,
        "seq_len": seq,
    }
    if r["remat"]:
        out["remat"] = True
    if r["attn_impl"] != "auto":
        out["attn_impl"] = r["attn_impl"]
    if probes:
        out["autotune_probes"] = probes
    if cached_hit:
        out["autotune_cached"] = True  # config provenance: prior session
    if peak:
        # MFU against this chip's MEASURED dense bf16 matmul rate (the
        # vs_baseline denominator stays the reference's published 64
        # TFLOPS/GPU so the driver metric is comparable across rounds)
        out["chip_dense_tflops"] = round(peak, 1)
        out["mfu_pct"] = round(100 * achieved_tflops / peak, 1)
    if r["n_dev"] == 1:
        out["note"] = ("world_size=1: ZeRO dp-sharding inactive; measures "
                       "the fused single-chip step only")
    return _attach_last_tpu(out)


def run_headroom(on_tpu: bool) -> dict:
    """Memory-headroom mode (DSTPU_BENCH_MODE=headroom): largest micro
    batch that fits on ONE chip for a mid-size GPT with remat + streaming
    CE, and the MFU at that batch — on-hardware evidence for the
    memory-first kernels that ZeRO can't show at world_size=1."""
    import jax

    import deepspeed_tpu
    from deepspeed_tpu.models import GPT, gpt2_config

    if on_tpu:
        size, seq, tries = "medium", 1024, (1, 2, 4, 8, 16, 32, 64)
    else:  # harness validation on CPU: tiny shapes, two attempts
        size, seq, tries = "nano", 128, (2, 4)
    size = os.environ.get("DSTPU_BENCH_SIZE", size)
    seq = int(os.environ.get("DSTPU_BENCH_SEQ", seq))

    cfg = gpt2_config(size, max_seq_len=seq, remat=True,
                      shard_activations=False)
    n_params = GPT(cfg).num_params()
    best = None  # (micro, tokens_per_sec)
    for micro in tries:
        try:
            engine, _, _, _ = deepspeed_tpu.initialize(
                model=GPT(cfg), config_params={
                    "train_batch_size": micro,
                    "bf16": {"enabled": True},
                    "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
                    "zero_optimization": {"stage": 0},
                    "mesh": {"data": 1},
                    "steps_per_print": 0,
                })
            tokens = jax.random.randint(jax.random.PRNGKey(0),
                                        (micro, seq + 1), 0, cfg.vocab_size)
            batch = (tokens[:, :-1], tokens[:, 1:])

            def step():
                loss = engine.forward(batch)
                engine.backward()
                engine.step()
                return loss

            step().block_until_ready()  # compile + first step (peak alloc)
            n_steps = 8 if on_tpu else 2
            t0 = time.perf_counter()
            for _ in range(n_steps):
                loss = step()
            loss.block_until_ready()
            dt = time.perf_counter() - t0
            best = (micro, n_steps * micro * seq / dt)
            # drop EVERY reference to this engine's device memory before
            # the next (larger) engine allocates: the step closure and
            # loss array both capture it, so `del engine` alone would
            # leave both models resident and OOM the search early
            del step, loss, engine
            import gc

            gc.collect()
        except Exception as exc:
            if "RESOURCE_EXHAUSTED" in str(exc) or "Out of memory" in str(exc):
                break  # found the ceiling
            raise
    if best is None:
        raise RuntimeError("no micro batch fit")
    micro, tps = best
    search_capped = micro == tries[-1]  # never hit OOM: not a true ceiling
    achieved = 6.0 * n_params * tps / 1e12
    peak = _dense_peak_tflops() if on_tpu else 0.0
    out = {
        "metric": f"gpt2_{size}_headroom_max_micro_batch",
        "value": micro,
        "unit": "micro_batch (remat + streaming CE, 1 chip)",
        "vs_baseline": round(achieved / REFERENCE_TFLOPS, 4),
        "platform": jax.default_backend() if on_tpu else "cpu-smoke",
        "tokens_per_sec_chip": round(tps, 1),
        "tflops_per_chip": round(achieved, 2),
        "seq_len": seq,
    }
    if peak:
        out["chip_dense_tflops"] = round(peak, 1)
        out["mfu_pct"] = round(100 * achieved / peak, 1)
    if search_capped:
        out["search_capped"] = True  # largest TRIED batch fit; not an OOM ceiling
    return _attach_last_tpu(out)


def _record_artifact(result: dict) -> dict:
    """Land the result in the committed, manifest-indexed artifact dir
    (deepspeed_tpu/monitor/artifacts.py) so a hardware measurement
    survives the session that produced it — the round-5 failure mode
    (on-TPU artifacts later deleted from the tree, docs pointing at
    nothing) cannot recur when every run writes through the manifest.
    Telemetry must never kill the headline: best-effort only."""
    try:
        from deepspeed_tpu.monitor.artifacts import record_bench_result

        result["artifact"] = record_bench_result(result)
    except Exception:
        pass
    return result


def main():
    on_tpu = _probe_tpu()
    if not on_tpu:
        _pin_cpu()
    mode = os.environ.get("DSTPU_BENCH_MODE", "throughput")
    runner = run_headroom if mode == "headroom" else run_bench
    try:
        result = runner(on_tpu)
    except Exception as exc:  # never exit nonzero without a JSON line
        if on_tpu:
            # TPU run died mid-bench (e.g. tunnel dropped). The in-process
            # backend table is already initialized on TPU, so a true CPU
            # fallback needs a fresh process.
            env = dict(os.environ, JAX_PLATFORMS="cpu")
            try:
                r = subprocess.run([sys.executable, os.path.abspath(__file__)],
                                   capture_output=True, text=True, env=env,
                                   timeout=600)
                line = r.stdout.strip().splitlines()[-1]
                result = json.loads(line)
                result["note"] = (f"tpu run failed ({type(exc).__name__}), "
                                  f"cpu-subprocess fallback")
            except Exception as exc2:
                result = {"metric": "bench_error", "value": 0.0,
                          "unit": "error", "vs_baseline": 0.0,
                          "error": f"{type(exc).__name__}: {exc}; "
                                   f"fallback: {type(exc2).__name__}: {exc2}"}
        else:
            result = {"metric": "bench_error", "value": 0.0,
                      "unit": "error", "vs_baseline": 0.0,
                      "error": f"{type(exc).__name__}: {exc}"}
    print(json.dumps(_record_artifact(result)))


if __name__ == "__main__":
    main()
