"""Benchmark: GPT-2 training throughput through the DeepSpeed-TPU engine.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Metric is tokens/sec/chip training GPT-2 (ZeRO-2, bf16) — the BASELINE.json
north-star axis. vs_baseline converts the achieved model FLOPS/chip
(6 * params * tokens/sec) against the reference's headline 64 TFLOPS/GPU
(BASELINE.md row 1, docs/_tutorials/bert-pretraining.md:387) — the only
published absolute compute-rate number in the reference docs.
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp

REFERENCE_TFLOPS = 64.0  # reference headline TFLOPS/GPU (BASELINE.md)


def main():
    import deepspeed_tpu
    from deepspeed_tpu.models import GPT, gpt2_config

    on_tpu = jax.default_backend() == "tpu"
    n_dev = jax.device_count()
    if on_tpu:
        size, seq, micro, steps = "small", 1024, 8, 20
    else:  # smoke mode for CPU dev runs
        size, seq, micro, steps = "nano", 128, 4, 5

    cfg = gpt2_config(size, max_seq_len=seq,
                      shard_activations=n_dev > 1, remat=False)
    model = GPT(cfg)
    config = {
        "train_batch_size": micro * n_dev,
        "train_micro_batch_size_per_gpu": micro,
        "bf16": {"enabled": True},
        "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
        "zero_optimization": {"stage": 2},
        "mesh": {"data": n_dev},
        "steps_per_print": 0,
    }
    engine, _, _, _ = deepspeed_tpu.initialize(model=model,
                                               config_params=config)
    n_params = model.num_params()
    global_batch = micro * n_dev
    rng = jax.random.PRNGKey(0)
    tokens = jax.random.randint(rng, (global_batch, seq + 1), 0,
                                cfg.vocab_size)
    batch = (tokens[:, :-1], tokens[:, 1:])

    def step():
        loss = engine.forward(batch)
        engine.backward()
        engine.step()
        return loss

    # warmup / compile
    step().block_until_ready()
    step().block_until_ready()

    t0 = time.perf_counter()
    for _ in range(steps):
        loss = step()
    loss.block_until_ready()
    dt = time.perf_counter() - t0

    tokens_per_sec = steps * global_batch * seq / dt
    tokens_per_sec_chip = tokens_per_sec / n_dev
    achieved_tflops = 6.0 * n_params * tokens_per_sec_chip / 1e12

    print(json.dumps({
        "metric": f"gpt2_{size}_zero2_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec_chip, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(achieved_tflops / REFERENCE_TFLOPS, 4),
    }))


if __name__ == "__main__":
    main()
