// Host-side vectorized Adam for optimizer-state offload.
//
// TPU-native equivalent of the reference's AVX/OpenMP CPU-Adam
// (/root/reference/csrc/adam/cpu_adam.cpp: SIMD macros cpu_adam.h:25-45,
// OpenMP tiling): the fp32 master params + moments live in host RAM while
// the device keeps bf16 working weights. Vectorization comes from
// `#pragma omp simd` + -O3 -march=native (AVX-512 on TPU-VM hosts) instead
// of hand-written intrinsics; same math, same memory traffic.
//
// C ABI (ctypes-loaded; no pybind11 in this image).

#include <cstdint>
#include <cmath>
#include <cstring>

extern "C" {

// One fused Adam step over a flat fp32 shard.
// adam_w != 0 -> decoupled weight decay (AdamW), else classic L2.
// bc1/bc2 are the bias-correction denominators (1 - beta^t), precomputed.
void ds_adam_step(int64_t n,
                  float* p,
                  const float* g,
                  float* m,
                  float* v,
                  float lr,
                  float beta1,
                  float beta2,
                  float eps,
                  float weight_decay,
                  int adam_w,
                  float bc1,
                  float bc2) {
    const float om_b1 = 1.0f - beta1;
    const float om_b2 = 1.0f - beta2;
#pragma omp parallel for simd schedule(static)
    for (int64_t i = 0; i < n; ++i) {
        float grad = g[i];
        if (!adam_w && weight_decay != 0.0f) grad += weight_decay * p[i];
        float mi = beta1 * m[i] + om_b1 * grad;
        float vi = beta2 * v[i] + om_b2 * grad * grad;
        m[i] = mi;
        v[i] = vi;
        float denom = sqrtf(vi / bc2) + eps;
        float update = (mi / bc1) / denom;
        if (adam_w && weight_decay != 0.0f) update += weight_decay * p[i];
        p[i] -= lr * update;
    }
}

// Same step but also emits the updated params as bf16 (round-to-nearest-even)
// into `out16` — the wire format copied back to device HBM.
void ds_adam_step_bf16(int64_t n,
                       float* p,
                       const float* g,
                       float* m,
                       float* v,
                       uint16_t* out16,
                       float lr,
                       float beta1,
                       float beta2,
                       float eps,
                       float weight_decay,
                       int adam_w,
                       float bc1,
                       float bc2) {
    ds_adam_step(n, p, g, m, v, lr, beta1, beta2, eps, weight_decay, adam_w,
                 bc1, bc2);
#pragma omp parallel for simd schedule(static)
    for (int64_t i = 0; i < n; ++i) {
        uint32_t bits;
        memcpy(&bits, &p[i], sizeof(bits));
        uint32_t rounding = 0x7FFFu + ((bits >> 16) & 1u);
        out16[i] = static_cast<uint16_t>((bits + rounding) >> 16);
    }
}

}  // extern "C"
