// Async file I/O engine for NVMe/SSD tensor swapping.
//
// Equivalent of the reference's libaio O_DIRECT engine
// (/root/reference/csrc/aio/common/deepspeed_aio_common.cpp:13-96,
// py_lib/deepspeed_py_aio_handle.cpp: handle with worker thread, pinned
// buffers, submit/wait).  Two engines behind one C ABI:
//
//  * UringEngine — kernel-async io_uring via raw syscalls
//    (io_uring_setup/io_uring_enter; this image has linux/io_uring.h but
//    no liburing).  Large transfers are split into block_size chunks
//    submitted concurrently on one ring, the in-kernel analogue of the
//    reference's io_submit block mode (deepspeed_aio_common.cpp:76-96).
//  * ThreadPoolEngine — std::thread pool issuing pread/pwrite; the
//    portable fallback when io_uring is unavailable (seccomp/container
//    policy), same overlap structure (submit returns, `wait` joins).
//
// O_DIRECT is honored per-op when buffer/offset/length meet the 4 KiB
// alignment contract, else that op silently degrades to buffered I/O
// (the caller opted in for bandwidth, not for EINVAL).
//
// C ABI for ctypes.

#include <algorithm>
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include <errno.h>
#include <fcntl.h>
#include <sys/mman.h>
#include <sys/syscall.h>
#include <unistd.h>

#if defined(__linux__) && defined(__has_include)
#if __has_include(<linux/io_uring.h>)
#include <linux/io_uring.h>
#define DSTPU_HAVE_URING 1
#endif
#endif

namespace {

struct IoOp {
    bool write;
    void* buf;
    std::string path;
    int64_t nbytes;
    int64_t file_offset;
};

struct Engine {
    virtual void submit(IoOp op) = 0;
    virtual int64_t wait() = 0;  // join all pending; returns failed-op count
    virtual int kind() const = 0;  // 1 = thread pool, 2 = io_uring
    virtual ~Engine() = default;
};

constexpr int64_t kDirectAlign = 4096;

bool direct_ok(const void* buf, int64_t nbytes, int64_t off) {
    return (reinterpret_cast<uintptr_t>(buf) | static_cast<uint64_t>(nbytes) |
            static_cast<uint64_t>(off)) % kDirectAlign == 0;
}

int open_for(const IoOp& op, bool want_direct) {
    int flags = op.write ? (O_WRONLY | O_CREAT) : O_RDONLY;
#ifdef O_DIRECT
    if (want_direct && direct_ok(op.buf, op.nbytes, op.file_offset))
        flags |= O_DIRECT;
#endif
    int fd = ::open(op.path.c_str(), flags, 0644);
#ifdef O_DIRECT
    if (fd < 0 && (flags & O_DIRECT)) {  // fs may not support O_DIRECT
        flags &= ~O_DIRECT;
        fd = ::open(op.path.c_str(), flags, 0644);
    }
#endif
    return fd;
}

// ---------------------------------------------------------------------------
// ThreadPoolEngine — pread/pwrite worker pool (fallback)
// ---------------------------------------------------------------------------

struct ThreadPoolEngine : Engine {
    std::vector<std::thread> workers;
    std::deque<IoOp> queue;
    std::mutex mu;
    std::condition_variable cv_submit;
    std::condition_variable cv_done;
    int64_t pending = 0;
    int64_t errors = 0;
    int block_size;
    bool use_o_direct;
    bool stop = false;

    explicit ThreadPoolEngine(int n_threads, int block, bool o_direct)
        : block_size(block > 0 ? block : (1 << 20)), use_o_direct(o_direct) {
        for (int i = 0; i < n_threads; ++i) {
            workers.emplace_back([this] { this->run(); });
        }
    }

    ~ThreadPoolEngine() override {
        {
            std::lock_guard<std::mutex> lk(mu);
            stop = true;
        }
        cv_submit.notify_all();
        for (auto& t : workers) t.join();
    }

    void submit(IoOp op) override {
        {
            std::lock_guard<std::mutex> lk(mu);
            queue.push_back(std::move(op));
            ++pending;
        }
        cv_submit.notify_one();
    }

    int64_t wait() override {
        std::unique_lock<std::mutex> lk(mu);
        cv_done.wait(lk, [this] { return pending == 0; });
        int64_t e = errors;
        errors = 0;
        return e;
    }

    int kind() const override { return 1; }

    void run() {
        for (;;) {
            IoOp op;
            {
                std::unique_lock<std::mutex> lk(mu);
                cv_submit.wait(lk, [this] { return stop || !queue.empty(); });
                if (stop && queue.empty()) return;
                op = std::move(queue.front());
                queue.pop_front();
            }
            bool ok = execute(op);
            {
                std::lock_guard<std::mutex> lk(mu);
                if (!ok) ++errors;
                if (--pending == 0) cv_done.notify_all();
            }
        }
    }

    bool execute(const IoOp& op) {
        int fd = open_for(op, use_o_direct);
        if (fd < 0) return false;
        char* p = static_cast<char*>(op.buf);
        int64_t remaining = op.nbytes;
        int64_t off = op.file_offset;
        bool ok = true;
        while (remaining > 0) {
            int64_t chunk = remaining < block_size ? remaining : block_size;
            ssize_t r = op.write ? ::pwrite(fd, p, chunk, off)
                                 : ::pread(fd, p, chunk, off);
            if (r <= 0) {
                ok = false;
                break;
            }
            p += r;
            off += r;
            remaining -= r;
        }
        ::close(fd);
        return ok;
    }
};

#ifdef DSTPU_HAVE_URING

// ---------------------------------------------------------------------------
// UringEngine — raw-syscall io_uring
// ---------------------------------------------------------------------------

int sys_uring_setup(unsigned entries, io_uring_params* p) {
    return static_cast<int>(syscall(__NR_io_uring_setup, entries, p));
}

int sys_uring_enter(int fd, unsigned to_submit, unsigned min_complete,
                    unsigned flags) {
    return static_cast<int>(syscall(__NR_io_uring_enter, fd, to_submit,
                                    min_complete, flags, nullptr, 0));
}

struct UringEngine : Engine {
    // one submitted op fans out into block_size chunks concurrently in
    // flight on the ring; the op completes when every chunk has
    struct OpState {
        int fd = -1;
        bool write = false;
        int live_chunks = 0;
        bool failed = false;
    };
    struct Chunk {
        OpState* op;
        char* buf;
        int64_t nbytes;
        int64_t off;
    };

    int ring_fd = -1;
    unsigned sq_entry_count = 0;
    unsigned cq_entry_count = 0;
    unsigned* sq_head = nullptr;
    unsigned* sq_tail = nullptr;
    unsigned* sq_mask = nullptr;
    unsigned* sq_array = nullptr;
    unsigned* cq_head = nullptr;
    unsigned* cq_tail = nullptr;
    unsigned* cq_mask = nullptr;
    io_uring_sqe* sqes = nullptr;
    io_uring_cqe* cqes = nullptr;
    void* sq_ring_ptr = nullptr;
    void* cq_ring_ptr = nullptr;
    size_t sq_ring_sz = 0, cq_ring_sz = 0;
    bool single_mmap = false;

    std::mutex mu;
    std::deque<Chunk*> backlog;  // chunks waiting for a free SQE
    int64_t inflight = 0;        // SQEs the kernel has consumed, not reaped
    int64_t sq_credit = 0;       // SQEs published but not yet consumed by
                                 // io_uring_enter (partial/EINTR submits)
    int64_t open_ops = 0;        // ops not yet fully completed
    int64_t errors = 0;
    int block_size;
    bool use_o_direct;
    bool ok_ = false;

    explicit UringEngine(int depth, int block, bool o_direct)
        : block_size(block > 0 ? block : (1 << 20)), use_o_direct(o_direct) {
        io_uring_params p;
        std::memset(&p, 0, sizeof(p));
        unsigned entries = depth > 0 ? static_cast<unsigned>(depth) : 64;
        ring_fd = sys_uring_setup(entries, &p);
        if (ring_fd < 0) return;
        sq_entry_count = p.sq_entries;
        cq_entry_count = p.cq_entries;
        single_mmap = (p.features & IORING_FEAT_SINGLE_MMAP) != 0;
        sq_ring_sz = p.sq_off.array + p.sq_entries * sizeof(unsigned);
        cq_ring_sz = p.cq_off.cqes + p.cq_entries * sizeof(io_uring_cqe);
        if (single_mmap) {
            sq_ring_sz = cq_ring_sz = std::max(sq_ring_sz, cq_ring_sz);
        }
        sq_ring_ptr = ::mmap(nullptr, sq_ring_sz, PROT_READ | PROT_WRITE,
                             MAP_SHARED | MAP_POPULATE, ring_fd,
                             IORING_OFF_SQ_RING);
        if (sq_ring_ptr == MAP_FAILED) {
            sq_ring_ptr = nullptr;
            teardown();
            return;
        }
        if (single_mmap) {
            cq_ring_ptr = sq_ring_ptr;
        } else {
            cq_ring_ptr = ::mmap(nullptr, cq_ring_sz, PROT_READ | PROT_WRITE,
                                 MAP_SHARED | MAP_POPULATE, ring_fd,
                                 IORING_OFF_CQ_RING);
            if (cq_ring_ptr == MAP_FAILED) {
                cq_ring_ptr = nullptr;
                teardown();
                return;
            }
        }
        void* sq_mem = ::mmap(nullptr, p.sq_entries * sizeof(io_uring_sqe),
                              PROT_READ | PROT_WRITE,
                              MAP_SHARED | MAP_POPULATE, ring_fd,
                              IORING_OFF_SQES);
        if (sq_mem == MAP_FAILED) {
            teardown();
            return;
        }
        sqes = static_cast<io_uring_sqe*>(sq_mem);
        auto* sqb = static_cast<char*>(sq_ring_ptr);
        sq_head = reinterpret_cast<unsigned*>(sqb + p.sq_off.head);
        sq_tail = reinterpret_cast<unsigned*>(sqb + p.sq_off.tail);
        sq_mask = reinterpret_cast<unsigned*>(sqb + p.sq_off.ring_mask);
        sq_array = reinterpret_cast<unsigned*>(sqb + p.sq_off.array);
        auto* cqb = static_cast<char*>(cq_ring_ptr);
        cq_head = reinterpret_cast<unsigned*>(cqb + p.cq_off.head);
        cq_tail = reinterpret_cast<unsigned*>(cqb + p.cq_off.tail);
        cq_mask = reinterpret_cast<unsigned*>(cqb + p.cq_off.ring_mask);
        cqes = reinterpret_cast<io_uring_cqe*>(cqb + p.cq_off.cqes);
        ok_ = true;
    }

    void teardown() {
        if (sqes) ::munmap(sqes, sq_entry_count * sizeof(io_uring_sqe));
        if (cq_ring_ptr && cq_ring_ptr != sq_ring_ptr)
            ::munmap(cq_ring_ptr, cq_ring_sz);
        if (sq_ring_ptr) ::munmap(sq_ring_ptr, sq_ring_sz);
        if (ring_fd >= 0) ::close(ring_fd);
        sqes = nullptr;
        sq_ring_ptr = cq_ring_ptr = nullptr;
        ring_fd = -1;
    }

    ~UringEngine() override {
        if (ok_) {
            wait();  // never unmap under in-flight kernel DMA
            teardown();
        }
    }

    void submit(IoOp op) override {
        std::lock_guard<std::mutex> lk(mu);
        auto* st = new OpState();
        st->write = op.write;
        st->fd = open_for(op, use_o_direct);
        ++open_ops;
        if (st->fd < 0) {
            st->failed = true;
            complete_op(st);
            return;
        }
        if (op.nbytes == 0) {
            complete_op(st);
            return;
        }
        char* p = static_cast<char*>(op.buf);
        int64_t remaining = op.nbytes;
        int64_t off = op.file_offset;
        while (remaining > 0) {
            int64_t chunk = remaining < block_size ? remaining : block_size;
            ++st->live_chunks;
            backlog.push_back(new Chunk{st, p, chunk, off});
            p += chunk;
            off += chunk;
            remaining -= chunk;
        }
        pump(0);  // fill free SQEs now; completions reaped in wait()
    }

    int64_t wait() override {
        std::lock_guard<std::mutex> lk(mu);
        while (open_ops > 0) {
            if (!pump(inflight + sq_credit > 0 ? 1 : 0)) {
                // enter failed hard: fail everything still queued; chunks
                // already in the kernel drain through complete_op as their
                // CQEs arrive on later calls (ring stays mapped)
                for (auto* c : backlog) finish_chunk(c, false);
                backlog.clear();
                break;
            }
        }
        int64_t e = errors;
        errors = 0;
        return e;
    }

    // move backlog into free SQEs, enter(min_complete), reap CQEs.
    // Returns false only on an unrecoverable io_uring_enter error.
    bool pump(unsigned min_complete) {
        unsigned head = __atomic_load_n(sq_head, __ATOMIC_ACQUIRE);
        unsigned tail = *sq_tail;
        // cap outstanding work at the CQ size: kernels without
        // IORING_FEAT_NODROP drop overflowed CQEs and the op would never
        // complete (SQ slots free as soon as enter consumes them, so the
        // SQ-room check alone does not bound completions)
        while (!backlog.empty() && tail - head < sq_entry_count &&
               inflight + sq_credit < cq_entry_count) {
            Chunk* c = backlog.front();
            backlog.pop_front();
            unsigned idx = tail & *sq_mask;
            io_uring_sqe* sqe = &sqes[idx];
            std::memset(sqe, 0, sizeof(*sqe));
            sqe->opcode = c->op->write ? IORING_OP_WRITE : IORING_OP_READ;
            sqe->fd = c->op->fd;
            sqe->addr = reinterpret_cast<uint64_t>(c->buf);
            sqe->len = static_cast<unsigned>(c->nbytes);
            sqe->off = static_cast<uint64_t>(c->off);
            sqe->user_data = reinterpret_cast<uint64_t>(c);
            sq_array[idx] = idx;
            ++tail;
            ++sq_credit;
        }
        __atomic_store_n(sq_tail, tail, __ATOMIC_RELEASE);
        int r = sys_uring_enter(ring_fd,
                                static_cast<unsigned>(sq_credit),
                                min_complete,
                                min_complete ? IORING_ENTER_GETEVENTS : 0);
        if (r < 0) {
            // nothing consumed: sq_credit stays, published SQEs are
            // re-credited on the next enter
            if (errno == EINTR || errno == EAGAIN || errno == EBUSY) {
                reap();
                return true;
            }
            return false;
        }
        // r = SQEs the kernel actually consumed (may be < sq_credit)
        inflight += r;
        sq_credit -= r;
        reap();
        return true;
    }

    void reap() {
        unsigned head = *cq_head;
        unsigned tail = __atomic_load_n(cq_tail, __ATOMIC_ACQUIRE);
        while (head != tail) {
            io_uring_cqe* cqe = &cqes[head & *cq_mask];
            auto* c = reinterpret_cast<Chunk*>(
                static_cast<uintptr_t>(cqe->user_data));
            int32_t res = cqe->res;
            ++head;
            --inflight;
            if (res <= 0) {
                finish_chunk(c, false);
            } else if (res < c->nbytes) {
                // short transfer: continue where the kernel stopped
                c->buf += res;
                c->off += res;
                c->nbytes -= res;
                backlog.push_back(c);
            } else {
                finish_chunk(c, true);
            }
        }
        __atomic_store_n(cq_head, head, __ATOMIC_RELEASE);
    }

    void finish_chunk(Chunk* c, bool ok) {
        OpState* st = c->op;
        delete c;
        if (!ok) st->failed = true;
        if (--st->live_chunks <= 0) complete_op(st);
    }

    void complete_op(OpState* st) {
        if (st->fd >= 0) ::close(st->fd);
        if (st->failed) ++errors;
        delete st;
        --open_ops;
    }

    int kind() const override { return 2; }
};

#endif  // DSTPU_HAVE_URING

}  // namespace

extern "C" {

// 1 iff an io_uring ring can actually be created (header presence is not
// enough — container seccomp policies commonly block the syscalls).
int aio_uring_supported() {
#ifdef DSTPU_HAVE_URING
    io_uring_params p;
    std::memset(&p, 0, sizeof(p));
    int fd = sys_uring_setup(4, &p);
    if (fd < 0) return 0;
    ::close(fd);
    return 1;
#else
    return 0;
#endif
}

// engine: 1 = thread pool, 2 = io_uring (NULL if unavailable),
//         0 = auto (io_uring when supported, else thread pool).
// n is the worker count (threads) or the SQ depth (io_uring).
void* aio_handle_create2(int n, int block_size, int o_direct, int engine) {
#ifdef DSTPU_HAVE_URING
    if (engine == 2 || engine == 0) {
        // the ring depth wants headroom beyond a thread-count-scale n;
        // bumped HERE so an auto fallback still gets n threads, not 64
        int depth = n < 64 ? 64 : n;
        auto* u = new UringEngine(depth, block_size, o_direct != 0);
        if (u->ok_) return static_cast<Engine*>(u);
        delete u;
        if (engine == 2) return nullptr;
    }
#else
    if (engine == 2) return nullptr;
#endif
    return static_cast<Engine*>(
        new ThreadPoolEngine(n > 0 ? n : 1, block_size, o_direct != 0));
}

// 1 = thread pool, 2 = io_uring — what the handle ACTUALLY is (auto may
// have fallen back after a setup/mmap failure).
int aio_handle_engine(void* h) {
    return static_cast<Engine*>(h)->kind();
}

void* aio_handle_create(int n_threads, int block_size, int o_direct) {
    if (n_threads <= 0) n_threads = 1;
    return static_cast<Engine*>(
        new ThreadPoolEngine(n_threads, block_size, o_direct != 0));
}

void aio_handle_destroy(void* h) {
    delete static_cast<Engine*>(h);
}

// async=0 blocks until THIS op (and all prior pending) completes.
int aio_pwrite(void* h, const void* buf, const char* path, int64_t nbytes,
               int64_t file_offset, int async_mode) {
    auto* e = static_cast<Engine*>(h);
    e->submit(IoOp{true, const_cast<void*>(buf), path, nbytes, file_offset});
    if (!async_mode) return static_cast<int>(e->wait());
    return 0;
}

int aio_pread(void* h, void* buf, const char* path, int64_t nbytes,
              int64_t file_offset, int async_mode) {
    auto* e = static_cast<Engine*>(h);
    e->submit(IoOp{false, buf, path, nbytes, file_offset});
    if (!async_mode) return static_cast<int>(e->wait());
    return 0;
}

// wait for all pending ops; returns number of failed ops (0 = success).
int aio_wait(void* h) {
    return static_cast<int>(static_cast<Engine*>(h)->wait());
}

}  // extern "C"
