// Async file I/O engine for NVMe/SSD tensor swapping.
//
// Equivalent of the reference's libaio O_DIRECT engine
// (/root/reference/csrc/aio/common/deepspeed_aio_common.cpp:13-96,
// py_lib/deepspeed_py_aio_handle.cpp: handle with worker thread, pinned
// buffers, submit/wait). This image has no libaio/liburing headers, so the
// engine is a std::thread pool issuing pread/pwrite (optionally O_DIRECT)
// — the same overlap structure (submit returns immediately, `wait` joins
// completions), portable to any TPU-VM local SSD.
//
// C ABI for ctypes.

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include <fcntl.h>
#include <unistd.h>

namespace {

struct IoOp {
    bool write;
    void* buf;
    std::string path;
    int64_t nbytes;
    int64_t file_offset;
};

struct AioHandle {
    std::vector<std::thread> workers;
    std::deque<IoOp> queue;
    std::mutex mu;
    std::condition_variable cv_submit;
    std::condition_variable cv_done;
    int64_t pending = 0;
    int64_t errors = 0;
    int block_size;
    bool use_o_direct;
    bool stop = false;

    explicit AioHandle(int n_threads, int block, bool o_direct)
        : block_size(block > 0 ? block : (1 << 20)), use_o_direct(o_direct) {
        for (int i = 0; i < n_threads; ++i) {
            workers.emplace_back([this] { this->run(); });
        }
    }

    ~AioHandle() {
        {
            std::lock_guard<std::mutex> lk(mu);
            stop = true;
        }
        cv_submit.notify_all();
        for (auto& t : workers) t.join();
    }

    void submit(IoOp op) {
        {
            std::lock_guard<std::mutex> lk(mu);
            queue.push_back(std::move(op));
            ++pending;
        }
        cv_submit.notify_one();
    }

    // Block until all submitted ops complete; returns count of failed ops.
    int64_t wait() {
        std::unique_lock<std::mutex> lk(mu);
        cv_done.wait(lk, [this] { return pending == 0; });
        int64_t e = errors;
        errors = 0;
        return e;
    }

    void run() {
        for (;;) {
            IoOp op;
            {
                std::unique_lock<std::mutex> lk(mu);
                cv_submit.wait(lk, [this] { return stop || !queue.empty(); });
                if (stop && queue.empty()) return;
                op = std::move(queue.front());
                queue.pop_front();
            }
            bool ok = execute(op);
            {
                std::lock_guard<std::mutex> lk(mu);
                if (!ok) ++errors;
                if (--pending == 0) cv_done.notify_all();
            }
        }
    }

    bool execute(const IoOp& op) {
        int flags = op.write ? (O_WRONLY | O_CREAT) : O_RDONLY;
#ifdef O_DIRECT
        if (use_o_direct) flags |= O_DIRECT;
#endif
        int fd = ::open(op.path.c_str(), flags, 0644);
#ifdef O_DIRECT
        if (fd < 0 && use_o_direct) {  // fs may not support O_DIRECT
            flags &= ~O_DIRECT;
            fd = ::open(op.path.c_str(), flags, 0644);
        }
#endif
        if (fd < 0) return false;
        char* p = static_cast<char*>(op.buf);
        int64_t remaining = op.nbytes;
        int64_t off = op.file_offset;
        bool ok = true;
        while (remaining > 0) {
            int64_t chunk = remaining < block_size ? remaining : block_size;
            ssize_t r = op.write ? ::pwrite(fd, p, chunk, off)
                                 : ::pread(fd, p, chunk, off);
            if (r <= 0) {
                ok = false;
                break;
            }
            p += r;
            off += r;
            remaining -= r;
        }
        ::close(fd);
        return ok;
    }
};

}  // namespace

extern "C" {

void* aio_handle_create(int n_threads, int block_size, int o_direct) {
    if (n_threads <= 0) n_threads = 1;
    return new AioHandle(n_threads, block_size, o_direct != 0);
}

void aio_handle_destroy(void* h) {
    delete static_cast<AioHandle*>(h);
}

// async=0 blocks until THIS op (and all prior pending) completes.
int aio_pwrite(void* h, const void* buf, const char* path, int64_t nbytes,
               int64_t file_offset, int async_mode) {
    auto* handle = static_cast<AioHandle*>(h);
    handle->submit(IoOp{true, const_cast<void*>(buf), path, nbytes,
                        file_offset});
    if (!async_mode) return static_cast<int>(handle->wait());
    return 0;
}

int aio_pread(void* h, void* buf, const char* path, int64_t nbytes,
              int64_t file_offset, int async_mode) {
    auto* handle = static_cast<AioHandle*>(h);
    handle->submit(IoOp{false, buf, path, nbytes, file_offset});
    if (!async_mode) return static_cast<int>(handle->wait());
    return 0;
}

// wait for all pending ops; returns number of failed ops (0 = success).
int aio_wait(void* h) {
    return static_cast<int>(static_cast<AioHandle*>(h)->wait());
}

}  // extern "C"
