// Contiguous flatten/unflatten of tensor lists (byte-level).
//
// Equivalent of the reference's flatten_unflatten extension
// (/root/reference/csrc/utils/flatten_unflatten.cpp:21-24, backed by
// torch's _flatten_dense_tensors): packs N host buffers into one
// contiguous arena and back, OpenMP-parallel across tensors. Used by the
// offload runtime to stage shards for aio writes and host optimizer steps.

#include <cstdint>
#include <cstring>

extern "C" {

void ds_flatten(int64_t n_tensors,
                const void** srcs,
                const int64_t* nbytes,
                void* out) {
    int64_t offset = 0;
    // prefix offsets first (cheap), copies in parallel
    int64_t* offs = new int64_t[n_tensors];
    for (int64_t i = 0; i < n_tensors; ++i) {
        offs[i] = offset;
        offset += nbytes[i];
    }
#pragma omp parallel for schedule(dynamic)
    for (int64_t i = 0; i < n_tensors; ++i) {
        memcpy(static_cast<char*>(out) + offs[i], srcs[i],
               static_cast<size_t>(nbytes[i]));
    }
    delete[] offs;
}

void ds_unflatten(int64_t n_tensors,
                  void** dsts,
                  const int64_t* nbytes,
                  const void* flat) {
    int64_t offset = 0;
    int64_t* offs = new int64_t[n_tensors];
    for (int64_t i = 0; i < n_tensors; ++i) {
        offs[i] = offset;
        offset += nbytes[i];
    }
#pragma omp parallel for schedule(dynamic)
    for (int64_t i = 0; i < n_tensors; ++i) {
        memcpy(dsts[i], static_cast<const char*>(flat) + offs[i],
               static_cast<size_t>(nbytes[i]));
    }
    delete[] offs;
}

}  // extern "C"
