"""BERT MLM pretraining with ZeRO-2 / 1-bit Adam compressed allreduce —
mirrors the BERT-large + 1-bit Adam recipe (BASELINE.json config 3).

1-bit mode (the compressed wire path) needs ZeRO stage 0 and gas 1 (the
same constraints as the reference implementation); pass --dense for the
ZeRO-2 dense-reduction variant.

    python examples/bert_onebit.py [--dense] [--steps 40]
"""

from __future__ import annotations

import argparse

from common import print_curve  # noqa: E402

import numpy as np

import jax

import deepspeed_tpu
from deepspeed_tpu.models import Bert, bert_config


def mlm_batches(steps, batch, seq, vocab, mask_id=1, seed=0):
    """Strided token sequences (next = prev + stride): masked positions
    are recoverable from context, so the MLM loss actually falls."""
    rng = np.random.RandomState(seed)
    for _ in range(steps):
        ids = np.zeros((batch, seq), np.int64)
        ids[:, 0] = rng.randint(4, vocab, batch)
        stride = rng.randint(1, 5, batch)
        for t in range(1, seq):
            ids[:, t] = (ids[:, t - 1] + stride - 4) % (vocab - 4) + 4
        ids = ids.astype(np.int32)
        labels = np.full((batch, seq), -100, np.int32)
        mask = rng.rand(batch, seq) < 0.15
        labels[mask] = ids[mask]
        ids = np.where(mask, mask_id, ids)
        yield {"input_ids": ids, "mlm_labels": labels}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--size", default="bert-tiny")
    ap.add_argument("--seq", type=int, default=64)
    ap.add_argument("--micro", type=int, default=4)
    ap.add_argument("--steps", type=int, default=60)
    ap.add_argument("--dense", action="store_true")
    ap.add_argument("--wire", default="sign", choices=("sign", "int8"),
                    help="compressed wire format: reference-parity sign "
                         "compression, or int8 (the one that actually "
                         "cuts XLA wire bytes ~2x)")
    args = ap.parse_args()

    n_dev = jax.device_count()
    cfg = bert_config(args.size, max_seq_len=args.seq,
                      vocab_size=64)  # tiny smoke-size task
    config = {
        "train_batch_size": args.micro * n_dev,
        "train_micro_batch_size_per_gpu": args.micro,
        "bf16": {"enabled": True},
        "mesh": {"data": n_dev},
        "steps_per_print": 10,
    }
    if args.dense:
        config["optimizer"] = {"type": "Adam", "params": {"lr": 3e-3}}
        config["zero_optimization"] = {"stage": 2}
    else:
        config["optimizer"] = {"type": "OneBitAdam",
                               "params": {"lr": 3e-3, "freeze_step": 45,
                                          "weight_decay": 0.0,
                                          "wire": args.wire}}
        config["zero_optimization"] = {"stage": 0}

    engine, _, _, _ = deepspeed_tpu.initialize(model=Bert(cfg),
                                               config_params=config)
    if not args.dense:
        assert getattr(engine, "_onebit_hot", False) or n_dev == 1, \
            "compressed hot path inactive"
    losses = []
    for batch in mlm_batches(args.steps, args.micro * n_dev, args.seq,
                             cfg.vocab_size):
        loss = engine.forward(batch)
        engine.backward()
        engine.step()
        losses.append(float(loss))
    mode = "zero2-dense" if args.dense else f"1bit-adam/{args.wire}"
    print_curve(f"{args.size} mlm {mode}", losses)
    assert min(losses[-10:]) < losses[0], losses


if __name__ == "__main__":
    main()
