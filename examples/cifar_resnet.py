"""CIFAR-10-shaped ResNet, ZeRO-0, fp32, single process — mirrors
DeepSpeedExamples/cifar (BASELINE.json config 1): the simplest
deepspeed_tpu.initialize loop, non-transformer model, no sharding.

    python examples/cifar_resnet.py [--steps 30]
"""

from __future__ import annotations

import argparse

from common import print_curve  # noqa: E402  (pins platform)

import jax
import jax.numpy as jnp
import numpy as np

import deepspeed_tpu
from deepspeed_tpu.runtime.module import TrainModule


def conv(x, w):
    return jax.lax.conv_general_dilated(
        x, w, (1, 1), "SAME", dimension_numbers=("NHWC", "HWIO", "NHWC"))


class ResNetSmall(TrainModule):
    """3-stage residual CNN (CIFAR scale)."""

    def __init__(self, width=16, num_classes=10):
        self.width = width
        self.num_classes = num_classes

    def init(self, rng):
        w = self.width
        ks = jax.random.split(rng, 8)
        he = lambda k, s: jax.random.normal(k, s) * np.sqrt(
            2.0 / (s[0] * s[1] * s[2]))
        return {
            "stem": he(ks[0], (3, 3, 3, w)),
            "blocks": [
                {"c1": he(ks[1 + 2 * i], (3, 3, w, w)),
                 "c2": he(ks[2 + 2 * i], (3, 3, w, w))}
                for i in range(3)],
            "head": jax.random.normal(ks[7],
                                      (w, self.num_classes)) * 0.01,
        }

    def apply(self, params, x, rng=None, train=False):
        h = jax.nn.relu(conv(x, params["stem"]))
        for bp in params["blocks"]:
            r = jax.nn.relu(conv(h, bp["c1"]))
            h = jax.nn.relu(h + conv(r, bp["c2"]))
        h = jnp.mean(h, axis=(1, 2))  # global average pool
        return h @ params["head"]

    def loss(self, params, batch, rng=None, train=True):
        x, y = batch
        logits = self.apply(params, x, rng=rng, train=train)
        logp = jax.nn.log_softmax(logits)
        return -jnp.mean(jnp.take_along_axis(logp, y[:, None], 1))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--batch", type=int, default=32)
    args = ap.parse_args()

    engine, _, _, _ = deepspeed_tpu.initialize(
        model=ResNetSmall(),
        config_params={
            "train_batch_size": args.batch,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 0},
            "steps_per_print": 10,
        })

    rng = np.random.RandomState(0)
    # synthetic CIFAR: class = dominant color channel (learnable)
    losses = []
    for _ in range(args.steps):
        y = rng.randint(0, 3, args.batch)
        x = rng.rand(args.batch, 32, 32, 3).astype(np.float32) * 0.2
        x[np.arange(args.batch), :, :, y] += 0.8
        loss = engine.forward((x, y.astype(np.int32)))
        engine.backward()
        engine.step()
        losses.append(float(loss))
    print_curve("cifar_resnet zero0 fp32", losses)
    assert losses[-1] < losses[0]


if __name__ == "__main__":
    main()
