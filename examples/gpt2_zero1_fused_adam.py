"""GPT-2 pretraining with ZeRO-1 + FusedAdam, mixed precision — mirrors
the Megatron-LM GPT-2 example (BASELINE.json config 2).

    python examples/gpt2_zero1_fused_adam.py                # tiny smoke
    python examples/gpt2_zero1_fused_adam.py --size small --seq 1024 \
        --micro 8    # the bench configuration (wants a real chip)
"""

from __future__ import annotations

import argparse

from common import print_curve, token_batches  # noqa: E402

import jax

import deepspeed_tpu
from deepspeed_tpu.models import GPT, gpt2_config


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--size", default="nano")
    ap.add_argument("--seq", type=int, default=128)
    ap.add_argument("--micro", type=int, default=4)
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--gas", type=int, default=2,
                    help="gradient accumulation steps (the scan-fused "
                    "train_batch path compiles the whole global batch)")
    args = ap.parse_args()

    n_dev = jax.device_count()
    cfg = gpt2_config(args.size, max_seq_len=args.seq,
                      shard_activations=n_dev > 1)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=GPT(cfg),
        config_params={
            "train_batch_size": args.micro * n_dev * args.gas,
            "train_micro_batch_size_per_gpu": args.micro,
            "gradient_accumulation_steps": args.gas,
            "bf16": {"enabled": True},
            "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
            "scheduler": {"type": "WarmupLR",
                          "params": {"warmup_max_lr": 1e-4,
                                     "warmup_num_steps": 100}},
            "zero_optimization": {"stage": 1},
            "mesh": {"data": n_dev},
            "steps_per_print": 10,
        })

    data = token_batches(args.steps * args.gas, args.micro * n_dev,
                         args.seq, cfg.vocab_size)
    losses = []
    for _ in range(args.steps):
        losses.append(float(engine.train_batch(data)))
    print_curve(f"gpt2-{args.size} zero1 bf16 (gas={args.gas})", losses)
    assert losses[-1] < losses[0]


if __name__ == "__main__":
    main()
