"""GPT-2 medium-style pipeline parallelism + sparse attention — mirrors
BASELINE.json config 5 (deepspeed.pipe + sparse_attention kernel). Two
pipeline executors are exercised:

* --executor spmd: stacked blocks compiled as a GPipe scan over the
  `pipe` mesh axis (one jitted program);
* --executor 1f1b: the TrainSchedule instruction-stream PipelineEngine
  over heterogeneous LayerSpec stages (tied embeddings, per-stage device
  groups).

Sparse attention (Fixed layout) runs inside the SPMD variant's blocks.

    python examples/gpt2_pipeline_sparse.py --executor spmd
    python examples/gpt2_pipeline_sparse.py --executor 1f1b
"""

from __future__ import annotations

import argparse

from common import print_curve, token_batches  # noqa: E402

import jax
import jax.numpy as jnp
import numpy as np

import deepspeed_tpu
from deepspeed_tpu.models import GPT, gpt2_config


def run_spmd(args, n_dev):
    cfg = gpt2_config("nano", num_layers=4, max_seq_len=args.seq,
                      pipeline_stages=2, pipeline_micro_batches=2,
                      shard_activations=True)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=GPT(cfg),
        config_params={
            "train_batch_size": args.micro * (n_dev // 2),
            "train_micro_batch_size_per_gpu": args.micro,
            "bf16": {"enabled": True},
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 1},
            "mesh": {"data": n_dev // 2, "pipe": 2},
            "steps_per_print": 10,
        })
    losses = []
    for batch in token_batches(args.steps, args.micro * (n_dev // 2),
                               args.seq, cfg.vocab_size):
        loss = engine.forward(batch)
        engine.backward()
        engine.step()
        losses.append(float(loss))
    return "pipeline spmd-gpipe", losses


def run_1f1b(args, n_dev):
    from deepspeed_tpu.ops.sparse_attention import (FixedSparsityConfig,
                                                    SparseSelfAttention)
    from deepspeed_tpu.runtime.pipe.module import (LayerSpec,
                                                   PipelineModule,
                                                   TiedLayerSpec)

    V, Dm, Hh = 128, 32, 2
    # unidirectional: this is a next-token LM — bidirectional layouts
    # would let position t attend to its own label at t+1
    ssa = SparseSelfAttention(FixedSparsityConfig(
        num_heads=Hh, block=16, num_local_blocks=2, num_global_blocks=1,
        attention="unidirectional"))

    class Embed:
        def init(self, rng):
            return {"w": jax.random.normal(rng, (V, Dm)) * 0.05}

        def apply(self, p, x, rng=None, train=True):
            return p["w"][x]

    class SparseBlock:
        """Attention block whose scores follow the sparse layout."""

        def init(self, rng):
            k1, k2 = jax.random.split(rng)
            return {"qkv": jax.random.normal(k1, (Dm, 3 * Dm)) * 0.05,
                    "proj": jax.random.normal(k2, (Dm, Dm)) * 0.05}

        def apply(self, p, x, rng=None, train=True):
            B, S, _ = x.shape
            qkv = x @ p["qkv"]
            q, k, v = jnp.split(qkv, 3, axis=-1)
            split = lambda t: t.reshape(B, S, Hh, Dm // Hh)
            a = ssa(split(q), split(k), split(v)).reshape(B, S, Dm)
            return x + a @ p["proj"]

    def head(layer, p, x):
        return x @ p["w"].T

    def ce(logits, labels):
        lp = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
        return -jnp.mean(jnp.take_along_axis(lp, labels[..., None], -1))

    mod = PipelineModule(
        [TiedLayerSpec("emb", Embed)]
        + [LayerSpec(SparseBlock) for _ in range(3)]
        + [TiedLayerSpec("emb", Embed, forward_fn=head)],
        num_stages=2, loss_fn=ce, interleave=args.interleave)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=mod,
        config_params={
            "train_batch_size": args.micro * 4,
            "train_micro_batch_size_per_gpu": args.micro,
            "gradient_accumulation_steps": 4,
            "optimizer": {"type": "Adam", "params": {"lr": 3e-3}},
            "mesh": {"data": 1, "pipe": -1},
            "steps_per_print": 10,
        })
    assert engine._staged
    losses = []
    for step in range(args.steps):
        data = list(token_batches(4, args.micro, args.seq, V,
                                  seed=step))
        losses.append(float(engine.train_batch(iter(data))))
    name = "pipeline 1f1b"
    if args.interleave > 1:
        name += f" x{args.interleave} interleaved"
    return name + " + sparse-attn", losses


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--executor", choices=["spmd", "1f1b"], default="1f1b")
    ap.add_argument("--interleave", type=int, default=1,
                    help="virtual model chunks per stage (1f1b executor)")
    ap.add_argument("--seq", type=int, default=64)
    ap.add_argument("--micro", type=int, default=4)
    ap.add_argument("--steps", type=int, default=25)
    args = ap.parse_args()
    n_dev = jax.device_count()
    if n_dev < 4:
        raise SystemExit(
            f"this example needs >= 4 devices (got {n_dev}); run with "
            f"XLA_FLAGS=--xla_force_host_platform_device_count=8 "
            f"JAX_PLATFORMS=cpu for a virtual mesh")
    name, losses = (run_spmd if args.executor == "spmd" else run_1f1b)(
        args, n_dev)
    print_curve(name, losses)
    assert losses[-1] < losses[0]


if __name__ == "__main__":
    main()
