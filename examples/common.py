"""Shared example plumbing: platform pinning + synthetic data."""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

import jax  # noqa: E402

if os.environ.get("JAX_PLATFORMS") == "cpu":
    # the baked sitecustomize pins the TPU platform programmatically; the
    # env var alone is too late (same dance as tests/conftest.py)
    jax.config.update("jax_platforms", "cpu")


def token_batches(steps, batch, seq, vocab, seed=0):
    import numpy as np

    rng = np.random.RandomState(seed)
    for _ in range(steps):
        toks = np.zeros((batch, seq + 1), np.int32)
        toks[:, 0] = rng.randint(0, vocab, batch)
        stride = rng.randint(1, 5, batch)
        for t in range(1, seq + 1):
            toks[:, t] = (toks[:, t - 1] + stride) % vocab
        yield toks[:, :-1], toks[:, 1:]


def print_curve(name, losses):
    head = " ".join(f"{l:.3f}" for l in losses[:3])
    tail = " ".join(f"{l:.3f}" for l in losses[-3:])
    print(f"{name}: {head} ... {tail}")
