"""Long-context training via sequence parallelism — the beyond-parity
capability (the reference's long-sequence story is block-sparse
attention only). One GPT, three SP implementations:

    --impl ring         exact ring attention (ppermute K/V rotation)
    --impl ring_zigzag  load-balanced causal ring (~2x fewer FLOPs)
    --impl ulysses      all-to-all head resharding (flash kernel intact)

    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python examples/gpt2_long_context.py --impl ring_zigzag --seq 1024
"""

from __future__ import annotations

import argparse
import time

from common import print_curve, token_batches  # noqa: E402  (pins platform)

import jax
import numpy as np

import deepspeed_tpu
from deepspeed_tpu.models import GPT, gpt2_config


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--impl", default="ring_zigzag",
                    choices=("ring", "ring_zigzag", "ulysses"))
    ap.add_argument("--seq", type=int, default=1024)
    ap.add_argument("--steps", type=int, default=8)
    ap.add_argument("--block-q", type=int, default=0,
                    help="bound ring score memory per step (0 = off)")
    args = ap.parse_args()

    n_dev = jax.device_count()
    # largest divisor of the device count <= 4, so the mesh covers
    # every device at any world size
    sp = max(d for d in (1, 2, 3, 4) if n_dev % d == 0)
    dp = n_dev // sp
    cfg = gpt2_config("nano", vocab_size=512, max_seq_len=args.seq,
                      dropout=0.0, embed_dropout=0.0,
                      sequence_parallel=True,
                      sequence_parallel_impl=args.impl,
                      flash_block_q=args.block_q,
                      shard_activations=True)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=GPT(cfg),
        config_params={
            "train_batch_size": 2 * dp,
            "bf16": {"enabled": True},
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 2},
            "mesh": {"data": dp, "seq": sp},
            "steps_per_print": 0,
        })
    losses, t0 = [], time.perf_counter()
    for batch in token_batches(args.steps, 2 * dp, args.seq, 512):
        loss = engine.forward(batch)
        engine.backward()
        engine.step()
        losses.append(float(loss))
    dt = time.perf_counter() - t0
    print_curve(f"gpt2-nano S={args.seq} sp={sp} {args.impl}", losses)
    print(f"{args.steps} steps in {dt:.1f}s "
          f"({args.steps * 2 * dp * args.seq / dt:.0f} tokens/s)")
    assert losses[-1] < losses[0] and np.isfinite(losses).all()


if __name__ == "__main__":
    main()
