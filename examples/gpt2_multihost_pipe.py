"""Multi-host pipeline parallelism: one physical stage per process,
activations/grads crossing process boundaries through p2p.Channel
collectives (the NCCL-p2p analogue; reference pipe/p2p.py:31-75).

Run as N cooperating processes (this script self-launches them on one
machine for the demo; on a real pod each host runs one process under
`jax.distributed`):

    JAX_PLATFORMS=cpu python examples/gpt2_multihost_pipe.py --procs 2

Or exercise the identical channel executor single-process on the
virtual mesh:

    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python examples/gpt2_multihost_pipe.py --single
"""

from __future__ import annotations

import argparse
import os
import socket
import subprocess
import sys

from common import print_curve, token_batches  # noqa: E402  (pins platform)

V, D = 128, 32
MICRO, M = 4, 4


def build_module(num_stages):
    import jax
    import jax.numpy as jnp

    from deepspeed_tpu.runtime.pipe.module import (LayerSpec,
                                                   PipelineModule,
                                                   TiedLayerSpec)

    class Embed:
        def init(self, rng):
            return {"w": jax.random.normal(rng, (V, D)) * 0.05}

        def apply(self, p, x, rng=None, train=True):
            return p["w"][x]

    class Block:
        def __init__(self, ff):
            self.ff = ff

        def init(self, rng):
            k1, k2 = jax.random.split(rng)
            return {"a": jax.random.normal(k1, (D, self.ff)) * 0.05,
                    "b": jax.random.normal(k2, (self.ff, D)) * 0.05}

        def apply(self, p, x, rng=None, train=True):
            return x + jnp.tanh(x @ p["a"]) @ p["b"]

    def head(layer, p, x):
        return x @ p["w"].T

    def ce(logits, labels):
        lp = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
        return -jnp.mean(jnp.take_along_axis(lp, labels[..., None], -1))

    return PipelineModule(
        [TiedLayerSpec("emb", Embed)]
        + [LayerSpec(Block, ff) for ff in (48, 64, 48)]
        + [TiedLayerSpec("emb", Embed, forward_fn=head)],
        num_stages=num_stages, loss_fn=ce)


def config(use_channels=False):
    c = {"train_batch_size": MICRO * M,
         "train_micro_batch_size_per_gpu": MICRO,
         "gradient_accumulation_steps": M,
         "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
         "gradient_clipping": 1.0,
         "mesh": {"data": 1, "pipe": -1},
         "steps_per_print": 0}
    if use_channels:
        c["pipeline"] = {"use_p2p_channels": True}
    return c


def worker(proc_id, nprocs, coord, steps):
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(coordinator_address=coord,
                               num_processes=nprocs, process_id=proc_id)
    import deepspeed_tpu

    engine, *_ = deepspeed_tpu.initialize(
        model=build_module(nprocs), dist_init_required=False,
        config_params=config())
    assert engine._mh, "multi-host pipe mode inactive"
    losses = []
    for step in range(steps):
        batches = list(token_batches(M, MICRO, 12, V, seed=step))
        losses.append(float(engine.train_batch(iter(batches))))
    if proc_id == 0:
        print_curve(f"mh-pipe (stage {proc_id}/{nprocs})", losses)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--procs", type=int, default=2)
    ap.add_argument("--steps", type=int, default=5)
    ap.add_argument("--single", action="store_true",
                    help="channel executor on local device groups")
    ap.add_argument("--_worker", type=int, default=None)
    ap.add_argument("--_coord", default=None)
    args = ap.parse_args()

    if args._worker is not None:
        worker(args._worker, args.procs, args._coord, args.steps)
        return

    if args.single:
        import deepspeed_tpu

        engine, *_ = deepspeed_tpu.initialize(
            model=build_module(2), config_params=config(use_channels=True))
        assert engine._mh
        losses = []
        for step in range(args.steps):
            batches = list(token_batches(M, MICRO, 12, V, seed=step))
            losses.append(float(engine.train_batch(iter(batches))))
        print_curve("mh-pipe channels (single-process)", losses)
        return

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    coord = f"127.0.0.1:{s.getsockname()[1]}"
    s.close()
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    procs = [subprocess.Popen(
        [sys.executable, os.path.abspath(__file__),
         "--procs", str(args.procs), "--steps", str(args.steps),
         "--_worker", str(i), "--_coord", coord], env=env)
        for i in range(args.procs)]
    try:
        # a dead worker leaves the others blocked in collectives — bound
        # the wait and kill the stragglers so the demo can't hang
        rc = [p.wait(timeout=600) for p in procs]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    assert all(r == 0 for r in rc), rc
    print("all processes done")


if __name__ == "__main__":
    main()
