"""GPT-2 with ZeRO-3 parameter partitioning + CPU/NVMe offload — mirrors
the GPT-2 1.5B ZeRO-3 offload recipe (BASELINE.json config 4) via the
ZeRO-Infinity streaming runtime: parameters live in host RAM (moments
optionally on NVMe through the native aio engine) and stream through the
device one block at a time, so the model need not fit in HBM.

    python examples/gpt2_zero3_offload.py                  # tiny smoke
    python examples/gpt2_zero3_offload.py --nvme /tmp/nv   # moments on SSD
    python examples/gpt2_zero3_offload.py --size xl --seq 1024  # 1.5B
"""

from __future__ import annotations

import argparse

from common import print_curve, token_batches  # noqa: E402

import deepspeed_tpu
from deepspeed_tpu.models import GPT, gpt2_config


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--size", default="nano")
    ap.add_argument("--seq", type=int, default=64)
    ap.add_argument("--micro", type=int, default=8)
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--nvme", default=None,
                    help="page Adam moments to this path via the aio engine")
    args = ap.parse_args()

    offload = {"device": "nvme", "nvme_path": args.nvme} if args.nvme \
        else {"device": "cpu"}
    cfg = gpt2_config(args.size, max_seq_len=args.seq,
                      shard_activations=False)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=GPT(cfg),
        config_params={
            "train_batch_size": args.micro,
            "bf16": {"enabled": True},
            "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
            "zero_optimization": {"stage": 3, "offload_param": offload},
            "mesh": {"data": -1},
            "steps_per_print": 5,
        })
    assert engine._infinity is not None
    print(f"streaming {engine._infinity.n_elements / 1e6:.1f}M params "
          f"from host ({'NVMe moments' if args.nvme else 'RAM'})")

    losses = []
    for batch in token_batches(args.steps, args.micro, args.seq,
                               cfg.vocab_size):
        loss = engine.forward(batch)
        engine.backward()
        engine.step()
        losses.append(float(loss))
    print_curve(f"gpt2-{args.size} zero3-infinity", losses)
    assert losses[-1] < losses[0]


if __name__ == "__main__":
    main()
