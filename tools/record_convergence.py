"""Record the pinned convergence baseline (tests/convergence/*.json).

Mirrors the reference's pinned-curve methodology
(/root/reference/tests/model/Megatron_GPT2/run_func_test.py:20-36: fixed
config, fixed seed, assert the metric within tolerance). Run on the 8-device
CPU mesh — the same environment the regression test uses:

    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python tools/record_convergence.py
"""

from __future__ import annotations

import json
import os
import sys

_ROOT = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")
sys.path.insert(0, _ROOT)
sys.path.insert(0, os.path.join(_ROOT, "tests"))

os.environ.setdefault(
    "XLA_FLAGS", "--xla_force_host_platform_device_count=8")
import jax  # noqa: E402

# the ambient sitecustomize pins the axon TPU platform programmatically —
# the JAX_PLATFORMS env var alone is too late (same dance as conftest.py)
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_threefry_partitionable", True)

from convergence_common import run_curve, BASELINE_PATH, CONFIG  # noqa: E402


def main():
    losses = run_curve()
    os.makedirs(os.path.dirname(BASELINE_PATH), exist_ok=True)
    with open(BASELINE_PATH, "w") as f:
        json.dump({"config": CONFIG, "losses": losses}, f, indent=1)
    print(f"wrote {BASELINE_PATH}: first={losses[0]:.4f} "
          f"last={losses[-1]:.4f}")


if __name__ == "__main__":
    main()
