"""ZeRO-Infinity capacity report: max params/chip (BASELINE.json axis).

For a GPT config, prints total parameters, the device-resident working
set under streaming (embed + head resident, 2 blocks double-buffered,
saved block inputs), and host bytes (fp32 masters + Adam moments), then
the implied max model size for a given HBM/host budget. With --step it
also runs one real streamed step to prove the config executes.

Usage:
  python tools/infinity_capacity.py --size xl --seq 1024 --micro 8
  python tools/infinity_capacity.py --size xl --hbm-gb 16 --host-gb 256
"""

from __future__ import annotations

import argparse
import os
import sys

_ROOT = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")
sys.path.insert(0, _ROOT)

import jax  # noqa: E402

if os.environ.get("JAX_PLATFORMS") == "cpu":
    # sitecustomize pins the TPU platform programmatically; honoring the
    # env var needs the config override too (same dance as conftest)
    jax.config.update("jax_platforms", "cpu")


def report(size, seq, micro, hbm_gb, host_gb, run_step=False,
           nvme_path=None):
    from deepspeed_tpu.models import GPT, gpt2_config

    cfg = gpt2_config(size, max_seq_len=seq)
    model = GPT(cfg)
    n = model.num_params()
    d, L, V = cfg.d_model, cfg.num_layers, cfg.vocab_size
    block_params = 12 * d * d + 13 * d  # qkv/proj/fc1/fc2 + ln/biases
    embed_params = V * d + seq * d
    wire = 2  # bf16 bytes
    resident = (embed_params + d * 2) * wire          # embed + head ln
    stream = 2 * block_params * wire                  # double buffer
    acts = (L + 1) * micro * seq * d * wire           # saved block inputs
    ce = micro * seq // max(1, micro * seq // 2048) * V * 4  # one CE chunk
    device = resident + stream + acts + ce
    host = n * 4 * 3  # fp32 masters + m + v
    print(f"gpt2-{size}: {n/1e9:.3f}B params, {L} layers, d={d}, seq={seq},"
          f" micro={micro}")
    print(f"  device working set : {device/2**30:.2f} GiB "
          f"(embed+head {resident/2**30:.2f}, 2-block stream "
          f"{stream/2**30:.3f}, activations {acts/2**30:.2f}, CE chunk "
          f"{ce/2**30:.2f})")
    print(f"  host masters+Adam  : {host/2**30:.2f} GiB")
    print(f"  resident-engine HBM would need ~{n*(4+4+8)/2**30:.1f} GiB "
          f"(fp32 master+grad+moments) + activations")
    # implied capacity: params bounded by host RAM at 12 B/param; device
    # side bounded by activations+embed only (blocks stream)
    host_cap = host_gb * 2**30 / 12
    print(f"  max params/chip    : ~{host_cap/1e9:.0f}B with {host_gb} GiB "
          f"host RAM (12 B/param host-side; device holds "
          f"{device/2**30:.2f} GiB << {hbm_gb} GiB HBM)")
    biggest_group = max(block_params, embed_params) * 4
    print(f"  with --nvme        : host RAM holds ~2 groups "
          f"({2 * biggest_group/2**30:.2f} GiB) + grad sink "
          f"({n*4/2**30:.2f} GiB); masters+moments page to SSD — "
          f"capacity is NVMe-bounded, not RAM-bounded")
    if run_step:
        import resource

        import numpy as np

        import deepspeed_tpu

        dev = ({"device": "nvme", "nvme_path": nvme_path}
               if nvme_path else {"device": "cpu"})
        engine, *_ = deepspeed_tpu.initialize(model=model, config_params={
            "train_batch_size": micro,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
            "zero_optimization": {"stage": 3, "offload_param": dev},
            "bf16": {"enabled": True},
            "mesh": {"data": 1},
            "steps_per_print": 0})
        rng = np.random.RandomState(0)
        tok = rng.randint(0, cfg.vocab_size, (micro, seq + 1)).astype("i4")
        loss = engine.forward((tok[:, :-1], tok[:, 1:]))
        engine.backward()
        engine.step()
        rss = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
        where = "NVMe-paged masters" if nvme_path else "RAM masters"
        print(f"  one streamed step  : loss={float(loss):.3f} OK "
              f"({where}); peak RSS {rss/2**30:.2f} GiB vs "
              f"{host/2**30:.2f} GiB masters+moments")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--size", default="xl")
    ap.add_argument("--seq", type=int, default=1024)
    ap.add_argument("--micro", type=int, default=8)
    ap.add_argument("--hbm-gb", type=float, default=16)
    ap.add_argument("--host-gb", type=float, default=256)
    ap.add_argument("--step", action="store_true")
    ap.add_argument("--nvme", default=None,
                    help="page fp32 masters+moments to this SSD path "
                         "(capacity becomes NVMe-bounded)")
    args = ap.parse_args()
    report(args.size, args.seq, args.micro, args.hbm_gb, args.host_gb,
           run_step=args.step, nvme_path=args.nvme)


if __name__ == "__main__":
    main()
