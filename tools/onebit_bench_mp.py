"""1-bit/int8 Adam wire measurement across a REAL serialization boundary.

The single-process CPU-mesh bench (tools/onebit_bench.py) cannot see
wire effects — all "collectives" are memory movement inside one address
space. Here N jax.distributed processes on localhost talk over TCP, so
cross-process collective payloads pay a real byte-proportional
serialize/send/deserialize cost: the first fabric where "fewer bytes"
can actually buy "less time" (VERDICT r4 weak #3).

Two measurements per wire variant {dense fp32, bucketed fp32, bucketed
blockwise-int8 (dense Adam semantics, comm/quant.py), sign, onebit
int8}:
  1. engine step time (median) — end-to-end through the fused hot path;
  2. a bare cross-process mean of an n_params-sized payload at the
     variant's wire dtype — isolates the transport from optimizer FLOPs.

Reference twin: tests/onebit/test_nccl_perf.py (NCCL compressed_allreduce
vs torch.distributed.all_reduce over sockets).

Usage: python tools/onebit_bench_mp.py [--nproc 2] [--steps 20]
           [--size nano] [--seq 32]
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import subprocess
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.join(HERE, ".."))


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def worker(args):
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(coordinator_address=args.coord,
                               num_processes=args.nproc,
                               process_id=args.proc_id)
    import numpy as np

    import deepspeed_tpu
    from deepspeed_tpu.models import GPT, gpt2_config

    dp = jax.device_count()
    cfg_base = {
        "train_batch_size": dp,
        "zero_optimization": {"stage": 0},
        "mesh": {"data": dp},
        "steps_per_print": 0,
    }
    model_cfg = gpt2_config(args.size, vocab_size=512,
                            max_seq_len=args.seq, dropout=0.0,
                            embed_dropout=0.0)
    n_params = GPT(model_cfg).num_params()
    rng = np.random.RandomState(0)  # identical stream on every process
    tok = rng.randint(0, 512, (dp, args.seq + 1)).astype(np.int32)
    batch = (tok[:, :-1], tok[:, 1:])

    def run(opt, wire):
        params = {"lr": 1e-4, "weight_decay": 0.0}
        if opt == "OneBitAdam":
            params["freeze_step"] = 8
            params["wire"] = wire
        cfg = dict(cfg_base)
        if wire == "bucketed":
            # dense Adam through the fused grad-wire buckets
            # (runtime/comm/bucketing.py) instead of per-leaf psums
            cfg["comm"] = {"gradient_reduction": "bucketed"}
        elif wire == "bucketed_int8":
            # dense Adam semantics over the blockwise-quantized gather
            # wire (comm/quant.py): ~1 byte/elem + fp16 scales, fp32
            # accumulation — the dense-algorithm counterpart to the
            # 1-bit optimizer's error-feedback int8 momentum wire
            cfg["comm"] = {"gradient_reduction": "bucketed",
                           "wire_dtype": "int8"}
        cfg["optimizer"] = {"type": opt, "params": params}
        engine, *_ = deepspeed_tpu.initialize(
            model=GPT(model_cfg), dist_init_required=False,
            config_params=cfg)
        if opt == "OneBitAdam":
            assert getattr(engine, "_onebit_hot", False)
        if wire.startswith("bucketed"):
            assert engine.bucket_plan is not None
        for _ in range(12):  # compile + freeze_step crossing
            engine.forward(batch); engine.backward(); engine.step()
        t = []
        for _ in range(args.steps):
            t0 = time.perf_counter()
            loss = engine.forward(batch)
            engine.backward(); engine.step()
            loss.block_until_ready()
            t.append(time.perf_counter() - t0)
        return float(np.median(t)), float(loss)

    results = {}
    for opt, wire in [("Adam", "dense"), ("Adam", "bucketed"),
                      ("Adam", "bucketed_int8"),
                      ("OneBitAdam", "sign"), ("OneBitAdam", "int8")]:
        sec, loss = run(opt, wire)
        results[wire] = {"step_ms": round(sec * 1e3, 2),
                         "loss": round(loss, 4)}

    # bare transport: cross-process mean of an n_params payload at each
    # wire dtype (the isolated bytes-vs-time curve)
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    import jax.numpy as jnp

    devs = sorted(jax.devices(), key=lambda d: (d.process_index, d.id))
    per = len(devs) // args.nproc
    mesh = Mesh(np.array(devs).reshape(args.nproc, per), ("proc", "dev"))
    row = NamedSharding(mesh, P("proc"))
    out = NamedSharding(mesh, P())
    # all-gather semantics (identity resharding P("proc") -> replicated):
    # the wire carries the RAW dtype, exactly like the int8 optimizer's
    # all_to_all+all_gather phases.  (An arithmetic reduce would upcast
    # before the transfer and measure fp32 bytes regardless.)
    for elems in [n_params, 1 << 22, 1 << 24]:  # find the byte-bound knee
        for name, dt in [("fp32", np.float32), ("int8", np.int8)]:
            local = np.ones((1, elems), dt)
            garr = jax.make_array_from_process_local_data(
                row, local, (args.nproc, elems))
            red = jax.jit(lambda x: x, out_shardings=out)
            red(garr).block_until_ready()  # compile
            t = []
            for _ in range(max(10, args.steps)):
                t0 = time.perf_counter()
                red(garr).block_until_ready()
                t.append(time.perf_counter() - t0)
            results[f"gather_{name}_{elems}"] = {
                "ms": round(float(np.median(t)) * 1e3, 3),
                "payload_bytes": int(elems * np.dtype(dt).itemsize)}

    if args.proc_id == 0:
        print(json.dumps({
            "metric": "onebit_wire_2proc_tcp",
            "platform": "cpu",
            "n_params": int(n_params),
            "world": {"processes": args.nproc, "devices": dp},
            **results,
        }), flush=True)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--nproc", type=int, default=2)
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--size", default="nano")
    ap.add_argument("--seq", type=int, default=32)
    ap.add_argument("--worker", action="store_true")
    ap.add_argument("--proc-id", dest="proc_id", type=int, default=0)
    ap.add_argument("--coord", default="")
    args = ap.parse_args()
    if args.worker:
        worker(args)
        return
    coord = f"127.0.0.1:{_free_port()}"
    procs = []
    for pid in range(args.nproc):
        procs.append(subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--worker",
             "--proc-id", str(pid), "--coord", coord,
             "--nproc", str(args.nproc), "--steps", str(args.steps),
             "--size", args.size, "--seq", str(args.seq)],
            stdout=subprocess.PIPE if pid == 0 else subprocess.DEVNULL,
            stderr=subprocess.STDOUT if pid == 0 else subprocess.DEVNULL,
            env={**os.environ, "JAX_PLATFORMS": "cpu"}))
    out, _ = procs[0].communicate(timeout=3600)
    for p in procs[1:]:
        p.wait(timeout=60)
    out = out.decode()
    sys.stdout.write(out)
    if any(p.returncode for p in procs):
        sys.exit(1)
    # durable artifact under bench_artifacts/runs/ + manifest (the PR-2
    # rule bench.py follows); the printed JSON stays the primary output
    try:
        line = next(ln for ln in out.splitlines()
                    if ln.startswith("{") and "metric" in ln)
        from deepspeed_tpu.monitor.artifacts import record_bench_result

        path = record_bench_result(json.loads(line))
        print(f"recorded: {path}", file=sys.stderr)
    except Exception as e:
        print(f"artifact recording failed: {e}", file=sys.stderr)


if __name__ == "__main__":
    main()
