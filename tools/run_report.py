#!/usr/bin/env python
"""Render a telemetry run (monitor/ JSONL event stream) as a BENCH.md-
style markdown report.

Usage:
    python tools/run_report.py runs/my_run            # a run directory
    python tools/run_report.py runs/my_run -o rep.md  # write to a file
    python tools/run_report.py --selftest             # synthetic round-trip

The run directory is what `{"monitor": {"enabled": true}}` produces:
manifest.json + events.rank*.jsonl (+ summaries).  `--selftest` writes a
synthetic run through the real writer and renders it back — a smoke for
the whole schema path with no engine involved.
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def selftest() -> int:
    import tempfile

    from deepspeed_tpu.monitor import (COUNTERS, DeepSpeedMonitorConfig,
                                       RunMonitor)
    from deepspeed_tpu.monitor.report import (load_run, render_markdown,
                                              summarize, validate_event)

    with tempfile.TemporaryDirectory() as root:
        cfg = DeepSpeedMonitorConfig({"monitor": {
            "enabled": True, "output_path": root, "job_name": "selftest",
            "flush_interval": 1, "tokens_per_sample": 128}})
        mon = RunMonitor(cfg, rank=0, world=1)
        for step in range(1, 4):
            mon.step_start(step - 1)
            COUNTERS.add("p2p.send", 1024)
            # hierarchical grad-wire levels: fast-fabric legs + the
            # slow-fabric shard hop (report renders them as their own
            # per-level section)
            COUNTERS.add("grad_wire.intra", 8192, calls=2)
            COUNTERS.add("grad_wire.inter", 1024, calls=1)
            # comm/compute overlap: exposed wire µs (the ckpt.stall_ms
            # µs-in-bytes convention) + qwZ prefetch hits — rendered in
            # the gradient-wire section, excluded from the byte table
            COUNTERS.add("grad_wire.exposed_ms", 850, calls=1)
            COUNTERS.add("qwz.prefetch_hits", 4200, calls=1)
            # input pipeline: host wait (µs in the bytes slot), H2D
            # payload, prefetch queue occupancy — rendered as their own
            # "Input pipeline" section, not comm rows
            COUNTERS.add("input.host_wait_ms", 1500, calls=1)
            COUNTERS.add("input.h2d_bytes", 4096, calls=2)
            COUNTERS.add("input.queue_depth", 2, calls=1)
            # resilience: injected faults absorbed by retry/respawn +
            # a watchdog trip — rendered as the "Resilience" section
            COUNTERS.add("fault.injected", calls=1)
            COUNTERS.add("fault.retried", calls=2)
            COUNTERS.add("fault.recovered_ms", 2500, calls=1)
            COUNTERS.add("watchdog.trips", calls=1)
            COUNTERS.add("input.worker_respawns", calls=1)
            # overlap-exchange self-healing: healed drops, replayed
            # frames (bytes = replayed payload), a demotion — all
            # Resilience rows, never comm byte rows
            COUNTERS.add("exchange.reconnects", calls=1)
            COUNTERS.add("exchange.resends", 2048, calls=1)
            COUNTERS.add("exchange.demotions", calls=1)
            # elastic world-size transitions consumed on restore —
            # Resilience rows, excluded from the comm byte table
            COUNTERS.add("elastic.shrinks", calls=1)
            COUNTERS.add("elastic.regrows", calls=1)
            # serving engine (deepspeed_tpu/serving): rendered as the
            # "Serving" section, never comm byte rows; serve.ttft_ms
            # carries µs in the bytes slot, kv.blocks_in_use is an
            # occupancy sample (mean = bytes/calls)
            COUNTERS.add("serve.requests", 24, calls=2)
            COUNTERS.add("serve.tokens", calls=12)
            COUNTERS.add("serve.decode_steps", 9, calls=3)
            COUNTERS.add("serve.prefill_chunks", 16, calls=2)
            COUNTERS.add("serve.ttft_ms", 250_000, calls=2)
            COUNTERS.add("serve.shed", calls=1)
            COUNTERS.add("kv.blocks_in_use", 10, calls=4)
            COUNTERS.add("kv.evictions", calls=3)
            # speculative decoding over a quantized cache: proposed vs
            # accepted drafts + decode dispatch wall µs against the
            # quantized store (kv.dequant_ms is µs-in-bytes) — rendered
            # as the Serving section's "Speculative decoding" rows
            COUNTERS.add("serve.draft_tokens", calls=8)
            COUNTERS.add("serve.accepted_tokens", calls=6)
            COUNTERS.add("kv.dequant_ms", 90_000, calls=3)
            # block-level prefix caching + pinned sessions: hit
            # admissions (bytes = blocks aliased), prompt tokens whose
            # prefill was skipped, COW privatizations (bytes = device
            # bytes copied), session pins (bytes = blocks held), LRU
            # reclaims — the Serving section's "Prefix cache" rows;
            # router.* (fleet dispatch/spill/shed) is the "Fleet
            # router" section.  All excluded from the comm byte table.
            COUNTERS.add("kv.prefix_hits", 4, calls=2)
            COUNTERS.add("kv.prefix_hit_tokens", 16, calls=2)
            COUNTERS.add("kv.cow_copies", 4608, calls=1)
            COUNTERS.add("kv.session_pins", 6, calls=2)
            COUNTERS.add("kv.prefix_evictions", calls=1)
            COUNTERS.add("router.dispatches", 5, calls=2)
            COUNTERS.add("router.spills", calls=1)
            COUNTERS.add("router.shed", calls=1)
            # MoE wire (moe/dispatch.py): a2a hop bytes + the
            # slow-fabric subset, exposed µs (ckpt.stall_ms
            # convention), capacity drops and ppm-in-bytes bucket
            # occupancy — the "MoE wire" section, never comm byte rows
            COUNTERS.add("moe.a2a_bytes", 65536, calls=4)
            COUNTERS.add("moe.a2a_inter", 16384, calls=2)
            COUNTERS.add("moe.a2a_exposed_ms", 1200, calls=1)
            COUNTERS.add("moe.dropped_tokens", 5, calls=2)
            COUNTERS.add("moe.capacity_frac", 750_000, calls=1)
            # the self-tuning runtime (runtime/autotune/): probe µs in
            # the bytes slot, cache/swap/retune counts — rendered as
            # the "Autotune" section, never comm byte rows
            COUNTERS.add("autotune.probes", 420_000, calls=3)
            COUNTERS.add("autotune.cache_hits", calls=1)
            COUNTERS.add("autotune.rejected", calls=2)
            COUNTERS.add("autotune.retunes", calls=1)
            COUNTERS.add("autotune.swaps", calls=1)
            # the Pallas kernel registry (deepspeed_tpu/kernels):
            # trace-time dispatch resolutions — rendered as the
            # "Kernels" section, never comm byte rows
            COUNTERS.add("kernel.dispatches", calls=4)
            COUNTERS.add("kernel.fallbacks", calls=2)
            # trace recorder bookkeeping (monitor/tracing.py): event/
            # byte tallies + SLO window count — rendered as the
            # "Serving SLO" section's Tracing rows, never comm byte rows
            COUNTERS.add("trace.events", 2048, calls=12)
            COUNTERS.add("trace.dropped", calls=1)
            COUNTERS.add("slo.windows", calls=1)
            sp = mon.span("forward")
            sp.close()
            mon.step_end(step, loss=4.0 / step, lr=1e-3, loss_scale=1.0,
                         samples_per_sec=100.0, skipped_steps=0,
                         pipe={"occupancy": [
                             {"stage": 0, "ticks": 9, "compute_ticks": 8,
                              "bubble_frac": 0.1111}]})
        # live SLO windows (monitor.tracing.ServingSLO snapshots) land
        # in the event stream as type="slo" events and render as the
        # "Serving SLO" section; the report keeps the LAST window plus
        # the worst p99 seen across windows
        for p99 in (41.5, 55.0):
            mon.emit("slo", {"slo": {
                "window_s": 10.0, "requests": 6,
                "ttft_ms": {"p50": 21.0, "p99": p99, "n": 6},
                "tok_per_s": 180.0, "queue_depth_mean": 1.5,
                "accept_rate": 0.75, "drafted": 16, "shed": 1}})
        mon.close()
        # a supervisor restart ledger beside the event streams
        # (elasticity/supervisor.py) renders as the "Restarts" section
        import json as _json

        with open(os.path.join(root, "selftest", "restarts.jsonl"),
                  "w") as f:
            f.write(_json.dumps({
                "t": 0.0, "event": "restart", "attempt": 1,
                "ran_for_s": 12.5, "exit_code": -15,
                "reason": "watchdog trip on rank 0: step deadline",
                "dead_ranks": [], "backoff_s": 5.0,
                "diagnostics": "watchdog_snapshot.rank00000.1.json",
            }) + "\n")
            # an elastic shrink + regrow pair (supervisor
            # --elastic-shrink) renders as the "Elastic transitions"
            # block beside the Restarts table
            f.write(_json.dumps({
                "t": 1.0, "event": "restart", "attempt": 2,
                "ran_for_s": 33.0, "exit_code": 1,
                "reason": "rank(s) [3] went quiet first",
                "dead_ranks": [3], "backoff_s": 5.0,
                "from_world": 4, "to_world": 3, "transition": "shrink",
                "incarnation": 2,
            }) + "\n")
            f.write(_json.dumps({
                "t": 2.0, "event": "restart", "attempt": 3,
                "ran_for_s": 60.0, "exit_code": 1,
                "reason": "exit code 1",
                "dead_ranks": [], "backoff_s": 5.0,
                "from_world": 3, "to_world": 4, "transition": "regrow",
                "incarnation": 3,
            }) + "\n")
        # an autotune ledger beside the event streams (runtime/
        # autotune/runtime.py) renders as the "Autotune" event table
        with open(os.path.join(root, "selftest", "autotune.jsonl"),
                  "w") as f:
            f.write(_json.dumps({
                "t": 0.0, "event": "search", "step": 1, "probes": 3,
                "baseline_ms": 12.5, "fingerprint": "abcd1234",
            }) + "\n")
            f.write(_json.dumps({
                "t": 1.0, "event": "retune", "step": 2,
                "reason": "step time regression: 30.0 ms/step > 1.50 x "
                          "baseline 12.5 ms",
                "incumbent": "flat_fp32_overlap", "probes": 2,
                "swapped": True, "winner": "flat_fp32",
            }) + "\n")
            f.write(_json.dumps({
                "t": 1.5, "event": "swap", "step": 2,
                "candidate": "flat_fp32",
                "reason": "online retune: exposed wire creep",
            }) + "\n")
        # a serving-bench lane table (tools/serve_bench.py serving.json)
        # renders as the "Serving bench" table beside the training
        # sections
        with open(os.path.join(root, "selftest", "serving.json"),
                  "w") as f:
            lane = lambda tps, p99: {
                "requests": 8, "completed": 8, "errored": 0,
                "tokens": 96, "tokens_per_sec": tps, "makespan_s": 1.0,
                "ttft_ms": {"p50": 12.0, "p99": p99, "mean": 20.0},
                "itl_ms": {"p50": 2.0, "p99": 6.0},
                "kv_blocks": {"mean": 9.5, "peak": 14, "capacity": 31},
                "shed": 0}
            spec_lane = dict(lane(165.0, 35.0), accepted_per_step=1.8,
                             kv_dtype="int8", draft_len=4)
            _json.dump({"schema_version": 1, "n_requests": 8,
                        "rate_hz": 4.0,
                        "model": {"layers": 2, "d_model": 32, "heads": 4,
                                  "vocab": 64},
                        "lanes": {"continuous": lane(120.0, 40.0),
                                  "static": lane(80.0, 90.0),
                                  "spec_int8_d4": spec_lane}}, f)
        run = load_run(os.path.join(root, "selftest"))
        bad = [err for events in run["ranks"].values()
               for e in events for err in validate_event(e)]
        assert not bad, f"schema violations: {bad}"
        s = summarize(run["ranks"][0])
        assert s["n_steps"] == 3, s
        assert s["comm"]["p2p.send"]["bytes"] == 3072, s
        assert s["mean_tokens_per_sec"] is not None, s
        md = render_markdown(run)
        for needle in ("Run report", "p2p.send", "Pipeline occupancy",
                       "11.1%", "forward", "Gradient wire levels",
                       "inter-group", "slow-fabric share",
                       "Input pipeline", "host wait", "H2D batch transfer",
                       "mean prefetch queue depth",
                       "Resilience", "faults injected", "transient retries",
                       "watchdog trips", "prefetch workers respawned",
                       "exchange connections healed",
                       "exchange frames resent", "6,144 B replayed",
                       "demotions to the serial path",
                       "Restarts (supervisor ledger)", "watchdog trip on "
                       "rank 0",
                       "Elastic transitions", "shrink | 4 → 3",
                       "regrow | 3 → 4",
                       "elastic shrinks (resumed at a smaller dp)",
                       "elastic regrows (resumed at a larger dp)",
                       "## Serving", "requests completed",
                       "mean batch occupancy", "mean time-to-first-token",
                       "mean KV blocks in use",
                       "KV blocks force-reclaimed",
                       "requests shed (wedged decode)",
                       "**Speculative decoding**",
                       "draft tokens proposed | 24 (75% accepted)",
                       "draft tokens accepted | 18 (+2.00 bonus "
                       "tokens/step)",
                       "quantized-KV decode dispatch",
                       "**Prefix cache**",
                       "prefix-hit admissions | 6 (12 blocks aliased)",
                       "prompt tokens skipped | 48 (50% of prefill "
                       "tokens)",
                       "copy-on-write privatizations | 3 "
                       "(13.50 KiB copied)",
                       "session pins | 6 (18 blocks held)",
                       "cached blocks reclaimed (LRU) | 3",
                       "## Fleet router",
                       "requests dispatched | 6 (mean load at dispatch "
                       "2.50 KV blocks)",
                       "queue spill-overs | 3",
                       "requests shed at front door | 3",
                       "Serving bench (continuous batching)",
                       "Speculative decoding lanes",
                       "spec_int8_d4: +1.80 tok/step (kv int8, draft 4)",
                       "continuous vs static batching: 1.50x",
                       "MoE wire (expert all-to-all)",
                       "a2a wire bytes", "slow-fabric (inter-group) share",
                       "exposed a2a time", "tokens dropped at capacity",
                       "mean expert-bucket utilisation | 75.0%",
                       "## Autotune", "candidate probes",
                       "winner-cache hits (zero probes)",
                       "candidates pruned by config validators",
                       "online retunes (sustained regression)",
                       "live config swaps applied",
                       "swapped to `flat_fp32`",
                       "online retune: exposed wire creep",
                       "## Kernels",
                       "Pallas kernel dispatches (trace-time) | 12",
                       "jnp oracle fallbacks (trace-time) | 6",
                       "## Serving SLO", "SLO windows emitted | 2",
                       "last window: TTFT p50/p99 | 21.00 / 55.00 ms "
                       "(n=6)",
                       "last window: decode throughput | 180.00 tokens/s",
                       "last window: mean admission queue depth | 1.50",
                       "last window: draft accept rate | 75.0% "
                       "(16 drafted)",
                       "last window: requests shed | 1",
                       "worst window TTFT p99 | 55.00 ms",
                       "**Tracing**", "trace events recorded | 36",
                       "trace events dropped (byte cap) | 3",
                       "SLO windows aggregated | 3"):
            assert needle in md, f"{needle!r} missing from report"
        assert "`input.host_wait_ms`" not in md, \
            "input.* rows must not leak into the comm table"
        assert "`grad_wire.exposed_ms`" not in md and \
            "`qwz.prefetch_hits`" not in md, \
            "µs-convention wire counters must not leak into the comm table"
        assert "`fault.injected`" not in md and \
            "`watchdog.trips`" not in md, \
            "fault.*/watchdog.* rows must not leak into the comm table"
        assert "`exchange.reconnects`" not in md and \
            "`exchange.resends`" not in md, \
            "exchange.* rows must not leak into the comm table"
        assert "`elastic.shrinks`" not in md and \
            "`elastic.regrows`" not in md, \
            "elastic.* rows must not leak into the comm table"
        assert "`serve.tokens`" not in md and \
            "`kv.blocks_in_use`" not in md and \
            "`serve.draft_tokens`" not in md and \
            "`serve.accepted_tokens`" not in md and \
            "`kv.dequant_ms`" not in md, \
            "serve.*/kv.* rows must not leak into the comm table"
        assert "`kv.prefix_hits`" not in md and \
            "`kv.prefix_hit_tokens`" not in md and \
            "`kv.cow_copies`" not in md and \
            "`kv.session_pins`" not in md and \
            "`kv.prefix_evictions`" not in md and \
            "`router.dispatches`" not in md and \
            "`router.spills`" not in md and \
            "`router.shed`" not in md, \
            "kv.*/router.* rows must not leak into the comm table"
        assert "`moe.a2a_bytes`" not in md and \
            "`moe.capacity_frac`" not in md, \
            "moe.* rows must not leak into the comm table"
        assert "`autotune.probes`" not in md and \
            "`autotune.swaps`" not in md, \
            "autotune.* rows must not leak into the comm table"
        assert "`kernel.dispatches`" not in md and \
            "`kernel.fallbacks`" not in md, \
            "kernel.* rows must not leak into the comm table"
        assert "`trace.events`" not in md and \
            "`trace.dropped`" not in md and \
            "`slo.windows`" not in md, \
            "trace.*/slo.* rows must not leak into the comm table"
        # serving.json alone must render without event streams (the
        # serve-bench run-dir shape)
        import shutil as _shutil

        sv_dir = os.path.join(root, "sv_only")
        os.makedirs(sv_dir)
        _shutil.copy(os.path.join(root, "selftest", "serving.json"),
                     sv_dir)
        md2 = render_markdown(load_run(sv_dir))
        assert "Serving bench (continuous batching)" in md2, md2
    print("run_report selftest ok")
    return 0


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("run_dir", nargs="?",
                    help="run directory (manifest.json + events.rank*.jsonl)")
    ap.add_argument("-o", "--output", help="write markdown here "
                    "(default: stdout)")
    ap.add_argument("--selftest", action="store_true",
                    help="synthetic write->render round-trip")
    args = ap.parse_args()
    if args.selftest:
        return selftest()
    if not args.run_dir:
        ap.error("run_dir is required (or --selftest)")

    from deepspeed_tpu.monitor.report import load_run, render_markdown

    md = render_markdown(load_run(args.run_dir))
    if args.output:
        with open(args.output, "w") as f:
            f.write(md)
        print(f"wrote {args.output}")
    else:
        print(md)
    return 0


if __name__ == "__main__":
    sys.exit(main())
