#!/usr/bin/env python
"""Chaos bench: scripted fault campaigns against the training runtime.

The point of the chaos runtime (runtime/resilience.py) is a provable
claim: a run that absorbs injected faults finishes with the SAME losses
as the fault-free run, with zero supervisor restarts — transient
KV/storage/worker failures are absorbed by retry/respawn instead of
being promoted to process death.  This tool runs that claim as a bench
and records the fault/retry/recovery accounting as durable artifacts
(the PR-2 rule).

Campaigns:

* **CPU dry-run** (default; also wired into tier-1 via
  tests/test_resilience.py, like grad_wire_bench/ckpt_bench): two lanes
  on the virtual mesh —
    baseline   fault-free training + checkpointing
    chaos      identical training with a FaultPlan injecting a
               transient checkpoint-write raise, a prefetch-worker
               death, and a step delay
  asserts byte-identical loss sequences, a committed final checkpoint,
  and PINS the fault counters (fault.injected / fault.retried /
  input.worker_respawns) exactly.  A third mini-lane injects a `hang`
  at the step boundary under an armed StepWatchdog and asserts the
  trip: diagnostic snapshot + `watchdog_trip.json` escalation that the
  supervisor's HeartbeatWatcher picks up as a restart trigger.

* **--nproc 2** (TCP): the same two lanes across 2 jax.distributed
  processes, where the KV faults hit the REAL coordination-service
  transport: transient raises on the commit-barrier done-key post and
  the heartbeat-wire KV gets, plus the checkpoint-write raise and the
  worker death.  Loss parity is asserted on every rank; the recorded
  artifact carries per-rank fault/retry counters.

* **--overlap** (CPU dry-run, also tier-1 via
  tests/test_overlap_healing.py): campaigns against the self-healing
  host exchange (runtime/comm/overlap.py) — a transient exchange.send
  raise absorbed by the retry taxonomy, a sustained send fault driving
  COORDINATED DEMOTION to the serial in-program wire (bitwise losses,
  `exchange.demotions` pinned), and a SIGTERM mid-run producing a
  committed emergency checkpoint that resumes with exact loss parity.

* **--overlap --nproc 2** (TCP): the same claims over the REAL socket
  mesh — a reconnect lane injecting a connection reset (send fault), a
  peer-kill-shaped recv fault, and a CRC-caught frame corruption, all
  healed by reconnect+resend (`exchange.reconnects` pinned exactly, one
  per rank per injected drop; zero demotions, zero restarts, bitwise
  losses); a demotion lane with the reconnect budget zeroed that
  completes the run on the serial wire; and a two-phase preemption lane
  where both ranks SIGTERM mid-run, commit the emergency checkpoint
  through the real coordination-service barrier, exit cleanly, and a
  relaunched pair resumes to bitwise-identical final params.

Usage: python tools/chaos_bench.py [--nproc 2] [--steps 6]
           [--no-record] [--overlap]
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import shutil
import signal
import socket
import subprocess
import sys
import tempfile
import time

HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.join(HERE, ".."))

DIM = 64
BATCH = 32


class _SyntheticRegression:
    """Deterministic indexable dataset (the index protocol is what lets
    PrefetchLoader parallelize collate — and what the worker-death
    respawn path needs to replay the exact failed batch)."""

    def __init__(self, n, dim=DIM, out=4, seed=0):
        import numpy as np

        rng = np.random.RandomState(seed)
        self.x = rng.randn(n, dim).astype(np.float32)
        w = rng.randn(dim, out).astype(np.float32)
        self.y = (self.x @ w).astype(np.float32)

    def __len__(self):
        return len(self.x)

    def __getitem__(self, i):
        return (self.x[i], self.y[i])


def _mlp(dim=DIM, out=4):
    import jax
    import jax.numpy as jnp

    from deepspeed_tpu.runtime.module import TrainModule

    class MLP(TrainModule):
        def init(self, rng):
            k1, k2 = jax.random.split(rng)
            return {"w1": jax.random.normal(k1, (dim, dim)) * 0.1,
                    "b1": jnp.zeros((dim,)),
                    "w2": jax.random.normal(k2, (dim, out)) * 0.1,
                    "b2": jnp.zeros((out,))}

        def loss(self, params, batch, rng=None, train=True, **kw):
            x, y = batch
            h = jnp.tanh(x @ params["w1"] + params["b1"])
            pred = h @ params["w2"] + params["b2"]
            return jnp.mean((pred - y.astype(pred.dtype)) ** 2)

    return MLP()


def run_lane(steps, ckpt_dir, faults=None, monitor_path=None,
             job_name="chaos", save_every=2, num_workers=2, batch=BATCH,
             watchdog=None):
    """One campaign lane: train `steps` global batches off the engine-
    owned prefetched loader, checkpointing every `save_every` steps.
    Returns (losses, counter_deltas, engine_done_marker)."""
    import deepspeed_tpu as ds
    from deepspeed_tpu.monitor.counters import COUNTERS
    from deepspeed_tpu.runtime import checkpointing as ckpt_io

    cfg = {
        "train_batch_size": batch,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "steps_per_print": 0,
        "data_pipeline": {"num_workers": num_workers},
    }
    faults_cfg = {}
    if faults:
        faults_cfg["rules"] = faults
    if watchdog:
        faults_cfg["watchdog"] = watchdog
    if faults_cfg:
        cfg["faults"] = faults_cfg
    if monitor_path is not None:
        cfg["monitor"] = {"enabled": True, "output_path": monitor_path,
                          "job_name": job_name, "flush_interval": 1,
                          "flops": False, "heartbeat_interval": 1}
    dataset = _SyntheticRegression(steps * batch)
    engine, *_ = ds.initialize(model=_mlp(), config_params=cfg,
                               training_data=dataset,
                               dist_init_required=False)
    snap = COUNTERS.snapshot()
    losses = []
    for i in range(steps):
        losses.append(float(engine.train_batch()))
        if save_every and (i + 1) % save_every == 0:
            engine.save_checkpoint(ckpt_dir, tag=f"step{i + 1}")
    ckpt_io.flush_pending()
    delta = COUNTERS.delta_since(snap)
    engine.finalize_monitoring()
    committed = ckpt_io.read_latest_tag(ckpt_dir) if save_every else None
    return losses, delta, committed


# the dry-run chaos schedule: three distinct fault kinds, all absorbed
# (a raise retried, a worker death respawned, a delay ridden out) —
# tests pin the resulting counters EXACTLY against this list
DRY_CHAOS_RULES = [
    # first checkpoint file write dies once with a transient error;
    # retry_transient absorbs it (storage-hiccup model)
    {"site": "ckpt.atomic_write", "kind": "raise", "calls": [0],
     "times": 1},
    # a prefetch worker dies mid-epoch; the consumer respawns it at the
    # exact failed batch (dead-data-worker model)
    {"site": "dataloader.worker", "kind": "raise", "calls": [1],
     "times": 1},
    # one slow step (GC pause / snapshot stall model)
    {"site": "engine.step", "kind": "delay_ms", "delay_ms": 5,
     "steps": [1], "times": 1},
]


def run_dry(artifact_root=None, steps=4, record=True, root=None):
    """Tier-1 CPU campaign (in-process; the grad_wire/ckpt_bench
    dry-run pattern): baseline vs chaos lanes must produce IDENTICAL
    losses with the chaos lane's fault counters pinned, plus the
    watchdog hang lane.  Returns the recorded result dict."""
    from deepspeed_tpu.elasticity.supervisor import HeartbeatWatcher
    from deepspeed_tpu.monitor.counters import COUNTERS

    made_root = root is None
    root = root or tempfile.mkdtemp(prefix="chaos_bench_")
    try:
        base_losses, base_delta, base_tag = run_lane(
            steps, os.path.join(root, "ck_base"))
        chaos_losses, chaos_delta, chaos_tag = run_lane(
            steps, os.path.join(root, "ck_chaos"),
            faults=DRY_CHAOS_RULES)

        assert base_losses == chaos_losses, (
            f"chaos lane diverged: {base_losses} vs {chaos_losses} — "
            f"an injected fault leaked into training instead of being "
            f"absorbed")
        assert base_tag == chaos_tag == f"step{steps - steps % 2}", \
            (base_tag, chaos_tag)
        injected = chaos_delta.get("fault.injected", {}).get("calls", 0)
        retried = chaos_delta.get("fault.retried", {}).get("calls", 0)
        respawns = chaos_delta.get("input.worker_respawns",
                                   {}).get("calls", 0)
        recovered = chaos_delta.get("fault.recovered_ms", {})
        assert injected == len(DRY_CHAOS_RULES), chaos_delta
        assert retried == 1 and respawns == 1, chaos_delta
        assert recovered.get("calls", 0) == 1, chaos_delta
        assert not base_delta.get("fault.injected"), base_delta

        # watchdog lane: a hang at the step boundary must trip the
        # watchdog, dump the snapshot, and leave the supervisor
        # escalation file where HeartbeatWatcher finds it
        run_root = os.path.join(root, "runs")
        run_dir = os.path.join(run_root, "wd")
        watcher = HeartbeatWatcher(run_dir, stall_timeout=0.0)
        wd_snap = COUNTERS.snapshot()
        # deadline sizing: it must exceed the worst-case LEGITIMATE
        # inter-beat gap (first-step compile + a synchronous save's
        # fsync can reach ~1s on a loaded 1-core box) while the hang
        # clears it with margin — a spurious trip here would be the
        # bench failing its own product
        wd_losses, wd_delta, _ = run_lane(
            steps, os.path.join(root, "ck_wd"),
            faults=[{"site": "engine.step", "kind": "hang",
                     "hang_s": 4.0, "steps": [2]}],
            monitor_path=run_root, job_name="wd",
            watchdog={"enabled": True, "deadline_s": 1.8, "poll_s": 0.05})
        trips = COUNTERS.delta_since(wd_snap).get("watchdog.trips",
                                                  {}).get("calls", 0)
        assert trips == 1, f"hang did not trip the watchdog ({wd_delta})"
        assert wd_losses == base_losses, "the hang changed training"
        trip_path = os.path.join(run_dir, "watchdog_trip.json")
        assert os.path.isfile(trip_path), "no escalation file"
        with open(trip_path) as f:
            trip = json.load(f)
        assert trip["snapshot"] and os.path.isfile(trip["snapshot"]), trip
        with open(trip["snapshot"]) as f:
            snapshot = json.load(f)
        assert snapshot["stacks"] and snapshot["counters"], \
            "snapshot missing stacks/counters"
        trigger = watcher.check()
        assert trigger is not None and "watchdog trip" in \
            trigger["reason"], trigger
        assert trigger["diagnostics"] == trip["snapshot"], trigger

        result = {
            "metric": "chaos_cpu_dryrun",
            "platform": "cpu",
            "steps": steps,
            "faults_injected": injected,
            "transient_retries": retried,
            "worker_respawns": respawns,
            "recovered_ms": round(recovered.get("bytes", 0) / 1000.0, 3),
            "watchdog_trips": trips,
            "loss_parity": "exact",
            "supervisor_restarts": 0,
            "value": injected + trips,
            "unit": "faults_absorbed_or_escalated",
            "losses": [round(x, 6) for x in base_losses],
        }
        if record:
            from deepspeed_tpu.monitor.artifacts import record_bench_result

            result["artifact"] = record_bench_result(
                result, root=artifact_root, name=result["metric"])
        return result
    finally:
        # never leak the campaign's fault plan into the caller's process
        from deepspeed_tpu.runtime import resilience

        resilience.install_fault_plan(None)
        resilience.install_retry_policy(None)
        if made_root:
            shutil.rmtree(root, ignore_errors=True)


# ---------------------------------------------------------------------------
# 2-process TCP campaign: KV faults hit the real coordination service
# ---------------------------------------------------------------------------

# rank-scoped so the two ranks inject DIFFERENT faults (the asymmetric
# case is the hard one: the other rank must ride out its peer's retry
# window inside the ordinary KV timeouts)
def tcp_chaos_rules():
    return [
        # transient KV raise on the commit barrier's done-key post
        {"site": "kv.post", "kind": "raise", "calls": [0], "times": 1,
         "rank": 0},
        # transient KV raise inside the heartbeat wire's part-key get
        {"site": "hostwire.kv_get", "kind": "raise", "calls": [1],
         "times": 1, "rank": 1},
        # checkpoint-write raise on the writing rank (at stage 0 with
        # replicated params only process 0 lands files)
        {"site": "ckpt.atomic_write", "kind": "raise", "calls": [0],
         "times": 1, "rank": 0},
        # prefetch worker death on rank 1
        {"site": "dataloader.worker", "kind": "raise", "calls": [1],
         "times": 1, "rank": 1},
    ]


def _worker(args):
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(coordinator_address=args.coord,
                               num_processes=args.nproc,
                               process_id=args.proc_id)
    import deepspeed_tpu  # noqa: F401  (gloo-collectives flag first)
    from deepspeed_tpu.monitor.counters import COUNTERS  # noqa: F401

    root = args.scratch
    base_losses, base_delta, base_tag = run_lane(
        args.steps, os.path.join(root, "ck_base"),
        monitor_path=os.path.join(root, "runs"), job_name="base",
        num_workers=2)
    chaos_losses, chaos_delta, chaos_tag = run_lane(
        args.steps, os.path.join(root, "ck_chaos"),
        faults=tcp_chaos_rules(),
        monitor_path=os.path.join(root, "runs"), job_name="chaos",
        num_workers=2)

    assert base_losses == chaos_losses, (
        f"rank {args.proc_id}: chaos lane diverged "
        f"({base_losses} vs {chaos_losses})")
    assert base_tag == chaos_tag and chaos_tag is not None, \
        (base_tag, chaos_tag)
    assert not base_delta.get("fault.injected"), base_delta
    print("CHAOS_RANK " + json.dumps({
        "rank": args.proc_id,
        "losses": [round(x, 6) for x in chaos_losses],
        "final_tag": chaos_tag,
        "faults_injected": chaos_delta.get("fault.injected",
                                           {}).get("calls", 0),
        "transient_retries": chaos_delta.get("fault.retried",
                                             {}).get("calls", 0),
        "worker_respawns": chaos_delta.get("input.worker_respawns",
                                           {}).get("calls", 0),
        "recovered_ms": round(chaos_delta.get("fault.recovered_ms",
                                              {}).get("bytes", 0)
                              / 1000.0, 3),
    }), flush=True)


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def run_tcp(nproc=2, steps=6, record=True, scratch=None, timeout=900):
    """Launch the N-process campaign; parent collects per-rank results,
    asserts the invariants, and records the artifact."""
    made = scratch is None
    scratch = scratch or tempfile.mkdtemp(prefix="chaos_tcp_")
    coord = f"127.0.0.1:{_free_port()}"
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    procs = [
        subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--worker",
             "--proc-id", str(i), "--nproc", str(nproc),
             "--coord", coord, "--steps", str(steps),
             "--scratch", scratch],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env)
        for i in range(nproc)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            outs.append(out)
            assert p.returncode == 0, out[-4000:]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        if made:
            shutil.rmtree(scratch, ignore_errors=True)

    ranks = []
    for out in outs:
        for line in out.splitlines():
            if line.startswith("CHAOS_RANK "):
                ranks.append(json.loads(line[len("CHAOS_RANK "):]))
    assert len(ranks) == nproc, outs
    ranks.sort(key=lambda r: r["rank"])
    # every rank saw the identical (global-mean) loss stream and agreed
    # on the final committed tag
    assert all(r["losses"] == ranks[0]["losses"] for r in ranks), ranks
    assert all(r["final_tag"] == ranks[0]["final_tag"] for r in ranks)
    total_injected = sum(r["faults_injected"] for r in ranks)
    # every rule is rank-scoped and times=1: the campaign injects
    # EXACTLY one fault per rule
    expected = len(tcp_chaos_rules())
    assert total_injected == expected, (total_injected, expected, ranks)
    assert sum(r["transient_retries"] for r in ranks) >= 3, ranks
    assert sum(r["worker_respawns"] for r in ranks) == 1, ranks

    result = {
        "metric": f"chaos_{nproc}proc_tcp",
        "platform": "cpu",
        "world": {"processes": nproc},
        "steps": steps,
        "fault_kinds": ["kv.post raise", "hostwire.kv_get raise",
                        "ckpt.atomic_write raise",
                        "dataloader.worker death"],
        "faults_injected": total_injected,
        "transient_retries": sum(r["transient_retries"] for r in ranks),
        "worker_respawns": sum(r["worker_respawns"] for r in ranks),
        "recovered_ms": round(sum(r["recovered_ms"] for r in ranks), 3),
        "loss_parity": "exact",
        "supervisor_restarts": 0,
        "value": total_injected,
        "unit": "faults_absorbed",
        "ranks": ranks,
    }
    if record:
        from deepspeed_tpu.monitor.artifacts import record_bench_result

        result["artifact"] = record_bench_result(result,
                                                 name=result["metric"])
    return result


# ---------------------------------------------------------------------------
# overlap-wire campaigns: self-healing exchange, demotion, preemption
# ---------------------------------------------------------------------------

OVERLAP_PREEMPT_AT = 4  # 0-based step that self-delivers SIGTERM


def _wait_wire_quiescent(engine, timeout=20.0):
    """Block until the exchange's resend buffer drains (every frame the
    sender retained has been ACKed by every peer).  Campaign faults
    then hit a QUIET wire, so `exchange.resends` pins tightly to the
    injection schedule instead of racing whatever ACKs were in flight.
    No-op for the in-process transport and once the KV fallback owns
    the wire (no ACKs ride the KV transport — waiting would only burn
    the timeout)."""
    ex = getattr(engine, "_overlap_exchange", None)
    unacked = getattr(ex, "_unacked", None)
    if ex is None or unacked is None:
        return
    deadline = time.monotonic() + timeout
    while unacked and not getattr(ex, "_kv_mode", False) and \
            time.monotonic() < deadline:
        time.sleep(0.005)


def overlap_lane(steps, comm=None, faults=None, preempt_dir=None,
                 sigterm_step=None, resume=None, seed=0):
    """One overlap-campaign lane: manual forward/backward/step loop
    (the split composition — step boundaries, where demotion and
    preemption land, are explicit), deterministic synthetic batches.

    `sigterm_step` self-delivers SIGTERM right before that step's
    boundary — the honest preemption shape (the signal lands mid-step;
    the handler defers to the boundary), made deterministic.  The lane
    then raises SystemExit(0) out of engine.step() after the emergency
    checkpoint commits.  `resume=(dir, tag, skip)` restores the tag and
    skips the consumed batches first.

    Returns (losses, params, counter_delta, engaged)."""
    import jax
    import numpy as np

    import deepspeed_tpu as ds
    from deepspeed_tpu.monitor.counters import COUNTERS

    cfg = {
        "train_batch_size": BATCH,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "steps_per_print": 0,
        "comm": dict({"gradient_reduction": "bucketed",
                      "reduce_bucket_size": 2048, "overlap": "auto"},
                     **(comm or {})),
    }
    if faults:
        cfg["faults"] = {"rules": faults}
    if preempt_dir:
        cfg["checkpoint"] = {"preempt_save_dir": preempt_dir}
    data = _SyntheticRegression(steps * BATCH, seed=seed)
    engine, *_ = ds.initialize(model=_mlp(), config_params=cfg,
                               dist_init_required=False)
    engaged = "grads" in engine._step_fns
    skip = 0
    if resume is not None:
        rdir, rtag, skip = resume
        engine.load_checkpoint(rdir, tag=rtag)
    snap = COUNTERS.snapshot()
    losses = []
    for i in range(skip, steps):
        batch = (data.x[i * BATCH:(i + 1) * BATCH],
                 data.y[i * BATCH:(i + 1) * BATCH])
        loss = engine.forward(batch)
        engine.backward()
        if sigterm_step is not None and i == sigterm_step:
            os.kill(os.getpid(), signal.SIGTERM)
        engine.step()
        losses.append(float(loss))
        _wait_wire_quiescent(engine)
    delta = COUNTERS.delta_since(snap)
    params = [np.asarray(x) for x in
              jax.tree_util.tree_leaves(engine.params)]
    engine.finalize_monitoring()
    return losses, params, delta, engaged


def _params_digest(params) -> str:
    h = hashlib.sha256()
    for p in params:
        h.update(p.tobytes())
    return h.hexdigest()


def _assert_params_equal(a, b, ctx):
    import numpy as np

    for x, y in zip(a, b):
        assert (x == y).all(), \
            f"{ctx}: params diverged (max |d|={np.abs(x - y).max()})"


def run_dry_overlap(artifact_root=None, steps=6, record=True, root=None):
    """Tier-1 CPU overlap campaign (in-process LocalExchange transport,
    same driver machinery as the socket mesh).  Lanes:

      serial     overlap off — the loss/params oracle
      overlap    fault-free overlap — bitwise vs serial
      transient  one exchange.send raise, absorbed by retry_transient
                 (no demotion, bitwise, fault counters pinned)
      demote     sustained send faults exhaust the retry budget ->
                 coordinated demotion: the step programs rebuild on the
                 serial wire MID-RUN and the run completes bitwise
                 (`exchange.demotions` == 1)
      preempt    SIGTERM mid-run -> committed emergency checkpoint ->
                 clean exit -> a fresh engine resumes from the tag and
                 finishes with exact loss/param parity
    """
    made_root = root is None
    root = root or tempfile.mkdtemp(prefix="chaos_overlap_")
    try:
        serial_losses, serial_params, _, _ = overlap_lane(
            steps, comm={"overlap": "none"})
        ovl_losses, ovl_params, ovl_delta, engaged = overlap_lane(steps)
        assert engaged, "overlap did not engage on the bucketed wire"
        assert ovl_losses == serial_losses, \
            f"overlap diverged: {serial_losses} vs {ovl_losses}"
        _assert_params_equal(serial_params, ovl_params, "overlap lane")
        assert not ovl_delta.get("exchange.demotions"), ovl_delta

        tr_losses, tr_params, tr_delta, _ = overlap_lane(
            steps, faults=[{"site": "exchange.send", "kind": "raise",
                            "calls": [1], "times": 1}])
        assert tr_losses == serial_losses, "transient fault leaked"
        _assert_params_equal(serial_params, tr_params, "transient lane")
        assert tr_delta.get("fault.injected", {}).get("calls") == 1
        assert tr_delta.get("fault.retried", {}).get("calls") == 1
        assert not tr_delta.get("exchange.demotions"), \
            "a single transient send fault must NOT demote"

        demote_steps = list(range(2, steps))
        dm_losses, dm_params, dm_delta, _ = overlap_lane(
            steps, faults=[{"site": "exchange.send", "kind": "raise",
                            "steps": demote_steps}])
        assert dm_losses == serial_losses, \
            f"demotion lane diverged: {serial_losses} vs {dm_losses}"
        _assert_params_equal(serial_params, dm_params, "demotion lane")
        demotions = dm_delta.get("exchange.demotions", {}).get("calls", 0)
        assert demotions == 1, dm_delta

        # preemption: SIGTERM mid-run -> committed tag -> clean exit
        from deepspeed_tpu.runtime import checkpointing as ckpt_io

        preempt_dir = os.path.join(root, "preempt_ck")
        exited = False
        try:
            overlap_lane(steps, preempt_dir=preempt_dir,
                         sigterm_step=OVERLAP_PREEMPT_AT)
        except SystemExit as e:
            exited = e.code == 0
        assert exited, "SIGTERM did not exit cleanly after the save"
        tag = ckpt_io.read_latest_tag(preempt_dir)
        assert tag == f"preempt_step{OVERLAP_PREEMPT_AT + 1}", tag
        rs_losses, rs_params, _, _ = overlap_lane(
            steps, resume=(preempt_dir, tag, OVERLAP_PREEMPT_AT + 1))
        assert rs_losses == serial_losses[OVERLAP_PREEMPT_AT + 1:], \
            (rs_losses, serial_losses)
        _assert_params_equal(serial_params, rs_params, "preempt resume")

        result = {
            "metric": "chaos_overlap_cpu_dryrun",
            "platform": "cpu",
            "steps": steps,
            "transient_absorbed": 1,
            "demotions": demotions,
            "preempt_tag": tag,
            "loss_parity": "exact",
            "supervisor_restarts": 0,
            "value": demotions + 1,
            "unit": "exchange_faults_absorbed_or_demoted",
            "losses": [round(x, 6) for x in serial_losses],
        }
        if record:
            from deepspeed_tpu.monitor.artifacts import record_bench_result

            result["artifact"] = record_bench_result(
                result, root=artifact_root, name=result["metric"])
        return result
    finally:
        from deepspeed_tpu.runtime import resilience

        resilience.install_fault_plan(None)
        resilience.install_retry_policy(None)
        if made_root:
            shutil.rmtree(root, ignore_errors=True)


# the 2-proc reconnect schedule: three distinct wire faults, each
# healed by reconnect+resend.  Windows are two steps wide (times=1, so
# each rule still injects EXACTLY once) and non-overlapping, with the
# inter-step quiescence wait ensuring each fault hits a drained wire.
def overlap_reconnect_rules():
    return [
        # connection reset: the send-side fault tears the conn down
        # before the frame hits the wire (frame stays unacked -> resent)
        {"site": "exchange.send", "kind": "raise", "steps": [1, 2],
         "times": 1, "rank": 0},
        # peer kill as the receiver sees it: the recv loop dies
        # mid-frame and the connection is torn down
        {"site": "exchange.recv", "kind": "raise", "steps": [3, 4],
         "times": 1, "rank": 1},
        # frame corruption: the payload is truncated in flight; the CRC
        # turns it into a connection fault the resend path heals
        {"site": "exchange.payload", "kind": "corrupt", "truncate_to": 3,
         "steps": [5, 6], "times": 1, "rank": 0},
    ]


def overlap_demotion_rules():
    return [
        # one torn connection with the reconnect budget zeroed: the
        # exchange falls back to the KV transport and the ranks demote
        {"site": "exchange.recv", "kind": "raise", "steps": [2, 3],
         "times": 1, "rank": 1},
    ]


def _overlap_worker(args):
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(coordinator_address=args.coord,
                               num_processes=args.nproc,
                               process_id=args.proc_id)
    import deepspeed_tpu  # noqa: F401  (gloo-collectives flag first)
    from deepspeed_tpu.runtime import checkpointing as ckpt_io

    steps, rank = args.steps, args.proc_id
    preempt_dir = os.path.join(args.scratch, "preempt_ck")

    if args.phase == "resume":
        # phase 2 of the preemption lane: a relaunched pair resumes
        # from the SIGTERM checkpoint and finishes the run
        tag = f"preempt_step{OVERLAP_PREEMPT_AT + 1}"
        losses, params, _, _ = overlap_lane(
            steps, resume=(preempt_dir, tag, OVERLAP_PREEMPT_AT + 1))
        print("OVL_RANK " + json.dumps({
            "rank": rank, "phase": "resume",
            "losses": [round(x, 8) for x in losses],
            "params_digest": _params_digest(params),
        }), flush=True)
        return

    base_losses, base_params, base_delta, engaged = overlap_lane(steps)
    assert engaged, "overlap did not engage over the socket mesh"
    assert not base_delta.get("exchange.reconnects"), base_delta

    rc_losses, rc_params, rc_delta, _ = overlap_lane(
        steps, faults=overlap_reconnect_rules())
    assert rc_losses == base_losses, (
        f"rank {rank}: reconnect lane diverged "
        f"({base_losses} vs {rc_losses})")
    _assert_params_equal(base_params, rc_params,
                         f"rank {rank} reconnect lane")
    reconnects = rc_delta.get("exchange.reconnects", {}).get("calls", 0)
    resends = rc_delta.get("exchange.resends", {}).get("calls", 0)
    # every injected drop heals through exactly ONE reconnect per rank
    # (the dialer re-dials, the acceptor re-accepts — both count their
    # side once); nothing may escalate to demotion
    n_drops = len(overlap_reconnect_rules())
    assert reconnects == n_drops, (reconnects, rc_delta)
    assert not rc_delta.get("exchange.demotions"), rc_delta

    dm_losses, dm_params, dm_delta, _ = overlap_lane(
        steps,
        comm={"overlap_reconnect_attempts": 0,
              "overlap_reconnect_window_ms": 2000},
        faults=overlap_demotion_rules())
    assert dm_losses == base_losses, (
        f"rank {rank}: demotion lane diverged "
        f"({base_losses} vs {dm_losses})")
    _assert_params_equal(base_params, dm_params,
                         f"rank {rank} demotion lane")
    assert dm_delta.get("exchange.demotions", {}).get("calls") == 1, \
        dm_delta

    # preemption phase 1: both ranks SIGTERM mid-run, save through the
    # real coordination-service commit barrier, exit cleanly
    exited = False
    try:
        overlap_lane(steps, preempt_dir=preempt_dir,
                     sigterm_step=OVERLAP_PREEMPT_AT)
    except SystemExit as e:
        exited = e.code == 0
    assert exited, f"rank {rank}: SIGTERM did not exit cleanly"
    tag = ckpt_io.read_latest_tag(preempt_dir)
    assert tag == f"preempt_step{OVERLAP_PREEMPT_AT + 1}", tag

    print("OVL_RANK " + json.dumps({
        "rank": rank, "phase": "chaos",
        "losses": [round(x, 8) for x in base_losses],
        "params_digest": _params_digest(base_params),
        "reconnects": reconnects,
        "resends": resends,
        "resend_bytes": rc_delta.get("exchange.resends",
                                     {}).get("bytes", 0),
        "demotions": dm_delta.get("exchange.demotions",
                                  {}).get("calls", 0),
        "faults_injected": (
            rc_delta.get("fault.injected", {}).get("calls", 0)
            + dm_delta.get("fault.injected", {}).get("calls", 0)),
        "preempt_tag": tag,
    }), flush=True)


def _launch_overlap_workers(nproc, steps, scratch, phase, timeout):
    coord = f"127.0.0.1:{_free_port()}"
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    procs = [
        subprocess.Popen(
            [sys.executable, os.path.abspath(__file__),
             "--overlap-worker", "--phase", phase,
             "--proc-id", str(i), "--nproc", str(nproc),
             "--coord", coord, "--steps", str(steps),
             "--scratch", scratch],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env)
        for i in range(nproc)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            outs.append(out)
            assert p.returncode == 0, out[-4000:]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    ranks = []
    for out in outs:
        for line in out.splitlines():
            if line.startswith("OVL_RANK "):
                ranks.append(json.loads(line[len("OVL_RANK "):]))
    assert len(ranks) == nproc, outs
    ranks.sort(key=lambda r: r["rank"])
    return ranks


def run_tcp_overlap(nproc=2, steps=8, record=True, scratch=None,
                    timeout=900):
    """The 2-proc TCP overlap campaign over the REAL socket mesh.
    Phase 1 (chaos): fault-free baseline, the reconnect lane (conn
    reset + peer-kill recv fault + CRC-caught corruption, all healed,
    counters pinned, zero demotions), the demotion lane (budget zeroed
    -> completes on the serial wire), and the preemption lane's SIGTERM
    half.  Phase 2 (resume): a relaunched pair resumes from the
    committed emergency tag and must land bitwise-identical final
    params.  Zero supervisor restarts throughout — each phase is one
    launch and every process exits 0."""
    made = scratch is None
    scratch = scratch or tempfile.mkdtemp(prefix="chaos_overlap_tcp_")
    try:
        ranks = _launch_overlap_workers(nproc, steps, scratch, "chaos",
                                        timeout)
        assert all(r["losses"] == ranks[0]["losses"] for r in ranks), ranks
        assert all(r["params_digest"] == ranks[0]["params_digest"]
                   for r in ranks), ranks
        n_drops = len(overlap_reconnect_rules())
        for r in ranks:
            assert r["reconnects"] == n_drops, ranks
            assert r["demotions"] == 1, ranks
        total_resends = sum(r["resends"] for r in ranks)
        # each drop loses the dropping side's in-flight frame (always
        # resent) and MAY lose the peer's concurrent frame (the duplex
        # race: its ACK was or wasn't in flight at teardown) — with the
        # quiescent-wire injection discipline that bounds resends to
        # [drops, 2*drops]; dedup makes the duplicates harmless
        assert n_drops <= total_resends <= 2 * n_drops, \
            (total_resends, ranks)

        resumed = _launch_overlap_workers(nproc, steps, scratch,
                                          "resume", timeout)
        assert all(r["losses"] == resumed[0]["losses"]
                   for r in resumed), resumed
        assert all(r["params_digest"] == ranks[0]["params_digest"]
                   for r in resumed), (
            "resume from the preemption checkpoint diverged from the "
            "uninterrupted run", ranks, resumed)

        result = {
            "metric": f"chaos_overlap_{nproc}proc_tcp",
            "platform": "cpu",
            "world": {"processes": nproc},
            "steps": steps,
            "fault_kinds": ["exchange.send raise (conn reset)",
                            "exchange.recv raise (peer kill)",
                            "exchange.payload corrupt (CRC)"],
            "reconnects_per_rank": ranks[0]["reconnects"],
            "resends_total": total_resends,
            "resend_bytes_total": sum(r["resend_bytes"] for r in ranks),
            "demotions_per_rank": 1,
            "preempt_tag": ranks[0]["preempt_tag"],
            "loss_parity": "exact",
            "resume_parity": "exact",
            "supervisor_restarts": 0,
            "value": n_drops,
            "unit": "wire_faults_healed",
            "ranks": ranks,
        }
        if record:
            from deepspeed_tpu.monitor.artifacts import record_bench_result

            result["artifact"] = record_bench_result(
                result, name=result["metric"])
        return result
    finally:
        if made:
            shutil.rmtree(scratch, ignore_errors=True)


# ---------------------------------------------------------------------------
# elastic shrink-to-survivors campaigns: kill a rank, shrink, grow back,
# lose zero samples (ISSUE 11; docs/tutorials/elasticity.md)
# ---------------------------------------------------------------------------

ELASTIC_BATCH = 24            # divisible by every width in the campaign
ELASTIC_DRY_N = 144           # 6 batches/epoch at B=24
ELASTIC_DRY_TOTAL = 12        # 2 full epochs
ELASTIC_DRY_KILL_AT = 5       # the simulated rank death lands here
ELASTIC_DRY_REGROW_AT = 9     # the shrunken phase hands back here


class _LedgerRegression(_SyntheticRegression):
    """_SyntheticRegression that LOGS every __getitem__ index — the
    sample ledger the exactly-once claim is pinned against.  Lanes run
    with the data pipeline disabled so pulls == trained batches."""

    def __init__(self, n, dim=DIM, out=4, seed=0):
        super().__init__(n, dim=dim, out=out, seed=seed)
        self.log = []

    def __getitem__(self, i):
        self.log.append(int(i))
        return super().__getitem__(i)


def _elastic_env_vars():
    """The elastic env contract, from its single source of truth
    (imported lazily: deepspeed_tpu pulls jax, which launcher-side code
    paths must not)."""
    from deepspeed_tpu.elasticity.elastic_env import ELASTIC_ENV_VARS

    return ELASTIC_ENV_VARS


class _elastic_env:
    """Scoped DSTPU_* elastic env for one in-process phase (the dry run
    plays supervisor: each phase is one incarnation's boot)."""

    def __init__(self, surviving=None, dead=None, incarnation=0,
                 restart=False, reason=None):
        self._want = {
            "DSTPU_SURVIVING_WORLD": (None if surviving is None
                                      else str(surviving)),
            "DSTPU_DEAD_RANKS": (None if not dead else
                                 ",".join(str(r) for r in dead)),
            "DSTPU_INCARNATION": str(incarnation),
            "DSTPU_ELASTIC_RESTART": "1" if restart else None,
            "DSTPU_ELASTIC_REASON": reason,
        }

    def __enter__(self):
        from deepspeed_tpu.runtime.comm.hostwire import set_incarnation

        env_vars = _elastic_env_vars()
        self._saved = {k: os.environ.get(k) for k in env_vars}
        for k in env_vars:
            os.environ.pop(k, None)
        for k, v in self._want.items():
            if v is not None:
                os.environ[k] = v
        set_incarnation(None)  # re-read the env lazily
        return self

    def __exit__(self, *exc):
        from deepspeed_tpu.runtime.comm.hostwire import set_incarnation

        for k, v in self._saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        set_incarnation(None)
        return False


def elastic_dry_lane(dataset, ckpt_dir, until_step, *, resume=False,
                     save=True, monitor_path=None, job_name="elastic"):
    """One incarnation of the dry campaign: boot (under whatever elastic
    env the caller scoped), optionally resume from `ckpt_dir`, train to
    `until_step` off the engine-owned loader, checkpointing each step.
    Returns (losses, counter_delta, run_dir)."""
    import deepspeed_tpu as ds
    from deepspeed_tpu.monitor.counters import COUNTERS
    from deepspeed_tpu.runtime import checkpointing as ckpt_io

    cfg = {
        "train_batch_size": ELASTIC_BATCH,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "steps_per_print": 0,
        # pulls == trained batches: the ledger dataset logs consumption
        "data_pipeline": {"enabled": False},
    }
    if monitor_path is not None:
        cfg["monitor"] = {"enabled": True, "output_path": monitor_path,
                          "job_name": job_name, "flush_interval": 1,
                          "flops": False, "heartbeat_interval": 1}
    engine, *_ = ds.initialize(model=_mlp(), config_params=cfg,
                               training_data=dataset,
                               dist_init_required=False)
    snap = COUNTERS.snapshot()
    if resume:
        engine.load_checkpoint(ckpt_dir)
    losses = []
    while engine.global_steps < until_step:
        losses.append(float(engine.train_batch()))
        if save:
            engine.save_checkpoint(ckpt_dir,
                                   tag=f"step{engine.global_steps}")
    ckpt_io.flush_pending()
    delta = COUNTERS.delta_since(snap)
    run_dir = (engine.run_monitor.run_dir
               if engine.run_monitor is not None else None)
    engine.finalize_monitoring()
    return losses, delta, run_dir


def run_dry_elastic(artifact_root=None, record=True, root=None):
    """Tier-1 CPU elastic campaign (in-process, 8 virtual devices):
    kill-simulated rank at dp 4 -> shrink to the 3 survivors -> grow
    back to 4 — with the sample ledger pinned exactly-once and the loss
    ledger pinned against the uninterrupted oracle.

    Lanes (each a fresh engine booted under the env the supervisor
    would export — `plan_world_transition` computes the same shrink/
    regrow the real supervise() loop applies):

      oracle   dp4, 12 steps uninterrupted (2 exact epochs of 144)
      A        dp4, incarnation 0: 5 steps, checkpoint each, "killed"
      D        dp4 resume (same world): remaining 7 steps — loss parity
               EXACT vs the oracle
      B        dp3 shrink (incarnation 1): 4 steps — resharding-on-
               restore, `elastic.shrinks` == 1, parity within
               reduction-order tolerance
      C        dp4 regrow (incarnation 2): 3 steps — `elastic.regrows`
               == 1, ledger + report render both transitions

    The A+B+C sample ledger must equal the oracle's: every one of the
    144 samples consumed exactly twice (once per epoch) — no drops, no
    double-counts across either transition."""
    import numpy as np

    from collections import Counter

    from deepspeed_tpu.elasticity.supervisor import (_ledger_append,
                                                     plan_world_transition)
    from deepspeed_tpu.monitor.report import load_run, render_markdown

    made_root = root is None
    root = root or tempfile.mkdtemp(prefix="chaos_elastic_")
    try:
        ck = os.path.join(root, "ck")
        runs = os.path.join(root, "runs")

        def fresh_data():
            return _LedgerRegression(ELASTIC_DRY_N)

        with _elastic_env(surviving=4):
            oracle_data = fresh_data()
            oracle_losses, _, _ = elastic_dry_lane(
                oracle_data, os.path.join(root, "ck_oracle"),
                ELASTIC_DRY_TOTAL)

        with _elastic_env(surviving=4, incarnation=0):
            a_data = fresh_data()
            a_losses, _, _ = elastic_dry_lane(a_data, ck,
                                              ELASTIC_DRY_KILL_AT)
        assert a_losses == oracle_losses[:ELASTIC_DRY_KILL_AT], \
            "pre-kill lane diverged from the oracle"

        # same-world resume: EXACT parity (saves nothing — lane B must
        # resume from the kill-point tag, not D's later ones)
        with _elastic_env(surviving=4):
            d_data = fresh_data()
            d_losses, d_delta, _ = elastic_dry_lane(
                d_data, ck, ELASTIC_DRY_TOTAL, resume=True, save=False)
        assert d_losses == oracle_losses[ELASTIC_DRY_KILL_AT:], \
            (f"same-world resume must be EXACT: "
             f"{d_losses} vs {oracle_losses[ELASTIC_DRY_KILL_AT:]}")
        assert not d_delta.get("elastic.shrinks") and \
            not d_delta.get("elastic.regrows"), d_delta
        assert Counter(a_data.log + d_data.log) == \
            Counter(oracle_data.log), "same-world resume ledger mismatch"

        # shrink to the 3 survivors (what supervise() would compute)
        to_w, transition = plan_world_transition(
            4, 4, [3], elastic_shrink=True, min_world=1)
        assert (to_w, transition) == (3, "shrink")
        with _elastic_env(surviving=3, dead=[3], incarnation=1,
                          restart=True,
                          reason="rank(s) [3] went quiet first"):
            b_data = fresh_data()
            b_losses, b_delta, _ = elastic_dry_lane(
                b_data, ck, ELASTIC_DRY_REGROW_AT, resume=True)
        assert b_delta.get("elastic.shrinks", {}).get("calls") == 1, \
            b_delta
        assert np.allclose(
            b_losses,
            oracle_losses[ELASTIC_DRY_KILL_AT:ELASTIC_DRY_REGROW_AT],
            rtol=1e-4, atol=1e-6), \
            (f"cross-world resume outside reduction-order tolerance: "
             f"{b_losses} vs "
             f"{oracle_losses[ELASTIC_DRY_KILL_AT:ELASTIC_DRY_REGROW_AT]}")

        # capacity back: grow to the full width
        to_w2, transition2 = plan_world_transition(
            3, 4, [], elastic_shrink=True, min_world=1)
        assert (to_w2, transition2) == (4, "regrow")
        with _elastic_env(surviving=4, incarnation=2, restart=True,
                          reason="capacity restored"):
            c_data = fresh_data()
            c_losses, c_delta, run_dir = elastic_dry_lane(
                c_data, ck, ELASTIC_DRY_TOTAL, resume=True,
                monitor_path=runs, job_name="elastic")
        assert c_delta.get("elastic.regrows", {}).get("calls") == 1, \
            c_delta
        assert np.allclose(c_losses,
                           oracle_losses[ELASTIC_DRY_REGROW_AT:],
                           rtol=1e-4, atol=1e-6), \
            (c_losses, oracle_losses[ELASTIC_DRY_REGROW_AT:])
        # the replicate-over-data-axis fallback must never fire: the
        # padded loader keeps every batch on the sharded path at every
        # width, so a resume can't double-count through replication
        for d in (b_delta, c_delta, d_delta):
            assert not d.get("input.replicated_batches"), d

        # THE claim: across kill -> shrink -> regrow, every sample of
        # every epoch is consumed exactly once — the multiset equals
        # the uninterrupted oracle's (each index exactly twice here)
        ledger = Counter(a_data.log + b_data.log + c_data.log)
        assert ledger == Counter(oracle_data.log), (
            "sample ledger broken across the shrink/grow cycle: "
            f"{len(+(ledger - Counter(oracle_data.log)))} over-consumed, "
            f"{len(+(Counter(oracle_data.log) - ledger))} dropped")
        assert set(ledger.values()) == {2}, ledger

        # the supervisor-side ledger + report: both transitions render
        ledger_path = os.path.join(run_dir, "restarts.jsonl")
        _ledger_append(ledger_path, {
            "t": time.time(), "event": "restart", "attempt": 2,
            "ran_for_s": 1.0, "exit_code": 1,
            "reason": "rank(s) [3] went quiet first",
            "dead_ranks": [3], "backoff_s": 0.05,
            "from_world": 4, "to_world": to_w, "transition": transition,
            "incarnation": 1, "restarts_used": 1})
        _ledger_append(ledger_path, {
            "t": time.time(), "event": "restart", "attempt": 3,
            "ran_for_s": 1.0, "exit_code": 75,
            "reason": "capacity restored", "dead_ranks": [],
            "backoff_s": 0.05, "from_world": to_w, "to_world": to_w2,
            "transition": transition2, "incarnation": 2,
            "restarts_used": 2})
        md = render_markdown(load_run(run_dir))
        assert "Elastic transitions" in md, md
        assert "shrink | 4 → 3" in md and "regrow | 3 → 4" in md, md
        assert "elastic regrows (resumed at a larger dp)" in md, md

        result = {
            "metric": "chaos_elastic_cpu_dryrun",
            "platform": "cpu",
            "steps": ELASTIC_DRY_TOTAL,
            "world_path": [4, 3, 4],
            "kill_at": ELASTIC_DRY_KILL_AT,
            "samples_exactly_once": True,
            "same_world_resume_parity": "exact",
            "cross_world_resume_parity": "reduction-order tolerance",
            "shrinks": 1,
            "regrows": 1,
            "supervisor_restarts": 0,
            "value": 2,
            "unit": "elastic_transitions_survived",
            "losses": [round(x, 6) for x in oracle_losses],
        }
        if record:
            from deepspeed_tpu.monitor.artifacts import record_bench_result

            result["artifact"] = record_bench_result(
                result, root=artifact_root, name=result["metric"])
        return result
    finally:
        from deepspeed_tpu.runtime import resilience

        resilience.install_fault_plan(None)
        resilience.install_retry_policy(None)
        if made_root:
            shutil.rmtree(root, ignore_errors=True)


# -- the real 2-proc TCP shrink lane ----------------------------------------
# supervise() drives a LAUNCHER child; the launcher spawns the jax
# worker processes at whatever world DSTPU_SURVIVING_WORLD dictates,
# reports a dead worker's rank via elastic_report.json, and the
# supervisor's --elastic-shrink policy relaunches the survivors.

ELASTIC_TCP_N = 96            # 4 batches/epoch at B=24
ELASTIC_TCP_TOTAL = 12        # 3 exact epochs
ELASTIC_TCP_KILL_AT = 5       # rank 1 self-kills at this step boundary
ELASTIC_TCP_REGROW_AT = 9     # the shrunken incarnation hands back here


def _elastic_rank(args):
    """One jax worker of the elastic TCP campaign.  Appends one JSON
    line per COMPLETED step to result_rank<r>.jsonl (a killed
    incarnation's in-flight step therefore never pollutes the ledger —
    exactly the batch the resume re-serves), plus a `done` record with
    the incarnation's counter deltas."""
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    import jax

    jax.config.update("jax_platforms", "cpu")
    world = args.nproc
    if world > 1:
        jax.distributed.initialize(coordinator_address=args.coord,
                                   num_processes=world,
                                   process_id=args.proc_id)
    import deepspeed_tpu as ds  # noqa: F401  (gloo-collectives flag first)
    from deepspeed_tpu.monitor.counters import COUNTERS
    from deepspeed_tpu.runtime import checkpointing as ckpt_io

    inc = int(os.environ.get("DSTPU_INCARNATION", "0") or 0)
    ckpt_dir = os.path.join(args.scratch, "ck")
    data = _LedgerRegression(ELASTIC_TCP_N)
    cfg = {
        "train_batch_size": ELASTIC_BATCH,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "steps_per_print": 0,
        "data_pipeline": {"enabled": False},
    }
    if args.monitor_dir:
        cfg["monitor"] = {"enabled": True,
                          "output_path": os.path.dirname(args.monitor_dir),
                          "job_name": os.path.basename(args.monitor_dir),
                          "flush_interval": 1, "flops": False,
                          "heartbeat_interval": 1}
    if args.kill_rank >= 0:
        cfg["faults"] = {"rules": [
            {"site": "engine.step", "kind": "kill", "exit_code": 173,
             "steps": [ELASTIC_TCP_KILL_AT], "rank": args.kill_rank}]}
    engine, *_ = ds.initialize(model=_mlp(), config_params=cfg,
                               training_data=data,
                               dist_init_required=False)
    snap = COUNTERS.snapshot()
    engine.load_checkpoint(ckpt_dir)  # fresh start just warns
    start = engine.global_steps
    out_path = os.path.join(args.scratch,
                            f"result_rank{args.proc_id}.jsonl")

    def emit(payload):
        with open(out_path, "a") as f:
            f.write(json.dumps(payload) + "\n")
            f.flush()

    emit({"kind": "boot", "rank": args.proc_id, "incarnation": inc,
          "world": world, "start_step": start})
    while engine.global_steps < args.steps:
        step_id = engine.global_steps
        mark = len(data.log)
        loss = float(engine.train_batch())
        engine.save_checkpoint(ckpt_dir, tag=f"step{engine.global_steps}")
        emit({"kind": "step", "rank": args.proc_id, "incarnation": inc,
              "step": step_id, "loss": round(loss, 8),
              "samples": data.log[mark:]})
    ckpt_io.flush_pending()
    delta = COUNTERS.delta_since(snap)
    engine.finalize_monitoring()
    emit({"kind": "done", "rank": args.proc_id, "incarnation": inc,
          "world": world,
          "shrinks": delta.get("elastic.shrinks", {}).get("calls", 0),
          "regrows": delta.get("elastic.regrows", {}).get("calls", 0),
          "replicated": delta.get("input.replicated_batches",
                                  {}).get("calls", 0)})


def _elastic_launcher(args):
    """The supervised child: spawns DSTPU_SURVIVING_WORLD jax workers
    (full width when unset), forwards SIGTERM, and — when a worker dies
    — kills the rest and writes `elastic_report.json` naming the dead
    rank into the monitor dir, then exits nonzero so the supervisor's
    shrink policy takes over.  A shrunken incarnation that reaches its
    step quota exits 75 ("capacity restored, restart me"), which the
    policy reads as a no-dead-ranks failure -> grow back to full."""
    inc = int(os.environ.get("DSTPU_INCARNATION", "0") or 0)
    try:
        world = int(os.environ.get("DSTPU_SURVIVING_WORLD", "")
                    or args.nproc)
    except ValueError:
        world = args.nproc
    until = args.steps if world >= args.nproc else ELASTIC_TCP_REGROW_AT
    coord = f"127.0.0.1:{_free_port()}" if world > 1 else ""
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    procs = [
        subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--elastic-rank",
             "--proc-id", str(r), "--nproc", str(world),
             "--coord", coord, "--steps", str(until),
             "--scratch", args.scratch, "--monitor-dir", args.monitor_dir,
             "--kill-rank", str(args.kill_rank if inc == 0 else -1)],
            env=env)
        for r in range(world)
    ]

    def forward(signum, _frame):
        for p in procs:
            if p.poll() is None:
                p.send_signal(signum)

    signal.signal(signal.SIGTERM, forward)
    dead_rank = None
    while dead_rank is None and any(p.poll() is None for p in procs):
        for r, p in enumerate(procs):
            rc = p.poll()
            if rc is not None and rc != 0:
                dead_rank = r
                break
        time.sleep(0.1)
    if dead_rank is not None:
        for p in procs:
            if p.poll() is None:
                p.terminate()
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait()
        os.makedirs(args.monitor_dir, exist_ok=True)
        with open(os.path.join(args.monitor_dir, "elastic_report.json"),
                  "w") as f:
            json.dump({"dead_ranks": [dead_rank],
                       "reason": f"worker rank {dead_rank} exited "
                       f"{procs[dead_rank].returncode}"}, f)
        return 1
    for p in procs:
        p.wait()
    return 0 if until >= args.steps else 75


def run_tcp_elastic(nproc=2, record=True, scratch=None, timeout=900):
    """The real shrink-to-survivors lane: kill 1 of 2 ranks mid-run ->
    supervise()'s --elastic-shrink relaunches the survivor at world 1
    -> trains on -> exits asking for capacity -> grows back to 2 ->
    finishes.  Assertions: exactly-once sample ledger across all three
    incarnations (3 exact epochs, every sample 3x), same-world prefix
    losses exact vs an uninterrupted 2-proc oracle, cross-world within
    reduction-order tolerance, shrink+regrow counters and ledger
    entries present, and the run report renders both transitions."""
    import numpy as np

    from collections import Counter

    from deepspeed_tpu.elasticity.supervisor import supervise
    from deepspeed_tpu.monitor.report import load_run, render_markdown

    made = scratch is None
    scratch = scratch or tempfile.mkdtemp(prefix="chaos_elastic_tcp_")
    saved_env = {k: os.environ.pop(k, None) for k in _elastic_env_vars()}
    try:
        def read_records(root):
            recs = []
            for r in range(nproc):
                path = os.path.join(root, f"result_rank{r}.jsonl")
                if not os.path.exists(path):
                    continue
                with open(path) as f:
                    for line in f:
                        line = line.strip()
                        if line:
                            recs.append(json.loads(line))
            return recs

        def launcher_cmd(root, monitor_dir, kill_rank):
            return [sys.executable, os.path.abspath(__file__),
                    "--elastic-launcher", "--nproc", str(nproc),
                    "--steps", str(ELASTIC_TCP_TOTAL),
                    "--scratch", root, "--monitor-dir", monitor_dir,
                    "--kill-rank", str(kill_rank)]

        # oracle: uninterrupted 2-proc run (no supervisor, no faults)
        oracle_root = os.path.join(scratch, "oracle")
        os.makedirs(oracle_root, exist_ok=True)
        rc = subprocess.call(launcher_cmd(
            oracle_root, os.path.join(oracle_root, "runs", "elastic"),
            -1), timeout=timeout)
        assert rc == 0, f"oracle launcher exited {rc}"
        oracle = read_records(oracle_root)
        oracle_steps = {e["step"]: e for e in oracle
                        if e["kind"] == "step" and e["rank"] == 0}
        assert sorted(oracle_steps) == list(range(ELASTIC_TCP_TOTAL))

        # the campaign, under the real supervisor
        camp = os.path.join(scratch, "camp")
        monitor_dir = os.path.join(camp, "runs", "elastic")
        os.makedirs(camp, exist_ok=True)
        rc = supervise(
            launcher_cmd(camp, monitor_dir, kill_rank=1),
            max_restarts=5, backoff=0.05, backoff_cap=0.1,
            monitor_dir=monitor_dir, stall_timeout=0.0,
            grace=15.0, poll_interval=0.2,
            elastic_shrink=True, min_world=1, world=nproc)
        assert rc == 0, f"supervised campaign exited {rc}"

        recs = read_records(camp)
        boots = [e for e in recs if e["kind"] == "boot"]
        dones = [e for e in recs if e["kind"] == "done"]
        steps = [e for e in recs if e["kind"] == "step"]
        incs = sorted({e["incarnation"] for e in boots})
        assert incs == [0, 1, 2], boots
        worlds = {e["incarnation"]: e["world"] for e in boots}
        assert worlds == {0: nproc, 1: nproc - 1, 2: nproc}, worlds

        # per-step stream: completed steps only (the killed step 5 was
        # never recorded by incarnation 0 and re-trains in 1) — every
        # step exactly once per RANK of its incarnation, in order
        by_step = {}
        for e in steps:
            by_step.setdefault(e["step"], []).append(e)
        assert sorted(by_step) == list(range(ELASTIC_TCP_TOTAL)), \
            sorted(by_step)
        for s, entries in by_step.items():
            owner_inc = {e["incarnation"] for e in entries}
            assert len(owner_inc) == 1, (s, entries)  # no re-trained step
            # every rank of the incarnation saw the identical global loss
            assert len({e["loss"] for e in entries}) == 1, (s, entries)
            # ... and assembled the identical global batch (the
            # same-value-everywhere device_put contract)
            assert len({tuple(e["samples"]) for e in entries}) == 1, \
                (s, entries)

        # loss parity vs the oracle: incarnation 0 (same world) exact,
        # the shrunken/regrown tail within reduction-order tolerance
        for s in range(ELASTIC_TCP_KILL_AT):
            assert by_step[s][0]["loss"] == oracle_steps[s]["loss"], \
                (s, by_step[s][0]["loss"], oracle_steps[s]["loss"])
        tail = [by_step[s][0]["loss"] for s in
                range(ELASTIC_TCP_KILL_AT, ELASTIC_TCP_TOTAL)]
        otail = [oracle_steps[s]["loss"] for s in
                 range(ELASTIC_TCP_KILL_AT, ELASTIC_TCP_TOTAL)]
        assert np.allclose(tail, otail, rtol=1e-4, atol=1e-6), \
            (tail, otail)

        # THE exactly-once claim, across incarnations: each step's
        # global batch (identical on every rank, asserted above) counted
        # once == every sample of every epoch exactly once (3 exact
        # epochs here)
        ledger = Counter()
        for entries in by_step.values():
            ledger.update(entries[0]["samples"])
        assert set(ledger.values()) == {ELASTIC_TCP_TOTAL * ELASTIC_BATCH
                                        // ELASTIC_TCP_N}, (
            "sample ledger broken across the TCP shrink/grow cycle",
            {k: v for k, v in ledger.items()
             if v != ELASTIC_TCP_TOTAL * ELASTIC_BATCH // ELASTIC_TCP_N})
        assert len(ledger) == ELASTIC_TCP_N, len(ledger)

        # counters: the shrink landed in incarnation 1, the regrow in 2
        inc_done = {e["incarnation"]: e for e in dones}
        assert inc_done[1]["shrinks"] == 1 and \
            inc_done[1]["regrows"] == 0, inc_done[1]
        assert inc_done[2]["regrows"] == 1 and \
            inc_done[2]["shrinks"] == 0, inc_done[2]
        assert all(e["replicated"] == 0 for e in dones), dones

        # supervisor ledger + report: both transitions recorded
        with open(os.path.join(monitor_dir, "restarts.jsonl")) as f:
            ledger_rows = [json.loads(x) for x in f if x.strip()]
        trans = [(r.get("transition"), r.get("from_world"),
                  r.get("to_world")) for r in ledger_rows
                 if r.get("transition")]
        assert ("shrink", nproc, nproc - 1) in trans, trans
        assert ("regrow", nproc - 1, nproc) in trans, trans
        md = render_markdown(load_run(monitor_dir))
        assert "Elastic transitions" in md and "shrink" in md and \
            "regrow" in md, md

        result = {
            "metric": f"chaos_elastic_{nproc}proc_tcp",
            "platform": "cpu",
            "world": {"processes": nproc},
            "steps": ELASTIC_TCP_TOTAL,
            "world_path": [nproc, nproc - 1, nproc],
            "kill": f"rank 1 os._exit(173) at step {ELASTIC_TCP_KILL_AT}",
            "samples_exactly_once": True,
            "same_world_prefix_parity": "exact",
            "cross_world_parity": "reduction-order tolerance",
            "shrinks": 1,
            "regrows": 1,
            "supervisor_restarts": 2,
            "value": 2,
            "unit": "elastic_transitions_survived",
            "losses": [by_step[s][0]["loss"]
                       for s in range(ELASTIC_TCP_TOTAL)],
        }
        if record:
            from deepspeed_tpu.monitor.artifacts import record_bench_result

            result["artifact"] = record_bench_result(
                result, name=result["metric"])
        return result
    finally:
        for k, v in saved_env.items():
            if v is not None:
                os.environ[k] = v
        if made:
            shutil.rmtree(scratch, ignore_errors=True)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--nproc", type=int, default=1)
    ap.add_argument("--steps", type=int, default=6)
    ap.add_argument("--no-record", action="store_true")
    ap.add_argument("--overlap", action="store_true")
    ap.add_argument("--elastic", action="store_true")
    ap.add_argument("--worker", action="store_true")
    ap.add_argument("--overlap-worker", dest="overlap_worker",
                    action="store_true")
    ap.add_argument("--elastic-launcher", dest="elastic_launcher",
                    action="store_true")
    ap.add_argument("--elastic-rank", dest="elastic_rank",
                    action="store_true")
    ap.add_argument("--phase", default="chaos",
                    choices=("chaos", "resume"))
    ap.add_argument("--proc-id", dest="proc_id", type=int, default=0)
    ap.add_argument("--coord", default="")
    ap.add_argument("--scratch", default="")
    ap.add_argument("--monitor-dir", dest="monitor_dir", default="")
    ap.add_argument("--kill-rank", dest="kill_rank", type=int, default=-1)
    args = ap.parse_args()
    if args.worker:
        _worker(args)
        return 0
    if args.overlap_worker:
        _overlap_worker(args)
        return 0
    if args.elastic_rank:
        _elastic_rank(args)
        return 0
    if args.elastic_launcher:
        return _elastic_launcher(args)
    if args.elastic and args.nproc > 1:
        result = run_tcp_elastic(nproc=args.nproc,
                                 record=not args.no_record)
    elif args.elastic:
        os.environ.setdefault(
            "XLA_FLAGS", "--xla_force_host_platform_device_count=8")
        import jax

        jax.config.update("jax_platforms", "cpu")
        result = run_dry_elastic(record=not args.no_record)
    elif args.overlap and args.nproc > 1:
        result = run_tcp_overlap(nproc=args.nproc,
                                 steps=max(8, args.steps),
                                 record=not args.no_record)
    elif args.overlap:
        os.environ.setdefault(
            "XLA_FLAGS", "--xla_force_host_platform_device_count=8")
        import jax

        jax.config.update("jax_platforms", "cpu")
        result = run_dry_overlap(steps=max(6, args.steps),
                                 record=not args.no_record)
    elif args.nproc <= 1:
        os.environ.setdefault(
            "XLA_FLAGS", "--xla_force_host_platform_device_count=8")
        import jax

        jax.config.update("jax_platforms", "cpu")
        result = run_dry(steps=max(4, args.steps),
                         record=not args.no_record)
    else:
        result = run_tcp(nproc=args.nproc, steps=args.steps,
                         record=not args.no_record)
    print(json.dumps(result, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
