#!/usr/bin/env python
"""Chaos bench: scripted fault campaigns against the training runtime.

The point of the chaos runtime (runtime/resilience.py) is a provable
claim: a run that absorbs injected faults finishes with the SAME losses
as the fault-free run, with zero supervisor restarts — transient
KV/storage/worker failures are absorbed by retry/respawn instead of
being promoted to process death.  This tool runs that claim as a bench
and records the fault/retry/recovery accounting as durable artifacts
(the PR-2 rule).

Campaigns:

* **CPU dry-run** (default; also wired into tier-1 via
  tests/test_resilience.py, like grad_wire_bench/ckpt_bench): two lanes
  on the virtual mesh —
    baseline   fault-free training + checkpointing
    chaos      identical training with a FaultPlan injecting a
               transient checkpoint-write raise, a prefetch-worker
               death, and a step delay
  asserts byte-identical loss sequences, a committed final checkpoint,
  and PINS the fault counters (fault.injected / fault.retried /
  input.worker_respawns) exactly.  A third mini-lane injects a `hang`
  at the step boundary under an armed StepWatchdog and asserts the
  trip: diagnostic snapshot + `watchdog_trip.json` escalation that the
  supervisor's HeartbeatWatcher picks up as a restart trigger.

* **--nproc 2** (TCP): the same two lanes across 2 jax.distributed
  processes, where the KV faults hit the REAL coordination-service
  transport: transient raises on the commit-barrier done-key post and
  the heartbeat-wire KV gets, plus the checkpoint-write raise and the
  worker death.  Loss parity is asserted on every rank; the recorded
  artifact carries per-rank fault/retry counters.

Usage: python tools/chaos_bench.py [--nproc 2] [--steps 6]
           [--no-record]
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import socket
import subprocess
import sys
import tempfile

HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.join(HERE, ".."))

DIM = 64
BATCH = 32


class _SyntheticRegression:
    """Deterministic indexable dataset (the index protocol is what lets
    PrefetchLoader parallelize collate — and what the worker-death
    respawn path needs to replay the exact failed batch)."""

    def __init__(self, n, dim=DIM, out=4, seed=0):
        import numpy as np

        rng = np.random.RandomState(seed)
        self.x = rng.randn(n, dim).astype(np.float32)
        w = rng.randn(dim, out).astype(np.float32)
        self.y = (self.x @ w).astype(np.float32)

    def __len__(self):
        return len(self.x)

    def __getitem__(self, i):
        return (self.x[i], self.y[i])


def _mlp(dim=DIM, out=4):
    import jax
    import jax.numpy as jnp

    from deepspeed_tpu.runtime.module import TrainModule

    class MLP(TrainModule):
        def init(self, rng):
            k1, k2 = jax.random.split(rng)
            return {"w1": jax.random.normal(k1, (dim, dim)) * 0.1,
                    "b1": jnp.zeros((dim,)),
                    "w2": jax.random.normal(k2, (dim, out)) * 0.1,
                    "b2": jnp.zeros((out,))}

        def loss(self, params, batch, rng=None, train=True, **kw):
            x, y = batch
            h = jnp.tanh(x @ params["w1"] + params["b1"])
            pred = h @ params["w2"] + params["b2"]
            return jnp.mean((pred - y.astype(pred.dtype)) ** 2)

    return MLP()


def run_lane(steps, ckpt_dir, faults=None, monitor_path=None,
             job_name="chaos", save_every=2, num_workers=2, batch=BATCH,
             watchdog=None):
    """One campaign lane: train `steps` global batches off the engine-
    owned prefetched loader, checkpointing every `save_every` steps.
    Returns (losses, counter_deltas, engine_done_marker)."""
    import deepspeed_tpu as ds
    from deepspeed_tpu.monitor.counters import COUNTERS
    from deepspeed_tpu.runtime import checkpointing as ckpt_io

    cfg = {
        "train_batch_size": batch,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "steps_per_print": 0,
        "data_pipeline": {"num_workers": num_workers},
    }
    faults_cfg = {}
    if faults:
        faults_cfg["rules"] = faults
    if watchdog:
        faults_cfg["watchdog"] = watchdog
    if faults_cfg:
        cfg["faults"] = faults_cfg
    if monitor_path is not None:
        cfg["monitor"] = {"enabled": True, "output_path": monitor_path,
                          "job_name": job_name, "flush_interval": 1,
                          "flops": False, "heartbeat_interval": 1}
    dataset = _SyntheticRegression(steps * batch)
    engine, *_ = ds.initialize(model=_mlp(), config_params=cfg,
                               training_data=dataset,
                               dist_init_required=False)
    snap = COUNTERS.snapshot()
    losses = []
    for i in range(steps):
        losses.append(float(engine.train_batch()))
        if save_every and (i + 1) % save_every == 0:
            engine.save_checkpoint(ckpt_dir, tag=f"step{i + 1}")
    ckpt_io.flush_pending()
    delta = COUNTERS.delta_since(snap)
    engine.finalize_monitoring()
    committed = ckpt_io.read_latest_tag(ckpt_dir) if save_every else None
    return losses, delta, committed


# the dry-run chaos schedule: three distinct fault kinds, all absorbed
# (a raise retried, a worker death respawned, a delay ridden out) —
# tests pin the resulting counters EXACTLY against this list
DRY_CHAOS_RULES = [
    # first checkpoint file write dies once with a transient error;
    # retry_transient absorbs it (storage-hiccup model)
    {"site": "ckpt.atomic_write", "kind": "raise", "calls": [0],
     "times": 1},
    # a prefetch worker dies mid-epoch; the consumer respawns it at the
    # exact failed batch (dead-data-worker model)
    {"site": "dataloader.worker", "kind": "raise", "calls": [1],
     "times": 1},
    # one slow step (GC pause / snapshot stall model)
    {"site": "engine.step", "kind": "delay_ms", "delay_ms": 5,
     "steps": [1], "times": 1},
]


def run_dry(artifact_root=None, steps=4, record=True, root=None):
    """Tier-1 CPU campaign (in-process; the grad_wire/ckpt_bench
    dry-run pattern): baseline vs chaos lanes must produce IDENTICAL
    losses with the chaos lane's fault counters pinned, plus the
    watchdog hang lane.  Returns the recorded result dict."""
    from deepspeed_tpu.elasticity.supervisor import HeartbeatWatcher
    from deepspeed_tpu.monitor.counters import COUNTERS

    made_root = root is None
    root = root or tempfile.mkdtemp(prefix="chaos_bench_")
    try:
        base_losses, base_delta, base_tag = run_lane(
            steps, os.path.join(root, "ck_base"))
        chaos_losses, chaos_delta, chaos_tag = run_lane(
            steps, os.path.join(root, "ck_chaos"),
            faults=DRY_CHAOS_RULES)

        assert base_losses == chaos_losses, (
            f"chaos lane diverged: {base_losses} vs {chaos_losses} — "
            f"an injected fault leaked into training instead of being "
            f"absorbed")
        assert base_tag == chaos_tag == f"step{steps - steps % 2}", \
            (base_tag, chaos_tag)
        injected = chaos_delta.get("fault.injected", {}).get("calls", 0)
        retried = chaos_delta.get("fault.retried", {}).get("calls", 0)
        respawns = chaos_delta.get("input.worker_respawns",
                                   {}).get("calls", 0)
        recovered = chaos_delta.get("fault.recovered_ms", {})
        assert injected == len(DRY_CHAOS_RULES), chaos_delta
        assert retried == 1 and respawns == 1, chaos_delta
        assert recovered.get("calls", 0) == 1, chaos_delta
        assert not base_delta.get("fault.injected"), base_delta

        # watchdog lane: a hang at the step boundary must trip the
        # watchdog, dump the snapshot, and leave the supervisor
        # escalation file where HeartbeatWatcher finds it
        run_root = os.path.join(root, "runs")
        run_dir = os.path.join(run_root, "wd")
        watcher = HeartbeatWatcher(run_dir, stall_timeout=0.0)
        wd_snap = COUNTERS.snapshot()
        # deadline sizing: it must exceed the worst-case LEGITIMATE
        # inter-beat gap (first-step compile + a synchronous save's
        # fsync can reach ~1s on a loaded 1-core box) while the hang
        # clears it with margin — a spurious trip here would be the
        # bench failing its own product
        wd_losses, wd_delta, _ = run_lane(
            steps, os.path.join(root, "ck_wd"),
            faults=[{"site": "engine.step", "kind": "hang",
                     "hang_s": 4.0, "steps": [2]}],
            monitor_path=run_root, job_name="wd",
            watchdog={"enabled": True, "deadline_s": 1.8, "poll_s": 0.05})
        trips = COUNTERS.delta_since(wd_snap).get("watchdog.trips",
                                                  {}).get("calls", 0)
        assert trips == 1, f"hang did not trip the watchdog ({wd_delta})"
        assert wd_losses == base_losses, "the hang changed training"
        trip_path = os.path.join(run_dir, "watchdog_trip.json")
        assert os.path.isfile(trip_path), "no escalation file"
        with open(trip_path) as f:
            trip = json.load(f)
        assert trip["snapshot"] and os.path.isfile(trip["snapshot"]), trip
        with open(trip["snapshot"]) as f:
            snapshot = json.load(f)
        assert snapshot["stacks"] and snapshot["counters"], \
            "snapshot missing stacks/counters"
        trigger = watcher.check()
        assert trigger is not None and "watchdog trip" in \
            trigger["reason"], trigger
        assert trigger["diagnostics"] == trip["snapshot"], trigger

        result = {
            "metric": "chaos_cpu_dryrun",
            "platform": "cpu",
            "steps": steps,
            "faults_injected": injected,
            "transient_retries": retried,
            "worker_respawns": respawns,
            "recovered_ms": round(recovered.get("bytes", 0) / 1000.0, 3),
            "watchdog_trips": trips,
            "loss_parity": "exact",
            "supervisor_restarts": 0,
            "value": injected + trips,
            "unit": "faults_absorbed_or_escalated",
            "losses": [round(x, 6) for x in base_losses],
        }
        if record:
            from deepspeed_tpu.monitor.artifacts import record_bench_result

            result["artifact"] = record_bench_result(
                result, root=artifact_root, name=result["metric"])
        return result
    finally:
        # never leak the campaign's fault plan into the caller's process
        from deepspeed_tpu.runtime import resilience

        resilience.install_fault_plan(None)
        resilience.install_retry_policy(None)
        if made_root:
            shutil.rmtree(root, ignore_errors=True)


# ---------------------------------------------------------------------------
# 2-process TCP campaign: KV faults hit the real coordination service
# ---------------------------------------------------------------------------

# rank-scoped so the two ranks inject DIFFERENT faults (the asymmetric
# case is the hard one: the other rank must ride out its peer's retry
# window inside the ordinary KV timeouts)
def tcp_chaos_rules():
    return [
        # transient KV raise on the commit barrier's done-key post
        {"site": "kv.post", "kind": "raise", "calls": [0], "times": 1,
         "rank": 0},
        # transient KV raise inside the heartbeat wire's part-key get
        {"site": "hostwire.kv_get", "kind": "raise", "calls": [1],
         "times": 1, "rank": 1},
        # checkpoint-write raise on the writing rank (at stage 0 with
        # replicated params only process 0 lands files)
        {"site": "ckpt.atomic_write", "kind": "raise", "calls": [0],
         "times": 1, "rank": 0},
        # prefetch worker death on rank 1
        {"site": "dataloader.worker", "kind": "raise", "calls": [1],
         "times": 1, "rank": 1},
    ]


def _worker(args):
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(coordinator_address=args.coord,
                               num_processes=args.nproc,
                               process_id=args.proc_id)
    import deepspeed_tpu  # noqa: F401  (gloo-collectives flag first)
    from deepspeed_tpu.monitor.counters import COUNTERS  # noqa: F401

    root = args.scratch
    base_losses, base_delta, base_tag = run_lane(
        args.steps, os.path.join(root, "ck_base"),
        monitor_path=os.path.join(root, "runs"), job_name="base",
        num_workers=2)
    chaos_losses, chaos_delta, chaos_tag = run_lane(
        args.steps, os.path.join(root, "ck_chaos"),
        faults=tcp_chaos_rules(),
        monitor_path=os.path.join(root, "runs"), job_name="chaos",
        num_workers=2)

    assert base_losses == chaos_losses, (
        f"rank {args.proc_id}: chaos lane diverged "
        f"({base_losses} vs {chaos_losses})")
    assert base_tag == chaos_tag and chaos_tag is not None, \
        (base_tag, chaos_tag)
    assert not base_delta.get("fault.injected"), base_delta
    print("CHAOS_RANK " + json.dumps({
        "rank": args.proc_id,
        "losses": [round(x, 6) for x in chaos_losses],
        "final_tag": chaos_tag,
        "faults_injected": chaos_delta.get("fault.injected",
                                           {}).get("calls", 0),
        "transient_retries": chaos_delta.get("fault.retried",
                                             {}).get("calls", 0),
        "worker_respawns": chaos_delta.get("input.worker_respawns",
                                           {}).get("calls", 0),
        "recovered_ms": round(chaos_delta.get("fault.recovered_ms",
                                              {}).get("bytes", 0)
                              / 1000.0, 3),
    }), flush=True)


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def run_tcp(nproc=2, steps=6, record=True, scratch=None, timeout=900):
    """Launch the N-process campaign; parent collects per-rank results,
    asserts the invariants, and records the artifact."""
    made = scratch is None
    scratch = scratch or tempfile.mkdtemp(prefix="chaos_tcp_")
    coord = f"127.0.0.1:{_free_port()}"
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    procs = [
        subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--worker",
             "--proc-id", str(i), "--nproc", str(nproc),
             "--coord", coord, "--steps", str(steps),
             "--scratch", scratch],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env)
        for i in range(nproc)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            outs.append(out)
            assert p.returncode == 0, out[-4000:]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        if made:
            shutil.rmtree(scratch, ignore_errors=True)

    ranks = []
    for out in outs:
        for line in out.splitlines():
            if line.startswith("CHAOS_RANK "):
                ranks.append(json.loads(line[len("CHAOS_RANK "):]))
    assert len(ranks) == nproc, outs
    ranks.sort(key=lambda r: r["rank"])
    # every rank saw the identical (global-mean) loss stream and agreed
    # on the final committed tag
    assert all(r["losses"] == ranks[0]["losses"] for r in ranks), ranks
    assert all(r["final_tag"] == ranks[0]["final_tag"] for r in ranks)
    total_injected = sum(r["faults_injected"] for r in ranks)
    # every rule is rank-scoped and times=1: the campaign injects
    # EXACTLY one fault per rule
    expected = len(tcp_chaos_rules())
    assert total_injected == expected, (total_injected, expected, ranks)
    assert sum(r["transient_retries"] for r in ranks) >= 3, ranks
    assert sum(r["worker_respawns"] for r in ranks) == 1, ranks

    result = {
        "metric": f"chaos_{nproc}proc_tcp",
        "platform": "cpu",
        "world": {"processes": nproc},
        "steps": steps,
        "fault_kinds": ["kv.post raise", "hostwire.kv_get raise",
                        "ckpt.atomic_write raise",
                        "dataloader.worker death"],
        "faults_injected": total_injected,
        "transient_retries": sum(r["transient_retries"] for r in ranks),
        "worker_respawns": sum(r["worker_respawns"] for r in ranks),
        "recovered_ms": round(sum(r["recovered_ms"] for r in ranks), 3),
        "loss_parity": "exact",
        "supervisor_restarts": 0,
        "value": total_injected,
        "unit": "faults_absorbed",
        "ranks": ranks,
    }
    if record:
        from deepspeed_tpu.monitor.artifacts import record_bench_result

        result["artifact"] = record_bench_result(result,
                                                 name=result["metric"])
    return result


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--nproc", type=int, default=1)
    ap.add_argument("--steps", type=int, default=6)
    ap.add_argument("--no-record", action="store_true")
    ap.add_argument("--worker", action="store_true")
    ap.add_argument("--proc-id", dest="proc_id", type=int, default=0)
    ap.add_argument("--coord", default="")
    ap.add_argument("--scratch", default="")
    args = ap.parse_args()
    if args.worker:
        _worker(args)
        return 0
    if args.nproc <= 1:
        os.environ.setdefault(
            "XLA_FLAGS", "--xla_force_host_platform_device_count=8")
        import jax

        jax.config.update("jax_platforms", "cpu")
        result = run_dry(steps=max(4, args.steps),
                         record=not args.no_record)
    else:
        result = run_tcp(nproc=args.nproc, steps=args.steps,
                         record=not args.no_record)
    print(json.dumps(result, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
