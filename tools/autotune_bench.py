"""Autotune bench: the self-tuning runtime measured end to end.

Three lanes:

* `run_dry` (tier-1, CPU, in-process) — the SEARCH machinery on a
  seeded synthetic cost surface: the driver converges on the argmin of
  the surface, the winner is pinned deterministic for a fixed seed,
  the fingerprint cache round-trips (hit = zero probes, changed
  fingerprint = loud miss), and a zero-budget driver skips everything
  without caching.  Plus a small REAL-engine search through
  `engine.autotune_search` so the live probe/swap path can't rot.

* `--nproc 2` SEARCH lane (slow marker) — two jax.distributed
  processes on localhost TCP, the fabric where the wire rounds were
  measured.  An engine-factory probe (fresh engine per candidate, so
  mesh-layout knobs like `comm.hierarchy` participate) searches the
  legal space starting from the naive default (implicit flat fp32
  wire, no overlap) and must land within 10% of the hand-tuned
  BENCH round-13..17 recipe (hierarchical int8 outer hop + overlap),
  which sits IN the enumerated space — the search trace and winner are
  recorded as the committed artifact.

* `--nproc 2` RETUNE lane (same run) — an engine on the numerics-safe
  overlapped fp32 wire trains with `autotune.online` armed; a fault
  rule injects a wire slowdown (`exchange.send` delay) mid-run.  The
  sustained-regression detector must trigger EXACTLY ONE online
  retune, the swap lands on the serial wire, and the loss stream stays
  BITWISE equal to a serial-wire oracle run — the parity contract of
  safe-only online swaps.

Usage: python tools/autotune_bench.py [--nproc 2] [--steps 4]
           [--size nano] [--seq 32]
"""

from __future__ import annotations

import argparse
import gc
import json
import os
import socket
import subprocess
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.join(HERE, ".."))

SURFACE_BASE_MS = 120.0
# relative wire cost of the slow hop on a serialization-bound fabric
# (shaped after the measured BENCH rounds 7/8/11/13 ratios)
_WIRE_FACTOR = {"fp32": 1.0, "bf16": 0.72, "split": 0.85,
                "int8": 0.58, "int4": 0.52}


def synthetic_cost_ms(candidate, seed: int = 0,
                      base: float = SURFACE_BASE_MS) -> float:
    """Deterministic seeded cost surface over the candidate space,
    shaped like the measured TCP-fabric results: bucketing ~2x,
    hierarchy keeps the inner hop on the fast fabric, compressed slow
    hops win proportionally, overlap hides the wire when the exchanged
    payload is compressed/hierarchical and LOSES on the flat fp32 wire
    (the round-13 counterexample)."""
    import random

    k = candidate.knobs()
    cost = base
    if k["gradient_reduction"] == "bucketed":
        cost *= 0.5
        hier = k["hierarchy"] not in ("none", None, 1)
        slow = (k["wire_dtype_outer"] or k["wire_dtype"]) if hier \
            else k["wire_dtype"]
        if hier:
            cost *= 0.75 * (1.0 + 0.01 * int(k["hierarchy"]))
        cost *= _WIRE_FACTOR.get(slow, 1.0)
        if k["overlap"] == "on":
            compressed = hier or slow in ("bf16", "int8", "int4")
            cost *= 0.55 if compressed else 1.25
    rng = random.Random(f"{seed}:{candidate.name}")
    return cost * rng.uniform(0.97, 1.03)


def _surface_probe(seed: int):
    def probe(candidate):
        return {"step_ms": synthetic_cost_ms(candidate, seed=seed)}

    return probe


def run_dry(artifact_root: str, seed: int = 0) -> dict:
    """Tier-1 CPU lane (the grad_wire_bench.run_dry pattern).  Returns
    the recorded result dict; every contract violation asserts."""
    from deepspeed_tpu.runtime.autotune import (SearchDriver, WinnerCache,
                                                generate_candidates,
                                                make_fingerprint)

    cands, rejected = generate_candidates(
        dp=8, stage=0, wire_dtypes=("fp32", "bf16", "int8", "int4"),
        inner_dtypes=(None, "int8"))
    # the validators pruned something (e.g. the int8 inner wire on the
    # scatter level) — the tentpole's prune-before-probe contract
    assert rejected > 0, "expected the config validators to prune"

    # 1. convergence: exhaustive search == argmin of the surface, and
    #    the winner is deterministic for the seed
    expected = min(cands,
                   key=lambda c: synthetic_cost_ms(c, seed=seed)).name
    d1 = SearchDriver(_surface_probe(seed))
    best1 = d1.search(cands)
    d2 = SearchDriver(_surface_probe(seed))
    best2 = d2.search(cands)
    assert best1.candidate.name == best2.candidate.name == expected, \
        (best1.candidate.name, best2.candidate.name, expected)
    assert d1.complete and len(d1.results) == len(cands)

    # 2. fingerprint cache: hit returns the winner with zero probing;
    #    a changed fingerprint (mesh/world/dtype) is a loud miss
    fp = make_fingerprint(surface={"seed": seed, "base": SURFACE_BASE_MS},
                          mesh={"dp": 8, "data_outer": 1},
                          fabric={"topology": "synthetic"})
    cache_path = os.path.join(artifact_root, "autotune_dry_cache.json")
    cache = WinnerCache(cache_path, mode="map")
    cache.store(fp, {"name": best1.candidate.name}, d1.trace())
    hit = cache.lookup(fp)
    assert hit is not None and hit["winner"]["name"] == expected
    fp2 = make_fingerprint(surface={"seed": seed, "base": SURFACE_BASE_MS},
                           mesh={"dp": 4, "data_outer": 2},
                           fabric={"topology": "synthetic"})
    assert cache.lookup(fp2) is None, \
        "a changed mesh fingerprint must never reuse the cached winner"

    # 3. budget: a zero-budget driver skips every candidate and the
    #    degraded outcome is not cacheable
    d3 = SearchDriver(_surface_probe(seed), budget_s=0.0)
    assert d3.search(cands) is None
    assert not d3.complete
    assert all(r.skipped == "budget" for r in d3.results)

    # 4. the REAL engine path: a small live search over three flat
    #    candidates through engine.autotune_search (probe -> decide ->
    #    swap), then a second search hitting the winner cache with
    #    ZERO probes
    import jax
    import numpy as np

    import deepspeed_tpu
    from deepspeed_tpu.models import GPT, gpt2_config

    dp = jax.device_count()
    model_cfg = gpt2_config("nano", vocab_size=512, max_seq_len=16,
                            dropout=0.0, embed_dropout=0.0)
    engine_cache = os.path.join(artifact_root, "autotune_engine_cache.json")

    def build():
        engine, *_ = deepspeed_tpu.initialize(
            model=GPT(model_cfg), dist_init_required=False,
            config_params={
                "train_batch_size": dp,
                "zero_optimization": {"stage": 0},
                "mesh": {"data": dp}, "steps_per_print": 0,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
                "autotune": {"enabled": True, "probe_steps": 1,
                             "probe_warmup": 1,
                             "cache_path": engine_cache},
            })
        return engine

    rng = np.random.RandomState(0)
    tok = rng.randint(0, 512, (dp, 17)).astype(np.int32)
    batch = (tok[:, :-1], tok[:, 1:])
    from deepspeed_tpu.runtime.autotune.space import generate_candidates \
        as gen

    live, _ = gen(dp=dp, stage=0, wire_dtypes=("fp32", "bf16"),
                  outers=(), overlap=(False,))
    engine = build()
    engine.forward(batch)
    engine.backward()
    engine.step()
    out = engine.autotune_search(candidates=live)
    assert not out["cached"] and out["probes"] == len(live), out
    engine.close_overlap()
    del engine
    gc.collect()
    engine2 = build()
    engine2.forward(batch)
    engine2.backward()
    engine2.step()
    out2 = engine2.autotune_search()
    assert out2["cached"] and out2["probes"] == 0, out2
    assert out2["winner"] == out["winner"], (out2["winner"], out["winner"])
    engine2.close_overlap()
    del engine2
    gc.collect()

    from deepspeed_tpu.monitor.artifacts import record_bench_result

    result = {
        "metric": "autotune_cpu_dryrun",
        "platform": "cpu",
        "world": {"processes": 1, "devices": dp},
        "value": len(cands),
        "unit": "legal_candidates",
        "synthetic": {"candidates": len(cands), "rejected": rejected,
                      "winner": expected,
                      "winner_ms": round(best1.metrics["step_ms"], 2),
                      "trace": d1.trace()},
        "engine": {"winner": out["winner"], "probes": out["probes"],
                   "baseline_ms": out["baseline_ms"],
                   "cached_second_search": bool(out2["cached"])},
    }
    result["artifact"] = record_bench_result(result, root=artifact_root)
    return result


# ---------------------------------------------------------------------------
# the 2-process TCP lanes
# ---------------------------------------------------------------------------


def _make_batches(dp: int, seq: int, n: int, vocab: int = 512):
    """Identical batch stream on every process (grad_wire_bench's
    discipline: device_put treats each process's value as the global
    array)."""
    import numpy as np

    rng = np.random.RandomState(0)
    out = []
    for _ in range(n):
        tok = rng.randint(0, vocab, (dp, seq + 1)).astype(np.int32)
        out.append((tok[:, :-1], tok[:, 1:]))
    return out


def _engine_probe_factory(model_cfg, dp: int, gas: int, steps: int,
                          warmup: int, batches):
    """Fresh engine per candidate: the rebuild-scope search (mesh-layout
    knobs like comm.hierarchy probe here, where initialize() can build
    the factored mesh the candidate asks for)."""
    import jax  # noqa: F401

    import deepspeed_tpu
    from deepspeed_tpu.models import GPT

    def probe(cand):
        import numpy as np

        cfg = {
            "train_batch_size": dp * gas,
            "zero_optimization": {"stage": cand.stage},
            "mesh": {"data": dp}, "steps_per_print": 0,
            "optimizer": {"type": "Adam",
                          "params": {"lr": 1e-4, "weight_decay": 0.0}},
            "comm": dict(cand.comm),
        }
        if gas > 1:
            cfg["train_micro_batch_size_per_gpu"] = 1
        engine, *_ = deepspeed_tpu.initialize(
            model=GPT(model_cfg), dist_init_required=False,
            config_params=cfg)
        try:
            for _ in range(warmup):
                for _m in range(gas):
                    engine.forward(batches[0])
                    engine.backward()
                engine.step()
            t = []
            for i in range(steps):
                t0 = time.perf_counter()
                for _m in range(gas):
                    loss = engine.forward(batches[0])
                    engine.backward()
                engine.step()
                loss.block_until_ready()
                t.append(time.perf_counter() - t0)
            return {"step_ms": round(float(np.median(t)) * 1e3, 2),
                    "loss": round(float(loss), 4)}
        finally:
            engine.close_overlap()
            del engine
            gc.collect()

    return probe


def _search_lane(args, dp: int):
    """The acceptance lane: from the naive default, find the fabric's
    config; must land within 10% of the hand-tuned recipe."""
    from deepspeed_tpu.models import gpt2_config
    from deepspeed_tpu.runtime.autotune import (SearchDriver,
                                                generate_candidates)

    model_cfg = gpt2_config(args.size, vocab_size=512,
                            max_seq_len=args.seq, dropout=0.0,
                            embed_dropout=0.0)
    gas = 2  # the BENCH round-13 shape: exchange N hides behind micro N+1
    cands, rejected = generate_candidates(
        dp=dp, stage=0, current_outer=1,
        wire_dtypes=("fp32", "bf16", "int8"),
        outers=(2,), overlap=(False, True))
    batches = _make_batches(dp, args.seq, 1)
    probe = _engine_probe_factory(model_cfg, dp, gas, args.steps,
                                  warmup=2, batches=batches)
    driver = SearchDriver(probe)
    best = driver.search(cands)
    assert best is not None and driver.complete, driver.trace()
    by_name = {r.candidate.name: r for r in driver.results if r.ok}
    naive = by_name["implicit"]
    hand_tuned = by_name["hier2_fp32_int8_overlap"]
    winner_ms = best.metrics["step_ms"]
    # the acceptance pin: the search (which starts blind) must discover
    # a config within 10% of the hand-tuned BENCH recipe's ms/step
    assert winner_ms <= 1.10 * hand_tuned.metrics["step_ms"], \
        (best.candidate.name, winner_ms, hand_tuned.metrics["step_ms"])
    return {
        "candidates": len(cands), "rejected": rejected,
        "winner": best.candidate.name,
        "winner_ms": winner_ms,
        "naive_ms": naive.metrics["step_ms"],
        "hand_tuned": "hier2_fp32_int8_overlap",
        "hand_tuned_ms": hand_tuned.metrics["step_ms"],
        "speedup_vs_naive": round(
            naive.metrics["step_ms"] / max(winner_ms, 1e-9), 2),
        "winner_vs_hand_tuned": round(
            winner_ms / max(hand_tuned.metrics["step_ms"], 1e-9), 3),
        "trace": driver.trace(),
    }


def _retune_lane(args, dp: int, ledger_dir: str):
    """Injected wire slowdown -> exactly one online retune -> swap to
    the serial wire -> bitwise loss parity with the serial oracle.

    The lane runs the outer=2 HIERARCHICAL fp32 wire: cross-process,
    overlap<->serial is bitwise only where the reduction orders
    coincide — gather-structured exchanges and outer==2 hierarchies
    (the PR-9 parity contract; gloo's flat in-program psum rotates
    chunk association, so a FLAT fp32 overlap/serial pair differs by
    reduction-order rounding on this fabric).  outer=2 is also the
    recommended deployment shape the search lane lands on."""
    import numpy as np

    import deepspeed_tpu
    from deepspeed_tpu.models import GPT, gpt2_config

    model_cfg = gpt2_config(args.size, vocab_size=512,
                            max_seq_len=args.seq, dropout=0.0,
                            embed_dropout=0.0)
    gas = 2
    n_steps = 18
    slow_from = 7
    batches = _make_batches(dp, args.seq, 1)
    ledger_path = os.path.join(ledger_dir, "autotune_retune.jsonl")

    def run(overlap: bool, online: bool):
        cfg = {
            "train_batch_size": dp * gas,
            "train_micro_batch_size_per_gpu": 1,
            "zero_optimization": {"stage": 0},
            "mesh": {"data": dp}, "steps_per_print": 0,
            "optimizer": {"type": "Adam",
                          "params": {"lr": 1e-4, "weight_decay": 0.0}},
            "comm": {"gradient_reduction": "bucketed",
                     "wire_dtype": "fp32", "hierarchy": {"outer": 2},
                     "overlap": "on" if overlap else "none"},
        }
        if online:
            cfg["autotune"] = {
                "enabled": True, "probe_steps": 1, "probe_warmup": 1,
                "ledger_path": ledger_path,
                "min_improvement": 0.05,
                "online": {"enabled": True, "window": 3,
                           "baseline_steps": 3, "threshold": 1.4,
                           "cooldown_steps": 4, "check_every": 1,
                           "safe_only": True}}
            # the injected wire slowdown: every exchange send from
            # step `slow_from` pays a delay — the degraded-fabric
            # scenario the online retuner exists for
            cfg["faults"] = {"rules": [{
                "site": "exchange.send", "kind": "delay_ms",
                "delay_ms": 120,
                "steps": list(range(slow_from, n_steps + 1))}]}
        engine, *_ = deepspeed_tpu.initialize(
            model=GPT(model_cfg), dist_init_required=False,
            config_params=cfg)
        losses = []
        try:
            for _ in range(n_steps):
                for _m in range(gas):
                    loss = engine.forward(batches[0])
                    engine.backward()
                engine.step()
                losses.append(float(loss))
            retunes = (engine._autotuner.retunes
                       if engine._autotuner is not None else 0)
            demoted = engine._overlap_mode is None
            return losses, retunes, demoted
        finally:
            engine.close_overlap()
            del engine
            gc.collect()

    if os.path.exists(ledger_path):
        os.remove(ledger_path)
    oracle, _r0, _d0 = run(overlap=False, online=False)
    retuned, retunes, swapped_serial = run(overlap=True, online=True)
    assert retunes == 1, f"expected exactly one online retune, got {retunes}"
    assert swapped_serial, "the retune did not swap off the overlap wire"
    assert [np.float32(a) for a in oracle] == \
        [np.float32(b) for b in retuned], \
        "loss parity broke across the online retune swap"
    events = []
    if os.path.exists(ledger_path):
        with open(ledger_path) as f:
            events = [json.loads(ln) for ln in f if ln.strip()]
    return {
        "steps": n_steps, "slowdown_from_step": slow_from,
        "injected_delay_ms": 120,
        "retunes": retunes, "swapped_to_serial": swapped_serial,
        "loss_bitwise_vs_serial_oracle": True,
        "ledger_events": [e["event"] for e in events],
        "final_loss": round(retuned[-1], 4),
    }


def bench_tcp(args, nproc: int, proc_id: int):
    import tempfile

    import jax

    dp = jax.device_count()
    ledger_dir = tempfile.mkdtemp(prefix=f"autotune_r{proc_id}_")
    search = _search_lane(args, dp)
    retune = _retune_lane(args, dp, ledger_dir)
    if proc_id == 0:
        print(json.dumps({
            "metric": "autotune_2proc_tcp",
            "platform": "cpu",
            "world": {"processes": nproc, "devices": dp},
            "steps": args.steps,
            "value": search["winner_vs_hand_tuned"],
            "unit": "winner_ms_over_hand_tuned_ms",
            "search": search,
            "retune": retune,
        }), flush=True)


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def worker(args):
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(coordinator_address=args.coord,
                               num_processes=args.nproc,
                               process_id=args.proc_id)
    import deepspeed_tpu  # noqa: F401  (gloo flag before the CPU client)

    bench_tcp(args, args.nproc, args.proc_id)


def _record(out: str):
    try:
        line = next(ln for ln in out.splitlines()
                    if ln.startswith("{") and "metric" in ln)
        result = json.loads(line)
        from deepspeed_tpu.monitor.artifacts import record_bench_result

        path = record_bench_result(result)
        print(f"recorded: {path}", file=sys.stderr)
    except Exception as e:
        print(f"artifact recording failed: {e}", file=sys.stderr)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--nproc", type=int, default=1)
    ap.add_argument("--steps", type=int, default=4)
    ap.add_argument("--size", default="nano")
    ap.add_argument("--seq", type=int, default=32)
    ap.add_argument("--no-record", dest="no_record", action="store_true",
                    help="skip the durable bench_artifacts/runs record "
                         "(CI/test invocations)")
    ap.add_argument("--worker", action="store_true")
    ap.add_argument("--proc-id", dest="proc_id", type=int, default=0)
    ap.add_argument("--coord", default="")
    args = ap.parse_args()
    if args.worker:
        worker(args)
        return
    if args.nproc <= 1:
        os.environ.setdefault(
            "XLA_FLAGS", "--xla_force_host_platform_device_count=8")
        import jax

        jax.config.update("jax_platforms", "cpu")
        import tempfile

        result = run_dry(tempfile.mkdtemp(prefix="autotune_dry_"))
        print(json.dumps(result, indent=2, default=str))
        if not args.no_record:
            # re-record into the repo's durable artifact dir
            from deepspeed_tpu.monitor.artifacts import record_bench_result

            result.pop("artifact", None)
            path = record_bench_result(result)
            print(f"recorded: {path}", file=sys.stderr)
        return
    coord = f"127.0.0.1:{_free_port()}"
    procs = []
    for pid in range(args.nproc):
        procs.append(subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--worker",
             "--proc-id", str(pid), "--coord", coord,
             "--nproc", str(args.nproc), "--steps", str(args.steps),
             "--size", args.size, "--seq", str(args.seq)],
            stdout=subprocess.PIPE if pid == 0 else subprocess.DEVNULL,
            stderr=subprocess.STDOUT if pid == 0 else subprocess.DEVNULL,
            env={**os.environ, "JAX_PLATFORMS": "cpu"}))
    out, _ = procs[0].communicate(timeout=3600)
    for p in procs[1:]:
        p.wait(timeout=120)
    out = out.decode()
    sys.stdout.write(out)
    if any(p.returncode for p in procs):
        sys.exit(1)
    if not args.no_record:
        _record(out)


if __name__ == "__main__":
    main()
