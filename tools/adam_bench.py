"""Native CPU-Adam micro-benchmark (reference tests/perf/adam_test.py:
DeepSpeedCPUAdam vs torch.optim.Adam on large flat tensors).

Times the OpenMP/SIMD C++ step (csrc/adam/cpu_adam.cpp via HostAdam)
against a pure-numpy Adam on the same buffers — the native op is what
ZeRO-Offload/Infinity spend their host milliseconds in, so its
elements/sec sets the offload step floor.

Usage: python tools/adam_bench.py [--elems 16777216] [--iters 10]
"""

from __future__ import annotations

import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))


def numpy_adam(params, grads, m, v, step, lr=1e-3, b1=0.9, b2=0.999,
               eps=1e-8):
    m *= b1
    m += (1 - b1) * grads
    v *= b2
    v += (1 - b2) * grads * grads
    mhat = m / (1 - b1 ** step)
    vhat = v / (1 - b2 ** step)
    params -= lr * mhat / (np.sqrt(vhat) + eps)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--elems", type=int, default=16 * 1024 * 1024)
    ap.add_argument("--iters", type=int, default=10)
    args = ap.parse_args()

    from deepspeed_tpu.ops.adam.cpu_adam import HostAdam

    n = args.elems
    rng = np.random.RandomState(0)
    grads = rng.randn(n).astype(np.float32)

    # native
    p1 = np.zeros(n, np.float32)
    adam = HostAdam(lr=1e-3)
    adam.begin_step()
    adam.update_flat(0, p1, grads)  # warm the extension + state
    t0 = time.perf_counter()
    for _ in range(args.iters):
        adam.begin_step()
        adam.update_flat(0, p1, grads)
    native_s = (time.perf_counter() - t0) / args.iters

    # numpy reference
    p2 = np.zeros(n, np.float32)
    m = np.zeros(n, np.float32)
    v = np.zeros(n, np.float32)
    numpy_adam(p2, grads, m, v, 1)
    t0 = time.perf_counter()
    for i in range(args.iters):
        numpy_adam(p2, grads, m, v, i + 2)
    numpy_s = (time.perf_counter() - t0) / args.iters

    print(f"elements: {n / 1e6:.1f}M fp32")
    print(f"native ds_adam_step : {native_s * 1e3:8.2f} ms/step "
          f"({n / native_s / 1e9:.2f} Gelem/s)")
    print(f"numpy adam          : {numpy_s * 1e3:8.2f} ms/step "
          f"({n / numpy_s / 1e9:.2f} Gelem/s)")
    print(f"speedup             : {numpy_s / native_s:8.2f}x")
    # at 12 B/param host state, a full GPT-2 XL (1.56B params) step costs:
    xl = 1.558e9
    print(f"implied GPT-2 XL offload optimizer step: "
          f"{xl / (n / native_s) * 1e3:.0f} ms (native)")


if __name__ == "__main__":
    main()
