"""Gradient-wire bench: unfused implicit psum vs the bucketed wire.

Measures the dense data-parallel engine step through every wire the
engine offers (runtime/comm/bucketing.py):

  unfused        implicit XLA psum at the loss-mean boundary — one
                 collective per grad leaf (~40 for gpt2-nano)
  bucketed       BucketPlan fp32 allreduce — one fused collective per
                 dtype bucket
  bucketed_bf16  same buckets, bf16 on the wire (half the bytes)
  bucketed_split same buckets, the EleutherAI 24-bit frexp wire
                 (fp16 mantissa + int8 exponent all-gathers)
  bucketed_int8  same buckets, blockwise int8 + fp16 scales (the qgZ
                 compression half, comm/quant.py)
  zero2 / zero2_bucketed   the ZeRO-2 lane: implicit vs the bucketed
                 reduce-scatter lowering

Two fabrics, following tools/onebit_bench_mp.py:

  --nproc 1  (default) single-process CPU mesh — collectives are memory
             movement; shows the bucketing overhead floor.
  --nproc N  N jax.distributed processes on localhost (gloo/TCP): every
             cross-process payload pays a real byte-proportional
             serialize/send cost — the fabric where round-5 measured the
             dense step at 270 ms vs 53 ms for the fused onebit wire.

--hierarchy adds the two-level lanes (comm.hierarchy, ZeRO++-style):
processes map to outer groups (data_outer = nproc on the TCP fabric, 2
on the single-process mesh), so only the 1/inner-size shard crosses the
slow boundary per bucket:

  hier             fp32 both levels (exact; parity with `bucketed`)
  hier_outer_bf16  slow hop compressed to bf16, fast hop exact
  hier_outer_split slow hop on the 24-bit frexp gather
  hier_outer_int8  slow hop on blockwise int8 + fp16 scales (qgZ)
  hier_outer_int4  slow hop on packed int4 nibbles + fp16 scales
  zero2_hier       hierarchical reduce-scatter + hpZ secondary shards
                   (post-step param gather stays intra-group)
  zero2_hier_int8  same + the quantized slow hop

Each hier row reports the measured grad_wire.intra / grad_wire.inter
counter split beside the plan prediction, and the pad-free logical
payload so bucket padding never masks a compression win.

Results are recorded through monitor/artifacts.py into
bench_artifacts/runs/ + manifest (the PR-2 durable-artifact rule).

Usage: python tools/grad_wire_bench.py [--nproc 2] [--steps 20]
           [--size nano] [--seq 32] [--hierarchy]
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import subprocess
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.join(HERE, ".."))

VARIANTS = [
    ("unfused", 0, None),
    ("bucketed", 0, {"gradient_reduction": "bucketed"}),
    ("bucketed_bf16", 0, {"gradient_reduction": "bucketed",
                          "wire_dtype": "bf16"}),
    ("bucketed_split", 0, {"gradient_reduction": "bucketed",
                           "wire_dtype": "split"}),
    ("bucketed_int8", 0, {"gradient_reduction": "bucketed",
                          "wire_dtype": "int8"}),
    ("zero2", 2, None),
    ("zero2_bucketed", 2, {"gradient_reduction": "bucketed"}),
]


def overlap_variants(outer: int, gas: int = 2):
    """--overlap lanes: serial/overlapped pairs over the same wires
    (comm.overlap rides the host exchange — runtime/comm/overlap.py).
    gas>1 so micro N's exchange hides behind micro N+1's compute; the
    serial twin runs the same composition for a like-for-like step.
    Parity contract: the int8 lanes and the outer=2 hierarchical lanes
    are BIT-identical serial-vs-overlap by construction (gather wires
    share the sum expression; a 2-element reduce is commutative); the
    flat bf16 pair matches within cross-process reduction-order
    rounding (gloo's ring rotates chunk association — measured)."""
    flat = {"gradient_reduction": "bucketed"}
    hier = dict(flat, hierarchy={"outer": outer})
    lanes = []
    for name, base, wire in (
            ("flat_bf16", flat, "bf16"), ("flat_int8", flat, "int8"),
            ("hier_bf16", hier, "bf16"), ("hier_int8", hier, "int8")):
        key = "wire_dtype" if base is flat else "wire_dtype_outer"
        comm = dict(base, **{key: wire})
        lanes.append((f"{name}_serial", 0, dict(comm, overlap="none"),
                      {"gas": gas}))
        lanes.append((f"{name}_overlap", 0, dict(comm, overlap="on"),
                      {"gas": gas}))
    return lanes


def hier_variants(outer: int):
    """--hierarchy lanes: two-level reduction with data_outer groups."""
    base = {"gradient_reduction": "bucketed", "hierarchy": {"outer": outer}}
    return [
        ("hier", 0, dict(base)),
        ("hier_outer_bf16", 0, dict(base, wire_dtype_outer="bf16")),
        ("hier_outer_split", 0, dict(base, wire_dtype_outer="split")),
        ("hier_outer_int8", 0, dict(base, wire_dtype_outer="int8")),
        ("hier_outer_int4", 0, dict(base, wire_dtype_outer="int4")),
        ("zero2_hier", 2, dict(base)),
        ("zero2_hier_int8", 2, dict(base, wire_dtype_outer="int8")),
    ]


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def measure_variants(variants, steps: int, size: str, seq: int,
                     warmup: int = 5):
    """Run each (name, stage, comm-config) lane through the engine and
    return ({name: entry}, n_params) — shared by the TCP/CPU bench
    paths and the tier-1 dry-run."""
    import jax
    import numpy as np

    import deepspeed_tpu
    from deepspeed_tpu.models import GPT, gpt2_config
    from deepspeed_tpu.monitor.counters import COUNTERS

    dp = jax.device_count()
    model_cfg = gpt2_config(size, vocab_size=512,
                            max_seq_len=seq, dropout=0.0,
                            embed_dropout=0.0)
    n_params = GPT(model_cfg).num_params()
    rng = np.random.RandomState(0)  # identical stream on every process
    tok = rng.randint(0, 512, (dp, seq + 1)).astype(np.int32)
    batch = (tok[:, :-1], tok[:, 1:])

    results = {}
    for variant in variants:
        name, stage, comm = variant[:3]
        opts = variant[3] if len(variant) > 3 else {}
        gas = int(opts.get("gas", 1))
        cfg = {
            "train_batch_size": dp * gas,
            "zero_optimization": {"stage": stage},
            "mesh": {"data": dp},
            "steps_per_print": 0,
            "optimizer": {"type": "Adam",
                          "params": {"lr": 1e-4, "weight_decay": 0.0}},
        }
        if gas > 1:
            # the same (dp, seq) token block feeds every micro step:
            # micro batch stays 1 row/rank, the step runs gas micros
            cfg["train_micro_batch_size_per_gpu"] = 1
        if comm is not None:
            cfg["comm"] = comm
        engine, *_ = deepspeed_tpu.initialize(
            model=GPT(model_cfg), dist_init_required=False,
            config_params=cfg)
        if comm is not None:
            assert engine.bucket_plan is not None, \
                f"{name}: bucketed wire did not engage"
        if comm is not None and comm.get("overlap") in ("on", "auto"):
            assert "grads" in engine._step_fns, \
                f"{name}: overlapped wire did not engage"
        for _ in range(warmup):  # compile + warm
            for _m in range(gas):
                engine.forward(batch)
                engine.backward()
            engine.step()
        snap = COUNTERS.snapshot()
        t = []
        for _ in range(steps):
            t0 = time.perf_counter()
            for _m in range(gas):
                loss = engine.forward(batch)
                engine.backward()
            engine.step()
            loss.block_until_ready()
            t.append(time.perf_counter() - t0)
        entry = {"step_ms": round(float(np.median(t)) * 1e3, 2),
                 "loss": float(loss), "gas": gas}
        if engine.bucket_plan is not None:
            plan = engine.bucket_plan
            deltas = COUNTERS.delta_since(snap)
            wire = deltas.get("grad_wire.reduce", {})
            entry.update({
                "n_buckets": plan.n_buckets,
                "wire": plan.wire,
                "lowering": ("reduce-scatter" if plan.scatter
                             else "allreduce"),
                "wire_bytes_per_step": plan.wire_bytes_per_reduction,
                "logical_bytes_per_step":
                    plan.wire_bytes_logical_per_reduction,
                "collectives_per_step": plan.collectives_per_reduction,
                "counted_wire_bytes": int(wire.get("bytes", 0)),
            })
            if plan.quantized:
                entry["quant_block"] = plan.quant_block
            deltas_overlap = deltas.get("grad_wire.exposed_ms", {})
            if deltas_overlap:
                # µs-in-bytes convention (ckpt.stall_ms): the host wait
                # NOT hidden behind device compute, per drain
                entry["exposed_ms_per_step"] = round(
                    deltas_overlap.get("bytes", 0) / 1000.0
                    / max(1, deltas_overlap.get("calls", 1)), 3)
            if plan.hierarchical:
                inner, outer = plan.levels
                entry.update({
                    "wire": f"{inner.wire}/{outer.wire}",
                    "hierarchy": f"outer={outer.size} x inner={inner.size}",
                    "intra_bytes_per_step":
                        plan.wire_bytes_intra_per_reduction,
                    "inter_bytes_per_step":
                        plan.wire_bytes_inter_per_reduction,
                    "inter_logical_bytes_per_step":
                        plan.wire_bytes_inter_logical_per_reduction,
                    "counted_intra_bytes": int(deltas.get(
                        "grad_wire.intra", {}).get("bytes", 0)),
                    "counted_inter_bytes": int(deltas.get(
                        "grad_wire.inter", {}).get("bytes", 0)),
                    "counted_inter_logical_bytes": int(deltas.get(
                        "grad_wire.inter_logical", {}).get("bytes", 0)),
                })
        engine.close_overlap()
        results[name] = entry

    # overlap pairs: exposed-wire fraction + the parity contract.  Of
    # the serial lane's wire cost, how much is still on the critical
    # path with overlap on?  hidden = t_serial - t_overlap; exposed is
    # the measured blocked-on-the-wire host time.
    for name in list(results):
        if not name.endswith("_overlap"):
            continue
        serial = results.get(name[:-8] + "_serial")
        lane = results[name]
        if serial is None:
            continue
        exposed = lane.get("exposed_ms_per_step", 0.0)
        hidden = max(0.0, serial["step_ms"] - lane["step_ms"])
        lane["wire_hidden_ms_per_step"] = round(hidden, 2)
        lane["exposed_wire_frac"] = round(
            exposed / max(exposed + hidden, 1e-9), 4)
        lane["loss_bitwise_vs_serial"] = bool(
            np.float32(lane["loss"]) == np.float32(serial["loss"]))
        if "int8" in name or name.startswith("hier"):
            assert lane["loss_bitwise_vs_serial"], \
                (name, lane["loss"], serial["loss"])
    for entry in results.values():
        entry["loss"] = round(entry["loss"], 4)
    return results, n_params


def bench(args, nproc: int, proc_id: int):
    variants = list(VARIANTS)
    if args.hierarchy:
        # processes are the slow-fabric boundary on the TCP lane; the
        # single-process mesh has no real boundary — split it 2-ways so
        # the lowering still runs end-to-end (overhead floor)
        variants += hier_variants(nproc if nproc > 1 else 2)
    if args.overlap:
        variants += overlap_variants(nproc if nproc > 1 else 2,
                                     gas=args.overlap_gas)
    results, n_params = measure_variants(variants, args.steps, args.size,
                                         args.seq)

    if proc_id == 0:
        import jax

        dp = jax.device_count()
        base = results["unfused"]["step_ms"]
        for name in results:
            results[name]["vs_unfused"] = round(
                base / max(results[name]["step_ms"], 1e-9), 2)
        suffix = ("_overlap" if args.overlap
                  else "_hier" if args.hierarchy else "")
        # the headline value must track the metric the manifest row is
        # NAMED for: the exposed-wire fraction on --overlap runs, the
        # hierarchical lane on --hierarchy runs, else the flat bucketed
        if args.overlap:
            headline = results["hier_int8_overlap"]["exposed_wire_frac"]
            unit = "exposed_wire_frac_hier_int8"
        else:
            headline = results[
                "hier" if args.hierarchy else "bucketed"]["vs_unfused"]
            unit = "x_vs_unfused_dense"
        print(json.dumps({
            "metric": ("grad_wire_2proc_tcp" if nproc > 1
                       else "grad_wire_cpu_mesh") + suffix,
            "platform": "cpu",
            "n_params": int(n_params),
            "world": {"processes": nproc, "devices": dp},
            "steps": args.steps,
            "value": headline,
            "unit": unit,
            **results,
        }), flush=True)


def worker(args):
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(coordinator_address=args.coord,
                               num_processes=args.nproc,
                               process_id=args.proc_id)
    import deepspeed_tpu  # noqa: F401  (installs the gloo-collectives
    #                       flag BEFORE the CPU client exists)

    bench(args, args.nproc, args.proc_id)


def single_process(args):
    os.environ.setdefault(
        "XLA_FLAGS", "--xla_force_host_platform_device_count=8")
    import jax

    jax.config.update("jax_platforms", "cpu")
    bench(args, 1, 0)


def run_dry(artifact_root: str, steps: int = 2, size: str = "nano",
            seq: int = 16, outer: int = 2):
    """Tier-1 CPU dry-run of the QUANTIZED grad-wire lanes (the
    ckpt_bench/input_pipeline_bench pattern): runs in-process on the
    suite's virtual mesh so the qgZ path — quantized flat wire, int8/int4
    outer hops, counters, artifact recording — can never silently rot.
    Returns the recorded result dict."""
    variants = [
        ("unfused", 0, None),
        ("bucketed_int8", 0, {"gradient_reduction": "bucketed",
                              "wire_dtype": "int8"}),
    ] + [v for v in hier_variants(outer)
         if v[0] in ("hier_outer_int8", "hier_outer_int4",
                     "zero2_hier_int8")]
    results, n_params = measure_variants(variants, steps, size, seq,
                                         warmup=1)
    import jax

    from deepspeed_tpu.monitor.artifacts import record_bench_result

    result = {
        "metric": "grad_wire_cpu_mesh_quant_dryrun",
        "platform": "cpu",
        "n_params": int(n_params),
        "world": {"processes": 1, "devices": jax.device_count()},
        "steps": steps,
        "value": results["hier_outer_int8"]["inter_bytes_per_step"],
        "unit": "inter_bytes_per_step",
        **results,
    }
    result["artifact"] = record_bench_result(result, root=artifact_root)
    return result


def run_dry_overlap(artifact_root: str, steps: int = 2, size: str = "nano",
                    seq: int = 16, outer: int = 2, gas: int = 2):
    """Tier-1 CPU dry-run of the OVERLAP lanes (the run_dry pattern):
    runs the serial/overlapped pairs in-process on the suite's virtual
    mesh — grads/exchange/combine pipeline, exposed-wire counter,
    bit-identical losses, artifact recording — so comm.overlap can
    never silently rot.  On the single-process mesh EVERY pair is
    bitwise (the in-process psum is the ordered fold the combine
    mirrors); the assert below pins that."""
    variants = [v for v in overlap_variants(outer, gas=gas)
                if v[0].startswith(("flat_bf16", "hier_int8"))]
    results, n_params = measure_variants(variants, steps, size, seq,
                                         warmup=1)
    for name, entry in results.items():
        if name.endswith("_overlap"):
            assert entry["loss_bitwise_vs_serial"], (name, entry)
            assert "exposed_ms_per_step" in entry, name
    import jax

    from deepspeed_tpu.monitor.artifacts import record_bench_result

    result = {
        "metric": "grad_wire_cpu_mesh_overlap_dryrun",
        "platform": "cpu",
        "n_params": int(n_params),
        "world": {"processes": 1, "devices": jax.device_count()},
        "steps": steps,
        "value": results["hier_int8_overlap"]["exposed_wire_frac"],
        "unit": "exposed_wire_frac_hier_int8",
        **results,
    }
    result["artifact"] = record_bench_result(result, root=artifact_root)
    return result


def _record(out: str):
    """Durable artifact under bench_artifacts/runs/ (PR-2 rule)."""
    try:
        line = next(ln for ln in out.splitlines()
                    if ln.startswith("{") and "metric" in ln)
        result = json.loads(line)
        from deepspeed_tpu.monitor.artifacts import record_bench_result

        path = record_bench_result(result)
        print(f"recorded: {path}", file=sys.stderr)
    except Exception as e:  # bench output stays usable without the record
        print(f"artifact recording failed: {e}", file=sys.stderr)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--nproc", type=int, default=1)
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--size", default="nano")
    ap.add_argument("--seq", type=int, default=32)
    ap.add_argument("--hierarchy", action="store_true",
                    help="add the two-level (data_outer x data_inner) "
                         "lanes; processes map to outer groups")
    ap.add_argument("--overlap", action="store_true",
                    help="add the comm.overlap serial/overlapped lane "
                         "pairs (flat/hier x bf16/int8) measuring the "
                         "exposed-wire fraction")
    ap.add_argument("--overlap-gas", dest="overlap_gas", type=int,
                    default=2, help="micro steps per overlap-lane step "
                                    "(exchange N hides behind micro N+1)")
    ap.add_argument("--worker", action="store_true")
    ap.add_argument("--proc-id", dest="proc_id", type=int, default=0)
    ap.add_argument("--coord", default="")
    args = ap.parse_args()
    if args.worker:
        worker(args)
        return
    if args.nproc <= 1:
        import contextlib
        import io

        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            single_process(args)
        out = buf.getvalue()
        sys.stdout.write(out)
        _record(out)
        return
    coord = f"127.0.0.1:{_free_port()}"
    procs = []
    for pid in range(args.nproc):
        procs.append(subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--worker",
             "--proc-id", str(pid), "--coord", coord,
             "--nproc", str(args.nproc), "--steps", str(args.steps),
             "--size", args.size, "--seq", str(args.seq),
             "--overlap-gas", str(args.overlap_gas)]
            + (["--hierarchy"] if args.hierarchy else [])
            + (["--overlap"] if args.overlap else []),
            stdout=subprocess.PIPE if pid == 0 else subprocess.DEVNULL,
            stderr=subprocess.STDOUT if pid == 0 else subprocess.DEVNULL,
            env={**os.environ, "JAX_PLATFORMS": "cpu"}))
    out, _ = procs[0].communicate(timeout=3600)
    for p in procs[1:]:
        p.wait(timeout=60)
    out = out.decode()
    sys.stdout.write(out)
    if any(p.returncode for p in procs):
        sys.exit(1)
    _record(out)


if __name__ == "__main__":
    main()
