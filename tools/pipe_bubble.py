"""Measure pipeline bubble + buffer behaviour of the 1F1B engine.

VERDICT r2 flagged that the GPipe bubble (M+P-1)/M was admitted but never
measured. This harness times the TrainSchedule PipelineEngine at varying
micro-batch counts M and fits the tick model t(M) = a·(M + P - 1) + c:
the bubble fraction (P-1)/(M+P-1) falls as M grows, so per-micro-batch
time must approach `a`. It also reports each stage's in-flight buffer
count (TrainSchedule.num_pipe_buffers: ≤ P for 1F1B) against the M
buffers a GPipe schedule holds — the 1F1B memory win.

Run on the CPU mesh: XLA_FLAGS=--xla_force_host_platform_device_count=8
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

import deepspeed_tpu  # noqa: E402
from deepspeed_tpu.runtime.pipe.module import (LayerSpec,  # noqa: E402
                                               PipelineModule)
from deepspeed_tpu.runtime.pipe.schedule import TrainSchedule  # noqa: E402


class Blk:
    def __init__(self, d, f):
        self.d, self.f = d, f

    def init(self, rng):
        k1, k2 = jax.random.split(rng)
        return {"a": jax.random.normal(k1, (self.d, self.f)) * 0.05,
                "b": jax.random.normal(k2, (self.f, self.d)) * 0.05}

    def apply(self, p, x, rng=None, train=True):
        return x + jnp.tanh(x @ p["a"]) @ p["b"]


def mse(out, labels):
    return jnp.mean((out - labels) ** 2)


def time_engine(stages, micro_batches, d=256, f=1024, micro_size=8,
                reps=5, interleave=1, n_layers=None):
    mod = PipelineModule([LayerSpec(Blk, d, f)
                          for _ in range(n_layers or stages * 2)],
                         num_stages=stages, loss_fn=mse,
                         interleave=interleave)
    engine, *_ = deepspeed_tpu.initialize(model=mod, config_params={
        "train_batch_size": micro_size * micro_batches,
        "train_micro_batch_size_per_gpu": micro_size,
        "gradient_accumulation_steps": micro_batches,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "mesh": {"data": 1, "pipe": -1},
        "steps_per_print": 0})
    assert engine._staged
    rng = np.random.RandomState(0)

    def data():
        return iter([(rng.rand(micro_size, d).astype(np.float32),) * 2
                     for _ in range(micro_batches)])

    engine.train_batch(data())  # compile
    t0 = time.perf_counter()
    for _ in range(reps):
        engine.train_batch(data())
    dt = (time.perf_counter() - t0) / reps
    bufs = [TrainSchedule(micro_batches, stages, s).num_pipe_buffers()
            for s in range(stages)]
    return dt, bufs


def main():
    P = 4
    print(f"stages={P}; t(M) should scale with (M + P - 1) ticks")
    print(f"{'M':>4} {'s/batch':>9} {'s/micro':>9} {'bubble%':>8} "
          f"{'1f1b bufs':>10} {'gpipe bufs':>10}")
    rows = []
    for M in (2, 4, 8, 16):
        dt, bufs = time_engine(P, M)
        bubble = (P - 1) / (M + P - 1) * 100
        rows.append((M, dt))
        print(f"{M:>4} {dt:>9.3f} {dt / M:>9.3f} {bubble:>7.1f}% "
              f"{str(bufs):>10} {M:>10}")
    # fit t = a*(M+P-1): per-tick cost should be ~constant
    ticks = np.array([m + P - 1 for m, _ in rows], float)
    times = np.array([t for _, t in rows], float)
    a = float(np.dot(ticks, times) / np.dot(ticks, ticks))
    resid = float(np.max(np.abs(times - a * ticks) / times))
    print(f"per-tick fit a={a * 1000:.1f} ms, max residual {resid:.1%} "
          f"(small residual => wall time follows the tick model; "
          f"bubble shrinks as (P-1)/(M+P-1))")

    # interleaved virtual stages: same model depth, bubble /v
    print(f"\ninterleaved 1F1B (P=2 physical stages, same total layers): "
          f"theoretical bubble (P-1)/(v*M+P-1)")
    print(f"{'v':>3} {'M':>4} {'s/batch':>9} {'s/micro':>9} {'bubble%':>8}")
    for v in (1, 2):
        for M in (4, 8):
            # SAME total depth (8 layers) for every v — only the chunking
            # changes, so s/micro differences are schedule, not model
            dt, _ = time_engine(2, M, interleave=v, n_layers=8)
            bubble = (2 - 1) / (v * M + 2 - 1) * 100
            print(f"{v:>3} {M:>4} {dt:>9.3f} {dt / M:>9.3f} "
                  f"{bubble:>7.1f}%")


if __name__ == "__main__":
    main()
